"""JSON-RPC 2.0 server over HTTP (reference rpc/jsonrpc/server +
rpc/core/routes.go:10-49).

Supports POST (JSON-RPC body) and GET (/method?arg=val) like the
reference.  Handlers close over the Node.
"""
from __future__ import annotations

import base64
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qsl, urlparse

from tendermint_tpu.libs.service import BaseService
from tendermint_tpu.types.block import Block


class RPCError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code


# 429-style overload rejection (ADR-018): the IngressGate's bounded
# admission queue is full or the caller is rate limited — the message
# carries a Retry-After hint in seconds.  Distinct from -32603 internal
# errors so load balancers / clients can back off instead of failing.
RPC_BUSY_CODE = -32011


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _parse_tx(arg) -> bytes:
    if isinstance(arg, str):
        return base64.b64decode(arg)
    raise RPCError(-32602, "tx must be base64 string")


# request-size cap (reference rpc/jsonrpc/server/http_server.go
# maxBodyBytes = 1000000)
MAX_BODY_BYTES = 1_000_000


def _int_arg(v, default=None):
    if v is None:
        return default
    return int(v)


class RPCServer(BaseService):
    def __init__(self, node, laddr: str,
                 max_body_bytes: int = MAX_BODY_BYTES):
        super().__init__("rpc")
        self.max_body_bytes = max_body_bytes
        from tendermint_tpu.libs import log as tmlog
        self.log = tmlog.logger("rpc")
        self.node = node
        host, _, port = laddr.rpartition(":")
        self.host, self.port = host or "127.0.0.1", int(port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self.routes = {
            "health": self.health,
            "status": self.status,
            "net_info": self.net_info,
            "genesis": self.genesis,
            "blockchain": self.blockchain,
            "block": self.block,
            "block_by_hash": self.block_by_hash,
            "block_results": self.block_results,
            "commit": self.commit,
            "validators": self.validators,
            "consensus_params": self.consensus_params,
            "consensus_state": self.consensus_state,
            "unconfirmed_txs": self.unconfirmed_txs,
            "num_unconfirmed_txs": self.num_unconfirmed_txs,
            "check_tx": self.check_tx,
            "broadcast_tx_sync": self.broadcast_tx_sync,
            "broadcast_tx_async": self.broadcast_tx_async,
            "broadcast_tx_commit": self.broadcast_tx_commit,
            "abci_info": self.abci_info,
            "abci_query": self.abci_query,
            "broadcast_evidence": self.broadcast_evidence,
            "tx": self.tx,
            "tx_search": self.tx_search,
            "block_search": self.block_search,
            "light_block": self.light_block,
            "block_proto": self.block_proto,
            "dump_consensus_state": self.dump_consensus_state,
            "genesis_chunked": self.genesis_chunked,
        }
        if getattr(getattr(node, "config", None), "rpc", None) is not None \
                and getattr(node.config.rpc, "unsafe", False):
            # reference rpc/core/routes.go AddUnsafeRoutes (--rpc.unsafe)
            self.routes["dial_seeds"] = self.dial_seeds
            self.routes["dial_peers"] = self.dial_peers
        # light-client serving plane (light/service.py, ADR-026):
        # thin parse/encode shims over the node's LightServe; overload
        # maps to the same RPC_BUSY_CODE class as mempool admission
        from tendermint_tpu.rpc import light as light_rpc
        light_rpc.register(self)

    # -- lifecycle ---------------------------------------------------------

    def on_start(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                # request-size cap (reference rpc/jsonrpc/server
                # http_server.go maxBodyBytes = 1MB)
                if n > server.max_body_bytes:
                    self._reply(server._err(
                        None, -32600,
                        f"request body too large (> {server.max_body_bytes})"))
                    return
                try:
                    req = json.loads(self.rfile.read(n) or b"{}")
                    if not isinstance(req, dict):
                        raise ValueError("request must be an object")
                except (json.JSONDecodeError, UnicodeDecodeError,
                        ValueError):
                    self._reply(server._err(None, -32700, "parse error"))
                    return
                self._reply(server.dispatch(req.get("method", ""),
                                            req.get("params") or {},
                                            req.get("id", -1)))

            def do_GET(self):
                u = urlparse(self.path)
                method = u.path.strip("/")
                if method == "websocket" and \
                        "websocket" in (self.headers.get("Upgrade", "")
                                        .lower()):
                    server._serve_websocket(self)
                    return
                if method == "metrics":
                    # Prometheus text exposition (reference serves this on
                    # a dedicated Instrumentation listener,
                    # node/node.go:959-962)
                    from tendermint_tpu.libs.metrics import DEFAULT
                    body = DEFAULT.render_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                params = {}
                for k, v in parse_qsl(u.query):
                    if v in ("true", "false"):
                        params[k] = v == "true"
                    elif v and v[0] in '["{':
                        try:
                            params[k] = json.loads(v)
                        except json.JSONDecodeError:
                            self._reply(server._err(
                                -1, -32602, f"malformed param {k}={v!r}"))
                            return
                    else:
                        params[k] = v
                if method == "":
                    self._reply({"jsonrpc": "2.0", "id": -1, "result": {
                        "routes": sorted(server.routes)}})
                    return
                self._reply(server.dispatch(method, params, -1))

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_port  # resolve port 0
        self.spawn(self._httpd.serve_forever, name="rpc-http")
        self.log.info("RPC server listening", laddr=self.laddr)

    def on_stop(self):
        self.log.info("RPC server stopping")
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()

    @property
    def laddr(self) -> str:
        return f"{self.host}:{self.port}"

    # -- websocket subscriptions (reference rpc/jsonrpc/server/ws_handler
    # + rpc/core/events.go Subscribe/Unsubscribe) --------------------------

    def _serve_websocket(self, handler):
        import base64 as _b64
        import hashlib
        import struct

        sock = handler.connection
        key = handler.headers.get("Sec-WebSocket-Key", "")
        accept = _b64.b64encode(hashlib.sha1(
            (key + "258EAFA5-E914-47DA-95CA-C5AB0DC85B11").encode())
            .digest()).decode()
        handler.wfile.write(
            b"HTTP/1.1 101 Switching Protocols\r\n"
            b"Upgrade: websocket\r\nConnection: Upgrade\r\n"
            b"Sec-WebSocket-Accept: " + accept.encode() + b"\r\n\r\n")
        handler.wfile.flush()

        send_lock = threading.Lock()

        def send_text(text: str):
            payload = text.encode()
            n = len(payload)
            if n < 126:
                hdr = struct.pack("!BB", 0x81, n)
            elif n < 1 << 16:
                hdr = struct.pack("!BBH", 0x81, 126, n)
            else:
                hdr = struct.pack("!BBQ", 0x81, 127, n)
            with send_lock:
                sock.sendall(hdr + payload)

        def recv_exact(n):
            # handler.rfile is buffered: frame bytes pipelined with the
            # upgrade request may already sit in its buffer, so a raw
            # sock.recv would hang forever waiting for them
            buf = handler.rfile.read(n)
            if buf is None or len(buf) < n:
                raise ConnectionError("ws closed")
            return buf

        def recv_frame():
            b1, b2 = recv_exact(2)
            opcode = b1 & 0x0F
            masked = b2 & 0x80
            ln = b2 & 0x7F
            if ln == 126:
                (ln,) = struct.unpack("!H", recv_exact(2))
            elif ln == 127:
                (ln,) = struct.unpack("!Q", recv_exact(8))
            if opcode >= 8 and ln > 125:
                raise ConnectionError("ws control frame too large")
            if ln > 1 << 20:
                raise ConnectionError("ws frame too large")
            mask = recv_exact(4) if masked else b"\x00" * 4
            data = bytearray(recv_exact(ln))
            for i in range(ln):
                data[i] ^= mask[i % 4]
            return opcode, bytes(data)

        # per-connection subscriptions: query string -> (Query, bus sub)
        from tendermint_tpu.libs.pubsub_query import Query, QueryError
        subs = {}
        stop = threading.Event()

        def pump():
            """Deliver matching events as JSON-RPC notifications shaped
            like the reference's #event responses."""
            import queue as _q
            while not stop.is_set():
                delivered = False
                for qstr, (query, sub) in list(subs.items()):
                    try:
                        ev = sub.queue.get_nowait()
                    except _q.Empty:
                        continue
                    if not query.matches(self._event_terms(ev)):
                        continue
                    delivered = True
                    try:
                        send_text(json.dumps({
                            "jsonrpc": "2.0", "id": "0#event",
                            "result": {
                                "query": qstr,
                                "data": {
                                    "type": f"tendermint/event/{ev.type}",
                                    "value": self._event_json(ev)}}}))
                    except OSError:
                        stop.set()
                        return
                if not delivered:
                    stop.wait(0.05)

        pump_thread = threading.Thread(target=pump, daemon=True)
        pump_thread.start()
        try:
            while not stop.is_set():
                opcode, data = recv_frame()
                if opcode == 8:  # close
                    break
                if opcode == 9:  # ping -> pong
                    with send_lock:
                        sock.sendall(b"\x8a" + bytes([len(data)]) + data)
                    continue
                if opcode != 1:
                    continue
                try:
                    req = json.loads(data)
                except (json.JSONDecodeError, UnicodeDecodeError):
                    send_text(json.dumps(self._err(None, -32700,
                                                   "parse error")))
                    continue
                rid = req.get("id", -1)
                method = req.get("method", "")
                params = req.get("params") or {}
                if method == "subscribe":
                    qstr = params.get("query", "")
                    try:
                        query = Query(qstr)
                    except QueryError as e:
                        send_text(json.dumps(self._err(rid, -32602,
                                                       str(e))))
                        continue
                    stale = subs.pop(qstr, None)
                    if stale is not None:  # re-subscribe: drop the old sub
                        self.node.event_bus.unsubscribe(stale[1])
                    sub = self.node.event_bus.subscribe()
                    subs[qstr] = (query, sub)
                    send_text(json.dumps({"jsonrpc": "2.0", "id": rid,
                                          "result": {}}))
                elif method == "unsubscribe":
                    qstr = params.get("query", "")
                    entry = subs.pop(qstr, None)
                    if entry is not None:
                        self.node.event_bus.unsubscribe(entry[1])
                    send_text(json.dumps({"jsonrpc": "2.0", "id": rid,
                                          "result": {}}))
                elif method == "unsubscribe_all":
                    for _, sub in subs.values():
                        self.node.event_bus.unsubscribe(sub)
                    subs.clear()
                    send_text(json.dumps({"jsonrpc": "2.0", "id": rid,
                                          "result": {}}))
                else:
                    send_text(json.dumps(self.dispatch(method, params,
                                                       rid)))
        except (ConnectionError, OSError):
            pass
        finally:
            stop.set()
            for _, sub in subs.values():
                self.node.event_bus.unsubscribe(sub)
            handler.close_connection = True

    def _event_terms(self, ev) -> dict:
        """Composite query terms for an event: tm.event plus attributes,
        plus app event attributes for Tx results (reference
        libs/pubsub/query semantics, e.g. tx.height / app.creator)."""
        terms = {"tm.event": [ev.type]}
        for k, v in (ev.attributes or {}).items():
            terms.setdefault(f"tm.{k}", []).append(str(v))
        data = ev.data or {}
        if isinstance(data, dict):
            if "height" in (ev.attributes or {}):
                terms.setdefault("tx.height" if ev.type == "Tx"
                                 else "block.height",
                                 []).append(ev.attributes["height"])
            res = data.get("result")
            for app_ev in (getattr(res, "events", None) or []):
                for k, v in (getattr(app_ev, "attributes", None)
                             or {}).items():
                    terms.setdefault(
                        f"{getattr(app_ev, 'type', '')}.{k}",
                        []).append(str(v))
        return terms

    def _event_json(self, ev) -> dict:
        """Shallow JSON projection of event data."""
        data = ev.data or {}
        if not isinstance(data, dict):
            return {"repr": str(data)}
        out = {}
        for k, v in data.items():
            if isinstance(v, (int, str, bool, float)) or v is None:
                out[k] = v
            elif isinstance(v, bytes):
                out[k] = _b64(v)
            elif k == "block":
                out["height"] = v.header.height
                out["hash"] = v.hash().hex().upper()
                out["num_txs"] = len(v.data.txs)
            elif k == "result":
                out["code"] = getattr(v, "code", 0)
                out["log"] = getattr(v, "log", "")
            else:
                out[k] = str(v)
        return out

    # -- dispatch ----------------------------------------------------------

    def _err(self, rid, code, message):
        return {"jsonrpc": "2.0", "id": rid,
                "error": {"code": code, "message": message}}

    def dispatch(self, method: str, params: dict, rid):
        fn = self.routes.get(method)
        if fn is None:
            return self._err(rid, -32601, f"unknown method {method!r}")
        try:
            result = fn(**params) if isinstance(params, dict) else fn(*params)
            return {"jsonrpc": "2.0", "id": rid, "result": result}
        except RPCError as e:
            return self._err(rid, e.code, str(e))
        except TypeError as e:
            return self._err(rid, -32602, f"invalid params: {e}")
        except Exception as e:
            return self._err(rid, -32603, f"internal error: {e}")

    # -- handlers (reference rpc/core/*.go) --------------------------------

    def health(self):
        return {}

    def status(self):
        return self.node.status()

    def net_info(self):
        sw = self.node.switch
        peers = [{
            "node_info": {"id": p.node_info.node_id,
                          "listen_addr": p.node_info.listen_addr,
                          "moniker": p.node_info.moniker},
            "is_outbound": p.outbound,
        } for p in sw.peers.values()]
        return {"listening": True, "listeners": [sw.actual_listen_addr()],
                "n_peers": len(peers), "peers": peers}

    def genesis(self):
        return {"genesis": json.loads(self.node.genesis.to_json())}

    def blockchain(self, minHeight=None, maxHeight=None):
        """Reference rpc/core/blocks.go BlockchainInfo: metas for a height
        range, newest first, max 20."""
        store = self.node.block_store
        max_h = min(_int_arg(maxHeight, store.height()) or store.height(),
                    store.height())
        min_h = max(_int_arg(minHeight, 1) or 1, store.base())
        min_h = max(min_h, max_h - 19)
        metas = []
        for h in range(max_h, min_h - 1, -1):
            m = store.load_block_meta(h)
            if m is not None:
                metas.append(self._meta_json(m))
        return {"last_height": store.height(), "block_metas": metas}

    def block(self, height=None):
        h = _int_arg(height, self.node.block_store.height())
        block = self.node.block_store.load_block(h)
        if block is None:
            raise RPCError(-32603, f"no block at height {h}")
        meta = self.node.block_store.load_block_meta(h)
        return {"block_id": self._bid_json(meta.block_id),
                "block": self._block_json(block)}

    def block_by_hash(self, hash=None):
        want = bytes.fromhex(hash) if hash else b""
        h = self.node.block_store.height_by_hash(want)
        if h is None:
            raise RPCError(-32603, "block not found")
        return self.block(h)

    def block_results(self, height=None):
        h = _int_arg(height, self.node.block_store.height())
        resp = self.node.state_store.load_abci_responses(h)
        if resp is None:
            raise RPCError(-32603, f"no results for height {h}")
        return {
            "height": h,
            "txs_results": [{"code": r.code, "data": _b64(r.data or b""),
                             "log": r.log,
                             "gas_used": getattr(r, "gas_used", 0)}
                            for r in (resp.deliver_txs or [])],
            "validator_updates": [
                {"pub_key_type": u.pub_key_type,
                 "pub_key": _b64(u.pub_key_bytes), "power": u.power}
                for u in (resp.end_block.validator_updates
                          if resp.end_block else [])],
        }

    def commit(self, height=None):
        store = self.node.block_store
        h = _int_arg(height, store.height())
        meta = store.load_block_meta(h)
        if meta is None:
            raise RPCError(-32603, f"no commit for height {h}")
        canonical = h < store.height()
        com = store.load_block_commit(h) if canonical \
            else store.load_seen_commit(h)
        return {"signed_header": {
            "header": self._header_json(meta.header),
            "commit": self._commit_json(com)},
            "canonical": canonical}

    def validators(self, height=None, page=None, per_page=None):
        h = _int_arg(height, self.node.block_store.height())
        vals = self.node.state_store.load_validators(h)
        if vals is None:
            raise RPCError(-32603, f"no validators for height {h}")
        per = min(_int_arg(per_page, 30) or 30, 100)
        pg = max(_int_arg(page, 1) or 1, 1)
        chunk = vals.validators[(pg - 1) * per: pg * per]
        return {"block_height": str(h),
                "validators": [self._val_json(v) for v in chunk],
                "count": str(len(chunk)), "total": str(vals.size())}

    def consensus_params(self, height=None):
        h = _int_arg(height, self.node.block_store.height())
        p = self.node.state.consensus_params
        return {"block_height": h, "consensus_params": {
            "block": {"max_bytes": p.block.max_bytes,
                      "max_gas": p.block.max_gas},
            "evidence": {
                "max_age_num_blocks": p.evidence.max_age_num_blocks,
                "max_age_duration":
                    p.evidence.max_age_duration_seconds * 10**9,
                "max_bytes": p.evidence.max_bytes},
            "validator": {"pub_key_types": p.validator.pub_key_types},
        }}

    def consensus_state(self):
        rs = self.node.consensus.get_round_state()
        return {"round_state": {
            "height": rs.height, "round": rs.round, "step": int(rs.step),
        }}

    def dump_consensus_state(self):
        """Full round state + per-peer round states (reference
        rpc/core/consensus.go DumpConsensusState)."""
        cs = self.node.consensus
        with cs._mtx:
            rs = cs.rs
            votes = rs.votes
            out_rs = {
                "height": rs.height, "round": rs.round,
                "step": int(rs.step),
                "proposal": rs.proposal is not None,
                "proposal_block_hash": (
                    rs.proposal_block.hash().hex().upper()
                    if rs.proposal_block is not None else ""),
                "locked_round": rs.locked_round,
                "locked_block_hash": (
                    rs.locked_block.hash().hex().upper()
                    if rs.locked_block is not None else ""),
                "valid_round": rs.valid_round,
                "commit_round": rs.commit_round,
                "validators": {
                    "total_voting_power":
                        rs.validators.total_voting_power()
                        if rs.validators else 0,
                    "count": rs.validators.size() if rs.validators else 0,
                },
            }
            if votes is not None:
                out_rs["votes"] = [{
                    "round": r,
                    "prevotes": str(votes.prevotes(r).bit_array()),
                    "precommits": str(votes.precommits(r).bit_array()),
                } for r in range(rs.round + 1)]
        peers = []
        reactor = getattr(self.node, "consensus_reactor", None)
        if reactor is not None:
            with reactor._lock:
                for pid, ps in reactor._peer_state.items():
                    peers.append({
                        "node_address": pid,
                        "peer_state": {
                            "height": ps.step.height,
                            "round": ps.step.round,
                            "step": ps.step.step,
                            "prevotes": (str(ps.prevotes)
                                         if ps.prevotes else ""),
                            "precommits": (str(ps.precommits)
                                           if ps.precommits else ""),
                        }})
        return {"round_state": out_rs, "peers": peers}

    GENESIS_CHUNK_SIZE = 16 * 1024 * 1024  # reference rpc/core/net.go
    _genesis_bytes = None  # serialized once (the doc is immutable)

    def genesis_chunked(self, chunk=None):
        """Reference rpc/core/net.go GenesisChunked: base64 16MB chunks
        for genesis docs too large for one response (serialized once —
        the route exists for LARGE docs, so per-request re-serialization
        would be O(size) per chunk)."""
        if self._genesis_bytes is None:
            self._genesis_bytes = self.node.genesis.to_json().encode()
        data = self._genesis_bytes
        nchunks = max(1, -(-len(data) // self.GENESIS_CHUNK_SIZE))
        i = _int_arg(chunk, 0) or 0
        if not 0 <= i < nchunks:
            raise RPCError(
                -32603,
                f"there are {nchunks} chunks, you asked for {i}")
        part = data[i * self.GENESIS_CHUNK_SIZE:
                    (i + 1) * self.GENESIS_CHUNK_SIZE]
        return {"chunk": i, "total": nchunks, "data": _b64(part)}

    def dial_seeds(self, seeds=None):
        """UNSAFE (rpc.unsafe config): crawl the given seeds
        (reference rpc/core/net.go UnsafeDialSeeds)."""
        if not seeds:
            raise RPCError(-32602, "no seeds provided")
        pex = getattr(self.node, "pex_reactor", None)
        if pex is None:
            raise RPCError(-32603, "pex reactor is not running")
        pex.seeds.extend(s for s in seeds if s not in pex.seeds)

        def dial():
            for s in seeds:
                peer = self.node.switch.dial_peer(s)
                if peer is not None:
                    pex._request_addrs(peer)
        # async: each dead address costs a ~10s connect timeout, which
        # would hold the HTTP request open (reference DialSeeds is async)
        threading.Thread(target=dial, daemon=True,
                         name="rpc-dial-seeds").start()
        return {"log": f"dialing seeds: {seeds}"}

    def dial_peers(self, peers=None, persistent=None, unconditional=None,
                   private=None):
        """UNSAFE (rpc.unsafe config): dial the given peers (reference
        rpc/core/net.go UnsafeDialPeers)."""
        if not peers:
            raise RPCError(-32602, "no peers provided")

        def dial():
            for p in peers:
                self.node.switch.dial_peer(p, persistent=bool(persistent))
        threading.Thread(target=dial, daemon=True,
                         name="rpc-dial-peers").start()
        return {"log": f"dialing peers: {peers}"}

    def unconfirmed_txs(self, limit=None):
        n = _int_arg(limit, 30) or 30
        txs = self.node.mempool.reap_max_txs(n)
        return {"n_txs": len(txs), "total": self.node.mempool.size(),
                "txs": [_b64(t) for t in txs]}

    def num_unconfirmed_txs(self):
        return {"n_txs": self.node.mempool.size(),
                "total": self.node.mempool.size()}

    def check_tx(self, tx=None):
        """App-only check without admitting to the mempool
        (reference rpc/core/abci.go CheckTx)."""
        from tendermint_tpu.abci.types import RequestCheckTx
        r = self.node.app.check_tx(RequestCheckTx(tx=_parse_tx(tx)))
        return {"code": r.code, "data": _b64(r.data or b""), "log": r.log}

    def _gate(self):
        """The node's IngressGate, iff running (ADR-018)."""
        g = getattr(self.node, "ingress_gate", None)
        return g if g is not None and g.is_running() else None

    @staticmethod
    def _busy_error(retry_after_s) -> RPCError:
        ms = int(max(0.0, retry_after_s or 1.0) * 1000)
        return RPCError(RPC_BUSY_CODE,
                        f"mempool is busy: retry after {ms} ms")

    def _admit_tx(self, raw: bytes):
        """Admission through the IngressGate when present: overload
        (queue full / rate limited / verify shed) surfaces as a
        429-style RPCError with a Retry-After hint instead of holding
        the HTTP thread on a blocking app call.  Without a gate this
        is exactly the old synchronous mempool.check_tx."""
        g = self._gate()
        if g is None:
            return self.node.mempool.check_tx(raw)
        fut = g.submit(raw, source="rpc")
        try:
            r = fut.result(timeout=10.0)
        except TimeoutError:
            # queue is moving but not fast enough for this caller:
            # same retryable overload class as a full queue
            raise self._busy_error(g.retry_after_s())
        if fut.retry_after_s is not None:
            raise self._busy_error(fut.retry_after_s)
        return r

    def broadcast_tx_async(self, tx=None):
        raw = _parse_tx(tx)
        from tendermint_tpu.types.block import tx_hash
        g = self._gate()
        if g is None:
            threading.Thread(target=self._add_tx, args=(raw,),
                             daemon=True).start()
        else:
            fut = g.submit(raw, source="rpc")
            # fire-and-forget EXCEPT overload: an immediately-settled
            # busy/ratelimit rejection means the tx was never queued —
            # silently returning a hash would lie to the client
            if fut.done() and fut.retry_after_s is not None:
                raise self._busy_error(fut.retry_after_s)
        return {"code": 0, "data": "", "log": "",
                "hash": tx_hash(raw).hex().upper()}

    def broadcast_tx_sync(self, tx=None):
        raw = _parse_tx(tx)
        from tendermint_tpu.types.block import tx_hash
        r = self._admit_tx(raw)
        return {"code": r.code, "data": _b64(r.data or b""), "log": r.log,
                "hash": tx_hash(raw).hex().upper()}

    def broadcast_tx_commit_raw(self, raw: bytes, timeout=30.0):
        """Reference rpc/core/mempool.go:52: add to mempool, wait for the
        tx to land in a committed block via the event bus.  Returns the
        full ABCI response objects (check_tx, deliver_tx, height) so both
        the JSON route and the gRPC BroadcastAPI can surface every field
        (data, gas, events, codespace) the reference returns."""
        from tendermint_tpu.abci.types import ResponseDeliverTx
        sub = self.node.event_bus.subscribe("Tx") \
            if self.node.event_bus else None
        try:
            r = self._admit_tx(raw)
            if not r.is_ok():
                return r, None, 0
            import queue as _q
            import time as _t
            deadline = _t.monotonic() + float(timeout)
            while sub is not None and _t.monotonic() < deadline:
                try:
                    ev = sub.queue.get(timeout=0.25)
                except _q.Empty:
                    continue
                data = ev.data or {}
                if data.get("tx") == raw:
                    res = data.get("result") or ResponseDeliverTx()
                    return r, res, data.get("height", 0)
            raise RPCError(-32603,
                           "timed out waiting for tx to be committed")
        finally:
            if sub is not None:
                self.node.event_bus.unsubscribe(sub)

    @staticmethod
    def _tx_result_json(res) -> dict:
        """Full ResponseCheckTx/ResponseDeliverTx projection (reference
        rpc/core/types ResultBroadcastTxCommit JSON shape)."""
        if res is None:
            return {}
        return {
            "code": res.code,
            "data": _b64(res.data or b""),
            "log": res.log,
            "gas_wanted": str(getattr(res, "gas_wanted", 0)),
            "gas_used": str(getattr(res, "gas_used", 0)),
            "events": [{"type": getattr(e, "type", ""),
                        "attributes": dict(getattr(e, "attributes", None)
                                           or {})}
                       for e in (getattr(res, "events", None) or [])],
            "codespace": getattr(res, "codespace", ""),
        }

    def broadcast_tx_commit(self, tx=None, timeout=30.0):
        raw = _parse_tx(tx)
        from tendermint_tpu.types.block import tx_hash
        th = tx_hash(raw)
        ct, dt, height = self.broadcast_tx_commit_raw(raw, timeout)
        return {"check_tx": self._tx_result_json(ct),
                "deliver_tx": self._tx_result_json(dt),
                "hash": th.hex().upper(), "height": height}

    def abci_info(self):
        from tendermint_tpu.abci.types import RequestInfo
        r = self.node.app.info(RequestInfo())
        return {"response": {
            "data": getattr(r, "data", ""),
            "last_block_height": getattr(r, "last_block_height", 0),
            "last_block_app_hash":
                _b64(getattr(r, "last_block_app_hash", b"") or b"")}}

    def abci_query(self, path="", data="", height=None, prove=False):
        from tendermint_tpu.abci.types import RequestQuery
        raw = bytes.fromhex(data) if data else b""
        r = self.node.app.query(RequestQuery(
            data=raw, path=path, height=_int_arg(height, 0) or 0,
            prove=bool(prove)))
        return {"response": {
            "code": r.code, "log": r.log, "key": _b64(r.key or b""),
            "value": _b64(r.value or b""), "height": r.height,
            "proof_ops": [{"type": t, "key": _b64(k), "data": _b64(d)}
                          for (t, k, d) in
                          (getattr(r, "proof_ops", None) or [])]}}

    def light_block(self, height=None):
        """Canonical-proto light block for light-client providers
        (reference light/provider/http fetches signed header + validator
        set over RPC; here both ride one call as canonical bytes so the
        provider verifies exactly what consensus signed)."""
        from tendermint_tpu.types.light_block import SignedHeader

        store = self.node.block_store
        h = _int_arg(height, store.height())
        meta = store.load_block_meta(h)
        vals = self.node.state_store.load_validators(h)
        if meta is None or vals is None:
            raise RPCError(-32603, f"no light block at height {h}")
        canonical = h < store.height()
        com = store.load_block_commit(h) if canonical \
            else store.load_seen_commit(h)
        if com is None:
            raise RPCError(-32603, f"no commit at height {h}")
        sh = SignedHeader(meta.header, com)
        return {"height": h,
                "signed_header": _b64(sh.proto()),
                "validator_set": _b64(vals.proto())}

    def block_proto(self, height=None):
        """Canonical-proto block bytes (hash-verifiable against a light
        client's verified header)."""
        h = _int_arg(height, self.node.block_store.height())
        block = self.node.block_store.load_block(h)
        if block is None:
            raise RPCError(-32603, f"no block at height {h}")
        return {"height": h, "block": _b64(block.proto())}

    def broadcast_evidence(self, evidence=None):
        from tendermint_tpu.types.evidence import evidence_from_proto
        ev = evidence_from_proto(base64.b64decode(evidence))
        self.node.evidence_pool.add_evidence(ev)
        return {"hash": ev.hash().hex().upper()}

    def tx(self, hash=None, prove=False):
        indexer = getattr(self.node, "tx_indexer", None)
        if indexer is None:
            raise RPCError(-32603, "tx indexing is disabled")
        res = indexer.get(bytes.fromhex(hash))
        if res is None:
            raise RPCError(-32603, f"tx {hash} not found")
        return res

    def tx_search(self, query="", prove=False, page=None, per_page=None,
                  order_by=""):
        indexer = getattr(self.node, "tx_indexer", None)
        if indexer is None:
            raise RPCError(-32603, "tx indexing is disabled")
        return indexer.search(query, _int_arg(page, 1) or 1,
                              _int_arg(per_page, 30) or 30)

    def block_search(self, query="", page=None, per_page=None, order_by=""):
        indexer = getattr(self.node, "block_indexer", None)
        if indexer is None:
            raise RPCError(-32603, "block indexing is disabled")
        return indexer.search(query, _int_arg(page, 1) or 1,
                              _int_arg(per_page, 30) or 30)

    # -- json shaping ------------------------------------------------------

    def _add_tx(self, raw):
        try:
            self.node.mempool.check_tx(raw)
        except Exception:
            pass

    def _bid_json(self, bid):
        return {"hash": bid.hash.hex().upper(),
                "parts": {"total": bid.part_set_header.total,
                          "hash": bid.part_set_header.hash.hex().upper()}}

    def _header_json(self, h):
        # amino-JSON dialect (libs/amino_json): int64 -> string, time ->
        # RFC3339, so reference clients parse the response unchanged
        from tendermint_tpu.libs import amino_json as aj
        return {
            "version": {"block": str(h.version.block),
                        "app": str(h.version.app)},
            "chain_id": h.chain_id, "height": str(h.height),
            "time": aj.ts_rfc3339(h.time),
            "last_block_id": self._bid_json(h.last_block_id),
            "last_commit_hash": h.last_commit_hash.hex().upper(),
            "data_hash": h.data_hash.hex().upper(),
            "validators_hash": h.validators_hash.hex().upper(),
            "next_validators_hash": h.next_validators_hash.hex().upper(),
            "consensus_hash": h.consensus_hash.hex().upper(),
            "app_hash": h.app_hash.hex().upper(),
            "last_results_hash": h.last_results_hash.hex().upper(),
            "evidence_hash": h.evidence_hash.hex().upper(),
            "proposer_address": h.proposer_address.hex().upper(),
        }

    def _commit_json(self, c):
        from tendermint_tpu.libs import amino_json as aj
        if c is None:
            return None
        return {
            "height": str(c.height), "round": c.round,
            "block_id": self._bid_json(c.block_id),
            "signatures": [{
                "block_id_flag": int(s.block_id_flag),
                "validator_address": s.validator_address.hex().upper(),
                "timestamp": aj.ts_rfc3339(s.timestamp),
                "signature": _b64(s.signature or b""),
            } for s in c.signatures],
        }

    def _vset_json(self, vs):
        from tendermint_tpu.libs import amino_json as aj
        prop = vs.get_proposer()
        return {"validators": [aj.validator_json(v)
                               for v in vs.validators],
                "proposer": aj.validator_json(prop) if prop else None}

    def _block_json(self, b: Block):
        from tendermint_tpu.libs import amino_json as aj
        return {"header": self._header_json(b.header),
                "data": {"txs": [_b64(t) for t in b.data.txs]},
                # tagged amino-JSON evidence (reference
                # types/evidence.go:529 RegisterType)
                "evidence": {"evidence": [
                    aj.evidence_json(ev, self._header_json,
                                     self._commit_json, self._vset_json)
                    for ev in b.evidence]},
                "last_commit": self._commit_json(b.last_commit)}

    def _meta_json(self, m):
        return {"block_id": self._bid_json(m.block_id),
                "block_size": m.block_size,
                "header": self._header_json(m.header),
                "num_txs": m.num_txs}

    def _val_json(self, v):
        from tendermint_tpu.libs import amino_json as aj
        return aj.validator_json(v)
