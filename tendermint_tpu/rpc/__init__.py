"""RPC surface (reference rpc/): JSON-RPC server over HTTP + client.
Routes mirror rpc/core/routes.go:10-49."""
from .client import HTTPClient, RPCClientError
from .server import RPCError, RPCServer

__all__ = ["RPCServer", "RPCError", "HTTPClient", "RPCClientError"]
