"""gRPC broadcast API (reference rpc/grpc/types.proto BroadcastAPI +
rpc/grpc/api.go): the two-method legacy convenience service —
Ping(RequestPing) and BroadcastTx(RequestBroadcastTx{tx=1}) returning
ResponseBroadcastTx{check_tx=1, deliver_tx=2} with the abci response
sub-messages.  BroadcastTx has broadcast_tx_commit semantics (reference
api.go:19 routes through core.BroadcastTxCommit).

Same no-codegen approach as the ABCI gRPC transport (abci/grpc.py):
grpcio generic handlers with the in-tree proto codec; the abci
sub-messages reuse the socket codec byte-for-byte.  Gated by
`[rpc] grpc_laddr` (reference config/config.go GRPCListenAddress).
"""
from __future__ import annotations

try:
    import grpc
except ImportError:  # optional dep: grpc_util.require_grpc() raises a
    grpc = None      # clear error before any use can be reached

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.grpc import (decode_response_bare,
                                      encode_response_bare)
from tendermint_tpu.libs import grpc_util
from tendermint_tpu.libs import log as tmlog
from tendermint_tpu.libs import protodec as pd
from tendermint_tpu.libs import protoenc as pe
from tendermint_tpu.libs.service import BaseService

_logger = tmlog.logger("rpc.grpc")

SERVICE = "tendermint.rpc.grpc.BroadcastAPI"


def _enc_broadcast_response(check_tx: abci.ResponseCheckTx,
                            deliver_tx: abci.ResponseDeliverTx) -> bytes:
    return (pe.message_field_always(
                1, encode_response_bare("check_tx", check_tx)) +
            pe.message_field_always(
                2, encode_response_bare("deliver_tx", deliver_tx)))


def _dec_broadcast_response(data: bytes):
    f = pd.parse(data)
    ct = decode_response_bare("check_tx", pd.get_bytes(f, 1))
    dt = decode_response_bare("deliver_tx", pd.get_bytes(f, 2))
    return ct, dt


class GRPCBroadcastServer(BaseService):
    """Serve BroadcastAPI next to (not on) the JSON-RPC listener,
    routing BroadcastTx through the node's broadcast_tx_commit handler
    (reference rpc/grpc/client_server.go StartGRPCServer)."""

    def __init__(self, rpc_handlers, addr: str):
        super().__init__("rpc-grpc")
        self._rpc = rpc_handlers  # rpc/server.RPCServer (handler methods)
        self._addr = addr
        self._server = None

    @property
    def addr(self) -> str:
        return self._addr

    def on_start(self):
        def ping(_req_bytes, _ctx):
            return b""  # ResponsePing {}

        def broadcast_tx(req_bytes, ctx):
            try:
                f = pd.parse(req_bytes)
                tx = pd.get_bytes(f, 1)
                # full abci response objects — data/gas/events/codespace
                # survive onto the wire (reference BroadcastAPI returns
                # the complete ResponseCheckTx/ResponseDeliverTx)
                ct, dt, _h = self._rpc.broadcast_tx_commit_raw(tx)
                return _enc_broadcast_response(
                    ct, dt if dt is not None else abci.ResponseDeliverTx())
            except Exception as e:  # noqa: BLE001 - surface as status
                _logger.error("BroadcastTx failed", err=str(e))
                ctx.abort(grpc.StatusCode.INTERNAL, str(e))

        handlers = {
            "Ping": grpc_util.raw_unary_handler(ping),
            "BroadcastTx": grpc_util.raw_unary_handler(broadcast_tx),
        }
        # BroadcastTx blocks up to ~30s waiting for commit, so keep
        # enough workers that in-flight broadcasts never starve Ping
        self._server, self._addr = grpc_util.serve_generic(
            SERVICE, handlers, self._addr, 8, "rpc-grpc")
        _logger.info("gRPC broadcast API up", laddr=self._addr)

    def on_stop(self):
        if self._server is not None:
            self._server.stop(grace=1.0).wait()


class GRPCBroadcastClient:
    """Reference rpc/grpc/client_server.go StartGRPCClient."""

    def __init__(self, addr: str, connect_timeout: float = 10.0):
        self.addr = addr
        self._channel = grpc_util.connect_channel(
            addr, connect_timeout, "gRPC broadcast API")
        self._ping = grpc_util.raw_stub(self._channel, SERVICE, "Ping")
        self._btx = grpc_util.raw_stub(self._channel, SERVICE,
                                       "BroadcastTx")

    def close(self):
        self._channel.close()

    def ping(self) -> None:
        self._ping(b"", timeout=10.0)

    def broadcast_tx(self, tx: bytes, timeout: float = 60.0):
        """Returns (ResponseCheckTx, ResponseDeliverTx)."""
        out = self._btx(pe.bytes_field(1, tx), timeout=timeout)
        return _dec_broadcast_response(out)
