"""HTTP + WebSocket JSON-RPC clients (reference rpc/client/http/http.go)
— the operator / light-client transport to a node's RPC server."""
from __future__ import annotations

import base64
import json
import os
import queue
import socket
import struct
import threading
import urllib.request
from typing import Optional


class RPCClientError(Exception):
    pass


class HTTPClient:
    def __init__(self, addr: str, timeout: float = 10.0):
        # accept host:port or full URL
        if not addr.startswith("http"):
            addr = "http://" + addr
        self.base = addr.rstrip("/")
        self.timeout = timeout
        self._id = 0

    def call(self, method: str, **params):
        self._id += 1
        body = json.dumps({
            "jsonrpc": "2.0", "id": self._id, "method": method,
            "params": params}).encode()
        req = urllib.request.Request(
            self.base, data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            payload = json.loads(resp.read())
        if "error" in payload:
            e = payload["error"]
            raise RPCClientError(f"{e.get('code')}: {e.get('message')}")
        return payload["result"]

    # -- typed helpers (reference rpc/client/http methods) ----------------

    def status(self):
        return self.call("status")

    def health(self):
        return self.call("health")

    def net_info(self):
        return self.call("net_info")

    def genesis(self):
        return self.call("genesis")

    def block(self, height: Optional[int] = None):
        return self.call("block", **({} if height is None
                                     else {"height": height}))

    def block_results(self, height: Optional[int] = None):
        return self.call("block_results", **({} if height is None
                                             else {"height": height}))

    def commit(self, height: Optional[int] = None):
        return self.call("commit", **({} if height is None
                                      else {"height": height}))

    def validators(self, height: Optional[int] = None, page: int = 1,
                   per_page: int = 100):
        kw = {"page": page, "per_page": per_page}
        if height is not None:
            kw["height"] = height
        return self.call("validators", **kw)

    def broadcast_tx_sync(self, tx: bytes):
        return self.call("broadcast_tx_sync",
                         tx=base64.b64encode(tx).decode())

    def broadcast_tx_async(self, tx: bytes):
        return self.call("broadcast_tx_async",
                         tx=base64.b64encode(tx).decode())

    def broadcast_tx_commit(self, tx: bytes, timeout: float = 30.0):
        return self.call("broadcast_tx_commit",
                         tx=base64.b64encode(tx).decode(), timeout=timeout)

    def abci_info(self):
        return self.call("abci_info")

    def abci_query(self, path: str = "", data: bytes = b""):
        return self.call("abci_query", path=path, data=data.hex())

    def tx(self, tx_hash: bytes):
        return self.call("tx", hash=tx_hash.hex())

    def tx_search(self, query: str, page: int = 1, per_page: int = 30):
        return self.call("tx_search", query=query, page=page,
                         per_page=per_page)


class WSClient:
    """WebSocket JSON-RPC client with event subscriptions (reference
    rpc/client/http/http.go:764 WSEvents + rpc/jsonrpc/client/ws_client):

        ws = WSClient("127.0.0.1:26657")
        sub = ws.subscribe("tm.event = 'NewBlock'")
        ev = sub.get(timeout=10)   # JSON-RPC notification params
        ws.unsubscribe("tm.event = 'NewBlock'")
        ws.close()

    RPC calls (ws.call) multiplex over the same connection.  A reader
    thread demuxes responses (by id) from event notifications (routed to
    the matching subscription's queue)."""

    def __init__(self, addr: str, timeout: float = 10.0):
        if addr.startswith("http"):
            addr = addr.split("://", 1)[1]
        host, _, port = addr.rstrip("/").rpartition(":")
        self.timeout = timeout
        self._sock = socket.create_connection((host or "127.0.0.1",
                                               int(port)), timeout=timeout)
        key = base64.b64encode(os.urandom(16)).decode()
        self._sock.sendall(
            f"GET /websocket HTTP/1.1\r\nHost: {host}\r\n"
            f"Upgrade: websocket\r\nConnection: Upgrade\r\n"
            f"Sec-WebSocket-Key: {key}\r\n"
            f"Sec-WebSocket-Version: 13\r\n\r\n".encode())
        resp = b""
        while b"\r\n\r\n" not in resp:
            chunk = self._sock.recv(4096)
            if not chunk:
                raise RPCClientError("websocket handshake failed: closed")
            resp += chunk
        if b"101" not in resp.split(b"\r\n", 1)[0]:
            raise RPCClientError(
                f"websocket handshake refused: {resp[:120]!r}")
        # the connect timeout must not linger: a quiet subscription (>10s
        # between events) would time out recv in the reader thread and
        # kill the client (same pattern as p2p/switch.py post-handshake)
        self._sock.settimeout(None)
        self._id = 0
        self._send_lock = threading.Lock()
        self._pending: dict = {}      # id -> Queue for the response
        self._subs: dict = {}         # query string -> Queue of events
        self._closed = threading.Event()
        self._reader = threading.Thread(target=self._read_loop,
                                        daemon=True, name="ws-rpc-reader")
        self._reader.start()

    # -- framing (RFC 6455; client frames are masked) ----------------------

    def _send_json(self, obj):
        payload = json.dumps(obj).encode()
        mask = os.urandom(4)
        n = len(payload)
        if n < 126:
            hdr = struct.pack("!BB", 0x81, 0x80 | n)
        elif n < 1 << 16:
            hdr = struct.pack("!BBH", 0x81, 0x80 | 126, n)
        else:
            hdr = struct.pack("!BBQ", 0x81, 0x80 | 127, n)
        masked = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
        with self._send_lock:
            self._sock.sendall(hdr + mask + masked)

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            c = self._sock.recv(n - len(buf))
            if not c:
                raise ConnectionError("websocket closed")
            buf += c
        return buf

    def _read_loop(self):
        try:
            while not self._closed.is_set():
                b1, b2 = self._recv_exact(2)
                ln = b2 & 0x7F
                if ln == 126:
                    (ln,) = struct.unpack("!H", self._recv_exact(2))
                elif ln == 127:
                    (ln,) = struct.unpack("!Q", self._recv_exact(8))
                data = self._recv_exact(ln)
                op = b1 & 0x0F
                if op == 8:       # close
                    break
                if op == 9:       # ping -> pong
                    with self._send_lock:
                        self._sock.sendall(b"\x8a\x80" + os.urandom(4))
                    continue
                if op != 1:
                    continue
                try:
                    msg = json.loads(data)
                except (json.JSONDecodeError, UnicodeDecodeError):
                    continue
                self._route(msg)
        except (ConnectionError, OSError):
            pass
        finally:
            self._closed.set()
            for q in list(self._pending.values()):
                q.put(RPCClientError("websocket closed"))

    def _route(self, msg: dict):
        rid = msg.get("id")
        if rid in self._pending:
            self._pending.pop(rid).put(msg)
            return
        # event notification: route by the subscription's query
        result = msg.get("result") or {}
        qstr = result.get("query", "")
        q = self._subs.get(qstr)
        if q is not None:
            q.put(result)

    # -- API ---------------------------------------------------------------

    def call(self, method: str, **params):
        if self._closed.is_set():
            raise RPCClientError("websocket closed")
        waiter: "queue.Queue" = queue.Queue(maxsize=1)
        with self._send_lock:  # id allocation + registration are atomic
            self._id += 1
            rid = self._id
            self._pending[rid] = waiter
        self._send_json({"jsonrpc": "2.0", "id": rid, "method": method,
                         "params": params})
        try:
            msg = waiter.get(timeout=self.timeout)
        except queue.Empty:
            self._pending.pop(rid, None)
            raise RPCClientError(f"{method}: timed out")
        if isinstance(msg, Exception):
            raise msg
        if "error" in msg:
            e = msg["error"]
            raise RPCClientError(f"{e.get('code')}: {e.get('message')}")
        return msg.get("result")

    def subscribe(self, query: str) -> "queue.Queue":
        """Subscribe to a pubsub query; returns the Queue its event
        notifications land on."""
        q: "queue.Queue" = queue.Queue()
        self._subs[query] = q
        self.call("subscribe", query=query)
        return q

    def unsubscribe(self, query: str) -> None:
        self._subs.pop(query, None)
        if not self._closed.is_set():
            self.call("unsubscribe", query=query)

    def close(self):
        self._closed.set()
        try:
            self._sock.close()
        except OSError:
            pass
