"""HTTP JSON-RPC client (reference rpc/client/http/http.go) — the operator
/ light-client transport to a node's RPC server."""
from __future__ import annotations

import base64
import json
import urllib.request
from typing import Optional


class RPCClientError(Exception):
    pass


class HTTPClient:
    def __init__(self, addr: str, timeout: float = 10.0):
        # accept host:port or full URL
        if not addr.startswith("http"):
            addr = "http://" + addr
        self.base = addr.rstrip("/")
        self.timeout = timeout
        self._id = 0

    def call(self, method: str, **params):
        self._id += 1
        body = json.dumps({
            "jsonrpc": "2.0", "id": self._id, "method": method,
            "params": params}).encode()
        req = urllib.request.Request(
            self.base, data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            payload = json.loads(resp.read())
        if "error" in payload:
            e = payload["error"]
            raise RPCClientError(f"{e.get('code')}: {e.get('message')}")
        return payload["result"]

    # -- typed helpers (reference rpc/client/http methods) ----------------

    def status(self):
        return self.call("status")

    def health(self):
        return self.call("health")

    def net_info(self):
        return self.call("net_info")

    def genesis(self):
        return self.call("genesis")

    def block(self, height: Optional[int] = None):
        return self.call("block", **({} if height is None
                                     else {"height": height}))

    def block_results(self, height: Optional[int] = None):
        return self.call("block_results", **({} if height is None
                                             else {"height": height}))

    def commit(self, height: Optional[int] = None):
        return self.call("commit", **({} if height is None
                                      else {"height": height}))

    def validators(self, height: Optional[int] = None, page: int = 1,
                   per_page: int = 100):
        kw = {"page": page, "per_page": per_page}
        if height is not None:
            kw["height"] = height
        return self.call("validators", **kw)

    def broadcast_tx_sync(self, tx: bytes):
        return self.call("broadcast_tx_sync",
                         tx=base64.b64encode(tx).decode())

    def broadcast_tx_async(self, tx: bytes):
        return self.call("broadcast_tx_async",
                         tx=base64.b64encode(tx).decode())

    def broadcast_tx_commit(self, tx: bytes, timeout: float = 30.0):
        return self.call("broadcast_tx_commit",
                         tx=base64.b64encode(tx).decode(), timeout=timeout)

    def abci_info(self):
        return self.call("abci_info")

    def abci_query(self, path: str = "", data: bytes = b""):
        return self.call("abci_query", path=path, data=data.hex())

    def tx(self, tx_hash: bytes):
        return self.call("tx", hash=tx_hash.hex())

    def tx_search(self, query: str, page: int = 1, per_page: int = 30):
        return self.call("tx_search", query=query, page=page,
                         per_page=per_page)
