"""Light-client serving RPC surface (ADR-026).

Deliberately THIN: these handlers only decode canonical proto bytes
and encode verdicts — admission, rate limiting, coalescing and the
follow cursors all live in light/service.py.  An overload refusal from
the serving plane (queue full / per-client rate limit) surfaces as the
same 429-style ``RPC_BUSY_CODE`` + Retry-After hint the mempool
ingress gate uses, so a flooding light client is told to back off
while consensus never sees the load.

Routes (registered by RPCServer when the node runs a LightServe):

  light_verify     one header verification (adjacent / non_adjacent /
                   trusting) against proto-encoded headers + valsets
  light_subscribe  open a bounded follow cursor
  light_poll       advance a follow cursor (proto LightBlocks out);
                   an evicted cursor answers {"evicted": true} — the
                   client re-subscribes
  light_unsubscribe
  light_status     the serving plane's debug report
"""
from __future__ import annotations

import base64
from fractions import Fraction

from tendermint_tpu.rpc.server import RPC_BUSY_CODE, RPCError

# service disabled / not running: distinct from busy so clients don't
# retry a node that will never serve them
RPC_LIGHT_OFF_CODE = -32012


def _serve(node):
    s = getattr(node, "light_serve", None)
    if s is None or not s.is_running():
        raise RPCError(RPC_LIGHT_OFF_CODE, "light serving is disabled")
    return s


def _unb64(v, what: str) -> bytes:
    if not isinstance(v, str):
        raise RPCError(-32602, f"{what} must be base64 proto bytes")
    try:
        return base64.b64decode(v)
    except Exception:  # noqa: BLE001 - caller input
        raise RPCError(-32602, f"{what}: invalid base64")


def _signed_header(v, what: str):
    from tendermint_tpu.types.light_block import SignedHeader
    try:
        return SignedHeader.from_proto(_unb64(v, what))
    except RPCError:
        raise
    except Exception as e:  # noqa: BLE001 - caller input
        raise RPCError(-32602, f"{what}: bad signed header: {e}")


def _valset(v, what: str):
    from tendermint_tpu.types.validator_set import ValidatorSet
    try:
        return ValidatorSet.from_proto(_unb64(v, what))
    except RPCError:
        raise
    except Exception as e:  # noqa: BLE001 - caller input
        raise RPCError(-32602, f"{what}: bad validator set: {e}")


def _trust_level(v) -> Fraction:
    if v is None:
        from tendermint_tpu.light.verifier import DEFAULT_TRUST_LEVEL
        return DEFAULT_TRUST_LEVEL
    try:
        f = Fraction(str(v))
    except (ValueError, ZeroDivisionError):
        raise RPCError(-32602, f"bad trust_level {v!r}")
    if not (0 < f <= 1):
        raise RPCError(-32602, "trust_level must be in (0, 1]")
    return f


def light_verify(server, kind=None, trusted=None, trusted_vals=None,
                 untrusted=None, untrusted_vals=None, now=None,
                 trust_level=None, trusting_period_s=None,
                 max_clock_drift_s=None, client=None):
    """One verification through the serving plane.  Busy verdicts map
    to RPC_BUSY_CODE with a Retry-After hint (429 semantics)."""
    from tendermint_tpu.light.service import LightRequest
    s = _serve(server.node)
    if kind not in ("adjacent", "non_adjacent", "trusting"):
        raise RPCError(-32602, f"bad light verify kind {kind!r}")
    kwargs = {"trust_level": _trust_level(trust_level)}
    if now is not None:
        from tendermint_tpu.types.basic import Timestamp
        sec = float(now)  # epoch seconds on the wire
        kwargs["now"] = Timestamp(int(sec), int((sec - int(sec)) * 1e9))
    if trusting_period_s is not None:
        kwargs["trusting_period_s"] = float(trusting_period_s)
    if max_clock_drift_s is not None:
        kwargs["max_clock_drift_s"] = float(max_clock_drift_s)
    if trusted is not None:
        kwargs["trusted"] = _signed_header(trusted, "trusted")
    if trusted_vals is not None:
        kwargs["trusted_vals"] = _valset(trusted_vals, "trusted_vals")
    if untrusted is not None:
        kwargs["untrusted"] = _signed_header(untrusted, "untrusted")
    if untrusted_vals is not None:
        kwargs["untrusted_vals"] = _valset(untrusted_vals,
                                           "untrusted_vals")
    req = LightRequest(kind, s.chain_id, **kwargs)
    v = s.verify(req, client=str(client or "rpc"))
    if v.retry_after_s is not None:
        ms = int(max(0.0, v.retry_after_s) * 1000)
        raise RPCError(RPC_BUSY_CODE,
                       f"light serve is busy: retry after {ms} ms")
    return {"ok": v.ok, "error": v.error}


def light_subscribe(server, client=None, from_height=None):
    s = _serve(server.node)
    cid = s.subscribe(str(client or "rpc"),
                      int(from_height) if from_height else 0)
    return {"cursor": cid}


def light_poll(server, cursor=None, max_items=None):
    s = _serve(server.node)
    if not cursor:
        raise RPCError(-32602, "cursor is required")
    blocks = s.poll(str(cursor),
                    int(max_items) if max_items else None)
    if blocks is None:
        # evicted under pressure (or never existed): the client
        # re-subscribes from its own trusted height
        return {"evicted": True, "blocks": []}
    return {"evicted": False,
            "blocks": [base64.b64encode(b.proto()).decode()
                       for b in blocks]}


def light_unsubscribe(server, cursor=None):
    s = _serve(server.node)
    if cursor:
        s.unsubscribe(str(cursor))
    return {}


def light_status(server):
    s = getattr(server.node, "light_serve", None)
    if s is None:
        from tendermint_tpu.light import service as lsvc
        return {"enabled": lsvc.enabled(), "running": False}
    return s.report()


def register(server):
    """Called from RPCServer.__init__ — adds the light-serve routes.
    The routes exist even when the plane is disabled so clients get a
    crisp RPC_LIGHT_OFF_CODE instead of method-not-found."""
    server.routes["light_verify"] = \
        lambda **kw: light_verify(server, **kw)
    server.routes["light_subscribe"] = \
        lambda **kw: light_subscribe(server, **kw)
    server.routes["light_poll"] = \
        lambda **kw: light_poll(server, **kw)
    server.routes["light_unsubscribe"] = \
        lambda **kw: light_unsubscribe(server, **kw)
    server.routes["light_status"] = \
        lambda **kw: light_status(server, **kw)
