"""State sync (reference statesync/): bootstrap a fresh node from an
application snapshot discovered over p2p, verified against light-client
headers, instead of replaying the whole chain."""
from .reactor import (CHUNK_CHANNEL, SNAPSHOT_CHANNEL, StateSyncReactor)
from .stateprovider import StateProvider
from .syncer import SnapshotRejected, StateSyncError, Syncer

__all__ = ["Syncer", "StateSyncError", "SnapshotRejected", "StateProvider",
           "StateSyncReactor", "SNAPSHOT_CHANNEL", "CHUNK_CHANNEL"]
