"""State sync reactor (reference statesync/reactor.go): snapshot discovery
on channel 0x60, chunk transfer on 0x61; the serving side answers from its
app's snapshot store.

ADR-022: the serving side is a bounded, rate-limited, per-peer-fair
chunk server (the IngressGate admission pattern, ADR-018).  Chunk
requests enter a bounded queue drained by a worker thread; a full
queue or a peer over its token bucket gets an immediate busy response
carrying a Retry-After hint instead of silently wedging the receive
routine — one node feeding many joiners cannot be starved by a
flooding peer, and the refusal is explicit so honest joiners rotate.
The fetching side requests from exactly the sender the Syncer's
rotation picked (attribution: a failure is charged to the peer that
earned it, reference syncer.go:411 fetchChunks).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, Optional

from tendermint_tpu.abci import types as abci
from tendermint_tpu.libs import fail, trace
from tendermint_tpu.libs import protodec as pd
from tendermint_tpu.libs import protoenc as pe
from tendermint_tpu.p2p import wire
from tendermint_tpu.p2p.connection import ChannelDescriptor
from tendermint_tpu.p2p.switch import Peer, Reactor

from .ledger import RestoreLedger
from .syncer import (ChunkBusy, StateSyncError, Syncer, default_chunk_timeout_s,
                     metrics, _param)

SNAPSHOT_CHANNEL = 0x60
CHUNK_CHANNEL = 0x61

# serving-side defaults ([statesync] serve_rate_per_s / serve_burst /
# the bounded request queue)
DEFAULT_SERVE_RATE_PER_S = 100.0
DEFAULT_SERVE_BURST = 32
SERVE_QUEUE = 128


def default_serve_rate_per_s() -> float:
    return max(0.0, _param("serve_rate_per_s", "TM_TPU_SS_SERVE_RATE",
                           DEFAULT_SERVE_RATE_PER_S, float))


def default_serve_burst() -> int:
    return max(1, _param("serve_burst", "TM_TPU_SS_SERVE_BURST",
                         DEFAULT_SERVE_BURST, int))


@dataclass
class SnapshotsRequest:
    pass


@dataclass
class SnapshotsResponse:
    height: int
    format: int
    chunks: int
    hash: bytes
    metadata: bytes


@dataclass
class ChunkRequest:
    height: int
    format: int
    index: int


@dataclass
class ChunkResponse:
    height: int
    format: int
    index: int
    chunk: bytes
    missing: bool = False
    # ADR-022 serving-side backpressure: the server is refusing (queue
    # full / rate limited), come back in retry_after_ms.  Old peers
    # ignore the extra fields (unknown proto fields skip).
    busy: bool = False
    retry_after_ms: int = 0


# -- wire codec (proto/tendermint/statesync/types.proto Message oneof:
# snapshots_request=1, snapshots_response=2, chunk_request=3,
# chunk_response=4) -------------------------------------------------------

def encode_msg(msg) -> bytes:
    if isinstance(msg, SnapshotsRequest):
        return wire.oneof_encode(1, b"")
    if isinstance(msg, SnapshotsResponse):
        return wire.oneof_encode(2, (
            pe.varint_field(1, msg.height) + pe.varint_field(2, msg.format)
            + pe.varint_field(3, msg.chunks) + pe.bytes_field(4, msg.hash)
            + pe.bytes_field(5, msg.metadata)))
    if isinstance(msg, ChunkRequest):
        return wire.oneof_encode(3, (
            pe.varint_field(1, msg.height) + pe.varint_field(2, msg.format)
            + pe.varint_field(3, msg.index)))
    if isinstance(msg, ChunkResponse):
        return wire.oneof_encode(4, (
            pe.varint_field(1, msg.height) + pe.varint_field(2, msg.format)
            + pe.varint_field(3, msg.index) + pe.bytes_field(4, msg.chunk)
            + pe.varint_field(5, 1 if msg.missing else 0)
            + pe.varint_field(6, 1 if msg.busy else 0)
            + pe.varint_field(7, int(msg.retry_after_ms))))
    raise TypeError(f"unknown statesync message {type(msg).__name__}")


def _dec_snapshots_response(b: bytes) -> SnapshotsResponse:
    f = pd.parse(b)
    return SnapshotsResponse(
        height=pd.get_uint(f, 1), format=pd.get_uint(f, 2),
        chunks=pd.get_uint(f, 3), hash=pd.get_bytes(f, 4),
        metadata=pd.get_bytes(f, 5))


def _dec_chunk_response(b: bytes) -> ChunkResponse:
    f = pd.parse(b)
    return ChunkResponse(
        height=pd.get_uint(f, 1), format=pd.get_uint(f, 2),
        index=pd.get_uint(f, 3), chunk=pd.get_bytes(f, 4),
        missing=bool(pd.get_uint(f, 5)), busy=bool(pd.get_uint(f, 6)),
        retry_after_ms=pd.get_uint(f, 7))


def _dec_chunk_request(b: bytes) -> ChunkRequest:
    f = pd.parse(b)
    return ChunkRequest(height=pd.get_uint(f, 1), format=pd.get_uint(f, 2),
                        index=pd.get_uint(f, 3))


_HANDLERS = {
    1: lambda b: SnapshotsRequest(),
    2: _dec_snapshots_response,
    3: _dec_chunk_request,
    4: _dec_chunk_response,
}


def decode_msg(data: bytes):
    return wire.oneof_decode(data, _HANDLERS)


wire.register_codec(SNAPSHOT_CHANNEL, encode_msg, decode_msg)
wire.register_codec(CHUNK_CHANNEL, encode_msg, decode_msg)


class _TokenBucket:
    """Per-peer serve rate limiter; mutated under the server lock
    only (the IngressGate pattern, ADR-018)."""

    __slots__ = ("rate", "burst", "tokens", "last")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = rate
        self.burst = burst
        self.tokens = burst
        self.last = now

    def allow(self, now: float) -> bool:
        self.tokens = min(self.burst,
                          self.tokens + (now - self.last) * self.rate)
        self.last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


# bound on distinct per-peer buckets (peer ids are remote-controlled)
_MAX_BUCKETS = 1024


class StateSyncReactor(Reactor):
    """BaseService lifecycle via Reactor; started/stopped by the Switch
    (reference statesync/reactor.go: a p2p.BaseReactor)."""

    def __init__(self, app, state_provider=None,
                 ledger: Optional[RestoreLedger] = None,
                 fetchers: Optional[int] = None,
                 chunk_timeout_s: Optional[float] = None,
                 retries: Optional[int] = None,
                 serve_rate_per_s: Optional[float] = None,
                 serve_burst: Optional[int] = None,
                 serve_queue: int = SERVE_QUEUE):
        super().__init__("STATESYNC")
        from tendermint_tpu.libs import log as tmlog
        self.log = tmlog.logger("statesync")
        self.app = app
        self.chunk_timeout_s = chunk_timeout_s
        self.syncer: Optional[Syncer] = None
        if state_provider is not None:
            self.syncer = Syncer(app, state_provider, self._fetch_chunk,
                                 ban_peer=self._ban_peer,
                                 fetchers=fetchers,
                                 chunk_timeout_s=chunk_timeout_s,
                                 retries=retries, ledger=ledger,
                                 stop_event=self.quitting)
        # received chunks keyed by (height, format, index, SENDER):
        # the syncer runs several concurrent fetchers, so responses
        # must route to the fetcher that asked — and only a response
        # from the peer that fetcher ASKED may satisfy it (a Byzantine
        # peer blind-spamming missing/busy responses must not be able
        # to charge its spoofs to an honest requested sender).  Only
        # AWAITED keys are stored at all: an unawaited response is
        # stale or spam either way, and dropping it bounds the map by
        # the fetcher count instead of by remote-controlled input
        self._chunks: dict = {}
        self._awaited: set = set()
        self._chunks_cv = threading.Condition()
        # -- serving side (bounded queue + per-peer token buckets) -----
        self.serve_rate_per_s = serve_rate_per_s \
            if serve_rate_per_s is not None else default_serve_rate_per_s()
        self.serve_burst = float(serve_burst) if serve_burst is not None \
            else float(default_serve_burst())
        self.serve_queue_size = max(1, int(serve_queue))
        # _serve_cv guards _serve_queue + _buckets only (bookkeeping);
        # the app and peer sends happen with it released
        self._serve_cv = threading.Condition()
        self._serve_queue: "deque" = deque()
        self._buckets: Dict[str, _TokenBucket] = {}

    def get_channels(self):
        return [
            ChannelDescriptor(SNAPSHOT_CHANNEL, priority=5,
                              send_queue_capacity=10),
            ChannelDescriptor(CHUNK_CHANNEL, priority=3,
                              send_queue_capacity=16),
        ]

    def on_start(self):
        self.spawn(self._serve_worker, name="statesync-chunk-server")

    def on_stop(self):
        with self._serve_cv:
            self._serve_queue.clear()
            self._serve_cv.notify_all()

    def add_peer(self, peer: Peer):
        if self.syncer is not None:
            peer.try_send(SNAPSHOT_CHANNEL, SnapshotsRequest())

    def request_snapshots(self):
        """Re-poll every peer for snapshots (the serving side may only
        take its first snapshot after we connected)."""
        if self.switch is not None:
            for peer in list(self.switch.peers.values()):
                peer.try_send(SNAPSHOT_CHANNEL, SnapshotsRequest())

    def receive(self, ch_id: int, peer: Peer, msg_bytes: bytes):
        msg = decode_msg(msg_bytes)
        if ch_id == SNAPSHOT_CHANNEL:
            if isinstance(msg, SnapshotsRequest):
                for s in (self.app.list_snapshots() or [])[-10:]:
                    peer.try_send(SNAPSHOT_CHANNEL, SnapshotsResponse(
                        s.height, s.format, s.chunks, s.hash, s.metadata))
            elif isinstance(msg, SnapshotsResponse) and self.syncer:
                self.log.debug("discovered snapshot", peer=peer.id,
                               height=msg.height, format=msg.format)
                self.syncer.add_snapshot(
                    abci.Snapshot(msg.height, msg.format, msg.chunks,
                                  msg.hash, msg.metadata), peer.id)
        elif ch_id == CHUNK_CHANNEL:
            if isinstance(msg, ChunkRequest):
                self._admit_chunk_request(msg, peer)
            elif isinstance(msg, ChunkResponse):
                key = (msg.height, msg.format, msg.index, peer.id)
                with self._chunks_cv:
                    if key in self._awaited:
                        self._chunks[key] = msg
                        self._chunks_cv.notify_all()

    # -- chunk serving (ADR-022: the IngressGate admission pattern) --------

    def serve_depth(self) -> int:
        with self._serve_cv:
            return len(self._serve_queue)

    def _retry_after_ms(self) -> int:
        """Crude Retry-After: a full queue at the configured rate."""
        rate = self.serve_rate_per_s or 100.0
        return int(min(5000.0, max(100.0,
                                   1000.0 * self.serve_depth() / rate)))

    def _refuse(self, msg: ChunkRequest, peer: Peer, reason: str):
        metrics().serve_refused.inc(reason=reason)
        peer.try_send(CHUNK_CHANNEL, ChunkResponse(
            msg.height, msg.format, msg.index, b"", busy=True,
            retry_after_ms=self._retry_after_ms()))

    def _admit_chunk_request(self, msg: ChunkRequest, peer: Peer):
        """Admission on the receive thread: token bucket + bounded
        queue; refusal is an immediate busy response, never a blocked
        channel read."""
        m = metrics()
        now = time.monotonic()
        with self._serve_cv:
            if self.serve_rate_per_s > 0:
                b = self._buckets.get(peer.id)
                if b is None:
                    if len(self._buckets) >= _MAX_BUCKETS:
                        idle = [k for k, v in self._buckets.items()
                                if v.tokens >= v.burst
                                or now - v.last > 300.0]
                        for k in idle:
                            del self._buckets[k]
                        if len(self._buckets) >= _MAX_BUCKETS:
                            self._buckets.clear()  # identity churn flood
                    b = self._buckets[peer.id] = _TokenBucket(
                        self.serve_rate_per_s, self.serve_burst, now)
                allowed = b.allow(now)
            else:
                allowed = True
            if allowed and len(self._serve_queue) < self.serve_queue_size:
                self._serve_queue.append((msg, peer))
                depth = len(self._serve_queue)
                self._serve_cv.notify()
                refuse_reason = None
            else:
                depth = len(self._serve_queue)
                refuse_reason = "ratelimit" if not allowed else "busy"
        m.serve_queue_depth.set(depth)
        if refuse_reason is not None:
            self._refuse(msg, peer, refuse_reason)

    def _serve_worker(self):
        m = metrics()
        while not self.quitting.is_set():
            with self._serve_cv:
                while not self._serve_queue and \
                        not self.quitting.is_set():
                    self._serve_cv.wait(0.1)
                if self.quitting.is_set():
                    return
                msg, peer = self._serve_queue.popleft()
                depth = len(self._serve_queue)
            m.serve_queue_depth.set(depth)
            with trace.span("statesync.serve", height=msg.height,
                            chunk=msg.index, peer=peer.id):
                try:
                    fail.inject("statesync.serve")
                    chunk = self.app.load_snapshot_chunk(
                        msg.height, msg.format, msg.index)
                except Exception as e:  # noqa: BLE001 - chaos/app fault:
                    # the serving side must stay up; the requester gets
                    # an explicit busy and retries elsewhere
                    self.log.error("chunk serve failed", chunk=msg.index,
                                   err=str(e))
                    self._refuse(msg, peer, "error")
                    continue
                if peer.try_send(CHUNK_CHANNEL, ChunkResponse(
                        msg.height, msg.format, msg.index, chunk or b"",
                        missing=not chunk)):
                    m.chunks_served.inc()
                else:
                    # channel backpressure: drop — the requester times
                    # out and rotates; blocking here would let one slow
                    # peer stall every other joiner's queue
                    m.serve_refused.inc(reason="backpressure")

    # -- chunk fetch over p2p (the Syncer's fetcher) -----------------------

    def _ban_peer(self, peer_id: str, reason: str):
        sw = self.switch
        if sw is None:
            return
        self.log.info("banning peer", peer=peer_id, reason=reason)
        peer = sw.peers.get(peer_id)
        if peer is not None:
            sw.stop_peer_for_error(peer, reason)

    def _fetch_chunk(self, snapshot: abci.Snapshot, index: int,
                     sender: str):
        """One chunk request/response from EXACTLY the requested
        sender; called concurrently by the syncer's fetcher pool, which
        owns rotation and failure attribution (a silent fallback to a
        different peer here would mis-charge its failures)."""
        sw = self.switch
        peer = sw.peers.get(sender) if sw else None
        if peer is None:
            raise StateSyncError(f"peer {sender} gone")
        key = (snapshot.height, snapshot.format, index, sender)
        with self._chunks_cv:
            self._chunks.pop(key, None)  # drop any stale response
            self._awaited.add(key)
        peer.try_send(CHUNK_CHANNEL, ChunkRequest(
            snapshot.height, snapshot.format, index))
        timeout_s = self.chunk_timeout_s \
            if self.chunk_timeout_s is not None \
            else default_chunk_timeout_s()
        deadline = time.monotonic() + timeout_s
        try:
            with self._chunks_cv:
                while key not in self._chunks:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise StateSyncError(f"chunk {index} timed out")
                    self._chunks_cv.wait(remaining)
                msg = self._chunks.pop(key)
        finally:
            with self._chunks_cv:
                self._awaited.discard(key)
                self._chunks.pop(key, None)
        if msg.busy:
            raise ChunkBusy(f"peer {sender} busy serving chunk {index}",
                            retry_after_s=msg.retry_after_ms / 1000.0)
        if msg.missing:
            raise StateSyncError(f"peer lacks chunk {index}")
        return msg.chunk, sender
