"""State sync reactor (reference statesync/reactor.go): snapshot discovery
on channel 0x60, chunk transfer on 0x61; the serving side answers from its
app's snapshot store."""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Optional

from tendermint_tpu.abci import types as abci
from tendermint_tpu.libs import protodec as pd
from tendermint_tpu.libs import protoenc as pe
from tendermint_tpu.p2p import wire
from tendermint_tpu.p2p.connection import ChannelDescriptor
from tendermint_tpu.p2p.switch import Peer, Reactor

from .syncer import StateSyncError, Syncer

SNAPSHOT_CHANNEL = 0x60
CHUNK_CHANNEL = 0x61
CHUNK_TIMEOUT_S = 15.0


@dataclass
class SnapshotsRequest:
    pass


@dataclass
class SnapshotsResponse:
    height: int
    format: int
    chunks: int
    hash: bytes
    metadata: bytes


@dataclass
class ChunkRequest:
    height: int
    format: int
    index: int


@dataclass
class ChunkResponse:
    height: int
    format: int
    index: int
    chunk: bytes
    missing: bool = False


# -- wire codec (proto/tendermint/statesync/types.proto Message oneof:
# snapshots_request=1, snapshots_response=2, chunk_request=3,
# chunk_response=4) -------------------------------------------------------

def encode_msg(msg) -> bytes:
    if isinstance(msg, SnapshotsRequest):
        return wire.oneof_encode(1, b"")
    if isinstance(msg, SnapshotsResponse):
        return wire.oneof_encode(2, (
            pe.varint_field(1, msg.height) + pe.varint_field(2, msg.format)
            + pe.varint_field(3, msg.chunks) + pe.bytes_field(4, msg.hash)
            + pe.bytes_field(5, msg.metadata)))
    if isinstance(msg, ChunkRequest):
        return wire.oneof_encode(3, (
            pe.varint_field(1, msg.height) + pe.varint_field(2, msg.format)
            + pe.varint_field(3, msg.index)))
    if isinstance(msg, ChunkResponse):
        return wire.oneof_encode(4, (
            pe.varint_field(1, msg.height) + pe.varint_field(2, msg.format)
            + pe.varint_field(3, msg.index) + pe.bytes_field(4, msg.chunk)
            + pe.varint_field(5, 1 if msg.missing else 0)))
    raise TypeError(f"unknown statesync message {type(msg).__name__}")


def _dec_snapshots_response(b: bytes) -> SnapshotsResponse:
    f = pd.parse(b)
    return SnapshotsResponse(
        height=pd.get_uint(f, 1), format=pd.get_uint(f, 2),
        chunks=pd.get_uint(f, 3), hash=pd.get_bytes(f, 4),
        metadata=pd.get_bytes(f, 5))


def _dec_chunk_response(b: bytes) -> ChunkResponse:
    f = pd.parse(b)
    return ChunkResponse(
        height=pd.get_uint(f, 1), format=pd.get_uint(f, 2),
        index=pd.get_uint(f, 3), chunk=pd.get_bytes(f, 4),
        missing=bool(pd.get_uint(f, 5)))


def _dec_chunk_request(b: bytes) -> ChunkRequest:
    f = pd.parse(b)
    return ChunkRequest(height=pd.get_uint(f, 1), format=pd.get_uint(f, 2),
                        index=pd.get_uint(f, 3))


_HANDLERS = {
    1: lambda b: SnapshotsRequest(),
    2: _dec_snapshots_response,
    3: _dec_chunk_request,
    4: _dec_chunk_response,
}


def decode_msg(data: bytes):
    return wire.oneof_decode(data, _HANDLERS)


wire.register_codec(SNAPSHOT_CHANNEL, encode_msg, decode_msg)
wire.register_codec(CHUNK_CHANNEL, encode_msg, decode_msg)


class StateSyncReactor(Reactor):
    """BaseService lifecycle via Reactor; started/stopped by the Switch
    (reference statesync/reactor.go: a p2p.BaseReactor)."""

    def __init__(self, app, state_provider=None):
        super().__init__("STATESYNC")
        from tendermint_tpu.libs import log as tmlog
        self.log = tmlog.logger("statesync")
        self.app = app
        self.syncer: Optional[Syncer] = None
        if state_provider is not None:
            self.syncer = Syncer(app, state_provider, self._fetch_chunk,
                                 ban_peer=self._ban_peer)
        # received chunks keyed by (height, format, index): the syncer
        # runs several concurrent fetchers, so responses must route to
        # the fetcher that asked — a shared FIFO would let one fetcher
        # consume (and drop) another's chunk
        self._chunks: dict = {}
        self._chunks_cv = threading.Condition()

    def get_channels(self):
        return [
            ChannelDescriptor(SNAPSHOT_CHANNEL, priority=5,
                              send_queue_capacity=10),
            ChannelDescriptor(CHUNK_CHANNEL, priority=3,
                              send_queue_capacity=16),
        ]

    def add_peer(self, peer: Peer):
        if self.syncer is not None:
            peer.try_send(SNAPSHOT_CHANNEL, SnapshotsRequest())

    def request_snapshots(self):
        """Re-poll every peer for snapshots (the serving side may only
        take its first snapshot after we connected)."""
        if self.switch is not None:
            for peer in list(self.switch.peers.values()):
                peer.try_send(SNAPSHOT_CHANNEL, SnapshotsRequest())

    def receive(self, ch_id: int, peer: Peer, msg_bytes: bytes):
        msg = decode_msg(msg_bytes)
        if ch_id == SNAPSHOT_CHANNEL:
            if isinstance(msg, SnapshotsRequest):
                for s in (self.app.list_snapshots() or [])[-10:]:
                    peer.try_send(SNAPSHOT_CHANNEL, SnapshotsResponse(
                        s.height, s.format, s.chunks, s.hash, s.metadata))
            elif isinstance(msg, SnapshotsResponse) and self.syncer:
                self.log.debug("discovered snapshot", peer=peer.id,
                               height=msg.height, format=msg.format)
                self.syncer.add_snapshot(
                    abci.Snapshot(msg.height, msg.format, msg.chunks,
                                  msg.hash, msg.metadata), peer.id)
        elif ch_id == CHUNK_CHANNEL:
            if isinstance(msg, ChunkRequest):
                chunk = self.app.load_snapshot_chunk(msg.height, msg.format,
                                                     msg.index)
                peer.try_send(CHUNK_CHANNEL, ChunkResponse(
                    msg.height, msg.format, msg.index, chunk or b"",
                    missing=not chunk))
            elif isinstance(msg, ChunkResponse):
                with self._chunks_cv:
                    self._chunks[(msg.height, msg.format, msg.index)] = \
                        (msg, peer.id)
                    self._chunks_cv.notify_all()

    # -- chunk fetch over p2p (the Syncer's fetcher) -----------------------

    def _ban_peer(self, peer_id: str, reason: str):
        sw = self.switch
        if sw is None:
            return
        self.log.info("banning peer", peer=peer_id, reason=reason)
        peer = sw.peers.get(peer_id)
        if peer is not None:
            sw.stop_peer_for_error(peer, reason)

    def _fetch_chunk(self, snapshot: abci.Snapshot, index: int,
                     peer_hint: str):
        """One chunk request/response; called concurrently by the
        syncer's fetcher pool, each call spreading across the available
        peers (reference syncer.go:411 runs parallel fetchers)."""
        sw = self.switch
        peers = list(sw.peers.values()) if sw else []
        peer = sw.peers.get(peer_hint) if sw else None
        if peer is None and peers:
            peer = peers[index % len(peers)]
        if peer is None:
            raise StateSyncError("no peers to fetch chunks from")
        key = (snapshot.height, snapshot.format, index)
        with self._chunks_cv:
            self._chunks.pop(key, None)  # drop any stale response
        peer.try_send(CHUNK_CHANNEL, ChunkRequest(
            snapshot.height, snapshot.format, index))
        import time as _t
        deadline = _t.monotonic() + CHUNK_TIMEOUT_S
        with self._chunks_cv:
            while key not in self._chunks:
                remaining = deadline - _t.monotonic()
                if remaining <= 0:
                    raise StateSyncError(f"chunk {index} timed out")
                self._chunks_cv.wait(remaining)
            msg, sender = self._chunks.pop(key)
        if msg.missing:
            raise StateSyncError(f"peer lacks chunk {index}")
        return msg.chunk, sender
