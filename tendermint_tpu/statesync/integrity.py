"""Per-chunk snapshot integrity (ADR-022).

The reference snapshot protocol (statesync/chunks.go) hands every
fetched chunk to the app and only finds out a peer lied when the
restore's final app-hash check fails — one corrupt chunk costs the
whole download and cannot be attributed to its sender.  This module
gives a snapshot self-describing chunk integrity: the serving side
packs the SHA-256 digest of every chunk into the snapshot's free-form
``metadata`` field together with the RFC-6962 merkle root over those
digests (crypto/merkle's iterative, host-vectorized reduction), and
the fetch plane verifies each chunk against its digest ON THE FETCH
THREAD, before the app ever sees peer bytes.

Trust model: the digests come from the advertising peer and are
self-consistent (the embedded root must re-derive from the digest
list, so a malformed advertisement is refused at discovery), but the
ROOT of trust stays the light-client-verified app hash checked after
the restore — a Byzantine advertiser can still lie coherently, and
then the final check rejects the snapshot exactly as before.  What
the digests buy is attribution and locality: a bad chunk is detected
at fetch time, charged to its sender (ban + refetch elsewhere), and
costs one chunk instead of one restore.

Snapshots without this metadata (other apps, older peers) verify
nothing per chunk and keep the reference end-to-end behavior.
"""
from __future__ import annotations

import hashlib
from typing import List, Optional

from tendermint_tpu.crypto.merkle import hash_from_byte_slices

# magic + root(32) + chunks * digest(32)
CHUNK_META_MAGIC = b"CKH1"
_DIGEST_LEN = 32


def make_chunk_metadata(chunks: List[bytes]) -> bytes:
    """Serving side: digest every chunk and bind the list under one
    merkle root (the iterative host reduction — one hashlib pass per
    level, no recursion)."""
    digests = [hashlib.sha256(c).digest() for c in chunks]
    root = hash_from_byte_slices(digests)
    return CHUNK_META_MAGIC + root + b"".join(digests)


def parse_chunk_metadata(metadata: bytes,
                         nchunks: int) -> Optional[List[bytes]]:
    """Digest list carried in a snapshot's metadata, or None when the
    snapshot doesn't carry one (legacy format — per-chunk verification
    is skipped and the app's end-to-end check is the only guard).
    A PRESENT-but-inconsistent header (bad length, root mismatch,
    wrong chunk count) also returns None: treat a malformed
    advertisement like an unverifiable one rather than trusting half
    a header."""
    if not metadata or not metadata.startswith(CHUNK_META_MAGIC):
        return None
    body = metadata[len(CHUNK_META_MAGIC):]
    if len(body) < _DIGEST_LEN:
        return None
    root, rest = body[:_DIGEST_LEN], body[_DIGEST_LEN:]
    if len(rest) % _DIGEST_LEN != 0:
        return None
    digests = [rest[i:i + _DIGEST_LEN]
               for i in range(0, len(rest), _DIGEST_LEN)]
    if len(digests) != nchunks:
        return None
    if hash_from_byte_slices(digests) != root:
        return None
    return digests


def verify_chunk(digests: List[bytes], index: int, chunk: bytes) -> bool:
    """One chunk against its advertised digest (the fetch-thread
    check)."""
    if not 0 <= index < len(digests):
        return False
    return hashlib.sha256(chunk).digest() == digests[index]


def verify_chunks(digests: Optional[List[bytes]],
                  stored: dict) -> List[int]:
    """Host-vectorized prefix re-verification for crash resume: hash
    every stored chunk in one pass and return the indices whose bytes
    still match their digest (hashlib releases the GIL on large
    buffers, so this is one tight C loop over the restore ledger's
    contents).  With no digest list every stored chunk is returned —
    the app's end-to-end hash check remains the guard, exactly as for
    a live legacy fetch."""
    if digests is None:
        return sorted(stored)
    return sorted(i for i, c in stored.items()
                  if verify_chunk(digests, i, c))
