"""State provider (reference statesync/stateprovider.go): reconstruct
consensus State at a snapshot height from light-client-verified headers.

Header offsets (spec): header(H+1).app_hash is the app state AFTER block H;
header(H+1).last_results_hash covers block H's results; validators for H+1
come from light block H+1 and NextValidators from header(H+1)'s
next_validators_hash — obtained via light block H+2 or the provider.
"""
from __future__ import annotations

from typing import Optional

from tendermint_tpu.light.client import Client as LightClient
from tendermint_tpu.state.state import State
from tendermint_tpu.types.basic import BlockID, Timestamp


class StateProvider:
    def __init__(self, light_client: LightClient,
                 now: Timestamp | None = None, params_fn=None):
        """params_fn(height) -> ConsensusParams fetches the chain's params
        (the reference's RPC provider queries /consensus_params); defaults
        are used when unavailable.  `now` pins verification time for
        deterministic tests; None means wall clock per call (a live chain
        keeps minting headers after construction)."""
        self.lc = light_client
        self.now = now
        self.params_fn = params_fn

    def _lb(self, height: int):
        return self.lc.verify_light_block_at_height(
            height, self.now if self.now is not None else Timestamp.now())

    def app_hash(self, height: int) -> bytes:
        """Trusted app hash of the state AFTER block `height`
        (reference stateprovider.go:94 AppHash -> header H+1)."""
        return self._lb(height + 1).signed_header.header.app_hash

    def commit(self, height: int):
        """The commit certifying block `height` (from light block H+1's
        last commit... the light block's own commit IS for H)."""
        return self._lb(height).signed_header.commit

    def state(self, height: int) -> State:
        """Reference stateprovider.go:108 State: builds sm.State for
        consensus to resume at height+1."""
        h = self._lb(height)          # header H + commit for H
        h1 = self._lb(height + 1)     # carries post-H app hash / results
        h2 = self._lb(height + 2)     # validators for H+2 = next for H+1
        header1 = h1.signed_header.header
        return State(
            chain_id=header1.chain_id,
            initial_height=1,
            last_block_height=height,
            last_block_id=h1.signed_header.header.last_block_id,
            last_block_time=h.signed_header.header.time,
            next_validators=h2.validators,
            validators=h1.validators,
            last_validators=h.validators,
            last_height_validators_changed=0,
            consensus_params=self._params(height),
            last_height_consensus_params_changed=0,
            last_results_hash=header1.last_results_hash,
            app_hash=header1.app_hash,
            app_version=header1.version.app,
        )

    def _params(self, height: int):
        from tendermint_tpu.types.params import ConsensusParams
        if self.params_fn is not None:
            p = self.params_fn(height)
            if p is not None:
                return p
        return ConsensusParams()
