"""RestoreLedger: crash-resumable snapshot restore state (ADR-022).

A statesync restore used to live entirely in memory: a kill anywhere
between the first chunk and the final app-hash check threw the whole
download away.  The ledger persists a restore *manifest* (the snapshot
key plus the applied-chunk high-water mark) and every verified chunk
body, so a restarted node reopens the ledger, re-verifies the stored
prefix against the snapshot's chunk digests (statesync/integrity.py,
one vectorized hashlib pass), and resumes fetching from the frontier
instead of from zero.

Durability rides kvdb.GroupCommitDB exactly like the block pipeline
(ADR-017): chunk writes buffer in group mode and land as ONE inner
write_batch every ``group_every`` chunks — on SQLite one transaction
and one fsync per group, with the chaos seam ``kvdb.group_commit``
firing before each commit and the synchronous ``flush()`` fallback
recovering a failed async commit.  A crash between group commits
loses at most the open group; everything behind it is durable and
resumable.
"""
from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional

from tendermint_tpu.libs.kvdb import KVDB, GroupCommitDB

_MANIFEST_KEY = b"ss:manifest"
_CHUNK_PREFIX = b"ss:chunk:"


def _chunk_key(index: int) -> bytes:
    return _CHUNK_PREFIX + b"%08d" % index


class RestoreLedger:
    """One restore-in-progress per node (the node points it at
    ``data/statesync.db``; tests and in-memory nodes use MemDB).  All
    mutation under one leaf lock — the fetch plane's threads write
    concurrently."""

    def __init__(self, db: KVDB, group_every: int = 8):
        self.db = db if isinstance(db, GroupCommitDB) else GroupCommitDB(db)
        self.group_every = max(1, int(group_every))
        self._lock = threading.Lock()
        # serializes take_group+commit_group as ONE unit: several
        # fetcher threads reach the commit trigger concurrently, and
        # GroupCommitDB's contract demands groups land in take order
        # (a stalled older group committing after a newer one would
        # durably regress keys both touched — e.g. a drop()'s delete
        # re-landing over the refetched chunk)
        self._commit_lock = threading.Lock()
        self._since_commit = 0
        self._manifest: Optional[dict] = None

    # -- manifest ----------------------------------------------------------

    @staticmethod
    def _key_of(snapshot) -> dict:
        return {"height": int(snapshot.height),
                "format": int(snapshot.format),
                "hash": bytes(snapshot.hash).hex(),
                "chunks": int(snapshot.chunks)}

    def manifest(self) -> Optional[dict]:
        raw = self.db.get(_MANIFEST_KEY)
        if raw is None:
            return None
        try:
            m = json.loads(raw)
        except ValueError:
            return None
        return m if isinstance(m, dict) else None

    def begin(self, snapshot) -> Dict[int, bytes]:
        """Open (or resume) a restore of this snapshot.  A stored
        manifest for a DIFFERENT snapshot is cleared — its chunks
        belong to bytes we are no longer restoring.  Returns the
        stored chunk bodies (unverified; the syncer re-checks them
        against the digest list before trusting any)."""
        key = self._key_of(snapshot)
        with self._lock:
            m = self.manifest()
            if m is None or any(m.get(k) != v for k, v in key.items()):
                self._clear_locked()
                m = dict(key, high_water=-1)
                self.db.set(_MANIFEST_KEY,
                            json.dumps(m, sort_keys=True).encode())
            self._manifest = m
            self.db.begin_group_mode()
            stored: Dict[int, bytes] = {}
            for k, v in self.db.iterate_prefix(_CHUNK_PREFIX):
                try:
                    stored[int(k[len(_CHUNK_PREFIX):])] = v
                except ValueError:
                    continue
            return stored

    # -- chunk writes (fetch-plane threads) --------------------------------

    def put_chunk(self, index: int, data: bytes):
        """Buffer one verified chunk; every ``group_every`` puts the
        open group lands as one inner write_batch.  The async-commit
        chaos seam lives inside commit_group; a failed group commit
        degrades to the synchronous flush() fallback (which skips the
        seam — it IS the fallback), so a chaos raise costs latency,
        never chunks.  ``high_water`` (highest persisted index) is
        informational — resume correctness rests on the begin() rescan
        + digest re-verification, never on the mark."""
        commit = False
        with self._lock:
            self.db.set(_chunk_key(index), bytes(data))
            m = self._manifest
            if m is not None and index > int(m.get("high_water", -1)):
                m["high_water"] = index
                self.db.set(_MANIFEST_KEY,
                            json.dumps(m, sort_keys=True).encode())
            self._since_commit += 1
            if self._since_commit >= self.group_every:
                self._since_commit = 0
                commit = True
        if commit:
            with self._commit_lock:
                group = self.db.take_group()
                if group is None:
                    return
                try:
                    self.db.commit_group(group)
                except Exception:  # noqa: BLE001 - chaos/IO: sync fallback
                    try:
                        self.db.flush()
                    except Exception:  # noqa: BLE001 - durability is
                        # opportunistic: a dead disk (or a DB closed by
                        # a racing node shutdown) must not kill the
                        # in-memory restore, it only loses resume-ability
                        pass

    def chunk(self, index: int) -> Optional[bytes]:
        return self.db.get(_chunk_key(index))

    def drop(self, indices: List[int]):
        """Forget chunks the app refused (refetch_chunks) or that
        failed the resume re-verification."""
        with self._lock:
            for i in indices:
                self.db.delete(_chunk_key(i))
            m = self._manifest
            if m is not None and indices:
                m["high_water"] = min(int(m.get("high_water", -1)),
                                      min(indices) - 1)
                self.db.set(_MANIFEST_KEY,
                            json.dumps(m, sort_keys=True).encode())

    # -- lifecycle ---------------------------------------------------------

    def _clear_locked(self):
        dels = [k for k, _ in self.db.iterate_prefix(b"ss:")]
        if dels:
            self.db.write_batch([], dels)
        self._manifest = None
        self._since_commit = 0

    def clear(self):
        """Drop everything (snapshot rejected: its bytes are bad)."""
        with self._lock:
            self._clear_locked()

    def complete(self):
        """Restore verified end-to-end: nothing left to resume."""
        self.db.end_group_mode()
        self.clear()

    def flush(self):
        self.db.flush()

    def close(self):
        self.db.close()
