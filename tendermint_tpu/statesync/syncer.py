"""State sync syncer (reference statesync/syncer.go:141): discover
snapshots from peers, offer to the app, fetch + verify + apply chunks,
verify the restored app hash against a light-client-verified header,
and bootstrap consensus state at the snapshot height.

ADR-022 fast-join rework.  The fetch plane is a pipelined
fetch -> verify -> apply path:

  * N fetcher threads fill a chunk buffer while the calling thread
    applies chunks strictly in order — app apply of chunk k overlaps
    the fetch of k+1 (the BlockPipeline stage/commit discipline,
    ADR-017).
  * Chunk integrity is checked ON THE FETCH THREAD against the
    snapshot's chunk-digest metadata (statesync/integrity.py) BEFORE
    the app ever sees peer bytes: a corrupt chunk is charged to its
    sender (banned) and refetched elsewhere, costing one chunk
    instead of one restore.
  * Failure accounting is per PEER, not per chunk (_PeerBook):
    consecutive failures earn jittered capped backoff and eventually
    a ban, senders rotate across every peer that advertised the
    snapshot, and a fetch slower than the per-chunk deadline
    quarantines the slow peer.  The old per-chunk counters let a
    single dead ``sender_hint`` burn the whole retry budget.
  * Verified chunks land in the RestoreLedger (statesync/ledger.py,
    kvdb.GroupCommitDB group transactions) so a crash mid-restore
    reopens, re-verifies the stored prefix and resumes from the
    frontier instead of refetching from zero.

Chaos seams (libs/fail.py): ``statesync.fetch`` (per fetch attempt,
raise = transport fault charged to the peer; ``corrupt-chunk`` flips
the fetched bytes so the pre-app digest check must catch them),
``statesync.verify`` (raise = verification machinery fault, retried
like a transport error, the app never sees the chunk) and
``statesync.apply`` (raise = app-layer failure, the snapshot is
rejected — the reference behavior for an app blowing up on restore).
"""
from __future__ import annotations

import collections
import os
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from tendermint_tpu.abci import types as abci
from tendermint_tpu.libs import fail, slo, trace
from tendermint_tpu.state.state import State

from .integrity import parse_chunk_metadata, verify_chunk, verify_chunks
from .ledger import RestoreLedger  # noqa: F401 - re-export (node wiring)
from .stateprovider import StateProvider

# defaults (reference config.go ChunkFetchers / chunk retry discipline);
# the [statesync] config section replaces the old hardcoded
# CHUNK_FETCHERS / CHUNK_RETRIES module constants
DEFAULT_FETCHERS = 4
DEFAULT_CHUNK_TIMEOUT_MS = 15000.0
DEFAULT_RETRIES = 3
# sanity cap on a peer-declared chunk count: 2^16 chunks x 64KB-ish
# chunks bounds any snapshot we would ever restore; without it a
# Byzantine SnapshotsResponse (chunks=2^60) would OOM the fetch queue
MAX_SNAPSHOT_CHUNKS = 1 << 16


class StateSyncError(Exception):
    pass


class SnapshotUnverifiable(StateSyncError):
    """The chain has not outgrown the snapshot yet (headers H+1/H+2
    missing) — retriable, unlike a rejection."""


class SnapshotRejected(StateSyncError):
    pass


class ChunkBusy(StateSyncError):
    """The serving peer refused with busy + Retry-After (its bounded
    chunk server is saturated or rate limiting us) — back off that
    peer and rotate, without a failure strike: a loaded server is not
    a dead one."""

    def __init__(self, msg: str, retry_after_s: float = 0.5):
        super().__init__(msg)
        self.retry_after_s = max(0.05, float(retry_after_s))


# ---------------------------------------------------------------------------
# [statesync] config resolution: explicit Syncer args (the node wires
# them from config, so config wins over env in BOTH directions) >
# module overrides (set_config, node-less tooling) > env > default
# ---------------------------------------------------------------------------

_cfg_lock = threading.Lock()
_cfg: Dict[str, float] = {}


def set_config(fetchers: Optional[int] = None,
               chunk_timeout_ms: Optional[float] = None,
               retries: Optional[int] = None):
    """Module-level overrides for node-less tooling (bench, tests).
    None clears a dimension back to env/default."""
    with _cfg_lock:
        for k, v in (("fetchers", fetchers),
                     ("chunk_timeout_ms", chunk_timeout_ms),
                     ("retries", retries)):
            if v is None:
                _cfg.pop(k, None)
            else:
                _cfg[k] = v


def _param(key: str, env: str, default, cast):
    with _cfg_lock:
        if key in _cfg:
            return cast(_cfg[key])
    v = os.environ.get(env)
    if v:
        try:
            return cast(v)
        except (TypeError, ValueError):
            pass
    return default


def default_fetchers() -> int:
    return max(1, _param("fetchers", "TM_TPU_SS_FETCHERS",
                         DEFAULT_FETCHERS, int))


def default_chunk_timeout_s() -> float:
    return max(0.001, _param("chunk_timeout_ms",
                             "TM_TPU_SS_CHUNK_TIMEOUT_MS",
                             DEFAULT_CHUNK_TIMEOUT_MS, float) / 1000.0)


def default_retries() -> int:
    return max(1, _param("retries", "TM_TPU_SS_RETRIES",
                         DEFAULT_RETRIES, int))


# ---------------------------------------------------------------------------
# metrics (one process-global bundle; the Registry dedupes)
# ---------------------------------------------------------------------------

_metrics_lock = threading.Lock()
_metrics_obj = None


def metrics():
    global _metrics_obj
    with _metrics_lock:
        if _metrics_obj is None:
            from tendermint_tpu.libs.metrics import StateSyncMetrics
            _metrics_obj = StateSyncMetrics()
        return _metrics_obj


# ---------------------------------------------------------------------------
# per-peer failure accounting
# ---------------------------------------------------------------------------

class _PeerState:
    __slots__ = ("strikes", "until", "dead", "last_strike_t",
                 "busy_streak")

    def __init__(self):
        self.strikes = 0
        self.until = 0.0          # quarantined until (monotonic)
        self.dead = False
        self.last_strike_t = 0.0
        self.busy_streak = 0      # consecutive busy refusals


class _PeerBook:
    """Per-peer (not per-chunk) failure accounting for one snapshot's
    providers: jittered capped backoff on consecutive failures, slow-
    peer quarantine, immediate ban on proven misbehavior (corrupt
    chunk), round-robin sender rotation over the usable set.

    The strike counter is EPOCH-guarded: a fetch that started before
    the peer's last recorded strike belongs to the same failure burst
    (N concurrent fetchers all hitting a dead peer at once) and does
    not strike again — a peer earns one strike per backoff epoch, so
    ``retries`` bounds distinct failure rounds, not racing threads.
    """

    BACKOFF_BASE_S = 0.05
    BACKOFF_CAP_S = 2.0
    # a busy refusal costs no strike — but a peer that answers busy
    # FOREVER must not hang the restore forever either: every
    # BUSY_STRIKES_AFTER consecutive busies convert into one ordinary
    # strike, so a permanently-saturated (or Byzantine always-busy)
    # provider eventually exhausts its budget and the sync aborts
    # instead of looping (any real chunk resets the streak)
    BUSY_STRIKES_AFTER = 16

    def __init__(self, peers, retries: int,
                 ban_cb: Optional[Callable] = None):
        self._lock = threading.Lock()
        self._peers: Dict[str, _PeerState] = {}
        self._order: List[str] = []
        self._rr = 0
        self.retries = max(1, int(retries))
        self.ban_cb = ban_cb
        for p in peers:
            self.add(p)

    def add(self, peer_id: str):
        with self._lock:
            if peer_id not in self._peers:
                self._peers[peer_id] = _PeerState()
                self._order.append(peer_id)

    def _backoff_s(self, strikes: int) -> float:
        base = min(self.BACKOFF_CAP_S,
                   self.BACKOFF_BASE_S * (2 ** max(0, strikes - 1)))
        return base * random.uniform(0.5, 1.5)

    def pick(self) -> Tuple[Optional[str], float]:
        """Next sender, rotating round-robin across usable providers.
        Returns (peer, 0.0); or (None, wait_s) when every live peer is
        quarantined (wait_s = time to the earliest expiry); or
        (None, -1.0) when every provider is dead."""
        now = time.monotonic()
        with self._lock:
            n = len(self._order)
            live_until: List[float] = []
            for k in range(n):
                peer = self._order[(self._rr + k) % n]
                st = self._peers[peer]
                if st.dead:
                    continue
                if st.until > now:
                    live_until.append(st.until)
                    continue
                self._rr = (self._rr + k + 1) % n
                return peer, 0.0
            if live_until:
                return None, max(0.01, min(live_until) - now)
            return None, -1.0

    def failure(self, peer: str, started_at: float, reason: str) -> bool:
        """One failed fetch; returns True when the strike killed the
        peer.  Same-epoch concurrent failures don't re-strike."""
        ban = False
        with self._lock:
            st = self._peers.get(peer)
            if st is None or st.dead:
                return False
            if started_at < st.last_strike_t:
                return False        # same burst as the recorded strike
            st.strikes += 1
            st.last_strike_t = time.monotonic()
            st.until = st.last_strike_t + self._backoff_s(st.strikes)
            if st.strikes > self.retries:
                st.dead = True
                ban = True
        if ban and self.ban_cb is not None:
            self.ban_cb(peer, f"statesync: {self.retries} fetch "
                              f"failures exhausted ({reason})")
        return ban

    def busy(self, peer: str, retry_after_s: float):
        """Server said busy: honor its Retry-After, no strike — until
        BUSY_STRIKES_AFTER consecutive busies, which convert into one
        (the forever-busy liveness bound)."""
        strike = False
        with self._lock:
            st = self._peers.get(peer)
            if st is None or st.dead:
                return
            st.until = max(st.until, time.monotonic() + retry_after_s)
            st.busy_streak += 1
            if st.busy_streak >= self.BUSY_STRIKES_AFTER:
                st.busy_streak = 0
                strike = True
        if strike:
            self.failure(peer, time.monotonic(), "busy forever")

    def slow(self, peer: str, started_at: float):
        """Fetch exceeded the per-chunk deadline: quarantine so the
        rotation prefers faster providers, one strike per EPOCH (the
        same started_at guard as failure() — N concurrent fetches
        stalling together is one slow burst, not N)."""
        self.failure(peer, started_at, "slow fetch")

    def success(self, peer: str):
        with self._lock:
            st = self._peers.get(peer)
            if st is not None:
                st.strikes = 0
                st.until = 0.0
                st.busy_streak = 0

    def ban(self, peer: str, reason: str):
        """Proven misbehavior (corrupt chunk): dead immediately."""
        with self._lock:
            st = self._peers.get(peer)
            if st is None:
                st = self._peers[peer] = _PeerState()
                self._order.append(peer)
            already = st.dead
            st.dead = True
        if not already and self.ban_cb is not None:
            self.ban_cb(peer, reason)

    def all_dead(self) -> bool:
        with self._lock:
            return all(st.dead for st in self._peers.values())

    def dead_peers(self) -> List[str]:
        with self._lock:
            return [p for p, st in self._peers.items() if st.dead]


class Syncer:
    """chunk_fetcher(snapshot, index, sender) -> (bytes, sender_id);
    in the reactor this requests over p2p from exactly that sender, in
    tests it reads a serving app directly.  It may raise ChunkBusy to
    signal server backpressure (backoff, no strike)."""

    def __init__(self, app, state_provider: StateProvider,
                 chunk_fetcher: Callable, ban_peer: Optional[Callable] = None,
                 fetchers: Optional[int] = None,
                 chunk_timeout_s: Optional[float] = None,
                 retries: Optional[int] = None,
                 ledger: Optional[RestoreLedger] = None,
                 stop_event: Optional[threading.Event] = None):
        self.app = app
        self.state_provider = state_provider
        self.chunk_fetcher = chunk_fetcher
        self.ban_peer = ban_peer            # ban_peer(peer_id, reason)
        self.fetchers = fetchers
        self.chunk_timeout_s = chunk_timeout_s
        self.retries = retries
        self.ledger = ledger
        # a set stop_event aborts an IN-FLIGHT restore promptly (the
        # reactor passes its quitting event, so Node.stop never waits
        # behind a wedged fetch plane); the ledger keeps its verified
        # chunks — the next process resumes from the frontier
        self.stop_event = stop_event or threading.Event()
        from tendermint_tpu.libs import log as tmlog
        self.log = tmlog.logger("statesync")
        # snapshot key -> (snapshot, ordered provider peer ids)
        self._snapshots: Dict[tuple, Tuple[abci.Snapshot, List[str]]] = {}
        self._rejected: set = set()
        self._lock = threading.Lock()
        self.last_restore: Optional[dict] = None  # stats of the last sync

    # -- resolved parameters ----------------------------------------------

    def _fetchers(self) -> int:
        return max(1, self.fetchers) if self.fetchers is not None \
            else default_fetchers()

    def _chunk_timeout_s(self) -> float:
        return self.chunk_timeout_s if self.chunk_timeout_s is not None \
            else default_chunk_timeout_s()

    def _retries(self) -> int:
        return max(1, self.retries) if self.retries is not None \
            else default_retries()

    # -- discovery ---------------------------------------------------------

    @staticmethod
    def _snap_key(snapshot: abci.Snapshot) -> tuple:
        """Snapshot identity INCLUDING the metadata hash: two
        advertisements that differ only in metadata are different
        snapshots.  Without this, a Byzantine first-advertiser could
        attach a self-consistent-but-wrong digest list to the real
        (height, format, hash) — every chunk from honest providers
        would then fail verification and frame THEM as corrupt."""
        import hashlib
        return (snapshot.height, snapshot.format, snapshot.hash,
                hashlib.sha256(bytes(snapshot.metadata or b"")).digest())

    def add_snapshot(self, snapshot: abci.Snapshot, peer_id: str) -> bool:
        """Record a snapshot advertisement.  Returns True the first
        time a snapshot is seen; later advertisements of the same
        snapshot still register their sender as a provider — the fetch
        plane rotates across ALL advertising peers."""
        if not 0 < snapshot.chunks <= MAX_SNAPSHOT_CHUNKS:
            return False
        key = self._snap_key(snapshot)
        with self._lock:
            if key in self._rejected:
                return False
            entry = self._snapshots.get(key)
            if entry is not None:
                if peer_id not in entry[1]:
                    entry[1].append(peer_id)
                return False
            self._snapshots[key] = (snapshot, [peer_id])
            return True

    def _best_snapshots(self) -> List[Tuple[abci.Snapshot, List[str]]]:
        with self._lock:
            # drop blacklisted entries so retries never re-download
            # known-bad snapshots (_rejected otherwise only gates
            # add_snapshot, not selection)
            for key in [k for k in self._snapshots if k in self._rejected]:
                del self._snapshots[key]
            return sorted(
                ((s, list(peers)) for s, peers in self._snapshots.values()),
                key=lambda sp: (-sp[0].height, -sp[0].format))

    # -- sync (reference syncer.go:141 SyncAny) ----------------------------

    def sync_any(self) -> Tuple[State, "object"]:
        """Try discovered snapshots best-first.  Returns (bootstrapped
        state, certifying commit for the snapshot height)."""
        reasons = []
        for snapshot, providers in self._best_snapshots():
            if self.stop_event.is_set():
                reasons.append("statesync stopping")
                break
            try:
                self.log.info("offering snapshot to app",
                              height=snapshot.height,
                              format=snapshot.format,
                              chunks=snapshot.chunks,
                              providers=len(providers))
                result = self._sync_one(snapshot, providers)
                self.log.info("snapshot restored",
                              height=snapshot.height)
                return result
            except SnapshotUnverifiable as e:
                # may verify on a later attempt; do not blacklist
                self.log.debug("snapshot not yet verifiable",
                               height=snapshot.height, err=str(e))
                reasons.append(f"h{snapshot.height}: {e}")
                continue
            except SnapshotRejected as e:
                self.log.error("snapshot rejected",
                               height=snapshot.height, err=str(e))
                reasons.append(f"h{snapshot.height}: REJECTED {e}")
                with self._lock:
                    self._rejected.add(self._snap_key(snapshot))
                continue
        raise StateSyncError(
            "no viable snapshots" + (": " + "; ".join(reasons[:3])
                                     if reasons else ""))

    def _sync_one(self, snapshot: abci.Snapshot, providers: List[str]):
        # trusted app hash for the snapshot height comes from the light
        # client (header H+1 carries the post-H app hash,
        # reference syncer.go:287 verifyApp).  Bootstrapping height H needs
        # verified headers up to H+2 — a snapshot taken at the chain head
        # is rejected until the chain outgrows it.  State/commit are
        # verified once here and reused after the restore.  The light
        # verification itself rides the VerifyScheduler at COMMIT
        # priority (light/verifier.py priority_context), i.e. through
        # the comb path when the validator-set tables are resident.
        t0 = time.monotonic()
        try:
            app_hash = self.state_provider.app_hash(snapshot.height)
            state = self.state_provider.state(snapshot.height)
            commit = self.state_provider.commit(snapshot.height)
        except Exception as e:
            raise SnapshotUnverifiable(
                f"cannot verify snapshot height {snapshot.height} "
                f"(chain may not have outgrown it yet): {e}")
        try:
            try:
                resp = self.app.offer_snapshot(snapshot, app_hash)
                if resp.result != abci.ResponseOfferSnapshot.ACCEPT:
                    raise SnapshotRejected(f"offer result {resp.result}")
                stats = self._fetch_and_apply(snapshot, providers)
                # verify the restored app (syncer.go:544 verifyApp)
                info = self.app.info(abci.RequestInfo())
                if info.last_block_height != snapshot.height:
                    raise SnapshotRejected(
                        f"app restored to height "
                        f"{info.last_block_height}, "
                        f"wanted {snapshot.height}")
                if info.last_block_app_hash != app_hash:
                    raise SnapshotRejected("restored app hash mismatch")
            except SnapshotRejected:
                raise
            except StateSyncError as e:
                # transport-layer trouble (chunk timeout, momentary
                # zero-peer window, snapshot pruned server-side):
                # retriable — do NOT blacklist a snapshot for the
                # network's weather.  The ledger KEEPS its verified
                # chunks: the next attempt (or a restarted process)
                # resumes from the frontier.
                if self.ledger is not None:
                    self.ledger.flush()
                raise SnapshotUnverifiable(f"chunk fetch failed: {e}")
            except Exception as e:
                # app blew up on peer-shaped data: this snapshot is
                # bad, not the whole sync
                raise SnapshotRejected(f"restore failed: {e}")
        except SnapshotRejected:
            # the ONE cleanup site: a rejected snapshot's chunks must
            # not linger as resumable state
            if self.ledger is not None:
                self.ledger.clear()
            raise
        if self.ledger is not None:
            self.ledger.complete()
        wall = max(1e-9, time.monotonic() - t0)
        stats["time_to_synced_s"] = wall
        stats["bytes_per_s"] = stats.get("bytes", 0) / wall
        self.last_restore = stats
        m = metrics()
        m.time_to_synced.set(wall)
        m.restore_bytes_per_s.set(stats["bytes_per_s"])
        return state, commit

    # -- banning -----------------------------------------------------------

    def _ban(self, peer_id: str, reason: str):
        metrics().peers_banned.inc()
        self.log.info("banning statesync peer", peer=peer_id,
                      reason=reason)
        if self.ban_peer is not None and peer_id:
            self.ban_peer(peer_id, reason)

    # -- pipelined fetch -> verify -> apply (reference syncer.go:411) ------

    def _fetch_and_apply(self, snapshot: abci.Snapshot,
                         providers: List[str]) -> dict:
        """N fetcher threads fetch + digest-verify chunks and land them
        in the restore ledger; chunks apply strictly in order from the
        calling thread, overlapped with the fetch of later chunks.
        Per-peer retry/backoff/quarantine with sender rotation;
        app-requested refetch_chunks are re-enqueued and reject_senders
        banned (reference syncer.go:465-476)."""
        nchunks = snapshot.chunks
        if nchunks <= 0 or nchunks > MAX_SNAPSHOT_CHUNKS:
            raise SnapshotRejected(f"implausible chunk count {nchunks}")
        m = metrics()
        digests = parse_chunk_metadata(snapshot.metadata, nchunks)
        book = _PeerBook(providers, retries=self._retries(),
                         ban_cb=self._ban)
        timeout_s = self._chunk_timeout_s()
        ledger = self.ledger

        # fetched[idx] = (chunk, sender, fetch_start_monotonic|None)
        fetched: Dict[int, Tuple[bytes, str, Optional[float]]] = {}
        resumed = 0
        if ledger is not None:
            stored = ledger.begin(snapshot)
            good = set(verify_chunks(digests, stored))
            bad = [i for i in stored if i not in good]
            if bad:
                # stored bytes rotted (partial write, disk fault):
                # drop and refetch — never feed the app unverified data
                ledger.drop(bad)
                m.chunks_verified.inc(len(bad), outcome="corrupt")
            for i in good:
                fetched[i] = (stored[i], "", None)
            resumed = len(good)
            if resumed:
                self.log.info("resuming restore from ledger",
                              height=snapshot.height, resumed=resumed,
                              total=nchunks)

        pending = collections.deque(
            i for i in range(nchunks) if i not in fetched)
        inflight: set = set()
        cv = threading.Condition()
        done = threading.Event()
        fetch_err: List[Exception] = []
        bytes_fetched = [0]

        def abort(e: Exception):
            fetch_err.append(e)
            done.set()
            with cv:
                cv.notify_all()

        def requeue(idx: int):
            with cv:
                inflight.discard(idx)
                if idx not in pending and idx not in fetched:
                    pending.append(idx)
                cv.notify_all()

        stop = self.stop_event

        def fetcher():
            while not done.is_set():
                if stop.is_set():
                    abort(StateSyncError("statesync stopping"))
                    return
                with cv:
                    while not pending and not done.is_set():
                        cv.wait(0.2)
                    if done.is_set():
                        return
                    idx = pending.popleft()
                    inflight.add(idx)
                peer, wait_s = book.pick()
                if peer is None:
                    requeue(idx)
                    if wait_s < 0:
                        abort(StateSyncError(
                            "all snapshot providers failed "
                            f"({book.dead_peers()})"))
                        return
                    done.wait(min(wait_s, 0.25))
                    continue
                t_start = time.monotonic()
                try:
                    with trace.span("statesync.fetch", chunk=idx,
                                    peer=peer):
                        fail.inject("statesync.fetch")
                        chunk, sender = self.chunk_fetcher(snapshot, idx,
                                                           peer)
                        chunk = fail.corrupt_bytes("statesync.fetch",
                                                   chunk)
                except ChunkBusy as e:
                    m.chunks_fetched.inc(outcome="busy")
                    book.busy(peer, e.retry_after_s)
                    requeue(idx)
                    continue
                except Exception as e:  # noqa: BLE001 - transport error
                    m.chunks_fetched.inc(outcome="error")
                    book.failure(peer, t_start, str(e))
                    requeue(idx)
                    if book.all_dead():
                        abort(StateSyncError(
                            f"chunk {idx} fetch failed and no providers "
                            f"remain: {e}"))
                        return
                    continue
                dt = time.monotonic() - t_start
                sender = sender or peer
                book.add(sender)
                # integrity check on THIS thread, before the app ever
                # sees the bytes (the tentpole invariant)
                verify_fault = False
                try:
                    fail.inject("statesync.verify")
                    ok = digests is None or verify_chunk(digests, idx,
                                                         chunk)
                except fail.InjectedFault:
                    ok, verify_fault = False, True
                if not ok:
                    m.chunks_verified.inc(outcome="corrupt")
                    if verify_fault:
                        # machinery fault, not proven peer misbehavior
                        book.failure(peer, t_start, "verify fault")
                    else:
                        self.log.error("corrupt chunk detected pre-app",
                                       chunk=idx, sender=sender)
                        book.ban(sender, "statesync chunk digest "
                                         "mismatch")
                    requeue(idx)
                    if book.all_dead():
                        abort(StateSyncError(
                            f"chunk {idx} unverifiable and no providers "
                            "remain"))
                        return
                    continue
                if digests is not None:
                    m.chunks_verified.inc(outcome="ok")
                m.chunks_fetched.inc(outcome="ok")
                if dt > timeout_s:
                    book.slow(peer, t_start)  # slow-peer quarantine
                else:
                    book.success(peer)
                if ledger is not None:
                    ledger.put_chunk(idx, chunk)
                with cv:
                    inflight.discard(idx)
                    bytes_fetched[0] += len(chunk)
                    fetched[idx] = (chunk, sender, t_start)
                    cv.notify_all()

        # at least one fetcher even on a fully-resumed restore: the app
        # may still demand refetches (RETRY/refetch_chunks) and the
        # apply loop would otherwise wait on a queue nobody drains
        n_threads = min(self._fetchers(), max(1, len(pending)))
        threads = [threading.Thread(target=fetcher, daemon=True,
                                    name=f"chunk-fetcher-{i}")
                   for i in range(n_threads)]
        for t in threads:
            t.start()
        refetched = 0
        bytes_applied = 0
        try:
            index = 0
            # RETRY budget resets whenever the apply cursor passes a new
            # high-water mark: a large restore may legitimately RETRY a
            # handful of times spread across many chunks (the reference's
            # chunks.Retry has no global cap, syncer.go:397), but an app
            # spinning at the SAME frontier still trips the cap — and the
            # high-water mark only ever rises, so reset cycles are bounded
            # by nchunks and cannot launder the count into an infinite loop
            retries = 0
            high_water = -1
            while index < nchunks:
                with cv:
                    while index not in fetched and not done.is_set():
                        if stop.is_set():
                            raise StateSyncError("statesync stopping")
                        cv.wait(0.2)
                    if index not in fetched:
                        raise StateSyncError(
                            f"chunk {index} fetch failed: "
                            f"{fetch_err[0] if fetch_err else 'aborted'}")
                    chunk, sender, t_fetch = fetched.pop(index)
                with trace.span("statesync.apply", chunk=index,
                                n=len(chunk)):
                    fail.inject("statesync.apply")
                    r = self.app.apply_snapshot_chunk(index, chunk,
                                                      sender)
                if t_fetch is not None:
                    slo.observe("statesync", time.monotonic() - t_fetch)
                m.restore_bytes.inc(len(chunk))
                bytes_applied += len(chunk)
                for pid in getattr(r, "reject_senders", ()) or ():
                    if pid:
                        book.ban(pid, "statesync chunk rejected by app")
                refetch = [i for i in (getattr(r, "refetch_chunks", ())
                                       or ()) if 0 <= i < nchunks]
                if r.result == abci.ResponseApplySnapshotChunk.ACCEPT:
                    nxt = index + 1
                    if index > high_water:
                        high_water = index
                        retries = 0
                elif r.result == abci.ResponseApplySnapshotChunk.RETRY:
                    retries += 1
                    if retries > self._retries():
                        raise SnapshotRejected("chunk retry limit")
                    if not refetch:
                        refetch = [index]
                    nxt = index
                else:
                    raise SnapshotRejected(f"apply result {r.result}")
                if refetch:
                    # the app discarded these (possibly already-applied)
                    # chunks: refetch them and rewind the apply cursor
                    # (reference syncer.go:465 enqueues them again).  An
                    # index already in flight is NOT re-enqueued — its
                    # fresh response is about to land in `fetched`, and a
                    # duplicate concurrent fetch of the same key would
                    # race on the reactor's response routing
                    if ledger is not None:
                        ledger.drop(refetch)
                    refetched += len(refetch)
                    with cv:
                        for i in refetch:
                            fetched.pop(i, None)
                            if i not in pending and i not in inflight:
                                pending.append(i)
                        cv.notify_all()
                    nxt = min(nxt, min(refetch))
                index = nxt
        finally:
            done.set()
            with cv:
                cv.notify_all()
            for t in threads:
                t.join(timeout=1.0)
        return {"chunks": nchunks, "resumed": resumed,
                "refetched": refetched, "bytes": bytes_applied,
                "fetched_bytes": bytes_fetched[0],
                "banned": book.dead_peers()}
