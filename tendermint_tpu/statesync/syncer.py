"""State sync syncer (reference statesync/syncer.go:141): discover
snapshots from peers, offer to the app, fetch + apply chunks, verify the
restored app hash against a light-client-verified header, and bootstrap
consensus state at the snapshot height."""
from __future__ import annotations

import threading
from typing import Callable, List, Tuple

from tendermint_tpu.abci import types as abci
from tendermint_tpu.state.state import State

from .stateprovider import StateProvider


class StateSyncError(Exception):
    pass


class SnapshotUnverifiable(StateSyncError):
    """The chain has not outgrown the snapshot yet (headers H+1/H+2
    missing) — retriable, unlike a rejection."""


class SnapshotRejected(StateSyncError):
    pass


class Syncer:
    """chunk_fetcher(snapshot, index, sender_hint) -> (bytes, sender_id);
    in the reactor this requests over p2p, in tests it reads a serving
    app directly."""

    def __init__(self, app, state_provider: StateProvider,
                 chunk_fetcher: Callable):
        self.app = app
        self.state_provider = state_provider
        self.chunk_fetcher = chunk_fetcher
        self._snapshots: List[Tuple[abci.Snapshot, str]] = []
        self._rejected: set = set()
        self._lock = threading.Lock()

    # -- discovery ---------------------------------------------------------

    def add_snapshot(self, snapshot: abci.Snapshot, peer_id: str) -> bool:
        key = (snapshot.height, snapshot.format, snapshot.hash)
        with self._lock:
            if key in self._rejected:
                return False
            if any((s.height, s.format, s.hash) == key
                   for s, _ in self._snapshots):
                return False
            self._snapshots.append((snapshot, peer_id))
            return True

    def _best_snapshots(self):
        with self._lock:
            # drop blacklisted entries so retries never re-download
            # known-bad snapshots (_rejected otherwise only gates
            # add_snapshot, not selection)
            self._snapshots = [
                (s, p) for s, p in self._snapshots
                if (s.height, s.format, s.hash) not in self._rejected]
            return sorted(self._snapshots,
                          key=lambda sp: (-sp[0].height, -sp[0].format))

    # -- sync (reference syncer.go:141 SyncAny) ----------------------------

    def sync_any(self) -> Tuple[State, "object"]:
        """Try discovered snapshots best-first.  Returns (bootstrapped
        state, certifying commit for the snapshot height)."""
        reasons = []
        for snapshot, peer_id in self._best_snapshots():
            try:
                return self._sync_one(snapshot, peer_id)
            except SnapshotUnverifiable as e:
                # may verify on a later attempt; do not blacklist
                reasons.append(f"h{snapshot.height}: {e}")
                continue
            except SnapshotRejected as e:
                reasons.append(f"h{snapshot.height}: REJECTED {e}")
                with self._lock:
                    self._rejected.add(
                        (snapshot.height, snapshot.format, snapshot.hash))
                continue
        raise StateSyncError(
            "no viable snapshots" + (": " + "; ".join(reasons[:3])
                                     if reasons else ""))

    def _sync_one(self, snapshot: abci.Snapshot, peer_id: str):
        # trusted app hash for the snapshot height comes from the light
        # client (header H+1 carries the post-H app hash,
        # reference syncer.go:287 verifyApp).  Bootstrapping height H needs
        # verified headers up to H+2 — a snapshot taken at the chain head
        # is rejected until the chain outgrows it.  State/commit are
        # verified once here and reused after the restore.
        try:
            app_hash = self.state_provider.app_hash(snapshot.height)
            state = self.state_provider.state(snapshot.height)
            commit = self.state_provider.commit(snapshot.height)
        except Exception as e:
            raise SnapshotUnverifiable(
                f"cannot verify snapshot height {snapshot.height} "
                f"(chain may not have outgrown it yet): {e}")
        try:
            resp = self.app.offer_snapshot(snapshot, app_hash)
            if resp.result != abci.ResponseOfferSnapshot.ACCEPT:
                raise SnapshotRejected(f"offer result {resp.result}")
            # fetch + apply chunks in order (reference syncer.go:395)
            index = 0
            attempts = 0
            while index < snapshot.chunks:
                chunk, sender = self.chunk_fetcher(snapshot, index, peer_id)
                r = self.app.apply_snapshot_chunk(index, chunk, sender)
                if r.result == abci.ResponseApplySnapshotChunk.ACCEPT:
                    index += 1
                    attempts = 0
                    continue
                if r.result == abci.ResponseApplySnapshotChunk.RETRY:
                    attempts += 1
                    if attempts > 3:
                        raise SnapshotRejected("chunk retry limit")
                    continue
                raise SnapshotRejected(f"apply result {r.result}")
            # verify the restored app (reference syncer.go:544 verifyApp)
            info = self.app.info(abci.RequestInfo())
        except SnapshotRejected:
            raise
        except StateSyncError as e:
            # transport-layer trouble (chunk timeout, momentary zero-peer
            # window, snapshot pruned server-side): retriable — do NOT
            # blacklist a snapshot for the network's weather
            raise SnapshotUnverifiable(f"chunk fetch failed: {e}")
        except Exception as e:
            # app blew up on peer-shaped data: this snapshot is bad,
            # not the whole sync
            raise SnapshotRejected(f"restore failed: {e}")
        if info.last_block_height != snapshot.height:
            raise SnapshotRejected(
                f"app restored to height {info.last_block_height}, "
                f"wanted {snapshot.height}")
        if info.last_block_app_hash != app_hash:
            raise SnapshotRejected("restored app hash mismatch")
        return state, commit
