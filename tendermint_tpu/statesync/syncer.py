"""State sync syncer (reference statesync/syncer.go:141): discover
snapshots from peers, offer to the app, fetch + apply chunks, verify the
restored app hash against a light-client-verified header, and bootstrap
consensus state at the snapshot height."""
from __future__ import annotations

import collections
import threading
from typing import Callable, List, Optional, Tuple

from tendermint_tpu.abci import types as abci
from tendermint_tpu.state.state import State

from .stateprovider import StateProvider

CHUNK_FETCHERS = 4      # reference config.go ChunkFetchers default
CHUNK_RETRIES = 3       # per-chunk fetch attempts before giving up
# sanity cap on a peer-declared chunk count: 2^16 chunks x 64KB-ish
# chunks bounds any snapshot we would ever restore; without it a
# Byzantine SnapshotsResponse (chunks=2^60) would OOM the fetch queue
MAX_SNAPSHOT_CHUNKS = 1 << 16


class StateSyncError(Exception):
    pass


class SnapshotUnverifiable(StateSyncError):
    """The chain has not outgrown the snapshot yet (headers H+1/H+2
    missing) — retriable, unlike a rejection."""


class SnapshotRejected(StateSyncError):
    pass


class Syncer:
    """chunk_fetcher(snapshot, index, sender_hint) -> (bytes, sender_id);
    in the reactor this requests over p2p, in tests it reads a serving
    app directly."""

    def __init__(self, app, state_provider: StateProvider,
                 chunk_fetcher: Callable, ban_peer: Optional[Callable] = None,
                 fetchers: int = CHUNK_FETCHERS):
        self.app = app
        self.state_provider = state_provider
        self.chunk_fetcher = chunk_fetcher
        self.ban_peer = ban_peer            # ban_peer(peer_id, reason)
        self.fetchers = max(1, fetchers)
        from tendermint_tpu.libs import log as tmlog
        self.log = tmlog.logger("statesync")
        self._snapshots: List[Tuple[abci.Snapshot, str]] = []
        self._rejected: set = set()
        self._lock = threading.Lock()

    # -- discovery ---------------------------------------------------------

    def add_snapshot(self, snapshot: abci.Snapshot, peer_id: str) -> bool:
        if not 0 < snapshot.chunks <= MAX_SNAPSHOT_CHUNKS:
            return False
        key = (snapshot.height, snapshot.format, snapshot.hash)
        with self._lock:
            if key in self._rejected:
                return False
            if any((s.height, s.format, s.hash) == key
                   for s, _ in self._snapshots):
                return False
            self._snapshots.append((snapshot, peer_id))
            return True

    def _best_snapshots(self):
        with self._lock:
            # drop blacklisted entries so retries never re-download
            # known-bad snapshots (_rejected otherwise only gates
            # add_snapshot, not selection)
            self._snapshots = [
                (s, p) for s, p in self._snapshots
                if (s.height, s.format, s.hash) not in self._rejected]
            return sorted(self._snapshots,
                          key=lambda sp: (-sp[0].height, -sp[0].format))

    # -- sync (reference syncer.go:141 SyncAny) ----------------------------

    def sync_any(self) -> Tuple[State, "object"]:
        """Try discovered snapshots best-first.  Returns (bootstrapped
        state, certifying commit for the snapshot height)."""
        reasons = []
        for snapshot, peer_id in self._best_snapshots():
            try:
                self.log.info("offering snapshot to app",
                              height=snapshot.height,
                              format=snapshot.format,
                              chunks=snapshot.chunks, peer=peer_id)
                result = self._sync_one(snapshot, peer_id)
                self.log.info("snapshot restored",
                              height=snapshot.height)
                return result
            except SnapshotUnverifiable as e:
                # may verify on a later attempt; do not blacklist
                self.log.debug("snapshot not yet verifiable",
                               height=snapshot.height, err=str(e))
                reasons.append(f"h{snapshot.height}: {e}")
                continue
            except SnapshotRejected as e:
                self.log.error("snapshot rejected",
                               height=snapshot.height, err=str(e))
                reasons.append(f"h{snapshot.height}: REJECTED {e}")
                with self._lock:
                    self._rejected.add(
                        (snapshot.height, snapshot.format, snapshot.hash))
                continue
        raise StateSyncError(
            "no viable snapshots" + (": " + "; ".join(reasons[:3])
                                     if reasons else ""))

    def _sync_one(self, snapshot: abci.Snapshot, peer_id: str):
        # trusted app hash for the snapshot height comes from the light
        # client (header H+1 carries the post-H app hash,
        # reference syncer.go:287 verifyApp).  Bootstrapping height H needs
        # verified headers up to H+2 — a snapshot taken at the chain head
        # is rejected until the chain outgrows it.  State/commit are
        # verified once here and reused after the restore.
        try:
            app_hash = self.state_provider.app_hash(snapshot.height)
            state = self.state_provider.state(snapshot.height)
            commit = self.state_provider.commit(snapshot.height)
        except Exception as e:
            raise SnapshotUnverifiable(
                f"cannot verify snapshot height {snapshot.height} "
                f"(chain may not have outgrown it yet): {e}")
        try:
            resp = self.app.offer_snapshot(snapshot, app_hash)
            if resp.result != abci.ResponseOfferSnapshot.ACCEPT:
                raise SnapshotRejected(f"offer result {resp.result}")
            self._fetch_and_apply(snapshot, peer_id)
            # verify the restored app (reference syncer.go:544 verifyApp)
            info = self.app.info(abci.RequestInfo())
        except SnapshotRejected:
            raise
        except StateSyncError as e:
            # transport-layer trouble (chunk timeout, momentary zero-peer
            # window, snapshot pruned server-side): retriable — do NOT
            # blacklist a snapshot for the network's weather
            raise SnapshotUnverifiable(f"chunk fetch failed: {e}")
        except Exception as e:
            # app blew up on peer-shaped data: this snapshot is bad,
            # not the whole sync
            raise SnapshotRejected(f"restore failed: {e}")
        if info.last_block_height != snapshot.height:
            raise SnapshotRejected(
                f"app restored to height {info.last_block_height}, "
                f"wanted {snapshot.height}")
        if info.last_block_app_hash != app_hash:
            raise SnapshotRejected("restored app hash mismatch")
        return state, commit

    # -- concurrent chunk fetch (reference syncer.go:411 fetchChunks) ------

    def _fetch_and_apply(self, snapshot: abci.Snapshot, peer_id: str):
        """N fetcher threads fill a chunk buffer; chunks apply strictly
        in order from the calling thread.  Per-chunk retry across
        fetchers; app-requested refetch_chunks are re-enqueued and
        reject_senders banned (reference syncer.go:465-476)."""
        nchunks = snapshot.chunks
        if nchunks <= 0 or nchunks > MAX_SNAPSHOT_CHUNKS:
            raise SnapshotRejected(f"implausible chunk count {nchunks}")
        pending = collections.deque(range(nchunks))
        fetched: dict = {}
        failures: dict = {}
        inflight: set = set()
        cv = threading.Condition()
        done = threading.Event()
        fetch_err: List[Exception] = []

        def fetcher():
            while not done.is_set():
                with cv:
                    while not pending and not done.is_set():
                        cv.wait(0.2)
                    if done.is_set():
                        return
                    idx = pending.popleft()
                    inflight.add(idx)
                try:
                    chunk, sender = self.chunk_fetcher(snapshot, idx,
                                                       peer_id)
                except Exception as e:  # noqa: BLE001 - transport error
                    with cv:
                        inflight.discard(idx)
                        failures[idx] = failures.get(idx, 0) + 1
                        if failures[idx] > CHUNK_RETRIES:
                            self.log.error("chunk fetch failed, giving up",
                                           chunk=idx, err=str(e))
                            fetch_err.append(e)
                            done.set()
                        else:
                            pending.append(idx)
                        cv.notify_all()
                    continue
                with cv:
                    inflight.discard(idx)
                    fetched[idx] = (chunk, sender)
                    cv.notify_all()

        threads = [threading.Thread(target=fetcher, daemon=True,
                                    name=f"chunk-fetcher-{i}")
                   for i in range(min(self.fetchers, nchunks))]
        for t in threads:
            t.start()
        try:
            index = 0
            # RETRY budget resets whenever the apply cursor passes a new
            # high-water mark: a large restore may legitimately RETRY a
            # handful of times spread across many chunks (the reference's
            # chunks.Retry has no global cap, syncer.go:397), but an app
            # spinning at the SAME frontier still trips the cap — and the
            # high-water mark only ever rises, so reset cycles are bounded
            # by nchunks and cannot launder the count into an infinite loop
            retries = 0
            high_water = -1
            while index < nchunks:
                with cv:
                    while index not in fetched and not done.is_set():
                        cv.wait(0.2)
                    if index not in fetched:
                        raise StateSyncError(
                            f"chunk {index} fetch failed: "
                            f"{fetch_err[0] if fetch_err else 'aborted'}")
                    chunk, sender = fetched.pop(index)
                r = self.app.apply_snapshot_chunk(index, chunk, sender)
                for pid in getattr(r, "reject_senders", ()) or ():
                    if self.ban_peer is not None and pid:
                        self.log.info("banning peer for rejected chunk",
                                      peer=pid, chunk=index)
                        self.ban_peer(pid, "statesync chunk rejected")
                refetch = [i for i in (getattr(r, "refetch_chunks", ())
                                       or ()) if 0 <= i < nchunks]
                if r.result == abci.ResponseApplySnapshotChunk.ACCEPT:
                    nxt = index + 1
                    if index > high_water:
                        high_water = index
                        retries = 0
                elif r.result == abci.ResponseApplySnapshotChunk.RETRY:
                    retries += 1
                    if retries > CHUNK_RETRIES:
                        raise SnapshotRejected("chunk retry limit")
                    if not refetch:
                        refetch = [index]
                    nxt = index
                else:
                    raise SnapshotRejected(f"apply result {r.result}")
                if refetch:
                    # the app discarded these (possibly already-applied)
                    # chunks: refetch them and rewind the apply cursor
                    # (reference syncer.go:465 enqueues them again).  An
                    # index already in flight is NOT re-enqueued — its
                    # fresh response is about to land in `fetched`, and a
                    # duplicate concurrent fetch of the same key would
                    # race on the reactor's response routing
                    with cv:
                        for i in refetch:
                            fetched.pop(i, None)
                            if i not in pending and i not in inflight:
                                pending.append(i)
                        cv.notify_all()
                    nxt = min(nxt, min(refetch))
                index = nxt
        finally:
            done.set()
            with cv:
                cv.notify_all()
            for t in threads:
                t.join(timeout=1.0)
