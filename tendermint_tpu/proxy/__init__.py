"""Proxy AppConns (reference proxy/app_conn.go:16-57 + multi_app_conn.go):
the node's four logical connections to one application — consensus,
mempool, query, snapshot — each its own ordered channel so a slow query
never blocks block execution.

ClientCreator mirrors proxy/client.go: local (in-process, shared instance)
or remote (one socket per connection)."""
from __future__ import annotations

from typing import Callable

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.client import SocketClient


class ClientCreator:
    """proxy/client.go NewLocalClientCreator / NewRemoteClientCreator."""

    def __init__(self, factory: Callable[[], abci.Application]):
        self._factory = factory

    @classmethod
    def local(cls, app: abci.Application) -> "ClientCreator":
        return cls(lambda: app)

    @classmethod
    def remote(cls, addr: str) -> "ClientCreator":
        """grpc://host:port selects the gRPC transport (reference
        proxy/client.go NewRemoteClientCreator transport arg);
        unix:///tcp:// the proto-framed socket transport."""
        if addr.startswith("grpc://"):
            target = addr[len("grpc://"):]

            def make():
                from tendermint_tpu.abci.grpc import GRPCClient
                return GRPCClient(target)
            return cls(make)
        return cls(lambda: SocketClient(addr))

    def new_client(self) -> abci.Application:
        return self._factory()


class AppConns:
    """Reference proxy/multi_app_conn.go: four connections, one app."""

    def __init__(self, creator: ClientCreator):
        self.consensus = creator.new_client()
        self.mempool = creator.new_client()
        self.query = creator.new_client()
        self.snapshot = creator.new_client()

    def stop(self):
        for c in (self.consensus, self.mempool, self.query, self.snapshot):
            close = getattr(c, "close", None)
            if close is not None:
                close()
