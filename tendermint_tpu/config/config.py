"""Node configuration (reference config/config.go), TOML-backed.

Layout under $TMHOME mirrors the reference: config/config.toml,
config/genesis.json, config/node_key.json, config/priv_validator_key.json,
data/ (stores + WAL).
"""
from __future__ import annotations

import math
import os
try:
    import tomllib
except ImportError:  # Python < 3.11: tomli is the same parser/API
    import tomli as tomllib
from dataclasses import dataclass, field

from tendermint_tpu.consensus.config import ConsensusConfig


@dataclass
class P2PConfig:
    laddr: str = "127.0.0.1:26656"
    persistent_peers: str = ""  # comma-separated id@host:port
    max_num_peers: int = 50
    pex: bool = True            # run the PEX reactor / addr book
    seeds: str = ""             # comma-separated id@host:port to crawl
    # per-connection byte-rate caps + dial/handshake deadlines
    # (reference config/config.go:604-607 SendRate/RecvRate and
    # :598 HandshakeTimeout/DialTimeout)
    send_rate: int = 5_120_000
    recv_rate: int = 5_120_000
    handshake_timeout_s: float = 20.0
    dial_timeout_s: float = 3.0

    def validate_basic(self):
        """Reference config/config.go:668-688 P2PConfig.ValidateBasic."""
        if self.max_num_peers <= 0:
            raise ValueError("p2p.max_num_peers must be positive")
        if self.send_rate <= 0 or self.recv_rate <= 0:
            raise ValueError("p2p.send_rate/recv_rate must be positive")
        if self.handshake_timeout_s <= 0 or self.dial_timeout_s <= 0:
            raise ValueError("p2p timeouts must be positive")


@dataclass
class MempoolConfig:
    version: str = "v0"         # "v0" FIFO or "v1" priority mempool
    size: int = 5000
    cache_size: int = 10000
    max_tx_bytes: int = 1048576
    # total byte budget across all pending txs (reference
    # config/config.go:731 MaxTxsBytes, default 1GB)
    max_txs_bytes: int = 1 << 30
    keep_invalid_txs_in_cache: bool = False
    # IngressGate admission pipeline (mempool/ingress.py, ADR-018).
    # Disabled, every CheckTx caller runs the synchronous in-caller
    # admission exactly as before the gate existed.
    ingress_enable: bool = True
    ingress_queue: int = 8192       # bounded admission queue (txs);
    #                                 full = immediate busy rejection
    ingress_workers: int = 1        # queue-draining worker threads
    ingress_batch: int = 256        # max txs drained per worker wakeup
    # per-source token bucket (rpc / p2p:<peer> / internal), admissions
    # per second; 0 = unlimited.  Burst 0 = auto (max(1, rate)).
    ingress_rate_per_s: float = 0.0
    ingress_burst: int = 0
    ingress_recheck_slice: int = 256  # post-block rechecks per wakeup

    def validate_basic(self):
        """Reference config/config.go:772-787 MempoolConfig.ValidateBasic."""
        if self.version not in ("v0", "v1"):
            raise ValueError(f"mempool.version must be v0|v1, "
                             f"got {self.version!r}")
        if self.size <= 0:
            raise ValueError("mempool.size must be positive")
        if self.cache_size <= 0:
            raise ValueError("mempool.cache_size must be positive")
        if self.max_tx_bytes <= 0:
            raise ValueError("mempool.max_tx_bytes must be positive")
        if self.max_txs_bytes <= 0:
            raise ValueError("mempool.max_txs_bytes must be positive")
        for k in ("ingress_queue", "ingress_workers", "ingress_batch",
                  "ingress_recheck_slice"):
            if getattr(self, k) <= 0:
                raise ValueError(f"mempool.{k} must be positive")
        # 0 = unlimited rate / auto burst; only negatives are nonsense
        if self.ingress_rate_per_s < 0:
            raise ValueError("mempool.ingress_rate_per_s must be >= 0")
        if self.ingress_burst < 0:
            raise ValueError("mempool.ingress_burst must be >= 0")


@dataclass
class RPCConfig:
    laddr: str = "127.0.0.1:26657"
    enabled: bool = True
    unsafe: bool = False  # expose dial_seeds/dial_peers (ref --rpc.unsafe)
    # request body cap (reference config/config.go:468 MaxBodyBytes)
    max_body_bytes: int = 1_000_000
    # debug/profiling endpoint (reference config/config.go:427
    # pprof_laddr); empty = disabled.  Serves /debug/stacks, /debug/
    # threads, /debug/profile, /debug/gc via libs/pprof.py
    pprof_laddr: str = ""
    # gRPC broadcast API (reference config/config.go GRPCListenAddress
    # "grpc_laddr"); empty = disabled.  rpc/grpc_api.py BroadcastAPI
    grpc_laddr: str = ""

    def validate_basic(self):
        if self.max_body_bytes <= 0:
            raise ValueError("rpc.max_body_bytes must be positive")
        if self.grpc_laddr and not self.enabled:
            raise ValueError(
                "rpc.grpc_laddr requires the RPC server (rpc.enabled): "
                "BroadcastTx routes through broadcast_tx_commit")


@dataclass
class BlockSyncConfig:
    enable: bool = True


@dataclass
class TxIndexConfig:
    """Reference config/config.go TxIndexConfig + the psql event sink
    selection (state/indexer/sink)."""
    indexer: str = "kv"        # "kv" | "null"
    sink_dsn: str = ""         # optional write-only SQL event sink


@dataclass
class StateSyncConfig:
    """Reference config/config.go StateSyncConfig: bootstrap a fresh node
    from an app snapshot verified through the light client.  The
    fast-join knobs (ADR-022) replace the old hardcoded
    CHUNK_FETCHERS/CHUNK_RETRIES module constants; the serve_* pair
    bounds the snapshot-serving side (per-peer token buckets on the
    chunk server — every node serves snapshots, so these apply even
    with enable=false)."""
    enable: bool = False
    rpc_servers: str = ""      # comma-separated full-node RPC addrs
    trust_height: int = 0
    trust_hash: str = ""       # hex header hash at trust_height
    trust_period: float = 86400.0 * 7
    fetchers: int = 4          # concurrent chunk fetcher threads
    chunk_timeout_ms: float = 15000.0  # per-chunk fetch deadline; a
    #                            slower peer is quarantined
    retries: int = 3           # PER-PEER consecutive-failure budget
    #                            before a provider is banned
    serve_rate_per_s: float = 100.0  # per-peer chunk-serve rate; 0 =
    #                            unlimited
    serve_burst: int = 32      # per-peer token-bucket burst

    def validate_basic(self):
        if self.fetchers <= 0:
            raise ValueError("state_sync.fetchers must be positive")
        if self.chunk_timeout_ms <= 0:
            raise ValueError(
                "state_sync.chunk_timeout_ms must be positive")
        if self.retries <= 0:
            raise ValueError("state_sync.retries must be positive")
        # 0 = unlimited serve rate; only negatives are nonsense
        if self.serve_rate_per_s < 0:
            raise ValueError(
                "state_sync.serve_rate_per_s must be >= 0")
        if self.serve_burst <= 0:
            raise ValueError("state_sync.serve_burst must be positive")


@dataclass
class BatchVerifierConfig:
    """TPU data-plane routing (no reference analog — the new component)."""
    tpu_threshold: int = 32
    enable: bool = True
    # opt-in to the cofactored RLC batch fast path (ops/msm.py).  OFF by
    # default for wire-compat: RLC uses ZIP-215/cofactored semantics, the
    # reference Go verifier is cofactorless, and a mixed fleet could be
    # chain-split by an adversarial small-order-component signature.
    rlc: bool = False
    # secp256k1 TPU lane (ops/secp.py).  ON by default since ADR-015:
    # verdicts are exact either way, the lane only engages when an
    # accelerator is attached, and it runs under the full degradation
    # runtime (breaker/timeout/host-C-fallback, chaos parity at site
    # ops.secp.verify_batch).  `secp_lane = false` is the rollback
    # switch to the host C lane.
    secp_lane: bool = True
    # host-lane verify pool (crypto/lanepool.py, ADR-015): worker count
    # for the multi-core native C lanes of a mixed batch.  0 = auto
    # (os.cpu_count()); 1 = serial in-caller (pool disabled).
    host_pool_workers: int = 0
    # fixed-base comb verify path (ops/ed25519, ADR-013): per-validator
    # window tables kept device-resident so known-set batches verify
    # with zero doublings.  ON by default — the verdict is the exact
    # cofactorless check either way; `comb = false` forces the ladder.
    comb: bool = True
    # HBM budget for the comb table cache, MB (LRU by validator-set
    # content hash; one padded key costs ~198 KB, so 256 MB holds ~1.3k
    # validator keys).  0 disables table builds entirely.
    table_cache_mb: int = 256

    def validate_basic(self):
        # 0 is meaningful (every batch routes to the device lane); only
        # negatives are nonsense
        if self.tpu_threshold < 0:
            raise ValueError("batch_verifier.tpu_threshold must be "
                             ">= 0")
        if self.table_cache_mb < 0:
            raise ValueError("batch_verifier.table_cache_mb must be "
                             ">= 0")
        # 0 = auto-size, 1 = serial; only negatives are nonsense
        if self.host_pool_workers < 0:
            raise ValueError("batch_verifier.host_pool_workers must be "
                             ">= 0")


@dataclass
class VerifySchedulerConfig:
    """Process-global cross-consumer verification scheduler
    (crypto/scheduler.py, docs/adr/adr-012-verify-scheduler.md).  When
    enabled the node installs + starts one VerifyScheduler and every
    verify consumer (vote preverify, commit/light checks, blocksync
    replay, bulk) coalesces through it; disabled, all call sites keep
    their direct BatchVerifier paths."""
    enable: bool = True
    window_ms: float = 2.0      # coalescing window (deadlines shorten it)
    max_batch: int = 8192       # lanes per coalesced launch / direct-path
    #                             cutover for verify_sigs_bulk
    max_pending: int = 65536    # bounded queue: beyond this the mempool
    #                             class is shed

    def validate_basic(self):
        if self.window_ms < 0:
            raise ValueError("verify_scheduler.window_ms must be >= 0")
        if self.max_batch <= 0 or self.max_pending <= 0:
            raise ValueError(
                "verify_scheduler.max_batch/max_pending must be positive")


@dataclass
class BlockPipelineConfig:
    """Prefetched, group-committed block application
    (state/pipeline.py, docs/adr/adr-017-block-pipeline.md).  When
    enabled the node wraps the block/state DBs in kvdb.GroupCommitDB,
    installs one BlockPipeline and blocksync replay routes stable
    windows through it: block N+1 stages and verifies while N applies,
    and storage commits land as one transaction per
    `group_commit_heights` heights instead of one per height.  `depth`
    bounds how many blocks the stage worker may run ahead of apply.
    Disabled, replay keeps the coalesced/strict paths and every store
    write commits per height exactly as before."""
    enable: bool = True
    depth: int = 4
    group_commit_heights: int = 8

    def validate_basic(self):
        if self.depth <= 0:
            raise ValueError("block_pipeline.depth must be positive")
        if self.group_commit_heights <= 0:
            raise ValueError(
                "block_pipeline.group_commit_heights must be positive")


@dataclass
class DevObsConfig:
    """Device observatory (crypto/devobs.py, ADR-021): per-launch
    transfer/compute/compile decomposition, the compile-cache
    inventory, and the HBM residency ledger.  ON by default — a few
    dict stores per launch is noise against a millisecond-scale launch
    wall; `enable = false` (or TM_TPU_DEVOBS=0 for node-less tooling)
    makes every record a guaranteed sub-microsecond no-op and removes
    the explicit H2D/compute brackets from the monolithic launch
    paths.  `capacity` bounds the launch-record ring."""
    enable: bool = True
    capacity: int = 256

    def validate_basic(self):
        if self.capacity <= 0:
            raise ValueError("devobs.capacity must be positive")


@dataclass
class SLOConfig:
    """Per-priority latency SLOs for the verify path (libs/slo.py,
    docs/adr/adr-016-latency-observatory.md).  When enabled the node
    arms the sliding-window quantile estimator: each priority stream
    keeps its last `window` end-to-end latencies and publishes
    windowed p50/p99 and (when a target is set) the error-budget burn
    rate.  Targets are p99 objectives in MILLISECONDS; 0 = track the
    quantiles but no target (no burn-rate gauge)."""
    # the per-priority verify streams (ADR-016) plus the consensus
    # observatory's height-lifecycle streams (ADR-020), the device
    # observatory's per-launch wall stream (ADR-021), the statesync
    # per-chunk fetch-to-applied stream (ADR-022), and the gossip
    # observatory's proposal -> useful-part receipt latency (ADR-025)
    STREAMS = ("consensus", "commit", "blocksync", "mempool",
               "block_interval", "propose", "quorum_prevote", "apply",
               "device_launch", "statesync", "gossip", "light")

    enable: bool = False
    window: int = 1024
    consensus_p99_ms: float = 0.0
    commit_p99_ms: float = 0.0
    blocksync_p99_ms: float = 0.0
    mempool_p99_ms: float = 0.0
    block_interval_p99_ms: float = 0.0
    propose_p99_ms: float = 0.0
    quorum_prevote_p99_ms: float = 0.0
    apply_p99_ms: float = 0.0
    device_launch_p99_ms: float = 0.0
    statesync_p99_ms: float = 0.0
    gossip_p99_ms: float = 0.0
    light_p99_ms: float = 0.0
    # per-stream error budgets in PERCENT of windowed requests allowed
    # over the p99 target (the burn-rate denominator; 1.0 = the p99
    # convention).  Replaces the old hardcoded _P99_BUDGET constant
    consensus_budget_pct: float = 1.0
    commit_budget_pct: float = 1.0
    blocksync_budget_pct: float = 1.0
    mempool_budget_pct: float = 1.0
    block_interval_budget_pct: float = 1.0
    propose_budget_pct: float = 1.0
    quorum_prevote_budget_pct: float = 1.0
    apply_budget_pct: float = 1.0
    device_launch_budget_pct: float = 1.0
    statesync_budget_pct: float = 1.0
    gossip_budget_pct: float = 1.0
    light_budget_pct: float = 1.0

    def targets_s(self) -> dict:
        """Stream -> p99 target in seconds (only the set ones)."""
        out = {}
        for stream in self.STREAMS:
            ms = getattr(self, f"{stream}_p99_ms")
            if ms > 0:
                out[stream] = ms / 1000.0
        return out

    def budgets(self) -> dict:
        """Stream -> error-budget FRACTION (percent / 100), every
        stream (the estimator falls back to its own default for
        missing ones, so emitting all keeps config the single source
        of truth)."""
        return {stream: getattr(self, f"{stream}_budget_pct") / 100.0
                for stream in self.STREAMS}

    def validate_basic(self):
        if self.window <= 0:
            raise ValueError("slo.window must be positive")
        for stream in self.STREAMS:
            if getattr(self, f"{stream}_p99_ms") < 0:
                raise ValueError(f"slo.{stream}_p99_ms must be >= 0")
            pct = getattr(self, f"{stream}_budget_pct")
            if not (0 < pct <= 100):
                raise ValueError(
                    f"slo.{stream}_budget_pct must be in (0, 100]")


@dataclass
class LightServeConfig:
    """Light-client serving plane (light/service.py, ADR-026): one
    process-global LightServe front door for many concurrent
    header-verifying clients.  `enable = false` (or TM_TPU_LIGHT_SERVE=0
    for node-less tooling) is the kill switch: the node never constructs
    the service and every light RPC route answers service-disabled —
    the full node's own paths are untouched either way."""
    enable: bool = True
    queue: int = 4096           # bounded admission queue (requests);
    #                             full = immediate busy + retry_after
    workers: int = 1            # queue-draining worker threads
    batch: int = 256            # max requests drained per worker wakeup
    # per-client token bucket, requests per second; 0 = unlimited.
    # Burst 0 = auto (max(1, rate)).
    rate_per_s: float = 0.0
    burst: int = 0
    # header-range follow cursors (the subscription surface): bounded
    # per client and globally; past the global bound the least-recently
    # polled cursor is evicted (newest-first survival under pressure)
    max_cursors_per_client: int = 4
    max_cursors: int = 1024
    cursor_batch: int = 64      # max headers returned per poll
    prewarm: bool = True        # comb-table prewarm on valset change

    def validate_basic(self):
        for k in ("queue", "workers", "batch", "max_cursors_per_client",
                  "max_cursors", "cursor_batch"):
            if getattr(self, k) <= 0:
                raise ValueError(f"light_serve.{k} must be positive")
        # 0 = unlimited rate / auto burst; only negatives are nonsense
        if self.rate_per_s < 0:
            raise ValueError("light_serve.rate_per_s must be >= 0")
        if self.burst < 0:
            raise ValueError("light_serve.burst must be >= 0")


@dataclass
class ControlConfig:
    """Adaptive control plane (libs/control.py, ADR-023): the
    SLO-burn-driven knob governor.  OFF by default — enabling it hands
    the declared knobs (verify-scheduler window, host-lane pool width,
    ingress admission rate/burst, block-pipeline depth, statesync
    fetchers, comb min-batch) to a bounded AIMD decision loop that
    steers them inside the per-knob [min, max] safe ranges below and
    reverts every knob to its static configured value on kill
    (`control.kill()` / TM_TPU_CONTROL=0) within one period.  Ranges
    here TIGHTEN the literal KNOB_SPECS declarations; they never widen
    what the code declared safe."""
    # one row per governed knob (libs/control.KNOB_SPECS)
    KNOBS = ("sched_window_ms", "host_pool_workers",
             "ingress_rate_per_s", "ingress_burst", "pipeline_depth",
             "statesync_fetchers", "comb_min_batch",
             "mesh_chunk_lanes")

    enable: bool = False
    period_ms: float = 1000.0   # decision-loop period
    recover_after: int = 3      # clean periods before additive recovery
    sched_window_ms_min: float = 0.5
    sched_window_ms_max: float = 20.0
    sched_window_ms_step: float = 0.5
    host_pool_workers_min: float = 1.0
    host_pool_workers_max: float = 16.0
    host_pool_workers_step: float = 1.0
    ingress_rate_per_s_min: float = 32.0
    ingress_rate_per_s_max: float = 100000.0
    ingress_rate_per_s_step: float = 64.0
    ingress_burst_min: float = 16.0
    ingress_burst_max: float = 65536.0
    ingress_burst_step: float = 64.0
    pipeline_depth_min: float = 2.0
    pipeline_depth_max: float = 32.0
    pipeline_depth_step: float = 1.0
    statesync_fetchers_min: float = 1.0
    statesync_fetchers_max: float = 32.0
    statesync_fetchers_step: float = 1.0
    comb_min_batch_min: float = 16.0
    comb_min_batch_max: float = 4096.0
    comb_min_batch_step: float = 16.0
    mesh_chunk_lanes_min: float = 1024.0
    mesh_chunk_lanes_max: float = 65536.0
    mesh_chunk_lanes_step: float = 1024.0

    def range_of(self, knob: str) -> tuple:
        return (getattr(self, f"{knob}_min"),
                getattr(self, f"{knob}_max"))

    def step_of(self, knob: str) -> float:
        return getattr(self, f"{knob}_step")

    def validate_basic(self):
        if self.period_ms <= 0:
            raise ValueError("control.period_ms must be positive")
        if self.recover_after <= 0:
            raise ValueError("control.recover_after must be positive")
        for knob in self.KNOBS:
            lo, hi = self.range_of(knob)
            step = self.step_of(knob)
            if not (math.isfinite(lo) and math.isfinite(hi)
                    and math.isfinite(step)):
                raise ValueError(
                    f"control.{knob} min/max/step must be finite")
            if lo > hi:
                raise ValueError(
                    f"control.{knob}_min must be <= {knob}_max")
            if step <= 0:
                raise ValueError(
                    f"control.{knob}_step must be positive")


@dataclass
class Config:
    home: str = ""
    moniker: str = "node"
    # reference config.go LogLevel: default level, with optional
    # per-module overrides "consensus:debug,p2p:error" in log_module_levels
    log_level: str = "info"
    log_module_levels: str = ""
    # if set ("unix:///..." or "tcp://host:port"), the node listens here
    # and uses the remote signer that dials in instead of the file PV
    # (reference config.go PrivValidatorListenAddr)
    priv_validator_laddr: str = ""
    p2p: P2PConfig = field(default_factory=P2PConfig)
    mempool: MempoolConfig = field(default_factory=MempoolConfig)
    rpc: RPCConfig = field(default_factory=RPCConfig)
    consensus: ConsensusConfig = field(default_factory=ConsensusConfig)
    block_sync: BlockSyncConfig = field(default_factory=BlockSyncConfig)
    state_sync: StateSyncConfig = field(default_factory=StateSyncConfig)
    tx_index: TxIndexConfig = field(default_factory=TxIndexConfig)
    batch_verifier: BatchVerifierConfig = field(
        default_factory=BatchVerifierConfig)
    verify_scheduler: VerifySchedulerConfig = field(
        default_factory=VerifySchedulerConfig)
    slo: SLOConfig = field(default_factory=SLOConfig)
    block_pipeline: BlockPipelineConfig = field(
        default_factory=BlockPipelineConfig)
    devobs: DevObsConfig = field(default_factory=DevObsConfig)
    control: ControlConfig = field(default_factory=ControlConfig)
    light_serve: LightServeConfig = field(
        default_factory=LightServeConfig)

    def validate_basic(self):
        """Reference config/config.go:107-133 Config.ValidateBasic:
        every section validates, errors carry the section name."""
        for name in ("p2p", "mempool", "rpc", "consensus",
                     "batch_verifier", "verify_scheduler", "slo",
                     "block_pipeline", "devobs", "state_sync",
                     "control", "light_serve"):
            section = getattr(self, name)
            vb = getattr(section, "validate_basic", None)
            if vb is None:
                continue
            try:
                vb()
            except ValueError as e:
                raise ValueError(f"error in [{name}] section: {e}")

    # -- paths -------------------------------------------------------------

    def config_dir(self) -> str:
        return os.path.join(self.home, "config")

    def data_dir(self) -> str:
        return os.path.join(self.home, "data")

    def genesis_file(self) -> str:
        return os.path.join(self.config_dir(), "genesis.json")

    def node_key_file(self) -> str:
        return os.path.join(self.config_dir(), "node_key.json")

    def priv_validator_key_file(self) -> str:
        return os.path.join(self.config_dir(), "priv_validator_key.json")

    def priv_validator_state_file(self) -> str:
        return os.path.join(self.data_dir(), "priv_validator_state.json")

    def wal_file(self) -> str:
        return os.path.join(self.data_dir(), "cs.wal")

    def addr_book_file(self) -> str:
        return os.path.join(self.config_dir(), "addrbook.json")

    def block_db_file(self) -> str:
        return os.path.join(self.data_dir(), "blockstore.db")

    def state_db_file(self) -> str:
        return os.path.join(self.data_dir(), "state.db")

    def ensure_dirs(self):
        os.makedirs(self.config_dir(), exist_ok=True)
        os.makedirs(self.data_dir(), exist_ok=True)

    # -- TOML --------------------------------------------------------------

    @staticmethod
    def _q(v: str) -> str:
        """TOML basic-string escape for template interpolation."""
        return v.replace("\\", "\\\\").replace('"', '\\"')

    def save(self):
        self.ensure_dirs()
        c = self.consensus
        text = f"""# tendermint_tpu node configuration
moniker = "{self._q(self.moniker)}"
priv_validator_laddr = "{self._q(self.priv_validator_laddr)}"
log_level = "{self._q(self.log_level)}"
log_module_levels = "{self._q(self.log_module_levels)}"

[p2p]
laddr = "{self._q(self.p2p.laddr)}"
persistent_peers = "{self._q(self.p2p.persistent_peers)}"
max_num_peers = {self.p2p.max_num_peers}
pex = {str(self.p2p.pex).lower()}
seeds = "{self._q(self.p2p.seeds)}"
send_rate = {self.p2p.send_rate}
recv_rate = {self.p2p.recv_rate}
handshake_timeout_s = {self.p2p.handshake_timeout_s}
dial_timeout_s = {self.p2p.dial_timeout_s}

[mempool]
version = "{self._q(self.mempool.version)}"
size = {self.mempool.size}
cache_size = {self.mempool.cache_size}
max_tx_bytes = {self.mempool.max_tx_bytes}
max_txs_bytes = {self.mempool.max_txs_bytes}
keep_invalid_txs_in_cache = {str(self.mempool.keep_invalid_txs_in_cache).lower()}
ingress_enable = {str(self.mempool.ingress_enable).lower()}
ingress_queue = {self.mempool.ingress_queue}
ingress_workers = {self.mempool.ingress_workers}
ingress_batch = {self.mempool.ingress_batch}
ingress_rate_per_s = {self.mempool.ingress_rate_per_s}
ingress_burst = {self.mempool.ingress_burst}
ingress_recheck_slice = {self.mempool.ingress_recheck_slice}

[rpc]
laddr = "{self._q(self.rpc.laddr)}"
enabled = {str(self.rpc.enabled).lower()}
unsafe = {str(self.rpc.unsafe).lower()}
max_body_bytes = {self.rpc.max_body_bytes}
pprof_laddr = "{self._q(self.rpc.pprof_laddr)}"
grpc_laddr = "{self._q(self.rpc.grpc_laddr)}"

[block_sync]
enable = {str(self.block_sync.enable).lower()}

[tx_index]
indexer = "{self._q(self.tx_index.indexer)}"
sink_dsn = "{self._q(self.tx_index.sink_dsn)}"

[state_sync]
enable = {str(self.state_sync.enable).lower()}
rpc_servers = "{self._q(self.state_sync.rpc_servers)}"
trust_height = {self.state_sync.trust_height}
trust_hash = "{self._q(self.state_sync.trust_hash)}"
trust_period = {self.state_sync.trust_period}
fetchers = {self.state_sync.fetchers}
chunk_timeout_ms = {self.state_sync.chunk_timeout_ms}
retries = {self.state_sync.retries}
serve_rate_per_s = {self.state_sync.serve_rate_per_s}
serve_burst = {self.state_sync.serve_burst}

[batch_verifier]
tpu_threshold = {self.batch_verifier.tpu_threshold}
enable = {str(self.batch_verifier.enable).lower()}
rlc = {str(self.batch_verifier.rlc).lower()}
secp_lane = {str(self.batch_verifier.secp_lane).lower()}
comb = {str(self.batch_verifier.comb).lower()}
table_cache_mb = {self.batch_verifier.table_cache_mb}
host_pool_workers = {self.batch_verifier.host_pool_workers}

[verify_scheduler]
enable = {str(self.verify_scheduler.enable).lower()}
window_ms = {self.verify_scheduler.window_ms}
max_batch = {self.verify_scheduler.max_batch}
max_pending = {self.verify_scheduler.max_pending}

[block_pipeline]
enable = {str(self.block_pipeline.enable).lower()}
depth = {self.block_pipeline.depth}
group_commit_heights = {self.block_pipeline.group_commit_heights}

[devobs]
enable = {str(self.devobs.enable).lower()}
capacity = {self.devobs.capacity}

[slo]
enable = {str(self.slo.enable).lower()}
window = {self.slo.window}
consensus_p99_ms = {self.slo.consensus_p99_ms}
commit_p99_ms = {self.slo.commit_p99_ms}
blocksync_p99_ms = {self.slo.blocksync_p99_ms}
mempool_p99_ms = {self.slo.mempool_p99_ms}
block_interval_p99_ms = {self.slo.block_interval_p99_ms}
propose_p99_ms = {self.slo.propose_p99_ms}
quorum_prevote_p99_ms = {self.slo.quorum_prevote_p99_ms}
apply_p99_ms = {self.slo.apply_p99_ms}
device_launch_p99_ms = {self.slo.device_launch_p99_ms}
statesync_p99_ms = {self.slo.statesync_p99_ms}
gossip_p99_ms = {self.slo.gossip_p99_ms}
light_p99_ms = {self.slo.light_p99_ms}
consensus_budget_pct = {self.slo.consensus_budget_pct}
commit_budget_pct = {self.slo.commit_budget_pct}
blocksync_budget_pct = {self.slo.blocksync_budget_pct}
mempool_budget_pct = {self.slo.mempool_budget_pct}
block_interval_budget_pct = {self.slo.block_interval_budget_pct}
propose_budget_pct = {self.slo.propose_budget_pct}
quorum_prevote_budget_pct = {self.slo.quorum_prevote_budget_pct}
apply_budget_pct = {self.slo.apply_budget_pct}
device_launch_budget_pct = {self.slo.device_launch_budget_pct}
statesync_budget_pct = {self.slo.statesync_budget_pct}
gossip_budget_pct = {self.slo.gossip_budget_pct}
light_budget_pct = {self.slo.light_budget_pct}

[control]
enable = {str(self.control.enable).lower()}
period_ms = {self.control.period_ms}
recover_after = {self.control.recover_after}
sched_window_ms_min = {self.control.sched_window_ms_min}
sched_window_ms_max = {self.control.sched_window_ms_max}
sched_window_ms_step = {self.control.sched_window_ms_step}
host_pool_workers_min = {self.control.host_pool_workers_min}
host_pool_workers_max = {self.control.host_pool_workers_max}
host_pool_workers_step = {self.control.host_pool_workers_step}
ingress_rate_per_s_min = {self.control.ingress_rate_per_s_min}
ingress_rate_per_s_max = {self.control.ingress_rate_per_s_max}
ingress_rate_per_s_step = {self.control.ingress_rate_per_s_step}
ingress_burst_min = {self.control.ingress_burst_min}
ingress_burst_max = {self.control.ingress_burst_max}
ingress_burst_step = {self.control.ingress_burst_step}
pipeline_depth_min = {self.control.pipeline_depth_min}
pipeline_depth_max = {self.control.pipeline_depth_max}
pipeline_depth_step = {self.control.pipeline_depth_step}
statesync_fetchers_min = {self.control.statesync_fetchers_min}
statesync_fetchers_max = {self.control.statesync_fetchers_max}
statesync_fetchers_step = {self.control.statesync_fetchers_step}
comb_min_batch_min = {self.control.comb_min_batch_min}
comb_min_batch_max = {self.control.comb_min_batch_max}
comb_min_batch_step = {self.control.comb_min_batch_step}
mesh_chunk_lanes_min = {self.control.mesh_chunk_lanes_min}
mesh_chunk_lanes_max = {self.control.mesh_chunk_lanes_max}
mesh_chunk_lanes_step = {self.control.mesh_chunk_lanes_step}

[light_serve]
enable = {str(self.light_serve.enable).lower()}
queue = {self.light_serve.queue}
workers = {self.light_serve.workers}
batch = {self.light_serve.batch}
rate_per_s = {self.light_serve.rate_per_s}
burst = {self.light_serve.burst}
max_cursors_per_client = {self.light_serve.max_cursors_per_client}
max_cursors = {self.light_serve.max_cursors}
cursor_batch = {self.light_serve.cursor_batch}
prewarm = {str(self.light_serve.prewarm).lower()}

[consensus]
timeout_propose = {c.timeout_propose}
timeout_propose_delta = {c.timeout_propose_delta}
timeout_prevote = {c.timeout_prevote}
timeout_prevote_delta = {c.timeout_prevote_delta}
timeout_precommit = {c.timeout_precommit}
timeout_precommit_delta = {c.timeout_precommit_delta}
timeout_commit = {c.timeout_commit}
skip_timeout_commit = {str(c.skip_timeout_commit).lower()}
create_empty_blocks = {str(c.create_empty_blocks).lower()}
create_empty_blocks_interval = {c.create_empty_blocks_interval}
propose_reap_budget_ms = {c.propose_reap_budget_ms}
propose_prepare_budget_ms = {c.propose_prepare_budget_ms}
propose_max_bytes = {c.propose_max_bytes}
"""
        with open(os.path.join(self.config_dir(), "config.toml"), "w") as f:
            f.write(text)

    @classmethod
    def load(cls, home: str) -> "Config":
        cfg = cls(home=home)
        path = os.path.join(home, "config", "config.toml")
        if not os.path.exists(path):
            return cfg
        with open(path, "rb") as f:
            d = tomllib.load(f)
        cfg.moniker = d.get("moniker", cfg.moniker)
        cfg.priv_validator_laddr = d.get("priv_validator_laddr", "")
        cfg.log_level = d.get("log_level", cfg.log_level)
        cfg.log_module_levels = d.get("log_module_levels", "")
        p = d.get("p2p", {})
        cfg.p2p = P2PConfig(
            laddr=p.get("laddr", cfg.p2p.laddr),
            persistent_peers=p.get("persistent_peers", ""),
            max_num_peers=p.get("max_num_peers", 50),
            pex=p.get("pex", True),
            seeds=p.get("seeds", ""),
            send_rate=int(p.get("send_rate", 5_120_000)),
            recv_rate=int(p.get("recv_rate", 5_120_000)),
            handshake_timeout_s=float(p.get("handshake_timeout_s", 20.0)),
            dial_timeout_s=float(p.get("dial_timeout_s", 3.0)))
        m = d.get("mempool", {})
        cfg.mempool = MempoolConfig(
            version=m.get("version", "v0"),
            size=m.get("size", 5000), cache_size=m.get("cache_size", 10000),
            max_tx_bytes=m.get("max_tx_bytes", 1048576),
            max_txs_bytes=int(m.get("max_txs_bytes", 1 << 30)),
            keep_invalid_txs_in_cache=bool(
                m.get("keep_invalid_txs_in_cache", False)),
            ingress_enable=bool(m.get("ingress_enable", True)),
            ingress_queue=int(m.get("ingress_queue", 8192)),
            ingress_workers=int(m.get("ingress_workers", 1)),
            ingress_batch=int(m.get("ingress_batch", 256)),
            ingress_rate_per_s=float(m.get("ingress_rate_per_s", 0.0)),
            ingress_burst=int(m.get("ingress_burst", 0)),
            ingress_recheck_slice=int(
                m.get("ingress_recheck_slice", 256)))
        r = d.get("rpc", {})
        cfg.rpc = RPCConfig(laddr=r.get("laddr", cfg.rpc.laddr),
                            enabled=r.get("enabled", True),
                            unsafe=r.get("unsafe", False),
                            max_body_bytes=int(
                                r.get("max_body_bytes", 1_000_000)),
                            pprof_laddr=r.get("pprof_laddr", ""),
                            grpc_laddr=r.get("grpc_laddr", ""))
        bs = d.get("block_sync", {})
        cfg.block_sync = BlockSyncConfig(enable=bs.get("enable", True))
        ti = d.get("tx_index", {})
        cfg.tx_index = TxIndexConfig(
            indexer=ti.get("indexer", "kv"),
            sink_dsn=ti.get("sink_dsn", ""))
        ss = d.get("state_sync", {})
        cfg.state_sync = StateSyncConfig(
            enable=ss.get("enable", False),
            rpc_servers=ss.get("rpc_servers", ""),
            trust_height=ss.get("trust_height", 0),
            trust_hash=ss.get("trust_hash", ""),
            trust_period=float(ss.get("trust_period", 86400.0 * 7)),
            fetchers=int(ss.get("fetchers", 4)),
            chunk_timeout_ms=float(ss.get("chunk_timeout_ms", 15000.0)),
            retries=int(ss.get("retries", 3)),
            serve_rate_per_s=float(ss.get("serve_rate_per_s", 100.0)),
            serve_burst=int(ss.get("serve_burst", 32)))
        bv = d.get("batch_verifier", {})
        cfg.batch_verifier = BatchVerifierConfig(
            tpu_threshold=bv.get("tpu_threshold", 32),
            enable=bv.get("enable", True),
            rlc=bool(bv.get("rlc", False)),
            secp_lane=bool(bv.get("secp_lane", True)),
            comb=bool(bv.get("comb", True)),
            table_cache_mb=int(bv.get("table_cache_mb", 256)),
            host_pool_workers=int(bv.get("host_pool_workers", 0)))
        vs = d.get("verify_scheduler", {})
        cfg.verify_scheduler = VerifySchedulerConfig(
            enable=bool(vs.get("enable", True)),
            window_ms=float(vs.get("window_ms", 2.0)),
            max_batch=int(vs.get("max_batch", 8192)),
            max_pending=int(vs.get("max_pending", 65536)))
        bp = d.get("block_pipeline", {})
        cfg.block_pipeline = BlockPipelineConfig(
            enable=bool(bp.get("enable", True)),
            depth=int(bp.get("depth", 4)),
            group_commit_heights=int(bp.get("group_commit_heights", 8)))
        do = d.get("devobs", {})
        cfg.devobs = DevObsConfig(
            enable=bool(do.get("enable", True)),
            capacity=int(do.get("capacity", 256)))
        sl = d.get("slo", {})
        cfg.slo = SLOConfig(
            enable=bool(sl.get("enable", False)),
            window=int(sl.get("window", 1024)),
            **{f"{s}_p99_ms": float(sl.get(f"{s}_p99_ms", 0.0))
               for s in SLOConfig.STREAMS},
            **{f"{s}_budget_pct": float(sl.get(f"{s}_budget_pct", 1.0))
               for s in SLOConfig.STREAMS})
        ct = d.get("control", {})
        defaults = ControlConfig()
        cfg.control = ControlConfig(
            enable=bool(ct.get("enable", False)),
            period_ms=float(ct.get("period_ms", 1000.0)),
            recover_after=int(ct.get("recover_after", 3)),
            **{f: float(ct.get(f, getattr(defaults, f)))
               for knob in ControlConfig.KNOBS
               for f in (f"{knob}_min", f"{knob}_max", f"{knob}_step")})
        ls = d.get("light_serve", {})
        cfg.light_serve = LightServeConfig(
            enable=bool(ls.get("enable", True)),
            queue=int(ls.get("queue", 4096)),
            workers=int(ls.get("workers", 1)),
            batch=int(ls.get("batch", 256)),
            rate_per_s=float(ls.get("rate_per_s", 0.0)),
            burst=int(ls.get("burst", 0)),
            max_cursors_per_client=int(
                ls.get("max_cursors_per_client", 4)),
            max_cursors=int(ls.get("max_cursors", 1024)),
            cursor_batch=int(ls.get("cursor_batch", 64)),
            prewarm=bool(ls.get("prewarm", True)))
        c = d.get("consensus", {})
        cc = ConsensusConfig()
        for k in ("timeout_propose", "timeout_propose_delta",
                  "timeout_prevote", "timeout_prevote_delta",
                  "timeout_precommit", "timeout_precommit_delta",
                  "timeout_commit", "create_empty_blocks_interval",
                  "propose_reap_budget_ms", "propose_prepare_budget_ms"):
            if k in c:
                setattr(cc, k, float(c[k]))
        for k in ("skip_timeout_commit", "create_empty_blocks"):
            if k in c:
                setattr(cc, k, bool(c[k]))
        if "propose_max_bytes" in c:
            cc.propose_max_bytes = int(c["propose_max_bytes"])
        cfg.consensus = cc
        return cfg
