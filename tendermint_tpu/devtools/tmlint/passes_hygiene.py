"""TM301-TM308 — hygiene rules and registry checks.

Each rule encodes one invariant that previously lived only as prose in
CHANGES.md / ADRs:

  TM301  every thread is a daemon (or joined by its creator) — the
         conftest thread-leak guard's static twin
  TM302  optional deps (cryptography, grpc) import guarded
  TM303  no backslash inside an f-string replacement field (py3.10)
  TM304  no silent `except Exception: pass` in ops/ and crypto/
  TM305  fail.inject sites registered in libs/fail.REGISTERED_SITES
  TM306  trace span/instant names registered in libs/trace.KNOWN_SPANS
  TM307  metrics-bundle attribute reads name registered metrics
  TM308  every KnobSpec declares a literal finite safe_range and a
         signal naming a registered metric (ADR-023 control plane)

The registries are read by AST, not import — the pass must work with
no package import at all (and libs/fail.py stays enforceable even when
it is itself the file being edited).
"""
from __future__ import annotations

import ast
import io
import tokenize
from typing import Dict, List, Optional, Set, Tuple

from .core import Corpus, Finding, SourceFile
from .passes_shape import _call_name

OPTIONAL_DEPS = {"cryptography", "grpc"}
HOT_SCOPE = ("tendermint_tpu/ops/", "tendermint_tpu/crypto/")


# ---------------------------------------------------------------------------
# TM301 — non-daemon threads
# ---------------------------------------------------------------------------

def _fn_joins_threads(node: ast.AST) -> bool:
    """Does this function contain an X.join(...)/X.join() call that
    plausibly joins threads?  String `sep.join(iterable)` must NOT
    count (a ", ".join() in the same function would otherwise suppress
    the rule): a Constant receiver is always a string join, and a
    thread join takes no positional arg (or a timeout keyword)."""
    for sub in ast.walk(node):
        if not (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "join"):
            continue
        if isinstance(sub.func.value, ast.Constant):
            continue  # ", ".join(...)
        if len(sub.args) == 0 or (
                len(sub.args) == 1
                and isinstance(sub.args[0], ast.Constant)
                and isinstance(sub.args[0].value, (int, float))):
            return True  # t.join() / t.join(2.0)
        if any(k.arg == "timeout" for k in sub.keywords):
            return True
    return False


def _fn_sets_daemon(node: ast.AST) -> bool:
    """X.daemon = True somewhere in the function."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Assign) and \
                isinstance(sub.value, ast.Constant) and \
                sub.value.value is True:
            for t in sub.targets:
                if isinstance(t, ast.Attribute) and t.attr == "daemon":
                    return True
    return False


def _check_threads(f: SourceFile, findings: List[Finding]):
    if f.tree is None or f.path == "tendermint_tpu/libs/service.py":
        return  # BaseService.spawn IS the sanctioned daemon-thread owner

    def check_fn(node, qual):
        joined = daemon_fixup = None  # computed lazily, once
        for sub in ast.walk(node):
            if not (isinstance(sub, ast.Call)
                    and _call_name(sub.func) == "Thread"):
                continue
            kw = {k.arg: k.value for k in sub.keywords}
            d = kw.get("daemon")
            if isinstance(d, ast.Constant) and d.value is True:
                continue
            if d is None:
                if joined is None:
                    joined = _fn_joins_threads(node)
                    daemon_fixup = _fn_sets_daemon(node)
                if joined or daemon_fixup:
                    continue  # joined by the creator / t.daemon = True
            findings.append(Finding(
                "TM301", f.path, sub.lineno, qual,
                "threading.Thread without daemon=True and never "
                "joined here — a wedged non-daemon thread blocks "
                "interpreter shutdown (use daemon=True or "
                "libs/service.BaseService.spawn)"))

    for node in f.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            check_fn(node, node.name)
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    check_fn(sub, f"{node.name}.{sub.name}")


# ---------------------------------------------------------------------------
# TM302 — unconditional optional-dep imports
# ---------------------------------------------------------------------------

def _check_optional_imports(f: SourceFile, findings: List[Finding]):
    if f.tree is None:
        return
    for node in f.tree.body:  # module level only; function-local or
        # try-guarded imports are exactly the sanctioned patterns
        mods: List[str] = []
        if isinstance(node, ast.Import):
            mods = [a.name.split(".")[0] for a in node.names]
        elif isinstance(node, ast.ImportFrom) and node.module:
            mods = [node.module.split(".")[0]]
        for m in mods:
            if m in OPTIONAL_DEPS:
                findings.append(Finding(
                    "TM302", f.path, node.lineno, "<module>",
                    f"unconditional top-level import of optional "
                    f"dependency '{m}' — guard with try/except "
                    "ImportError and degrade the feature, not the "
                    "module"))


# ---------------------------------------------------------------------------
# TM303 — backslash inside an f-string replacement field
# ---------------------------------------------------------------------------

def find_fstring_backslashes(src: str) -> List[Tuple[int, str]]:
    """[(line, token_head)] for every f-string whose {...} expression
    part contains a backslash — the class Python 3.10 rejects at parse
    time.

    On <= 3.11 an f-string is one STRING token and the brace-tracking
    scan below applies.  On 3.12+ (PEP 701) f-strings tokenize as
    FSTRING_START/MIDDLE/END with the expression parts as ordinary
    tokens, and the breakage class appears as a STRING token carrying a
    backslash escape INSIDE an open f-string (e.g. the seed-era
    f"{chr(10).join(...)}" written as f"{'\\n'.join(...)}") — tracked
    via fstring depth so the rule still fires for a developer editing
    on a newer interpreter than the 3.10 container."""
    out: List[Tuple[int, str]] = []
    try:
        tokens = list(tokenize.generate_tokens(
            io.StringIO(src).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return out
    fstart = getattr(tokenize, "FSTRING_START", None)
    fend = getattr(tokenize, "FSTRING_END", None)
    fdepth = 0
    for tok in tokens:
        if fstart is not None:
            if tok.type == fstart:
                fdepth += 1
                continue
            if tok.type == fend:
                fdepth = max(0, fdepth - 1)
                continue
            if fdepth > 0 and tok.type == tokenize.STRING and \
                    "\\" in tok.string:
                out.append((tok.start[0], tok.string[:40]))
                continue
        if tok.type != tokenize.STRING:
            continue
        s = tok.string
        q = s.find('"')
        qq = s.find("'")
        qpos = min(x for x in (q, qq) if x >= 0) if max(q, qq) >= 0 \
            else -1
        if qpos <= 0:
            continue
        prefix = s[:qpos].lower()
        if "f" not in prefix:
            continue
        body = s[qpos:]
        if body[:3] in ('"""', "'''"):
            body = body[3:-3]
        else:
            body = body[1:-1]
        depth = 0
        i = 0
        while i < len(body):
            c = body[i]
            if depth == 0:
                if c == "\\":
                    i += 2  # escape in the literal part: fine, skip
                    continue
                if c == "{":
                    if body[i:i + 2] == "{{":
                        i += 2
                        continue
                    depth = 1
                elif c == "}" and body[i:i + 2] == "}}":
                    i += 2
                    continue
            else:
                if c == "\\":
                    out.append((tok.start[0], s[:40]))
                    break
                if c == "{":
                    depth += 1
                elif c == "}":
                    depth -= 1
            i += 1
    return out


def _check_fstrings(f: SourceFile, findings: List[Finding]):
    for line, head in find_fstring_backslashes(f.src):
        findings.append(Finding(
            "TM303", f.path, line, "<module>",
            f"backslash inside an f-string replacement field ({head!r}) "
            "— Python 3.10 rejects this at parse time (the seed-era "
            "metrics breakage); hoist the escape into a variable"))


# ---------------------------------------------------------------------------
# TM304 — silent except-pass in hot paths
# ---------------------------------------------------------------------------

def _check_except_pass(f: SourceFile, findings: List[Finding]):
    if f.tree is None or not f.path.startswith(HOT_SCOPE):
        return
    lines = f.src.splitlines()
    for node in ast.walk(f.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        bare = node.type is None
        plain_exc = isinstance(node.type, ast.Name) and \
            node.type.id == "Exception"
        if not (bare or plain_exc):
            continue
        if not (len(node.body) == 1 and isinstance(node.body[0],
                                                   ast.Pass)):
            continue
        span = range(node.lineno - 1,
                     min(node.body[0].lineno, len(lines)))
        if any("#" in lines[i] for i in span if i < len(lines)):
            continue  # a written justification is the accepted escape
        findings.append(Finding(
            "TM304", f.path, node.lineno, "<module>",
            "silent `except Exception: pass` in a verify hot path — "
            "justify with a comment or handle the failure"))


# ---------------------------------------------------------------------------
# registry extraction (AST-level, no imports)
# ---------------------------------------------------------------------------

def _literal_strings(node: ast.AST) -> Set[str]:
    return {n.value for n in ast.walk(node)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)}


def registered_fail_sites(corpus: Corpus) -> Tuple[Set[str], Set[str]]:
    """(exact sites, dynamic prefixes) from libs/fail.py."""
    f = corpus.files.get("tendermint_tpu/libs/fail.py")
    exact: Set[str] = set()
    prefixes: Set[str] = set()
    if f is None or f.tree is None:
        return exact, prefixes
    for node in f.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            if node.targets[0].id == "REGISTERED_SITES":
                exact |= _literal_strings(node.value)
            elif node.targets[0].id == "DYNAMIC_SITE_PREFIXES":
                prefixes |= _literal_strings(node.value)
    return exact, prefixes


def known_trace_spans(corpus: Corpus) -> Set[str]:
    f = corpus.files.get("tendermint_tpu/libs/trace.py")
    if f is None or f.tree is None:
        return set()
    for node in f.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "KNOWN_SPANS":
            return _literal_strings(node.value)
    return set()


def registered_metric_attrs(corpus: Corpus) -> Set[str]:
    f = corpus.files.get("tendermint_tpu/libs/metrics.py")
    out: Set[str] = set()
    if f is None or f.tree is None:
        return out
    for cls in f.tree.body:
        if not isinstance(cls, ast.ClassDef):
            continue
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Attribute) \
                    and isinstance(node.targets[0].value, ast.Name) \
                    and node.targets[0].value.id == "self" \
                    and isinstance(node.value, ast.Call) \
                    and _call_name(node.value.func) in (
                        "counter", "gauge", "histogram"):
                out.add(node.targets[0].attr)
    return out


# ---------------------------------------------------------------------------
# TM305 — fail.inject literal sites
# ---------------------------------------------------------------------------

def _site_registered(site: str, exact: Set[str],
                     prefixes: Set[str]) -> bool:
    return site in exact or any(site.startswith(p) for p in prefixes)


def _check_fail_sites(f: SourceFile, exact: Set[str],
                      prefixes: Set[str], findings: List[Finding]):
    if f.tree is None or f.path == "tendermint_tpu/libs/fail.py":
        return
    for node in ast.walk(f.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node.func)
        if name not in ("inject", "corrupt_bitmap", "set_mode",
                        "fired"):
            continue
        recv = getattr(node.func, "value", None)
        if not (isinstance(recv, ast.Name) and recv.id == "fail"):
            continue
        if not node.args:
            continue
        a0 = node.args[0]
        if not (isinstance(a0, ast.Constant) and
                isinstance(a0.value, str)):
            continue  # dynamic sites are enforced at runtime (set_mode)
        if a0.value == "*":
            continue
        if not _site_registered(a0.value, exact, prefixes):
            findings.append(Finding(
                "TM305", f.path, node.lineno, "<module>",
                f"fail site '{a0.value}' is not in libs/fail.py "
                "REGISTERED_SITES / DYNAMIC_SITE_PREFIXES — register "
                "it so chaos coverage can be asserted"))


# ---------------------------------------------------------------------------
# TM306 — trace span names
# ---------------------------------------------------------------------------

def _check_trace_spans(f: SourceFile, known: Set[str],
                       findings: List[Finding]):
    if f.tree is None or f.path == "tendermint_tpu/libs/trace.py":
        return
    for node in ast.walk(f.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node.func)
        if name not in ("span", "instant"):
            continue
        recv = getattr(node.func, "value", None)
        if not (isinstance(recv, ast.Name) and recv.id == "trace"):
            continue
        if not node.args:
            continue
        a0 = node.args[0]
        if not (isinstance(a0, ast.Constant) and
                isinstance(a0.value, str)):
            continue
        if a0.value not in known:
            findings.append(Finding(
                "TM306", f.path, node.lineno, "<module>",
                f"trace span '{a0.value}' is not in libs/trace.py "
                "KNOWN_SPANS — register the name so trace consumers "
                "can rely on it"))


# ---------------------------------------------------------------------------
# TM308 — KnobSpec declarations (adaptive control plane, ADR-023)
# ---------------------------------------------------------------------------

_KNOBSPEC_PARAMS = ("name", "safe_range", "step", "direction",
                    "signal", "mode", "labels")


def _knobspec_arg(call: ast.Call, param: str) -> Optional[ast.AST]:
    idx = _KNOBSPEC_PARAMS.index(param)
    if idx < len(call.args):
        return call.args[idx]
    for kw in call.keywords:
        if kw.arg == param:
            return kw.value
    return None


def _numeric_const(node: Optional[ast.AST]) -> Optional[float]:
    """The value of a literal int/float (incl. unary minus), else None."""
    if isinstance(node, ast.UnaryOp) and \
            isinstance(node.op, ast.USub) and \
            isinstance(node.operand, ast.Constant) and \
            isinstance(node.operand.value, (int, float)):
        return -float(node.operand.value)
    if isinstance(node, ast.Constant) and \
            isinstance(node.value, (int, float)) and \
            not isinstance(node.value, bool):
        return float(node.value)
    return None


def _check_knob_specs(f: SourceFile, metric_attrs: Set[str],
                      findings: List[Finding]):
    """Every KnobSpec(...) call must DECLARE its envelope as literals:
    a finite (lo, hi) safe_range with lo <= hi, a literal step > 0,
    and a literal signal string naming a metric some bundle class in
    libs/metrics.py registers.  The governor only ever moves a knob
    inside a range a human wrote down and reviews — a computed range
    or a typo'd steering signal is a lint error, not a 3am incident."""
    if f.tree is None:
        return
    import math as _math
    for node in ast.walk(f.tree):
        if not (isinstance(node, ast.Call)
                and _call_name(node.func) == "KnobSpec"):
            continue
        name_node = _knobspec_arg(node, "name")
        label = name_node.value \
            if isinstance(name_node, ast.Constant) \
            and isinstance(name_node.value, str) else "<dynamic>"
        rng = _knobspec_arg(node, "safe_range")
        ok_range = False
        if isinstance(rng, (ast.Tuple, ast.List)) and \
                len(rng.elts) == 2:
            lo = _numeric_const(rng.elts[0])
            hi = _numeric_const(rng.elts[1])
            ok_range = (lo is not None and hi is not None
                        and _math.isfinite(lo) and _math.isfinite(hi)
                        and lo <= hi)
        if not ok_range:
            findings.append(Finding(
                "TM308", f.path, node.lineno, "<module>",
                f"KnobSpec {label!r}: safe_range must be a LITERAL "
                "finite (lo, hi) tuple with lo <= hi — the governor's "
                "envelope is declared and reviewed, never computed"))
        step = _numeric_const(_knobspec_arg(node, "step"))
        if step is None or not (_math.isfinite(step) and step > 0):
            findings.append(Finding(
                "TM308", f.path, node.lineno, "<module>",
                f"KnobSpec {label!r}: step must be a literal finite "
                "number > 0"))
        sig = _knobspec_arg(node, "signal")
        if not (isinstance(sig, ast.Constant)
                and isinstance(sig.value, str)
                and sig.value in metric_attrs):
            got = sig.value if isinstance(sig, ast.Constant) else None
            findings.append(Finding(
                "TM308", f.path, node.lineno, "<module>",
                f"KnobSpec {label!r}: signal {got!r} must be a literal "
                "string naming a metric registered by a bundle class "
                "in libs/metrics.py — the control plane steers on "
                "PUBLISHED signals only"))


# ---------------------------------------------------------------------------
# TM307 — metric attribute reads
# ---------------------------------------------------------------------------

def _metrics_receiver(expr: ast.AST, local_metric_names: Set[str]) -> bool:
    if isinstance(expr, ast.Attribute) and expr.attr == "metrics":
        return True
    if isinstance(expr, ast.Call) and \
            _call_name(expr.func) == "_metrics":
        return True
    if isinstance(expr, ast.Name) and expr.id in local_metric_names:
        return True
    return False


def _check_metric_attrs(f: SourceFile, attrs: Set[str],
                        findings: List[Finding]):
    if f.tree is None or f.path == "tendermint_tpu/libs/metrics.py":
        return
    for fn in ast.walk(f.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        local: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and _metrics_receiver(node.value, set()):
                local.add(node.targets[0].id)
        for node in ast.walk(fn):
            if isinstance(node, ast.Attribute) and \
                    _metrics_receiver(node.value, local):
                if node.attr not in attrs:
                    findings.append(Finding(
                        "TM307", f.path, node.lineno, fn.name,
                        f"metric attribute '{node.attr}' is not "
                        "registered by any bundle class in "
                        "libs/metrics.py — typo, or register the "
                        "metric"))
    return


def check(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    exact, prefixes = registered_fail_sites(corpus)
    spans = known_trace_spans(corpus)
    metric_attrs = registered_metric_attrs(corpus)
    for f in corpus.files.values():
        _check_threads(f, findings)
        _check_optional_imports(f, findings)
        _check_fstrings(f, findings)
        _check_except_pass(f, findings)
        _check_fail_sites(f, exact, prefixes, findings)
        _check_trace_spans(f, spans, findings)
        _check_metric_attrs(f, metric_attrs, findings)
        _check_knob_specs(f, metric_attrs, findings)
    return findings
