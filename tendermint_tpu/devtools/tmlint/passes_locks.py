"""TM201-TM204 — lock-order and blocking-call analysis.

Phase 1 walks every file for lock creation sites
(``self._x = threading.Lock()`` inside a class, module-level
``_x = threading.Lock()``) and derives the same ids
devtools/lockorder.py declares ranks for.

Phase 2 builds per-function summaries: which locks a function acquires
directly (``with self._x:`` / ``with _x:``), which calls it makes while
holding which locks, and which blocking calls appear under a held lock.
Call targets resolve naively but effectively for this codebase:
``self.m()`` to the enclosing class, ``mod.f()`` through the import
table to analyzed modules, bare ``f()`` to the same module.

Phase 3 closes the call graph to a fixpoint (transitive acquire sets),
emits the acquires-while-holding edge set, and checks it against the
declared ranks: an edge from rank a to rank b requires a < b (TM201);
any cycle among creation-site locks is TM201 regardless of ranks.
Blocking calls (queue get/put, future .result, .join, sleep, waiting
on a primitive other than the held condition, device kernel entries)
under a RANKED lock are TM202.  Core-module locks with no rank are
TM203; declared ranks with no creation site are TM204.

The static pass underapproximates (dynamic dispatch, callbacks); its
runtime twin — the lockset monitor in tmlint/runtime.py, armed with
TM_TPU_LOCKSAN=1 — records the ACTUAL acquisition order in the
scheduler/degrade/comb tests against the same table.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from tendermint_tpu.devtools import lockorder

from .core import Corpus, Finding
from .passes_shape import CROSS_MODULE_ENTRIES, _call_name

# lock-order discipline is enforced in the concurrency core; p2p/rpc
# socket locks serialize I/O by design and stay out of the table
CORE_SCOPE = ("tendermint_tpu/crypto/", "tendermint_tpu/ops/",
              "tendermint_tpu/libs/", "tendermint_tpu/parallel/")

LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

# callee attribute names that block the calling thread
BLOCKING_ATTRS = {"result", "join", "sleep", "serve_forever", "accept",
                  "recv", "recv_into", "sendall", "connect", "select",
                  "block_until_ready", "device_put"}
# queue verbs: blocking unless the _nowait variant
QUEUE_ATTRS = {"get", "put"}
# jitted kernel entries whose CALL launches device work; building a
# shard_map/pallas_call wrapper is lazy and cheap, so those two are
# excluded here even though they are shape-discipline entries
KERNEL_LAUNCH_ENTRIES = CROSS_MODULE_ENTRIES - {"shard_map",
                                                "pallas_call"}


@dataclass(frozen=True)
class LockSite:
    lock_id: str       # "path:Class.attr" / "path:name"
    path: str
    line: int
    kind: str          # Lock / RLock / Condition
    scope: str         # "class" / "module" / "local"


@dataclass
class FnSummary:
    key: Tuple[str, Optional[str], str]     # (path, class, name)
    acquires: Set[str] = field(default_factory=set)
    # calls made while holding locks: (callee_key_candidates, held ids,
    # line) — candidates because resolution is by name
    calls: List[Tuple[List[Tuple[str, Optional[str], str]],
                      Tuple[str, ...], int]] = field(default_factory=list)
    blocking: List[Tuple[str, Tuple[str, ...], int]] = \
        field(default_factory=list)
    direct_edges: Set[Tuple[str, str, int]] = field(default_factory=set)


def _lock_factory_kind(call: ast.AST) -> Optional[str]:
    """'Lock' for threading.Lock() / Lock() / __import__("threading")
    .Lock(); None otherwise."""
    if not isinstance(call, ast.Call):
        return None
    f = call.func
    name = _call_name(f)
    if name not in LOCK_FACTORIES:
        return None
    if isinstance(f, ast.Name):
        return name
    if isinstance(f, ast.Attribute):
        v = f.value
        if isinstance(v, ast.Name) and v.id == "threading":
            return name
        if isinstance(v, ast.Call) and _call_name(v.func) == "__import__":
            return name
    return None


def lock_creation_sites(corpus: Corpus) -> List[LockSite]:
    sites: List[LockSite] = []
    for f in sorted(corpus.files.values(), key=lambda x: x.path):
        if f.tree is None:
            continue

        def scan_body(body, cls: Optional[str], fn: Optional[str]):
            for node in body:
                if isinstance(node, ast.ClassDef):
                    scan_body(node.body, node.name, None)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    scan_body(node.body, cls, node.name)
                elif isinstance(node, ast.Assign):
                    kind = _lock_factory_kind(node.value)
                    if kind is None:
                        continue
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self" and cls:
                            sites.append(LockSite(
                                f"{f.path}:{cls}.{t.attr}", f.path,
                                node.lineno, kind, "class"))
                        elif isinstance(t, ast.Name):
                            if fn is None and cls is None:
                                sites.append(LockSite(
                                    f"{f.path}:{t.id}", f.path,
                                    node.lineno, kind, "module"))
                            else:
                                sites.append(LockSite(
                                    f"{f.path}:{fn or cls}.{t.id}",
                                    f.path, node.lineno, kind, "local"))
                else:
                    for child in ast.iter_child_nodes(node):
                        if not isinstance(child, ast.expr):
                            # stmt or ExceptHandler/match_case: a lock
                            # created in an except block is still a lock
                            scan_body([child], cls, fn)

        scan_body(f.tree.body, None, None)
    return sites


def _import_table(tree: ast.AST, path: str) -> Dict[str, str]:
    """local alias -> dotted module for tendermint_tpu imports,
    including relative ones (``from . import degrade``)."""
    pkg_parts = path.rsplit("/", 1)[0].split("/") \
        if "/" in path else []
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name.startswith("tendermint_tpu"):
                    out[(a.asname or a.name).split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom):
            if node.level:  # relative: resolve against this file's pkg
                base = pkg_parts[:len(pkg_parts) - (node.level - 1)]
                mod = ".".join(base + ([node.module] if node.module
                                       else []))
            else:
                mod = node.module or ""
            if not mod.startswith("tendermint_tpu"):
                continue
            for a in node.names:
                out[a.asname or a.name] = f"{mod}.{a.name}"
    return out


def _mod_to_path(dotted: str) -> str:
    return dotted.replace(".", "/") + ".py"


class _FnLockWalk:
    """Walk one function body tracking the held-lock stack."""

    def __init__(self, path: str, cls: Optional[str],
                 class_locks: Dict[Tuple[str, str], str],
                 module_locks: Dict[Tuple[str, str], str],
                 imports: Dict[str, str], summary: FnSummary,
                 cond_ids: Set[str]):
        self.path = path
        self.cls = cls
        self.class_locks = class_locks
        self.module_locks = module_locks
        self.imports = imports
        self.s = summary
        self.cond_ids = cond_ids
        self.held: List[str] = []
        self.nested: List[FnSummary] = []

    # -- resolution -----------------------------------------------------

    def _lock_ref(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and self.cls:
            return self.class_locks.get((self.cls, expr.attr))
        if isinstance(expr, ast.Name):
            return self.module_locks.get((self.path, expr.id))
        return None

    def _callee_keys(self, func: ast.AST) \
            -> List[Tuple[str, Optional[str], str]]:
        if isinstance(func, ast.Name):
            tgt = self.imports.get(func.id)
            if tgt:  # from tendermint_tpu.x import f
                mod, _, name = tgt.rpartition(".")
                return [(_mod_to_path(mod), None, name)]
            return [(self.path, None, func.id),
                    (self.path, self.cls, func.id)]
        if isinstance(func, ast.Attribute):
            v = func.value
            if isinstance(v, ast.Name):
                if v.id in ("self", "cls") and self.cls:
                    return [(self.path, self.cls, func.attr)]
                tgt = self.imports.get(v.id)
                if tgt:
                    return [(_mod_to_path(tgt), None, func.attr)]
        return []

    # -- walk -----------------------------------------------------------

    def run(self, fn: ast.AST):
        for st in fn.body:
            self._stmt(st)

    def _stmt(self, st: ast.AST):
        if isinstance(st, ast.With):
            pushed = 0
            for item in st.items:
                self._expr(item.context_expr)
                lid = self._lock_ref(item.context_expr)
                if lid is not None:
                    for held in self.held:
                        if held != lid:
                            self.s.direct_edges.add(
                                (held, lid, st.lineno))
                    self.s.acquires.add(lid)
                    self.held.append(lid)
                    pushed += 1
            for s in st.body:
                self._stmt(s)
            for _ in range(pushed):
                self.held.pop()
            return
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: its body runs LATER (not under the current
            # held set), and what it acquires must NOT count as an
            # acquisition of the enclosing factory — collect it into a
            # sibling summary so direct nesting inside the closure is
            # still checked
            sub = FnSummary((self.path, self.cls,
                             f"{self.s.key[2]}.{st.name}"))
            walker = _FnLockWalk(self.path, self.cls, self.class_locks,
                                 self.module_locks, self.imports, sub,
                                 self.cond_ids)
            walker.run(st)
            self.nested.append(sub)
            self.nested.extend(walker.nested)
            return
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                self._expr(child)
            else:
                # stmt OR a non-stmt container (ast.ExceptHandler,
                # ast.match_case, withitem...): recurse — lock
                # acquisitions and blocking calls in error-recovery
                # paths must not be invisible
                self._stmt(child)

    def _expr(self, expr: ast.AST):
        # skip Lambda bodies: a lambda built under a lock runs later,
        # not while the lock is held
        lambda_nodes = set()
        for node in ast.walk(expr):
            if isinstance(node, ast.Lambda):
                for sub in ast.walk(node.body):
                    lambda_nodes.add(id(sub))
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call) or id(node) in lambda_nodes:
                continue
            self._call(node)

    def _call(self, node: ast.Call):
        name = _call_name(node.func)
        held = tuple(self.held)
        # record EVERY resolvable call (held or not): the transitive
        # closure must see lock-free intermediates — submit() holds
        # _cond while calling _gauge_depth(), which only via _metrics()
        # reaches degrade.runtime()'s install lock
        keys = self._callee_keys(node.func)
        if keys:
            self.s.calls.append((keys, held, node.lineno))
        if held:
            self._check_blocking(node, name, held)

    def _check_blocking(self, node: ast.Call, name: Optional[str],
                        held: Tuple[str, ...]):
        if name is None:
            return
        desc = None
        if name in BLOCKING_ATTRS:
            # allow event.wait-style names only via the wait rule below;
            # .sleep only when the receiver is `time`
            if name == "sleep":
                v = getattr(node.func, "value", None)
                if not (isinstance(v, ast.Name) and v.id == "time"):
                    return
            desc = f".{name}()"
        elif name in QUEUE_ATTRS and isinstance(node.func, ast.Attribute):
            # heuristic: queue-like receivers (self._q, *_queue, staged)
            v = node.func.value
            rname = v.attr if isinstance(v, ast.Attribute) else \
                (v.id if isinstance(v, ast.Name) else "")
            if not any(h in rname.lower() for h in ("q", "queue",
                                                    "staged")):
                return
            desc = f"{rname}.{name}()"
        elif name == "wait" and isinstance(node.func, ast.Attribute):
            # waiting on the condition you hold is the whole point of a
            # condition variable; waiting on anything else under a lock
            # parks the thread with the lock held
            ref = self._lock_ref(node.func.value)
            if ref is not None and ref in self.held and \
                    ref in self.cond_ids:
                return
            if ref is None and not isinstance(node.func.value,
                                              (ast.Name, ast.Attribute)):
                return
            desc = ".wait() on a primitive other than the held condition"
        elif name in KERNEL_LAUNCH_ENTRIES:
            desc = f"device kernel entry {name}()"
        if desc is not None:
            self.s.blocking.append((desc, held, node.lineno))


def _build_summaries(corpus: Corpus, sites: List[LockSite]):
    class_locks: Dict[str, Dict[Tuple[str, str], str]] = {}
    module_locks: Dict[Tuple[str, str], str] = {}
    cond_ids = {s.lock_id for s in sites if s.kind == "Condition"}
    for s in sites:
        mod, _, qual = s.lock_id.partition(":")
        if s.scope == "class":
            cls, attr = qual.split(".", 1)
            class_locks.setdefault(s.path, {})[(cls, attr)] = s.lock_id
        elif s.scope == "module":
            module_locks[(s.path, qual)] = s.lock_id

    summaries: Dict[Tuple[str, Optional[str], str], FnSummary] = {}
    for f in corpus.files.values():
        if f.tree is None:
            continue
        imports = _import_table(f.tree, f.path)

        def visit_fn(fn, cls: Optional[str]):
            key = (f.path, cls, fn.name)
            summary = FnSummary(key)
            walker = _FnLockWalk(f.path, cls, class_locks.get(f.path, {}),
                                 module_locks, imports, summary, cond_ids)
            walker.run(fn)
            summaries[key] = summary
            for sub in walker.nested:  # closures: own edge context,
                # invisible to the name-resolved call graph
                summaries.setdefault(sub.key, sub)

        for node in f.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit_fn(node, None)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        visit_fn(sub, node.name)
    return summaries


def _transitive_acquires(summaries) -> Dict[Tuple, Set[str]]:
    """Fixpoint: locks a call to fn may acquire (directly or via
    callees)."""
    acq = {k: set(s.acquires) for k, s in summaries.items()}
    changed = True
    while changed:
        changed = False
        for k, s in summaries.items():
            for keys, _held, _line in s.calls:
                for cand in keys:
                    got = acq.get(cand)
                    if got and not got <= acq[k]:
                        acq[k] |= got
                        changed = True
    return acq


def check(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    sites = lock_creation_sites(corpus)

    # TM203: core locks must be ranked (module + instance locks; locals
    # are scoped to one call and cannot order-invert across threads)
    declared = set(lockorder.LOCK_ORDER)
    seen_ids = set()
    for s in sites:
        seen_ids.add(s.lock_id)
        if s.scope == "local":
            continue
        if s.path.startswith(CORE_SCOPE) and s.lock_id not in declared:
            findings.append(Finding(
                "TM203", s.path, s.line, s.lock_id.partition(":")[2],
                f"lock {s.lock_id} has no rank in devtools/lockorder.py "
                "— every core-module lock takes a declared position"))

    # TM204: declared ranks must correspond to live creation sites
    for lock_id in sorted(declared - seen_ids):
        findings.append(Finding(
            "TM204", "tendermint_tpu/devtools/lockorder.py", 1,
            lock_id.partition(":")[2],
            f"declared lock {lock_id} has no creation site in the tree "
            "(renamed or removed?) — drop or fix the table row"))

    summaries = _build_summaries(corpus, sites)
    acq = _transitive_acquires(summaries)

    # edge set: direct with-nesting plus call-closure edges
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    for k, s in summaries.items():
        path, cls, name = k
        qual = f"{cls}.{name}" if cls else name
        for a, b, line in s.direct_edges:
            edges.setdefault((a, b), (path, line, qual))
        for keys, held, line in s.calls:
            if not held:
                continue
            for cand in keys:
                for b in acq.get(cand, ()):
                    for a in held:
                        if a != b:
                            edges.setdefault(
                                (a, b),
                                (path, line,
                                 f"{qual} -> {cand[2]}()"))

    # TM201: rank violations on edges
    for (a, b), (path, line, qual) in sorted(edges.items()):
        ra, rb = lockorder.rank(a), lockorder.rank(b)
        if ra is not None and rb is not None and ra >= rb:
            findings.append(Finding(
                "TM201", path, line, qual,
                f"acquires {b} (rank {rb}) while holding {a} (rank "
                f"{ra}); declared order requires "
                f"{'strictly lower-ranked locks first' if ra > rb else 'distinct ranks for nested locks'}"))

    # TM201: cycles even among unranked locks
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    for start in sorted(graph):
        stack, seen = [(start, [start])], set()
        while stack:
            cur, trail = stack.pop()
            for nxt in graph.get(cur, ()):
                if nxt == start:
                    path, line, qual = edges[(cur, nxt)]
                    cyc = " -> ".join(trail + [start])
                    findings.append(Finding(
                        "TM201", path, line, qual,
                        f"lock cycle: {cyc}"))
                elif nxt not in seen and nxt > start:
                    seen.add(nxt)
                    stack.append((nxt, trail + [nxt]))

    # TM202: blocking calls under a RANKED lock
    for k, s in summaries.items():
        path, cls, name = k
        if not path.startswith(CORE_SCOPE) and path != "bench.py":
            continue
        qual = f"{cls}.{name}" if cls else name
        for desc, held, line in s.blocking:
            ranked = [h for h in held if lockorder.rank(h) is not None]
            if ranked:
                findings.append(Finding(
                    "TM202", path, line, qual,
                    f"blocking call {desc} while holding "
                    f"{', '.join(ranked)} — park the thread only after "
                    "releasing ranked locks"))
    return findings
