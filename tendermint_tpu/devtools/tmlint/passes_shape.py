"""TM101/TM102 — compile-shape discipline for the kernel modules.

The whole 870 s tier-1 compile budget rests on one invariant: the
jitted kernels see FEW distinct shapes, because every batch is padded
into a registered bucket (ops/ed25519.bucket_size powers of two,
MAX_CHUNK sub-launches, SPLIT_CHUNK multiples, _comb_k_pad validator
buckets) before it reaches a kernel.  A new route that pads to
`len(batch)` — or passes a raw-sized array straight into a jit entry —
compiles one XLA executable per batch size and the budget is gone
before any test fails functionally.

This pass is a per-function taint analysis over the kernel modules
(ops/, parallel/):

  * taint source: `len(...)` — the raw batch size — and names assigned
    from tainted expressions;
  * blessing: a call to a registered bucket helper, a module-level
    ALL_CAPS constant (MAX_CHUNK, PALLAS_TILE, ... — compile-time
    fixed), or an existing array's `.shape` (no new shape class can
    come from a shape that already exists on-device);
  * sinks: jnp array constructors' shape argument, np/jnp.pad widths,
    and EVERY argument of a jitted-entry call (module-level names bound
    to jax.jit(...), @jax.jit functions, pl.pallas_call, shard_map,
    plus the cross-module entry list below).

An expression reaching a sink is flagged when it is tainted and not
blessed.  Blessing wins: `nb - n` with nb = bucket_size(n) is the
canonical pad width.  The helpers themselves are exempt (they ARE the
discipline).

TM102 separately flags jax.jit/shard_map/pallas_call invoked inside a
function body whose result is not cached (module constant, attribute/
subscript store e.g. ``self._fns[key] = f``, closure factory, or
returned) — a per-call jit re-traces every invocation.
"""
from __future__ import annotations

import ast
from typing import List, Optional, Set

from .core import Corpus, Finding

SCOPE = ("tendermint_tpu/ops/", "tendermint_tpu/parallel/")

# the registered bucket helpers: deriving a size THROUGH one of these
# is the sanctioned way to go from len(batch) to a compile shape.
# (Keep in sync with docs/adr/adr-014-tmlint.md when adding a helper.)
BUCKET_HELPERS = {
    "bucket_size",        # ops/ed25519: pow2 lane bucket, floor MIN_BUCKET
    "_comb_k_pad",        # ops/ed25519: validator-axis pow2 bucket
    "_pad_dev",           # ops/ed25519: pad staged dict to a bucket
    "msm_bucket",         # parallel/sharding: mesh MSM bucket policy
    "worth_sharding_msm",  # parallel/sharding: bucket-memory policy
}

# jit entries callable across module boundaries (module-local entries
# are auto-detected from `NAME = jax.jit(...)` / @jax.jit).
CROSS_MODULE_ENTRIES = {
    "verify_kernel", "comb_kernel", "comb_build_kernel",
    "verify_packed_pallas", "verify_packed_split_pallas",
    "verify_staged", "comb_verify_staged",
    "pallas_call", "shard_map",
}

# device-allocating constructors: only the jnp namespace — host-side
# np staging buffers are padded into buckets before any kernel seam,
# and *_like constructors inherit an existing array's shape class
JNP_CONSTRUCTORS = {"zeros", "ones", "full", "empty", "arange"}


def _call_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _is_jit_factory(call: ast.Call) -> bool:
    """jax.jit(...) / jit(...) / partial(jax.jit, ...)(...) /
    @partial(jax.jit, ...)."""
    f = call.func
    if _call_name(f) == "jit":
        return True
    # partial(jax.jit, ...)(...) — outer call whose func is a call to
    # partial with jit as first arg
    if isinstance(f, ast.Call) and _call_name(f.func) == "partial" \
            and f.args and _call_name(f.args[0]) == "jit":
        return True
    return False


def _decorated_jit(fn: ast.AST) -> bool:
    for d in getattr(fn, "decorator_list", []):
        if _call_name(d) == "jit":
            return True
        if isinstance(d, ast.Call):
            if _call_name(d.func) == "jit":
                return True
            if _call_name(d.func) == "partial" and d.args \
                    and _call_name(d.args[0]) == "jit":
                return True
    return False


def module_constants(tree: ast.AST) -> Set[str]:
    """Module-level ALL_CAPS names: compile-time-fixed sizes."""
    out: Set[str] = set()
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.target is not None:
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Name) and t.id.upper() == t.id \
                    and any(c.isalpha() for c in t.id):
                out.add(t.id)
    return out


def module_jit_entries(tree: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call) \
                and _is_jit_factory(node.value):
            out.add(node.targets[0].id)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and _decorated_jit(node):
            out.add(node.name)
    return out


class _FnShapeCheck:
    """Single-function (plus nested defs, one shared namespace) taint
    walk in source order."""

    def __init__(self, path: str, qual: str, constants: Set[str],
                 entries: Set[str], findings: List[Finding]):
        self.path = path
        self.qual = qual
        self.constants = constants
        self.entries = entries
        self.findings = findings
        self.tainted: Set[str] = set()
        self.blessed: Set[str] = set()

    # -- expression classification ------------------------------------

    def _expr_flags(self, expr: ast.AST):
        """(tainted, blessed) for an expression subtree.  Blessing WINS
        at use sites: `nb - n` with nb = bucket_size(n) is the
        canonical pad width."""
        tainted = blessed = False
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                name = _call_name(node.func)
                if name == "len":
                    tainted = True
                elif name in BUCKET_HELPERS:
                    blessed = True
            elif isinstance(node, ast.Name):
                if node.id in self.tainted:
                    tainted = True
                if node.id in self.constants or node.id in self.blessed:
                    blessed = True
            elif isinstance(node, ast.Attribute) and node.attr == "shape":
                blessed = True
        return tainted, blessed

    def _is_raw(self, expr: ast.AST) -> bool:
        tainted, blessed = self._expr_flags(expr)
        return tainted and not blessed

    def _flag(self, node: ast.AST, msg: str):
        self.findings.append(Finding(
            "TM101", self.path, getattr(node, "lineno", 1), self.qual,
            msg))

    # -- walk ----------------------------------------------------------

    def run(self, fn: ast.AST):
        for stmt in fn.body:
            self._stmt(stmt)

    def _assign_target(self, target: ast.AST, value: ast.AST):
        if not isinstance(target, ast.Name):
            return
        tainted, blessed = self._expr_flags(value)
        if blessed:
            self.blessed.add(target.id)
            self.tainted.discard(target.id)
        elif tainted:
            self.tainted.add(target.id)
            self.blessed.discard(target.id)
        else:
            self.tainted.discard(target.id)
            self.blessed.discard(target.id)

    def _stmt(self, stmt: ast.AST):
        if isinstance(stmt, ast.Assign):
            self._visit_expr(stmt.value)
            for t in stmt.targets:
                if isinstance(t, ast.Tuple):
                    for el in t.elts:
                        self._assign_target(el, stmt.value)
                else:
                    self._assign_target(t, stmt.value)
            return
        if isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            if stmt.value is not None:
                self._visit_expr(stmt.value)
                self._assign_target(stmt.target, stmt.value)
            return
        if isinstance(stmt, ast.For):
            self._visit_expr(stmt.iter)
            self._assign_target(stmt.target, stmt.iter)
            for s in stmt.body + stmt.orelse:
                self._stmt(s)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: shared namespace (closures read the enclosing
            # function's bucket locals)
            for s in stmt.body:
                self._stmt(s)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._visit_expr(child)
            else:
                # stmt or non-stmt container (ExceptHandler,
                # match_case): recurse either way so fallback paths in
                # except blocks stay under shape discipline
                self._stmt(child)

    def _visit_expr(self, expr: ast.AST):
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node.func)
            if name in JNP_CONSTRUCTORS and \
                    isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == "jnp":
                if node.args and self._is_raw(node.args[0]):
                    self._flag(node, f"jnp.{name} shape derives from a "
                               "raw len(batch); route it through a "
                               "bucket helper (bucket_size, "
                               "_comb_k_pad, chunk constants)")
            elif name == "pad" and len(node.args) >= 2:
                if self._is_raw(node.args[1]):
                    self._flag(node, "pad width derives from a raw "
                               "len(batch); pad to a registered bucket "
                               "(bucket_size/_comb_k_pad/chunk "
                               "constants) instead")
            elif name in self.entries:
                for arg in list(node.args) + \
                        [kw.value for kw in node.keywords]:
                    if self._is_raw(arg):
                        self._flag(node, f"jit entry {name}() receives "
                                   "an argument sized by a raw "
                                   "len(batch) — this compiles one XLA "
                                   "shape class per batch size")
                        break


def _check_tm102(path: str, qual: str, fn: ast.AST,
                 findings: List[Finding]):
    """jit factories invoked inside a function body must cache their
    result."""
    # names that escape into a cache: attribute/subscript stores,
    # setdefault args, returns, or use inside a nested def (factory)
    escaped: Set[str] = set()
    nested_names: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name):
                    nested_names.add(sub.id)
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)) \
                        and isinstance(node.value, ast.Name):
                    escaped.add(node.value.id)
        elif isinstance(node, ast.Return) and \
                isinstance(node.value, ast.Name):
            escaped.add(node.value.id)
        elif isinstance(node, ast.Call) and \
                _call_name(node.func) == "setdefault":
            for a in node.args:
                if isinstance(a, ast.Name):
                    escaped.add(a.id)

    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            continue
        if not (isinstance(node, ast.Call) and _is_jit_factory(node)):
            continue
        parent_assign = None
        for st in ast.walk(fn):
            if isinstance(st, ast.Assign) and st.value is node:
                parent_assign = st
                break
        ok = False
        if parent_assign is not None:
            t = parent_assign.targets[0]
            if isinstance(t, (ast.Attribute, ast.Subscript)):
                ok = True  # stored straight into a cache slot
            elif isinstance(t, ast.Name) and (
                    t.id in escaped or t.id in nested_names):
                ok = True  # cached later / closed over by a factory
        else:
            # bare `return jax.jit(...)` or `cache[k] = jax.jit(...)`
            for st in ast.walk(fn):
                if isinstance(st, ast.Return) and st.value is node:
                    ok = True
                if isinstance(st, ast.Assign) and st.value is node and \
                        isinstance(st.targets[0],
                                   (ast.Attribute, ast.Subscript)):
                    ok = True
        if not ok:
            findings.append(Finding(
                "TM102", path, node.lineno, qual,
                "jax.jit/shard_map built inside a function without "
                "caching the result — this re-traces (and may "
                "recompile) on every call; hoist to module level or "
                "store in a keyed cache"))


def check(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    for f in corpus.in_scope(*SCOPE):
        if f.tree is None:
            continue
        constants = module_constants(f.tree)
        entries = module_jit_entries(f.tree) | CROSS_MODULE_ENTRIES
        # top-level functions and class methods only: nested defs are
        # walked WITHIN their parent (shared bucket-local namespace),
        # never re-checked standalone with the taint context lost
        tops = [(n.name, n) for n in f.tree.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for cls in f.tree.body:
            if isinstance(cls, ast.ClassDef):
                tops += [(f"{cls.name}.{n.name}", n) for n in cls.body
                         if isinstance(n, (ast.FunctionDef,
                                           ast.AsyncFunctionDef))]
        for qual, node in tops:
            if node.name in BUCKET_HELPERS:
                continue
            _FnShapeCheck(f.path, qual, constants, entries,
                          findings).run(node)
            _check_tm102(f.path, qual, node, findings)
    return findings
