"""tmlint — invariant-enforcing static analysis + runtime sanitizers
for the tendermint_tpu verify stack (docs/adr/adr-014-tmlint.md).

Static passes (pure AST, no jax):
  passes_shape    TM101/TM102  compile-shape discipline at kernel seams
  passes_locks    TM201-TM204  lock order, blocking calls, table parity
  passes_hygiene  TM301-TM308  threads, optional deps, f-strings,
                               except-pass, chaos/trace/metric
                               registries, KnobSpec envelopes

Runtime sanitizers (tmlint.runtime, imported only by tests):
  CompileSentinel  per-test XLA bucket/compile accounting
  LockSanitizer    lockset monitor against devtools/lockorder.py

CLI:  python -m tendermint_tpu.devtools.tmlint \
          --baseline devtools/lint_baseline.json
"""
from .core import (Finding, RULES, RULES_BY_ID, generate_docs,  # noqa: F401
                   load_baseline, load_corpus, main, run_lint)
