"""`python -m tendermint_tpu.devtools.tmlint` entry point."""
import sys

from .core import main

sys.exit(main())
