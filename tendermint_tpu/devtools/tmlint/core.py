"""tmlint core: corpus loading, the rule registry, baseline handling
and the CLI driver.

The static passes are pure-AST (ast + tokenize from the stdlib, no jax,
no import of the modules under analysis), so the whole suite runs in
well under a second over the tree and is safe as a tier-1 gate on a
machine with no accelerator stack.

Findings are keyed WITHOUT line numbers — (rule, path, enclosing
qualname, detail) — so a baseline survives unrelated edits to the same
file.  Policy (docs/adr/adr-014-tmlint.md): the baseline starts and
stays empty unless a finding is consciously accepted with a written
justification; real violations get fixed, not baselined.
"""
from __future__ import annotations

import argparse
import ast
import json
import os
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional


def repo_root() -> str:
    """The directory holding tendermint_tpu/ (three levels up)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))


@dataclass
class Finding:
    rule: str           # "TM101"
    path: str           # repo-relative, "/"-separated
    line: int
    qual: str           # enclosing "Class.func" / "<module>"
    msg: str

    def key(self) -> str:
        """Stable identity for baselining: no line number (edits above
        a finding must not churn the baseline)."""
        return f"{self.rule}|{self.path}|{self.qual}|{self.msg}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "qual": self.qual, "msg": self.msg, "key": self.key()}

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} [{self.qual}] " \
            f"{self.msg}"


@dataclass
class SourceFile:
    path: str           # repo-relative
    src: str
    tree: Optional[ast.AST]
    parse_error: Optional[str] = None


@dataclass
class Corpus:
    root: str
    files: Dict[str, SourceFile] = field(default_factory=dict)

    def in_scope(self, *prefixes: str) -> List[SourceFile]:
        return [f for p, f in sorted(self.files.items())
                if any(p.startswith(pre) for pre in prefixes)]


# directories under the repo root that tmlint walks.  tests/ is
# deliberately excluded (fixtures contain seeded violations); the
# devtools package itself IS linted — the linter must hold its own bar.
LINT_ROOTS = ("tendermint_tpu", "scripts")
LINT_FILES = ("bench.py", "__graft_entry__.py")


def collect_paths(root: str) -> List[str]:
    out: List[str] = []
    for top in LINT_ROOTS:
        for dirpath, dirnames, filenames in os.walk(
                os.path.join(root, top)):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, fn), root)
                    out.append(rel.replace(os.sep, "/"))
    for fn in LINT_FILES:
        if os.path.exists(os.path.join(root, fn)):
            out.append(fn)
    return sorted(out)


def load_corpus(root: Optional[str] = None,
                paths: Optional[List[str]] = None) -> Corpus:
    root = root or repo_root()
    corpus = Corpus(root=root)
    for rel in paths if paths is not None else collect_paths(root):
        full = os.path.join(root, rel)
        try:
            with open(full, "r", encoding="utf-8") as f:
                src = f.read()
        except OSError as e:
            corpus.files[rel] = SourceFile(rel, "", None, str(e))
            continue
        try:
            tree = ast.parse(src, filename=rel)
            err = None
        except SyntaxError as e:
            tree, err = None, f"{e.msg} (line {e.lineno})"
        corpus.files[rel] = SourceFile(rel, src, tree, err)
    return corpus


# ---------------------------------------------------------------------------
# rule registry — one row per rule; docs/lint.md is generated from this
# table (scripts/metricsgen.py-style: edit here, regenerate, a tier-1
# test fails when the doc is stale)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Rule:
    id: str
    name: str
    scope: str
    description: str


RULES = [
    Rule("TM100", "parse-error", "all linted files",
         "The file does not parse under the container's Python (3.10). "
         "Backslash-in-f-string-expression breakage lands here when the "
         "interpreter itself rejects the file."),
    Rule("TM101", "raw-shape-at-kernel-seam", "ops/, parallel/",
         "A jnp array construction, np/jnp.pad, or jitted-kernel call "
         "whose size derives from a raw `len(batch)` instead of the "
         "registered bucket helpers (bucket_size, _comb_k_pad, "
         "msm_bucket, chunk constants).  Every such site mints a fresh "
         "XLA shape class per batch size and silently burns the tier-1 "
         "compile budget."),
    Rule("TM102", "uncached-jit-in-function", "ops/, parallel/",
         "jax.jit / shard_map / pl.pallas_call invoked inside a "
         "function body without caching the result (module constant, "
         "attribute/subscript store, closure factory).  A per-call jit "
         "recompiles — or at best re-traces — on every invocation."),
    Rule("TM201", "lock-order-inversion", "crypto/, ops/, libs/, parallel/",
         "The static acquires-while-holding graph contains an edge that "
         "acquires a lower-ranked lock while holding a higher-ranked "
         "one (or a cycle), against devtools/lockorder.py."),
    Rule("TM202", "blocking-call-under-lock", "crypto/, ops/, libs/, parallel/",
         "A blocking call (queue get/put, future.result, thread join, "
         "sleep, wait on a different primitive, device kernel entry) "
         "made while holding a ranked lock.  Condition.wait on the "
         "condition itself is allowed (wait releases it)."),
    Rule("TM203", "undeclared-lock", "crypto/, ops/, libs/, parallel/",
         "A threading.Lock/RLock/Condition creation site in the core "
         "modules with no rank in devtools/lockorder.py.  Every core "
         "lock must take a position in the declared order."),
    Rule("TM204", "stale-lock-declaration", "devtools/lockorder.py",
         "A lockorder.py row whose creation site no longer exists — "
         "the table must not rot as locks are removed or renamed."),
    Rule("TM301", "non-daemon-thread", "all linted files",
         "threading.Thread created without daemon=True outside "
         "libs/service.BaseService and never joined in the creating "
         "function.  A stray non-daemon thread blocks interpreter "
         "shutdown behind whatever it is wedged on (the conftest "
         "thread-leak guard is the runtime twin of this rule)."),
    Rule("TM302", "unconditional-optional-import", "all linted files",
         "Top-level import of an optional dependency (cryptography, "
         "grpc) outside try/except ImportError.  The container bakes "
         "neither in; a hard import makes the whole module unusable "
         "instead of degrading the one feature that needs it."),
    Rule("TM303", "backslash-in-fstring-expression", "all linted files",
         "A backslash inside an f-string replacement field.  Python "
         "3.10 rejects the file at parse time (the seed-era breakage "
         "that blocked every metrics-importing module); this rule "
         "catches it from the tokens even where newer interpreters "
         "would accept it."),
    Rule("TM304", "silent-except-pass", "ops/, crypto/",
         "`except Exception:`/bare `except:` whose body is only `pass` "
         "with no justifying comment, in verify hot-path modules.  A "
         "swallowed device fault is how bitmaps rot silently."),
    Rule("TM305", "unregistered-fail-site", "all linted files",
         "fail.inject/corrupt_bitmap called with a literal site name "
         "that is not in libs/fail.py REGISTERED_SITES.  Unregistered "
         "sites dodge the chaos-coverage gate."),
    Rule("TM306", "unregistered-trace-span", "all linted files",
         "trace.span/trace.instant called with a literal name that is "
         "not in libs/trace.py KNOWN_SPANS.  The registry is what lets "
         "trace consumers (bench report, debug-trace CLI) rely on span "
         "names."),
    Rule("TM307", "unknown-metric-attr", "all linted files",
         "An attribute read on a metrics bundle (``*.metrics.X``, "
         "``self._metrics().X``) that no bundle class in "
         "libs/metrics.py registers.  Catches typo'd metric names that "
         "would otherwise AttributeError only on the failure path."),
    Rule("TM308", "undeclared-knob-envelope", "all linted files",
         "A KnobSpec(...) declaration (libs/control.py, ADR-023) whose "
         "safe_range is not a literal finite (lo, hi) tuple with "
         "lo <= hi, whose step is not a literal > 0, or whose signal "
         "does not name a metric registered by a bundle class in "
         "libs/metrics.py.  The adaptive control plane only moves "
         "knobs inside ranges a human declared and reviews, steering "
         "on published signals only."),
]

RULES_BY_ID = {r.id: r for r in RULES}


def run_lint(root: Optional[str] = None,
             corpus: Optional[Corpus] = None) -> List[Finding]:
    """Run every static pass; returns findings sorted by path/line."""
    from . import passes_hygiene, passes_locks, passes_shape

    corpus = corpus or load_corpus(root)
    findings: List[Finding] = []
    for f in corpus.files.values():
        if f.parse_error is not None:
            findings.append(Finding("TM100", f.path, 1, "<module>",
                                    f"does not parse: {f.parse_error}"))
    findings += passes_shape.check(corpus)
    findings += passes_locks.check(corpus)
    findings += passes_hygiene.check(corpus)
    findings.sort(key=lambda x: (x.path, x.line, x.rule))
    return findings


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def load_baseline(path: str) -> Dict[str, str]:
    """{finding key -> justification}; missing file = empty baseline."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return {}
    return {e["key"]: e.get("justification", "")
            for e in data.get("findings", [])}


def write_baseline(path: str, findings: List[Finding]):
    data = {
        "comment": ("tmlint baseline — accepted findings with written "
                    "justifications.  Policy: fix violations, don't "
                    "baseline them; this file should stay empty."),
        "findings": [{"key": f.as_dict()["key"],
                      "justification": "TODO: justify or fix"}
                     for f in findings],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


# ---------------------------------------------------------------------------
# docs generation (docs/lint.md; staleness-gated in tests/test_lint.py)
# ---------------------------------------------------------------------------

def generate_docs() -> str:
    lines = [
        "# tmlint rules",
        "",
        "Static-analysis rules and runtime sanitizers enforcing the "
        "verify-stack",
        "invariants (docs/adr/adr-014-tmlint.md).  GENERATED by "
        "`python -m",
        "tendermint_tpu.devtools.tmlint --docs` from the rule table in",
        "`tendermint_tpu/devtools/tmlint/core.py` — edit the table, "
        "then",
        "regenerate; `tests/test_lint.py` fails when this file is "
        "stale.",
        "",
        "Run: `python -m tendermint_tpu.devtools.tmlint --baseline "
        "devtools/lint_baseline.json`",
        "",
        "| Rule | Name | Scope | What it enforces |",
        "|---|---|---|---|",
    ]
    for r in RULES:
        desc = " ".join(r.description.split())
        lines.append(f"| `{r.id}` | {r.name} | {r.scope} | {desc} |")
    lines += [
        "",
        "## Runtime sanitizers",
        "",
        "| Sanitizer | Arming | What it enforces |",
        "|---|---|---|",
        "| compile sentinel | `compile_sentinel` fixture "
        "(tests/conftest.py) | No test may land a device-launch bucket "
        "whose padded lane count is outside the known bucket set "
        "(power-of-two >= MIN_BUCKET capped at MAX_CHUNK, or "
        "chunk-aligned), and watched jit entries must not grow their "
        "compile caches unexpectedly. |",
        "| lockset monitor | `TM_TPU_LOCKSAN=1` (all tests) or the "
        "`locksan` marker | Locks created in tendermint_tpu modules "
        "are wrapped; acquiring a lower-ranked lock while holding a "
        "higher-ranked one (per devtools/lockorder.py) fails the "
        "test. |",
        "",
    ]
    return "\n".join(lines)


def docs_path(root: Optional[str] = None) -> str:
    return os.path.join(root or repo_root(), "docs", "lint.md")


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tendermint_tpu.devtools.tmlint",
        description="invariant-enforcing static analysis for the "
                    "tendermint_tpu verify stack")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON (devtools/lint_baseline.json); "
                         "keyed findings listed there are accepted")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON (scripts/lint_report.py "
                         "consumes this)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current findings to --baseline and "
                         "exit 0 (bootstrap only; justify every entry)")
    ap.add_argument("--docs", action="store_true",
                    help="regenerate docs/lint.md from the rule table")
    ap.add_argument("--check-docs", action="store_true",
                    help="exit 1 when docs/lint.md is stale")
    ap.add_argument("--dump-locks", action="store_true",
                    help="print every lock creation site id (for "
                         "maintaining devtools/lockorder.py)")
    ap.add_argument("--root", default=None, help=argparse.SUPPRESS)
    ap.add_argument("paths", nargs="*",
                    help="restrict to these repo-relative files")
    args = ap.parse_args(argv)

    root = args.root or repo_root()
    if args.docs or args.check_docs:
        text = generate_docs()
        dp = docs_path(root)
        if args.check_docs:
            try:
                with open(dp, "r", encoding="utf-8") as f:
                    cur = f.read()
            except FileNotFoundError:
                cur = ""
            if cur != text:
                print("docs/lint.md is stale; run python -m "
                      "tendermint_tpu.devtools.tmlint --docs",
                      file=sys.stderr)
                return 1
            print("docs/lint.md is current")
            return 0
        with open(dp, "w", encoding="utf-8") as f:
            f.write(text)
        print(f"wrote {dp}")
        return 0

    corpus = load_corpus(root, paths=args.paths or None)
    if args.dump_locks:
        from . import passes_locks
        for site in passes_locks.lock_creation_sites(corpus):
            print(f"{site.lock_id}  ({site.kind}, line {site.line})")
        return 0

    findings = run_lint(root=root, corpus=corpus)

    if args.write_baseline:
        if not args.baseline:
            print("--write-baseline requires --baseline", file=sys.stderr)
            return 2
        write_baseline(os.path.join(root, args.baseline), findings)
        print(f"wrote {len(findings)} finding(s) to {args.baseline}")
        return 0

    baseline = load_baseline(os.path.join(root, args.baseline)) \
        if args.baseline else {}
    new = [f for f in findings if f.key() not in baseline]
    stale = set(baseline) - {f.key() for f in findings}

    if args.as_json:
        print(json.dumps({
            "findings": [f.as_dict() for f in findings],
            "new": [f.as_dict() for f in new],
            "baselined": len(findings) - len(new),
            "stale_baseline_keys": sorted(stale),
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        for k in sorted(stale):
            print(f"stale baseline entry (finding no longer exists): {k}",
                  file=sys.stderr)
        n_files = len(corpus.files)
        print(f"tmlint: {n_files} files, {len(findings)} finding(s), "
              f"{len(findings) - len(new)} baselined, {len(new)} new")
    return 1 if new else 0
