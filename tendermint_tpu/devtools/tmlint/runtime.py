"""tmlint runtime sanitizers: the dynamic twins of the static passes.

CompileSentinel — compile-shape discipline at runtime.  The static
TM101 pass can only prove that sizes flow through bucket helpers; the
sentinel proves what actually happened: it snapshots the launch-bucket
set (ops/ed25519._seen_buckets, fed by every _record_launch) and the
jit-cache sizes of the registered kernel entries before a test, and
fails the test if a launch landed in a padded lane count outside the
known bucket shapes or a watched entry compiled more than expected.
Used as the opt-in `compile_sentinel` fixture (tests/conftest.py).

LockSanitizer — the lockset monitor.  Under TM_TPU_LOCKSAN=1 (or the
`locksan` pytest marker) threading.Lock/RLock/Condition are patched so
locks CREATED by tendermint_tpu modules are wrapped: each acquisition
records the per-thread held set and an acquisition that takes a
lower-ranked lock while holding a higher-ranked one (per
devtools/lockorder.py) is recorded as a violation the fixture fails
the test with.  Locks created by foreign code (jax, stdlib queues) get
the real primitive — zero overhead outside our modules.

This module may import jax-adjacent modules lazily (it reads
sys.modules, never forces an import); the static passes must NOT
import it.
"""
from __future__ import annotations

import linecache
import os
import re
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

from tendermint_tpu.devtools import lockorder


# ---------------------------------------------------------------------------
# compile sentinel
# ---------------------------------------------------------------------------

# jitted kernel entries watched for cache growth, per module.  Only
# modules ALREADY imported are inspected — the sentinel never forces a
# kernel module (and its compile cost) into a test that didn't use it.
ENTRY_NAMES = [
    ("tendermint_tpu.ops.ed25519", "verify_kernel"),
    ("tendermint_tpu.ops.ed25519", "comb_kernel"),
    ("tendermint_tpu.ops.ed25519", "comb_build_kernel"),
    ("tendermint_tpu.ops.msm", "_msm_core"),
    ("tendermint_tpu.ops.sr25519", "_verify_core"),
    ("tendermint_tpu.ops.secp", "_verify_core"),
]


class CompileSentinel:
    """Per-test XLA bucket/compile accounting.

    start() snapshots; check() raises AssertionError when a NEW launch
    bucket's padded lane count is outside the known bucket set, and
    returns a report dict ({"new_buckets", "compiles"}) either way.
    `max_new_compiles` (default None = unlimited) additionally bounds
    total watched-entry cache growth — a test that reuses the shared
    nb=64 bucket passes with max_new_compiles=0.
    """

    def __init__(self, extra_entries=None,
                 max_new_compiles: Optional[int] = None):
        self.extra_entries = list(extra_entries or [])
        self.max_new_compiles = max_new_compiles
        self._buckets0: Set[tuple] = set()
        self._caches0: Dict[str, int] = {}

    # -- plumbing ------------------------------------------------------

    @staticmethod
    def _edops():
        return sys.modules.get("tendermint_tpu.ops.ed25519")

    def _entries(self):
        for mod, attr in ENTRY_NAMES:
            m = sys.modules.get(mod)
            fn = getattr(m, attr, None) if m is not None else None
            if fn is not None and hasattr(fn, "_cache_size"):
                yield f"{mod}.{attr}", fn
        for label, fn in self.extra_entries:
            if hasattr(fn, "_cache_size"):
                yield label, fn

    @staticmethod
    def _seen_buckets() -> Set[tuple]:
        ed = CompileSentinel._edops()
        if ed is None:
            return set()
        with ed._launch_lock:
            return set(ed._seen_buckets)

    @staticmethod
    def bucket_allowed(nb: int, shards: int = 1) -> bool:
        """Is `nb` a known padded-lane shape?  Power-of-two lane
        buckets (ops/ed25519.bucket_size) up to MAX_CHUNK, SPLIT_CHUNK
        multiples (the split path), MAX_CHUNK multiples (pipelined
        sub-batching), and on the mesh the per-shard rounding of any of
        those."""
        ed = CompileSentinel._edops()
        if ed is None:  # no kernel module imported -> nothing launched
            return True
        if nb <= 0:
            return False
        if shards > 1:
            if nb % shards:
                return False
            # mesh paths round the bucket UP to a shard multiple; the
            # underlying per-shard shape still obeys the lane buckets
            per = nb // shards
            return CompileSentinel.bucket_allowed(per) or \
                CompileSentinel.bucket_allowed(nb)
        if nb == ed.bucket_size(nb) and nb <= ed.MAX_CHUNK:
            return True
        if nb % ed.SPLIT_CHUNK == 0 or nb % ed.MAX_CHUNK == 0:
            return True
        return False

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "CompileSentinel":
        self._buckets0 = self._seen_buckets()
        self._caches0 = {label: fn._cache_size()
                         for label, fn in self._entries()}
        return self

    def check(self) -> dict:
        new = self._seen_buckets() - self._buckets0
        bad = []
        for rec in sorted(new):
            path, nb, shards = rec[0], rec[1], rec[2] if len(rec) > 2 \
                else 1
            if not self.bucket_allowed(nb, shards):
                bad.append(rec)
        compiles = {}
        for label, fn in self._entries():
            grew = fn._cache_size() - self._caches0.get(label, 0)
            if grew > 0:
                compiles[label] = grew
        report = {"new_buckets": sorted(new), "compiles": compiles}
        assert not bad, (
            f"compile sentinel: launch bucket(s) outside the known "
            f"shape set: {bad} — pad through ops/ed25519.bucket_size / "
            f"chunk constants (report: {report})")
        if self.max_new_compiles is not None:
            total = sum(compiles.values())
            assert total <= self.max_new_compiles, (
                f"compile sentinel: {total} new kernel compile(s) "
                f"(> {self.max_new_compiles} allowed): {compiles} — "
                f"reuse the shared lane buckets (report: {report})")
        return report


# ---------------------------------------------------------------------------
# lockset monitor
# ---------------------------------------------------------------------------

_ASSIGN_RE = re.compile(r"^\s*(?:self\.)?(\w+)\s*[:=]")


def _creation_lock_id(frame) -> Optional[str]:
    """Derive the lockorder id for a lock created at `frame`:
    path from the executing code object, attr name from the source
    line, class from self's MRO (handles BaseService._mtx constructed
    while self is a subclass)."""
    fname = frame.f_code.co_filename
    root = _repo_root()
    try:
        rel = os.path.relpath(fname, root).replace(os.sep, "/")
    except ValueError:
        return None
    if rel.startswith(".."):
        return None
    line = linecache.getline(fname, frame.f_lineno)
    m = _ASSIGN_RE.match(line)
    if not m:
        return None
    attr = m.group(1)
    slf = frame.f_locals.get("self")
    if slf is not None:
        for klass in type(slf).__mro__:
            cand = f"{rel}:{klass.__name__}.{attr}"
            if cand in lockorder.LOCK_ORDER:
                return cand
        return f"{rel}:{type(slf).__name__}.{attr}"
    return f"{rel}:{attr}"


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))


class _SanLock:
    """Wraps a real Lock/RLock; reports acquisitions to the sanitizer.
    Implements the Condition lock protocol (_release_save /
    _acquire_restore / _is_owned) so a wrapped RLock can back a
    threading.Condition."""

    def __init__(self, inner, lock_id: Optional[str], san:
                 "LockSanitizer"):
        self._inner = inner
        self.lock_id = lock_id
        self.rank = lockorder.rank(lock_id) if lock_id else None
        self._san = san

    # -- core protocol -------------------------------------------------

    def acquire(self, *a, **kw):
        got = self._inner.acquire(*a, **kw)
        if got:
            self._san._on_acquire(self)
        return got

    def release(self):
        self._san._on_release(self)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    # -- Condition lock protocol ----------------------------------------

    def _release_save(self):
        self._san._on_release(self)
        if hasattr(self._inner, "_release_save"):
            return self._inner._release_save()
        self._inner.release()
        return None

    def _acquire_restore(self, state):
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        self._san._on_acquire(self)

    def _is_owned(self):
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        # plain-Lock fallback (threading.Condition's own heuristic)
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __repr__(self):
        return f"<SanLock {self.lock_id or '?'} rank={self.rank}>"


class LockSanitizer:
    """Patch threading lock factories; record per-thread lock order.

    install()/uninstall() bracket a test.  Only locks whose creation
    frame executes a file under this repo are wrapped — foreign code
    gets the real primitives.  Violations (lower rank acquired under
    higher rank) collect in .violations; the observed acquired-while-
    holding edge set in .edges.
    """

    def __init__(self, include_paths: Tuple[str, ...] =
                 ("tendermint_tpu/",),
                 rank_overrides: Optional[Dict[str, int]] = None):
        self.include_paths = include_paths
        self.rank_overrides = dict(rank_overrides or {})
        self.violations: List[str] = []
        self.edges: Set[Tuple[str, str]] = set()
        self._tls = threading.local()
        self._mtx = threading.Lock()  # guards violations/edges
        self._orig = None
        self._enabled = False

    # -- wrapping ------------------------------------------------------

    def _should_wrap(self, frame) -> bool:
        fname = frame.f_code.co_filename
        root = _repo_root()
        try:
            rel = os.path.relpath(fname, root).replace(os.sep, "/")
        except ValueError:
            return False
        if rel.startswith("tendermint_tpu/devtools/"):
            return False  # never instrument the instrumentation
        return any(rel.startswith(p) for p in self.include_paths)

    def _wrap(self, inner, frame):
        lock_id = _creation_lock_id(frame)
        w = _SanLock(inner, lock_id, self)
        if lock_id in self.rank_overrides:
            w.rank = self.rank_overrides[lock_id]
        return w

    def _caller_frame(self):
        f = sys._getframe(2)
        # skip our own factory frames (Condition() -> RLock())
        while f is not None and f.f_code.co_filename == __file__:
            f = f.f_back
        return f

    def install(self):
        assert self._orig is None, "LockSanitizer already installed"
        self._orig = (threading.Lock, threading.RLock,
                      threading.Condition)
        orig_lock, orig_rlock, orig_cond = self._orig
        san = self

        def make(factory):
            def _factory():
                inner = factory()
                f = san._caller_frame()
                if f is not None and san._should_wrap(f):
                    return san._wrap(inner, f)
                return inner
            return _factory

        def cond_factory(lock=None):
            if lock is None:
                inner = orig_rlock()
                f = san._caller_frame()
                if f is not None and san._should_wrap(f):
                    lock = san._wrap(inner, f)
                else:
                    lock = inner
            return orig_cond(lock)

        threading.Lock = make(orig_lock)
        threading.RLock = make(orig_rlock)
        threading.Condition = cond_factory
        self._enabled = True
        return self

    def uninstall(self):
        if self._orig is not None:
            (threading.Lock, threading.RLock,
             threading.Condition) = self._orig
            self._orig = None
        self._enabled = False  # surviving wrapped locks go quiet

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    # -- tracking ------------------------------------------------------

    def _stack(self) -> List[_SanLock]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _on_acquire(self, lock: _SanLock):
        if not self._enabled:
            return
        st = self._stack()
        reentrant = any(h is lock for h in st)
        if not reentrant and lock.rank is not None:
            for held in st:
                if held.rank is None or held is lock:
                    continue
                if held.rank >= lock.rank:
                    with self._mtx:
                        self.violations.append(
                            f"acquired {lock.lock_id} (rank "
                            f"{lock.rank}) while holding "
                            f"{held.lock_id} (rank {held.rank}) on "
                            f"thread {threading.current_thread().name}")
        if not reentrant:
            with self._mtx:
                for held in st:
                    if held.lock_id and lock.lock_id and \
                            held is not lock:
                        self.edges.add((held.lock_id, lock.lock_id))
        st.append(lock)

    def _on_release(self, lock: _SanLock):
        if not self._enabled:
            return
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i] is lock:
                del st[i]
                return
