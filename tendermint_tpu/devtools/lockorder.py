"""The declared lock-order table for the verify stack.

Five PRs of concurrency (degradation runtime, VerifyScheduler,
DeviceLRU, comb table index, flight recorder) left ~25 locks in the
core modules.  This table makes the acquisition order an explicit,
machine-checked contract: tmlint's static pass builds the
acquires-while-holding graph from the AST and the lockset monitor
(TM_TPU_LOCKSAN=1) records the real acquisition order at runtime —
both fail on an edge that acquires a LOWER-ranked lock while holding a
HIGHER-ranked one.

Rules of the table:

  * A lock id is "<path>:<Class>.<attr>" for instance locks and
    "<path>:<name>" for module-level locks, with <path> relative to the
    repo root.  tmlint derives the same ids from creation sites
    (`self._x = threading.Lock()` / `_x = threading.Lock()`), so adding
    a lock without a row here fails the TM203 rule in core modules, and
    a row whose creation site disappeared fails TM204 (no table rot in
    either direction).
  * Lower rank = acquired FIRST.  While holding rank r, only locks of
    rank > r may be acquired.  Two locks that are never nested may sit
    anywhere relative to each other; give every new lock its own value
    so a future nesting has a defined verdict.
  * Utility locks everything calls into (metrics, trace ring) rank
    HIGHEST: they must always be acquired last and hold nothing.
  * Condition variables rank like locks; waiting on the condition you
    hold is allowed (wait releases it), waiting on anything else under
    a lock is a blocking-call finding (TM202).

Intended nestings this table encodes:

  degrade._runtime_lock (5)  -> metrics Registry/_Metric (80/84):
      runtime() constructs CryptoMetrics under the install lock.
  VerifyScheduler._cond (20) -> _stats_lock (28):
      submit/evict update pipeline stats while holding the queue cond.
  ed25519._table_key_lock (44) -> DeviceLRU._lock (48):
      eviction repointing peeks surviving cache entries while holding
      the key index.
"""
from __future__ import annotations

# rank by lock id; see module docstring for the id grammar
LOCK_ORDER = {
    # -- light client trusted-state advance (light/, ADR-026): the
    # client lock serializes the store read -> verify -> save path and
    # is held across the verifier (scheduler _cond 20) and the trusted
    # store (kvdb 65-69), so it must rank below both
    "tendermint_tpu/light/client.py:Client._lock": 8,

    # -- light serving plane (light/service.py, ADR-026): ingress
    # discipline — _cond guards the admission queue + coalesce groups
    # ONLY (bookkeeping); the verifier, scheduler (20), stores and
    # metrics are all called with it released.  _rl_lock (per-client
    # token buckets), _cur_lock (follow cursors; block-store reads run
    # with it released) and _stats_lock are leaves taken alone.
    "tendermint_tpu/light/service.py:LightServe._cond": 21,
    "tendermint_tpu/light/service.py:LightServe._rl_lock": 23,
    "tendermint_tpu/light/service.py:LightServe._cur_lock": 25,
    "tendermint_tpu/light/service.py:LightServe._stats_lock": 37,

    # -- process-global installers (held while constructing the world) --
    "tendermint_tpu/crypto/degrade.py:_runtime_lock": 5,
    "tendermint_tpu/crypto/scheduler.py:_global_lock": 10,
    "tendermint_tpu/crypto/lanepool.py:_install_lock": 12,
    "tendermint_tpu/state/pipeline.py:_install_lock": 13,

    # -- block application pipeline (ADR-017): _busy serializes whole
    # windows and is taken before everything the window touches (the
    # _cond bookkeeping, scheduler 20, kvdb 67-69); _cond itself is
    # held only for bookkeeping — stores, scheduler and metrics are
    # all called outside it
    "tendermint_tpu/state/pipeline.py:BlockPipeline._busy": 14,
    "tendermint_tpu/state/pipeline.py:BlockPipeline._cond": 16,

    # -- mempool ingress gate (ADR-018): _cond guards the admission +
    # recheck queues only (bookkeeping); the mempool, scheduler (20),
    # app and metrics are all called with it released.  _rl_lock
    # (token buckets) and _stats_lock are leaves taken alone.
    "tendermint_tpu/mempool/ingress.py:IngressGate._cond": 17,
    "tendermint_tpu/mempool/ingress.py:IngressGate._rl_lock": 18,
    "tendermint_tpu/mempool/ingress.py:IngressGate._stats_lock": 19,

    # -- network harness (networks/, ADR-019): the harness lock (11)
    # wraps scenario bookkeeping and may drive vnet fault APIs; the
    # vnet engine condition (15) guards heap/policies/pending and is
    # released before inbox pushes and reactor dispatch; each endpoint
    # inbox condition (22) is taken alone (a dispatcher holding 22 must
    # never acquire 15 — it reads the running flag lock-free instead)
    "tendermint_tpu/networks/harness.py:NetHarness._lock": 11,
    "tendermint_tpu/networks/vnet.py:VirtualNetwork._cond": 15,
    "tendermint_tpu/networks/vnet.py:_Endpoint._cond": 22,

    # -- VerifyScheduler pipeline --
    "tendermint_tpu/crypto/scheduler.py:VerifyScheduler._cond": 20,
    "tendermint_tpu/crypto/scheduler.py:VerifyScheduler._res_lock": 24,
    "tendermint_tpu/crypto/scheduler.py:VerifyScheduler._stats_lock": 28,

    # -- statesync fast-join (statesync/, ADR-022): the metrics-
    # bundle install lock (27) constructs StateSyncMetrics under it
    # (Registry 80); the syncer discovery lock (31), the per-peer
    # book (33; its ban callback runs with the lock RELEASED) and the
    # reactor's response-routing / serve-queue conditions (34/35) are
    # bookkeeping leaves — app calls, peer sends and metrics all
    # happen outside them.  The restore ledger (63) buffers chunk
    # writes through GroupCommitDB (67) while held; group COMMITS run
    # with it released (commit_mutex 65)
    "tendermint_tpu/statesync/syncer.py:_metrics_lock": 27,
    "tendermint_tpu/statesync/syncer.py:_cfg_lock": 29,
    "tendermint_tpu/statesync/syncer.py:Syncer._lock": 31,
    "tendermint_tpu/statesync/syncer.py:_PeerBook._lock": 33,
    "tendermint_tpu/statesync/reactor.py:StateSyncReactor._chunks_cv": 34,
    "tendermint_tpu/statesync/reactor.py:StateSyncReactor._serve_cv": 35,
    # _commit_lock is held across a whole take_group+commit_group unit
    # (nests GroupCommitDB._commit_mutex 65 / _lock 67) so groups land
    # strictly in take order under concurrent fetcher threads; the
    # buffer lock (63) is never held while committing
    "tendermint_tpu/statesync/ledger.py:RestoreLedger._commit_lock": 61,
    "tendermint_tpu/statesync/ledger.py:RestoreLedger._lock": 63,

    # -- batch verifier / caches --
    "tendermint_tpu/crypto/lanepool.py:HostLanePool._lock": 30,
    "tendermint_tpu/crypto/batch.py:SigCache._lock": 32,

    # -- degradation runtime --
    "tendermint_tpu/crypto/degrade.py:CircuitBreaker._lock": 36,
    "tendermint_tpu/crypto/degrade.py:DeviceLaneRuntime._pool_lock": 38,
    "tendermint_tpu/crypto/degrade.py:DeviceLaneRuntime._backend_lock": 40,

    # -- device-resident caches and launch bookkeeping (ops/) --
    "tendermint_tpu/ops/ed25519.py:_table_key_lock": 44,
    "tendermint_tpu/ops/ed25519.py:DeviceLRU._lock": 48,
    "tendermint_tpu/ops/ed25519.py:_base_comb_lock": 52,
    "tendermint_tpu/ops/ed25519.py:_launch_lock": 54,
    "tendermint_tpu/ops/msm.py:_route_lock": 56,
    "tendermint_tpu/parallel/sharding.py:_PLANE_LOCK": 57,
    "tendermint_tpu/parallel/sharding.py:_DataPlane._lock": 58,

    # -- libs/ leaves --
    "tendermint_tpu/libs/service.py:BaseService._mtx": 60,
    "tendermint_tpu/libs/fail.py:_lock": 62,
    "tendermint_tpu/libs/log.py:_lock": 64,
    "tendermint_tpu/libs/native.py:_lock": 66,
    # GroupCommitDB: _commit_mutex is held across a whole group commit
    # (membership check -> inner write_batch -> removal) and so nests
    # the buffer lock and the wrapped DB's lock; the buffer lock (_lock)
    # itself is never held while calling the inner DB
    "tendermint_tpu/libs/kvdb.py:GroupCommitDB._commit_mutex": 65,
    "tendermint_tpu/libs/kvdb.py:GroupCommitDB._lock": 67,
    "tendermint_tpu/libs/kvdb.py:MemDB._lock": 68,
    "tendermint_tpu/libs/kvdb.py:SQLiteDB._lock": 69,
    "tendermint_tpu/libs/autofile.py:Group._lock": 70,
    "tendermint_tpu/libs/flowrate.py:Monitor._lock": 72,
    # gossip observatory table (p2p/netobs.py, ADR-025): a leaf —
    # every recorder takes it alone (fail.inject runs BEFORE
    # acquisition) and may be called under the vnet engine condition
    # (15) or a consensus seam, so it must outrank both;
    # publish_pending() releases it before touching slo (76) or the
    # metrics locks (80/84)
    "tendermint_tpu/p2p/netobs.py:NetObs._lock": 73,
    # consensus observatory ring (consensus/observatory.py, ADR-020):
    # a leaf — stamp()/receipt() take it alone (fail.inject runs
    # BEFORE acquisition), and publish_pending() releases it before
    # touching slo (76) or the metrics locks (80/84)
    "tendermint_tpu/consensus/observatory.py:Observatory._lock": 74,
    # SLO estimator ring (libs/slo.py, ADR-016): a leaf like the
    # metrics locks — observe() takes it alone, and the read side
    # (stream_report) sorts a snapshot OUTSIDE it
    "tendermint_tpu/libs/slo.py:SloEstimator._lock": 76,
    # device observatory ring (crypto/devobs.py, ADR-021): a leaf —
    # record()/ledger_* take it alone (fail.inject runs BEFORE
    # acquisition), and publish_pending() releases it before touching
    # slo (76... metrics 80/84 — publication runs with the ring lock
    # dropped, so the lower slo rank is never acquired under it)
    "tendermint_tpu/crypto/devobs.py:DevObs._lock": 78,
    # adaptive control plane (libs/control.py, ADR-023): the install
    # lock ranks with the other process-global install locks (it holds
    # is_running()'s _mtx 60 check under it); Controller._lock is a
    # LEAF — registry/ring/bookkeeping only, every knob setter (which
    # acquires pipeline 14/16, ingress 18, scheduler 20...) and every
    # metrics/trace publication runs with it RELEASED
    "tendermint_tpu/libs/control.py:_global_lock": 26,
    "tendermint_tpu/libs/control.py:Controller._lock": 79,

    # -- observability: always acquired last, hold nothing --
    "tendermint_tpu/libs/metrics.py:Registry._lock": 80,
    "tendermint_tpu/libs/metrics.py:_Metric._lock": 84,
    "tendermint_tpu/libs/trace.py:Tracer._lock": 90,
}


def rank(lock_id: str):
    """Declared rank of a lock id, or None when unranked."""
    return LOCK_ORDER.get(lock_id)
