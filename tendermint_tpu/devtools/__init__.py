"""Developer tooling that ships with the package but is never imported
by the node runtime: tmlint (invariant-enforcing static analysis +
runtime sanitizers, docs/adr/adr-014-tmlint.md) and the declared
lock-order table it checks against (lockorder.py).

Nothing here may import jax: the static passes run as a tier-1 gate
before any kernel module is touched, and `python -m
tendermint_tpu.devtools.tmlint` must work on a machine with no
accelerator stack at all.
"""
