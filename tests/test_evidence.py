"""Evidence: types round-trip, duplicate-vote + light-attack verification,
pool lifecycle (reference types/evidence_test.go, evidence/verify_test.go,
pool_test.go)."""
from __future__ import annotations

import copy

import pytest

from helpers import build_chain, make_genesis
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.blocksync.replay import block_id_of, replay_window
from tendermint_tpu.evidence import EvidencePool
from tendermint_tpu.evidence.verify import (verify_duplicate_vote,
                                            verify_light_client_attack)
from tendermint_tpu.libs.kvdb import MemDB
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.state import state_from_genesis
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.store.block_store import BlockStore
from tendermint_tpu.types.basic import (BlockID, PartSetHeader,
                                        SignedMsgType, Timestamp)
from tendermint_tpu.types.evidence import (DuplicateVoteEvidence,
                                           EvidenceError,
                                           LightClientAttackEvidence,
                                           evidence_from_proto,
                                           evidence_list_hash)
from tendermint_tpu.types.light_block import LightBlock, SignedHeader
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.types.vote import Vote

CHAIN = "test-chain-tpu"


def _dup_votes(priv, height=5, round_=0):
    def vote(h):
        bid = BlockID(hash=h, part_set_header=PartSetHeader(1, h))
        v = Vote(type=SignedMsgType.PRECOMMIT, height=height, round=round_,
                 block_id=bid, timestamp=Timestamp(1700000005, 0),
                 validator_address=priv.pub_key().address(),
                 validator_index=0)
        v.signature = priv.sign(v.sign_bytes(CHAIN))
        return v
    return vote(b"\xAA" * 32), vote(b"\xBB" * 32)


def test_duplicate_vote_evidence_roundtrip_and_verify():
    gdoc, privs = make_genesis(4)
    vals = ValidatorSet.__new__(ValidatorSet)
    state = state_from_genesis(gdoc)
    vals = state.validators
    _, val = vals.get_by_address(privs[0].pub_key().address())
    v1, v2 = _dup_votes(privs[0])
    ev = DuplicateVoteEvidence.from_votes(v1, v2, Timestamp(1700000005, 0),
                                          vals)
    ev.validate_basic()
    # wire round-trip preserves hash
    ev2 = evidence_from_proto(ev.proto())
    assert ev2.hash() == ev.hash()
    verify_duplicate_vote(ev, CHAIN, vals)
    # same block ID is not duplicate evidence
    ev_same = copy.deepcopy(ev)
    ev_same.vote_b = ev.vote_a
    with pytest.raises(EvidenceError):
        verify_duplicate_vote(ev_same, CHAIN, vals)
    # tampered power rejected
    ev_pow = copy.deepcopy(ev)
    ev_pow.total_voting_power += 1
    with pytest.raises(EvidenceError):
        verify_duplicate_vote(ev_pow, CHAIN, vals)
    # bad signature rejected
    ev_sig = copy.deepcopy(ev)
    ev_sig.vote_a.signature = bytes(64)
    with pytest.raises(EvidenceError):
        verify_duplicate_vote(ev_sig, CHAIN, vals)


def test_evidence_list_hash_stable():
    gdoc, privs = make_genesis(4)
    state = state_from_genesis(gdoc)
    v1, v2 = _dup_votes(privs[0])
    ev = DuplicateVoteEvidence.from_votes(v1, v2, Timestamp(1700000005, 0),
                                          state.validators)
    h1 = evidence_list_hash([ev])
    h2 = evidence_list_hash([evidence_from_proto(ev.proto())])
    assert h1 == h2 and len(h1) == 32


def _synced_node(gdoc, blocks, commits):
    state_store = StateStore(MemDB())
    block_store = BlockStore(MemDB())
    ex = BlockExecutor(state_store, KVStoreApplication())
    state = state_from_genesis(gdoc)
    state_store.save(state)
    applied = 0
    while applied < len(blocks):
        state, n = replay_window(ex, block_store, state, blocks[applied:],
                                 commits[applied:], max_window=16)
        applied += n
    return ex, state_store, block_store, state


def test_pool_accepts_and_gossips_duplicate_vote():
    gdoc, privs = make_genesis(4)
    blocks, commits, _ = build_chain(gdoc, privs, 8)
    ex, state_store, block_store, state = _synced_node(gdoc, blocks, commits)
    pool = EvidencePool(MemDB(), state_store, block_store)
    # evidence at height 5, timestamp = that block's header time
    bt = block_store.load_block_meta(5).header.time
    v1, v2 = _dup_votes(privs[1])
    vals = state_store.load_validators(5)
    ev = DuplicateVoteEvidence.from_votes(v1, v2, bt, vals)
    pool.add_evidence(ev)
    assert pool.size() == 1
    pending = pool.pending_evidence()
    assert pending[0].hash() == ev.hash()
    # committing it removes it from pending
    pool.update(state, [ev])
    assert pool.size() == 0
    # re-adding committed evidence is a no-op
    pool.add_evidence(ev)
    assert pool.size() == 0


def test_pool_consensus_buffer_produces_evidence():
    gdoc, privs = make_genesis(4)
    blocks, commits, _ = build_chain(gdoc, privs, 8)
    ex, state_store, block_store, state = _synced_node(gdoc, blocks, commits)
    pool = EvidencePool(MemDB(), state_store, block_store)
    v1, v2 = _dup_votes(privs[2], height=6)
    # consensus reports the raw conflicting votes; next update forms evidence
    pool.report_conflicting_votes(v1, v2)
    # patch votes' timestamp to match block 6 time (vote time is sign time;
    # evidence timestamp comes from the block, which from_votes handles)
    pool.update(state, [])
    assert pool.size() == 1


def test_pool_rejects_expired_and_unknown_height():
    gdoc, privs = make_genesis(4)
    blocks, commits, _ = build_chain(gdoc, privs, 8)
    ex, state_store, block_store, state = _synced_node(gdoc, blocks, commits)
    pool = EvidencePool(MemDB(), state_store, block_store)
    v1, v2 = _dup_votes(privs[0], height=100)
    ev = DuplicateVoteEvidence.from_votes(v1, v2, Timestamp(1700000100, 0),
                                          state.validators)
    with pytest.raises(EvidenceError):
        pool.add_evidence(ev)


def test_light_client_attack_evidence_verifies():
    gdoc, privs = make_genesis(4)
    blocks, commits, states = build_chain(gdoc, privs, 10)
    ex, state_store, block_store, state = _synced_node(gdoc, blocks, commits)
    # forge a conflicting block at height 7: equivocation-style fork — same
    # derived fields, different data hash, re-signed by the same validators
    from tendermint_tpu.types.canonical import canonical_vote_bytes
    from tendermint_tpu.types.commit import Commit, CommitSig
    from tendermint_tpu.types.basic import BlockIDFlag
    evil = copy.deepcopy(blocks[6])
    evil.data.txs = [b"forged-tx"]
    evil.header.data_hash = evil.data.hash()
    bid, _ = block_id_of(evil)
    sigs = []
    by_addr = {p.pub_key().address(): p for p in privs}
    vals7 = state_store.load_validators(7)
    ts = Timestamp(1700000007, 500)
    for val in vals7.validators:
        sb = canonical_vote_bytes(gdoc.chain_id, SignedMsgType.PRECOMMIT,
                                  7, 0, bid, ts)
        sigs.append(CommitSig(BlockIDFlag.COMMIT, val.address, ts,
                              by_addr[val.address].sign(sb)))
    evil_commit = Commit(7, 0, bid, sigs)
    lb = LightBlock(SignedHeader(evil.header, evil_commit), vals7)
    ev = LightClientAttackEvidence(
        conflicting_block=lb, common_height=7,
        total_voting_power=vals7.total_voting_power(),
        timestamp=block_store.load_block_meta(7).header.time)
    ev.validate_basic()
    common = SignedHeader(block_store.load_block_meta(7).header,
                          block_store.load_block_commit(7))
    verify_light_client_attack(ev, common, common, vals7)
    # pool end-to-end
    pool = EvidencePool(MemDB(), state_store, block_store)
    pool.add_evidence(ev)
    assert pool.size() == 1


def test_light_attack_evidence_validate_basic():
    gdoc, privs = make_genesis(4)
    blocks, commits, states = build_chain(gdoc, privs, 6)
    lb = LightBlock(SignedHeader(blocks[4].header, commits[4]),
                    states[4].validators)
    ev = LightClientAttackEvidence(
        conflicting_block=lb, common_height=3,
        total_voting_power=states[4].validators.total_voting_power(),
        timestamp=blocks[2].header.time)
    ev.validate_basic()
    ev2 = evidence_from_proto(ev.proto())
    assert ev2.hash() == ev.hash()
    bad = copy.deepcopy(ev)
    bad.common_height = 9
    with pytest.raises(EvidenceError):
        bad.validate_basic()


def test_reactor_gates_evidence_on_peer_height():
    """Reference evidence/reactor.go:165-184: evidence is held back from
    a peer whose consensus height is below the evidence height, sent
    once it catches up, and skipped for a peer far past the age window
    (VERDICT r3 #6)."""
    from tendermint_tpu.evidence.reactor import EvidenceReactor

    class FakePeer:
        def __init__(self, pid):
            self.id = pid
            self.data = {}
            self.got = []

        def try_send(self, ch, msg):
            self.got.append(msg)
            return True

    gdoc, privs = make_genesis(4)
    blocks, commits, _ = build_chain(gdoc, privs, 8)
    ex, state_store, block_store, state = _synced_node(gdoc, blocks, commits)
    pool = EvidencePool(MemDB(), state_store, block_store)
    bt = block_store.load_block_meta(5).header.time
    v1, v2 = _dup_votes(privs[1])
    vals = state_store.load_validators(5)
    pool.add_evidence(DuplicateVoteEvidence.from_votes(v1, v2, bt, vals))

    reactor = EvidenceReactor(pool)
    peer = FakePeer("behind")
    reactor.add_peer(peer)
    assert not peer.got            # no height known yet: held back
    peer.data["height"] = 3        # still below the ev height (5)
    reactor._send_pending(peer)
    assert not peer.got
    peer.data["height"] = 6        # caught up past the evidence height
    reactor._send_pending(peer)
    assert len(peer.got) == 1 and len(peer.got[0].evidence_protos) == 1
    # already-sent items are not resent
    reactor._send_pending(peer)
    assert len(peer.got) == 1

    # a peer far past the age window never receives the item
    far = FakePeer("far-ahead")
    far.data["height"] = (5 + 1
                          + state.consensus_params.evidence
                          .max_age_num_blocks)
    reactor._send_pending(far)
    assert not far.got


def test_duplicate_vote_verify_scheduler_parity():
    """ISSUE 11 satellite: the two vote signatures route through
    crypto/scheduler.verify_items at COMMIT priority — the verdict and
    the which-vote-failed attribution must be bitmap-exact vs the
    scheduler-less direct path, for good and for tampered votes."""
    from tendermint_tpu.crypto import scheduler as vsched

    gdoc, privs = make_genesis(4)
    state = state_from_genesis(gdoc)
    vals = state.validators
    v1, v2 = _dup_votes(privs[0])

    def outcomes():
        out = []
        ev = DuplicateVoteEvidence.from_votes(
            v1, v2, Timestamp(1700000005, 0), vals)
        verify_duplicate_vote(ev, CHAIN, vals)  # both good: no raise
        out.append("ok")
        for tamper, expect in (("vote_a", "VoteA"), ("vote_b", "VoteB")):
            bad = copy.deepcopy(ev)
            getattr(bad, tamper).signature = bytes(64)
            with pytest.raises(EvidenceError) as ei:
                verify_duplicate_vote(bad, CHAIN, vals)
            assert expect in str(ei.value)
            out.append(str(ei.value))
        return out

    assert vsched.running() is None
    direct = outcomes()  # scheduler absent: direct BatchVerifier path

    sched = vsched.install(vsched.VerifyScheduler(window_s=0.002))
    sched.start()
    try:
        via_sched = outcomes()  # same triples through the scheduler
    finally:
        sched.stop()
        vsched.uninstall(sched)
    assert via_sched == direct
