"""WebSocket event subscriptions over the RPC server (reference
rpc/jsonrpc/server/ws_handler.go + rpc/core/events.go): subscribe with a
pubsub query, receive matching events as JSON-RPC notifications,
unsubscribe."""
from __future__ import annotations

import base64
import json
import os
import socket
import struct
import time

import pytest


class MiniWSClient:
    """Minimal RFC6455 client (client frames must be masked)."""

    def __init__(self, host, port, timeout=10.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        key = base64.b64encode(os.urandom(16)).decode()
        self.sock.sendall(
            f"GET /websocket HTTP/1.1\r\nHost: {host}\r\n"
            f"Upgrade: websocket\r\nConnection: Upgrade\r\n"
            f"Sec-WebSocket-Key: {key}\r\n"
            f"Sec-WebSocket-Version: 13\r\n\r\n".encode())
        resp = b""
        while b"\r\n\r\n" not in resp:
            resp += self.sock.recv(4096)
        assert b"101" in resp.split(b"\r\n")[0], resp

    def send_json(self, obj):
        payload = json.dumps(obj).encode()
        mask = os.urandom(4)
        n = len(payload)
        if n < 126:
            hdr = struct.pack("!BB", 0x81, 0x80 | n)
        else:
            hdr = struct.pack("!BBH", 0x81, 0x80 | 126, n)
        masked = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
        self.sock.sendall(hdr + mask + masked)

    def _recv_exact(self, n):
        buf = b""
        while len(buf) < n:
            c = self.sock.recv(n - len(buf))
            if not c:
                raise ConnectionError("closed")
            buf += c
        return buf

    def recv_json(self):
        b1, b2 = self._recv_exact(2)
        ln = b2 & 0x7F
        if ln == 126:
            (ln,) = struct.unpack("!H", self._recv_exact(2))
        elif ln == 127:
            (ln,) = struct.unpack("!Q", self._recv_exact(8))
        data = self._recv_exact(ln)
        if (b1 & 0x0F) != 1:
            return self.recv_json()
        return json.loads(data)

    def close(self):
        self.sock.close()


# demoted from @pytest.mark.slow: 4.2 s on CPU (< 5 s bar, pytest.ini) —
# safety tests must not be the least-run tests
def test_ws_subscribe_new_block_and_tx(tmp_path):
    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.config.config import Config
    from tendermint_tpu.consensus.config import test_config as fast_config
    from tendermint_tpu.node import Node
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.types.basic import Timestamp
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    home = str(tmp_path / "node")
    cfg = Config(home=home)
    cfg.consensus = fast_config()
    cfg.p2p.laddr = "127.0.0.1:0"
    cfg.p2p.pex = False
    cfg.rpc.laddr = "127.0.0.1:0"
    cfg.ensure_dirs()
    pv = FilePV.load_or_generate(cfg.priv_validator_key_file(),
                                 cfg.priv_validator_state_file())
    NodeKey.load_or_generate(cfg.node_key_file())
    pub = pv.get_pub_key()
    gdoc = GenesisDoc(chain_id="ws-chain",
                      genesis_time=Timestamp(1700000000, 0),
                      validators=[GenesisValidator(
                          address=pub.address(), pub_key_type=pub.type_name,
                          pub_key_bytes=pub.bytes(), power=10)])
    with open(cfg.genesis_file(), "w") as f:
        f.write(gdoc.to_json())

    node = Node(cfg, KVStoreApplication())
    node.start()
    try:
        host, port = node.rpc_server.host, node.rpc_server.port
        ws = MiniWSClient(host, port)

        # subscribe to new blocks
        ws.send_json({"jsonrpc": "2.0", "id": 1, "method": "subscribe",
                      "params": {"query": "tm.event='NewBlock'"}})
        ack = ws.recv_json()
        assert ack["id"] == 1 and "result" in ack, ack

        ev = ws.recv_json()
        assert ev["result"]["query"] == "tm.event='NewBlock'"
        assert ev["result"]["data"]["type"] == "tendermint/event/NewBlock"
        h1 = ev["result"]["data"]["value"]["height"]
        ev2 = ws.recv_json()
        assert ev2["result"]["data"]["value"]["height"] > h1

        # tx subscription with an app-attribute filter
        ws.send_json({"jsonrpc": "2.0", "id": 2, "method": "subscribe",
                      "params": {
                          "query": "tm.event='Tx' AND app.creator="
                                   "'kvstore'"}})
        assert "result" in ws.recv_json()
        node.mempool.check_tx(b"wskey=wsvalue")
        deadline = time.time() + 30
        got_tx = False
        while time.time() < deadline and not got_tx:
            msg = ws.recv_json()
            if msg["result"]["data"]["type"] == "tendermint/event/Tx":
                assert msg["result"]["data"]["value"]["code"] == 0
                got_tx = True
        assert got_tx, "tx event never delivered"

        # unsubscribe stops block delivery
        ws.send_json({"jsonrpc": "2.0", "id": 3, "method":
                      "unsubscribe_all", "params": {}})
        # drain until the ack; then no further frames should arrive
        while True:
            msg = ws.recv_json()
            if msg.get("id") == 3:
                break
        ws.sock.settimeout(1.5)
        with pytest.raises((TimeoutError, socket.timeout,
                            ConnectionError)):
            ws.recv_json()
        ws.close()
    finally:
        node.stop()


# demoted from @pytest.mark.slow: 2.7 s on CPU (< 5 s bar, pytest.ini)
def test_production_ws_client_and_new_rpc_routes(tmp_path):
    """The shipped WSClient (rpc/client.py) subscribes / receives /
    multiplexes calls over one socket, and the round-3 RPC routes
    (dump_consensus_state, genesis_chunked, unsafe dial gating) answer
    with the reference shapes."""
    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.config.config import Config
    from tendermint_tpu.consensus.config import test_config as fast_config
    from tendermint_tpu.node import Node
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.rpc.client import HTTPClient, RPCClientError, WSClient
    from tendermint_tpu.types.basic import Timestamp
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    home = str(tmp_path / "node")
    cfg = Config(home=home)
    cfg.consensus = fast_config()
    cfg.p2p.laddr = "127.0.0.1:0"
    cfg.p2p.pex = False
    cfg.rpc.laddr = "127.0.0.1:0"
    cfg.ensure_dirs()
    pv = FilePV.load_or_generate(cfg.priv_validator_key_file(),
                                 cfg.priv_validator_state_file())
    NodeKey.load_or_generate(cfg.node_key_file())
    pub = pv.get_pub_key()
    gdoc = GenesisDoc(chain_id="wsc-chain",
                      genesis_time=Timestamp(1700000000, 0),
                      validators=[GenesisValidator(
                          address=pub.address(), pub_key_type=pub.type_name,
                          pub_key_bytes=pub.bytes(), power=10)])
    with open(cfg.genesis_file(), "w") as f:
        f.write(gdoc.to_json())

    node = Node(cfg, KVStoreApplication())
    node.start()
    ws = None
    try:
        addr = f"{node.rpc_server.host}:{node.rpc_server.port}"
        ws = WSClient(addr)
        sub = ws.subscribe("tm.event='NewBlock'")
        ev = sub.get(timeout=30)
        assert ev["data"]["type"] == "tendermint/event/NewBlock"
        h1 = ev["data"]["value"]["height"]
        # a plain RPC call multiplexes over the same connection
        st = ws.call("status")
        assert int(st["sync_info"]["latest_block_height"]) >= h1
        ev2 = sub.get(timeout=30)
        assert ev2["data"]["value"]["height"] > h1
        ws.unsubscribe("tm.event='NewBlock'")

        http = HTTPClient(addr)
        dump = http.call("dump_consensus_state")
        assert dump["round_state"]["height"] >= 1
        assert "votes" in dump["round_state"]
        assert isinstance(dump["peers"], list)

        g = http.call("genesis_chunked", chunk=0)
        assert g["total"] == 1 and g["chunk"] == 0
        import base64 as b64
        assert b"wsc-chain" in b64.b64decode(g["data"])
        with pytest.raises(RPCClientError, match="chunks"):
            http.call("genesis_chunked", chunk=5)

        # unsafe routes are gated off by default
        with pytest.raises(RPCClientError, match="not found|unknown"):
            http.call("dial_peers", peers=["x@127.0.0.1:1"])
    finally:
        if ws is not None:
            ws.close()
        node.stop()
