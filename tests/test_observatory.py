"""Consensus observatory (consensus/observatory.py, ADR-020): the
per-height block-lifecycle decomposition, its debug surfaces, and the
ISSUE 12 satellites.

The acceptance test re-runs the tier-1 4-node partition-heal smoke
with the flight recorder armed and proves the observatory's stamps
agree with the recorder's span timestamps, that `/debug/consensus`
and the `debug-consensus` CLI agree with the in-process report, and
that `consensus_quorum_prevote_delay` is finally published on the
real 2/3-prevote path.  Unit tests pin the ring bounds, the disabled
sub-microsecond no-op (timeit-gated like trace/slo), the chaos shed
at `observatory.record`, the receipt DoS guard, the quorum-delay
origin semantics, the pipeline writer's durable stamps, and the
flight recorder's new dropped-span counter.
"""
from __future__ import annotations

import json
import os
import threading
import time
import timeit
import urllib.request

import pytest

from tendermint_tpu.consensus import observatory as obsv
from tendermint_tpu.consensus.observatory import Observatory
from tendermint_tpu.libs import fail, slo, trace
from tendermint_tpu.libs.metrics import (ConsensusMetrics, Registry,
                                         TraceMetrics)


@pytest.fixture(autouse=True)
def _clean():
    obsv.reset()
    obsv.enable()
    yield
    fail.clear()
    obsv.reset()
    obsv.enable()


def _full_lifecycle(o, node="n", height=1, t0=100.0):
    """Stamp one clean height: every stage 10 ms apart."""
    order = ("new_height", "propose_start", "proposal", "first_part",
             "parts_complete", "prevote_any", "prevote_quorum",
             "precommit_quorum", "commit", "apply_start", "apply_done")
    for i, stage in enumerate(order):
        o.stamp(node, height, stage, t=t0 + 0.01 * i)
    return t0


# ---------------------------------------------------------------------------
# record mechanics: decomposition, first-write-wins, ring bounds
# ---------------------------------------------------------------------------

def test_stage_decomposition_and_interval():
    o = Observatory(capacity=8, enabled=True)
    _full_lifecycle(o, height=1, t0=100.0)
    _full_lifecycle(o, height=2, t0=101.0)
    recs = o.records("n")
    assert [r["height"] for r in recs] == [1, 2]
    st = recs[0]["stages"]
    # propose = new_height -> proposal (2 steps), gossip = proposal ->
    # parts_complete (2 steps), each step 10 ms
    assert st["propose"] == pytest.approx(0.02)
    assert st["gossip"] == pytest.approx(0.02)
    assert st["prevote_wait"] == pytest.approx(0.02)
    assert st["precommit_wait"] == pytest.approx(0.01)
    assert st["commit"] == pytest.approx(0.02)  # quorum -> apply_start
    assert st["apply"] == pytest.approx(0.01)
    assert st["persist"] is None  # no durable stamp on this path
    # block interval: commit(h2) - commit(h1) = 1.0 s
    assert recs[1]["info"]["interval_s"] == pytest.approx(1.0)
    assert recs[0].get("info", {}).get("interval_s") is None


def test_first_write_wins_and_final_round():
    o = Observatory(capacity=8, enabled=True)
    assert o.stamp("n", 5, "proposal", round_=0, t=1.0,
                   proposal_ts=10.0) is True
    # a round-1 re-proposal: stage stamp keeps round 0's time, but the
    # quorum-delay origin follows the latest round's proposal and
    # final_round records the dirty path
    assert o.stamp("n", 5, "proposal", round_=1, t=2.0,
                   proposal_ts=11.5) is False
    r = o.records("n")[0]
    assert r["stamps"]["proposal"] == 1.0
    assert r["final_round"] == 1
    assert r["info"]["proposal_ts"] == 11.5


def test_ring_bounds_hold_and_evictions_counted():
    o = Observatory(capacity=4, enabled=True)
    for h in range(1, 11):
        o.stamp("n", h, "new_height", t=float(h))
    recs = o.records("n")
    assert len(recs) == 4
    assert [r["height"] for r in recs] == [7, 8, 9, 10]
    assert o.shed_counts()["evict"] == 6
    # per-node rings are independent
    o.stamp("m", 1, "new_height", t=1.0)
    assert len(o.records("n")) == 4 and len(o.records("m")) == 1


def test_receipt_updates_existing_records_only():
    """The DoS guard: receipt heights are peer-controlled, so a peer
    must not be able to mint records (and wash the ring); the per-peer
    maps are hard-capped."""
    o = Observatory(capacity=4, enabled=True)
    o.receipt("n", 999999, "part", "peer-a")
    assert o.records("n") == []          # nothing minted
    o.stamp("n", 7, "new_height", t=1.0)
    o.receipt("n", 7, "part", "peer-a")
    o.receipt("n", 7, "part", "peer-a")
    o.receipt("n", 7, "vote", "peer-b")
    r = o.records("n")[0]
    assert r["parts_from"] == {"peer-a": 2}
    assert r["votes_from"] == {"peer-b": 1}
    for i in range(500):  # cap: remote-controlled peer ids
        o.receipt("n", 7, "vote", f"peer-{i}")
    assert len(o.records("n")[0]["votes_from"]) <= 128


def test_pending_publication_queue_is_bounded():
    """With no drainer at all, the deferred-publication queue must not
    grow without bound (its normal drains are the consensus receive
    loop, _apply_one on the catch-up path, and the pipeline writer)."""
    o = Observatory(capacity=8, enabled=True)
    for h in range(1, 6001):
        o.stamp("n", h, "apply_done", t=float(h))
    assert len(o._pending) <= 4096
    assert o.shed_counts()["evict"] >= 6000 - 4096  # dropped + ring


def test_disabled_is_noop_and_sub_microsecond():
    """The observatory is called from the consensus hot path
    unconditionally, so the disabled path must stay sub-microsecond —
    the same gate trace.py and slo.py carry.  min-of-repeats dodges CI
    load spikes."""
    obsv.disable()
    try:
        obsv.stamp("n", 1, "new_height")
        obsv.receipt("n", 1, "part", "p")
        assert obsv.records("n") == []

        n = 20000

        def site():
            obsv.stamp("n", 1, "commit", round_=0)

        per_call = min(timeit.repeat(site, number=n, repeat=5)) / n
        assert per_call < 1e-6, f"disabled stamp cost {per_call:.2e}s"

        def site_receipt():
            obsv.receipt("n", 1, "part", "p")

        per_call = min(timeit.repeat(site_receipt, number=n,
                                     repeat=5)) / n
        assert per_call < 1e-6, f"disabled receipt cost {per_call:.2e}s"
    finally:
        obsv.enable()


# ---------------------------------------------------------------------------
# chaos: a recording fault sheds, consensus proceeds
# ---------------------------------------------------------------------------

def test_chaos_record_raise_sheds_without_propagating():
    reg_before = ConsensusMetrics().observatory_shed.value(reason="chaos")
    fail.set_mode("observatory.record", "raise")
    try:
        # neither call may raise — recording must never take down the
        # state machine it observes
        assert obsv.stamp("n", 1, "new_height") is False
        obsv.stamp("n", 1, "commit")
        obsv.receipt("n", 1, "part", "p")
        assert fail.fired("observatory.record", "raise") == 3
        assert obsv.records("n") == []
        assert obsv.OBS.shed_counts()["chaos"] == 3
        # shed counts flush even when NO height completed (a stalled
        # node under chaos must not park the counter at zero forever)
        obsv.publish_pending()
        assert ConsensusMetrics().observatory_shed.value(
            reason="chaos") == reg_before + 3
        assert obsv.OBS.shed_counts()["chaos"] == 0
    finally:
        fail.clear()
    # disarmed: recording resumes
    obsv.stamp("n", 2, "new_height")
    obsv.stamp("n", 2, "apply_done")
    obsv.publish_pending()
    assert ConsensusMetrics().observatory_shed.value(
        reason="chaos") == reg_before + 3  # no new sheds
    assert [r["height"] for r in obsv.records("n")] == [2]


def test_chaos_latency_mode_also_swallowed():
    fail.set_mode("observatory.record", "latency:5")
    try:
        t0 = time.monotonic()
        obsv.stamp("n", 1, "new_height")
        assert time.monotonic() - t0 >= 0.004
        assert [r["height"] for r in obsv.records("n")] == [1]
    finally:
        fail.clear()


# ---------------------------------------------------------------------------
# satellite 1: consensus_quorum_prevote_delay origin semantics
# ---------------------------------------------------------------------------

def test_quorum_prevote_delay_published_from_proposal_origin():
    """The gauge existed since the seed but was NEVER set.  It now
    publishes on record completion: quorum vote wall timestamp minus
    the (latest round's) proposal wall timestamp, clamped >= 0."""
    m = ConsensusMetrics()
    obsv.stamp("n", 3, "proposal", t=1.0, proposal_ts=500.0)
    obsv.stamp("n", 3, "prevote_quorum", t=1.1,
               prevote_quorum_ts=500.35)
    obsv.stamp("n", 3, "commit", t=1.2)
    obsv.stamp("n", 3, "apply_start", t=1.3)
    obsv.stamp("n", 3, "apply_done", t=1.4)
    obsv.publish_pending()
    assert m.quorum_prevote_delay.value() == pytest.approx(0.35)
    # negative (BFT-time skew after a round change) clamps to zero
    obsv.stamp("n", 4, "proposal", t=2.0, proposal_ts=600.0)
    obsv.stamp("n", 4, "prevote_quorum", t=2.1,
               prevote_quorum_ts=599.0)
    obsv.stamp("n", 4, "apply_done", t=2.2)
    obsv.publish_pending()
    assert m.quorum_prevote_delay.value() == 0.0
    # cross-round pairing is REFUSED: a round-0 polka must not be
    # measured against a round-1 proposal (proposal_ts is latest-wins,
    # the quorum stamp is first-wins)
    m.quorum_prevote_delay.set(-1.0)  # sentinel
    obsv.stamp("n", 5, "proposal", t=3.0, proposal_ts=700.0,
               proposal_round=0)
    obsv.stamp("n", 5, "prevote_quorum", t=3.1,
               prevote_quorum_ts=700.2, prevote_quorum_round=0)
    obsv.stamp("n", 5, "proposal", t=3.2, proposal_ts=705.0,
               proposal_round=1)  # round change after the polka
    obsv.stamp("n", 5, "apply_done", t=3.3)
    obsv.publish_pending()
    assert m.quorum_prevote_delay.value() == -1.0  # untouched
    # same-round pairing still publishes
    obsv.stamp("n", 6, "proposal", t=4.0, proposal_ts=800.0,
               proposal_round=2)
    obsv.stamp("n", 6, "prevote_quorum", t=4.1,
               prevote_quorum_ts=800.25, prevote_quorum_round=2)
    obsv.stamp("n", 6, "apply_done", t=4.2)
    obsv.publish_pending()
    assert m.quorum_prevote_delay.value() == pytest.approx(0.25)


def test_publication_feeds_histogram_and_slo_streams():
    slo.set_config(enabled=True, window=64)
    reg = ConsensusMetrics()
    base = {s: reg.height_stage.count(stage=s)
            for s in ("propose", "apply", "interval")}
    try:
        _full_lifecycle(obsv.OBS, node="n", height=1, t0=100.0)
        _full_lifecycle(obsv.OBS, node="n", height=2, t0=101.0)
        obsv.publish_pending()
        assert reg.height_stage.count(stage="propose") == base["propose"] + 2
        assert reg.height_stage.count(stage="apply") == base["apply"] + 2
        # interval needs two commits
        assert reg.height_stage.count(stage="interval") == \
            base["interval"] + 1
        for stream in ("propose", "quorum_prevote", "apply",
                       "block_interval"):
            assert slo.stream_report(stream) is not None, stream
        assert slo.stream_report("block_interval")["n"] == 1
        # publication is idempotent: draining again observes nothing new
        obsv.publish_pending()
        assert reg.height_stage.count(stage="apply") == base["apply"] + 2
    finally:
        slo.set_config(enabled=False)
        slo.reset()


# ---------------------------------------------------------------------------
# pipeline writer durable ack -> persist stage
# ---------------------------------------------------------------------------

def test_pipeline_writer_stamps_durable_persist_stage():
    from tendermint_tpu.state.pipeline import BlockPipeline, _WriteJob

    reg = ConsensusMetrics()
    base = reg.height_stage.count(stage="persist")
    obsv.stamp("pl", 1, "apply_start", t=1.0)
    obsv.stamp("pl", 1, "apply_done", t=1.5)
    obsv.publish_pending()
    p = BlockPipeline(depth=2, group_commit_heights=2, enabled=True)
    p.obs_node = "pl"
    p.start()
    try:
        # an empty group commit exercises exactly the success path the
        # real writer takes after landing a group
        p._write_q.put(_WriteJob(p._gen, 1, []))
        deadline = time.monotonic() + 5.0
        while p.durable_height() < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert p.durable_height() == 1
    finally:
        p.stop()
    rec = obsv.records("pl")[0]
    assert "durable" in rec["stamps"]
    assert rec["stages"]["persist"] is not None
    assert reg.height_stage.count(stage="persist") == base + 1


def test_pipeline_writer_durable_attribution_bounded_by_group_base():
    """A job's durable stamps cover exactly [job.base, job.height] —
    prev_durable alone would mint junk records below the first group
    of a run (and a >64-height group must not be truncated)."""
    from tendermint_tpu.state.pipeline import BlockPipeline, _WriteJob

    p = BlockPipeline(depth=2, group_commit_heights=2, enabled=True)
    p.obs_node = "pb"
    p.start()
    try:
        p._write_q.put(_WriteJob(p._gen, 500, [], base=498))
        deadline = time.monotonic() + 5.0
        while p.durable_height() < 500 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert p.durable_height() == 500
    finally:
        p.stop()
    heights = [r["height"] for r in obsv.records("pb")]
    assert heights == [498, 499, 500]  # nothing minted below the base


# ---------------------------------------------------------------------------
# cross-node skew
# ---------------------------------------------------------------------------

def test_skew_report_spreads_and_offsets():
    for i, node in enumerate(("a", "b", "c")):
        obsv.stamp(node, 5, "proposal", t=10.0 + 0.01 * i)
        obsv.stamp(node, 5, "commit", t=11.0 + 0.02 * i)
    obsv.stamp("a", 6, "commit", t=12.0)  # single-node height: excluded
    sk = obsv.skew_report()
    assert list(sk["heights"]) == [5]
    row = sk["heights"][5]
    assert row["proposal"]["spread_s"] == pytest.approx(0.02)
    assert row["commit"]["spread_s"] == pytest.approx(0.04)
    assert row["commit"]["offsets_s"]["a"] == 0.0
    assert row["commit"]["offsets_s"]["c"] == pytest.approx(0.04)
    assert sk["max_spread_s"]["commit"] == pytest.approx(0.04)


# ---------------------------------------------------------------------------
# satellite 2: flight-recorder ring overflow is no longer invisible
# ---------------------------------------------------------------------------

def test_trace_dropped_span_counter_moves_on_wraparound():
    metric_before = TraceMetrics().dropped_spans.value()
    tr = trace.Tracer(capacity=4, enabled=True)
    assert tr.dropped() == 0
    for i in range(10):
        tr.instant("consensus.step", i=i)
    assert tr.dropped() == 6
    doc = tr.chrome_trace()
    assert doc["dropped_spans"] == 6
    assert len(doc["traceEvents"]) == 4
    # the process-global counter moved with it (metric satellite)
    assert TraceMetrics().dropped_spans.value() == metric_before + 6
    # an un-wrapped ring reports zero
    tr2 = trace.Tracer(capacity=64, enabled=True)
    tr2.instant("consensus.step")
    assert tr2.dropped() == 0
    assert tr2.chrome_trace()["dropped_spans"] == 0


# ---------------------------------------------------------------------------
# debug surfaces: GET /debug/consensus + the debug-consensus CLI
# ---------------------------------------------------------------------------

def test_debug_consensus_endpoint_and_cli_agree_with_report():
    from tendermint_tpu.libs.pprof import PprofServer

    _full_lifecycle(obsv.OBS, node="node-a", height=1, t0=50.0)
    _full_lifecycle(obsv.OBS, node="node-a", height=2, t0=51.0)
    _full_lifecycle(obsv.OBS, node="node-b", height=2, t0=51.2)
    obsv.publish_pending()
    rep = obsv.report(last=16)

    srv = PprofServer("127.0.0.1:0")
    srv.start()
    try:
        with urllib.request.urlopen(
                f"http://{srv.laddr}/debug/consensus?last=16",
                timeout=10) as r:
            assert r.status == 200
            assert r.headers["Content-Type"] == "application/json"
            doc = json.loads(r.read().decode())
        assert sorted(doc["nodes"]) == ["node-a", "node-b"]
        assert [x["height"] for x in doc["nodes"]["node-a"]] == [1, 2]
        assert doc["nodes"]["node-a"][0]["stages"]["apply"] == \
            pytest.approx(rep["nodes"]["node-a"][0]["stages"]["apply"])
        # two nodes share the recorder: the skew report rides along
        assert "skew" in doc and "2" in json.dumps(
            list(doc["skew"]["heights"]))
        # node filter
        with urllib.request.urlopen(
                f"http://{srv.laddr}/debug/consensus?node=node-b",
                timeout=10) as r:
            one = json.loads(r.read().decode())
        assert list(one["nodes"]) == ["node-b"]

        # the CLI mirrors debug-latency: fetch + write the same JSON
        from tendermint_tpu.cmd.__main__ import main as cli_main
        out = os.path.join(os.environ.get("TMPDIR", "/tmp"),
                           f"consensus-cli-{os.getpid()}.json")
        try:
            cli_main(["debug-consensus", "--pprof-laddr", srv.laddr,
                      "--output-file", out])
            with open(out) as f:
                cli_doc = json.load(f)
            assert cli_doc["nodes"]["node-a"][0]["stamps"] == {
                k: pytest.approx(v) for k, v in
                rep["nodes"]["node-a"][0]["stamps"].items()}
        finally:
            if os.path.exists(out):
                os.remove(out)

        # /debug/trace carries the dropped-span field (satellite 2)
        with urllib.request.urlopen(
                f"http://{srv.laddr}/debug/trace", timeout=10) as r:
            tdoc = json.loads(r.read().decode())
        assert "dropped_spans" in tdoc
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# satellite 6 (small fix): recording never holds a ranked lock across
# a blocking call — proven under the LockSanitizer
# ---------------------------------------------------------------------------

@pytest.mark.locksan
def test_locksan_concurrent_stamp_publish_report_roundtrip():
    """Hammer stamp/receipt (the consensus-thread shape), the deferred
    publication (the post-lock drain) and the read side concurrently
    under the lockset monitor: any acquisition of a lower-ranked lock
    while holding the observatory lock (74) — e.g. metrics (80/84) is
    fine, but fail._lock (62) or a scheduler lock would fail — and any
    blocking call under it is a sanitizer violation."""
    slo.set_config(enabled=True, window=64)
    errs = []

    def writer(base):
        try:
            for h in range(base, base + 40):
                _full_lifecycle(obsv.OBS, node=f"n{base % 3}",
                                height=h, t0=float(h))
                obsv.receipt(f"n{base % 3}", h, "vote", "peer")
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    def drainer():
        try:
            for _ in range(60):
                obsv.publish_pending()
                obsv.report(last=4)
                obsv.skew_report()
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=writer, args=(b,))
               for b in (1, 1000, 2000)] + [
        threading.Thread(target=drainer) for _ in range(2)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
    finally:
        slo.set_config(enabled=False)
        slo.reset()
    assert errs == []
    obsv.publish_pending()


# ---------------------------------------------------------------------------
# THE acceptance test: the tier-1 4-node partition-heal smoke, with
# the observatory proven against the flight recorder's clock
# ---------------------------------------------------------------------------

def test_smoke_observatory_agrees_with_flight_recorder(tmp_path):
    from tendermint_tpu.networks import scenarios
    from tendermint_tpu.networks.harness import NetHarness

    sc = scenarios.by_name("partition_heal_majority")
    trace.enable(capacity=1 << 16)
    seq0 = trace.last_seq()
    try:
        res = NetHarness.run(sc, seed=42, workdir=str(tmp_path))
    finally:
        trace.disable()
    assert res["violations"] == []
    obsv.publish_pending()

    rep = obsv.report(last=64)
    assert sorted(rep["nodes"]) == ["node0", "node1", "node2", "node3"]

    # pick node0's newest height with a full lifecycle
    full = [r for r in rep["nodes"]["node0"]
            if {"new_height", "proposal", "parts_complete",
                "prevote_quorum", "precommit_quorum", "commit",
                "apply_start", "apply_done"} <= set(r["stamps"])]
    assert full, "no fully-stamped height on node0"
    rec = full[-1]
    h = rec["height"]
    # the committed height really was decomposed: every non-persist
    # stage has a value and they are sane
    for stage in ("propose", "gossip", "prevote_wait",
                  "precommit_wait", "commit", "apply"):
        assert rec["stages"][stage] is not None
        assert 0.0 <= rec["stages"][stage] < 60.0
    assert rec["proposer"], "proposer id missing"
    # gossip really was accounted per peer (3 peers served this node)
    assert rec["votes_from"], "no per-peer vote receipts"

    # -- flight-recorder agreement: same clock, same story ------------
    spans = trace.snapshot(since=seq0)
    tname = "consensus-node0"

    def _instants(name, **attrs):
        return [s for s in spans if s["name"] == name
                and s["tname"] == tname
                and all(s["attrs"].get(k) == v
                        for k, v in attrs.items())]

    commit_steps = _instants("consensus.step", step="COMMIT", height=h)
    assert commit_steps, f"no COMMIT step instant for height {h}"
    span_t = commit_steps[0]["ts_ns"] / 1e9
    assert span_t == pytest.approx(rec["stamps"]["commit"], abs=0.25)

    applies = [s for s in spans if s["name"] == "state.apply_block"
               and s["tname"] == tname and s["attrs"].get("height") == h]
    assert applies, f"no apply span for height {h}"
    ap = applies[0]
    assert ap["ts_ns"] / 1e9 == \
        pytest.approx(rec["stamps"]["apply_start"], abs=0.25)
    assert (ap["ts_ns"] + ap["dur_ns"]) / 1e9 == \
        pytest.approx(rec["stamps"]["apply_done"], abs=0.25)

    quorums = _instants("consensus.quorum", type="prevote", height=h)
    assert quorums, f"no prevote quorum instant for height {h}"
    assert quorums[0]["ts_ns"] / 1e9 == \
        pytest.approx(rec["stamps"]["prevote_quorum"], abs=0.25)

    # -- satellite 1 on the REAL path: the gauge finally moves --------
    assert ConsensusMetrics().quorum_prevote_delay.value() > 0.0

    # -- cross-node skew: the same heights seen from four clocks ------
    sk = obsv.skew_report()
    assert sk["heights"], "skew report empty on a 4-node run"
    assert "commit" in sk["max_spread_s"]

    # -- the stitched artifact now carries observatory timelines ------
    from tendermint_tpu.networks.invariants import (ChainWatcher,
                                                    export_artifact)
    paths = export_artifact(str(tmp_path), "obs-check", 42, [],
                            ChainWatcher("netharness-chain"), [], [])
    with open(paths["timeline"]) as f:
        art = json.load(f)
    assert set(art["observatory"]) >= {"node0", "node1"}
    assert art["skew"]["heights"]
