"""VoteSet semantics: majorities, conflicts, commits (modeled on reference
types/vote_set_test.go)."""
import pytest

from tendermint_tpu.crypto import ed25519 as edkeys
from tendermint_tpu.types.basic import (
    BlockID, PartSetHeader, SignedMsgType, Timestamp)
from tendermint_tpu.types.validator import Validator
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.types.vote import Vote
from tendermint_tpu.types.vote_set import (
    ConflictingVoteError, VoteSet, VoteSetError)

CHAIN = "test-chain"


def make_fixture(n=4, power=10):
    pairs = []
    for i in range(n):
        priv = edkeys.PrivKey((1000 + i).to_bytes(32, "big"))
        pairs.append((priv, Validator.new(priv.pub_key(), power)))
    vs = ValidatorSet([v for _, v in pairs])
    by_addr = {v.address: p for p, v in pairs}
    privs_in_order = [by_addr[v.address] for v in vs.validators]
    return vs, privs_in_order


def mkvote(priv, idx, vs, block_id, height=1, round_=0,
           vtype=SignedMsgType.PRECOMMIT, ts=None):
    v = Vote(
        type=vtype, height=height, round=round_, block_id=block_id,
        timestamp=ts or Timestamp(1700000000 + idx, 0),
        validator_address=vs.validators[idx].address,
        validator_index=idx)
    v.signature = priv.sign(v.sign_bytes(CHAIN))
    return v


BID = BlockID(bytes([1] * 32), PartSetHeader(2, bytes([2] * 32)))
NIL = BlockID()


def test_add_votes_to_majority():
    vs, privs = make_fixture(4)
    vset = VoteSet(CHAIN, 1, 0, SignedMsgType.PRECOMMIT, vs)
    assert not vset.has_two_thirds_majority()
    for i in range(3):
        assert vset.add_vote(mkvote(privs[i], i, vs, BID))
        if i < 2:
            assert not vset.has_two_thirds_majority(), i
    bid, ok = vset.two_thirds_majority()
    assert ok and bid == BID
    assert vset.has_two_thirds_any()


def test_duplicate_vote_not_added():
    vs, privs = make_fixture(4)
    vset = VoteSet(CHAIN, 1, 0, SignedMsgType.PRECOMMIT, vs)
    v = mkvote(privs[0], 0, vs, BID)
    assert vset.add_vote(v)
    assert vset.add_vote(v) is False  # same vote: no-op


def test_wrong_height_round_type_rejected():
    vs, privs = make_fixture(4)
    vset = VoteSet(CHAIN, 1, 0, SignedMsgType.PRECOMMIT, vs)
    with pytest.raises(VoteSetError):
        vset.add_vote(mkvote(privs[0], 0, vs, BID, height=2))
    with pytest.raises(VoteSetError):
        vset.add_vote(mkvote(privs[0], 0, vs, BID, round_=1))
    with pytest.raises(VoteSetError):
        vset.add_vote(mkvote(privs[0], 0, vs, BID,
                             vtype=SignedMsgType.PREVOTE))


def test_bad_signature_rejected():
    vs, privs = make_fixture(4)
    vset = VoteSet(CHAIN, 1, 0, SignedMsgType.PRECOMMIT, vs)
    v = mkvote(privs[0], 0, vs, BID)
    v.signature = bytes([v.signature[0] ^ 1]) + v.signature[1:]
    with pytest.raises(VoteSetError, match="invalid signature"):
        vset.add_vote(v)


def test_conflicting_votes_raise_evidence():
    vs, privs = make_fixture(4)
    vset = VoteSet(CHAIN, 1, 0, SignedMsgType.PRECOMMIT, vs)
    assert vset.add_vote(mkvote(privs[0], 0, vs, BID))
    other = BlockID(bytes([9] * 32), PartSetHeader(2, bytes([9] * 32)))
    with pytest.raises(ConflictingVoteError) as ei:
        vset.add_vote(mkvote(privs[0], 0, vs, other))
    assert ei.value.vote_a.block_id == BID
    assert ei.value.vote_b.block_id == other


def test_nil_votes_count_toward_any_but_not_block():
    vs, privs = make_fixture(4)
    vset = VoteSet(CHAIN, 1, 0, SignedMsgType.PRECOMMIT, vs)
    for i in range(3):
        vset.add_vote(mkvote(privs[i], i, vs, NIL))
    assert vset.has_two_thirds_any()
    bid, ok = vset.two_thirds_majority()
    assert ok and bid == NIL  # 2/3 for nil is a valid majority (nil block)


def test_make_commit():
    vs, privs = make_fixture(4)
    vset = VoteSet(CHAIN, 1, 0, SignedMsgType.PRECOMMIT, vs)
    for i in range(3):
        vset.add_vote(mkvote(privs[i], i, vs, BID))
    # validator 3 votes nil -> included as NIL sig
    vset.add_vote(mkvote(privs[3], 3, vs, NIL))
    commit = vset.make_commit()
    assert commit.height == 1 and commit.block_id == BID
    assert len(commit.signatures) == 4
    flags = [s.block_id_flag for s in commit.signatures]
    from tendermint_tpu.types.basic import BlockIDFlag
    assert flags.count(BlockIDFlag.COMMIT) == 3
    assert flags.count(BlockIDFlag.NIL) == 1
    # the produced commit verifies through the batch plane
    vs.verify_commit(CHAIN, BID, 1, commit)


def test_peer_maj23_tracking():
    vs, privs = make_fixture(4)
    vset = VoteSet(CHAIN, 1, 0, SignedMsgType.PREVOTE, vs)
    other = BlockID(bytes([9] * 32), PartSetHeader(2, bytes([9] * 32)))
    vset.set_peer_maj23("peer1", other)
    # conflicting vote for tracked block is recorded (then raises evidence)
    assert vset.add_vote(mkvote(privs[0], 0, vs, BID,
                                vtype=SignedMsgType.PREVOTE))
    with pytest.raises(ConflictingVoteError):
        vset.add_vote(mkvote(privs[0], 0, vs, other,
                             vtype=SignedMsgType.PREVOTE))
    ba = vset.bit_array_by_block_id(other)
    assert ba is not None and ba.get_index(0)


def test_bitarray():
    from tendermint_tpu.libs.bits import BitArray
    ba = BitArray(10)
    assert ba.is_empty() and not ba.is_full()
    for i in (0, 3, 9):
        ba.set_index(i, True)
    assert ba.get_true_indices() == [0, 3, 9]
    assert ba.num_true_bits() == 3
    nb = ba.not_()
    assert nb.get_true_indices() == [1, 2, 4, 5, 6, 7, 8]
    full = BitArray.from_indices(4, range(4))
    assert full.is_full()
    assert BitArray.from_bytes(10, ba.to_bytes()) == ba
