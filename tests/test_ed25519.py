"""Batched ed25519 verify kernel vs OpenSSL + pure-Python oracles.

Runs on the CPU backend (see conftest.py); the same jitted code path runs
on TPU (driven separately by bench.py / __graft_entry__.py).
"""
import hashlib
import os
import random

import numpy as np
import pytest

from tendermint_tpu.crypto import _edref
from tendermint_tpu.crypto import ed25519 as edkeys
from tendermint_tpu.ops import ed25519 as edops

rng = random.Random(42)


def _rand_seed():
    return bytes(rng.randrange(256) for _ in range(32))


def make_batch(n, msg_len=64):
    seeds = [_rand_seed() for _ in range(n)]
    msgs = [bytes(rng.randrange(256) for _ in range(msg_len)) for _ in range(n)]
    pubs = [_edref.pubkey_from_seed(s) for s in seeds]
    sigs = [_edref.sign(s, m) for s, m in zip(seeds, msgs)]
    return pubs, msgs, sigs


def test_pyref_matches_openssl():
    """The pure-Python reference itself must agree with OpenSSL."""
    pubs, msgs, sigs = make_batch(8)
    for p, m, s in zip(pubs, msgs, sigs):
        assert edkeys.PubKey(p).verify_signature(m, s)
        assert _edref.verify(p, m, s)
        assert not _edref.verify(p, m + b"x", s)


def test_kernel_all_valid():
    pubs, msgs, sigs = make_batch(32)
    out = edops.verify_batch(pubs, msgs, sigs)
    assert out.shape == (32,)
    assert out.all()


def test_kernel_rejects_corruption():
    """Flip one bit somewhere in (pub, msg, sig) per lane; all must fail."""
    n = 24
    pubs, msgs, sigs = make_batch(n)
    pubs, msgs, sigs = list(pubs), list(msgs), list(sigs)
    for i in range(n):
        which = i % 3
        if which == 0:
            b = bytearray(sigs[i]); b[rng.randrange(64)] ^= 1 << rng.randrange(8)
            sigs[i] = bytes(b)
        elif which == 1:
            b = bytearray(msgs[i]); b[rng.randrange(len(b))] ^= 1
            msgs[i] = bytes(b)
        else:
            b = bytearray(pubs[i]); b[rng.randrange(32)] ^= 1 << rng.randrange(8)
            pubs[i] = bytes(b)
    out = edops.verify_batch(pubs, msgs, sigs)
    # oracle: per-lane OpenSSL result (a corrupted pubkey may still decode to
    # a different valid key, but then the sig must not verify under it)
    oracle = np.array([
        edkeys.PubKey(p).verify_signature(m, s)
        for p, m, s in zip(pubs, msgs, sigs)
    ])
    assert not oracle.any()
    np.testing.assert_array_equal(np.asarray(out), oracle)


def test_kernel_mixed_validity_bitmap():
    n = 40
    pubs, msgs, sigs = make_batch(n)
    sigs = list(sigs)
    bad = set(rng.sample(range(n), 13))
    for i in bad:
        b = bytearray(sigs[i]); b[5] ^= 0x40
        sigs[i] = bytes(b)
    out = np.asarray(edops.verify_batch(pubs, msgs, sigs))
    for i in range(n):
        assert out[i] == (i not in bad)


def test_kernel_noncanonical_s_rejected():
    """s >= L must be rejected even when the point equation would hold."""
    pubs, msgs, sigs = make_batch(4)
    sigs = list(sigs)
    s0 = int.from_bytes(sigs[0][32:], "little")
    s_bad = s0 + _edref.L  # same value mod L
    if s_bad < (1 << 256):
        sigs[0] = sigs[0][:32] + s_bad.to_bytes(32, "little")
        out = np.asarray(edops.verify_batch(pubs, msgs, sigs))
        assert not out[0]
        assert out[1:].all()
        # Go/OpenSSL agree
        assert not edkeys.PubKey(pubs[0]).verify_signature(msgs[0], sigs[0])


def test_kernel_bad_pubkey_encoding():
    """A y-coordinate with no valid x (non-square) must be rejected."""
    pubs, msgs, sigs = make_batch(6)
    pubs = list(pubs)
    # find a y that is not on the curve
    y = 2
    while _edref._recover_x(y, 0) is not None:
        y += 1
    pubs[2] = y.to_bytes(32, "little")
    out = np.asarray(edops.verify_batch(pubs, msgs, sigs))
    assert not out[2]
    assert out[0] and out[1] and out[3] and out[4] and out[5]


def test_kernel_zero_and_smallorder():
    """Identity pubkey (y=1) and torsion points must not crash; result must
    match the oracle."""
    pubs, msgs, sigs = make_batch(3)
    pubs, sigs = list(pubs), list(sigs)
    ident = (1).to_bytes(32, "little")  # point (0, 1) = identity
    pubs[0] = ident
    out = np.asarray(edops.verify_batch(pubs, msgs, sigs))
    oracle = np.array([
        _edref.verify(p, m, s) for p, m, s in zip(pubs, msgs, sigs)
    ])
    np.testing.assert_array_equal(out, oracle)


def test_sign_verify_roundtrip_keys_api():
    priv = edkeys.PrivKey.generate()
    msg = b"tendermint_tpu vote"
    sig = priv.sign(msg)
    assert priv.pub_key().verify_signature(msg, sig)
    assert not priv.pub_key().verify_signature(msg + b"!", sig)
    assert len(priv.pub_key().address()) == 20
    # Go 64-byte privkey layout roundtrip
    priv2 = edkeys.PrivKey(priv.bytes())
    assert priv2.pub_key().bytes() == priv.pub_key().bytes()


def test_digit_decomposition():
    """Signed radix-16 digits must recompose to the scalar."""
    scalars = [rng.randrange(edops.L) for _ in range(16)]
    b = np.stack([
        np.frombuffer(s.to_bytes(32, "little"), dtype=np.uint8)
        for s in scalars
    ])
    digits = edops.scalars_to_digits(b)  # (B, 64) int8, balanced
    assert digits.min() >= -8 and digits.max() <= 7
    for i, s in enumerate(scalars):
        val = sum(int(digits[i, j]) << (4 * j) for j in range(64))
        assert val == s


def test_pub_cache_routing(monkeypatch):
    """The device-resident pubkey cache path (verify_batch cache_pubs):
    padding, pipelined chunking, LRU bookkeeping, and host_ok merging —
    kernel stubbed out, so this runs fast on CPU."""
    import jax.numpy as jnp

    from tendermint_tpu.crypto import _edref
    from tendermint_tpu.ops import ed25519 as edops
    from tendermint_tpu.ops import pallas_ed25519 as pe

    calls = []

    def stub(pub_t, rsk, tile=None):
        assert pub_t.shape[0] == 32 and rsk.shape[0] == 96
        assert pub_t.shape[1] == rsk.shape[1]
        calls.append((pub_t.shape, rsk.shape))
        return jnp.ones(rsk.shape[1], dtype=bool)

    monkeypatch.setattr(edops, "_use_pallas", lambda: True)
    monkeypatch.setattr(edops, "PUB_CACHE_MIN", 64)
    monkeypatch.setattr(edops, "SPLIT_CHUNK", 128)
    monkeypatch.setattr(edops, "PALLAS_TILE", 32)
    monkeypatch.setattr(pe, "verify_packed_split_pallas", stub)
    monkeypatch.setattr(edops, "_pub_cache",
                        edops.DeviceLRU(max_entries=edops._PUB_CACHE_MAX))
    monkeypatch.setattr(edops, "_comb_enabled_override", False)

    n = 200
    seeds = [(7000 + i).to_bytes(32, "little") for i in range(n)]
    msgs = [b"cache %d" % i for i in range(n)]
    pubs = [_edref.pubkey_from_seed(s) for s in seeds]
    sigs = [bytearray(_edref.sign(s, m)) for s, m in zip(seeds, msgs)]
    sigs[9] = sigs[9][:32] + b"\xff" * 32  # non-canonical s -> host_ok False
    sigs = [bytes(s) for s in sigs]

    out = edops.verify_batch(pubs, msgs, sigs, cache_pubs=True)
    assert out.shape == (n,)
    assert not out[9] and out.sum() == n - 1  # host_ok merged
    # bucket(200) = 256, SPLIT_CHUNK 128 -> 2 pipelined chunks of 128
    assert calls == [((32, 128), (96, 128))] * 2
    assert len(edops._pub_cache) == 1
    key0, = edops._pub_cache.keys()
    chunks0 = edops._pub_cache.get(key0)
    assert len(chunks0) == 2

    # same set again: cache hit (same chunk objects), two more launches
    edops.verify_batch(pubs, msgs, sigs, cache_pubs=True)
    assert len(edops._pub_cache) == 1
    assert edops._pub_cache.get(key0) is chunks0
    assert len(calls) == 4

    # 4 more distinct sets -> LRU capped at _PUB_CACHE_MAX, oldest evicted
    for j in range(4):
        pubs_j = [pubs[(i + j + 1) % n] for i in range(n)]
        edops.verify_batch(pubs_j, msgs, sigs, cache_pubs=True)
    assert len(edops._pub_cache) == edops._PUB_CACHE_MAX
    assert key0 not in edops._pub_cache
