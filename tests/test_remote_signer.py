"""Remote signer over a socket (reference privval/signer_client_test.go
intent): pubkey fetch, vote/proposal signing, the HRS double-sign guard
refusing REMOTELY, and signer redial after a connection drop."""
from __future__ import annotations

import os
import tempfile
import threading
import time

import pytest

from tendermint_tpu.crypto import ed25519 as edkeys
from tendermint_tpu.privval.file_pv import FilePV
from tendermint_tpu.privval.signer import (RemoteSignerError, SignerClient,
                                           SignerServer)
from tendermint_tpu.types.basic import (BlockID, PartSetHeader,
                                        SignedMsgType, Timestamp)
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.vote import Vote

CHAIN = "signer-chain"


def _mk_vote(h, r, blk=b"\x11" * 32):
    return Vote(type=SignedMsgType.PREVOTE, height=h, round=r,
                block_id=BlockID(hash=blk,
                                 part_set_header=PartSetHeader(1, b"\x22" * 32)),
                timestamp=Timestamp.now(), validator_address=b"\x00" * 20,
                validator_index=0)


def _pair(tmp):
    pv = FilePV(edkeys.PrivKey.generate())
    addr = f"unix://{os.path.join(tmp, 'pv.sock')}"
    client = SignerClient(addr, timeout_s=5.0)
    server = SignerServer(pv, addr)
    server.start()
    return pv, client, server


def test_remote_sign_and_double_sign_guard():
    tmp = tempfile.mkdtemp(prefix="tm_signer_")
    pv, client, server = _pair(tmp)
    try:
        assert client.ping()
        assert client.get_pub_key() == pv.get_pub_key()

        v = _mk_vote(3, 0)
        signed = client.sign_vote(CHAIN, v)
        assert signed.signature
        assert pv.get_pub_key().verify_signature(
            signed.sign_bytes(CHAIN), signed.signature)

        # same HRS, different block -> the REMOTE guard must refuse
        v2 = _mk_vote(3, 0, blk=b"\x99" * 32)
        with pytest.raises(RemoteSignerError):
            client.sign_vote(CHAIN, v2)

        # proposals flow too
        p = Proposal(height=4, round=0, pol_round=-1,
                     block_id=BlockID(hash=b"\x33" * 32,
                                      part_set_header=PartSetHeader(
                                          1, b"\x44" * 32)),
                     timestamp=Timestamp.now())
        sp = client.sign_proposal(CHAIN, p)
        assert sp.signature
    finally:
        client.close()
        server.stop()


def test_node_with_remote_signer_commits_blocks():
    """A single-validator node whose key lives in a separate SignerServer
    (priv_validator_laddr) must still propose/commit blocks."""
    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.cmd.__main__ import main as cli_main
    from tendermint_tpu.config.config import Config
    from tendermint_tpu.consensus.config import test_config as fast_config
    from tendermint_tpu.node import Node

    tmp = tempfile.mkdtemp(prefix="tm_signer_node_")
    home = os.path.join(tmp, "node0")
    cli_main(["--home", home, "init", "--chain-id", "rs-chain"])
    cfg = Config.load(home)
    cfg.home = home
    cfg.consensus = fast_config()
    cfg.rpc.enabled = False
    cfg.p2p.laddr = "127.0.0.1:0"
    cfg.priv_validator_laddr = f"unix://{os.path.join(tmp, 'pv.sock')}"
    cfg.save()

    # the signer process-equivalent: serves the SAME key `init` created
    pv = FilePV.load_or_generate(cfg.priv_validator_key_file(),
                                 cfg.priv_validator_state_file())
    server = SignerServer(pv, cfg.priv_validator_laddr)
    server.start()

    node = Node(Config.load(home), KVStoreApplication())
    try:
        node.start()
        deadline = time.time() + 60
        while time.time() < deadline and node.block_store.height() < 3:
            time.sleep(0.2)
        assert node.block_store.height() >= 3, "no blocks with remote signer"
    finally:
        node.stop()
        server.stop()


def test_signer_redials_after_drop():
    tmp = tempfile.mkdtemp(prefix="tm_signer_")
    pv, client, server = _pair(tmp)
    try:
        assert client.ping()
        # simulate a node-side connection failure
        client._drop()
        # the signer's serve loop notices EOF and redials; the client
        # accepts the fresh connection on the next call
        deadline = time.time() + 10
        ok = False
        while time.time() < deadline and not ok:
            try:
                ok = client.ping()
            except RemoteSignerError:
                time.sleep(0.1)
        assert ok, "signer did not redial"
        signed = client.sign_vote(CHAIN, _mk_vote(9, 1))
        assert signed.signature
    finally:
        client.close()
        server.stop()
