"""Live-vote coalescing window (SURVEY §7 hard part 2 / VERDICT r1 #7):
votes queued at the consensus boundary are signature-verified in one
batched launch; the in-order apply then hits the verified-signature cache
instead of verifying serially."""
from __future__ import annotations

import pytest

from helpers import Node, make_genesis
from tendermint_tpu.consensus.round_types import VoteMessage
from tendermint_tpu.crypto import batch as cbatch
from tendermint_tpu.types.basic import (BlockID, PartSetHeader,
                                        SignedMsgType, Timestamp)
from tendermint_tpu.types.vote import Vote

N_VALS = 150


def _signed_prevotes(gdoc, privs, state, height, round_=0):
    bid = BlockID(hash=bytes([5] * 32),
                  part_set_header=PartSetHeader(1, bytes([6] * 32)))
    votes = []
    vals = state.validators
    by_addr = {p.pub_key().address(): p for p in privs}
    for idx in range(vals.size()):
        addr, val = vals.get_by_index(idx)
        v = Vote(type=SignedMsgType.PREVOTE, height=height, round=round_,
                 block_id=bid, timestamp=Timestamp(1700000100, idx),
                 validator_address=addr, validator_index=idx)
        v.signature = by_addr[addr].sign(v.sign_bytes(gdoc.chain_id))
        votes.append(v)
    return votes


def test_vote_storm_rides_the_batch_path():
    gdoc, privs = make_genesis(N_VALS)
    node = Node(gdoc, privs[0])
    cs = node.cs
    state = cs.state
    votes = _signed_prevotes(gdoc, privs, state, height=cs.rs.height)

    batch = [(VoteMessage(v), f"peer{i}") for i, v in enumerate(votes)]
    h0, m0 = cbatch.verified_sigs.hits, cbatch.verified_sigs.misses
    cs._preverify_votes(batch)
    with cs._mtx:
        for msg, peer_id in batch:
            cs._apply_msg(msg, peer_id)

    # every vote landed
    prevotes = cs.rs.votes.prevotes(cs.rs.round)
    assert prevotes.has_two_thirds_majority()
    assert sum(1 for v in prevotes.votes if v is not None) == N_VALS

    # >90% of the serial verifies were cache hits from the one batch launch
    hits = cbatch.verified_sigs.hits - h0
    misses = cbatch.verified_sigs.misses - m0
    # misses include the batch's own pre-insertion lookups; only the apply
    # phase counts hits, one per vote
    assert hits >= 0.9 * N_VALS, (hits, misses)


def test_invalid_vote_in_storm_still_rejected():
    gdoc, privs = make_genesis(8)
    node = Node(gdoc, privs[0])
    cs = node.cs
    votes = _signed_prevotes(gdoc, privs, cs.state, height=cs.rs.height)
    bad = votes[3]
    bad.signature = bytes([bad.signature[0] ^ 1]) + bad.signature[1:]
    batch = [(VoteMessage(v), "p") for v in votes]
    cs._preverify_votes(batch)
    applied = 0
    with cs._mtx:
        for msg, peer_id in batch:
            try:
                cs._apply_msg(msg, peer_id)
                applied += 1
            except Exception:
                pass
    prevotes = cs.rs.votes.prevotes(cs.rs.round)
    present = [i for i, v in enumerate(prevotes.votes) if v is not None]
    assert 3 not in present and len(present) == 7
