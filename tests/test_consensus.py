"""Consensus state machine end-to-end: block production, multi-validator
agreement, tx inclusion, WAL replay after crash (modeled on reference
consensus/state_test.go + replay_test.go scenarios)."""
import os
import tempfile
import time

import pytest

from tendermint_tpu.consensus.wal import WAL, EndHeightMessage

from helpers import Node, make_genesis, wire, wait_for_height


def test_single_validator_produces_blocks():
    gdoc, privs = make_genesis(1)
    node = Node(gdoc, privs[0], "solo")
    node.start()
    try:
        wait_for_height([node], 3, timeout=30)
        # committed blocks verify and link
        b1 = node.block_store.load_block(1)
        b2 = node.block_store.load_block(2)
        assert b1 is not None and b2 is not None
        assert b2.last_commit is not None
        assert b2.header.last_block_id.hash == b1.hash()
        sc = node.block_store.load_seen_commit(1)
        assert sc is not None and sc.height == 1
    finally:
        node.stop()


def test_four_validators_commit_same_chain():
    gdoc, privs = make_genesis(4)
    nodes = [Node(gdoc, p, f"v{i}") for i, p in enumerate(privs)]
    wire(nodes)
    for n in nodes:
        n.start()
    try:
        wait_for_height(nodes, 3, timeout=45)
        h1 = {n.block_store.load_block(1).hash() for n in nodes}
        h2 = {n.block_store.load_block(2).hash() for n in nodes}
        assert len(h1) == 1 and len(h2) == 1, "nodes disagree on chain"
    finally:
        for n in nodes:
            n.stop()


def test_tx_inclusion_and_app_state():
    gdoc, privs = make_genesis(1)
    node = Node(gdoc, privs[0], "solo-tx")
    node.start()
    try:
        res = node.mempool.check_tx(b"alice=1000")
        assert res.is_ok()
        deadline = time.time() + 30
        while time.time() < deadline:
            if node.app.data.get(b"alice") == b"1000":
                break
            time.sleep(0.05)
        assert node.app.data.get(b"alice") == b"1000"
        assert node.mempool.size() == 0  # removed after commit
    finally:
        node.stop()


def test_three_of_four_liveness():
    """Consensus proceeds with one validator down (2/3+ alive)."""
    gdoc, privs = make_genesis(4)
    nodes = [Node(gdoc, p, f"l{i}") for i, p in enumerate(privs[:3])]
    # node 3 never starts; wire only the live ones
    wire(nodes)
    for n in nodes:
        n.start()
    try:
        wait_for_height(nodes, 2, timeout=60)
    finally:
        for n in nodes:
            n.stop()


def test_wal_written_and_replayable(tmp_path):
    wal_path = str(tmp_path / "cs.wal")
    gdoc, privs = make_genesis(1)
    node = Node(gdoc, privs[0], "walnode", wal_path=wal_path)
    node.start()
    try:
        wait_for_height([node], 2, timeout=30)
    finally:
        node.stop()
    msgs = list(WAL.iter_messages(wal_path))
    assert msgs, "WAL empty"
    ends = [m for m in msgs if isinstance(m, EndHeightMessage)]
    assert any(m.height == 1 for m in ends)
    # torn tail tolerance: truncate mid-frame, iteration still works
    with open(wal_path, "ab") as f:
        f.write(b"\x00\x01\x02")
    msgs2 = list(WAL.iter_messages(wal_path))
    assert len(msgs2) == len(msgs)


def test_crash_recovery_resumes_chain(tmp_path):
    """Stop a node mid-chain; a fresh node over the same stores+WAL resumes
    from the persisted height (handshake-free restart path)."""
    wal_path = str(tmp_path / "cs2.wal")
    gdoc, privs = make_genesis(1)
    node = Node(gdoc, privs[0], "crash1", wal_path=wal_path)
    node.start()
    try:
        wait_for_height([node], 2, timeout=30)
    finally:
        node.stop()
    committed = node.block_store.height()
    assert committed >= 2

    # "restart": same app state is rebuilt by replaying blocks into a fresh
    # app (the reference's handshake replay); here we reuse store+state.
    st = node.state_store.load()
    assert st is not None and st.last_block_height == committed

    from tendermint_tpu.consensus.state import ConsensusState
    from tendermint_tpu.consensus.config import test_config
    from tendermint_tpu.state.execution import BlockExecutor

    # replay blocks into a fresh app to rebuild app state
    from tendermint_tpu.abci.kvstore import KVStoreApplication
    app2 = KVStoreApplication()
    exec2 = BlockExecutor(node.state_store, app2, mempool=node.mempool)
    cs2 = ConsensusState(test_config(), st, exec2, node.block_store,
                         mempool=node.mempool, priv_validator=node.pv,
                         wal_path=wal_path, name="crash2")
    cs2.start()
    try:
        deadline = time.time() + 30
        while time.time() < deadline:
            if node.block_store.height() >= committed + 2:
                break
            time.sleep(0.05)
        assert node.block_store.height() >= committed + 2
    finally:
        cs2.stop()


def test_byzantine_peer_messages_do_not_kill_node():
    """ADVICE r1 high #2: malformed peer proposals/parts must be dropped,
    not escalate to CONSENSUS FAILURE; and a peer-supplied part set larger
    than max_bytes must be rejected (ADVICE r1 medium #2)."""
    from tendermint_tpu.crypto import merkle
    from tendermint_tpu.types.basic import BlockID, PartSetHeader, Timestamp
    from tendermint_tpu.types.part_set import Part
    from tendermint_tpu.types.proposal import Proposal

    gdoc, privs = make_genesis(1)
    node = Node(gdoc, privs[0], name="victim")
    node.start()
    try:
        # 1. proposal with a bogus signature and absurd part-set total
        evil_psh = PartSetHeader(total=1 << 30, hash=b"\xEE" * 32)
        evil = Proposal(height=1, round=0, pol_round=-1,
                        block_id=BlockID(b"\xEE" * 32, evil_psh),
                        timestamp=Timestamp.now(), signature=b"\x01" * 64)
        node.cs.set_proposal(evil, peer_id="attacker")

        # 2. garbage block part with an unverifiable proof
        bad_part = Part(index=0, bytes_=b"\xFF" * 100,
                        proof=merkle.Proof(total=1, index=0,
                                           leaf_hash=b"\x00" * 32, aunts=[]))
        node.cs.add_block_part(1, 0, bad_part, peer_id="attacker")

        # the node keeps committing blocks regardless
        wait_for_height([node], 2, timeout=30)
        assert node.cs.is_running()
    finally:
        node.stop()


def test_stale_round_own_part_not_fatal():
    """A block part queued internally for round r that arrives after the
    node moved to a different round (different part-set header) fails the
    merkle proof check — that must be squelched, not treated as consensus
    failure (reference consensus/state.go:837-841 'received block part from
    wrong round'; regression for the socket-localnet fatality)."""
    from tendermint_tpu.crypto import merkle
    from tendermint_tpu.types.part_set import Part

    gdoc, privs = make_genesis(1)
    node = Node(gdoc, privs[0], name="stale")
    node.start()
    try:
        wait_for_height([node], 2, timeout=30)
        # internal (peer_id="") part for a round the node is not in: the
        # proof cannot match the current header, but round mismatch makes
        # it a stale-message drop, not an invariant violation.
        stale = Part(index=0, bytes_=b"\xAB" * 64,
                     proof=merkle.Proof(total=1, index=0,
                                        leaf_hash=b"\x11" * 32, aunts=[]))
        h = node.cs.rs.height
        node.cs.add_block_part(h, 99, stale, peer_id="")
        wait_for_height([node], h + 1, timeout=30)
        assert node.cs.is_running()
    finally:
        node.stop()


def test_late_own_precommit_from_earlier_round_not_fatal():
    """Regression (round-4 e2e): after a height commits at round r > 0,
    the node's OWN round-0 precommit can still be draining through the
    internal queue; adding it to last_commit (which tracks only round r)
    raised VoteSetError and — because own votes re-raise — killed the
    receive routine, zombifying the node (consensus dead, RPC alive).
    The reference's LastCommit.AddVote refuses cross-round votes without
    error (consensus/state.go:2221)."""
    import copy

    from tendermint_tpu.types.basic import SignedMsgType
    from tendermint_tpu.types.vote import Vote

    gdoc, privs = make_genesis(1)
    node = Node(gdoc, privs[0], name="lateown")
    node.start()
    try:
        wait_for_height([node], 3, timeout=30)
        cs = node.cs
        with cs._mtx:
            rs = cs.rs
            assert rs.last_commit is not None
            prev_h = rs.height - 1
            # a synthetic own precommit for the previous height at a
            # round last_commit does NOT track
            tmpl = None
            for v in rs.last_commit.votes:
                if v is not None:
                    tmpl = copy.copy(v)
                    break
            assert tmpl is not None
            tmpl.round = rs.last_commit.round + 1
        # deliver as an internal (own) message — must be dropped, not
        # raise through the receive routine
        from tendermint_tpu.consensus.round_types import VoteMessage
        cs._internal_queue.put((VoteMessage(tmpl), ""))
        wait_for_height([node], rs.height + 1, timeout=30)
        assert cs.is_running()
        assert tmpl.height == prev_h  # fixture sanity: height-1 precommit
        assert tmpl.type == SignedMsgType.PRECOMMIT
    finally:
        node.stop()


# ---------------------------------------------------------------------------
# TimeoutTicker: schedule-replaces-schedule + stop-while-armed (the
# harness's proposer-kill scenarios lean on "newest schedule wins")
# ---------------------------------------------------------------------------

def _ti(duration, height=1, round_=0):
    from tendermint_tpu.consensus.round_types import Step, TimeoutInfo
    return TimeoutInfo(duration=duration, height=height, round=round_,
                       step=Step.PROPOSE)


def test_ticker_newer_schedule_replaces_older():
    """Two schedules racing: only the NEWER TimeoutInfo may deliver,
    even though the older timer had the shorter duration and was armed
    first."""
    from tendermint_tpu.consensus.ticker import TimeoutTicker
    fired = []
    t = TimeoutTicker(fired.append)
    try:
        t.schedule(_ti(0.3, height=1))      # stale: replaced below
        t.schedule(_ti(0.05, height=2))     # newest wins
        deadline = time.time() + 5
        while not fired and time.time() < deadline:
            time.sleep(0.005)
        time.sleep(0.4)  # past the stale timer's duration
        assert [ti.height for ti in fired] == [2], fired
    finally:
        t.stop()


def test_ticker_stale_fire_is_dropped():
    """The cancel() race made deterministic: a timer whose callback has
    already been invoked cannot be cancelled, so _fire must drop by
    generation.  Simulate the raced thread by calling _fire with the
    superseded generation directly."""
    from tendermint_tpu.consensus.ticker import TimeoutTicker
    fired = []
    t = TimeoutTicker(fired.append)
    try:
        t.schedule(_ti(60.0, height=1))
        stale_gen = t._gen
        t.schedule(_ti(60.0, height=2))
        # the stale timer's callback finally runs, after replacement
        t._fire(_ti(60.0, height=1), stale_gen)
        assert fired == []
        # the current generation still delivers
        t._fire(_ti(60.0, height=2), t._gen)
        assert [ti.height for ti in fired] == [2]
    finally:
        t.stop()


def test_ticker_stop_while_armed():
    """stop() with a pending timer: nothing fires, even via the
    already-queued-callback race, and later schedules are no-ops."""
    from tendermint_tpu.consensus.ticker import TimeoutTicker
    fired = []
    t = TimeoutTicker(fired.append)
    t.schedule(_ti(0.05, height=1))
    armed_gen = t._gen
    t.stop()
    time.sleep(0.2)
    assert fired == []
    # a callback that was already past cancel() when stop() ran
    t._fire(_ti(0.05, height=1), armed_gen)
    assert fired == []
    t.schedule(_ti(0.01, height=3))  # schedule-after-stop: no-op
    time.sleep(0.1)
    assert fired == [] and t._timer is None
