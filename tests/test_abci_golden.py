"""ABCI socket interop proof (VERDICT r3 #7): golden Request/Response
frames generated from the REFERENCE proto schemas (scripts/
gen_abci_golden.py compiles /root/reference/proto/tendermint/abci/
types.proto with protoc and serializes each message with the official
protobuf runtime).  abci/wire.py must encode byte-identically and
decode the golden bytes back — so a Go/Rust reference app can sit on
the other end of the socket (reference abci/types/messages.go
WriteMessage, abci/client/socket_client.go)."""
from __future__ import annotations

import json
import os

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci import wire

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures",
                        "abci_golden.json")

with open(FIXTURES) as f:
    GOLDEN = json.load(f)


def _internal_for(kind: str, method: str):
    """Rebuild the internal object for each golden case — the same
    values scripts/gen_abci_golden.py used."""
    from tendermint_tpu.types.basic import (BlockID, PartSetHeader,
                                            Timestamp)
    from tendermint_tpu.types.block import Consensus, Header
    H = Header(
        version=Consensus(block=11, app=1), chain_id="golden-chain",
        height=42, time=Timestamp(1700000100, 500),
        last_block_id=BlockID(b"\x11" * 32, PartSetHeader(2, b"\x22" * 32)),
        last_commit_hash=b"\x33" * 32, data_hash=b"\x44" * 32,
        validators_hash=b"\x55" * 32, next_validators_hash=b"\x66" * 32,
        consensus_hash=b"\x77" * 32, app_hash=b"\x88" * 32,
        last_results_hash=b"\x99" * 32, evidence_hash=b"\xAA" * 32,
        proposer_address=b"\xBB" * 20)
    snap = abci.Snapshot(height=20, format=1, chunks=3, hash=b"\xF0" * 32,
                         metadata=b"meta")
    ev = abci.Event("app", {"key": "k1", "creator": "kvstore"})
    mis = abci.Misbehavior(type=1, validator_address=b"\xCC" * 20,
                           validator_power=10, height=40,
                           time_seconds=1700000050, time_nanos=25,
                           total_voting_power=30)

    class _V:
        def __init__(self, address, voting_power):
            self.address = address
            self.voting_power = voting_power

    reqs = {
        "echo": "hello-golden",
        "flush": None,
        "info": abci.RequestInfo("0.34.20", 11, 8),
        "init_chain": abci.RequestInitChain(
            time_seconds=1700000100, chain_id="golden-chain",
            consensus_params=abci.ConsensusParamsUpdate(22020096, -1),
            validators=[abci.ValidatorUpdate("ed25519", b"\x01" * 32, 10),
                        abci.ValidatorUpdate("secp256k1", b"\x02" * 33, 5)],
            app_state_bytes=b'{"k":"v"}', initial_height=1),
        "query": abci.RequestQuery(b"key1", "/store", 7, True),
        "begin_block": abci.RequestBeginBlock(
            hash=H.hash(), header_proto=H.proto(),
            last_commit_votes=[(_V(b"\xDD" * 20, 10), True),
                               (_V(b"\xEE" * 20, 20), False)],
            byzantine_validators=[mis]),
        "check_tx": abci.RequestCheckTx(b"tx-bytes",
                                        abci.CheckTxType.RECHECK),
        "deliver_tx": b"deliver-me",
        "end_block": 42,
        "commit": None,
        "list_snapshots": None,
        "offer_snapshot": (snap, b"\xF1" * 32),
        "load_snapshot_chunk": (9, 1, 2),
        "apply_snapshot_chunk": (2, b"chunkdata", "peer-1"),
        "prepare_proposal": abci.RequestPrepareProposal(
            block_data=[b"a", b"bb"], block_data_size=1000),
        "process_proposal": abci.RequestProcessProposal(
            txs=[b"t1", b"t22"], header_proto=H.proto()),
    }
    rsps = {
        "exception": "boom",
        "echo": "hello-golden",
        "flush": None,
        "info": abci.ResponseInfo("{\"size\":1}", "0.1.0", 1, 99,
                                  b"\xAB" * 32),
        "init_chain": abci.ResponseInitChain(
            consensus_params=abci.ConsensusParamsUpdate(2048, 100000),
            validators=[abci.ValidatorUpdate("ed25519", b"\x04" * 32, 7)],
            app_hash=b"\x05" * 32),
        "query": abci.ResponseQuery(
            code=1, log="nope", info="", index=2, key=b"key1",
            value=b"val1", height=7, codespace="app",
            proof_ops=[("ics23:iavl", b"key1", b"\x0A\x01")]),
        "begin_block": abci.ResponseBeginBlock(events=[ev]),
        "check_tx": abci.ResponseCheckTx(
            code=3, data=b"d", log="l", gas_wanted=10, gas_used=5,
            priority=77, sender="s", codespace="cs"),
        "deliver_tx": abci.ResponseDeliverTx(
            code=0, data=b"res", log="ok", gas_wanted=2, gas_used=1,
            events=[ev], codespace=""),
        "end_block": abci.ResponseEndBlock(
            validator_updates=[
                abci.ValidatorUpdate("ed25519", b"\x06" * 32, 0)],
            consensus_param_updates=abci.ConsensusParamsUpdate(4096, -1),
            events=[ev]),
        "commit": abci.ResponseCommit(data=b"\x0C" * 32, retain_height=50),
        "list_snapshots": [snap],
        "offer_snapshot": abci.ResponseOfferSnapshot(
            result=abci.ResponseOfferSnapshot.REJECT_FORMAT),
        "load_snapshot_chunk": b"chunk-bytes",
        "apply_snapshot_chunk": abci.ResponseApplySnapshotChunk(
            result=abci.ResponseApplySnapshotChunk.RETRY,
            refetch_chunks=[1, 3, 5], reject_senders=["bad1", "bad2"]),
        "prepare_proposal": abci.ResponsePrepareProposal(block_data=[b"x"]),
        "process_proposal": abci.ResponseProcessProposal(accept=True),
    }
    return (reqs if kind == "request" else rsps)[method]


@pytest.mark.parametrize("name", sorted(GOLDEN))
def test_encode_matches_reference_bytes(name):
    case = GOLDEN[name]
    golden = bytes.fromhex(case["hex"])
    internal = _internal_for(case["kind"], case["method"])
    mine = (wire.encode_request(case["method"], internal)
            if case["kind"] == "request"
            else wire.encode_response(case["method"], internal))
    assert mine == golden, (
        f"{name}: wire encoding diverges from the reference schema's "
        f"canonical bytes\n golden={golden.hex()}\n mine={mine.hex()}")


@pytest.mark.parametrize("name", sorted(GOLDEN))
def test_decode_roundtrips_reference_bytes(name):
    case = GOLDEN[name]
    golden = bytes.fromhex(case["hex"])
    if case["kind"] == "request":
        method, obj = wire.decode_request(golden)
        reenc = wire.encode_request(method, obj)
    else:
        method, obj = wire.decode_response(golden)
        reenc = wire.encode_response(method, obj)
    assert method == case["method"]
    # decode -> encode must reproduce the reference bytes exactly
    assert reenc == golden, (
        f"{name}: decode/re-encode not stable over reference bytes")
