"""ops/field_secp.py limb arithmetic against a Python-bignum oracle.

GF(2^256 - 2^32 - 977) on radix-2^12 int32 limb vectors is the secp256k1
counterpart of ops/field.py; its docstring promises the int32 bounds are
"regression-checked against a bignum oracle in tests/test_secp_lane.py
rather than re-proved" — this is that file.  Every ring op, predicate and
exponentiation chain is compared to Python integer arithmetic mod p over
structured edge values (0, 1, p-1, p, 2^256-1, fold-boundary patterns)
and seeded random field elements, both as single lanes and batched.
"""
from __future__ import annotations

import random

import numpy as np
import jax.numpy as jnp
import pytest

from tendermint_tpu.ops import field_secp as FS

P = FS.P
rng = random.Random(20260803)

# structured values that stress every fold path: small, the 2^256
# boundary, the p boundary, all-ones limbs, and the fold multipliers'
# weight positions (2^32, 2^40)
EDGE = [0, 1, 2, 976, 977, 978,
        (1 << 32) - 1, 1 << 32, (1 << 32) + 977,
        (1 << 40) - 1, 1 << 40,
        P - 1, P, P + 1, P + 977,
        (1 << 255), (1 << 256) - 1,
        int("aa" * 32, 16), int("55" * 32, 16)]


def _rand(n):
    return [rng.randrange(P) for _ in range(n)]


def _col(xs):
    """ints -> (NLIMB, B) device array (batch on the trailing axis)."""
    return jnp.stack([jnp.asarray(FS.int_to_limbs(x)) for x in xs], axis=1)


def _vals(limbs):
    """(NLIMB, B) limbs -> list of ints (no reduction: callers mod p)."""
    arr = np.asarray(limbs)
    return [FS.limbs_to_int(arr[:, j]) for j in range(arr.shape[1])]


def test_int_limb_roundtrip_and_canonical_range():
    for x in EDGE + _rand(20):
        limbs = FS.int_to_limbs(x)
        assert FS.limbs_to_int(limbs) == x % P
        assert ((limbs >= 0) & (limbs <= FS.MASK)).all(), x


def test_mul_oracle():
    xs = EDGE + _rand(30)
    ys = list(reversed(xs))
    out = _vals(FS.mul(_col(xs), _col(ys)))
    for x, y, got in zip(xs, ys, out):
        assert got % P == (x % P) * (y % P) % P, (x, y)


def test_sqr_oracle():
    xs = EDGE + _rand(30)
    out = _vals(FS.sqr(_col(xs)))
    for x, got in zip(xs, out):
        assert got % P == (x % P) ** 2 % P, x


def test_mul_small_oracle():
    xs = EDGE + _rand(10)
    for k in (0, 1, 2, 8, 977, 250112):
        out = _vals(FS.mul_small(_col(xs), k))
        for x, got in zip(xs, out):
            assert got % P == (x % P) * k % P, (x, k)


def test_add_sub_carry_chain_oracle():
    """Lazy add/sub feed the next mul without an intermediate carry —
    the operand-budget contract of the parent design.  Exercise the
    worst chain the curve formulas produce: (a+b) * (c-d)."""
    a, b = EDGE + _rand(10), list(reversed(EDGE + _rand(10)))
    c, d = _rand(len(a)), _rand(len(a))
    la, lb, lc, ld = map(_col, (a, b, c, d))
    out = _vals(FS.mul(FS.add(la, lb), FS.sub(lc, ld)))
    for i, got in enumerate(out):
        want = (a[i] + b[i]) % P * ((c[i] - d[i]) % P) % P
        assert got % P == want, i


def test_carry_bounds_after_mul():
    """mul's output limbs must be loose-carried (small enough for lazy
    reuse): check against a generous int32-safety envelope."""
    xs = EDGE + _rand(50)
    limbs = np.asarray(FS.mul(_col(xs), _col(list(reversed(xs)))))
    assert np.abs(limbs).max() < (1 << 16), np.abs(limbs).max()


def test_freeze_canonical_oracle():
    """freeze: any loose value -> the canonical representative in
    [0, p), limb-exact against int_to_limbs."""
    xs = EDGE + _rand(30)
    ys = list(reversed(xs))
    loose = FS.mul(_col(xs), _col(ys))  # loose-carried input
    frozen = np.asarray(FS.freeze(loose))
    for j, (x, y) in enumerate(zip(xs, ys)):
        want = FS.int_to_limbs(x * y % P)
        assert (frozen[:, j] == want).all(), (x, y)
        assert ((frozen[:, j] >= 0) & (frozen[:, j] <= FS.MASK)).all()


def test_eq_is_zero_is_odd_oracle():
    xs = [0, 1, P - 1, 977] + _rand(8)
    la = _col(xs)
    # a representation shifted by +p must still compare equal
    lb = la + jnp.asarray(FS.int_to_limbs(0) +
                          np.array([(P >> (12 * i)) & FS.MASK
                                    for i in range(FS.NLIMB)],
                                   dtype=np.int32)).reshape(FS.NLIMB, 1)
    assert np.asarray(FS.eq(la, lb)).all()
    assert np.asarray(FS.is_zero(la)).tolist() == [x % P == 0 for x in xs]
    assert np.asarray(FS.is_odd(la)).tolist() == [x % P % 2 == 1
                                                  for x in xs]


def test_invert_oracle():
    xs = [x for x in EDGE if x % P != 0] + _rand(10)
    inv = FS.invert(_col(xs))
    prod = _vals(FS.mul(_col(xs), inv))
    assert all(v % P == 1 for v in prod)
    for x, got in zip(xs, _vals(inv)):
        assert got % P == pow(x, P - 2, P), x


def test_sqrt_oracle():
    """p = 3 (mod 4): sqrt via a^((p+1)/4) on quadratic residues; the
    caller-side contract is sqr(sqrt(a)) == a, checked here, plus the
    value against the bignum exponentiation."""
    roots = [2, 3, 976, P - 2] + _rand(8)
    qrs = [r * r % P for r in roots]
    s = FS.sqrt(_col(qrs))
    back = _vals(FS.sqr(s))
    for a, got in zip(qrs, back):
        assert got % P == a, a
    for a, got in zip(qrs, _vals(s)):
        assert got % P == pow(a, (P + 1) // 4, P), a


def test_sqrt_non_residue_detectable():
    """Non-residues yield garbage by contract — but sqr(result) != a
    must hold so the caller's check catches them."""
    # find a non-residue (Euler's criterion)
    nr = next(x for x in range(2, 50)
              if pow(x, (P - 1) // 2, P) == P - 1)
    s = FS.sqrt(_col([nr]))
    assert _vals(FS.sqr(s))[0] % P != nr


# ---------------------------------------------------------------------------
# the device lane itself (ops/secp.py) — orphaned in the r5 seed (559 LoC
# imported by nothing, tested by nothing, and its unrolled pow chains
# never even finished compiling); now wired into crypto/batch behind
# TM_TPU_SECP_LANE=1 / [batch_verifier] secp_lane
# ---------------------------------------------------------------------------

def _secp_adversarial_vectors():
    """The consensus-relevant structured encodings (mirrors
    test_native_ec._secp_adversarial_cases): s >= N, r >= P, pubkey
    x >= P, non-square lift_x, off-curve R_x, plus valid controls."""
    from tendermint_tpu.crypto import secp256k1 as secp

    k = secp.PrivKey.gen_from_secret(b"\x77" * 32)
    pub = k.pub_key().bytes()
    m = b"structured secp lane"
    good = k.sign(m)
    r_good, s_good = good[:32], good[32:]

    def be(x):
        return x.to_bytes(32, "big")

    x = 5
    while pow((pow(x, 3, secp.P) + 7) % secp.P,
              (secp.P - 1) // 2, secp.P) == 1:
        x += 1
    off_curve_x = be(x)

    k2 = secp.PrivKey.gen_from_secret(b"\x78" * 32)
    m2 = b"second control"
    return [
        (pub, m, r_good + be(secp.N)),           # s == group order
        (pub, m, r_good + be(secp.N + 1)),       # s > group order
        (pub, m, be(secp.P) + s_good),           # r == field prime
        (pub, m, be(secp.P + 1) + s_good),       # r > field prime
        (pub, m, off_curve_x + s_good),          # R_x: non-square lift_x
        (b"\x02" + be(secp.P), m, good),         # pubkey x >= p
        (b"\x02" + off_curve_x, m, good),        # pubkey off curve
        (pub, m, r_good + be(0)),                # s == 0
        (pub, m, good),                          # control: valid
        (k2.pub_key().bytes(), m2, k2.sign(m2)),  # second valid control
    ]


@pytest.mark.slow
def test_secp_device_lane_bitmap_vs_host_oracles():
    """Bitmap of the TPU lane pinned against the host oracles on the
    adversarial vectors + corrupted-signature sweep.  Slow tier: the
    64-step complete-add ladder costs a multi-minute XLA-on-CPU compile
    (one per process)."""
    from tendermint_tpu.crypto import secp256k1 as secp
    from tendermint_tpu.libs import native
    from tendermint_tpu.ops import secp as secp_ops

    cases = _secp_adversarial_vectors()
    # plus a corrupted sweep over fresh keys
    for i in range(6):
        k = secp.PrivKey.gen_from_secret((0xE100 + i).to_bytes(32, "big"))
        m = b"sweep %d" % i
        s = bytearray(k.sign(m))
        if i % 2:
            s[(i * 11) % 64] ^= 1 << (i % 8)
        cases.append((k.pub_key().bytes(), m, bytes(s)))
    pubs = [c[0] for c in cases]
    msgs = [c[1] for c in cases]
    sigs = [c[2] for c in cases]

    want = [secp.PubKey(p).verify_signature(m, s)
            for p, m, s in zip(pubs, msgs, sigs)]
    assert any(want) and not all(want)
    got = secp_ops.verify_batch_device(pubs, msgs, sigs)
    assert [bool(b) for b in got] == want
    cok = native.secp_verify(pubs, msgs, sigs) \
        if native.get_lib() is not None else None
    if cok is not None:  # the C oracle, where a toolchain exists
        assert [bool(b) for b in got] == [bool(b) for b in cok]


def test_secp_lane_routing_default_on_with_rollback(monkeypatch):
    """crypto/batch routes secp256k1 to the device lane BY DEFAULT
    (ADR-015); TM_TPU_SECP_LANE=0 or config secp_lane=false ->
    set_lane_enabled is the rollback switch back to the host C lane,
    config winning over env both directions.  The bitmap stays exact
    either way.  The heavy kernel is stubbed with the host oracle —
    compile-free, the lane's own bitmap is pinned in the slow-tier test
    above."""
    from tendermint_tpu.crypto import batch as cb
    from tendermint_tpu.crypto import secp256k1 as secp
    from tendermint_tpu.ops import secp as secp_ops

    monkeypatch.setenv("TM_TPU_FORCE_BATCH", "1")
    monkeypatch.setattr(secp_ops, "_lane_override", None)
    routed = []

    def spy(pubs_, msgs_, sigs_):
        routed.append(len(pubs_))
        return np.array([secp.PubKey(p).verify_signature(m, s)
                         for p, m, s in zip(pubs_, msgs_, sigs_)])

    monkeypatch.setattr(secp_ops, "verify_batch_device", spy)

    def run_batch():
        bv = cb.BatchVerifier(tpu_threshold=2)
        want = []
        for i in range(6):
            k = secp.PrivKey.gen_from_secret((0xE200 + i).to_bytes(32,
                                                                   "big"))
            m = b"route optin %d" % i
            s = bytearray(k.sign(m))
            ok = True
            if i == 3:
                s[0] ^= 1
                ok = False
            bv.add(k.pub_key(), m, bytes(s))
            want.append(ok)
        _, bits = bv.verify()
        return want, list(bits)

    # default (no env, no config): routes to the device lane
    monkeypatch.delenv("TM_TPU_SECP_LANE", raising=False)
    want, bits = run_batch()
    assert bits == want and routed == [6]
    # env rollback keeps it on the host C/python lane
    monkeypatch.setenv("TM_TPU_SECP_LANE", "0")
    want, bits = run_batch()
    assert bits == want and routed == [6]
    # config override wins over the env, both directions
    secp_ops.set_lane_enabled(True)
    want, bits = run_batch()
    assert bits == want and routed == [6, 6]
    secp_ops.set_lane_enabled(False)
    monkeypatch.delenv("TM_TPU_SECP_LANE")
    want, bits = run_batch()
    assert bits == want and routed == [6, 6]
