"""Device observatory (crypto/devobs.py, ADR-021): the per-launch
transfer/compute/compile decomposition, its debug surfaces, and the
ISSUE 13 satellites.

The acceptance test drives a real batch through the degradation
runtime onto the CPU mesh path (the one mesh path CI can exercise) and
proves the recorded stage + h2d + compute + collect phases sum to the
launch wall AND sit inside the flight recorder's device.launch /
device.collect spans — with CompileSentinel(max_new_compiles=0)
pinning that the whole proof reuses the shared nb=64 bucket.  Unit
tests pin the ring/inventory/ledger mechanics, the disabled
sub-microsecond no-op (timeit-gated like trace/slo/observatory), the
chaos shed at `devobs.record` with exact-bitmap identity, the
compile-inventory-vs-CompileSentinel agreement, `GET /debug` +
`GET /debug/device` + the debug-device/debug-index CLIs, the [devobs]
config section, the `[slo]` device_launch stream, and bench_trend's
compile-inflation exclusion.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import timeit
import urllib.request

import numpy as np
import pytest

from tendermint_tpu.crypto import devobs
from tendermint_tpu.crypto import ed25519 as edkeys
from tendermint_tpu.crypto.devobs import DevObs
from tendermint_tpu.libs import fail, slo, trace
from tendermint_tpu.libs.metrics import DevObsMetrics, Registry

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean():
    devobs.reset()
    devobs.enable()
    yield
    fail.clear()
    devobs.reset()
    devobs.enable()


def _batch(n, bad=()):
    privs = [edkeys.PrivKey((0xDB00 + i).to_bytes(32, "big"))
             for i in range(n)]
    msgs = [b"devobs %6d" % i for i in range(n)]
    sigs = [p.sign(m) for p, m in zip(privs, msgs)]
    for i in bad:
        s = bytearray(sigs[i])
        s[3] ^= 0x40
        sigs[i] = bytes(s)
    pubs = [p.pub_key().bytes() for p in privs]
    return pubs, msgs, sigs


# ---------------------------------------------------------------------------
# record mechanics: ring bounds, compile inventory, ledger
# ---------------------------------------------------------------------------

def test_ring_bounds_and_compile_inventory():
    o = DevObs(capacity=4, enabled=True)
    assert o.record({"path": "xla", "n": 48, "nb": 64, "shards": 1,
                     "first_launch": True, "wall_s": 2.0})
    for i in range(5):
        o.record({"path": "xla", "n": 40 + i, "nb": 64, "shards": 1,
                  "first_launch": False, "wall_s": 0.01})
    recs = o.records()
    assert len(recs) == 4                      # ring bound holds
    # ring turnover is benign rotation, NOT loss: the records were
    # stored and queued for publication before aging out
    assert o.rotated() >= 2
    assert o.shed_counts()["evict"] == 0
    assert [r["obs_seq"] for r in recs] == [3, 4, 5, 6]
    inv = o.compile_inventory()
    assert len(inv) == 1
    ent = inv[0]
    # the FIRST launch's wall is the attributed compile cost; the five
    # steady-state launches count as cache hits
    assert (ent["path"], ent["nb"], ent["shards"]) == ("xla", 64, 1)
    assert ent["compile_s"] == 2.0
    assert ent["hits"] == 5
    assert ent["first_seen_seq"] == 1
    # a second bucket shape is a second entry
    o.record({"path": "comb", "n": 100, "nb": 128, "shards": 1,
              "first_launch": True, "wall_s": 1.5})
    assert len(o.compile_inventory()) == 2


def test_pending_queue_overflow_is_a_real_shed():
    """With no drainer at all the deferred-publication queue drops its
    oldest UNPUBLISHED records — that IS loss, counted in shed{evict}
    (unlike benign ring rotation)."""
    from tendermint_tpu.crypto.devobs import _MAX_PENDING

    o = DevObs(capacity=4, enabled=True)
    for i in range(_MAX_PENDING + 10):
        o.record({"path": "xla", "n": 1, "nb": 64, "wall_s": 0.001})
    assert len(o._pending) <= _MAX_PENDING
    assert o.shed_counts()["evict"] >= 10


def test_device_block_totals_survive_ring_rotation():
    """device_block's compile_frac reads the lifetime totals (diffed
    against a cursor when one is given), so a run whose first-launch
    compile records aged out of the ring still reports the true compile
    share (the bench_trend compile-inflation exclusion depends on it) —
    and the ring-scoped phase sums are honestly labeled as a `window`
    with their own launch count."""
    o = DevObs(capacity=4, enabled=True)
    o._metrics = DevObsMetrics(Registry("devobs_totals"))
    cur0 = o.cursor()
    o.record({"path": "xla", "n": 64, "nb": 64, "shards": 1,
              "first_launch": True, "wall_s": 9.0})
    for i in range(20):                        # rotate the compile out
        o.record({"path": "xla", "n": 64, "nb": 64, "shards": 1,
                  "first_launch": False, "wall_s": 0.05,
                  "compute_s": 0.04})
    assert all(not r["first_launch"] for r in o.records())
    for blk in (o.device_block(), o.device_block(since=cur0)):
        assert blk["launches"] == 21
        assert blk["compile_s"] == pytest.approx(9.0)
        assert blk["compile_frac"] == pytest.approx(9.0 / 10.0)
        # the window decomposes only what the ring still holds
        assert blk["window"]["launches"] == 4
        assert blk["window"]["compute_s"] == pytest.approx(0.16)


def test_ledger_levels_and_high_water():
    o = DevObs(capacity=4, enabled=True)
    o.ledger_set("table_cache", 1000)
    o.ledger_set("table_cache", 400)           # level drops...
    o.ledger_add("staging", 300)
    o.ledger_add("staging", 200)
    o.ledger_add("staging", -500)
    o.ledger_add("staging", -50)               # clamped at zero
    rep = o.ledger_report()
    assert rep["table_cache"] == {"bytes": 400, "peak_bytes": 1000}
    assert rep["staging"] == {"bytes": 0, "peak_bytes": 500}
    # report orders known pools first and includes everything
    o.ledger_set("exotic_pool", 7)
    keys = list(o.ledger_report())
    assert keys.index("table_cache") < keys.index("exotic_pool")


def test_publish_pending_feeds_metrics_and_slo():
    o = DevObs(capacity=8, enabled=True)
    o._metrics = DevObsMetrics(Registry("devobs_pub"))
    o.ledger_set("staging", 123)
    o.record({"path": "mesh-sharded", "n": 48, "nb": 64, "shards": 8,
              "first_launch": False, "wall_s": 0.5, "stage_s": 0.1,
              "h2d_s": 0.1, "compute_s": 0.2, "collect_s": 0.1,
              "chunk_overlap": 0.75, "shard_imbalance": 1.25,
              "shard_h2d_s": [0.1, 0.3]})
    o.record({"path": "pallas-split", "n": 100, "nb": 128, "shards": 1,
              "first_launch": False, "wall_s": 0.3, "h2d_s": 0.1,
              "drain_s": 0.2})
    slo.reset()
    slo.enable(targets={"device_launch": 0.001})
    try:
        o.publish_pending()
        m = o._metrics
        assert m.device_transfer.count(path="mesh-sharded") == 1
        assert m.device_compute.total(path="mesh-sharded") == \
            pytest.approx(0.2)
        assert m.device_stage.count(path="mesh-sharded") == 1
        assert m.device_collect.count(path="mesh-sharded") == 1
        # a double-buffered path's merged final wait lands in the drain
        # histogram, never mislabeled as collect
        assert m.device_drain.count(path="pallas-split") == 1
        assert m.device_collect.count(path="pallas-split") == 0
        assert m.chunk_overlap.value() == 0.75
        # the companion freshness gauge advances with the launch's
        # observatory seq, so the control plane can tell "busy path
        # republishing the same ratio" from "idle path"
        assert m.chunk_overlap_seq.value() == 1.0
        assert m.shard_imbalance.value() == 1.25
        # per-shard put walls [0.1, 0.3]: max/mean = 0.3/0.2
        assert m.shard_h2d_imbalance.value() == pytest.approx(1.5)
        assert m.hbm_resident.value(pool="staging") == 123
        assert m.compile_cache_entries.value() == 2
        # the [slo] device_launch stream saw both walls, and the
        # hundreds-of-ms launches burn the 1 ms p99 budget
        rep = slo.stream_report("device_launch")
        assert rep is not None and rep["n"] == 2
        assert rep["burn_rate"] == pytest.approx(100.0)
    finally:
        slo.disable()
        slo.reset()


def test_disabled_is_noop_and_sub_microsecond():
    """record() is called on every device launch unconditionally, so
    the disabled path must stay sub-microsecond — the same gate trace /
    slo / the consensus observatory carry.  min-of-repeats dodges CI
    load spikes."""
    devobs.disable()
    try:
        dummy = {"path": "xla", "n": 1, "nb": 64, "wall_s": 0.1}
        assert devobs.record(dummy) is False
        devobs.ledger_add("staging", 100)
        assert devobs.records() == []
        assert devobs.ledger_report() == {}

        n = 20000

        def site():
            devobs.record(dummy)

        per_call = min(timeit.repeat(site, number=n, repeat=5)) / n
        assert per_call < 1e-6, f"disabled record cost {per_call:.2e}s"

        def site_ledger():
            devobs.ledger_add("staging", 1)

        per_call = min(timeit.repeat(site_ledger, number=n,
                                     repeat=5)) / n
        assert per_call < 1e-6, f"disabled ledger cost {per_call:.2e}s"
    finally:
        devobs.enable()


def test_set_config_wins_both_ways_and_resizes():
    o = DevObs(capacity=8, enabled=False)
    o.set_config(enabled=True)
    assert o.is_enabled()
    for i in range(6):
        o.record({"path": "xla", "n": i, "nb": 64, "wall_s": 0.1})
    o.set_config(capacity=3)
    assert o.capacity == 3 and len(o.records()) == 3
    o.set_config(enabled=False)                 # config disables too
    assert not o.is_enabled()
    o.set_config(capacity=5)                    # None leaves enabled alone
    assert not o.is_enabled() and o.capacity == 5


# ---------------------------------------------------------------------------
# the acceptance proof: CPU mesh decomposition + span agreement
# ---------------------------------------------------------------------------

def test_mesh_decomposition_sums_to_wall_and_agrees_with_spans():
    """On the production CPU mesh path (the overlapped compact ladder,
    "mesh-xla" since ADR-027) the launch record carries the overlapped
    decomposition — host stage, summed per-shard device_put wall, the
    chunk_overlap ratio and the merged drain — each phase bounded by
    the recorded wall (an overlapped pipeline's phases deliberately do
    NOT tile the wall: H2D hides behind compute), the psum'd all_valid
    verdict, per-shard rows/imbalance, and the record sits inside the
    flight recorder's device.launch/device.collect spans.  The whole
    proof reuses the shared nb=64 bucket (CompileSentinel
    max_new_compiles=0)."""
    from tendermint_tpu.crypto import degrade
    from tendermint_tpu.devtools.tmlint.runtime import CompileSentinel
    from tendermint_tpu.ops import ed25519 as edops
    from tendermint_tpu.parallel import sharding

    assert sharding.data_plane() is not None, "virtual CPU mesh absent"
    pubs, msgs, sigs = _batch(48)
    # warm: the mesh bucket compile (if this process hasn't paid it
    # yet) must not land inside the measured/asserted launch
    assert edops.verify_batch(pubs, msgs, sigs).all()

    devobs.reset()
    sentinel = CompileSentinel(max_new_compiles=0).start()
    trace.enable()
    rt = degrade.configure(registry=Registry("devobs_acc"))
    try:
        out = rt.run("batch.ed25519",
                     lambda: edops.verify_batch(pubs, msgs, sigs),
                     lambda: np.ones(len(pubs), dtype=bool))
        assert np.asarray(out).all()
        sentinel.check()  # no foreign bucket, no new compile

        recs = [r for r in devobs.records()
                if r.get("path") == "mesh-xla"]
        assert recs, devobs.records()
        rec = recs[-1]
        # the overlapped decomposition: each phase is a real sub-wall
        # of the launch, but their sum is only BOUNDED by the wall —
        # the per-shard puts of chunk j+1 hide behind chunk j's compute
        for k in ("stage_s", "h2d_s", "drain_s"):
            assert 0 <= rec[k] <= rec["wall_s"] + 0.05, (k, rec)
        assert 0.0 <= rec["chunk_overlap"] <= 1.0
        # the psum'd verdict bit is part of the record even when the
        # batch is clean (the global plane's cross-process contract)
        assert rec["all_valid"] is True
        # per-shard H2D walls: one put wall per mesh position
        assert len(rec["shard_h2d_s"]) == 8
        assert all(w >= 0 for w in rec["shard_h2d_s"])
        # per-shard real-row accounting: 48 rows over 8 shards of 8
        # lanes — six full shards, two pure-pad shards
        assert rec["shard_rows"] == [8, 8, 8, 8, 8, 8, 0, 0]
        assert rec["shard_imbalance"] == pytest.approx(8 / 6)
        assert rec["nb"] == 64 and rec["shards"] == 8

        # span agreement: the launch record was stamped inside the
        # degradation runtime's device.launch span (the dispatch runs
        # on the lane worker under it) and before device.collect
        # settled — all on the one monotonic clock
        evs = trace.snapshot()
        launch = [e for e in evs if e["name"] == "device.launch"
                  and e["attrs"].get("site") == "batch.ed25519"][-1]
        collect = [e for e in evs if e["name"] == "device.collect"
                   and e["attrs"].get("site") == "batch.ed25519"][-1]
        l0 = launch["ts_ns"] / 1e9
        l1 = l0 + launch["dur_ns"] / 1e9
        assert l0 <= rec["t_mono"] <= l1 + 0.05
        assert rec["wall_s"] <= launch["dur_ns"] / 1e9 + 0.05
        c0 = collect["ts_ns"] / 1e9
        c1 = c0 + collect["dur_ns"] / 1e9
        assert c0 <= rec["t_mono"] <= c1 + 0.05
    finally:
        degrade.reset()
        trace.disable()
        trace.reset()


def test_compile_inventory_agrees_with_compile_sentinel():
    """The inventory keys are exactly ops/ed25519._seen_buckets' —
    every (path, nb, shards) the observatory attributes a compile to
    must be a bucket the CompileSentinel would account, and a launch
    recorded through _record_launch lands in BOTH."""
    from tendermint_tpu.devtools.tmlint.runtime import CompileSentinel
    from tendermint_tpu.ops import ed25519 as edops

    pubs, msgs, sigs = _batch(16)
    devobs.reset()
    assert edops.verify_batch(pubs, msgs, sigs).all()
    inv = devobs.compile_inventory()
    assert inv, "no launch recorded"
    keys = {(e["path"], e["nb"], e["shards"]) for e in inv}
    seen = CompileSentinel._seen_buckets()
    assert keys <= seen, (keys, seen)
    for e in inv:
        assert CompileSentinel.bucket_allowed(e["nb"], e["shards"]), e


# ---------------------------------------------------------------------------
# chaos: a recording fault sheds, the launch and bitmap are untouched
# ---------------------------------------------------------------------------

def test_chaos_devobs_record_raise_sheds_bitmap_exact():
    from tendermint_tpu.ops import ed25519 as edops

    pubs, msgs, sigs = _batch(24, bad=(3, 17))
    want = np.ones(24, dtype=bool)
    want[[3, 17]] = False
    base = np.asarray(edops.verify_batch(pubs, msgs, sigs))
    assert (base == want).all(), base

    shed0 = DevObsMetrics().devobs_shed.value(reason="chaos")
    devobs.reset()
    fail.set_mode("devobs.record", "raise")
    try:
        out = np.asarray(edops.verify_batch(pubs, msgs, sigs))
        # EXACT bitmap identity: telemetry chaos must be invisible to
        # the verdict (the ADR-020 contract, now on the launch seam)
        assert (out == want).all(), out
        assert fail.fired("devobs.record", "raise") >= 1
        assert devobs.records() == []      # the record really shed
    finally:
        fail.clear("devobs.record")
    # the shed is visible once the deferred publication drains, and the
    # report surface shows the CUMULATIVE count (the endpoint flushes
    # before reading, so a delta view would always render zeros there)
    devobs.publish_pending()
    assert DevObsMetrics().devobs_shed.value(reason="chaos") > shed0
    assert devobs.report()["shed"]["chaos"] >= 1


def test_chaos_devobs_record_latency_swallowed_bitmap_exact():
    """latency:<ms> at devobs.record is absorbed into the recording —
    the launch proceeds, the bitmap is exact, nothing raises."""
    from tendermint_tpu.ops import ed25519 as edops

    pubs, msgs, sigs = _batch(16, bad=(5,))
    want = np.ones(16, dtype=bool)
    want[5] = False
    devobs.reset()
    fail.set_mode("devobs.record", "latency:5")
    try:
        out = np.asarray(edops.verify_batch(pubs, msgs, sigs))
        assert (out == want).all(), out
        assert fail.fired("devobs.record", "latency:5") >= 1
        # the record itself survives a latency injection (only raise
        # sheds): the launch is still fully decomposed
        assert devobs.records()
    finally:
        fail.clear("devobs.record")


# ---------------------------------------------------------------------------
# debug surfaces: GET /debug index, GET /debug/device, the CLIs
# ---------------------------------------------------------------------------

def _get(laddr, path):
    try:
        with urllib.request.urlopen(f"http://{laddr}{path}",
                                    timeout=10) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_debug_index_and_device_endpoint_and_clis(tmp_path, capsys):
    from tendermint_tpu.cmd.__main__ import main as cmd_main
    from tendermint_tpu.libs.pprof import DEBUG_ENDPOINTS, PprofServer
    from tendermint_tpu.ops import ed25519 as edops

    pubs, msgs, sigs = _batch(16)
    devobs.reset()
    assert edops.verify_batch(pubs, msgs, sigs).all()

    srv = PprofServer("127.0.0.1:0")
    srv.start()
    try:
        # satellite: the index page names every registered endpoint
        code, body = _get(srv.laddr, "/debug")
        assert code == 200
        for path, desc in DEBUG_ENDPOINTS:
            assert path in body, path
        assert "device observatory" in body

        code, body = _get(srv.laddr, "/debug/device?last=4")
        assert code == 200
        doc = json.loads(body)
        assert doc["enabled"] is True
        assert doc["launches"], doc
        rec = doc["launches"][-1]
        # the endpoint, the in-process report, and last_launch() agree
        # on the same decomposition
        local = devobs.report(last=4)["launches"][-1]
        assert rec["obs_seq"] == local["obs_seq"]
        assert rec["wall_s"] == pytest.approx(local["wall_s"])
        assert doc["compile_cache"] and "hbm" in doc
        ll = edops.last_launch()
        assert rec["path"] == ll["path"] and rec["nb"] == ll["nb"]

        # the 404 page points at the index now
        code, body = _get(srv.laddr, "/debug/nope")
        assert code == 404 and "/debug" in body

        # debug-device CLI writes the same JSON
        out_file = tmp_path / "device.json"
        cmd_main(["debug-device", "--pprof-laddr", srv.laddr,
                  "--output-file", str(out_file)])
        doc2 = json.loads(out_file.read_text())
        assert doc2["launches"][-1]["obs_seq"] == rec["obs_seq"]
        assert "launch records" in capsys.readouterr().out

        # debug-index CLI mirrors the index page
        cmd_main(["debug-index", "--pprof-laddr", srv.laddr])
        out = capsys.readouterr().out
        for path, _ in DEBUG_ENDPOINTS:
            assert path in out
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# HBM ledger integration: the real pools feed it
# ---------------------------------------------------------------------------

def test_hbm_ledger_real_pools():
    from tendermint_tpu.ops import ed25519 as edops

    devobs.reset()
    # static basepoint comb: accounted on every access, not just build
    by, bm, bt = edops._base_comb()
    rep = devobs.ledger_report()
    want = int(by.nbytes) + int(bm.nbytes) + int(bt.nbytes)
    assert rep["base_comb"]["bytes"] == want > 0

    # pubkey-row cache: put() now charges real bytes (it charged 0
    # before ADR-021, leaving the byte ledger blind to the pool)
    pub_rows = np.zeros((32, 64), dtype=np.uint8)
    pub_rows[0] = np.arange(64, dtype=np.uint8)
    edops._pub_cache_get(pub_rows, 1)
    rep = devobs.ledger_report()
    assert rep["pub_cache"]["bytes"] >= pub_rows.nbytes
    assert edops._pub_cache.total_bytes >= pub_rows.nbytes

    # staging: the mesh launch brackets its in-flight buffers — level
    # returns to zero, the high-water mark records the footprint
    pubs, msgs, sigs = _batch(16)
    assert edops.verify_batch(pubs, msgs, sigs).all()
    rep = devobs.ledger_report()
    assert rep["staging"]["bytes"] == 0
    assert rep["staging"]["peak_bytes"] > 0


# ---------------------------------------------------------------------------
# locksan: record/drain concurrency under the monitor (satellite 5)
# ---------------------------------------------------------------------------

@pytest.mark.locksan
def test_locksan_record_drain_concurrency():
    """A fresh DevObs built UNDER the lockset monitor (so its lock is
    wrapped and ranked), hammered by concurrent recorders + ledger
    writers while the main thread drains — the declared leaf ordering
    holds (the conftest fixture fails the test on any inversion)."""
    o = DevObs(capacity=64, enabled=True)
    o._metrics = DevObsMetrics(Registry("devobs_locksan"))
    stop = threading.Event()

    def recorder(k):
        i = 0
        while not stop.is_set() and i < 500:
            o.record({"path": "xla", "n": 48, "nb": 64, "shards": 1,
                      "first_launch": i == 0, "wall_s": 0.001,
                      "stage_s": 0.0005, "compute_s": 0.0005})
            o.ledger_add("staging", 64 if i % 2 == 0 else -64)
            i += 1

    threads = [threading.Thread(target=recorder, args=(k,))
               for k in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(50):
            o.publish_pending()
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
    o.publish_pending()
    assert o._metrics.device_compute.count(path="xla") > 0
    assert o.shed_counts()["chaos"] == 0


# ---------------------------------------------------------------------------
# config + bench surfaces
# ---------------------------------------------------------------------------

def test_config_devobs_section_and_slo_stream_roundtrip(tmp_path):
    from tendermint_tpu.config.config import Config

    cfg = Config(home=str(tmp_path))
    cfg.devobs.enable = False
    cfg.devobs.capacity = 77
    cfg.slo.device_launch_p99_ms = 12.5
    cfg.validate_basic()
    cfg.save()
    back = Config.load(str(tmp_path))
    assert back.devobs.enable is False
    assert back.devobs.capacity == 77
    assert back.slo.device_launch_p99_ms == 12.5
    assert back.slo.targets_s().get("device_launch") == \
        pytest.approx(0.0125)
    cfg.devobs.capacity = 0
    with pytest.raises(ValueError, match="devobs.capacity"):
        cfg.validate_basic()


def test_device_block_shape_and_bench_trend_compile_exclusion():
    sys.path.insert(0, os.path.join(ROOT, "scripts"))
    import bench_trend

    from tendermint_tpu.ops import ed25519 as edops

    # a real block: launches counted since the cursor, phases summed,
    # compile share computed
    devobs.reset()
    cur0 = devobs.cursor()
    pubs, msgs, sigs = _batch(16)
    assert edops.verify_batch(pubs, msgs, sigs).all()
    blk = devobs.device_block(since=cur0)
    assert blk["launches"] == 1
    # the production mesh launch is the overlapped compact ladder
    # (ADR-027): the window carries the overlapped decomposition, not
    # a serialized compute bracket
    assert blk["wall_s"] > 0
    assert "h2d_s" in blk["window"] and "drain_s" in blk["window"]
    assert 0.0 <= blk["compile_frac"] <= 1.0
    assert blk["compile_cache_entries"] >= 1
    assert blk["window"]["paths"]
    # a cursor past the launch sees nothing — the bench_report
    # per-config isolation
    assert devobs.device_block(since=devobs.cursor()) \
        .get("launches") == 0

    # satellite: bench_trend excludes compile-inflated rounds from the
    # REGRESSION-vs-best baseline (a cold compile cache measured 9x
    # slow must not poison later rounds OR set a bogus best)
    obs = [
        {"label": "r01", "value": 50_000.0, "rc": 0,
         "device": {"compile_frac": 0.85}},      # compile-dominated
        {"label": "r02", "value": 40_000.0, "rc": 0,
         "device": {"compile_frac": 0.01}},      # honest capture
        {"label": "r03", "value": 39_000.0, "rc": 0},  # no block: legacy
    ]
    rows = bench_trend.trend_rows(obs, 0.05)
    assert rows[0]["flag"].startswith("compile-inflated")
    # the inflated 50k did NOT become best: the honest 40k is best, and
    # 39k is only ~2.5% below it (not the 22% a 50k best would imply)
    assert rows[1]["flag"] == "best"
    assert not rows[2]["flag"].startswith("REGRESSION")
    # a genuine later regression against the honest best still flags
    rows2 = bench_trend.trend_rows(
        obs + [{"label": "r04", "value": 30_000.0, "rc": 0}], 0.05)
    assert rows2[3]["flag"].startswith("REGRESSION")
