"""Config surface parity: TOML round-trip of the operator knobs and
ValidateBasic-style rejection of nonsense (reference config/config.go
ValidateBasic per section, :939-956 for consensus; VERDICT r3 #9)."""
from __future__ import annotations

import pytest

from tendermint_tpu.config.config import Config
from tendermint_tpu.e2e.manifest import manifest_from_dict


def test_toml_roundtrip_preserves_new_knobs(tmp_path):
    cfg = Config(home=str(tmp_path), moniker="knobs")
    cfg.consensus.timeout_commit = 2.5
    cfg.mempool.size = 1234
    cfg.mempool.cache_size = 777
    cfg.mempool.max_txs_bytes = 9_000_000
    cfg.mempool.keep_invalid_txs_in_cache = True
    cfg.p2p.send_rate = 1_000_000
    cfg.p2p.recv_rate = 2_000_000
    cfg.p2p.dial_timeout_s = 1.5
    cfg.p2p.handshake_timeout_s = 7.0
    cfg.rpc.max_body_bytes = 65536
    cfg.batch_verifier.secp_lane = False   # non-default (rollback)
    cfg.batch_verifier.host_pool_workers = 6
    cfg.block_pipeline.enable = False      # non-default (ADR-017)
    cfg.block_pipeline.depth = 7
    cfg.block_pipeline.group_commit_heights = 24
    cfg.slo.enable = True                  # non-default (ADR-016)
    cfg.slo.window = 2048
    cfg.slo.consensus_p99_ms = 5.0
    cfg.slo.mempool_p99_ms = 250.0
    cfg.slo.block_interval_p99_ms = 1500.0  # observatory streams
    cfg.slo.apply_p99_ms = 40.0             # (ADR-020)
    cfg.mempool.ingress_enable = False     # non-default (ADR-018)
    cfg.mempool.ingress_queue = 321
    cfg.mempool.ingress_workers = 3
    cfg.mempool.ingress_batch = 17
    cfg.mempool.ingress_rate_per_s = 125.5
    cfg.mempool.ingress_burst = 9
    cfg.mempool.ingress_recheck_slice = 33
    cfg.save()
    back = Config.load(str(tmp_path))
    assert back.consensus.timeout_commit == 2.5
    assert back.mempool.size == 1234
    assert back.mempool.cache_size == 777
    assert back.mempool.max_txs_bytes == 9_000_000
    assert back.mempool.keep_invalid_txs_in_cache is True
    assert back.p2p.send_rate == 1_000_000
    assert back.p2p.recv_rate == 2_000_000
    assert back.p2p.dial_timeout_s == 1.5
    assert back.p2p.handshake_timeout_s == 7.0
    assert back.rpc.max_body_bytes == 65536
    assert back.batch_verifier.secp_lane is False
    assert back.batch_verifier.host_pool_workers == 6
    assert back.block_pipeline.enable is False
    assert back.block_pipeline.depth == 7
    assert back.block_pipeline.group_commit_heights == 24
    assert back.mempool.ingress_enable is False
    assert back.mempool.ingress_queue == 321
    assert back.mempool.ingress_workers == 3
    assert back.mempool.ingress_batch == 17
    assert back.mempool.ingress_rate_per_s == 125.5
    assert back.mempool.ingress_burst == 9
    assert back.mempool.ingress_recheck_slice == 33
    assert Config(home=str(tmp_path)).mempool.ingress_enable is True
    assert Config(home=str(tmp_path)).mempool.ingress_queue == 8192
    assert back.slo.enable is True
    assert back.slo.window == 2048
    assert back.slo.consensus_p99_ms == 5.0
    assert back.slo.mempool_p99_ms == 250.0
    assert back.slo.block_interval_p99_ms == 1500.0
    assert back.slo.apply_p99_ms == 40.0
    # only the set targets appear, converted ms -> seconds
    assert back.slo.targets_s() == {"consensus": 0.005, "mempool": 0.25,
                                    "block_interval": 1.5,
                                    "apply": 0.04}
    # and the shipped defaults survive a round trip too
    assert Config(home=str(tmp_path)).batch_verifier.secp_lane is True
    assert Config(home=str(tmp_path)).slo.enable is False
    assert Config(home=str(tmp_path)).block_pipeline.enable is True
    assert Config(home=str(tmp_path)).block_pipeline.depth == 4
    assert Config(home=str(tmp_path)).block_pipeline.group_commit_heights \
        == 8
    back.validate_basic()


@pytest.mark.parametrize("mutate,wants", [
    (lambda c: setattr(c.consensus, "timeout_commit", -1.0), "consensus"),
    (lambda c: setattr(c.consensus, "timeout_propose_delta", -0.1),
     "consensus"),
    (lambda c: setattr(c.mempool, "size", 0), "mempool"),
    (lambda c: setattr(c.mempool, "max_txs_bytes", -5), "mempool"),
    (lambda c: setattr(c.mempool, "version", "v9"), "mempool"),
    (lambda c: setattr(c.mempool, "ingress_queue", 0), "mempool"),
    (lambda c: setattr(c.mempool, "ingress_workers", -1), "mempool"),
    (lambda c: setattr(c.mempool, "ingress_batch", 0), "mempool"),
    (lambda c: setattr(c.mempool, "ingress_rate_per_s", -0.5), "mempool"),
    (lambda c: setattr(c.mempool, "ingress_burst", -1), "mempool"),
    (lambda c: setattr(c.mempool, "ingress_recheck_slice", 0), "mempool"),
    (lambda c: setattr(c.p2p, "send_rate", 0), "p2p"),
    (lambda c: setattr(c.p2p, "max_num_peers", -1), "p2p"),
    (lambda c: setattr(c.rpc, "max_body_bytes", 0), "rpc"),
    (lambda c: setattr(c.batch_verifier, "host_pool_workers", -2),
     "batch_verifier"),
    (lambda c: setattr(c.block_pipeline, "depth", 0), "block_pipeline"),
    (lambda c: setattr(c.block_pipeline, "group_commit_heights", -1),
     "block_pipeline"),
    (lambda c: setattr(c.slo, "window", 0), "slo"),
    (lambda c: setattr(c.slo, "consensus_p99_ms", -1.0), "slo"),
])
def test_validate_basic_rejects_nonsense(mutate, wants):
    cfg = Config(home="/tmp/x")
    mutate(cfg)
    with pytest.raises(ValueError, match=wants):
        cfg.validate_basic()


def test_node_rejects_invalid_config(tmp_path):
    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.node import Node

    cfg = Config(home=str(tmp_path), moniker="bad")
    cfg.ensure_dirs()
    cfg.consensus.timeout_commit = -3.0
    with pytest.raises(ValueError, match="consensus"):
        Node(cfg, KVStoreApplication(), in_memory=True)


def test_manifest_per_node_overrides(tmp_path):
    m = manifest_from_dict({
        "chain_id": "ovr",
        "node": {
            "v0": {"mempool_size": 42, "timeout_commit": 1.25},
            "v1": {},
        },
    })
    from tendermint_tpu.e2e import E2ERunner
    r = E2ERunner(m, str(tmp_path / "net"))
    r.setup()
    cfg0 = Config.load(r.nodes["v0"].home)
    cfg1 = Config.load(r.nodes["v1"].home)
    assert cfg0.mempool.size == 42
    assert cfg0.consensus.timeout_commit == 1.25
    assert cfg1.mempool.size == 5000
    assert cfg1.consensus.timeout_commit == m.timeout_commit


def test_rpc_aux_laddrs_roundtrip(tmp_path):
    """pprof_laddr / grpc_laddr survive save() -> load() (they gate the
    debug endpoint and the gRPC broadcast API)."""
    from tendermint_tpu.config.config import Config

    cfg = Config(home=str(tmp_path))
    cfg.ensure_dirs()
    cfg.rpc.pprof_laddr = "127.0.0.1:6060"
    cfg.rpc.grpc_laddr = "127.0.0.1:26660"
    cfg.save()
    cfg2 = Config.load(str(tmp_path))
    assert cfg2.rpc.pprof_laddr == "127.0.0.1:6060"
    assert cfg2.rpc.grpc_laddr == "127.0.0.1:26660"


def test_grpc_laddr_requires_rpc_enabled(tmp_path):
    import pytest

    from tendermint_tpu.config.config import RPCConfig

    rc = RPCConfig(grpc_laddr="127.0.0.1:26660", enabled=False)
    with pytest.raises(ValueError, match="grpc_laddr"):
        rc.validate_basic()
