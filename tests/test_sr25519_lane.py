"""TPU sr25519 lane (ops/sr25519.py + ops/ristretto.py): device ristretto
decode/eq + the shared Straus ladder must reproduce schnorrkel semantics
exactly (oracle: crypto/sr25519.verify, itself interop-tested against
go-schnorrkel vectors)."""
from __future__ import annotations

import numpy as np
import pytest

from tendermint_tpu.crypto import sr25519 as srpy
from tendermint_tpu.ops import sr25519 as srlane


def _batch(n):
    privs = [(0xABC0 + i).to_bytes(32, "little") for i in range(n)]
    msgs = [b"sr lane %d" % i for i in range(n)]
    sigs = [srpy.sign(privs[i], msgs[i]) for i in range(n)]
    pubs = [srpy.PrivKey(privs[i]).pub_key().bytes() for i in range(n)]
    return pubs, msgs, sigs


def test_device_lane_matches_oracle():
    n = 24
    pubs, msgs, sigs = _batch(n)
    out = srlane.verify_batch_device(pubs, msgs, sigs)
    assert out.shape == (n,) and out.all()
    # oracle agreement on the valid batch
    assert all(srpy.verify(pubs[i], msgs[i], sigs[i]) for i in range(n))

    # tampered classes: flipped sig byte, wrong message, wrong pubkey,
    # missing schnorrkel marker bit, s >= L
    bad_sigs = [bytearray(s) for s in sigs]
    bad_sigs[3][2] ^= 1            # R tampered
    bad_sigs[5][40] ^= 1           # s tampered
    bad_sigs[7][63] &= 0x7F        # marker cleared
    bad_sigs[9][63] = 0xFF         # s top bits -> s >= L after mask
    bad = [bytes(b) for b in bad_sigs]
    msgs2 = list(msgs)
    msgs2[11] = b"tampered"
    pubs2 = list(pubs)
    pubs2[13] = pubs[14]
    out = srlane.verify_batch_device(pubs2, msgs2, bad)
    want = np.ones(n, dtype=bool)
    for i in (3, 5, 7, 11, 13):
        want[i] = False
    want[9] = srpy.verify(pubs[9], msgs[9], bad[9])  # oracle decides
    for i in range(n):
        assert out[i] == srpy.verify(pubs2[i], msgs2[i], bad[i]), i
    assert (out == want).all()


def test_ristretto_decode_matches_bignum():
    """Device decode vs the pure-Python ristretto reference, including
    non-canonical and odd (negative) encodings."""
    import jax.numpy as jnp

    from tendermint_tpu.crypto import _ristretto as rr
    from tendermint_tpu.ops import field as F
    from tendermint_tpu.ops import ristretto as rops

    enc = []
    # valid encodings: a few multiples of the basepoint
    for i in range(1, 9):
        enc.append(rr.Point.base().mul(i).encode())
    p = 2**255 - 19
    screens = rops.bytes_canonical_nonneg(
        np.stack([np.frombuffer(e, np.uint8) for e in enc]))
    assert screens.all()
    rows = np.stack([np.frombuffer(e, np.uint8) for e in enc])
    pt, ok = rops.decode(srlane._bytes_to_limbs_dev(jnp.asarray(rows)))
    assert np.asarray(ok).all()
    for i, e in enumerate(enc):
        ref = rr.Point.decode(e)
        x = F.limbs_to_int(np.asarray(pt.x)[:, i]) % p
        y = F.limbs_to_int(np.asarray(pt.y)[:, i]) % p
        z = F.limbs_to_int(np.asarray(pt.z)[:, i]) % p
        zi = pow(z, p - 2, p)
        assert (x * zi % p, y * zi % p) == (ref.x % p, ref.y % p), i
    # screens reject: odd value, value >= p, high bit set
    bad_rows = np.stack([
        np.frombuffer((3).to_bytes(32, "little"), np.uint8),        # odd
        np.frombuffer((p + 2).to_bytes(32, "little"), np.uint8),    # >= p
        np.frombuffer((2 + (1 << 255)).to_bytes(32, "little"),
                      np.uint8),                                    # bit255
    ])
    assert not rops.bytes_canonical_nonneg(bad_rows).any()
    # non-square candidate must fail decode on device (s = 2 encodes no
    # point iff the invsqrt check fails; find one such s < 16)
    found_invalid = False
    for sval in range(2, 40, 2):
        if rr.Point.decode(sval.to_bytes(32, "little")) is None:
            row = np.frombuffer(sval.to_bytes(32, "little"), np.uint8)
            _, okv = rops.decode(srlane._bytes_to_limbs_dev(
                jnp.asarray(row[None, :])))
            assert not bool(np.asarray(okv)[0]), sval
            found_invalid = True
            break
    assert found_invalid


def test_batch_verifier_routes_sr25519_to_device(monkeypatch):
    """Mixed ed25519+sr25519 batch through BatchVerifier with the device
    forced: the sr lane must route to ops/sr25519.verify_batch_device and
    the merged bitmap must stay exact per item."""
    monkeypatch.setenv("TM_TPU_FORCE_BATCH", "1")
    from tendermint_tpu.crypto import batch as cb
    from tendermint_tpu.crypto import ed25519 as edkeys

    routed = []
    orig = srlane.verify_batch_device

    def spy(pubs, msgs, sigs):
        routed.append(len(pubs))
        return orig(pubs, msgs, sigs)

    monkeypatch.setattr(srlane, "verify_batch_device", spy)
    bv = cb.BatchVerifier(tpu_threshold=4)
    want = []
    for i in range(8):
        if i % 2 == 0:
            mini = (0x5500 + i).to_bytes(32, "little")
            pk = srpy.PrivKey(mini)
            msg = b"mixed sr %d" % i
            sig = pk.sign(msg)
            if i == 4:
                sig = bytes([sig[0] ^ 1]) + sig[1:]
            bv.add(pk.pub_key(), msg, sig)
            want.append(i != 4)
        else:
            k = edkeys.PrivKey((0x6600 + i).to_bytes(32, "big"))
            msg = b"mixed ed %d" % i
            sig = k.sign(msg)
            if i == 5:
                sig = sig[:-1] + bytes([sig[-1] ^ 1])
            bv.add(k.pub_key(), msg, sig)
            want.append(i != 5)
    all_ok, bits = bv.verify()
    assert routed == [4]
    assert not all_ok and bits.tolist() == want
