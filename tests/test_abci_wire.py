"""ABCI socket proto codec (reference proto/tendermint/abci/types.proto,
abci/types/messages.go framing): golden layouts, roundtrips over every
method, enum offset mapping, and decoder fuzz."""
from __future__ import annotations

import random
import socket

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci import wire
from tendermint_tpu.libs import protodec as pd


def test_request_golden_bytes():
    # Request{deliver_tx=9{tx=1:"ab"}}: tag(9,BYTES)=0x4a
    assert wire.encode_request("deliver_tx", b"ab") == \
        b"\x4a\x04\x0a\x02ab"
    m, req = wire.decode_request(b"\x4a\x04\x0a\x02ab")
    assert (m, req) == ("deliver_tx", b"ab")
    # Request{end_block=10{height=1:7}}: tag(10,BYTES)=0x52
    assert wire.encode_request("end_block", 7) == b"\x52\x02\x08\x07"
    # Request{echo=1{message="hi"}}
    assert wire.encode_request("echo", "hi") == b"\x0a\x04\x0a\x02hi"
    # Request{flush=2{}}
    assert wire.encode_request("flush", None) == b"\x12\x00"


def test_response_golden_bytes():
    # Response{commit=12{data=2:"h"}}: tag(12,BYTES)=0x62
    r = abci.ResponseCommit(data=b"h", retain_height=0)
    assert wire.encode_response("commit", r) == b"\x62\x03\x12\x01h"
    # offer_snapshot enum: internal ACCEPT=0 -> wire 1 (0 = UNKNOWN)
    enc = wire.encode_response(
        "offer_snapshot",
        abci.ResponseOfferSnapshot(abci.ResponseOfferSnapshot.ACCEPT))
    body = pd.get_message(pd.parse(enc), 14)
    assert pd.get_uint(pd.parse(body), 1) == 1
    m, resp = wire.decode_response(enc)
    assert resp.result == abci.ResponseOfferSnapshot.ACCEPT


def test_all_methods_roundtrip():
    from tendermint_tpu.types.basic import Timestamp

    cases = [
        ("echo", "x"),
        ("flush", None),
        ("info", abci.RequestInfo("v1", 11, 8)),
        ("init_chain", abci.RequestInitChain(
            time_seconds=1700000000, chain_id="c",
            consensus_params=abci.ConsensusParamsUpdate(1 << 20, -1),
            validators=[abci.ValidatorUpdate("ed25519", b"\x01" * 32, 10)],
            app_state_bytes=b"{}", initial_height=3)),
        ("query", abci.RequestQuery(b"k", "/store", 9, True)),
        ("check_tx", abci.RequestCheckTx(b"tx", abci.CheckTxType.RECHECK)),
        ("deliver_tx", b"raw"),
        ("end_block", 42),
        ("commit", None),
        ("list_snapshots", None),
        ("offer_snapshot", (abci.Snapshot(9, 1, 4, b"h" * 32, b"m"),
                            b"a" * 32)),
        ("load_snapshot_chunk", (9, 1, 2)),
        ("apply_snapshot_chunk", (2, b"chunk", "peer1")),
        ("prepare_proposal", abci.RequestPrepareProposal(
            block_data=[b"t1", b"t2"], block_data_size=100)),
    ]
    for method, req in cases:
        data = wire.encode_request(method, req)
        m, out = wire.decode_request(data)
        assert m == method
        assert wire.encode_request(m, out) == data, method

    responses = [
        ("info", abci.ResponseInfo("d", "v", 1, 5, b"hash")),
        ("init_chain", abci.ResponseInitChain(
            validators=[abci.ValidatorUpdate("ed25519", b"\x02" * 32, 7)],
            app_hash=b"h")),
        ("query", abci.ResponseQuery(
            code=0, key=b"k", value=b"v", height=5,
            proof_ops=[("ics23:iavl", b"k", b"proofdata")])),
        ("begin_block", abci.ResponseBeginBlock(
            events=[abci.Event("tx", {"k": "v"})])),
        ("check_tx", abci.ResponseCheckTx(code=1, log="bad", priority=9,
                                          sender="s")),
        ("deliver_tx", abci.ResponseDeliverTx(
            code=0, data=b"r", events=[abci.Event("e", {"a": "b"})])),
        ("end_block", abci.ResponseEndBlock(
            validator_updates=[abci.ValidatorUpdate("ed25519",
                                                    b"\x03" * 32, 0)])),
        ("commit", abci.ResponseCommit(b"apphash", 4)),
        ("list_snapshots", [abci.Snapshot(9, 1, 4, b"h", b"m")]),
        ("load_snapshot_chunk", b"chunkbytes"),
        ("apply_snapshot_chunk", abci.ResponseApplySnapshotChunk(
            result=abci.ResponseApplySnapshotChunk.RETRY,
            refetch_chunks=[1, 3], reject_senders=["p1"])),
        ("prepare_proposal", abci.ResponsePrepareProposal([b"t1"])),
        ("process_proposal", abci.ResponseProcessProposal(accept=False)),
        ("exception", "boom"),
    ]
    for method, resp in responses:
        data = wire.encode_response(method, resp)
        m, out = wire.decode_response(data)
        assert m == method
        if method != "exception":
            assert wire.encode_response(m, out) == data, method
        else:
            assert out == "boom"


def test_begin_block_misbehavior_conversion():
    from tendermint_tpu.types.basic import Timestamp

    mis = abci.Misbehavior(type=1, validator_address=b"\x09" * 20,
                           validator_power=10, height=5,
                           time_seconds=1700000000, total_voting_power=40)
    req = abci.RequestBeginBlock(
        hash=b"\x01" * 32, header_proto=b"",
        last_commit_votes=[(abci.ValidatorInfo(b"\x07" * 20, 10), True),
                           (abci.ValidatorInfo(b"\x08" * 20, 10), False)],
        byzantine_validators=[mis])
    data = wire.encode_request("begin_block", req)
    m, out = wire.decode_request(data)
    assert m == "begin_block"
    assert [(v.address, v.voting_power, s)
            for v, s in out.last_commit_votes] == \
        [(b"\x07" * 20, 10, True), (b"\x08" * 20, 10, False)]
    assert out.byzantine_validators == [mis]


def test_framing_roundtrip_over_socketpair():
    a, b = socket.socketpair()
    try:
        payload = wire.encode_request("deliver_tx", b"x" * 300)
        wire.write_frame(a, payload)
        wire.write_frame(a, wire.encode_request("flush", None))
        assert wire.read_frame(b) == payload
        assert wire.decode_request(wire.read_frame(b))[0] == "flush"
        a.close()
        assert wire.read_frame(b) is None  # clean EOF
    finally:
        b.close()


def test_decoders_reject_garbage():
    rng = random.Random(99)
    for n in (1, 5, 40, 200):
        for _ in range(50):
            blob = bytes(rng.randrange(256) for _ in range(n))
            for dec in (wire.decode_request, wire.decode_response):
                try:
                    dec(blob)
                except ValueError:
                    pass
