"""PEX address book + reactor (reference p2p/pex/addrbook_test.go,
pex_reactor_test.go intent): bucket bookkeeping, selection, persistence,
and socket-level address discovery -> dial."""
from __future__ import annotations

import os
import tempfile
import time

import pytest

from tendermint_tpu.p2p import secret_connection
from tendermint_tpu.p2p.key import NodeKey
from tendermint_tpu.p2p.pex import (AddrBook, KnownAddress, PexReactor,
                                    MAX_GET_SELECTION)
from tendermint_tpu.p2p.switch import Switch

# the socket-level discovery tests handshake through SecretConnection,
# which needs the optional `cryptography` package (X25519/HKDF/
# ChaCha20-Poly1305); without it every dial fails the handshake, so
# skip cleanly instead of failing tier-1 (the addr-book logic above is
# covered regardless)
requires_secret_connection = pytest.mark.skipif(
    not secret_connection._HAVE_CRYPTO,
    reason="cryptography package unavailable (secret connection needs "
           "X25519/HKDF/ChaCha20-Poly1305)")


def _nid(i: int) -> str:
    return f"{i:040x}"


def test_addrbook_add_pick_good_bad():
    book = AddrBook()
    assert book.is_empty()
    for i in range(100):
        assert book.add_address(_nid(i), f"10.0.{i}.1:26656",
                                src_id=_nid(1000 + i % 3))
    assert book.size() == 100

    ka = book.pick_address(new_bias_pct=100)
    assert ka is not None and not ka.is_old()

    # promotion to old on mark_good
    book.mark_good(_nid(7))
    assert book._addrs[_nid(7)].is_old()
    # old addresses survive pick with bias 0
    ka = book.pick_address(new_bias_pct=0)
    assert ka.is_old()

    # repeated failed attempts with no success evict
    for _ in range(4):
        book.mark_attempt(_nid(8))
    assert not book.has(_nid(8))
    # but a proven-good address survives attempts
    for _ in range(4):
        book.mark_attempt(_nid(7))
    assert book.has(_nid(7))

    # our own id never enters
    book.add_our_id(_nid(42))
    assert not book.has(_nid(42))
    assert not book.add_address(_nid(42), "1.2.3.4:1")


def test_addrbook_selection_and_ban():
    book = AddrBook()
    for i in range(500):
        # diverse /16 groups so group-bucket eviction doesn't kick in
        book.add_address(_nid(i), f"{10 + i % 100}.{i % 250}.0.1:26656")
    assert book.size() == 500
    sel = book.get_selection()
    # 23% of 500 = 115, within [32, 250]
    assert 32 <= len(sel) <= MAX_GET_SELECTION
    assert len(sel) == 115
    assert len({nid for nid, _ in sel}) == len(sel)

    # one group cannot own the table: same-/16 flood tops out at the
    # per-group bucket capacity instead of growing unboundedly
    flood = AddrBook()
    for i in range(500):
        flood.add_address(_nid(1000 + i), f"10.0.{i % 250}.1:26656")
    assert flood.size() < 200

    book.mark_bad(_nid(3))
    assert not book.has(_nid(3))
    assert book.is_banned(_nid(3))


def test_addrbook_persistence_roundtrip():
    tmp = os.path.join(tempfile.mkdtemp(prefix="tm_pex_"), "addrbook.json")
    book = AddrBook(tmp)
    for i in range(40):
        book.add_address(_nid(i), f"10.0.{i}.1:26656")
    book.mark_good(_nid(5))
    book.save()

    book2 = AddrBook(tmp)
    assert book2.size() == 40
    assert book2._addrs[_nid(5)].is_old()
    # bucket membership was rebuilt
    assert any(_nid(5) in b for b in book2._old)


def _mk_switch(i: int, reactor: PexReactor) -> Switch:
    sw = Switch(NodeKey.generate(), "127.0.0.1:0", network="pex-chain",
                moniker=f"pex{i}")
    sw.add_reactor("PEX", reactor)
    reactor.book.add_our_id(sw.node_key.node_id)
    sw.start()       # starts the reactor too (switch.go:226 OnStart)
    assert reactor.is_running()
    return sw


@requires_secret_connection
def test_pex_discovery_over_sockets():
    """A knows only B; C is connected to B.  A must learn C's address via
    a PEX exchange with B and dial it."""
    books = [AddrBook() for _ in range(3)]
    reactors = [PexReactor(books[i], ensure_period_s=0.5,
                           target_out_peers=4) for i in range(3)]
    switches = [_mk_switch(i, reactors[i]) for i in range(3)]
    try:
        addr = [sw.actual_listen_addr() for sw in switches]
        nid = [sw.node_key.node_id for sw in switches]
        # C dials B and registers its own listen addr so B can share it
        assert switches[2].dial_peer(f"{nid[1]}@{addr[1]}") is not None
        # B's book learns C (add_peer hook uses NodeInfo.listen_addr,
        # which for an inbound peer is its *listener*, not the ephemeral
        # socket) — fix it up directly to the routable one for the test
        books[1].add_address(nid[2], addr[2], src_id=nid[2])
        # A dials B; discovery must pull C's address into A's book and
        # the ensure-peers routine must then dial C
        assert switches[0].dial_peer(f"{nid[1]}@{addr[1]}") is not None
        deadline = time.time() + 15
        while time.time() < deadline:
            if nid[2] in switches[0].peers:
                break
            time.sleep(0.1)
        assert books[0].has(nid[2]), "A never learned C's address"
        assert nid[2] in switches[0].peers, "A never dialed C"
    finally:
        for sw in switches:
            sw.stop()


@requires_secret_connection
def test_pex_request_flood_disconnects():
    """More than one PexRequest per ensure period -> peer dropped and
    banned (reference pex_reactor.go:83 receiveRequest flood guard)."""
    books = [AddrBook(), AddrBook()]
    reactors = [PexReactor(books[0], ensure_period_s=30.0),
                PexReactor(books[1], ensure_period_s=30.0)]
    switches = [_mk_switch(i, reactors[i]) for i in range(2)]
    try:
        addr1 = switches[1].actual_listen_addr()
        nid1 = switches[1].node_key.node_id
        peer = switches[0].dial_peer(f"{nid1}@{addr1}")
        assert peer is not None
        # first request is fine (add_peer may already have sent one —
        # send two more, fast, to trip the guard regardless)
        from tendermint_tpu.p2p.pex import PEX_CHANNEL, PexRequest
        peer.send(PEX_CHANNEL, PexRequest())
        peer.send(PEX_CHANNEL, PexRequest())
        deadline = time.time() + 10
        while time.time() < deadline:
            if switches[1].num_peers() == 0:
                break
            time.sleep(0.1)
        assert switches[1].num_peers() == 0, "flooding peer not dropped"
        assert books[1].is_banned(switches[0].node_key.node_id)
    finally:
        for sw in switches:
            sw.stop()
