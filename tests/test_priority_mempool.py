"""v1 priority mempool tests (reference mempool/v1/mempool_test.go)."""
import numpy as np

from tendermint_tpu.abci import types as abci
from tendermint_tpu.mempool.priority_mempool import PriorityMempool


class PrioApp(abci.Application):
    """CheckTx priority = first byte of the tx; sender = byte 1 (if the
    tx is >= 2 bytes and byte 1 is nonzero)."""

    def check_tx(self, req):
        tx = req.tx
        if not tx:
            return abci.ResponseCheckTx(code=1, log="empty")
        sender = ""
        if len(tx) >= 2 and tx[1]:
            sender = f"s{tx[1]}"
        return abci.ResponseCheckTx(code=abci.CODE_TYPE_OK,
                                    priority=tx[0], gas_wanted=1,
                                    sender=sender)


def tx(priority, sender=0, tag=b""):
    return bytes([priority, sender]) + tag


def test_reap_orders_by_priority_then_fifo():
    mp = PriorityMempool(PrioApp())
    mp.check_tx(tx(5, tag=b"a"))
    mp.check_tx(tx(9, tag=b"b"))
    mp.check_tx(tx(5, tag=b"c"))
    mp.check_tx(tx(7, tag=b"d"))
    reaped = mp.reap_max_bytes_max_gas(-1, -1)
    assert [t[0] for t in reaped] == [9, 7, 5, 5]
    # FIFO within equal priority
    assert reaped[2][2:] == b"a" and reaped[3][2:] == b"c"


def test_eviction_of_lower_priority_when_full():
    mp = PriorityMempool(PrioApp(), size_limit=3)
    mp.check_tx(tx(1, tag=b"low1"))
    mp.check_tx(tx(2, tag=b"low2"))
    mp.check_tx(tx(8, tag=b"high"))
    assert mp.size() == 3
    # higher priority than the floor: evicts the lowest (priority 1)
    res = mp.check_tx(tx(5, tag=b"mid"))
    assert res.is_ok()
    assert mp.size() == 3
    prios = sorted(t[0] for t in mp.reap_max_txs(-1))
    assert prios == [2, 5, 8]
    # lower priority than everything resident: rejected
    res = mp.check_tx(tx(1, tag=b"lower"))
    assert not res.is_ok()
    assert mp.size() == 3


def test_sender_exclusivity():
    mp = PriorityMempool(PrioApp())
    assert mp.check_tx(tx(5, sender=7, tag=b"x")).is_ok()
    res = mp.check_tx(tx(6, sender=7, tag=b"y"))
    assert not res.is_ok() and "sender" in res.log
    # after commit of the first, the sender slot frees up
    mp.lock()
    try:
        mp.update(1, [tx(5, sender=7, tag=b"x")])
    finally:
        mp.unlock()
    assert mp.check_tx(tx(6, sender=7, tag=b"y")).is_ok()


def test_update_removes_committed_and_rechecks():
    class DropAfterHeight(PrioApp):
        def __init__(self):
            self.drop = False

        def check_tx(self, req):
            if self.drop and req.type == abci.CheckTxType.RECHECK:
                return abci.ResponseCheckTx(code=1, log="stale")
            return super().check_tx(req)

    app = DropAfterHeight()
    mp = PriorityMempool(app)
    mp.check_tx(tx(3, tag=b"keep"))
    mp.check_tx(tx(4, tag=b"gone"))
    app.drop = True
    mp.lock()
    try:
        mp.update(2, [tx(3, tag=b"keep")])
    finally:
        mp.unlock()
    # committed tx removed; survivor failed recheck and was dropped
    assert mp.size() == 0


def test_reap_respects_byte_and_gas_caps():
    mp = PriorityMempool(PrioApp())
    for i in range(10):
        mp.check_tx(tx(10 - i, tag=bytes(8)))
    # each tx is 10 bytes + 20 overhead = 30; cap at 3 txs worth
    reaped = mp.reap_max_bytes_max_gas(95, -1)
    assert len(reaped) == 3
    assert [t[0] for t in reaped] == [10, 9, 8]
    reaped = mp.reap_max_bytes_max_gas(-1, 4)
    assert len(reaped) == 4


def test_node_uses_v1_when_configured(tmp_path):
    import argparse
    from tendermint_tpu.cmd.__main__ import cmd_init
    from tendermint_tpu.config.config import Config
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.abci.kvstore import KVStoreApplication

    home = str(tmp_path / "n0")
    cmd_init(argparse.Namespace(home=home, chain_id="prio-chain"))
    cfg = Config.load(home)
    cfg.mempool.version = "v1"
    node = Node(cfg, KVStoreApplication(), in_memory=True)
    assert isinstance(node.mempool, PriorityMempool)
