"""Canonical encodings and validator-set semantics.

Sign-bytes golden vectors are copied from the reference's own test suite
(reference types/vote_test.go:60-131 TestVoteSignBytesTestVectors) — the
encodings must match the Go implementation byte-for-byte.
"""
import hashlib
import random
from fractions import Fraction

import pytest

from tendermint_tpu.crypto import ed25519 as edkeys
from tendermint_tpu.crypto import merkle
from tendermint_tpu.types.basic import (
    BlockID, BlockIDFlag, PartSetHeader, SignedMsgType, Timestamp)
from tendermint_tpu.types.canonical import (
    canonical_proposal_bytes, canonical_vote_bytes)
from tendermint_tpu.types.commit import Commit, CommitSig
from tendermint_tpu.types.validator import Validator
from tendermint_tpu.types.validator_set import (
    CommitVerifyError, NotEnoughVotingPowerError, ValidatorSet)

rng = random.Random(99)


# --- sign bytes golden vectors (reference types/vote_test.go:60-131) -------

ZERO_TS_BYTES = bytes([0xb, 0x8, 0x80, 0x92, 0xb8, 0xc3, 0x98, 0xfe,
                       0xff, 0xff, 0xff, 0x1])


def test_vote_sign_bytes_vector_0():
    # ("", &Vote{}) — zero vote
    got = canonical_vote_bytes("", SignedMsgType.UNKNOWN, 0, 0, BlockID(),
                               Timestamp.zero())
    want = bytes([0xd, 0x2a]) + ZERO_TS_BYTES
    assert got == want


def test_vote_sign_bytes_vector_precommit():
    got = canonical_vote_bytes("", SignedMsgType.PRECOMMIT, 1, 1, BlockID(),
                               Timestamp.zero())
    want = (bytes([0x21, 0x8, 0x2,
                   0x11, 0x1, 0, 0, 0, 0, 0, 0, 0,
                   0x19, 0x1, 0, 0, 0, 0, 0, 0, 0,
                   0x2a]) + ZERO_TS_BYTES)
    assert got == want


def test_vote_sign_bytes_vector_prevote():
    got = canonical_vote_bytes("", SignedMsgType.PREVOTE, 1, 1, BlockID(),
                               Timestamp.zero())
    want = (bytes([0x21, 0x8, 0x1,
                   0x11, 0x1, 0, 0, 0, 0, 0, 0, 0,
                   0x19, 0x1, 0, 0, 0, 0, 0, 0, 0,
                   0x2a]) + ZERO_TS_BYTES)
    assert got == want


def test_vote_sign_bytes_vector_no_type():
    got = canonical_vote_bytes("", SignedMsgType.UNKNOWN, 1, 1, BlockID(),
                               Timestamp.zero())
    want = (bytes([0x1f,
                   0x11, 0x1, 0, 0, 0, 0, 0, 0, 0,
                   0x19, 0x1, 0, 0, 0, 0, 0, 0, 0,
                   0x2a]) + ZERO_TS_BYTES)
    assert got == want


def test_vote_sign_bytes_vector_chain_id():
    got = canonical_vote_bytes("test_chain_id", SignedMsgType.UNKNOWN, 1, 1,
                               BlockID(), Timestamp.zero())
    want = (bytes([0x2e,
                   0x11, 0x1, 0, 0, 0, 0, 0, 0, 0,
                   0x19, 0x1, 0, 0, 0, 0, 0, 0, 0,
                   0x2a]) + ZERO_TS_BYTES
            + bytes([0x32, 0xd]) + b"test_chain_id")
    assert got == want


def test_proposal_vs_vote_sign_bytes_differ():
    v = canonical_vote_bytes("", SignedMsgType.UNKNOWN, 1, 1, BlockID(),
                             Timestamp.zero())
    p = canonical_proposal_bytes("", 1, 1, 0, BlockID(), Timestamp.zero())
    assert v != p  # reference TestVoteProposalNotEq


def test_sign_bytes_with_block_id_roundtrip_sig():
    """A signature over our sign bytes must verify through the key API."""
    priv = edkeys.PrivKey(bytes(range(32)))
    bid = BlockID(hash=bytes(32), part_set_header=PartSetHeader(1, bytes(32)))
    sb = canonical_vote_bytes("chain", SignedMsgType.PRECOMMIT, 5, 2, bid,
                              Timestamp(1700000000, 123456789))
    sig = priv.sign(sb)
    assert priv.pub_key().verify_signature(sb, sig)


# --- merkle ---------------------------------------------------------------

def test_merkle_empty_and_single():
    assert merkle.hash_from_byte_slices([]) == hashlib.sha256(b"").digest()
    leaf = b"hello"
    assert (merkle.hash_from_byte_slices([leaf])
            == hashlib.sha256(b"\x00" + leaf).digest())


def test_merkle_inner_structure():
    items = [b"a", b"b", b"c"]
    l0 = hashlib.sha256(b"\x00a").digest()
    l1 = hashlib.sha256(b"\x00b").digest()
    l2 = hashlib.sha256(b"\x00c").digest()
    left = hashlib.sha256(b"\x01" + l0 + l1).digest()
    want = hashlib.sha256(b"\x01" + left + l2).digest()
    assert merkle.hash_from_byte_slices(items) == want


def test_merkle_proofs():
    items = [f"item{i}".encode() for i in range(11)]
    root, proofs = merkle.proofs_from_byte_slices(items)
    assert root == merkle.hash_from_byte_slices(items)
    for i, p in enumerate(proofs):
        assert p.verify(root, items[i]), i
        assert not p.verify(root, items[(i + 1) % len(items)])


# --- validator set --------------------------------------------------------

def _mkvals(n, power=lambda i: 10):
    out = []
    for i in range(n):
        priv = edkeys.PrivKey(i.to_bytes(32, "big"))
        out.append((priv, Validator.new(priv.pub_key(), power(i))))
    return out


def test_valset_sorted_and_total_power():
    pairs = _mkvals(7, power=lambda i: (i + 1) * 5)
    vs = ValidatorSet([v for _, v in pairs])
    assert vs.total_voting_power() == sum((i + 1) * 5 for i in range(7))
    powers = [v.voting_power for v in vs.validators]
    assert powers == sorted(powers, reverse=True)


def test_proposer_rotation_weighted():
    """Over one full cycle, each validator proposes proportionally to its
    power (the proposer-selection contract, reference
    spec/consensus/proposer-selection.md)."""
    pairs = _mkvals(3, power=lambda i: [1, 2, 3][i])
    vs = ValidatorSet([v for _, v in pairs])
    counts = {}
    for _ in range(60):
        p = vs.get_proposer()
        counts[p.address] = counts.get(p.address, 0) + 1
        vs.increment_proposer_priority(1)
    by_power = {v.address: v.voting_power for _, v in pairs}
    got = sorted(counts.values())
    assert got == [10, 20, 30], (got, counts, by_power)


def test_valset_update_and_remove():
    pairs = _mkvals(4, power=lambda i: 10)
    vs = ValidatorSet([v for _, v in pairs])
    # raise one validator's power
    target = pairs[0][1]
    vs.update_with_change_set(
        [Validator.new(pairs[0][0].pub_key(), 100)])
    assert vs.total_voting_power() == 130
    # remove it (power 0)
    vs.update_with_change_set([Validator.new(pairs[0][0].pub_key(), 0)])
    assert vs.total_voting_power() == 30
    assert not vs.has_address(target.address)


def test_valset_hash_changes_with_membership():
    pairs = _mkvals(4)
    vs = ValidatorSet([v for _, v in pairs])
    h1 = vs.hash()
    vs.update_with_change_set([Validator.new(pairs[0][0].pub_key(), 99)])
    assert vs.hash() != h1


# --- commit verification over the batch data plane ------------------------

CHAIN = "test-chain"


def _make_commit(pairs, height=3, round_=0, absent=(), nil=(), bad=()):
    bid = BlockID(hash=bytes([7] * 32),
                  part_set_header=PartSetHeader(1, bytes([8] * 32)))
    vs = ValidatorSet([v for _, v in pairs])
    sigs = []
    # commit order must match validator-set order; map address -> priv
    by_addr = {v.address: priv for priv, v in pairs}
    for idx, val in enumerate(vs.validators):
        priv = by_addr[val.address]
        if idx in absent:
            sigs.append(CommitSig.absent())
            continue
        flag = BlockIDFlag.NIL if idx in nil else BlockIDFlag.COMMIT
        voted = BlockID() if idx in nil else bid
        ts = Timestamp(1700000000 + idx, idx)
        from tendermint_tpu.types.canonical import canonical_vote_bytes
        sb = canonical_vote_bytes(CHAIN, SignedMsgType.PRECOMMIT, height,
                                  round_, voted, ts)
        sig = priv.sign(sb)
        if idx in bad:
            sig = bytes([sig[0] ^ 1]) + sig[1:]
        sigs.append(CommitSig(flag, val.address, ts, sig))
    return vs, bid, Commit(height, round_, bid, sigs)


def test_verify_commit_all_good():
    pairs = _mkvals(6)
    vs, bid, commit = _make_commit(pairs)
    vs.verify_commit(CHAIN, bid, 3, commit)          # must not raise
    vs.verify_commit_light(CHAIN, bid, 3, commit)
    vs.verify_commit_light_trusting(CHAIN, commit, Fraction(1, 3))


def test_verify_commit_with_absent_and_nil():
    pairs = _mkvals(7)
    vs, bid, commit = _make_commit(pairs, absent={2}, nil={4})
    vs.verify_commit(CHAIN, bid, 3, commit)


def test_verify_commit_bad_sig_identified():
    pairs = _mkvals(6)
    vs, bid, commit = _make_commit(pairs, bad={3})
    with pytest.raises(CommitVerifyError, match=r"wrong signature \(#3\)"):
        vs.verify_commit(CHAIN, bid, 3, commit)


def test_verify_commit_insufficient_power():
    pairs = _mkvals(6)
    vs, bid, commit = _make_commit(pairs, absent={0, 1, 2, 3})
    with pytest.raises(NotEnoughVotingPowerError):
        vs.verify_commit(CHAIN, bid, 3, commit)


def test_verify_commit_light_ignores_bad_sig_after_twothirds():
    """The serial reference exits at 2/3 and never sees later signatures; the
    batched implementation must preserve that acceptance."""
    pairs = _mkvals(6)
    vs, bid, commit = _make_commit(pairs, bad={5})
    # full check rejects...
    with pytest.raises(CommitVerifyError):
        vs.verify_commit(CHAIN, bid, 3, commit)
    # ...light check (prefix crosses 2/3 before index 5) accepts
    vs.verify_commit_light(CHAIN, bid, 3, commit)


def test_verify_commit_wrong_height_and_blockid():
    pairs = _mkvals(4)
    vs, bid, commit = _make_commit(pairs)
    with pytest.raises(CommitVerifyError, match="wrong height"):
        vs.verify_commit(CHAIN, bid, 4, commit)
    other = BlockID(hash=bytes([9] * 32),
                    part_set_header=PartSetHeader(1, bytes([8] * 32)))
    with pytest.raises(CommitVerifyError, match="wrong block ID"):
        vs.verify_commit(CHAIN, other, 3, commit)


def test_light_trusting_different_valset():
    """Commit from a 6-val set verified against a 4-val overlapping set."""
    pairs = _mkvals(6)
    vs, bid, commit = _make_commit(pairs)
    # trusted set = subset of 4 validators (by the same keys)
    sub = ValidatorSet([v for _, v in pairs[:4]])
    sub.verify_commit_light_trusting(CHAIN, commit, Fraction(1, 3))


def test_commit_hash_covers_signatures():
    pairs = _mkvals(4)
    _, _, c1 = _make_commit(pairs)
    _, _, c2 = _make_commit(pairs, nil={1})
    assert c1.hash() != c2.hash()
    assert len(c1.hash()) == 32


def test_commit_sign_bytes_batch_byte_exact():
    """commit_sign_bytes_batch must be byte-identical to the per-index
    canonical_vote_bytes encoder, for both the native C assembler and the
    pure-Python fallback (nil votes, zero nanos, Go-zero timestamps)."""
    from tendermint_tpu.libs import native
    from tendermint_tpu.types.canonical import commit_sign_bytes_batch

    pairs = _mkvals(9)
    vs, bid, commit = _make_commit(pairs, nil={1, 5})
    # edge-case timestamps: zero nanos, Go zero time (negative seconds)
    commit.signatures[2].__dict__["timestamp"] = Timestamp(1700000000, 0)
    commit.signatures[5].__dict__["timestamp"] = Timestamp.zero()
    idxs = list(range(len(commit.signatures)))
    want = [commit.vote_sign_bytes(CHAIN, i) for i in idxs]

    got = commit_sign_bytes_batch(CHAIN, commit, idxs)
    assert len(got) == len(want)
    assert [got[i] for i in idxs] == want

    if native.get_lib() is not None:  # force the no-C fallback too
        orig = native.vote_sign_bytes
        native.vote_sign_bytes = lambda *a, **k: None
        try:
            fb = commit_sign_bytes_batch(CHAIN, commit, idxs)
        finally:
            native.vote_sign_bytes = orig
        assert [fb[i] for i in idxs] == want

    # subsets and duplicates resolve by index
    sub = commit_sign_bytes_batch(CHAIN, commit, [7, 0, 7])
    assert [sub[0], sub[1], sub[2]] == [want[7], want[0], want[7]]
