"""Blocksync: coalesced window replay, pool scheduling, and end-to-end
sync over the reactor message flow (reference blocksync/pool_test.go +
reactor_test.go)."""
from __future__ import annotations

import threading
import time

import pytest

from helpers import build_chain, make_genesis
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.blocksync.pool import BlockPool
from tendermint_tpu.blocksync.replay import (WindowSyncError, block_id_of,
                                             replay_window)
from tendermint_tpu.libs.kvdb import MemDB
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.state import state_from_genesis
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.store.block_store import BlockStore


def _fresh_node(gdoc):
    ex = BlockExecutor(StateStore(MemDB()), KVStoreApplication())
    store = BlockStore(MemDB())
    return ex, store, state_from_genesis(gdoc)


# --- replay core ----------------------------------------------------------

def test_replay_window_coalesced_applies_all():
    gdoc, privs = make_genesis(6)
    blocks, commits, states = build_chain(gdoc, privs, 20)
    ex, store, state = _fresh_node(gdoc)
    # feed in two windows; certifier of block i is commits[i]
    state, n1 = replay_window(ex, store, state, blocks[:12], commits[:12],
                              max_window=16)
    assert n1 == 12
    state, n2 = replay_window(ex, store, state, blocks[12:], commits[12:],
                              max_window=16)
    assert n2 == 8
    assert state.last_block_height == 20
    assert store.height() == 20
    assert state.app_hash == states[-1].app_hash
    # stored blocks round-trip
    assert store.load_block(7).hash() == blocks[6].hash()


def test_replay_window_detects_bad_commit():
    gdoc, privs = make_genesis(4)
    blocks, commits, _ = build_chain(gdoc, privs, 10, tamper_height=6)
    ex, store, state = _fresh_node(gdoc)
    with pytest.raises(WindowSyncError) as ei:
        replay_window(ex, store, state, blocks, commits, max_window=16)
    # heights 1..5 applied; 6's certifying commit is bad
    assert ei.value.height == 6
    assert ei.value.applied == 5
    assert ei.value.state.last_block_height == 5
    # resume with a corrected certifier succeeds
    good_blocks, good_commits, _ = build_chain(gdoc, privs, 10)
    state = ei.value.state
    state, n = replay_window(ex, store, state, good_blocks[5:],
                             good_commits[5:], max_window=16)
    assert n == 5 and state.last_block_height == 10


def test_replay_window_bad_app_hash_rejected():
    gdoc, privs = make_genesis(4)
    blocks, commits, _ = build_chain(gdoc, privs, 5)
    ex, store, state = _fresh_node(gdoc)
    blocks[2].header.app_hash = b"\xEE" * 32  # breaks hash/commit chain
    # first window applies the good prefix (heights 1-2) and stops short
    state, n = replay_window(ex, store, state, blocks, commits, max_window=8)
    assert n == 2 and state.last_block_height == 2
    # the tampered block is now first: strict path attributes it
    with pytest.raises(WindowSyncError) as ei:
        replay_window(ex, store, state, blocks[2:], commits[2:],
                      max_window=8)
    assert ei.value.height == 3
    assert ei.value.applied == 0


def test_replay_window_nonprefix_garbage_signature_rejected():
    """A LastCommit signature AFTER the >2/3 certification prefix must
    still be verified before the enclosing block applies (full
    verify_commit semantics, reference state/validation.go:92) — the
    pre-verified cache may only absorb fully-verified commits."""
    gdoc, privs = make_genesis(4)
    blocks, commits, _ = build_chain(gdoc, privs, 8)
    # equal powers: the light prefix is the first 3 of 4 signatures; corrupt
    # the 4th inside block 5's embedded LastCommit (certifying height 4)
    lc = blocks[4].last_commit
    s = lc.signatures[3]
    lc.signatures[3] = type(s)(s.block_id_flag, s.validator_address,
                               s.timestamp,
                               bytes([s.signature[0] ^ 1])
                               + s.signature[1:])
    blocks[4].header.last_commit_hash = lc.hash()
    blocks[4].fill_header()
    ex, store, state = _fresh_node(gdoc)
    applied_total = 0
    with pytest.raises(WindowSyncError) as ei:
        state, n = replay_window(ex, store, state, blocks, commits,
                                 max_window=16)
        applied_total += n
        # corrupted block 5 changed its hash, so its certifier fails first;
        # either way nothing at or past height 5 may apply
        while True:
            state, n = replay_window(ex, store, state,
                                     blocks[applied_total:],
                                     commits[applied_total:], max_window=16)
            if n == 0:
                break
            applied_total += n
    assert ei.value.height <= 5
    assert ei.value.state is None or ei.value.state.last_block_height < 5


# --- pool -----------------------------------------------------------------

def test_pool_schedules_and_serves_window():
    sent = []
    errs = []
    pool = BlockPool(1, lambda pid, h: sent.append((pid, h)),
                     lambda pid, r: errs.append((pid, r)))
    gdoc, privs = make_genesis(4)
    blocks, commits, _ = build_chain(gdoc, privs, 8)
    pool.set_peer_range("p1", 1, 8)
    pool._schedule_once()
    assert sent, "requests must go out"
    for pid, h in list(sent):
        assert pid == "p1"
        assert pool.add_block("p1", blocks[h - 1])
    win = pool.peek_window(10)
    assert [b.header.height for b in win] == list(
        range(1, len(win) + 1))
    pool.pop_requests(len(win) - 1)
    assert pool.height == len(win)
    assert not errs


def test_pool_rejects_wrong_peer_and_redoes():
    sent = []
    pool = BlockPool(1, lambda pid, h: sent.append((pid, h)),
                     lambda pid, r: None)
    gdoc, privs = make_genesis(4)
    blocks, _, _ = build_chain(gdoc, privs, 4)
    pool.set_peer_range("p1", 1, 4)
    pool.set_peer_range("p2", 1, 4)
    pool._schedule_once()
    (pid1, h1) = sent[0]
    other = "p2" if pid1 == "p1" else "p1"
    assert not pool.add_block(other, blocks[h1 - 1])  # wrong peer
    assert pool.add_block(pid1, blocks[h1 - 1])
    # redo removes the peer and clears the block
    assert pool.redo_request(h1) == pid1
    assert pool.num_peers() == 1
    assert pool.peek_window(4) == []


def test_pool_caught_up():
    pool = BlockPool(5, lambda *a: None, lambda *a: None)
    assert not pool.is_caught_up()          # no peers
    pool.set_peer_range("p1", 1, 5)
    pool._start_time -= 10                   # pretend we waited
    assert pool.is_caught_up()               # height 5 >= max(5)-1
    pool.set_peer_range("p2", 1, 50)
    assert not pool.is_caught_up()


# --- reactor-level end-to-end over an in-memory wire ----------------------

class _MemPeer:
    """Duck-typed Peer delivering messages directly to a target reactor."""

    def __init__(self, pid, deliver):
        self.id = pid
        self._deliver = deliver

    def send(self, ch_id, msg):
        from tendermint_tpu.p2p import wire
        self._deliver(ch_id, self, wire.encode(ch_id, msg))
        return True

    try_send = send


def test_blocksync_reactor_end_to_end():
    """A served node catches up from a serving node through real reactor
    messages (StatusRequest/Response, BlockRequest/Response) — in-memory
    transport, full verify+apply."""
    from tendermint_tpu.blocksync.reactor import BlocksyncReactor

    gdoc, privs = make_genesis(4)
    blocks, commits, _ = build_chain(gdoc, privs, 25)

    # server side: store holds the whole chain
    ex_s, store_s, state_s = _fresh_node(gdoc)
    for b, c in zip(blocks, commits):
        _bid, parts = block_id_of(b)
        store_s.save_block(b, parts, c)
    server = BlocksyncReactor(ex_s, store_s, state_s, fast_sync=False)

    # client side: empty, wants to catch up
    ex_c, store_c, state_c = _fresh_node(gdoc)
    caught = threading.Event()
    client = BlocksyncReactor(ex_c, store_c, state_c, window=8,
                              on_caught_up=lambda st: caught.set())

    # cross-wire: sending to the "server" handle lands in server.receive
    # (which sees the "client" handle as the sender), and vice versa
    handles = {}
    server_peer = _MemPeer("server", lambda ch, p, mb: server.receive(
        ch, handles["client"], mb))
    client_peer = _MemPeer("client", lambda ch, p, mb: client.receive(
        ch, handles["server"], mb))
    handles["server"] = server_peer
    handles["client"] = client_peer

    class _OneSwitch:
        def __init__(self, peer):
            self.peers = {peer.id: peer}

        def broadcast(self, ch_id, msg):
            for p in self.peers.values():
                p.send(ch_id, msg)

        def stop_peer_for_error(self, peer, reason):
            raise AssertionError(f"peer error: {reason}")

    client.switch = _OneSwitch(server_peer)
    server.switch = _OneSwitch(client_peer)

    client.start()
    client.add_peer(server_peer)
    server.add_peer(client_peer)
    # announce server's range
    client.pool.set_peer_range("server", store_s.base(), store_s.height())

    deadline = time.time() + 30
    while time.time() < deadline and client.state.last_block_height < 24:
        time.sleep(0.05)
    client.stop()
    # can only sync up to height-1 (last block needs successor commit)
    assert client.state.last_block_height >= 24
    assert store_c.load_block(24).hash() == blocks[23].hash()
    assert client.blocks_synced >= 24
