"""Gossip observatory (p2p/netobs.py, docs/adr/adr-025-gossip-observatory.md):
per-peer/per-channel flow telemetry, duplicate-waste accounting and
per-link WAN attribution.

Tier-1 carries the acceptance gates:

  * exact byte reconciliation — the netobs sent/recv ledgers against
    the vnet's replayable decision schedule (sent = every verdict but
    backpressure; recv = deliver* sizes x copies);
  * RTT attribution — the vnet control-plane pinger's samples against
    the armed LinkPolicy latency, and the MConnection ping/pong RTT
    against an injected clock;
  * duplicate-waste accounting — useful vs duplicate receipts through
    the consensus seam, on a real 4-node NetHarness with a `dup`
    policy armed, reconciled against /debug/net, /metrics and the
    harness artifact gossip table;
  * the house observability discipline — chaos at `netobs.record`
    sheds samples without touching delivery, and the disabled path
    stays sub-microsecond (the same gate observatory/devobs carry).
"""
from __future__ import annotations

import json
import queue
import threading
import time
import timeit
import urllib.request

import pytest

from tendermint_tpu.libs import fail, metrics
from tendermint_tpu.networks.harness import NetHarness
from tendermint_tpu.networks.vnet import VirtualNetwork
from tendermint_tpu.p2p import connection as mconn
from tendermint_tpu.p2p import netobs, wire
from tendermint_tpu.p2p.connection import ChannelDescriptor, MConnection

CH = 0x7C


def _codec():
    try:
        wire.register_codec(CH, lambda m: m, lambda b: b)
    except ValueError:
        pass  # already registered by an earlier test in this process


@pytest.fixture(autouse=True)
def _fresh_netobs():
    netobs.reset()
    netobs.enable()
    yield
    netobs.reset()
    fail.clear()


def _chans(cap=100):
    return [ChannelDescriptor(CH, priority=1, send_queue_capacity=cap)]


def _wait(pred, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return pred()


# ---------------------------------------------------------------------------
# exact byte reconciliation against the vnet decision schedule
# ---------------------------------------------------------------------------

def test_vnet_bytes_reconcile_exactly_with_decision_schedule():
    """The acceptance property: for every directed link, the netobs
    sent ledger equals the sum of decision sizes for every verdict but
    backpressure (the sender's view: a lossy/partitioned link still
    swallowed the frame), and the recv ledger equals the deliver*
    sizes times copies (a +dup verdict delivers twice)."""
    net = VirtualNetwork(seed=41).start()
    got = []
    try:
        a, _b = net.connect_raw("nra", "nrb", _chans(cap=10_000),
                                on_b=lambda c, m: got.append(m))
        net.set_link("nra", "nrb", drop=0.25, dup=0.25,
                     latency_s=0.001)
        n, size = 300, 4
        for i in range(n):
            assert a.send(CH, b"m%03d" % i)

        decisions = [d for d in net.decisions()
                     if (d[0], d[1]) == ("nra", "nrb")]
        assert len(decisions) == n  # blocking sends: none refused
        exp_sent = sum(d[4] for d in decisions
                       if d[5] != "drop:backpressure")
        exp_recv = sum(d[4] * (2 if "+dup" in d[5] else 1)
                       for d in decisions if d[5].startswith("deliver"))
        assert exp_sent == n * size
        assert exp_recv > 0
        # drain: every scheduled delivery dispatched
        assert _wait(lambda: len(got) * size == exp_recv), \
            f"delivered {len(got) * size}, schedule says {exp_recv}"

        flow = netobs.flow_table()
        assert flow["nra"]["nrb"]["sent_bytes"] == exp_sent
        assert _wait(lambda: netobs.flow_table()
                     ["nrb"]["nra"]["recv_bytes"] == exp_recv)
        # the drop verdicts are the sent-minus-delivered gap
        assert exp_sent - sum(
            d[4] for d in decisions
            if d[5].startswith("deliver")) == sum(
            d[4] for d in decisions if d[5].startswith("drop:"))
    finally:
        net.stop()


def test_vnet_rtt_tracks_injected_link_latency():
    """The control-plane pinger: RTT samples on a link with a fixed
    one-way latency armed both ways must straddle 2x that latency
    (never below — the vnet cannot deliver early) within a scheduling
    tolerance, and must consume no link RNG (the decision schedule
    stays ping-free)."""
    net = VirtualNetwork(seed=43, ping_interval_s=0.1).start()
    try:
        net.connect_raw("rta", "rtb", _chans())
        lat = 0.02
        net.set_link("rta", "rtb", latency_s=lat)
        net.set_link("rtb", "rta", latency_s=lat)
        assert _wait(lambda: (netobs.flow_table().get("rta", {})
                              .get("rtb", {}).get("rtt") or {})
                     .get("n", 0) >= 2, timeout=15.0)
        rtt = netobs.flow_table()["rta"]["rtb"]["rtt"]
        assert rtt["min_s"] >= 2 * lat
        assert rtt["mean_s"] < 2 * lat + 0.25  # scheduling tolerance
        assert net.decisions() == []  # pings never touch the schedule
    finally:
        net.stop()


# ---------------------------------------------------------------------------
# duplicate-waste accounting (consensus seam, unit level)
# ---------------------------------------------------------------------------

def test_gossip_receipt_accounting_and_flow_rate():
    netobs.gossip_receipt("n", "p1", "part", useful=True,
                          latency_s=0.01)
    netobs.gossip_receipt("n", "p2", "part", useful=False)
    netobs.gossip_receipt("n", "p1", "vote", useful=True)
    netobs.flow_rate("n", "p1", send_bps=5.0, recv_bps=7.0)
    flow = netobs.flow_table("n")["n"]
    assert flow["p1"]["useful_parts"] == 1
    assert flow["p1"]["useful_votes"] == 1
    assert flow["p2"]["dup_parts"] == 1
    assert flow["p1"]["rate_send_bps"] == 5.0
    assert flow["p1"]["rate_recv_bps"] == 7.0
    rep = netobs.report("n")
    assert rep["totals"]["useful_receipts"] == 2
    assert rep["totals"]["duplicate_receipts"] == 1
    assert rep["totals"]["duplicate_ratio"] == round(1 / 3, 4)


def test_observatory_first_useful_delivery_attribution():
    """The JOIN with the consensus observatory (ADR-020): useful
    receipts land on the EXISTING height record only (no remote-
    controlled record creation) and pin the first-useful peer."""
    from tendermint_tpu.consensus.observatory import Observatory

    o = Observatory(enabled=True)
    o.stamp("n", 5, "new_height")
    o.useful_receipt("n", 5, "part", "peerX")
    o.useful_receipt("n", 5, "part", "peerY")
    o.useful_receipt("n", 5, "vote", "peerY")
    rec = o.records("n")[0]
    assert rec["first_useful"] == {"part": "peerX", "vote": "peerY"}
    assert rec["useful_from"] == {"part": {"peerX": 1, "peerY": 1},
                                  "vote": {"peerY": 1}}
    o.useful_receipt("n", 99, "part", "peerZ")  # unknown height
    assert [r["height"] for r in o.records("n")] == [5]


# ---------------------------------------------------------------------------
# metrics funnel (satellite: bytes_sent / bytes_recv finally move)
# ---------------------------------------------------------------------------

def _scrape_value(text: str, needle: str) -> float:
    for ln in text.splitlines():
        if ln.startswith(needle):
            return float(ln.rsplit(" ", 1)[1])
    return 0.0


def test_metrics_scrape_byte_counters_move():
    send_k = 'tendermint_p2p_message_send_bytes_total{ch_id="0x7c"}'
    recv_k = 'tendermint_p2p_message_receive_bytes_total{ch_id="0x7c"}'
    before_s = _scrape_value(metrics.DEFAULT.render_text(), send_k)
    before_r = _scrape_value(metrics.DEFAULT.render_text(), recv_k)
    netobs.sent("m1", "p", CH, 111, queue_wait_s=0.001, depth=3)
    netobs.recv("m1", "p", CH, 222)
    netobs.publish_pending()
    text = metrics.DEFAULT.render_text()
    assert _scrape_value(text, send_k) == before_s + 111
    assert _scrape_value(text, recv_k) == before_r + 222
    assert 'tendermint_p2p_channel_queue_depth{ch_id="0x7c"} 3' in text
    # publishing twice without new traffic must not double-count
    netobs.publish_pending()
    assert _scrape_value(metrics.DEFAULT.render_text(),
                         send_k) == before_s + 111


# ---------------------------------------------------------------------------
# chaos: recording faults shed, delivery untouched
# ---------------------------------------------------------------------------

def test_chaos_netobs_record_sheds_without_touching_delivery():
    net = VirtualNetwork(seed=11).start()
    got = []
    try:
        a, _b = net.connect_raw("cha", "chb", _chans(),
                                on_b=lambda c, m: got.append(m))
        fail.set_mode("netobs.record", "raise")
        try:
            for _ in range(5):
                assert a.send(CH, b"keep!")
            assert _wait(lambda: len(got) == 5), \
                "chaos at netobs.record must not drop deliveries"
            assert fail.fired("netobs.record", "raise") >= 1
            assert netobs.NOBS.shed_counts()["chaos"] >= 1
            # every sample shed: the ledger saw nothing
            assert netobs.flow_table().get("cha", {}) \
                                      .get("chb", {}) \
                                      .get("sent_bytes", 0) == 0
        finally:
            fail.clear("netobs.record")
        # latency at the same site: the sample is merely late — the
        # frame still arrives and is still counted
        fail.set_mode("netobs.record", "latency:20")
        try:
            assert a.send(CH, b"after")
            assert _wait(lambda: len(got) == 6)
            assert _wait(lambda: netobs.flow_table()
                         ["cha"]["chb"]["sent_bytes"] == 5)
            assert fail.fired("netobs.record", "latency:20") >= 1
        finally:
            fail.clear("netobs.record")
    finally:
        net.stop()


def test_disabled_is_noop_and_sub_microsecond():
    """netobs sits on the MConnection send/recv routines and the vnet
    delivery engine unconditionally, so the disabled path must stay
    sub-microsecond — the same gate observatory/devobs/trace carry.
    min-of-repeats dodges CI load spikes."""
    netobs.disable()
    try:
        netobs.sent("n", "p", CH, 100)
        netobs.recv("n", "p", CH, 100)
        netobs.rtt("n", "p", 0.01)
        assert netobs.flow_table() == {}

        n = 20000

        def site_sent():
            netobs.sent("n", "p", CH, 100, queue_wait_s=0.001)

        per_call = min(timeit.repeat(site_sent, number=n, repeat=5)) / n
        assert per_call < 1e-6, f"disabled sent cost {per_call:.2e}s"

        def site_recv():
            netobs.recv("n", "p", CH, 100)

        per_call = min(timeit.repeat(site_recv, number=n, repeat=5)) / n
        assert per_call < 1e-6, f"disabled recv cost {per_call:.2e}s"
    finally:
        netobs.enable()


# ---------------------------------------------------------------------------
# MConnection: monotonic keepalive clock + RTT (satellite regression)
# ---------------------------------------------------------------------------

class _FakeSecret:
    """Duck-typed SecretConnection: scripted inbound frames, captured
    outbound frames."""

    def __init__(self):
        self.sent = []
        self._inbox: "queue.Queue" = queue.Queue()
        self.closed = False

    def send_frame(self, frame):
        self.sent.append(bytes(frame))

    def feed(self, frame: bytes):
        self._inbox.put(frame)

    def recv_frame(self) -> bytes:
        f = self._inbox.get()
        if f is None:
            raise OSError("closed")
        return f

    def close(self):
        self.closed = True
        self._inbox.put(None)


class _FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


def test_mconn_default_clock_is_monotonic():
    """The regression this satellite fixes: keepalive arithmetic on
    time.time() let an NTP step suppress (backward) or spuriously fire
    (forward) the pong timeout.  The deadline clock must be monotonic
    by default and injectable for tests."""
    fs = _FakeSecret()
    mc = MConnection(fs, _chans(), lambda c, m: None, lambda e: None)
    assert mc._clock is time.monotonic
    mc.stop()


def test_mconn_rtt_and_flow_recorded_on_injected_clock():
    clk = _FakeClock(1000.0)
    fs = _FakeSecret()
    got, errs = [], []
    mc = MConnection(fs, _chans(), lambda c, m: got.append(m),
                     errs.append, obs_node="nodeA", obs_peer="peerB",
                     clock=clk)
    mc.start()
    try:
        # send path: frame on the wire, bytes + queue wait in the ledger
        assert mc.send(CH, b"hello")
        assert _wait(lambda: any(f[0] == 0x01 for f in fs.sent))
        assert _wait(lambda: netobs.flow_table().get("nodeA", {})
                     .get("peerB", {}).get("sent_bytes", 0) == 7)

        # rtt: a pong answering an outstanding ping, 35ms later on the
        # injected clock (wall clock irrelevant by construction)
        mc._ping_sent_t = clk.t
        clk.t += 0.035
        fs.feed(bytes([mconn._PONG]))
        assert _wait(lambda: mc._ping_sent_t is None)
        assert mc._last_pong == clk.t
        rtt = netobs.flow_table()["nodeA"]["peerB"]["rtt"]
        assert rtt["last_s"] == pytest.approx(0.035)

        # recv path: dispatch wall + bytes under the peer's ledger
        fs.feed(bytes([0x01, CH]) + b"payload")
        assert _wait(lambda: got == [b"payload"])
        assert _wait(lambda: netobs.flow_table()
                     ["nodeA"]["peerB"]["recv_bytes"] == 9)
        assert errs == []
    finally:
        mc.stop()


def test_mconn_pong_timeout_fires_on_monotonic_clock(monkeypatch):
    """Advance the injected monotonic clock past PONG_TIMEOUT without
    any wall-clock movement: the keepalive must fire (under the old
    time.time() arithmetic a backward NTP step could postpone this
    indefinitely)."""
    monkeypatch.setattr(mconn, "PING_INTERVAL", 0.01)
    clk = _FakeClock(1000.0)
    fs = _FakeSecret()
    errs = []
    mc = MConnection(fs, _chans(), lambda c, m: None, errs.append,
                     clock=clk)
    mc.start()
    try:
        clk.t += mconn.PONG_TIMEOUT + 1.0
        assert _wait(lambda: len(errs) == 1)
        assert isinstance(errs[0], TimeoutError)
        assert fs.closed
    finally:
        mc.stop()


# ---------------------------------------------------------------------------
# 4-node harness smoke: /debug/net, /metrics and the artifact gossip
# table agree with the vnet decision schedule
# ---------------------------------------------------------------------------

def test_harness_gossip_table_debug_net_and_artifact_agree(tmp_path):
    _codec()
    from tendermint_tpu.libs.pprof import PprofServer

    h = NetHarness(validators=4, seed=515, workdir=str(tmp_path))
    h.start()
    stopped = False
    try:
        for i in range(4):
            for j in range(4):
                if i != j:
                    h.set_link(i, j, dup=0.25, latency_s=0.002)
        h.run_scenario({
            "name": "netobs_smoke", "validators": 4,
            "steps": [{"op": "wait_height", "delta": 2,
                       "timeout": 120.0}]})
        addrs = {hn.addr for hn in h.nodes}
        names = {hn.addr: hn.name for hn in h.nodes}
        # quiesce before reconciling: a live network never stops
        # sending, a stopped one holds both ledgers still
        h.stop()
        stopped = True

        # (1) sent reconciliation, exact: per directed vnet link, the
        # netobs sent ledger == decision sizes minus backpressure
        by_link = {}
        for src, dst, _idx, _ch, size, verdict, _delay in \
                h.net.decisions():
            if verdict != "drop:backpressure":
                by_link[(src, dst)] = by_link.get((src, dst), 0) + size
        assert by_link, "4 nodes committing blocks must gossip"
        flow = netobs.flow_table()
        for (src, dst), total in by_link.items():
            assert flow[src][dst]["sent_bytes"] == total, \
                f"link {src}->{dst}"
        # recv never exceeds the schedule (dispatchers stop mid-heap)
        for src in addrs:
            for dst, pf in flow.get(src, {}).items():
                if dst in addrs:
                    exp = sum(
                        d[4] * (2 if "+dup" in d[5] else 1)
                        for d in h.net.decisions()
                        if (d[0], d[1]) == (dst, src)
                        and d[5].startswith("deliver"))
                    assert pf["recv_bytes"] <= exp

        # (2) duplicate-waste moved under the armed dup policy
        rep = netobs.report()
        assert rep["totals"]["useful_receipts"] > 0
        assert rep["totals"]["duplicate_receipts"] > 0
        assert 0.0 < rep["totals"]["duplicate_ratio"] < 1.0

        # (3) the artifact gossip table: canonical names, policy JOIN,
        # byte totals preserved by the keying fold
        gt = h.gossip_table()
        assert gt["links"]
        for key, row in gt["links"].items():
            src, dst = key.split("->")
            assert src in names.values() and dst in names.values()
            assert "latency_s" in row["policy"]
        assert sum(r["sent_bytes"] for r in gt["links"].values()) == \
            sum(by_link.values())
        assert any(r["dup_parts"] + r["dup_votes"] > 0
                   for r in gt["links"].values())
        assert any(r["rtt"] for r in gt["links"].values())
        armed = [r for r in gt["links"].values()
                 if r["policy"]["dup"] == 0.25]
        assert armed, "armed LinkPolicy must survive the JOIN"

        # (4) /debug/net serves the same report over HTTP
        srv = PprofServer("127.0.0.1:0")
        srv.start()
        try:
            with urllib.request.urlopen(
                    f"http://{srv.laddr}/debug/net", timeout=10) as r:
                served = json.loads(r.read().decode())
        finally:
            srv.stop()
        assert served["totals"] == rep["totals"]
        assert served["enabled"] is True

        # (5) /metrics: the dead-since-seed byte counters finally move
        netobs.publish_pending()
        text = metrics.DEFAULT.render_text()
        assert _scrape_value(
            text, "tendermint_p2p_message_send_bytes_total") >= 0
        assert "tendermint_p2p_message_send_bytes_total" in text
        assert "tendermint_p2p_message_receive_bytes_total" in text
        assert "tendermint_p2p_gossip_receipts_total" in text
        assert "tendermint_p2p_peer_rtt_seconds" in text
    finally:
        if not stopped:
            h.stop()
