"""Light-client RPC proxy over a live single-validator node.

Reference light/proxy + light/rpc/client.go: every answer the proxy
serves is verified against light-client-verified headers — commits and
validator sets from the verified store, blocks hash-checked against the
verified header, abci_query results proven into the verified app hash
via merkle proof operators.
"""
from __future__ import annotations

import base64
import time

import pytest

from tendermint_tpu.abci.kvstore import ProvableKVStoreApplication
from tendermint_tpu.config.config import Config
from tendermint_tpu.libs.kvdb import MemDB
from tendermint_tpu.light.client import Client, TrustOptions
from tendermint_tpu.light.provider import HTTPProvider
from tendermint_tpu.light.proxy import LightProxy
from tendermint_tpu.light.store import LightStore
from tendermint_tpu.node import Node
from tendermint_tpu.p2p.key import NodeKey
from tendermint_tpu.privval.file_pv import FilePV
from tendermint_tpu.rpc.client import HTTPClient, RPCClientError
from tendermint_tpu.types.basic import Timestamp
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.light_block import SignedHeader
from tendermint_tpu.types.validator_set import ValidatorSet


@pytest.fixture(scope="module")
def node(tmp_path_factory):
    home = str(tmp_path_factory.mktemp("lightproxy-node"))
    cfg = Config(home=home)
    cfg.p2p.laddr = "127.0.0.1:0"
    cfg.p2p.pex = False
    cfg.rpc.laddr = "127.0.0.1:0"
    c = cfg.consensus
    c.timeout_propose = c.timeout_prevote = c.timeout_precommit = 0.2
    c.timeout_propose_delta = c.timeout_prevote_delta = \
        c.timeout_precommit_delta = 0.1
    c.timeout_commit = 0.05
    cfg.ensure_dirs()
    pv = FilePV.load_or_generate(cfg.priv_validator_key_file(),
                                 cfg.priv_validator_state_file())
    NodeKey.load_or_generate(cfg.node_key_file())
    pub = pv.get_pub_key()
    from tendermint_tpu.types.params import ConsensusParams
    params = ConsensusParams()
    # fast localnet: block cadence ~0.1s real time; the default 1000ms
    # time iota would mint header times into the future and the light
    # verifier would (correctly) refuse them
    params.block.time_iota_ms = 1
    gdoc = GenesisDoc(chain_id="light-proxy-chain",
                      genesis_time=Timestamp(1700000000, 0),
                      consensus_params=params,
                      validators=[GenesisValidator(
                          address=pub.address(), pub_key_type=pub.type_name,
                          pub_key_bytes=pub.bytes(), power=10)])
    with open(cfg.genesis_file(), "w") as f:
        f.write(gdoc.to_json())

    n = Node(cfg, ProvableKVStoreApplication())
    n.start()
    deadline = time.time() + 60
    while n.block_store.height() < 3 and time.time() < deadline:
        time.sleep(0.05)
    assert n.block_store.height() >= 3, "node made no progress"
    yield n
    n.stop()


@pytest.fixture(scope="module")
def proxy(node):
    addr = node.rpc_server.laddr
    chain_id = node.state.chain_id
    provider = HTTPProvider(chain_id, addr)
    anchor = provider.light_block(1)
    client = Client(chain_id, TrustOptions(1, anchor.hash()),
                    provider, witnesses=[], store=LightStore(MemDB()))
    p = LightProxy(client, addr, "127.0.0.1:0")
    p.start()
    yield p
    p.stop()


def _call(p, method, **params):
    return HTTPClient(p.laddr).call(method, **params)


def test_http_provider_roundtrip(node):
    prov = HTTPProvider(node.state.chain_id, node.rpc_server.laddr)
    lb = prov.light_block(2)
    assert lb.height == 2
    assert lb.validators.hash() == \
        lb.signed_header.header.validators_hash


def test_proxy_commit_and_validators_verified(node, proxy):
    r = _call(proxy, "commit", height=2)
    assert r["verified"] and r["height"] == 2
    sh = SignedHeader.from_proto(base64.b64decode(r["signed_header"]))
    assert sh.height == 2

    v = _call(proxy, "validators", height=2)
    assert v["verified"]
    vals = ValidatorSet.from_proto(base64.b64decode(v["validator_set"]))
    assert vals.hash() == sh.header.validators_hash


def test_proxy_block_hash_checked(node, proxy):
    r = _call(proxy, "block", height=2)
    assert r["verified"]
    from tendermint_tpu.types.block import Block
    block = Block.from_proto(base64.b64decode(r["block"]))
    assert block.header.height == 2


def test_proxy_status_and_header(node, proxy):
    st = _call(proxy, "status")
    assert st["light_client"]["last_trusted_height"] >= 1
    hd = _call(proxy, "header", height=2)
    assert hd["chain_id"] == node.state.chain_id


def test_proxy_abci_query_proof_verified(node, proxy):
    # commit a tx through the proxy's forwarding path, then query it back
    # with a merkle proof anchored in a verified header
    r = _call(proxy, "broadcast_tx_commit", tx=base64.b64encode(
        b"lightkey=lightvalue").decode())
    assert r["deliver_tx"]["code"] == 0

    # wait for the NEXT block: the proof anchors to the app hash in
    # header h+1
    target = node.block_store.height() + 1
    deadline = time.time() + 30
    while node.block_store.height() < target and time.time() < deadline:
        time.sleep(0.05)

    q = _call(proxy, "abci_query", data=b"lightkey".hex())
    assert q["response"]["verified"], q
    assert base64.b64decode(q["response"]["value"]) == b"lightvalue"


def test_proxy_abci_query_stripped_proof_rejected(node, proxy):
    """A primary stripping proof_ops (e.g. to deny a key's existence)
    must error when the client asked for proof, not pass with
    verified=False (reference light/rpc/client.go errors on empty
    proof)."""
    orig = proxy.primary.call

    def stripped(method, **params):
        r = orig(method, **params)
        if method == "abci_query":
            r["response"]["proof_ops"] = []
        return r

    proxy.primary.call = stripped
    try:
        with pytest.raises(RPCClientError, match="no proof_ops"):
            _call(proxy, "abci_query", data=b"lightkey".hex())
    finally:
        proxy.primary.call = orig


def test_proxy_abci_query_bad_proof_rejected(node, proxy):
    """A primary serving a value that does not match its own app hash
    must be caught (tamper with the forwarded response)."""
    orig = proxy.primary.call

    def tampered(method, **params):
        r = orig(method, **params)
        if method == "abci_query":
            r["response"]["value"] = base64.b64encode(b"evil").decode()
        return r

    proxy.primary.call = tampered
    try:
        with pytest.raises(RPCClientError,
                           match="proof verification failed"):
            _call(proxy, "abci_query", data=b"lightkey".hex())
    finally:
        proxy.primary.call = orig
