"""Amino-compatible JSON (VERDICT r3 #10, reference libs/json +
RegisterType calls): reference-shaped fixtures for keys, votes,
validators, evidence, and the RPC surfaces existing Tendermint tooling
parses (status/validators/block/genesis)."""
from __future__ import annotations

import base64
import json

from helpers import build_chain, make_genesis
from tendermint_tpu.libs import amino_json as aj
from tendermint_tpu.types.basic import (BlockID, PartSetHeader,
                                        SignedMsgType, Timestamp)
from tendermint_tpu.types.vote import Vote


def test_pub_key_tagging_reference_shapes():
    # the exact registered names (crypto/ed25519/ed25519.go:22 etc.)
    d = aj.pub_key_json("ed25519", b"\x01" * 32)
    assert d == {"type": "tendermint/PubKeyEd25519",
                 "value": base64.b64encode(b"\x01" * 32).decode()}
    assert aj.pub_key_json("secp256k1", b"\x02" * 33)["type"] == \
        "tendermint/PubKeySecp256k1"
    assert aj.pub_key_json("sr25519", b"\x03" * 32)["type"] == \
        "tendermint/PubKeySr25519"
    # round trip, plus legacy bare-name + hex acceptance
    t, b = aj.pub_key_from_json(d)
    assert (t, b) == ("ed25519", b"\x01" * 32)
    t, b = aj.pub_key_from_json({"type": "ed25519",
                                 "value": ("01" * 32)})
    assert (t, b) == ("ed25519", b"\x01" * 32)


def test_rfc3339_time_reference_shapes():
    # Go time.Time JSON: trailing-zero-trimmed fraction, Z suffix
    assert aj.ts_rfc3339(Timestamp(1700000100, 0)) == \
        "2023-11-14T22:15:00Z"
    assert aj.ts_rfc3339(Timestamp(1700000100, 500000000)) == \
        "2023-11-14T22:15:00.5Z"
    assert aj.ts_rfc3339(Timestamp(1700000100, 25)) == \
        "2023-11-14T22:15:00.000000025Z"
    for ts in (Timestamp(1700000100, 0), Timestamp(123456, 789)):
        assert aj.parse_rfc3339(aj.ts_rfc3339(ts)) == ts
    # arbitrary RFC3339 offsets normalize to UTC (Go tooling may write
    # genesis_time with a non-UTC zone)
    assert aj.parse_rfc3339("2023-11-15T00:15:00+02:00") == \
        Timestamp(1700000100, 0)
    assert aj.parse_rfc3339("2023-11-14T17:45:00.25-04:30") == \
        Timestamp(1700000100, 250000000)
    assert aj.parse_rfc3339("2023-11-14T22:15:00+00:00") == \
        Timestamp(1700000100, 0)
    # but a nonsense offset is rejected, not silently applied as a
    # multi-day shift (hours <= 23, minutes <= 59)
    import pytest
    for bad in ("2023-11-14T22:15:00+99:99", "2023-11-14T22:15:00-24:00",
                "2023-11-14T22:15:00+00:60"):
        with pytest.raises(ValueError):
            aj.parse_rfc3339(bad)
    # boundary offsets stay valid
    assert aj.parse_rfc3339("2023-11-15T22:14:00+23:59") == \
        Timestamp(1700000100, 0)


def test_vote_json_reference_shape():
    v = Vote(type=SignedMsgType.PRECOMMIT, height=42, round=1,
             block_id=BlockID(b"\xAA" * 32, PartSetHeader(1, b"\xBB" * 32)),
             timestamp=Timestamp(1700000100, 0),
             validator_address=b"\xCC" * 20, validator_index=3)
    v.signature = b"\xDD" * 64
    d = aj.vote_json(v)
    # int64 height -> string; int32 round/index -> numbers; hex address
    assert d["height"] == "42" and d["round"] == 1
    assert d["validator_index"] == 3
    assert d["validator_address"] == "CC" * 20
    assert d["block_id"]["hash"] == "AA" * 32
    assert d["block_id"]["parts"]["total"] == 1
    assert d["signature"] == base64.b64encode(b"\xDD" * 64).decode()
    assert d["timestamp"].endswith("Z")


def test_genesis_doc_amino_shape_and_legacy_load():
    gdoc, privs = make_genesis(2)
    d = json.loads(gdoc.to_json())
    assert isinstance(d["genesis_time"], str)  # RFC3339, not {s, n}
    for v in d["validators"]:
        assert v["pub_key"]["type"] == "tendermint/PubKeyEd25519"
        base64.b64decode(v["pub_key"]["value"], validate=True)
        assert isinstance(v["power"], str)
    # round trip
    from tendermint_tpu.types.genesis import GenesisDoc
    back = GenesisDoc.from_json(gdoc.to_json())
    assert back.chain_id == gdoc.chain_id
    assert back.validators[0].pub_key_bytes == \
        gdoc.validators[0].pub_key_bytes
    # a legacy doc (bare type name, hex key, {seconds,nanos} time) loads
    d["genesis_time"] = {"seconds": 1700000000, "nanos": 0}
    for v in d["validators"]:
        v["pub_key"] = {"type": "ed25519",
                        "value": base64.b64decode(
                            v["pub_key"]["value"]).hex()}
    legacy = GenesisDoc.from_json(json.dumps(d))
    assert legacy.validators[0].pub_key_bytes == \
        gdoc.validators[0].pub_key_bytes


def test_duplicate_vote_evidence_json_reference_shape():
    from tendermint_tpu.types.evidence import DuplicateVoteEvidence

    gdoc, privs = make_genesis(4)
    blocks, commits, states = build_chain(gdoc, privs, 3)
    vals = states[2].validators
    addr = privs[0].pub_key().address()
    idx, _ = vals.get_by_address(addr)

    def mkvote(mark):
        v = Vote(type=SignedMsgType.PRECOMMIT, height=3, round=0,
                 block_id=BlockID(mark * 32, PartSetHeader(1, mark * 32)),
                 timestamp=Timestamp(1700000100, 0),
                 validator_address=addr, validator_index=idx)
        v.signature = privs[0].sign(v.sign_bytes(gdoc.chain_id))
        return v

    ev = DuplicateVoteEvidence.from_votes(
        mkvote(b"\xAA"), mkvote(b"\xBB"), Timestamp(1700000100, 0), vals)
    d = aj.evidence_json(ev, None, None, None)
    assert d["type"] == "tendermint/DuplicateVoteEvidence"
    val = d["value"]
    # untagged Go fields marshal under their Go names with int64->string
    # (reference types/evidence.go:35-43)
    assert set(val) == {"vote_a", "vote_b", "TotalVotingPower",
                        "ValidatorPower", "Timestamp"}
    assert val["TotalVotingPower"] == str(vals.total_voting_power())
    assert val["ValidatorPower"] == "10"
    assert val["vote_a"]["height"] == "3"


def test_rpc_block_and_validators_amino_shapes():
    """The RPC emitters themselves produce the dialect (heights as
    strings, RFC3339 times, tagged keys) a reference client expects."""
    from tendermint_tpu.libs.kvdb import MemDB
    from tendermint_tpu.rpc.server import RPCServer
    from tendermint_tpu.state.store import StateStore
    from tendermint_tpu.store.block_store import BlockStore
    from tendermint_tpu.blocksync.replay import block_id_of
    from tendermint_tpu.state.state import state_from_genesis

    gdoc, privs = make_genesis(3)
    blocks, commits, states = build_chain(gdoc, privs, 3)
    block_store = BlockStore(MemDB())
    state_store = StateStore(MemDB())
    state_store.save(state_from_genesis(gdoc))
    for b, c, st in zip(blocks, commits, states):
        _bid, parts = block_id_of(b)
        block_store.save_block(b, parts, c)
        state_store.save(st)

    class FakeNode:
        pass

    node = FakeNode()
    node.block_store = block_store
    node.state_store = state_store
    node.state = states[-1]
    srv = RPCServer(node, "127.0.0.1:0")
    blk = srv.block(2)
    hdr = blk["block"]["header"]
    assert hdr["height"] == "2"
    assert isinstance(hdr["time"], str) and hdr["time"].endswith("Z")
    assert isinstance(hdr["version"]["block"], str)
    lc = blk["block"]["last_commit"]
    assert lc["height"] == "1" and isinstance(lc["round"], int)
    assert lc["signatures"][0]["timestamp"].endswith("Z")
    vr = srv.validators(height=2)
    assert vr["block_height"] == "2"
    v0 = vr["validators"][0]
    assert v0["pub_key"]["type"] == "tendermint/PubKeyEd25519"
    assert isinstance(v0["voting_power"], str)
    assert isinstance(v0["proposer_priority"], str)
