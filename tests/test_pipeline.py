"""BlockPipeline (ADR-017): pipelined window replay equivalence, group
commit crash consistency, chaos degradation, and the kvdb/merkle
satellites that ride with it.

The equivalence property every test here leans on: for the same input
window, the pipelined path must produce BYTE-IDENTICAL final State and
store contents to the serial path — including under validator-set
changes, absent votes, a malformed block at position k, chaos at the
pipeline's three fail sites, and a kill between group commits followed
by reopen + resume.
"""
from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import time

import pytest

from helpers import build_chain, make_genesis
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.blocksync.replay import WindowSyncError, replay_window
from tendermint_tpu.crypto import merkle
from tendermint_tpu.libs import fail, safe_codec, trace
from tendermint_tpu.libs.kvdb import (GroupCommitDB, MemDB, SQLiteDB,
                                      prefix_upper_bound)
from tendermint_tpu.state import pipeline
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.state import state_from_genesis
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.store.block_store import BlockStore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _pipeline_hygiene():
    """No test may leak an installed pipeline, armed chaos mode, or
    group-mode store into the next."""
    yield
    fail.clear()
    p = pipeline.installed()
    if p is not None:
        if p.is_running():
            p.stop()
        pipeline.install(None)


def _fresh(gdoc, grouped=True, bdb=None, sdb=None):
    bdb = bdb if bdb is not None else (
        GroupCommitDB(MemDB()) if grouped else MemDB())
    sdb = sdb if sdb is not None else (
        GroupCommitDB(MemDB()) if grouped else MemDB())
    ex = BlockExecutor(StateStore(sdb), KVStoreApplication())
    store = BlockStore(bdb)
    return ex, store, state_from_genesis(gdoc), bdb, sdb


def _raw(db):
    """The underlying MemDB dict regardless of wrapping."""
    inner = db.inner if isinstance(db, GroupCommitDB) else db
    return dict(inner._data)


def _replay_all(ex, store, state, blocks, commits, window=16):
    applied = 0
    while applied < len(blocks):
        state, n = replay_window(ex, store, state, blocks[applied:],
                                 commits[applied:], max_window=window)
        assert n > 0, f"no progress at {applied}"
        applied += n
    return state


def _run_both_ways(gdoc, blocks, commits, window=16, depth=3, group=4):
    """Replay the chain serially and pipelined; assert byte-identical
    final state + store contents; returns the final state."""
    ex1, store1, st1, b1, s1 = _fresh(gdoc, grouped=False)
    st1 = _replay_all(ex1, store1, st1, blocks, commits, window)

    pipeline.set_config(enable=True, depth=depth, group_commit_heights=group)
    try:
        ex2, store2, st2, b2, s2 = _fresh(gdoc, grouped=True)
        st2 = _replay_all(ex2, store2, st2, blocks, commits, window)
    finally:
        pipeline.set_config(enable=False)

    assert safe_codec.dumps(st1) == safe_codec.dumps(st2)
    assert _raw(b1) == _raw(b2), "block store contents differ"
    assert _raw(s1) == _raw(s2), "state store contents differ"
    assert b2.pending_ops() == 0 and s2.pending_ops() == 0
    return st1


# ---------------------------------------------------------------------------
# equivalence properties
# ---------------------------------------------------------------------------

def test_pipeline_equivalence_stable_window():
    gdoc, privs = make_genesis(5)
    blocks, commits, states = build_chain(gdoc, privs, 20)
    st = _run_both_ways(gdoc, blocks, commits)
    assert st.last_block_height == 20
    assert st.app_hash == states[-1].app_hash


def test_pipeline_equivalence_validator_set_change():
    """A mid-chain power change breaks the stable window; the pipeline
    must decline/shorten around it and still match the serial path
    byte for byte."""
    gdoc, privs = make_genesis(4)
    import base64
    pub_b64 = base64.b64encode(privs[0].pub_key().bytes())

    def txs(h):
        if h == 7:  # power 10 -> 25 at height 7 (effective height 9)
            return [b"val:" + pub_b64 + b"!25"]
        return [b"k%d=%d" % (h, h)]

    blocks, commits, states = build_chain(gdoc, privs, 16, txs_fn=txs)
    # the chain really changed its validator set
    assert states[-1].validators.hash() != states[0].validators.hash()
    st = _run_both_ways(gdoc, blocks, commits, window=10)
    assert st.app_hash == states[-1].app_hash


def test_pipeline_equivalence_absent_votes():
    """Commits with ABSENT votes (one of five validators down) verify
    and apply identically on both paths."""
    gdoc, privs = make_genesis(5)
    blocks, commits, states = build_chain(
        gdoc, privs, 14, absent_fn=lambda h, vi: vi == (h % 5))
    st = _run_both_ways(gdoc, blocks, commits, window=14)
    assert st.app_hash == states[-1].app_hash


def test_pipeline_malformed_block_attribution_matches_serial():
    """Tampered certifier at height 6: the pipelined path must raise
    WindowSyncError with the SAME height/applied/state attribution as
    the serial path, and the stores must hold the same prefix."""
    gdoc, privs = make_genesis(4)
    blocks, commits, _ = build_chain(gdoc, privs, 10, tamper_height=6)

    def attempt(pipelined):
        if pipelined:
            pipeline.set_config(enable=True, depth=3,
                                group_commit_heights=3)
        ex, store, st, bdb, sdb = _fresh(gdoc, grouped=pipelined)
        try:
            with pytest.raises(WindowSyncError) as ei:
                replay_window(ex, store, st, blocks, commits,
                              max_window=16)
        finally:
            if pipelined:
                pipeline.set_config(enable=False)
        e = ei.value
        return (e.height, e.applied, e.state.last_block_height,
                store.height(), _raw(bdb), _raw(sdb))

    h1, a1, s1, sh1, braw1, sraw1 = attempt(False)
    h2, a2, s2, sh2, braw2, sraw2 = attempt(True)
    assert (h1, a1, s1, sh1) == (h2, a2, s2, sh2) == (6, 5, 5, 5)
    assert braw1 == braw2 and sraw1 == sraw2


def test_pipeline_declines_trivial_and_busy_windows():
    """Single-block windows and a stopped pipeline decline to the
    serial path (replay_window still works)."""
    gdoc, privs = make_genesis(4)
    blocks, commits, _ = build_chain(gdoc, privs, 3)
    p = pipeline.set_config(enable=True, depth=2, group_commit_heights=2)
    try:
        assert p.replay_window(None, None, state_from_genesis(gdoc),
                               [], [], 8) is None
        ex, store, st, *_ = _fresh(gdoc)
        st, n = replay_window(ex, store, st, blocks[:1], commits[:1],
                              max_window=8)
        assert n == 1
    finally:
        pipeline.set_config(enable=False)
    # disabled pipeline: replay_window never consults it
    ex, store, st, *_ = _fresh(gdoc, grouped=False)
    st = _replay_all(ex, store, st, blocks, commits)
    assert st.last_block_height == 3


# ---------------------------------------------------------------------------
# chaos: every registered pipeline site, raise + latency
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("site,mode", [
    ("pipeline.stage", "raise"),
    ("pipeline.stage", "latency:30"),
    ("pipeline.commit", "raise"),
    ("pipeline.commit", "latency:20"),
    ("kvdb.group_commit", "raise"),
    ("kvdb.group_commit", "latency:20"),
])
def test_pipeline_chaos_degrades_with_identical_results(site, mode):
    """Armed chaos at each pipeline fail site: raise drains the window
    to the strict sequential path, latency just slows it — either way
    the final state/stores are byte-identical to the clean serial run
    and no buffered write is lost."""
    gdoc, privs = make_genesis(4)
    blocks, commits, _ = build_chain(gdoc, privs, 12)
    ex1, store1, st1, b1, s1 = _fresh(gdoc, grouped=False)
    st1 = _replay_all(ex1, store1, st1, blocks, commits, window=12)

    pipeline.set_config(enable=True, depth=3, group_commit_heights=4)
    fail.set_mode(site, mode)
    try:
        ex2, store2, st2, b2, s2 = _fresh(gdoc, grouped=True)
        st2 = _replay_all(ex2, store2, st2, blocks, commits, window=12)
    finally:
        fail.clear()
        pipeline.set_config(enable=False)
    assert fail.fired(site, mode) >= 1, "chaos never injected"
    assert safe_codec.dumps(st1) == safe_codec.dumps(st2)
    assert _raw(b1) == _raw(b2) and _raw(s1) == _raw(s2)
    assert b2.pending_ops() == 0 and s2.pending_ops() == 0


def test_pipeline_raise_chaos_counts_strict_path_blocks():
    """A raise at the stage site must actually degrade: the strict
    path counter moves and the degraded-window count increments."""
    from tendermint_tpu.libs.metrics import BlockSyncMetrics
    m = BlockSyncMetrics()
    before = m.blocks_applied.value(path="strict")
    gdoc, privs = make_genesis(4)
    blocks, commits, _ = build_chain(gdoc, privs, 8)
    p = pipeline.set_config(enable=True, depth=2, group_commit_heights=4)
    fail.set_mode("pipeline.stage", "raise")
    try:
        ex, store, st, *_ = _fresh(gdoc)
        st = _replay_all(ex, store, st, blocks, commits, window=8)
    finally:
        fail.clear()
        pipeline.set_config(enable=False)
    assert st.last_block_height == 8
    assert m.blocks_applied.value(path="strict") - before >= 8
    assert p.windows_degraded >= 1


def test_pipeline_stage_starvation_degrades():
    """Queue-overflow/starvation class: a stage handoff that never
    arrives inside the timeout degrades the window instead of hanging
    the sync thread."""
    gdoc, privs = make_genesis(4)
    blocks, commits, _ = build_chain(gdoc, privs, 6)
    p = pipeline.set_config(enable=True, depth=2, group_commit_heights=4)
    p._stage_timeout_s = 0.05
    fail.set_mode("pipeline.stage", "latency:400")
    try:
        ex, store, st, *_ = _fresh(gdoc)
        t0 = time.monotonic()
        st = _replay_all(ex, store, st, blocks, commits, window=6)
        assert time.monotonic() - t0 < 10.0
    finally:
        fail.clear()
        pipeline.set_config(enable=False)
    assert st.last_block_height == 6
    assert p.windows_degraded >= 1


# ---------------------------------------------------------------------------
# observability acceptance
# ---------------------------------------------------------------------------

def test_pipeline_spans_and_metrics_published():
    from tendermint_tpu.libs.metrics import BlockSyncMetrics

    m = BlockSyncMetrics()
    base_pipelined = m.blocks_applied.value(path="pipelined")
    gdoc, privs = make_genesis(4)
    blocks, commits, _ = build_chain(gdoc, privs, 12)
    since = trace.last_seq()
    trace.enable(capacity=4096)
    pipeline.set_config(enable=True, depth=3, group_commit_heights=4)
    try:
        ex, store, st, bdb, sdb = _fresh(gdoc)
        st = _replay_all(ex, store, st, blocks, commits, window=12)
    finally:
        pipeline.set_config(enable=False)
        spans = trace.snapshot(since=since)
        trace.disable()
    assert st.last_block_height == 12
    assert m.blocks_applied.value(path="pipelined") - base_pipelined >= 12
    # group commits really happened and were timed
    assert m.group_commit_seconds.count() >= 1
    got = {s["name"] for s in spans}
    for name in ("pipeline.stage", "pipeline.apply", "pipeline.commit"):
        assert name in got, (name, sorted(got)[:20])
    # the stage worker really ran ahead of apply: some stage span for a
    # LATER height starts before the apply span for height h ends
    stages = {s["attrs"].get("height"): s for s in spans
              if s["name"] == "pipeline.stage"}
    applies = {s["attrs"].get("height"): s for s in spans
               if s["name"] == "pipeline.apply"}
    overlapped = any(
        h + 1 in stages
        and stages[h + 1]["ts_ns"] < a["ts_ns"] + a["dur_ns"]
        for h, a in applies.items() if isinstance(h, int))
    assert overlapped, "no stage/apply overlap observed"


# ---------------------------------------------------------------------------
# crash consistency: kill between group commits -> reopen -> resume
# ---------------------------------------------------------------------------

_KILL_CHILD = r"""
REPO_DIR = @@REPO@@
import os, sys
sys.path.insert(0, REPO_DIR)
sys.path.insert(0, os.path.join(REPO_DIR, "tests"))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["TM_TPU_DISABLE_BATCH"] = "1"

from helpers import build_chain, make_genesis
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.blocksync.replay import replay_window
import tendermint_tpu.libs.kvdb as kv
from tendermint_tpu.state import pipeline
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.state import state_from_genesis
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.store.block_store import BlockStore

home, kill_at = sys.argv[1], int(sys.argv[2])
gdoc, privs = make_genesis(4)
blocks, commits, states = build_chain(gdoc, privs, 24)

# die IMMEDIATELY before the kill_at-th group-commit write lands: the
# process vanishes mid-stream, no recovery flush, no close()
calls = {"n": 0}
orig = kv.GroupCommitDB._commit_one
def dying(self, group):
    calls["n"] += 1
    if calls["n"] == kill_at:
        os._exit(77)
    return orig(self, group)
kv.GroupCommitDB._commit_one = dying

bdb = kv.GroupCommitDB(kv.SQLiteDB(os.path.join(home, "blocks.db")))
sdb = kv.GroupCommitDB(kv.SQLiteDB(os.path.join(home, "state.db")))
ex = BlockExecutor(StateStore(sdb), KVStoreApplication())
store = BlockStore(bdb)
state = state_from_genesis(gdoc)
pipeline.set_config(enable=True, depth=3, group_commit_heights=4)
state, n = replay_window(ex, store, state, blocks, commits, max_window=24)
sys.exit(3)  # the kill should have fired mid-window
"""


@pytest.mark.parametrize("kill_at,want_store,want_state", [
    # commit sequence per group of 4 heights: block batch, state batch.
    # kill before commit #3 (block group 2): groups 1 durable -> 4/4
    (3, 4, 4),
    # kill before commit #4 (state group 2): block store one full group
    # AHEAD of the state store — the asymmetric crash window ADR-017's
    # ordering exists for
    (4, 8, 4),
])
def test_kill_between_group_commits_reopen_resume(tmp_path, kill_at,
                                                  want_store, want_state):
    """Child process really dies (os._exit) between group commits; the
    parent reopens the SQLite files, checks the durability invariants
    (store height monotonic, state never ahead of its block), replays
    the handshake gap, resumes pipelined replay, and lands on the
    byte-exact oracle app hash."""
    from tendermint_tpu.node.node import handshake

    home = str(tmp_path)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "-c",
         _KILL_CHILD.replace("@@REPO@@", repr(REPO)), home,
         str(kill_at)],
        env=env, capture_output=True, timeout=180)
    assert r.returncode == 77, (
        f"child rc={r.returncode}\n"
        f"stderr: {r.stderr[-2000:].decode(errors='replace')}")

    gdoc, privs = make_genesis(4)
    blocks, commits, states = build_chain(gdoc, privs, 24)

    bdb = SQLiteDB(os.path.join(home, "blocks.db"))
    sdb = SQLiteDB(os.path.join(home, "state.db"))
    store, sstore = BlockStore(bdb), StateStore(sdb)
    st = sstore.load()
    state_h = st.last_block_height if st is not None else 0
    assert store.height() == want_store
    assert state_h == want_state
    assert state_h <= store.height(), "state ran ahead of its block"
    # every stored block is intact and linked
    for h in range(1, store.height() + 1):
        b = store.load_block(h)
        assert b is not None and b.hash() == blocks[h - 1].hash()

    # handshake rebuilds the gap (up to one commit group) into a fresh
    # app + state, then pipelined replay resumes to the chain tip
    if st is None:
        st = state_from_genesis(gdoc)
    app = KVStoreApplication()
    st = handshake(app, st, sstore, store, gdoc)
    assert st.last_block_height == store.height()
    ex = BlockExecutor(sstore, app)
    pipeline.set_config(enable=True, depth=3, group_commit_heights=4)
    try:
        st = _replay_all(ex, store, st, blocks[st.last_block_height:],
                         commits[st.last_block_height:], window=16)
    finally:
        pipeline.set_config(enable=False)
    assert st.last_block_height == 24
    assert st.app_hash == states[-1].app_hash
    bdb.close()
    sdb.close()


def test_handshake_recovers_multi_height_gap():
    """In-process twin of the subprocess matrix: state store left 3
    heights behind the block store (one group) must rebuild height by
    height — the pre-ADR-017 handshake refused anything past 1."""
    from tendermint_tpu.node.node import handshake

    gdoc, privs = make_genesis(4)
    blocks, commits, states = build_chain(gdoc, privs, 9)
    ex, store, st, bdb, sdb = _fresh(gdoc, grouped=False)
    st = _replay_all(ex, store, st, blocks, commits, window=9)
    assert store.height() == 9

    # simulate the crash window: a state store that only saw height 6
    sstore2 = StateStore(MemDB())
    ex2, store2 = BlockExecutor(sstore2, KVStoreApplication()), store
    st6 = states[5]
    sstore2.bootstrap(st6)
    app = KVStoreApplication()
    st_re = handshake(app, sstore2.load(), sstore2, store2, gdoc)
    assert st_re.last_block_height == 9
    assert st_re.app_hash == states[-1].app_hash


# ---------------------------------------------------------------------------
# satellites: kvdb
# ---------------------------------------------------------------------------

def test_prefix_upper_bound():
    assert prefix_upper_bound(b"P:") == b"P;"
    assert prefix_upper_bound(b"a\xff\xff") == b"b"
    assert prefix_upper_bound(b"\xff") is None
    assert prefix_upper_bound(b"") is None


def test_sqlite_iterate_prefix_long_keys(tmp_path):
    """Regression: the old upper bound prefix+8x\\xff dropped keys more
    than 8 bytes longer than the prefix (part keys at 7+-digit
    heights)."""
    db = SQLiteDB(str(tmp_path / "kv.db"))
    long_keys = [b"P:12345678:123", b"P:" + b"z" * 40, b"P:1:0",
                 b"P:\xff\xff\xff\xff\xff\xff\xff\xff\xffx"]
    for k in long_keys:
        db.set(k, b"v" + k)
    db.set(b"Q:other", b"no")
    got = [k for k, _ in db.iterate_prefix(b"P:")]
    assert got == sorted(long_keys)
    # prefix whose successor needs the trailing-0xff strip
    db.set(b"a\xff\xff\x01" + b"k" * 20, b"deep")
    assert [k for k, _ in db.iterate_prefix(b"a\xff\xff")] == \
        [b"a\xff\xff\x01" + b"k" * 20]
    db.close()


def test_sqlite_deferred_single_writes(tmp_path):
    """set/delete defer their COMMIT into a bounded window: a second
    connection (= a crashed process's view) sees nothing until the
    window fills, a write_batch lands, or flush()/close() runs — and
    then sees everything at once."""
    import sqlite3

    path = str(tmp_path / "kv.db")
    db = SQLiteDB(path, commit_every=4)
    other = sqlite3.connect(path)

    def other_count():
        return other.execute("SELECT COUNT(*) FROM kv").fetchone()[0]

    db.set(b"a", b"1")
    db.set(b"b", b"2")
    assert db.get(b"a") == b"1"          # same-connection visibility
    assert other_count() == 0            # not yet durable
    db.set(b"c", b"3")
    db.set(b"d", b"4")                   # 4th write commits the window
    assert other_count() == 4
    db.set(b"e", b"5")
    assert other_count() == 4
    db.write_batch([(b"f", b"6")])       # batch commit flushes deferred
    assert other_count() == 6
    db.set(b"g", b"7")
    db.flush()
    assert other_count() == 7
    db.set(b"h", b"8")
    db.close()                           # close keeps its commit contract
    other2 = sqlite3.connect(path)
    assert other2.execute("SELECT COUNT(*) FROM kv").fetchone()[0] == 8
    other.close()
    other2.close()


def test_save_seen_commit_is_batch_committed():
    """BlockStore.save_seen_commit must ride write_batch (immediately
    durable), not the deferred single-op window."""
    calls = []

    class Spy(MemDB):
        def set(self, k, v):
            calls.append(("set", bytes(k)))
            super().set(k, v)

        def write_batch(self, sets, deletes=()):
            calls.append(("batch", [bytes(k) for k, _ in sets]))
            super().write_batch(sets, deletes)

    gdoc, privs = make_genesis(4)
    blocks, commits, _ = build_chain(gdoc, privs, 1)
    store = BlockStore(Spy())
    calls.clear()
    store.save_seen_commit(1, commits[0])
    assert calls and calls[0][0] == "batch"
    assert not any(c[0] == "set" for c in calls)
    assert store.load_seen_commit(1) is not None


def test_group_commit_db_modes_and_merge():
    inner = MemDB()
    g = GroupCommitDB(inner)
    # pass-through by default
    g.set(b"a", b"1")
    assert inner.get(b"a") == b"1"
    g.begin_group_mode()
    g.set(b"b", b"2")
    g.delete(b"a")
    g.write_batch([(b"c", b"3")], deletes=[b"nope"])
    # read-your-writes incl. tombstones; inner untouched
    assert g.get(b"b") == b"2" and g.get(b"a") is None
    assert g.get(b"c") == b"3" and inner.get(b"b") is None
    assert g.has(b"c") and not g.has(b"a")
    # iterate merges buffered over inner, sorted, tombstones hidden
    assert [k for k, _ in g.iterate_prefix(b"")] == [b"b", b"c"]
    # async handoff keeps visibility until the commit lands
    grp = g.take_group()
    # buffered ops: b, c, and the two tombstones (a, nope)
    assert g.get(b"b") == b"2" and g.pending_ops() == 4
    g.commit_group(grp)
    assert inner.get(b"b") == b"2" and inner.get(b"a") is None
    assert g.pending_ops() == 0
    # end_group_mode flushes whatever is left and returns to pass-through
    g.set(b"d", b"4")
    g.end_group_mode()
    assert inner.get(b"d") == b"4" and not g.group_mode()
    g.set(b"e", b"5")
    assert inner.get(b"e") == b"5"


def test_group_commit_db_single_batch_per_group():
    """One group = ONE inner write_batch (the whole durability story)."""
    batches = []

    class Spy(MemDB):
        def write_batch(self, sets, deletes=()):
            batches.append((len(list(sets)), len(list(deletes))))
            super().write_batch(sets, deletes)

    g = GroupCommitDB(Spy())
    g.begin_group_mode()
    for i in range(10):
        g.set(b"k%d" % i, b"v")
    g.delete(b"k3")
    g.flush()
    assert batches == [(9, 1)]
    g.end_group_mode()


# ---------------------------------------------------------------------------
# satellites: merkle
# ---------------------------------------------------------------------------

def _rec_root(items):
    """The pre-ADR-017 recursive reference implementation (oracle)."""
    import hashlib

    def sha(b):
        return hashlib.sha256(b).digest()

    n = len(items)
    if n == 0:
        return sha(b"")
    if n == 1:
        return sha(b"\x00" + items[0])
    k = 1 << (n - 1).bit_length() - 1
    if k == n:
        k >>= 1
    return sha(b"\x01" + _rec_root(items[:k]) + _rec_root(items[k:]))


def test_merkle_iterative_matches_recursive_oracle():
    import random

    rng = random.Random(0xAD17)
    for n in list(range(0, 40)) + [63, 64, 65, 100, 127, 128, 129, 200]:
        items = [rng.randbytes(rng.randrange(0, 200)) for _ in range(n)]
        root = merkle.hash_from_byte_slices(items)
        assert root == _rec_root(items), n
        proot, proofs = merkle.proofs_from_byte_slices(items)
        if n:
            assert proot == root
        assert len(proofs) == n
        for i, p in enumerate(proofs):
            assert p.verify(root, items[i]), (n, i)
            # aunts round-trip through the wire-form compute too
            assert p.compute_root() == root


def test_merkle_iterative_no_recursion_limit():
    """The iterative form survives leaf counts that would blow the
    recursion limit at default settings if each leaf added a frame."""
    items = [b"%d" % i for i in range(5000)]
    assert merkle.hash_from_byte_slices(items) == _rec_root(items)


# ---------------------------------------------------------------------------
# config / env wiring
# ---------------------------------------------------------------------------

def test_set_config_wins_over_env_both_ways(monkeypatch):
    # env says off, config says on -> on
    monkeypatch.setenv("TM_TPU_BLOCK_PIPELINE", "0")
    p = pipeline.set_config(enable=True, depth=2, group_commit_heights=3)
    assert p is not None and p.is_running() and p.depth == 2
    assert pipeline.running() is p
    # env says on, config says off -> off (stopped + uninstalled)
    monkeypatch.setenv("TM_TPU_BLOCK_PIPELINE", "1")
    assert pipeline.set_config(enable=False) is None
    assert pipeline.installed() is None and not p.is_running()
    # None defers to env
    monkeypatch.setenv("TM_TPU_BLOCK_PIPELINE", "0")
    assert pipeline.set_config(enable=None) is None
    monkeypatch.delenv("TM_TPU_BLOCK_PIPELINE")
    monkeypatch.setenv("TM_TPU_PIPELINE_DEPTH", "5")
    monkeypatch.setenv("TM_TPU_GROUP_COMMIT_HEIGHTS", "11")
    p = pipeline.set_config(enable=None)
    assert p is not None and p.depth == 5 and p.group_commit_heights == 11
    # live reconfiguration re-resolves the env too: same depth updates
    # in place, a depth change rebuilds the service
    monkeypatch.setenv("TM_TPU_GROUP_COMMIT_HEIGHTS", "13")
    p2 = pipeline.set_config(enable=None)
    assert p2 is p and p2.group_commit_heights == 13
    monkeypatch.setenv("TM_TPU_PIPELINE_DEPTH", "6")
    p3 = pipeline.set_config(enable=None)
    assert p3 is not p and p3.depth == 6 and p3.is_running()
    assert not p.is_running()
    pipeline.set_config(enable=False)


def test_node_wires_pipeline_and_group_dbs(tmp_path):
    """A default-config node wraps its stores in GroupCommitDB, installs
    + starts the pipeline, and tears all of it down on stop."""
    from tendermint_tpu.config.config import Config
    from tendermint_tpu.node import Node
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.types.basic import Timestamp
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    cfg = Config(home=str(tmp_path / "home"))
    cfg.p2p.laddr = "127.0.0.1:0"
    cfg.p2p.pex = False
    cfg.rpc.enabled = False
    cfg.ensure_dirs()
    pv = FilePV.load_or_generate(cfg.priv_validator_key_file(),
                                 cfg.priv_validator_state_file())
    NodeKey.load_or_generate(cfg.node_key_file())
    pub = pv.get_pub_key()
    gdoc = GenesisDoc(chain_id="pipe-wire-chain",
                      genesis_time=Timestamp(1700000000, 0),
                      validators=[GenesisValidator(
                          address=pub.address(), pub_key_type=pub.type_name,
                          pub_key_bytes=pub.bytes(), power=10)])
    node = Node(cfg, KVStoreApplication(), genesis=gdoc, in_memory=True)
    assert isinstance(node.block_store.db, GroupCommitDB)
    assert isinstance(node.state_store.db, GroupCommitDB)
    node.start()
    try:
        assert pipeline.running() is not None
    finally:
        node.stop()
    assert pipeline.installed() is None

    # enable=False: plain stores, nothing installed
    cfg2 = Config(home=str(tmp_path / "home2"))
    cfg2.p2p.laddr = "127.0.0.1:0"
    cfg2.p2p.pex = False
    cfg2.rpc.enabled = False
    cfg2.block_pipeline.enable = False
    cfg2.ensure_dirs()
    FilePV.load_or_generate(cfg2.priv_validator_key_file(),
                            cfg2.priv_validator_state_file())
    NodeKey.load_or_generate(cfg2.node_key_file())
    node2 = Node(cfg2, KVStoreApplication(), genesis=gdoc, in_memory=True)
    assert not isinstance(node2.block_store.db, GroupCommitDB)
    node2.start()
    try:
        assert pipeline.installed() is None
    finally:
        node2.stop()
