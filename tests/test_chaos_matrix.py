"""Chaos matrix over the device verify lane (crypto/degrade.py).

Counterpart of tests/test_crash_matrix.py for the NON-fatal failure
classes: instead of killing the process at indexed fail points, each
case arms a libs/fail.py mode at the device-lane seams and asserts the
degradation runtime's contract — BatchVerifier.verify() returns the
EXACT bitmap of the pure-host path (no hang, no crash, no exception)
under every injected failure class, and the circuit breaker demonstrably
opens, backs off, and re-closes (ISSUE 1 acceptance criteria).

The device lane here is the XLA-composed kernel forced onto CPU
(TM_TPU_FORCE_BATCH=1, same trick as the sr25519 lane tests): the
degradation runtime sits strictly above the kernel, so the failure
plumbing exercised is exactly what runs against real hardware.
"""
from __future__ import annotations

import random

import numpy as np
import pytest

from tendermint_tpu.crypto import batch as cb
from tendermint_tpu.crypto import degrade
from tendermint_tpu.crypto import ed25519 as edkeys
from tendermint_tpu.libs import fail
from tendermint_tpu.libs.metrics import Registry

rng = random.Random(77)


@pytest.fixture(autouse=True)
def _force_device(monkeypatch):
    monkeypatch.setenv("TM_TPU_FORCE_BATCH", "1")
    monkeypatch.delenv("TM_TPU_DISABLE_BATCH", raising=False)
    fail.reset()
    yield
    fail.reset()
    degrade.reset()


def _runtime(clk=None, **kw):
    cfg = degrade.DegradeConfig(
        failure_threshold=kw.pop("failure_threshold", 3),
        launch_timeout_s=kw.pop("launch_timeout_s", 120.0),
        backoff_base_s=10.0, backoff_max_s=100.0, backoff_jitter=0.0)
    return degrade.configure(cfg, clock=clk or (lambda: 0.0),
                             registry=Registry("chaos"))


def _mixed_batch(n=24, bad=(3, 11, 17)):
    """n ed25519 triples, `bad` lanes invalid (flipped sig byte, one
    truncated) — the bitmap must attribute failures exactly."""
    privs = [edkeys.PrivKey(bytes([i + 1]) * 32) for i in range(n)]
    msgs = [b"chaos vote %d" % i for i in range(n)]
    sigs = [p.sign(m) for p, m in zip(privs, msgs)]
    for i in bad:
        sigs[i] = (sigs[i][:50] if i == bad[-1]
                   else bytes([sigs[i][0] ^ 1]) + sigs[i][1:])
    return privs, msgs, sigs


def _verify(privs, msgs, sigs, threshold=4):
    bv = cb.BatchVerifier(tpu_threshold=threshold)
    for p, m, s in zip(privs, msgs, sigs):
        bv.add(p.pub_key(), m, s)
    return bv.verify()


def _host_baseline(privs, msgs, sigs, monkeypatch):
    monkeypatch.setenv("TM_TPU_DISABLE_BATCH", "1")
    try:
        _, bits = _verify(privs, msgs, sigs)
    finally:
        monkeypatch.delenv("TM_TPU_DISABLE_BATCH")
    return bits


# (site, mode, failure-class counter the case must increment)
CASES = [
    (None, None, None),                                   # control
    ("ops.ed25519.verify_batch", "raise", "raise"),       # device raises
    ("ops.ed25519.verify_batch", "latency:25", None),     # slow, in budget
    ("batch.ed25519", "corrupt-bitmap", "integrity"),     # garbage bitmap
]


@pytest.mark.parametrize("site,mode,reason", CASES,
                         ids=["control", "raise", "latency", "corrupt"])
def test_bitmap_identical_to_host_under_injection(monkeypatch, site,
                                                  mode, reason):
    rt = _runtime()
    privs, msgs, sigs = _mixed_batch()
    base = _host_baseline(privs, msgs, sigs, monkeypatch)
    assert not base.all() and base.sum() == len(privs) - 3
    if site:
        fail.set_mode(site, mode)
    ok, bits = _verify(privs, msgs, sigs)
    assert (bits == base).all(), (mode, bits, base)
    assert ok == bool(base.all())
    if mode:
        assert fail.fired(site, mode) >= 1, "injection never triggered"
    if reason:
        assert rt.metrics.device_failures.value(
            site="batch.ed25519", reason=reason) == 1
        assert rt.metrics.host_fallbacks.value(
            site="batch.ed25519", reason=reason) == 1


def test_sr25519_lane_chaos_raise_bitmap_exact():
    """The ristretto lane's chaos seam (ops.sr25519.verify_batch — a
    registered site in libs/fail.REGISTERED_SITES, asserted exercised
    by tests/test_lint.py): an injected raise at the lane entry
    degrades to host re-verify with the exact per-sig bitmap.  The
    injection fires at function entry BEFORE any staging or kernel
    dispatch, so this spends no XLA compile budget on the sr kernel."""
    from tendermint_tpu.crypto import sr25519 as srpy

    rt = _runtime()
    n = 6
    minis = [(0xBEE0 + i).to_bytes(32, "little") for i in range(n)]
    msgs = [b"sr chaos %d" % i for i in range(n)]
    sigs = [srpy.sign(minis[i], msgs[i]) for i in range(n)]
    sigs[2] = bytes([sigs[2][0] ^ 1]) + sigs[2][1:]  # tamper R
    pubs = [srpy.PrivKey(m).pub_key() for m in minis]
    fail.set_mode("ops.sr25519.verify_batch", "raise")
    bv = cb.BatchVerifier(tpu_threshold=4)
    for p, m, s in zip(pubs, msgs, sigs):
        bv.add(p, m, s)
    ok, bits = bv.verify()
    assert not ok
    assert bits.tolist() == [True, True, False, True, True, True]
    assert fail.fired("ops.sr25519.verify_batch", "raise") >= 1
    assert rt.metrics.device_failures.value(
        site="batch.sr25519", reason="raise") == 1
    assert rt.metrics.host_fallbacks.value(
        site="batch.sr25519", reason="raise") == 1


def _secp_batch(n=6, bad=(2,)):
    from tendermint_tpu.crypto import secp256k1 as secp

    privs = [secp.PrivKey.gen_from_secret((0xC500 + i).to_bytes(32, "big"))
             for i in range(n)]
    msgs = [b"secp chaos %d" % i for i in range(n)]
    sigs = [p.sign(m) for p, m in zip(privs, msgs)]
    for i in bad:
        sigs[i] = bytes([sigs[i][0] ^ 1]) + sigs[i][1:]
    return [p.pub_key() for p in privs], msgs, sigs


def test_secp_device_lane_chaos_raise_bitmap_exact():
    """The secp256k1 lane is default-on (ADR-015) and its chaos seam
    (ops.secp.verify_batch, registered in libs/fail.REGISTERED_SITES,
    asserted exercised by tests/test_lint.py) degrades to the host C
    lane with the exact per-sig bitmap.  Like the sr25519 twin above,
    the injection fires at function entry BEFORE any staging or kernel
    dispatch — no XLA compile budget spent on the secp ladder."""
    rt = _runtime()
    pubs, msgs, sigs = _secp_batch()
    fail.set_mode("ops.secp.verify_batch", "raise")
    bv = cb.BatchVerifier(tpu_threshold=4)
    for p, m, s in zip(pubs, msgs, sigs):
        bv.add(p, m, s)
    ok, bits = bv.verify()
    assert not ok
    assert bits.tolist() == [True, True, False, True, True, True]
    assert fail.fired("ops.secp.verify_batch", "raise") >= 1
    assert rt.metrics.device_failures.value(
        site="batch.secp256k1", reason="raise") == 1
    assert rt.metrics.host_fallbacks.value(
        site="batch.secp256k1", reason="raise") == 1


def test_secp_lane_latency_timeout_and_corrupt_bitmap(monkeypatch):
    """The remaining secp failure classes — a stalled launch past its
    deadline and a garbage bitmap caught by the host spot check — with
    the kernel stubbed by the host oracle: the degrade plumbing under
    test sits strictly ABOVE the kernel, and running the real 64-step
    complete-add ladder would cost a multi-minute XLA-on-CPU compile
    (its own bitmap is pinned in test_secp_lane's slow tier)."""
    from tendermint_tpu.crypto import secp256k1 as secp
    from tendermint_tpu.ops import secp as secp_ops

    def stub(pubs_, msgs_, sigs_):
        # batch.py hands the device verifier raw key bytes
        fail.inject("ops.secp.verify_batch")
        return np.array([secp.PubKey(bytes(p)).verify_signature(m, s)
                         for p, m, s in zip(pubs_, msgs_, sigs_)])

    monkeypatch.setattr(secp_ops, "verify_batch_device", stub)
    pubs, msgs, sigs = _secp_batch()

    def run(rt):
        bv = cb.BatchVerifier(tpu_threshold=4)
        for p, m, s in zip(pubs, msgs, sigs):
            bv.add(p, m, s)
        return bv.verify()

    # timeout class: stalled past the launch deadline -> quarantine +
    # host re-verify, bitmap exact
    rt = _runtime(launch_timeout_s=0.05)
    fail.set_mode("ops.secp.verify_batch", "latency:400")
    ok, bits = run(rt)
    assert bits.tolist() == [True, True, False, True, True, True]
    assert rt.metrics.device_failures.value(
        site="batch.secp256k1", reason="timeout") == 1
    fail.clear()

    # integrity class: corrupt bitmap at the degrade seam -> spot check
    # catches it -> host re-verify, bitmap exact
    monkeypatch.setattr(cb, "verified_sigs", cb.SigCache())
    rt = _runtime()
    fail.set_mode("batch.secp256k1", "corrupt-bitmap")
    ok, bits = run(rt)
    assert bits.tolist() == [True, True, False, True, True, True]
    assert fail.fired("batch.secp256k1", "corrupt-bitmap") >= 1
    assert rt.metrics.device_failures.value(
        site="batch.secp256k1", reason="integrity") == 1
    assert rt.metrics.host_fallbacks.value(
        site="batch.secp256k1", reason="integrity") == 1


def test_lanepool_chaos_all_modes_bitmap_exact():
    """The host-lane pool's chaos seam (lanepool.verify, ADR-015):
    raise, latency and corrupt-bitmap each degrade to the serial
    in-caller C path with the exact per-index bitmap.  No device, no
    kernels — this is pure host-pool plumbing."""
    from tendermint_tpu.crypto import lanepool
    from tendermint_tpu.libs import native

    if native.get_lib() is None:
        pytest.skip("no C toolchain: native lane unavailable")
    pubs, msgs, sigs = _secp_batch(n=32, bad=(3, 19))
    pb = [p.bytes() for p in pubs]
    want = [p.verify_signature(m, s)
            for p, m, s in zip(pubs, msgs, sigs)]
    # pin the pool size: corrupt-bitmap only fires on the POOLED path
    # (the chunked merge), and a 1-core runner would otherwise resolve
    # pool() to None and never exercise it
    lanepool.set_workers(2)
    try:
        for mode in ("raise", "latency:20", "corrupt-bitmap"):
            fail.reset()
            fail.set_mode("lanepool.verify", mode)
            got = lanepool.verify_sharded("secp256k1", pb, msgs, sigs)
            assert got is not None and got.tolist() == want, mode
            assert fail.fired("lanepool.verify", mode) >= 1, \
                "injection never triggered"
    finally:
        lanepool.set_workers(None)


def test_latency_past_deadline_times_out_bitmap_exact(monkeypatch):
    """The timeout class: a launch stalled past its wall-clock budget is
    abandoned and the batch re-verifies host-side — same bitmap, no
    hang.  Warm the kernel first so the tight deadline measures the
    injected stall, not jit compile."""
    rt = _runtime(launch_timeout_s=120.0)
    privs, msgs, sigs = _mixed_batch()
    base = _host_baseline(privs, msgs, sigs, monkeypatch)
    _verify(privs, msgs, sigs)  # warmup/compile through the device lane
    assert rt.breaker.state == degrade.CLOSED
    rt.cfg.launch_timeout_s = 0.05
    fail.set_mode("ops.ed25519.verify_batch", "latency:400")
    ok, bits = _verify(privs, msgs, sigs)
    assert (bits == base).all()
    assert rt.metrics.device_failures.value(
        site="batch.ed25519", reason="timeout") == 1
    # the quarantined worker must not poison the next launch
    rt.cfg.launch_timeout_s = 120.0
    fail.clear()
    ok, bits = _verify(privs, msgs, sigs)
    assert (bits == base).all()


def test_breaker_opens_backs_off_and_recloses(monkeypatch):
    """The acceptance-criteria lifecycle, through the production verify
    seam: N consecutive device faults open the breaker (everything
    host-side, no device launches), the open interval backs off, a
    post-deadline probe re-closes it, and the bitmap is host-exact at
    every step."""
    clk_t = [0.0]
    rt = _runtime(clk=lambda: clk_t[0], failure_threshold=2)
    trans = []
    rt.breaker.add_listener(lambda o, n, r: trans.append((o, n)))
    privs, msgs, sigs = _mixed_batch()
    base = _host_baseline(privs, msgs, sigs, monkeypatch)

    fail.set_mode("ops.ed25519.verify_batch", "raise")
    for _ in range(2):
        ok, bits = _verify(privs, msgs, sigs)
        assert (bits == base).all()
    assert rt.breaker.state == degrade.OPEN
    launches_when_open = rt.metrics.device_launches.value(
        site="batch.ed25519")

    # open: host-routed, zero new device launches, bitmap exact
    ok, bits = _verify(privs, msgs, sigs)
    assert (bits == base).all()
    assert rt.metrics.device_launches.value(site="batch.ed25519") == \
        launches_when_open
    assert rt.metrics.host_fallbacks.value(
        site="batch.ed25519", reason="breaker_open") == 1

    # before the backoff deadline the probe is still denied
    clk_t[0] = 9.9
    _verify(privs, msgs, sigs)
    assert rt.breaker.state == degrade.OPEN

    # device healthy again + deadline passed -> half-open probe -> close
    fail.clear()
    clk_t[0] = 10.1
    ok, bits = _verify(privs, msgs, sigs)
    assert (bits == base).all()
    assert rt.breaker.state == degrade.CLOSED
    assert (degrade.OPEN, degrade.HALF_OPEN) in trans
    assert (degrade.HALF_OPEN, degrade.CLOSED) in trans

    # and the re-closed lane actually serves from the device again
    before = rt.metrics.device_launches.value(site="batch.ed25519")
    ok, bits = _verify(privs, msgs, sigs)
    assert (bits == base).all()
    assert rt.metrics.device_launches.value(site="batch.ed25519") == \
        before + 1


def test_chaos_sweep_bulk_seam(monkeypatch):
    """Same sweep through verify_sigs_bulk (the whole-commit path, raw
    pubkey matrix — no per-key objects) — every injected class must
    yield the host-exact bitmap."""
    _runtime()
    privs, msgs, sigs = _mixed_batch(n=16, bad=(2, 9))
    pubs = np.stack([np.frombuffer(p.pub_key().bytes(), np.uint8)
                     for p in privs])
    sig_list = [bytes(s) for s in sigs]
    monkeypatch.setenv("TM_TPU_DISABLE_BATCH", "1")
    base = cb.verify_sigs_bulk(pubs, msgs, sig_list, tpu_threshold=4)
    monkeypatch.delenv("TM_TPU_DISABLE_BATCH")
    assert base.sum() == 14
    for site, mode in ((None, None),
                       ("ops.ed25519.verify_batch", "raise"),
                       ("bulk.ed25519", "corrupt-bitmap")):
        fail.reset()
        degrade.configure(degrade.DegradeConfig(backoff_jitter=0.0),
                          registry=Registry("chaos2"))
        if site:
            fail.set_mode(site, mode)
        bits = cb.verify_sigs_bulk(pubs, msgs, sig_list, tpu_threshold=4)
        assert (bits == base).all(), (mode, bits, base)
        if site:
            assert fail.fired(site, mode) >= 1
