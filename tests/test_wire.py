"""Wire-format (proto encode/decode) and Byzantine-input hardening tests.

VERDICT r1 weak #4: gossiped block parts must never be able to execute code
or kill the node.  The gossip encoding is now the deterministic proto Block
encoding (types/block.py proto()/from_proto()), and malformed bytes raise
protodec.ProtoError, which the consensus peer path treats as a bad peer,
not a consensus failure.
"""
import pickle

import pytest

from tendermint_tpu.libs import protodec as pd
from tendermint_tpu.types.basic import (
    BlockID, BlockIDFlag, PartSetHeader, SignedMsgType, Timestamp)
from tendermint_tpu.types.block import Block, Consensus, Data, Header
from tendermint_tpu.types.commit import Commit, CommitSig
from tendermint_tpu.types.part_set import PartSet
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.vote import Vote


def _sample_block() -> Block:
    commit = Commit(
        height=6, round=1,
        block_id=BlockID(b"\x11" * 32, PartSetHeader(2, b"\x22" * 32)),
        signatures=[
            CommitSig(BlockIDFlag.COMMIT, b"\x01" * 20,
                      Timestamp(1234567890, 999), b"\x55" * 64),
            CommitSig.absent(),
            CommitSig(BlockIDFlag.NIL, b"\x02" * 20,
                      Timestamp(1234567891, 1), b"\x66" * 64),
        ])
    block = Block(
        header=Header(
            version=Consensus(block=11, app=3),
            chain_id="test-chain", height=7,
            time=Timestamp(1700000000, 123456789),
            last_block_id=BlockID(b"\x11" * 32,
                                  PartSetHeader(2, b"\x22" * 32)),
            validators_hash=b"\x33" * 32,
            next_validators_hash=b"\x34" * 32,
            consensus_hash=b"\x35" * 32,
            app_hash=b"\x42" * 8,
            proposer_address=b"\x01" * 20,
        ),
        data=Data(txs=[b"tx-1", b"", b"tx-3" * 100]),
        last_commit=commit)
    block.fill_header()
    return block


def test_block_proto_roundtrip():
    block = _sample_block()
    data = block.proto()
    got = Block.from_proto(data)
    assert got.hash() == block.hash()
    assert got.proto() == data  # byte-stable re-encode
    assert got.data.txs == block.data.txs
    assert got.last_commit.hash() == block.last_commit.hash()
    assert got.header == block.header


def test_vote_proto_roundtrip():
    vote = Vote(type=SignedMsgType.PRECOMMIT, height=5, round=2,
                block_id=BlockID(b"\x0a" * 32, PartSetHeader(1, b"\x0b" * 32)),
                timestamp=Timestamp(1700000001, 42),
                validator_address=b"\x07" * 20, validator_index=3,
                signature=b"\x09" * 64)
    assert Vote.from_proto(vote.proto()) == vote
    # nil vote (zero block id) round-trips too
    nil_vote = Vote(type=SignedMsgType.PREVOTE, height=1, round=0,
                    block_id=BlockID(), timestamp=Timestamp.now(),
                    validator_address=b"\x01" * 20, validator_index=0,
                    signature=b"\x01")
    assert Vote.from_proto(nil_vote.proto()) == nil_vote


def test_proposal_proto_roundtrip_negative_polround():
    prop = Proposal(height=4, round=1, pol_round=-1,
                    block_id=BlockID(b"\x01" * 32,
                                     PartSetHeader(1, b"\x02" * 32)),
                    timestamp=Timestamp(1700000002, 7),
                    signature=b"\x03" * 64)
    got = Proposal.from_proto(prop.proto())
    assert got == prop
    assert got.pol_round == -1


def test_partset_roundtrip_through_parts():
    block = _sample_block()
    ps = PartSet.from_data(block.proto(), part_size=64)
    ps2 = PartSet(ps.header())
    for i in range(ps.header().total):
        part = ps.get_part(i)
        from tendermint_tpu.types.part_set import Part
        decoded = Part.from_proto(part.proto())
        assert ps2.add_part(decoded)
    assert Block.from_proto(ps2.assemble()).hash() == block.hash()


def test_malicious_pickle_payload_is_inert():
    """A part-set assembling to a pickle bomb must raise ProtoError — never
    unpickle (the round-1 RCE)."""
    class Evil:
        def __reduce__(self):
            return (print, ("pwned",))

    payload = pickle.dumps(Evil())
    with pytest.raises(ValueError):  # ProtoError subclasses ValueError
        Block.from_proto(payload)


def test_garbage_bytes_raise_proto_error():
    for garbage in (b"\xff" * 40, b"\x00", b"\x0a\xff", b"\x08"):
        with pytest.raises(pd.ProtoError):
            pd.parse(garbage) and Block.from_proto(garbage)


def test_block_validate_basic_unconditional_binding():
    """ADVICE r1 medium: empty data_hash must NOT bypass the
    header-to-content check (reference types/block.go:75-88)."""
    block = _sample_block()
    block.validate_basic()  # well-formed passes

    evil = _sample_block()
    evil.header.data_hash = b""          # "forgot" to commit to the data
    evil.data = Data(txs=[b"arbitrary injected tx"])
    with pytest.raises(ValueError, match="DataHash"):
        evil.validate_basic()

    evil2 = _sample_block()
    evil2.header.last_commit_hash = b""
    with pytest.raises(ValueError, match="LastCommitHash"):
        evil2.validate_basic()

    evil3 = _sample_block()
    evil3.last_commit = None
    with pytest.raises(ValueError, match="LastCommit"):
        evil3.validate_basic()
