"""Host-lane verify pool (crypto/lanepool.py, ADR-015): admission
semantics, sharded-bitmap exactness and order stability under
concurrency, saturation/disable fallbacks, and fault degradation."""
from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from tendermint_tpu.crypto import lanepool
from tendermint_tpu.crypto import secp256k1 as secp
from tendermint_tpu.crypto import sr25519 as sr
from tendermint_tpu.libs import fail
from tendermint_tpu.libs import native


@pytest.fixture(autouse=True)
def _fresh_pool():
    lanepool.set_workers(None)
    lanepool.close()
    fail.reset()
    yield
    fail.reset()
    lanepool.set_workers(None)
    lanepool.close()


def _secp_batch(n, bad=()):
    privs = [secp.PrivKey.gen_from_secret(b"lp%d" % i) for i in range(n)]
    msgs = [b"lanepool msg %d" % i for i in range(n)]
    sigs = [p.sign(m) for p, m in zip(privs, msgs)]
    for i in bad:
        sigs[i] = bytes([sigs[i][0] ^ 1]) + sigs[i][1:]
    pubs = [p.pub_key() for p in privs]
    return pubs, msgs, sigs


def _oracle(pubs, msgs, sigs):
    return [p.verify_signature(m, s) for p, m, s in zip(pubs, msgs, sigs)]


def _need_native():
    if native.get_lib() is None:
        pytest.skip("no C toolchain: native lane unavailable")


# ---------------------------------------------------------------------------
# HostLanePool mechanics
# ---------------------------------------------------------------------------

def test_pool_threads_are_daemon_and_close_joins():
    p = lanepool.HostLanePool(3, name="lp-test")
    try:
        assert all(t.daemon for t in p._threads)
        assert p.try_submit(lambda: 7).result(timeout=5) == 7
    finally:
        p.close()
    assert all(not t.is_alive() for t in p._threads)


def test_try_submit_admits_only_idle_workers():
    """The no-deadlock property: admission is bounded by idle workers,
    so a full pool returns None instead of queueing — the caller runs
    the work itself."""
    p = lanepool.HostLanePool(2, name="lp-sat")
    gate = threading.Event()
    try:
        f1 = p.try_submit(gate.wait, 10)
        f2 = p.try_submit(gate.wait, 10)
        assert f1 is not None and f2 is not None
        # both workers busy: nothing else is admitted
        deadline = time.monotonic() + 2.0
        while p.idle() > 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert p.try_submit(lambda: 1) is None
        assert p.depth() == 2
        gate.set()
        assert f1.result(timeout=5) and f2.result(timeout=5)
        # workers drained: admission works again
        deadline = time.monotonic() + 2.0
        while p.idle() < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert p.try_submit(lambda: 3).result(timeout=5) == 3
    finally:
        gate.set()
        p.close()


def test_run_lanes_order_and_saturation_fallback():
    """Results come back in input order even when the pool admits none
    of the thunks (every lane then runs serially in the caller)."""
    gate = threading.Event()
    try:
        # drive run_lanes against a global pool sized 2 whose workers
        # are wedged, so every thunk must run inline
        lanepool.set_workers(2)
        gp = lanepool.pool()
        assert gp is not None and gp.workers == 2
        b1 = gp.try_submit(gate.wait, 10)
        b2 = gp.try_submit(gate.wait, 10)
        deadline = time.monotonic() + 2.0
        while gp.idle() > 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        caller = threading.current_thread().ident
        ran_on = []

        def thunk(i):
            ran_on.append(threading.current_thread().ident)
            return i * 10

        out = lanepool.run_lanes([lambda i=i: thunk(i) for i in range(4)])
        assert out == [0, 10, 20, 30]
        assert set(ran_on) == {caller}  # saturated -> all inline
        gate.set()
        assert b1.result(timeout=5) and b2.result(timeout=5)
    finally:
        gate.set()


def test_run_lanes_propagates_exception_after_settling():
    lanepool.set_workers(4)
    done = []

    def ok(i):
        done.append(i)
        return i

    with pytest.raises(ValueError, match="lane boom"):
        lanepool.run_lanes([
            lambda: (_ for _ in ()).throw(ValueError("lane boom")),
            lambda: ok(1), lambda: ok(2)])
    assert sorted(done) == [1, 2]  # other lanes still settled


def test_pool_disabled_is_serial_in_caller():
    lanepool.set_workers(1)
    assert lanepool.pool() is None
    caller = threading.current_thread().ident
    ran_on = []
    out = lanepool.run_lanes(
        [lambda i=i: ran_on.append(threading.current_thread().ident) or i
         for i in range(3)])
    assert out == [0, 1, 2]
    assert set(ran_on) == {caller}


# ---------------------------------------------------------------------------
# verify_sharded: exactness, order stability, fallbacks
# ---------------------------------------------------------------------------

def test_verify_sharded_bitmap_identity_both_schemes():
    _need_native()
    pubs, msgs, sigs = _secp_batch(37, bad=(0, 13, 36))
    want = _oracle(pubs, msgs, sigs)
    got = lanepool.verify_sharded(
        "secp256k1", [p.bytes() for p in pubs], msgs, sigs)
    assert got is not None and got.tolist() == want

    minis = [(0xA50 + i).to_bytes(32, "little") for i in range(21)]
    smsgs = [b"sr lp %d" % i for i in range(21)]
    ssigs = [sr.sign(minis[i], smsgs[i]) for i in range(21)]
    ssigs[4] = bytes([ssigs[4][0] ^ 1]) + ssigs[4][1:]
    spubs = [sr.PrivKey(m).pub_key() for m in minis]
    want = _oracle(spubs, smsgs, ssigs)
    got = lanepool.verify_sharded(
        "sr25519", [p.bytes() for p in spubs], smsgs, ssigs)
    assert got is not None and got.tolist() == want


def test_verify_sharded_unknown_scheme_and_empty():
    assert lanepool.verify_sharded("ed25519", [], [], []) is None
    _need_native()
    out = lanepool.verify_sharded("secp256k1", [], [], [])
    assert out is not None and out.shape == (0,)


def test_verify_sharded_irregular_inputs_return_none():
    """A malformed-length row anywhere makes the whole call return None
    (the caller's per-item path decides) — the exact contract of an
    unsharded libs/native call, regardless of which chunk held it."""
    _need_native()
    pubs, msgs, sigs = _secp_batch(40)
    sigs[33] = sigs[33][:50]  # truncated: native returns None
    assert lanepool.verify_sharded(
        "secp256k1", [p.bytes() for p in pubs], msgs, sigs) is None


def test_verify_sharded_concurrency_hammer_order_stable():
    """Many threads, each with its own batch whose size straddles the
    chunking threshold: every returned bitmap must match the per-item
    oracle index for index (a chunk-merge off-by-one or cross-batch mixup
    would misattribute verdicts)."""
    _need_native()
    lanepool.set_workers(4)  # pooled chunking even on a 1-core runner
    batches = []
    for k, n in enumerate((3, 16, 17, 31, 48, 64)):
        bad = tuple(i for i in range(n) if i % 7 == k % 7)
        pubs, msgs, sigs = _secp_batch(n, bad=bad)
        batches.append(([p.bytes() for p in pubs], msgs, sigs,
                        _oracle(pubs, msgs, sigs)))
    errors = []

    def worker(k):
        pb, msgs, sigs, want = batches[k % len(batches)]
        try:
            for _ in range(8):
                got = lanepool.verify_sharded("secp256k1", pb, msgs, sigs)
                assert got is not None and got.tolist() == want
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(k,))
               for k in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors


def test_verify_sharded_pool_disabled_still_exact():
    _need_native()
    lanepool.set_workers(1)
    pubs, msgs, sigs = _secp_batch(24, bad=(5,))
    got = lanepool.verify_sharded(
        "secp256k1", [p.bytes() for p in pubs], msgs, sigs)
    assert got is not None and got.tolist() == _oracle(pubs, msgs, sigs)


def test_set_workers_resizes_and_env_governs(monkeypatch):
    lanepool.set_workers(3)
    assert lanepool.pool().workers == 3
    lanepool.set_workers(2)
    assert lanepool.pool().workers == 2
    lanepool.set_workers(None)
    monkeypatch.setenv("TM_TPU_HOST_POOL_WORKERS", "4")
    assert lanepool.pool().workers == 4
    monkeypatch.setenv("TM_TPU_HOST_POOL_WORKERS", "1")
    assert lanepool.pool() is None
