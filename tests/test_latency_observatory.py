"""Verify-path latency observatory (ADR-016, ISSUE 8) acceptance:

Real VerifyScheduler traffic under injected device-lane latency
(chaos ``latency:<ms>`` at ``sched.ed25519``) must surface in the
queue-wait and e2e histograms, trip ``sched_deadline_miss_total``, and
agree — within tolerance — across FOUR surfaces: the metrics bundle,
``scheduler.last_latency_report()``, ``GET /debug/latency`` on the
pprof listener, and the flight recorder's span timestamps.  The device
lane is a stubbed host-computing verifier (same trick as the
test_comb/test_mixed_lanes routing tests) so the chaos seam fires with
ZERO XLA compile cost.

Plus: the direct BatchVerifier path's ``path="direct"`` e2e bracket,
the degrade-fallback window labeling, the bench.probe chaos seam +
BENCH_OPPORTUNISTIC retry window, bench_history.jsonl partial-run
capture, and the scripts/bench_trend.py harness over the repo's real
BENCH_r01..r05 captures (rc=0, r04->r05 gap flagged).
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "scripts"))

from tendermint_tpu.crypto import batch as cb  # noqa: E402
from tendermint_tpu.crypto import degrade  # noqa: E402
from tendermint_tpu.crypto import ed25519 as edkeys  # noqa: E402
from tendermint_tpu.crypto import scheduler as vs  # noqa: E402
from tendermint_tpu.libs import fail, slo, trace  # noqa: E402
from tendermint_tpu.libs.metrics import Registry  # noqa: E402


@pytest.fixture(autouse=True)
def _clean():
    fail.reset()
    yield
    fail.reset()
    vs.uninstall()
    degrade.reset()
    slo.disable()
    slo.reset()
    trace.disable()


@pytest.fixture
def sched():
    created = []

    def make(**kw):
        s = vs.VerifyScheduler(**kw)
        created.append(s)
        vs.install(s)
        s.start()
        return s

    yield make
    for s in created:
        s.stop()
    vs.uninstall()


def _signed(n, tag=b"lat"):
    privs = [edkeys.PrivKey(bytes([(i * 11 + 5) % 255 + 1]) * 32)
             for i in range(n)]
    msgs = [tag + b" item %d" % i for i in range(n)]
    return [(p.pub_key(), m, p.sign(m)) for p, m in zip(privs, msgs)]


def _host_stub_verifier(pubs, msgs, sigs):
    """Stands in for the device kernel: verdict-identical, no XLA
    compile.  Runs INSIDE degrade's lane worker, after fail.inject at
    the sched.ed25519 seam — so injected lane latency/raise exercises
    the full degradation ladder."""
    return np.array([edkeys.PubKey(bytes(p)).verify_signature(m, s)
                     for p, m, s in zip(pubs, msgs, sigs)], dtype=bool)


@pytest.fixture
def _stub_device(monkeypatch):
    monkeypatch.setenv("TM_TPU_FORCE_BATCH", "1")
    monkeypatch.delenv("TM_TPU_DISABLE_BATCH", raising=False)
    monkeypatch.setattr(
        cb, "_device_verifier",
        lambda tname: _host_stub_verifier
        if tname == edkeys.KEY_TYPE else None)


def _spans(records, name):
    return [r for r in records if r["name"] == name]


# ---------------------------------------------------------------------------
# THE acceptance test: four surfaces agree under injected lane latency
# ---------------------------------------------------------------------------

def test_latency_observatory_four_surfaces_agree(sched, _stub_device):
    reg = Registry("latency")
    rt = degrade.configure(registry=reg)
    slo.set_config(enabled=True, window=64,
                   targets={"blocksync": 0.010})  # 10 ms: will be blown
    trace.enable(capacity=1 << 12)
    seq0 = trace.last_seq()

    s = sched(window_s=0.5, tpu_threshold=4)
    items = _signed(12, tag=b"acceptance")
    fail.set_mode("sched.ed25519", "latency:120")
    try:
        # deadline 20 ms out: the window closes early to chase it, but
        # the injected 120 ms lane latency guarantees the settle MISSES
        fut = s.submit(items, vs.Priority.BLOCKSYNC,
                       deadline=time.monotonic() + 0.02,
                       populate_cache=False)
        bits = fut.result(timeout=60)
    finally:
        fail.clear()
    trace.disable()
    assert bits.all()
    assert fail.fired("sched.ed25519", "latency:120") == 1

    # -- surface 1: the metrics bundle ---------------------------------
    m = rt.metrics
    assert m.sched_queue_wait.count(priority="blocksync") == 1
    qw_metric = m.sched_queue_wait.total(priority="blocksync")
    assert m.verify_e2e_latency.count(priority="blocksync",
                                      path="sched-device") == 1
    e2e_metric = m.verify_e2e_latency.total(priority="blocksync",
                                            path="sched-device")
    assert e2e_metric >= 0.12, "e2e must include the injected latency"
    assert m.sched_deadline_miss.value(priority="blocksync") == 1

    # -- surface 2: last_latency_report() ------------------------------
    rep = vs.last_latency_report()
    assert rep["path"] == "sched-device"
    assert rep["submissions"] == 1 and rep["items"] == 12
    assert rep["lanes"] == 12
    req = rep["requests"][0]
    assert req["priority"] == "blocksync" and req["deadline_met"] is False
    assert req["e2e_s"] == pytest.approx(e2e_metric, abs=1e-4)
    assert req["queue_wait_s"] == pytest.approx(qw_metric, abs=1e-4)
    # decomposition: the injected lane latency lands in execute_s
    assert rep["execute_s"] >= 0.11
    assert rep["e2e_max_s"] >= rep["execute_s"]

    # -- surface 3: flight-recorder span timestamps --------------------
    records = trace.snapshot(since=seq0)
    submit = _spans(records, "sched.submit")[0]
    resolve = _spans(records, "sched.resolve")[0]
    coalesce = _spans(records, "sched.coalesce")[0]
    launch = [r for r in _spans(records, "device.launch")
              if r["attrs"].get("site") == "sched.ed25519"][0]
    miss = _spans(records, "sched.deadline_miss")
    assert len(miss) == 1 and miss[0]["attrs"]["priority"] == "blocksync"
    # span-derived e2e (submit instant -> resolve instant) must agree
    # with the stamped report
    e2e_spans = (resolve["ts_ns"] - submit["ts_ns"]) / 1e9
    assert e2e_spans == pytest.approx(req["e2e_s"], abs=0.05)
    # span-derived queue wait (submit -> stage start) agrees too
    qw_spans = (coalesce["ts_ns"] - submit["ts_ns"]) / 1e9
    assert qw_spans == pytest.approx(req["queue_wait_s"], abs=0.05)
    # the device lane span carries the injected latency
    assert launch["dur_ns"] >= int(0.11e9)

    # -- surface 4: GET /debug/latency + the debug-latency CLI ---------
    from tendermint_tpu.libs.pprof import PprofServer
    srv = PprofServer("127.0.0.1:0")
    srv.start()
    try:
        with urllib.request.urlopen(
                f"http://{srv.laddr}/debug/latency", timeout=10) as r:
            assert r.status == 200
            assert r.headers["Content-Type"] == "application/json"
            doc = json.loads(r.read().decode())
        assert doc["last_latency_report"]["e2e_max_s"] == \
            rep["e2e_max_s"]
        assert doc["last_latency_report"]["requests"][0][
            "deadline_met"] is False
        stream = doc["slo"]["streams"]["blocksync"]
        assert stream["n"] == 1
        assert stream["p99_s"] == pytest.approx(req["e2e_s"], abs=1e-4)
        assert stream["burn_rate"] == pytest.approx(100.0)  # 1/1 over

        # the CLI mirrors debug-trace: fetch + write the same JSON
        from tendermint_tpu.cmd.__main__ import main as cli_main
        out = os.path.join(os.environ.get("TMPDIR", "/tmp"),
                           f"latency-cli-{os.getpid()}.json")
        try:
            cli_main(["debug-latency", "--pprof-laddr", srv.laddr,
                      "--output-file", out])
            with open(out) as f:
                cli_doc = json.load(f)
            assert cli_doc["last_latency_report"]["e2e_max_s"] == \
                rep["e2e_max_s"]
        finally:
            if os.path.exists(out):
                os.remove(out)
    finally:
        srv.stop()

    # SLO gauges were refreshed from the window
    assert m.slo_p99.value(stream="blocksync") == \
        pytest.approx(req["e2e_s"], abs=1e-4)
    assert m.slo_burn_rate.value(stream="blocksync") == \
        pytest.approx(100.0)


def test_fallback_window_labeled_sched_fallback(sched, _stub_device):
    """A device raise inside the window re-verifies on the host
    (degrade ladder) — the e2e path label must say sched-fallback, not
    claim device latency for a host re-verify."""
    rt = degrade.configure(registry=Registry("latfall"))
    s = sched(window_s=0.0, tpu_threshold=4)
    items = _signed(8, tag=b"fallback")
    fail.set_mode("sched.ed25519", "raise")
    try:
        bits = s.submit(items, vs.Priority.COMMIT,
                        populate_cache=False).result(timeout=60)
    finally:
        fail.clear()
    assert bits.all()
    m = rt.metrics
    assert m.verify_e2e_latency.count(priority="commit",
                                      path="sched-fallback") == 1
    assert m.verify_e2e_latency.count(priority="commit",
                                      path="sched-device") == 0
    assert vs.last_latency_report()["path"] == "sched-fallback"


def test_cache_resolved_window_and_queue_wait(sched, _stub_device):
    """A window resolved entirely from SigCache settles with
    path=sched-cache and still records queue wait + e2e."""
    rt = degrade.configure(registry=Registry("latcache"))
    s = sched(window_s=0.0, tpu_threshold=4)
    items = _signed(8, tag=b"cachewin")
    assert s.submit(items, vs.Priority.COMMIT).result(timeout=60).all()
    assert s.submit(items, vs.Priority.COMMIT).result(timeout=60).all()
    m = rt.metrics
    assert m.verify_e2e_latency.count(priority="commit",
                                      path="sched-cache") == 1
    assert m.sched_queue_wait.count(priority="commit") == 2
    rep = vs.last_latency_report()
    assert rep["path"] == "sched-cache" and rep["lanes"] == 0
    assert rep["requests"][0]["e2e_s"] is not None


def test_direct_path_publishes_e2e_at_context_priority():
    """The BatchVerifier direct path (scheduler not running) lands in
    the SAME e2e histogram, path="direct", at the caller's priority
    context — so per-request latency exists on every route."""
    rt = degrade.configure(registry=Registry("latdirect"))
    assert vs.running() is None
    items = _signed(6, tag=b"direct")

    bv = cb.BatchVerifier()
    for p, m_, s_ in items:
        bv.add(p, m_, s_)
    ok, _ = bv.verify()
    assert ok
    m = rt.metrics
    assert m.verify_e2e_latency.count(priority="commit",
                                      path="direct") == 1

    with vs.priority_context(vs.Priority.BLOCKSYNC):
        bv2 = cb.BatchVerifier()
        for p, m_, s_ in _signed(6, tag=b"direct2"):
            bv2.add(p, m_, s_)
        assert bv2.verify()[0]
    assert m.verify_e2e_latency.count(priority="blocksync",
                                      path="direct") == 1


# ---------------------------------------------------------------------------
# bench: probe chaos + opportunistic retry + history capture
# ---------------------------------------------------------------------------

def test_bench_probe_chaos_and_opportunistic_retry(monkeypatch):
    """The bench.probe seam forces the dead-backend class without a
    tunnel; BENCH_OPPORTUNISTIC=1 grants ONE bounded retry window and
    a probe that recovers mid-window succeeds (ROADMAP item 5's
    opportunistic capture)."""
    import bench

    fail.set_mode("bench.probe", "raise")
    try:
        monkeypatch.delenv("BENCH_OPPORTUNISTIC", raising=False)
        platform, err = bench._probe_backend(timeout_s=10)
        assert platform is None and "InjectedFault" in err
        n0 = fail.fired("bench.probe", "raise")
        assert n0 == 1

        monkeypatch.setenv("BENCH_OPPORTUNISTIC", "1")
        monkeypatch.setenv("BENCH_RETRY_WINDOW_S", "0.5")
        monkeypatch.setenv("BENCH_PROBE_RETRY_S", "0.1")
        platform, err = bench._probe_backend(timeout_s=10)
        assert platform is None
        assert "opportunistic retry window" in err
        assert fail.fired("bench.probe", "raise") >= n0 + 2  # retried
    finally:
        fail.clear()

    # a backend that comes back inside the window is caught
    fail.set_mode("bench.probe", "raise")
    t = threading.Timer(0.15, lambda: fail.clear("bench.probe"))
    t.daemon = True
    t.start()
    try:
        monkeypatch.setenv("BENCH_OPPORTUNISTIC", "1")
        monkeypatch.setenv("BENCH_RETRY_WINDOW_S", "10")
        monkeypatch.setenv("BENCH_PROBE_RETRY_S", "0.1")
        platform, err = bench._probe_backend(timeout_s=10)
        assert err is None and platform == "cpu"
    finally:
        t.cancel()
        fail.clear()


def test_bench_history_emit_partial_capture(monkeypatch, tmp_path,
                                            capsys):
    """_emit prints the driver's JSON line UNCHANGED and appends an
    enriched record to bench_history.jsonl immediately — a later
    config wedging cannot lose it.  Malformed lines never poison the
    load side."""
    import bench

    hist = tmp_path / "hist.jsonl"
    monkeypatch.setenv("BENCH_HISTORY", str(hist))
    monkeypatch.setenv("BENCH_ROUND", "r99")
    line1 = {"metric": "m1", "value": 10.0, "unit": "sigs/s"}
    bench._emit(line1)
    bench._emit({"metric": "m2", "value": 20.0, "unit": "sigs/s"})
    out = [json.loads(ln) for ln in
           capsys.readouterr().out.strip().splitlines()]
    assert out[0] == line1  # stdout contract untouched (no ts/source)
    recs = bench.load_history()
    assert [r["metric"] for r in recs] == ["m1", "m2"]
    assert recs[0]["source"] == "bench" and recs[0]["round"] == "r99"
    assert "ts" in recs[0]
    with open(hist, "a") as f:
        f.write('{"broken\n')
    assert len(bench.load_history()) == 2  # half-written line skipped


# ---------------------------------------------------------------------------
# the trend harness
# ---------------------------------------------------------------------------

def test_bench_trend_rc0_and_flags_r04_r05_gap(capsys, monkeypatch):
    """Acceptance: rc=0 over the repo's real BENCH_r01..r05 files, and
    the r04 (rc=0) -> r05 (rc=1) capture gap is flagged in the trend
    table."""
    import bench_trend

    monkeypatch.delenv("BENCH_HISTORY", raising=False)
    rc = bench_trend.main(["--root", ROOT])
    out = capsys.readouterr().out
    assert rc == 0
    assert "CAPTURE-FAILED rc=1" in out
    assert "r04 rc=0 -> r05 rc=1" in out
    assert "ed25519_verify_throughput_e2e" in out and "best" in out
    # --strict turns the gap into a nonzero exit (CI mode)
    assert bench_trend.main(["--root", ROOT, "--strict"]) == 1
    capsys.readouterr()


def test_bench_trend_regression_flag(tmp_path, capsys):
    """A round dropping more than the threshold below best-known is
    flagged REGRESSION; a host-fallback capture is excluded from
    best-known instead of being mistaken for a regression."""
    import bench_trend

    def write(n, rc, value, note=None):
        parsed = {"metric": "x_e2e", "value": value, "unit": "sigs/s",
                  "vs_baseline": 1.0}
        if note:
            parsed["note"] = note
        doc = {"n": n, "rc": rc, "parsed": parsed}
        if value is None:
            doc["parsed"] = {}
        (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps(doc))

    write(1, 0, 100.0)
    write(2, 0, 9.0, note="device unavailable, host fallback")
    write(3, 0, 50.0)
    rc = bench_trend.main(["--root", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "REGRESSION" in out and "50" in out
    assert "host-fallback (excluded from best)" in out
    rows = bench_trend.trend_rows([
        {"label": "r01", "value": 100.0, "rc": 0, "note": None},
        {"label": "r02", "value": 9.0, "rc": 0,
         "note": "device unavailable, host fallback"},
        {"label": "r03", "value": 50.0, "rc": 0, "note": None},
    ], threshold=0.05)
    assert rows[0]["flag"] == "best"
    assert rows[1]["flag"].startswith("host-fallback")
    assert rows[2]["flag"].startswith("REGRESSION")
    # delta is computed against the last REAL capture (r01), not the
    # host-fallback row
    assert rows[2]["delta_vs_prev_pct"] == pytest.approx(-50.0)


def test_bench_report_prev_round_delta_columns():
    """bench_report's delta-vs-previous-round annotation is pure: the
    most recent comparable history record for the same config feeds
    prev_sigs_per_s / delta_vs_prev_pct; unknown configs pass
    through untouched."""
    from bench_trend import with_prev_round_delta

    hist = [
        {"config": "5: mixed", "sigs_per_s": 1000, "source": "bench_report"},
        {"config": "2: commit", "sigs_per_s": 77, "source": "bench_report"},
        {"config": "5: mixed", "sigs_per_s": 2000, "source": "bench_report"},
    ]
    out = with_prev_round_delta({"config": "5: mixed",
                                 "sigs_per_s": 3000}, hist)
    assert out["prev_sigs_per_s"] == 2000
    assert out["delta_vs_prev_pct"] == pytest.approx(50.0)
    untouched = {"config": "9: comb", "sigs_per_s": 5}
    assert with_prev_round_delta(untouched, hist) == untouched
    # bench lines key on "metric" instead of "config"
    mhist = [{"metric": "headline", "value": 10.0, "source": "bench"}]
    out2 = with_prev_round_delta({"metric": "headline", "value": 5.0},
                                 mhist)
    assert out2["delta_vs_prev_pct"] == pytest.approx(-50.0)
