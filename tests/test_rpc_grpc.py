"""gRPC broadcast API (reference rpc/grpc: Ping + BroadcastTx with
broadcast_tx_commit semantics)."""
from __future__ import annotations

import pytest

pytest.importorskip("grpc")

from tendermint_tpu.abci import types as abci
from tendermint_tpu.rpc.grpc_api import (GRPCBroadcastClient,
                                         GRPCBroadcastServer,
                                         _dec_broadcast_response,
                                         _enc_broadcast_response)


def test_broadcast_response_codec_roundtrip():
    ct = abci.ResponseCheckTx(code=0, log="ok")
    dt = abci.ResponseDeliverTx(code=3, log="bad key")
    data = _enc_broadcast_response(ct, dt)
    ct2, dt2 = _dec_broadcast_response(data)
    assert ct2.code == 0 and ct2.log == "ok"
    assert dt2.code == 3 and dt2.log == "bad key"


class _FakeRPC:
    """Stands in for rpc/server.RPCServer's handler surface."""

    def __init__(self):
        self.seen = []

    def broadcast_tx_commit(self, tx=None, timeout=30.0):
        import base64
        self.seen.append(base64.b64decode(tx))
        return {"check_tx": {"code": 0},
                "deliver_tx": {"code": 0, "log": "committed"},
                "hash": "AA", "height": 5}


def test_grpc_broadcast_server_client():
    rpc = _FakeRPC()
    srv = GRPCBroadcastServer(rpc, "127.0.0.1:0")
    srv.start()
    try:
        cli = GRPCBroadcastClient(srv.addr)
        cli.ping()
        ct, dt = cli.broadcast_tx(b"k=v")
        assert ct.code == 0
        assert dt.code == 0 and dt.log == "committed"
        assert rpc.seen == [b"k=v"]
        cli.close()
    finally:
        srv.stop()


def test_grpc_broadcast_error_maps_to_status():
    import grpc as _grpc

    class Boom:
        def broadcast_tx_commit(self, tx=None, timeout=30.0):
            raise RuntimeError("mempool is full")

    srv = GRPCBroadcastServer(Boom(), "127.0.0.1:0")
    srv.start()
    try:
        cli = GRPCBroadcastClient(srv.addr)
        with pytest.raises(_grpc.RpcError, match="mempool is full"):
            cli.broadcast_tx(b"x")
        cli.close()
    finally:
        srv.stop()
