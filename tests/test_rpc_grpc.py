"""gRPC broadcast API (reference rpc/grpc: Ping + BroadcastTx with
broadcast_tx_commit semantics)."""
from __future__ import annotations

import pytest

pytest.importorskip("grpc")

from tendermint_tpu.abci import types as abci
from tendermint_tpu.rpc.grpc_api import (GRPCBroadcastClient,
                                         GRPCBroadcastServer,
                                         _dec_broadcast_response,
                                         _enc_broadcast_response)


def test_broadcast_response_codec_roundtrip():
    ct = abci.ResponseCheckTx(code=0, log="ok")
    dt = abci.ResponseDeliverTx(code=3, log="bad key")
    data = _enc_broadcast_response(ct, dt)
    ct2, dt2 = _dec_broadcast_response(data)
    assert ct2.code == 0 and ct2.log == "ok"
    assert dt2.code == 3 and dt2.log == "bad key"


class _FakeRPC:
    """Stands in for rpc/server.RPCServer's handler surface."""

    def __init__(self):
        self.seen = []

    def broadcast_tx_commit_raw(self, raw, timeout=30.0):
        self.seen.append(raw)
        return (abci.ResponseCheckTx(code=0, data=b"cd", gas_wanted=7),
                abci.ResponseDeliverTx(code=0, log="committed",
                                       gas_used=21, codespace="app"),
                5)


def test_grpc_broadcast_server_client():
    rpc = _FakeRPC()
    srv = GRPCBroadcastServer(rpc, "127.0.0.1:0")
    srv.start()
    try:
        cli = GRPCBroadcastClient(srv.addr)
        cli.ping()
        ct, dt = cli.broadcast_tx(b"k=v")
        assert ct.code == 0
        # full abci fields survive the wire (ADVICE r4): data, gas,
        # codespace are no longer dropped by the server
        assert ct.data == b"cd" and ct.gas_wanted == 7
        assert dt.code == 0 and dt.log == "committed"
        assert dt.gas_used == 21 and dt.codespace == "app"
        assert rpc.seen == [b"k=v"]
        cli.close()
    finally:
        srv.stop()


def test_grpc_broadcast_error_maps_to_status():
    import grpc as _grpc

    class Boom:
        def broadcast_tx_commit_raw(self, raw, timeout=30.0):
            raise RuntimeError("mempool is full")

    srv = GRPCBroadcastServer(Boom(), "127.0.0.1:0")
    srv.start()
    try:
        cli = GRPCBroadcastClient(srv.addr)
        with pytest.raises(_grpc.RpcError, match="mempool is full"):
            cli.broadcast_tx(b"x")
        cli.close()
    finally:
        srv.stop()


def test_node_serves_grpc_broadcast_api(tmp_path):
    """A node with [rpc] grpc_laddr set serves BroadcastAPI end to end:
    Ping + BroadcastTx commits a tx into a block."""
    import os
    import time

    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.config.config import Config
    from tendermint_tpu.consensus.config import test_config
    from tendermint_tpu.node import Node
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.types.basic import Timestamp
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    cfg = Config(home=os.path.join(str(tmp_path), "grpc-node"))
    cfg.ensure_dirs()
    cfg.consensus = test_config()
    cfg.p2p.laddr = "127.0.0.1:0"
    cfg.rpc.laddr = "127.0.0.1:0"
    cfg.rpc.grpc_laddr = "127.0.0.1:0"
    pv = FilePV.load_or_generate(cfg.priv_validator_key_file(),
                                 cfg.priv_validator_state_file())
    NodeKey.load_or_generate(cfg.node_key_file())
    pub = pv.get_pub_key()
    gdoc = GenesisDoc(chain_id="grpc-chain",
                      genesis_time=Timestamp(1700000000, 0),
                      validators=[GenesisValidator(
                          address=pub.address(), pub_key_type=pub.type_name,
                          pub_key_bytes=pub.bytes(), power=10)])
    with open(cfg.genesis_file(), "w") as f:
        f.write(gdoc.to_json())
    node = Node(cfg, KVStoreApplication(), in_memory=True)
    node.start(wait_for_sync=True)
    try:
        assert node.grpc_server is not None
        cli = GRPCBroadcastClient(node.grpc_server.addr)
        cli.ping()
        t0 = time.time()
        ct, dt = cli.broadcast_tx(b"grpckey=grpcval")
        assert ct.code == 0 and dt.code == 0, (ct, dt)
        assert time.time() - t0 < 30
        q = node.app.query(
            abci.RequestQuery(data=b"grpckey"))
        assert q.value == b"grpcval"
        cli.close()
    finally:
        node.stop()
