"""Blocksync replay throughput at BASELINE config-4 shape (150-validator
commits), scaled down for CI.  The full-scale run is
scripts/bench_report.py (config 4); this asserts the coalesced path works at the
real validator count and reports blocks/s + where the time goes."""
from __future__ import annotations

import time

import pytest

from helpers import build_chain, make_genesis
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.blocksync.replay import replay_window
from tendermint_tpu.libs.kvdb import MemDB
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.state import state_from_genesis
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.store.block_store import BlockStore

N_VALS = 150
N_BLOCKS = 60
WINDOW = 20


@pytest.mark.slow
def test_blocksync_replay_150_validators():
    gdoc, privs = make_genesis(N_VALS)
    t0 = time.perf_counter()
    blocks, commits, states = build_chain(gdoc, privs, N_BLOCKS)
    build_s = time.perf_counter() - t0

    ex = BlockExecutor(StateStore(MemDB()), KVStoreApplication())
    store = BlockStore(MemDB())
    state = state_from_genesis(gdoc)

    t0 = time.perf_counter()
    applied = 0
    while applied < N_BLOCKS:
        state, n = replay_window(ex, store, state, blocks[applied:],
                                 commits[applied:], max_window=WINDOW)
        assert n > 0
        applied += n
    replay_s = time.perf_counter() - t0

    assert state.last_block_height == N_BLOCKS
    assert state.app_hash == states[-1].app_hash
    rate = N_BLOCKS / replay_s
    sigs = N_BLOCKS * N_VALS  # full last_commit sets alone
    print(f"\nblocksync replay: {rate:.1f} blocks/s "
          f"({sigs / replay_s:.0f}+ sigs/s incl. light prefixes; "
          f"build={build_s:.1f}s replay={replay_s:.1f}s)")
