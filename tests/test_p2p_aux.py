"""Flowrate, fuzzed connection, trust metric, armor tests (reference
libs/flowrate, p2p/fuzz.go, p2p/trust/metric.go, crypto/armor)."""
import random
import time

import pytest

from tendermint_tpu.crypto.armor import (ArmorError, decode_armor,
                                         encode_armor,
                                         encrypt_armor_priv_key,
                                         unarmor_decrypt_priv_key)
from tendermint_tpu.libs.flowrate import Monitor
from tendermint_tpu.p2p.fuzz import FuzzConnConfig, FuzzedConnection
from tendermint_tpu.p2p.trust import TrustMetric, TrustMetricStore


def _has_cryptography() -> bool:
    try:
        import cryptography  # noqa: F401
        return True
    except ImportError:
        return False


# the armor AEAD paths (ChaCha20-Poly1305) lean on the optional
# `cryptography` package; environments without it skip cleanly instead
# of failing tier-1 (the in-repo xsalsa20 armor is covered regardless)
requires_cryptography = pytest.mark.skipif(
    not _has_cryptography(),
    reason="cryptography package unavailable "
           "(armor ChaCha20-Poly1305 AEAD needs it)")


def test_flowrate_limits_throughput():
    m = Monitor(limit_bytes_per_s=50_000)
    t0 = time.monotonic()
    for _ in range(10):
        m.update(10_000)  # 100 KB at 50 KB/s -> >= ~1s after burst credit
    elapsed = time.monotonic() - t0
    assert elapsed >= 0.8, elapsed
    assert m.total() == 100_000


def test_flowrate_unlimited_is_fast():
    m = Monitor(limit_bytes_per_s=0)
    t0 = time.monotonic()
    for _ in range(100):
        m.update(1 << 20)
    assert time.monotonic() - t0 < 0.5


class _PipeConn:
    def __init__(self):
        self.sent = []
        self.inbox = []

    def send_frame(self, data):
        self.sent.append(data)

    def recv_frame(self):
        return self.inbox.pop(0)

    def close(self):
        pass


def test_fuzzed_connection_drops_frames():
    inner = _PipeConn()
    fz = FuzzedConnection(inner, FuzzConnConfig(
        prob_drop_rw=0.5, prob_sleep=0.0), rng=random.Random(42))
    for i in range(200):
        fz.send_frame(b"x%d" % i)
    assert 0 < len(inner.sent) < 200
    assert fz.dropped_frames == 200 - len(inner.sent)
    # recv: dropped frames are skipped, later ones delivered
    inner.inbox = [b"a", b"b", b"c", b"d", b"e", b"f"]
    got = fz.recv_frame()
    assert got in (b"a", b"b", b"c", b"d", b"e", b"f")


def test_fuzz_start_after_grace():
    inner = _PipeConn()
    fz = FuzzedConnection(inner, FuzzConnConfig(
        prob_drop_rw=1.0, start_after_s=60.0), rng=random.Random(1))
    fz.send_frame(b"hello")  # within grace: no fuzzing
    assert inner.sent == [b"hello"]


def test_trust_metric_declines_on_bad_events():
    tm = TrustMetric(interval_s=0.05, max_history=4)
    assert tm.value() == pytest.approx(1.0)
    tm.bad_events(10)
    v1 = tm.value()
    assert v1 < 1.0
    time.sleep(0.06)
    tm.bad_events(10)
    v2 = tm.value()
    assert v2 < 0.9
    # recovery with good events
    for _ in range(6):
        time.sleep(0.06)
        tm.good_events(20)
    assert tm.value() > v2


def test_trust_store():
    st = TrustMetricStore()
    assert st.peer_trust("unknown") == 1.0
    st.get("p1").bad_events(5)
    assert st.peer_trust("p1") < 1.0
    assert st.size() == 1


def test_armor_round_trip_and_crc():
    text = encode_armor("TEST BLOCK", {"k": "v"}, b"\x00\x01\xFFdata" * 40)
    bt, headers, data = decode_armor(text)
    assert bt == "TEST BLOCK" and headers == {"k": "v"}
    assert data == b"\x00\x01\xFFdata" * 40
    # corrupt one base64 char -> CRC failure
    lines = text.splitlines()
    for i, ln in enumerate(lines):
        if ln and not ln.startswith(("-", "=", "k")):
            lines[i] = ("B" if ln[0] != "B" else "C") + ln[1:]
            break
    with pytest.raises(ArmorError):
        decode_armor("\n".join(lines))


@requires_cryptography
def test_encrypt_armor_priv_key():
    priv = bytes(range(32))
    armored = encrypt_armor_priv_key(priv, "hunter2", key_type="ed25519")
    assert "TENDERMINT PRIVATE KEY" in armored
    assert "kdf: scrypt" in armored
    out, kt = unarmor_decrypt_priv_key(armored, "hunter2")
    assert out == priv and kt == "ed25519"
    with pytest.raises(ArmorError):
        unarmor_decrypt_priv_key(armored, "wrong-pass")


@requires_cryptography
def test_armor_xsalsa20_legacy_aead():
    """Legacy NaCl secretbox armor (reference crypto/xsalsa20symmetric)
    round-trips, cross-rejects with the modern AEAD, and unknown AEAD
    headers are refused before key derivation."""
    import pytest

    from tendermint_tpu.crypto.armor import (ArmorError, decode_armor,
                                             encode_armor,
                                             encrypt_armor_priv_key,
                                             unarmor_decrypt_priv_key)

    priv = bytes(range(32))
    a = encrypt_armor_priv_key(priv, "hunter2", aead="xsalsa20poly1305")
    btype, headers, body = decode_armor(a)
    assert headers["aead"] == "xsalsa20poly1305"
    pt, ktype = unarmor_decrypt_priv_key(a, "hunter2")
    assert pt == priv and ktype == "ed25519"
    with pytest.raises(ArmorError):
        unarmor_decrypt_priv_key(a, "wrong")
    # cross-AEAD: a secretbox body relabeled chacha20poly1305 (and any
    # unknown AEAD tag) must not decrypt
    relabeled = encode_armor(btype, {**headers,
                                     "aead": "chacha20poly1305"}, body)
    with pytest.raises(ArmorError):
        unarmor_decrypt_priv_key(relabeled, "hunter2")
    bogus = encode_armor(btype, {**headers, "aead": "bogus"}, body)
    with pytest.raises(ArmorError, match="AEAD"):
        unarmor_decrypt_priv_key(bogus, "hunter2")
