"""NetHarness + vnet (docs/adr/adr-019-net-harness.md): the in-process
multi-node network under Byzantine weather.

Tier-1 carries the 4-node partition-heal smoke (real Nodes, full
reactors, host-only verification — 4-lane batches stay under
tpu_threshold so no XLA shape compiles), the vnet transport unit
matrix (determinism, asymmetric drops, dup/reorder, backpressure), the
chaos seams (vnet.deliver / vnet.reorder / vnet.partition /
harness.step) and the Switch persistent-reconnect regressions the
harness hammers.  The full scenario suite and the 12/16-node matrix
run in the slow tier.
"""
from __future__ import annotations

import json
import os
import threading
import time

import pytest

from tendermint_tpu.libs import fail
from tendermint_tpu.networks import scenarios
from tendermint_tpu.networks.harness import NetHarness, ScenarioFailure
from tendermint_tpu.networks.vnet import LinkPolicy, VirtualNetwork
from tendermint_tpu.p2p.connection import ChannelDescriptor
from tendermint_tpu.p2p.key import NodeKey
from tendermint_tpu.p2p.switch import Reactor, Switch
from tendermint_tpu.p2p import wire

CH = 0x7B


def _codec():
    try:
        wire.register_codec(CH, lambda m: m, lambda b: b)
    except ValueError:
        pass  # already registered by an earlier test in this process


@pytest.fixture
def vnet():
    net = VirtualNetwork(seed=99).start()
    yield net
    net.stop()
    fail.clear()


def _chans(cap=100):
    return [ChannelDescriptor(CH, priority=1, send_queue_capacity=cap)]


def _drain(net, s=0.25):
    time.sleep(s)


# ---------------------------------------------------------------------------
# vnet transport unit matrix
# ---------------------------------------------------------------------------

def test_vnet_deterministic_schedule_replay():
    """The acceptance property behind seed replay: the same seed and the
    same per-link send sequence produce the SAME per-link fault
    decisions (drop/dup/reorder verdicts and delays), so a failed
    scenario's printed seed reproduces its delivery schedule.  A
    different seed produces a different schedule."""
    def run(seed):
        net = VirtualNetwork(seed=seed).start()
        try:
            got = []
            a, b = net.connect_raw("ra", "rb", _chans(cap=10_000),
                                   on_b=lambda c, m: got.append(m))
            net.set_link("ra", "rb", drop=0.3, dup=0.2, reorder=0.3,
                         latency_s=0.0005, jitter_s=0.002)
            for i in range(200):
                a.send(CH, b"m%04d" % i)
            deadline = time.monotonic() + 5
            while net._heap and time.monotonic() < deadline:
                time.sleep(0.01)
            time.sleep(0.1)
            return net.decisions(), len(got)
        finally:
            net.stop()

    d1, n1 = run(7)
    d2, n2 = run(7)
    d3, _ = run(8)
    assert d1 == d2, "same seed must replay the same schedule"
    assert n1 == n2
    assert d1 != d3, "a different seed must perturb the schedule"
    verdicts = {d[5].split(":")[0].split("+")[0] for d in d1}
    assert "drop" in str(verdicts) or any("drop" in d[5] for d in d1)
    assert any("dup" in d[5] for d in d1)
    assert any("reorder" in d[5] for d in d1)


def test_vnet_asymmetric_one_way_drop(vnet):
    got_a, got_b = [], []
    a, b = vnet.connect_raw("owa", "owb", _chans(),
                            on_a=lambda c, m: got_a.append(m),
                            on_b=lambda c, m: got_b.append(m))
    vnet.set_link("owa", "owb", drop=1.0)   # a -> b silenced
    for i in range(5):
        a.send(CH, b"dead")
        b.send(CH, b"alive")
    _drain(vnet)
    assert got_b == []                       # one-way: nothing arrives
    assert got_a == [b"alive"] * 5           # reverse direction intact
    assert vnet.dropped["loss"] == 5


def test_vnet_partition_and_heal_counters(vnet):
    got = []
    a, _b = vnet.connect_raw("pa", "pb", _chans(),
                             on_b=lambda c, m: got.append(m))
    vnet.set_partition({"pa"}, {"pb"})
    assert vnet.partitioned("pa", "pb")
    assert vnet.metrics.partitions_active.value() == 2
    a.send(CH, b"x")
    _drain(vnet)
    assert got == [] and vnet.dropped["partition"] == 1
    vnet.heal()
    assert vnet.metrics.partitions_active.value() == 0
    a.send(CH, b"y")
    _drain(vnet)
    assert got == [b"y"]


def test_vnet_backpressure_try_send_cap(vnet):
    """Per-channel in-flight cap == MConnection's bounded send queue:
    try_send over the cap refuses and the drop is counted."""
    stall = threading.Event()

    def slow_receiver(c, m):
        stall.wait(5.0)
    a, _b = vnet.connect_raw("bpa", "bpb", _chans(cap=4),
                             on_b=slow_receiver)
    results = [a.try_send(CH, b"x") for _ in range(20)]
    assert not all(results), "cap must eventually refuse try_send"
    assert vnet.dropped["backpressure"] >= 1
    stall.set()


# ---------------------------------------------------------------------------
# chaos seams (CHAOS_TEST_FILES coverage: vnet.* + harness.step)
# ---------------------------------------------------------------------------

def test_chaos_vnet_deliver_raise_drops_frames(vnet):
    got = []
    a, _b = vnet.connect_raw("ca", "cb", _chans(),
                             on_b=lambda c, m: got.append(m))
    fail.set_mode("vnet.deliver", "raise")
    try:
        assert a.send(CH, b"gone") is True   # lossy network, not an error
        _drain(vnet)
        assert got == []
        assert fail.fired("vnet.deliver", "raise") >= 1
        assert vnet.dropped["chaos"] == 1
    finally:
        fail.clear("vnet.deliver")
    a.send(CH, b"back")
    _drain(vnet)
    assert got == [b"back"]                  # disarmed: traffic resumes


def test_chaos_vnet_reorder_raise(vnet):
    got = []
    a, _b = vnet.connect_raw("roa", "rob", _chans(),
                             on_b=lambda c, m: got.append(m))
    vnet.set_link("roa", "rob", reorder=1.0, reorder_window_s=0.01)
    fail.set_mode("vnet.reorder", "raise")
    try:
        a.send(CH, b"x")
        _drain(vnet)
        assert fail.fired("vnet.reorder", "raise") >= 1
        assert got == [] and vnet.dropped["chaos"] == 1
    finally:
        fail.clear("vnet.reorder")
    a.send(CH, b"y")
    _drain(vnet)
    assert got == [b"y"] and any("reorder" in d[5]
                                 for d in vnet.decisions())


def test_chaos_vnet_partition_raise(vnet):
    fail.set_mode("vnet.partition", "raise")
    try:
        with pytest.raises(fail.InjectedFault):
            vnet.set_partition({"x"}, {"y"})
        assert fail.fired("vnet.partition", "raise") >= 1
    finally:
        fail.clear("vnet.partition")
    vnet.heal()  # disarmed: transitions work again


def test_chaos_harness_step_fails_scenario_with_artifact(tmp_path):
    """raise at harness.step: the scenario fails loudly, the failure
    counter moves, and the stitched artifact (timeline + seed + vnet
    decision log) lands on disk for replay."""
    h = NetHarness(validators=2, seed=777, workdir=str(tmp_path))
    h.start()
    before = h.net.metrics.scenario_failures.value()
    fail.set_mode("harness.step", "raise")
    try:
        with pytest.raises(ScenarioFailure) as ei:
            h.run_scenario({"name": "chaos_step", "validators": 2,
                            "steps": [{"op": "sleep", "s": 0.1}]})
    finally:
        fail.clear("harness.step")
        h.stop()
    assert fail.fired("harness.step", "raise") >= 1
    assert h.net.metrics.scenario_failures.value() == before + 1
    assert "seed=777" in str(ei.value)
    art = ei.value.artifact
    assert art.get("timeline") and os.path.exists(art["timeline"])
    payload = json.load(open(art["timeline"]))
    assert payload["seed"] == 777
    assert payload["error"] and "InjectedFault" in payload["error"]
    assert isinstance(payload["vnet_decisions"], list)


# ---------------------------------------------------------------------------
# Switch persistent-reconnect regressions (the path the harness hammers)
# ---------------------------------------------------------------------------

class _Probe(Reactor):
    def __init__(self):
        super().__init__("PROBE")
        self.got = []

    def get_channels(self):
        return _chans()

    def receive(self, ch_id, peer, msg):
        self.got.append(msg)


def _switch_pair(net, base_s=0.05):
    _codec()
    sws = []
    for i in range(2):
        sw = Switch(NodeKey.generate(), f"rp{i}", network="reconnet",
                    moniker=f"rp{i}", transport=net.transport(f"rp{i}"))
        sw.RECONNECT_BASE_S = base_s
        sw.add_reactor("PROBE", _Probe())
        sw.start()
        sws.append(sw)
    return sws


def _wait_peers(sws, n=1, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(sw.num_peers() >= n for sw in sws):
            return True
        time.sleep(0.02)
    return False


def test_reconnect_flapping_link_no_leak_no_double_dial(vnet):
    """Satellite regression: a flapping link must always converge back
    to exactly ONE peer per side with the _reconnecting entry retired —
    no leaked entry endlessly re-dialing, no double connection."""
    a, b = _switch_pair(vnet)
    try:
        assert a.dial_peer(f"{b.node_key.node_id}@rp1",
                           persistent=True) is not None
        for _ in range(3):                      # flap
            vnet.break_link("rp0", "rp1")
            time.sleep(0.15)
        assert _wait_peers([a, b]), "flapped link never re-converged"
        deadline = time.monotonic() + 5.0       # let reconnectors retire
        while time.monotonic() < deadline and a._reconnecting:
            time.sleep(0.05)
        assert not a._reconnecting, "reconnect entry leaked"
        time.sleep(0.5)
        assert a.num_peers() == 1 and b.num_peers() == 1, \
            "double-dial produced a second peer"
    finally:
        a.stop()
        b.stop()


def test_reconnect_inbound_while_reconnecting_retires_entry(vnet):
    """The peer reconnects INBOUND while our reconnect routine is in
    backoff: the routine must observe the restored peer and retire
    instead of bouncing off the duplicate-peer check forever."""
    a, b = _switch_pair(vnet, base_s=0.8)  # long backoff window
    try:
        assert a.dial_peer(f"{b.node_key.node_id}@rp1",
                           persistent=True) is not None
        vnet.break_link("rp0", "rp1")
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not a._reconnecting:
            time.sleep(0.01)
        assert a._reconnecting, "persistent drop never armed reconnect"
        # inbound restore while the dialer sleeps in its backoff
        assert b.dial_peer(f"{a.node_key.node_id}@rp0") is not None
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and a._reconnecting:
            time.sleep(0.05)
        assert not a._reconnecting, \
            "reconnect entry not retired by inbound restore"
        assert a.num_peers() == 1 and b.num_peers() == 1
    finally:
        a.stop()
        b.stop()


def test_reconnect_backoff_is_capped_and_jittered():
    """The schedule knobs exist and are sane: cap >= base, and the
    jittered sleep factor stays inside [0.5, 1.5) of backoff."""
    assert Switch.RECONNECT_BASE_S <= Switch.RECONNECT_MAX_S <= 60.0
    # the cap is enforced by construction in the routine: backoff is
    # min(backoff * 2, RECONNECT_MAX_S) — pin the expression here so a
    # refactor dropping the cap fails a test, not an operator
    backoff = Switch.RECONNECT_BASE_S
    for _ in range(16):
        backoff = min(backoff * 2, Switch.RECONNECT_MAX_S)
    assert backoff == Switch.RECONNECT_MAX_S


# ---------------------------------------------------------------------------
# the tier-1 smoke scenario: 4 REAL nodes, partition + heal, all
# invariant checkers armed (host-only verification: 4-lane batches
# stay below tpu_threshold, so no XLA shape compiles)
# ---------------------------------------------------------------------------

def test_smoke_partition_heal_4node(tmp_path):
    sc = scenarios.by_name("partition_heal_majority")
    assert sc.get("smoke"), "the smoke scenario must stay tier-1 shaped"
    res = NetHarness.run(sc, seed=42, workdir=str(tmp_path))
    assert res["violations"] == []
    hs = res["heights"]
    assert len(hs) == 4 and min(hs.values()) >= 5, hs
    # the partition really bit: cross-group frames were swallowed
    steps = {s["step"]["op"] for s in res["steps"]}
    assert {"partition", "heal", "wait_height"} <= steps


# ---------------------------------------------------------------------------
# the full suite + scale matrix (slow tier)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize(
    "name", [s["name"] for s in scenarios.standard_scenarios()])
def test_scenario_suite(name, tmp_path):
    """Every standard scenario commits past its fault with zero
    agreement/validity violations (the evidence scenario additionally
    proves DuplicateVoteEvidence landed in a committed block)."""
    res = NetHarness.run(scenarios.by_name(name), seed=1234,
                         workdir=str(tmp_path))
    assert res["violations"] == []
    if name == "double_sign_evidence":
        evs = res["ctx"].get("evidence")
        assert evs, "evidence gate passed without evidence?"
    if name == "flood_vs_ingress":
        assert res["ctx"].get("rejections", 0) >= 1


@pytest.mark.slow
@pytest.mark.parametrize(
    "name", [s["name"] for s in scenarios.SCENARIOS
             if s.get("slow_matrix")])
def test_scenario_scale_matrix(name, tmp_path):
    res = NetHarness.run(scenarios.by_name(name), seed=4321,
                         workdir=str(tmp_path))
    assert res["violations"] == []


def test_every_scenario_validates():
    for sc in scenarios.SCENARIOS:
        scenarios.validate_scenario(sc)
    with pytest.raises(ValueError):
        scenarios.validate_scenario(
            {"name": "bad", "validators": 2,
             "steps": [{"op": "warp_drive"}]})
    with pytest.raises(ValueError):
        scenarios.validate_scenario(
            {"name": "oob", "validators": 2,
             "steps": [{"op": "kill", "node": 7}]})
