"""Worker process for the 2-process jax.distributed DCN dryrun
(tests/test_multihost_replay.py; SURVEY §5.8, VERDICT r3 #8).

Each process owns 4 virtual CPU devices; together they form one global
8-device mesh spanning "hosts".  Both enter the SAME sharded
verification computation in lockstep — exactly the discipline the
coordinated blocksync-replay path provides (a single thread applying a
deterministic window, unlike uncoordinated reactor calls) — and each
writes its addressable bitmap shards for the parent to stitch and
check.  XLA inserts the cross-process collective for the replicated
all-valid bit (the psum in make_sharded_verifier's out_shardings).

Two modes (argv[6], default "raw"):

  raw   — the original dryrun: make_sharded_verifier driven directly,
          per-process addressable bitmap shards written for the parent
          to stitch.
  prod  — the PRODUCTION path (ADR-027): ops/ed25519.verify_batch
          called inside a sharding.lockstep() window, exactly the shape
          blocksync replay_window / coordinated bulk verify produce.
          The route must come back "global-mesh" with the psum'd
          all-valid bit in the launch record; the returned bitmap is
          replicated, so each process emits the FULL bitmap and the
          parent asserts both copies equal the host oracle.

Usage: python multihost_worker.py <pid> <nproc> <coord> <npz> <out> [mode]
Env: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4
"""
from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _main_prod(pid, nproc, npz_path, out_path):
    """Production route: verify_batch under lockstep() — the global
    mesh plane end-to-end, including the AOT-compile + barrier seal and
    the per-process addressable staging inside _put_sharded."""
    import numpy as np

    from tendermint_tpu.ops import ed25519 as edops
    from tendermint_tpu.parallel import sharding as shd

    assert shd.global_mesh_ready(), "distributed runtime not detected"

    data = np.load(npz_path)
    pubs = [bytes(p) for p in data["pubs"]]
    sigs = [bytes(s) for s in data["sigs"]]
    msgs = [bytes(m) for m in data["msgs"]]

    with shd.lockstep():
        bitmap = edops.verify_batch(pubs, msgs, sigs)
    ll = edops.last_launch()
    with open(out_path, "w") as f:
        json.dump({
            "pid": pid,
            "path": ll.get("path"),
            "shards": ll.get("shards"),
            "all_valid": ll.get("all_valid"),
            # a backend without multi-process computations (CPU jaxlib
            # today) latches the global plane off after the first real
            # collective fault; the parent asserts the degrade contract
            # in that case instead of the global route
            "global_latched_off": shd._GLOBAL_PLANE is False,
            "bitmap": np.asarray(bitmap).astype(int).tolist(),
        }, f)


def main():
    pid, nproc = int(sys.argv[1]), int(sys.argv[2])
    coord, npz_path, out_path = sys.argv[3], sys.argv[4], sys.argv[5]
    mode = sys.argv[6] if len(sys.argv) > 6 else "raw"

    import jax

    # this environment pre-imports jax with the tunneled-TPU plugin
    # (sitecustomize sets JAX_PLATFORMS=axon), so the platform must be
    # forced via config, not env (see tests/conftest.py)
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=nproc, process_id=pid)
    assert len(jax.devices()) == 4 * nproc, jax.devices()
    assert len(jax.local_devices()) == 4

    if mode == "prod":
        _main_prod(pid, nproc, npz_path, out_path)
        return

    from jax.sharding import NamedSharding, PartitionSpec as P

    from tendermint_tpu.ops import ed25519 as edops
    from tendermint_tpu.parallel import sharding as shd

    data = np.load(npz_path)
    pubs, sigs = data["pubs"], data["sigs"]
    msgs = [bytes(m) for m in data["msgs"]]

    # identical host staging on every process (deterministic)
    dev, host_ok = edops.prepare_batch(pubs, sigs, msgs)
    n = host_ok.shape[0]
    ndev = 4 * nproc
    nb = -(-n // ndev) * ndev
    dev = edops._pad_dev(dev, n, nb)

    mesh = shd.make_mesh(jax.devices())
    jitted, _run = shd.make_sharded_verifier(mesh)
    sh = NamedSharding(mesh, P(shd.BATCH_AXIS))

    def to_global(a):
        return jax.make_array_from_callback(
            a.shape, sh, lambda idx: np.ascontiguousarray(a[idx]))

    args = (to_global(dev["pub"]), to_global(dev["r"]),
            to_global(dev["s_digits"]), to_global(dev["k_digits"]))
    # AOT-compile, then rendezvous at a coordination-service barrier
    # before executing: compilation is per-process and can skew by
    # minutes under load, while Gloo's collective-context setup inside
    # the first execution only waits ~30 s for the other process.
    compiled = jitted.lower(*args).compile()
    from jax._src import distributed as _dist
    _dist.global_state.client.wait_at_barrier("tm_tpu_mh_compiled",
                                              240 * 1000)
    bitmap, all_valid = compiled(*args)
    # the all-valid bit is replicated (out_shardings P()): every process
    # observes the same value via the XLA-inserted cross-host reduction
    av = bool(np.asarray(
        [s.data for s in all_valid.addressable_shards][0]))
    shards = sorted(
        ((s.index[0].start or 0, np.asarray(s.data))
         for s in bitmap.addressable_shards), key=lambda t: t[0])
    with open(out_path, "w") as f:
        json.dump({
            "pid": pid,
            "all_valid": av,
            "shards": [{"start": int(st), "bits": b.astype(int).tolist()}
                       for st, b in shards],
        }, f)


if __name__ == "__main__":
    main()
