"""Worker process for the 2-process jax.distributed DCN dryrun
(tests/test_multihost_replay.py; SURVEY §5.8, VERDICT r3 #8).

Each process owns 4 virtual CPU devices; together they form one global
8-device mesh spanning "hosts".  Both enter the SAME sharded
verification computation in lockstep — exactly the discipline the
coordinated blocksync-replay path provides (a single thread applying a
deterministic window, unlike uncoordinated reactor calls) — and each
writes its addressable bitmap shards for the parent to stitch and
check.  XLA inserts the cross-process collective for the replicated
all-valid bit (the psum in make_sharded_verifier's out_shardings).

Usage: python multihost_worker.py <pid> <nproc> <coord> <npz> <out>
Env: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4
"""
from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    pid, nproc = int(sys.argv[1]), int(sys.argv[2])
    coord, npz_path, out_path = sys.argv[3], sys.argv[4], sys.argv[5]

    import jax

    # this environment pre-imports jax with the tunneled-TPU plugin
    # (sitecustomize sets JAX_PLATFORMS=axon), so the platform must be
    # forced via config, not env (see tests/conftest.py)
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=nproc, process_id=pid)
    assert len(jax.devices()) == 4 * nproc, jax.devices()
    assert len(jax.local_devices()) == 4

    from jax.sharding import NamedSharding, PartitionSpec as P

    from tendermint_tpu.ops import ed25519 as edops
    from tendermint_tpu.parallel import sharding as shd

    data = np.load(npz_path)
    pubs, sigs = data["pubs"], data["sigs"]
    msgs = [bytes(m) for m in data["msgs"]]

    # identical host staging on every process (deterministic)
    dev, host_ok = edops.prepare_batch(pubs, sigs, msgs)
    n = host_ok.shape[0]
    ndev = 4 * nproc
    nb = -(-n // ndev) * ndev
    dev = edops._pad_dev(dev, n, nb)

    mesh = shd.make_mesh(jax.devices())
    jitted, _run = shd.make_sharded_verifier(mesh)
    sh = NamedSharding(mesh, P(shd.BATCH_AXIS))

    def to_global(a):
        return jax.make_array_from_callback(
            a.shape, sh, lambda idx: np.ascontiguousarray(a[idx]))

    args = (to_global(dev["pub"]), to_global(dev["r"]),
            to_global(dev["s_digits"]), to_global(dev["k_digits"]))
    # AOT-compile, then rendezvous at a coordination-service barrier
    # before executing: compilation is per-process and can skew by
    # minutes under load, while Gloo's collective-context setup inside
    # the first execution only waits ~30 s for the other process.
    compiled = jitted.lower(*args).compile()
    from jax._src import distributed as _dist
    _dist.global_state.client.wait_at_barrier("tm_tpu_mh_compiled",
                                              240 * 1000)
    bitmap, all_valid = compiled(*args)
    # the all-valid bit is replicated (out_shardings P()): every process
    # observes the same value via the XLA-inserted cross-host reduction
    av = bool(np.asarray(
        [s.data for s in all_valid.addressable_shards][0]))
    shards = sorted(
        ((s.index[0].start or 0, np.asarray(s.data))
         for s in bitmap.addressable_shards), key=lambda t: t[0])
    with open(out_path, "w") as f:
        json.dump({
            "pid": pid,
            "all_valid": av,
            "shards": [{"start": int(st), "bits": b.astype(int).tolist()}
                       for st, b in shards],
        }, f)


if __name__ == "__main__":
    main()
