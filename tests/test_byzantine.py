"""Byzantine behavior (reference consensus/byzantine_test.go +
invalid_test.go intent): an equivocating validator must not stop the
chain, honest nodes must capture DuplicateVoteEvidence, and the evidence
must land in a committed block."""
from __future__ import annotations

import time

import pytest

from helpers import Node, make_genesis, wire, wait_for_height
from tendermint_tpu.types.basic import (BlockID, PartSetHeader,
                                        SignedMsgType, Timestamp)
from tendermint_tpu.types.evidence import DuplicateVoteEvidence
from tendermint_tpu.types.vote import Vote


@pytest.mark.slow
def test_equivocating_prevoter_chain_survives_and_evidence_committed():
    gdoc, privs = make_genesis(4)
    nodes = [Node(gdoc, p, name=f"byz{i}")
             for i, p in enumerate(privs)]
    wire(nodes)

    byz = nodes[3]
    orig_do_prevote = byz.cs.do_prevote

    def equivocating_prevote(height, round_):
        """Reference byzantine_test.go: cast the honest prevote AND a
        conflicting one for a fabricated block — signed with the raw key,
        since FilePV's double-sign guard (correctly) refuses."""
        orig_do_prevote(height, round_)
        try:
            fake_bid = BlockID(hash=bytes([0xEE] * 32),
                               part_set_header=PartSetHeader(
                                   1, bytes([0xEF] * 32)))
            addr = privs[3].pub_key().address()
            idx, _ = byz.cs.rs.validators.get_by_address(addr)
            v = Vote(type=SignedMsgType.PREVOTE, height=height,
                     round=round_, block_id=fake_bid,
                     timestamp=Timestamp.now(), validator_address=addr,
                     validator_index=idx)
            v.signature = privs[3].sign(v.sign_bytes(gdoc.chain_id))
            for fn in byz.cs.broadcast_vote:
                fn(v)
        except Exception:
            pass

    byz.cs.do_prevote = equivocating_prevote
    for n in nodes:
        n.start()
    try:
        wait_for_height(nodes, 4, timeout=60)

        def committed_evidence():
            out = []
            for n in nodes[:3]:
                for h in range(2, n.block_store.height() + 1):
                    b = n.block_store.load_block(h)
                    if b is not None and b.evidence:
                        out.extend(b.evidence)
            return out

        # the property under test is the COMMITTED end state (reference
        # byzantine_test.go asserts evidence in a block, not pool
        # residency): keep the chain running until the DuplicateVote
        # evidence lands in a committed block
        committed = []
        deadline = time.time() + 120
        while time.time() < deadline:
            committed = committed_evidence()
            if committed:
                break
            time.sleep(0.5)
        pools = [n.evidence_pool.size() for n in nodes[:3]]
        assert committed, (
            f"equivocation evidence never committed (pools={pools}, "
            f"heights={[n.block_store.height() for n in nodes]})")
        assert isinstance(committed[0], DuplicateVoteEvidence)
        ev = committed[0]
        assert ev.vote_a.validator_address == \
            privs[3].pub_key().address()
    finally:
        for n in nodes:
            n.stop()

@pytest.mark.slow
def test_equivocating_proposer_chain_survives():
    """Reference byzantine_test.go conflicting-proposal split: when the
    byzantine validator is the proposer it sends its honest proposal to
    one peer and a CONFLICTING proposal to the two others.  The 2/2
    prevote split prevents that round from deciding; the next (honest)
    proposer must still commit, and all honest nodes must agree on every
    block."""
    from tendermint_tpu.types.part_set import PartSet
    from tendermint_tpu.types.proposal import Proposal

    gdoc, privs = make_genesis(4)
    nodes = [Node(gdoc, p, name=f"byzprop{i}")
             for i, p in enumerate(privs)]
    wire(nodes)

    byz = nodes[3]
    orig_decide = byz.cs.decide_proposal
    equivocated = []

    # re-route byz's proposal/part gossip: honest payload reaches ONLY
    # node 2 (votes still flow full-mesh — liveness needs them)
    byz.cs.broadcast_proposal.clear()
    byz.cs.broadcast_block_part.clear()
    byz.cs.broadcast_proposal.append(
        lambda p: nodes[2].cs.set_proposal(p, peer_id="byzprop"))
    byz.cs.broadcast_block_part.append(
        lambda h, r, part: nodes[2].cs.add_block_part(
            h, r, part, peer_id="byzprop"))

    def equivocating_decide(height, round_):
        orig_decide(height, round_)
        try:
            commit = byz.cs._commit_for_proposal(height)
            if commit is None:
                return
            addr = privs[3].pub_key().address()
            b2 = byz.cs.block_exec.create_proposal_block(
                height, byz.cs.state, commit, addr)
            # nudge the header time: a second, different-but-plausible
            # block for the same (height, round)
            b2.header.time = Timestamp(b2.header.time.seconds,
                                       b2.header.time.nanos + 1)
            parts2 = PartSet.from_data(b2.proto())
            bid2 = BlockID(b2.hash(), parts2.header())
            p2 = Proposal(height=height, round=round_,
                          pol_round=byz.cs.rs.valid_round, block_id=bid2,
                          timestamp=Timestamp.now())
            # raw-key signature: FilePV's double-sign guard (correctly)
            # refuses a second proposal at the same HRS
            p2.signature = privs[3].sign(p2.sign_bytes(gdoc.chain_id))
            for target in (nodes[0], nodes[1]):
                target.cs.set_proposal(p2, peer_id="byzprop")
                for i in range(parts2.header().total):
                    target.cs.add_block_part(height, round_,
                                             parts2.get_part(i),
                                             peer_id="byzprop")
            equivocated.append(height)
        except Exception:
            pass

    byz.cs.decide_proposal = equivocating_decide
    for n in nodes:
        n.start()
    try:
        wait_for_height(nodes, 6, timeout=120)
        assert equivocated, "byzantine node was never proposer"
        # honest nodes agree on every committed block
        top = min(n.block_store.height() for n in nodes[:3])
        for h in range(1, top + 1):
            hashes = {n.block_store.load_block(h).hash()
                      for n in nodes[:3]
                      if n.block_store.load_block(h) is not None}
            assert len(hashes) == 1, f"fork at height {h}"
    finally:
        for n in nodes:
            n.stop()
