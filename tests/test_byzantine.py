"""Byzantine behavior (reference consensus/byzantine_test.go +
invalid_test.go intent): an equivocating validator must not stop the
chain, honest nodes must capture DuplicateVoteEvidence, and the evidence
must land in a committed block."""
from __future__ import annotations

import time

import pytest

from helpers import Node, make_genesis, wire, wait_for_height
from tendermint_tpu.types.basic import (BlockID, PartSetHeader,
                                        SignedMsgType, Timestamp)
from tendermint_tpu.types.evidence import DuplicateVoteEvidence
from tendermint_tpu.types.vote import Vote


@pytest.mark.slow
def test_equivocating_prevoter_chain_survives_and_evidence_committed():
    gdoc, privs = make_genesis(4)
    nodes = [Node(gdoc, p, name=f"byz{i}")
             for i, p in enumerate(privs)]
    wire(nodes)

    byz = nodes[3]
    orig_do_prevote = byz.cs.do_prevote

    def equivocating_prevote(height, round_):
        """Reference byzantine_test.go: cast the honest prevote AND a
        conflicting one for a fabricated block — signed with the raw key,
        since FilePV's double-sign guard (correctly) refuses."""
        orig_do_prevote(height, round_)
        try:
            fake_bid = BlockID(hash=bytes([0xEE] * 32),
                               part_set_header=PartSetHeader(
                                   1, bytes([0xEF] * 32)))
            addr = privs[3].pub_key().address()
            idx, _ = byz.cs.rs.validators.get_by_address(addr)
            v = Vote(type=SignedMsgType.PREVOTE, height=height,
                     round=round_, block_id=fake_bid,
                     timestamp=Timestamp.now(), validator_address=addr,
                     validator_index=idx)
            v.signature = privs[3].sign(v.sign_bytes(gdoc.chain_id))
            for fn in byz.cs.broadcast_vote:
                fn(v)
        except Exception:
            pass

    byz.cs.do_prevote = equivocating_prevote
    for n in nodes:
        n.start()
    try:
        wait_for_height(nodes, 4, timeout=60)
        # honest nodes captured the double sign
        deadline = time.time() + 30
        while time.time() < deadline:
            if any(n.evidence_pool.size() > 0 for n in nodes[:3]):
                break
            time.sleep(0.2)
        sizes = [n.evidence_pool.size() for n in nodes[:3]]
        committed = []
        # evidence should be proposed + committed within a few heights
        top = max(n.block_store.height() for n in nodes)
        wait_for_height(nodes, top + 3, timeout=60)
        for n in nodes[:3]:
            for h in range(2, n.block_store.height() + 1):
                b = n.block_store.load_block(h)
                if b is not None and b.evidence:
                    committed.extend(b.evidence)
        assert any(sizes) or committed, (
            f"no evidence captured (pools={sizes})")
        if committed:
            assert isinstance(committed[0], DuplicateVoteEvidence)
            ev = committed[0]
            assert ev.vote_a.validator_address == \
                privs[3].pub_key().address()
    finally:
        for n in nodes:
            n.stop()
