"""libs/pprof: the live-debug endpoint (reference config.go:427
PprofListenAddress / net/http/pprof equivalent)."""
import threading
import time
import urllib.request

from tendermint_tpu.libs.pprof import PprofServer, format_stacks


def _get(laddr, path):
    try:
        with urllib.request.urlopen(f"http://{laddr}{path}",
                                    timeout=10) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_pprof_endpoints():
    srv = PprofServer("127.0.0.1:0")
    srv.start()
    try:
        # a busy worker the profiler must observe
        stop = threading.Event()

        def spin():
            while not stop.is_set():
                time.sleep(0.002)

        t = threading.Thread(target=spin, name="pprof-test-worker",
                             daemon=True)
        t.start()

        code, body = _get(srv.laddr, "/debug/stacks")
        assert code == 200
        assert "pprof-test-worker" in body and "spin" in body

        code, body = _get(srv.laddr, "/debug/threads")
        assert code == 200 and "pprof-test-worker" in body

        code, body = _get(srv.laddr, "/debug/profile?seconds=0.3")
        assert code == 200
        # folded stacks: "frame;frame;... count" lines, worker visible
        assert "spin" in body
        lines = [ln for ln in body.splitlines() if ln]
        assert all(ln.rsplit(" ", 1)[1].isdigit() for ln in lines)

        code, body = _get(srv.laddr, "/debug/gc")
        assert code == 200 and "gc counts" in body

        code, body = _get(srv.laddr, "/debug/nope")
        assert code == 404

        stop.set()
        t.join()
    finally:
        srv.stop()


def test_format_stacks_includes_own_thread():
    out = format_stacks()
    assert "format_stacks" in out or "MainThread" in out
