"""Pallas fused-verify kernel tests.

The full kernel only compiles for real TPUs (Mosaic); in CI (CPU-forced,
see conftest.py) correctness is checked through the pallas interpreter.
The interpret path traces the identical kernel jaxpr, so field-arithmetic
bounds, byte unpacking, ladder control flow, and accept/reject semantics
are all exercised; only the Mosaic lowering itself needs real hardware
(driven by bench.py / __graft_entry__ on the TPU side).
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.experimental import pallas as pl

import tendermint_tpu.ops.pallas_ed25519 as pe
from tendermint_tpu.crypto import _edref
from tendermint_tpu.ops import ed25519 as edops


@pytest.fixture
def interpret_pallas(monkeypatch):
    orig = pl.pallas_call
    monkeypatch.setattr(
        pe.pl, "pallas_call",
        lambda *a, **k: orig(*a, **{**k, "interpret": True}))


@pytest.fixture(params=["school", "k2", "k3"])
def mul_impl(request, monkeypatch):
    """Run a test under each conv implementation (_MUL_IMPL is read at
    trace time; clear the jit cache so the monkeypatched value retraces)."""
    monkeypatch.setattr(pe, "_MUL_IMPL", request.param)
    monkeypatch.setattr(pe, "_KMUL", request.param != "school")
    jax.clear_caches()
    yield request.param
    jax.clear_caches()


@pytest.mark.slow
def test_pallas_kernel_matches_oracle_interpret(interpret_pallas):
    """Full-kernel jaxpr vs the pure-Python RFC 8032 oracle, including
    corrupted signature/pubkey/message lanes and a non-canonical pubkey."""
    n = 128
    seeds = [i.to_bytes(32, "little") for i in range(1, n + 1)]
    msgs = [b"pallas oracle %d" % i for i in range(n)]
    pubs = [_edref.pubkey_from_seed(s) for s in seeds]
    sigs = [bytearray(_edref.sign(s, m)) for s, m in zip(seeds, msgs)]
    bad = {3: "sig", 17: "pub", 64: "msg", 127: "sig"}
    for i, kind in bad.items():
        if kind == "sig":
            sigs[i][5] ^= 1
        elif kind == "pub":
            pubs[i] = bytes([pubs[i][0] ^ 1]) + pubs[i][1:]
        else:
            msgs[i] = msgs[i] + b"!"
    sigs = [bytes(s) for s in sigs]

    dev, host_ok = edops.prepare_batch_compact(pubs, sigs, msgs)
    out = pe.verify_staged_pallas(
        jnp.asarray(dev["pub"]), jnp.asarray(dev["r"]),
        jnp.asarray(dev["s"]), jnp.asarray(dev["digest"]),
        tile=128)
    out = np.asarray(out) & host_ok
    expected = np.array([_edref.verify(p, m, s)
                         for p, m, s in zip(pubs, msgs, sigs)])
    assert (out == expected).all()


def test_pallas_field_ops_match_field_module(interpret_pallas, mul_impl):
    """The in-kernel field ops (mul/sqr/carry/freeze/reduce) against the
    ops.field reference implementation, under every conv implementation.
    Operands sit at each impl's contract edge: schoolbook allows two lazy
    operands; the Karatsuba impls allow at most one (the other loose)."""
    from jax.experimental.pallas import tpu as pltpu
    from tendermint_tpu.ops import field as F

    T = 128
    rng = np.random.default_rng(7)
    a_np = rng.integers(-9216, 9216, (22, T), dtype=np.int32)
    if mul_impl == "school":
        b_np = rng.integers(-9216, 9216, (22, T), dtype=np.int32)
    else:  # K contract: second operand loose, (-2^10, L)
        b_np = rng.integers(-1024, 4608, (22, T), dtype=np.int32)
    # pin contract-edge extremes into fixed lanes
    a_np[:, 0] = 9216
    a_np[:, 1] = -9216
    b_np[:, 0] = b_np[:, 1] = (9216 if mul_impl == "school" else 4607)

    def run(body):
        def kern(a_ref, b_ref, o_ref):
            o_ref[:] = body(a_ref[:], b_ref[:])
        return np.asarray(pl.pallas_call(
            kern,
            out_shape=jax.ShapeDtypeStruct((22, T), jnp.int32),
            interpret=True,
        )(jnp.asarray(a_np), jnp.asarray(b_np)))

    def val(limbs, c):
        return F.limbs_to_int(limbs[:, c]) % F.P

    got = run(lambda a, b: pe._mul(a, b))
    want = np.asarray(F.mul(jnp.asarray(a_np), jnp.asarray(b_np)))
    for c in (0, 17, T - 1):
        assert val(got, c) == val(want, c)
    assert abs(got).max() < 4608

    # sqr operand: lazy allowed under schoolbook, loose-only under K
    got = run(lambda a, b: pe._sqr(b))
    want = np.asarray(F.sqr(jnp.asarray(b_np)))
    for c in (0, 31, T - 1):
        assert val(got, c) == val(want, c)

    got = run(lambda a, b: pe._carry(a * 131072 + b))
    want = np.asarray(F.carry(jnp.asarray(a_np) * 131072 + jnp.asarray(b_np)))
    for c in (0, 63):
        assert val(got, c) == val(want, c)
    assert abs(got).max() < 4608

    two_p = np.asarray(F._TWO_P).reshape(22, 1).astype(np.int32)

    def kern_fr(a_ref, tp_ref, o_ref):
        o_ref[:] = pe._freeze(a_ref[:], tp_ref[:])

    got = np.asarray(pl.pallas_call(
        kern_fr,
        out_shape=jax.ShapeDtypeStruct((22, T), jnp.int32),
        interpret=True,
    )(jnp.asarray(a_np), jnp.asarray(two_p)))
    want = np.asarray(F.freeze(jnp.asarray(a_np)))
    assert (got == want).all()


@pytest.mark.slow
def test_pallas_kernel_oracle_karatsuba(interpret_pallas, mul_impl):
    """Full-kernel jaxpr vs the RFC 8032 oracle under each conv impl —
    exercises the K call-site carries in _dbl/_add_cached/_madd_niels
    through decompression, the table build, and the full ladder."""
    n = 32
    seeds = [(1000 + i).to_bytes(32, "little") for i in range(n)]
    msgs = [b"karatsuba oracle %d" % i for i in range(n)]
    pubs = [_edref.pubkey_from_seed(s) for s in seeds]
    sigs = [bytearray(_edref.sign(s, m)) for s, m in zip(seeds, msgs)]
    sigs[7][3] ^= 1
    sigs = [bytes(s) for s in sigs]
    packed, host_ok = edops.prepare_batch_packed(pubs, sigs, msgs)
    out = np.asarray(pe.verify_packed_pallas(jnp.asarray(packed), tile=32))
    out = out & host_ok
    expected = np.array([_edref.verify(p, m, s)
                         for p, m, s in zip(pubs, msgs, sigs)])
    assert (out == expected).all()
    if mul_impl == "school":
        # split-input kernel (device-resident pubkey cache) must agree
        # bit-for-bit with the packed kernel on the same batch
        pub_rows, rsk, host_ok2 = edops.prepare_batch_split(pubs, sigs, msgs)
        out2 = np.asarray(pe.verify_packed_split_pallas(
            jnp.asarray(pub_rows.view(np.int8)), jnp.asarray(rsk), tile=32))
        assert (host_ok2 == host_ok).all()
        assert ((out2 & host_ok2) == expected).all()


def test_verify_batch_routes_by_backend():
    """verify_batch must pick the XLA kernel off-TPU (CI) and still give
    exact accept/reject semantics through the public API."""
    assert not edops._use_pallas()  # conftest forces CPU
    n = 65
    seeds = [i.to_bytes(32, "little") for i in range(1, n + 1)]
    msgs = [b"route %d" % i for i in range(n)]
    pubs = [_edref.pubkey_from_seed(s) for s in seeds]
    sigs = [_edref.sign(s, m) for s, m in zip(seeds, msgs)]
    sigs[10] = sigs[10][:10] + bytes([sigs[10][10] ^ 1]) + sigs[10][11:]
    out = edops.verify_batch(pubs, msgs, sigs)
    assert out.shape == (n,)
    assert not out[10] and out.sum() == n - 1
