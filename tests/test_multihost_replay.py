"""Multi-host (DCN) data plane for the coordinated replay path
(SURVEY §5.8, VERDICT r3 #8): two OS processes form one global
8-device jax.distributed CPU mesh and verify the SAME batch in
lockstep through parallel/sharding.make_sharded_verifier — the shape
the blocksync-replay verifier (the one lockstep-safe call site) would
drive across hosts.  The stitched cross-process bitmap must equal the
host-side truth, and the XLA-reduced all-valid bit must agree on both
processes."""
from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


_BIND_ERRORS = ("Address already in use", "address already in use",
                "Failed to bind", "EADDRINUSE")


def _run_worker_pair(tmp_path, npz, mode, attempts=3, timeout=300):
    """Launch the 2-process worker pair, retrying on the port race:
    _free_port() probes by bind-and-release, so another process can
    claim the port before the coordinator (worker 0) binds it.  A pair
    whose logs show a bind failure is retried on a fresh port; any
    other failure raises with the logs attached.  Returns the two
    parsed worker JSON results."""
    last_logs = ""
    for attempt in range(attempts):
        port = _free_port()
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env.pop("TM_TPU_NO_MESH", None)
        procs, outs, logs = [], [], []
        for pid in range(2):
            out = tmp_path / f"worker{pid}.{mode}.{attempt}.json"
            log = tmp_path / f"worker{pid}.{mode}.{attempt}.log"
            outs.append(out)
            logs.append(log)
            procs.append(subprocess.Popen(
                [sys.executable, os.path.join(REPO, "tests",
                                              "multihost_worker.py"),
                 str(pid), "2", f"127.0.0.1:{port}", str(npz),
                 str(out), mode],
                cwd=REPO, env=env, stdout=open(log, "wb"),
                stderr=subprocess.STDOUT))
        try:
            for p in procs:
                p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            for q in procs:
                q.wait()
            raise AssertionError(
                "worker timeout; logs:\n" +
                "\n".join(l.read_text()[-2000:] for l in logs))
        if all(p.returncode == 0 for p in procs):
            return [json.load(open(o)) for o in outs]
        last_logs = "\n".join(l.read_text()[-3000:] for l in logs)
        if not any(e in last_logs for e in _BIND_ERRORS):
            raise AssertionError(last_logs)
        # port raced away between probe and coordinator bind: retry
    raise AssertionError(
        f"coordinator port kept racing ({attempts} attempts):\n"
        + last_logs)


@pytest.mark.slow
def test_two_process_mesh_bitmap_agrees(tmp_path):
    from tendermint_tpu.crypto import ed25519 as ed

    # a replay-shaped batch: vote-sign-bytes-sized messages, a couple of
    # invalid lanes the bitmap must pinpoint
    rng = np.random.default_rng(7)
    n = 96
    pubs, sigs, msgs, want = [], [], [], []
    for i in range(n):
        k = ed.PrivKey(bytes(rng.integers(0, 256, 32, dtype=np.uint8)))
        m = b"replay-vote-%03d" % i + bytes(rng.integers(0, 256, 80,
                                                         dtype=np.uint8))
        sig = bytearray(k.sign(m))
        ok = True
        if i in (5, 37, 70):
            sig[i % 64] ^= 1
            ok = False
        pubs.append(np.frombuffer(k.pub_key().bytes(), dtype=np.uint8))
        sigs.append(np.frombuffer(bytes(sig), dtype=np.uint8))
        msgs.append(np.frombuffer(m, dtype=np.uint8))
        want.append(ok)
    npz = tmp_path / "batch.npz"
    np.savez(npz, pubs=np.stack(pubs), sigs=np.stack(sigs),
             msgs=np.stack(msgs))

    results = _run_worker_pair(tmp_path, npz, "raw")
    # the replicated all-valid bit agrees across processes (and is False:
    # the batch carries corrupted lanes)
    assert results[0]["all_valid"] == results[1]["all_valid"] is False
    # stitch each process's addressable shards into the global bitmap:
    # together they cover the whole padded batch exactly once
    nb = -(-n // 8) * 8
    got = np.full(nb, -1, dtype=int)
    for r in results:
        for sh in r["shards"]:
            st, bits = sh["start"], sh["bits"]
            assert np.all(got[st:st + len(bits)] == -1), "shard overlap"
            got[st:st + len(bits)] = bits
    assert np.all(got >= 0), "shard gap"
    assert got[:n].astype(bool).tolist() == want
    # padding lanes verify as invalid (zeroed inputs), never as valid
    assert not got[n:].any()


@pytest.mark.slow
def test_two_process_production_verify_global_mesh(tmp_path):
    """The PRODUCTION route (ADR-027): each process calls
    ops/ed25519.verify_batch inside a sharding.lockstep() window — the
    exact shape blocksync replay_window and the coordinated bulk verify
    produce.  On a backend with multi-process computation support the
    launch record must report the "global-mesh" route over all 8
    devices with the psum'd all-valid bit; on today's CPU jaxlib (which
    refuses multi-process XLA programs) the first real collective fault
    must LATCH the global plane off and degrade to each process's local
    4-device mesh.  Either way both processes return the identical
    full bitmap, equal to the host oracle."""
    from tendermint_tpu.crypto import ed25519 as ed

    rng = np.random.default_rng(11)
    n = 96
    pubs, sigs, msgs, want = [], [], [], []
    for i in range(n):
        k = ed.PrivKey(bytes(rng.integers(0, 256, 32, dtype=np.uint8)))
        m = b"gmesh-vote-%03d" % i + bytes(rng.integers(0, 256, 40,
                                                        dtype=np.uint8))
        sig = bytearray(k.sign(m))
        ok = True
        if i in (5, 37, 70):
            sig[i % 64] ^= 1
            ok = False
        pubs.append(np.frombuffer(k.pub_key().bytes(), dtype=np.uint8))
        sigs.append(np.frombuffer(bytes(sig), dtype=np.uint8))
        msgs.append(np.frombuffer(m, dtype=np.uint8))
        want.append(ok)
    npz = tmp_path / "prod_batch.npz"
    np.savez(npz, pubs=np.stack(pubs), sigs=np.stack(sigs),
             msgs=np.stack(msgs))

    results = _run_worker_pair(tmp_path, npz, "prod")
    for r in results:
        if r["path"] == "global-mesh":
            # the real thing: one collective over both processes
            assert r["shards"] == 8, r
            assert r["all_valid"] is False
        else:
            # backend refused the collective: the latch must be set and
            # the batch must have ridden the LOCAL overlapped mesh
            assert r["global_latched_off"] is True, r
            assert r["path"] == "mesh-xla" and r["shards"] == 4, r
            assert r["all_valid"] is False
        assert r["bitmap"] == [int(w) for w in want]
    # both processes observe the identical verdict and bitmap
    assert results[0]["path"] == results[1]["path"]
    assert results[0]["bitmap"] == results[1]["bitmap"]
