"""Multi-host (DCN) data plane for the coordinated replay path
(SURVEY §5.8, VERDICT r3 #8): two OS processes form one global
8-device jax.distributed CPU mesh and verify the SAME batch in
lockstep through parallel/sharding.make_sharded_verifier — the shape
the blocksync-replay verifier (the one lockstep-safe call site) would
drive across hosts.  The stitched cross-process bitmap must equal the
host-side truth, and the XLA-reduced all-valid bit must agree on both
processes."""
from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_mesh_bitmap_agrees(tmp_path):
    from tendermint_tpu.crypto import ed25519 as ed

    # a replay-shaped batch: vote-sign-bytes-sized messages, a couple of
    # invalid lanes the bitmap must pinpoint
    rng = np.random.default_rng(7)
    n = 96
    pubs, sigs, msgs, want = [], [], [], []
    for i in range(n):
        k = ed.PrivKey(bytes(rng.integers(0, 256, 32, dtype=np.uint8)))
        m = b"replay-vote-%03d" % i + bytes(rng.integers(0, 256, 80,
                                                         dtype=np.uint8))
        sig = bytearray(k.sign(m))
        ok = True
        if i in (5, 37, 70):
            sig[i % 64] ^= 1
            ok = False
        pubs.append(np.frombuffer(k.pub_key().bytes(), dtype=np.uint8))
        sigs.append(np.frombuffer(bytes(sig), dtype=np.uint8))
        msgs.append(np.frombuffer(m, dtype=np.uint8))
        want.append(ok)
    npz = tmp_path / "batch.npz"
    np.savez(npz, pubs=np.stack(pubs), sigs=np.stack(sigs),
             msgs=np.stack(msgs))

    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("TM_TPU_NO_MESH", None)
    procs, outs, logs = [], [], []
    for pid in range(2):
        out = tmp_path / f"worker{pid}.json"
        log = tmp_path / f"worker{pid}.log"
        outs.append(out)
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests",
                                          "multihost_worker.py"),
             str(pid), "2", f"127.0.0.1:{port}", str(npz), str(out)],
            cwd=REPO, env=env, stdout=open(log, "wb"),
            stderr=subprocess.STDOUT))
    for p, log in zip(procs, logs):
        try:
            p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            for q in procs:
                q.wait()
            raise AssertionError(
                "worker timeout; logs:\n" +
                "\n".join(l.read_text()[-2000:] for l in logs))
        assert p.returncode == 0, log.read_text()[-3000:]

    results = [json.load(open(o)) for o in outs]
    # the replicated all-valid bit agrees across processes (and is False:
    # the batch carries corrupted lanes)
    assert results[0]["all_valid"] == results[1]["all_valid"] is False
    # stitch each process's addressable shards into the global bitmap:
    # together they cover the whole padded batch exactly once
    nb = -(-n // 8) * 8
    got = np.full(nb, -1, dtype=int)
    for r in results:
        for sh in r["shards"]:
            st, bits = sh["start"], sh["bits"]
            assert np.all(got[st:st + len(bits)] == -1), "shard overlap"
            got[st:st + len(bits)] = bits
    assert np.all(got >= 0), "shard gap"
    assert got[:n].astype(bool).tolist() == want
    # padding lanes verify as invalid (zeroed inputs), never as valid
    assert not got[n:].any()
