"""Operator CLI commands over a real node home dir (reference
cmd/tendermint/commands: rollback, gen_validator, gen_node_key, compact,
reindex_event, debug dump)."""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tarfile
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cli(*argv, timeout=120):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, "-m", "tendermint_tpu.cmd", *argv],
        capture_output=True, cwd=REPO, env=env, timeout=timeout, text=True)


@pytest.fixture(scope="module")
def ran_home(tmp_path_factory):
    """A home dir whose node committed a few blocks, then stopped."""
    home = str(tmp_path_factory.mktemp("cli") / "node")
    r = _cli("--home", home, "init")
    assert r.returncode == 0, r.stderr
    child = r"""
import sys, time
sys.path.insert(0, %r)
import tendermint_tpu, jax
jax.config.update("jax_platforms", "cpu")
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.config.config import Config
from tendermint_tpu.node import Node
cfg = Config.load(%r); cfg.home = %r
cfg.p2p.laddr = "127.0.0.1:0"; cfg.rpc.laddr = "127.0.0.1:0"
c = cfg.consensus
c.timeout_propose = c.timeout_prevote = c.timeout_precommit = 0.2
c.timeout_commit = 0.05
node = Node(cfg, KVStoreApplication())
node.start()
node.mempool.check_tx(b"cli=tools")
deadline = time.time() + 60
while node.block_store.height() < 4 and time.time() < deadline:
    time.sleep(0.05)
node.stop()
sys.exit(0 if node.block_store.height() >= 4 else 3)
""" % (REPO, home, home)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([sys.executable, "-c", child], env=env,
                       capture_output=True, timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]
    return home


def test_gen_validator():
    r = _cli("gen-validator")
    assert r.returncode == 0, r.stderr
    d = json.loads(r.stdout)
    assert len(bytes.fromhex(d["pub_key"]["value"])) == 32
    # Go-style 64-byte ed25519 private key: seed || pubkey
    assert len(bytes.fromhex(d["priv_key"]["value"])) == 64
    assert bytes.fromhex(d["priv_key"]["value"])[32:] == \
        bytes.fromhex(d["pub_key"]["value"])


def test_gen_node_key(tmp_path):
    home = str(tmp_path / "h")
    r = _cli("--home", home, "gen-node-key")
    assert r.returncode == 0, r.stderr
    nid = r.stdout.strip()
    assert len(nid) == 40
    # idempotent: same id on the second run
    r2 = _cli("--home", home, "gen-node-key")
    assert r2.stdout.strip() == nid


def test_rollback(ran_home):
    from tendermint_tpu.libs.kvdb import SQLiteDB
    from tendermint_tpu.state.store import StateStore

    ss = StateStore(SQLiteDB(os.path.join(ran_home, "data", "state.db")))
    before = ss.load().last_block_height
    ss.db.close() if hasattr(ss, "db") else None

    r = _cli("--home", ran_home, "rollback")
    assert r.returncode == 0, r.stderr
    assert f"height {before - 1}" in r.stdout

    ss = StateStore(SQLiteDB(os.path.join(ran_home, "data", "state.db")))
    assert ss.load().last_block_height == before - 1


def test_reindex_event(ran_home):
    # wipe the tx index, rebuild it, and find the tx again
    ix = os.path.join(ran_home, "data", "tx_index.db")
    for f in (ix, ix + "-wal", ix + "-shm"):
        if os.path.exists(f):
            os.remove(f)
    r = _cli("--home", ran_home, "reindex-event")
    assert r.returncode == 0, r.stderr
    assert "reindexed events" in r.stdout

    import hashlib

    from tendermint_tpu.libs.kvdb import SQLiteDB
    from tendermint_tpu.state.indexer import TxIndexer

    tx_ix = TxIndexer(SQLiteDB(ix))
    rec = tx_ix.get(hashlib.sha256(b"cli=tools").digest())
    assert rec is not None, "reindexed tx not found"


def test_compact(ran_home):
    r = _cli("--home", ran_home, "compact")
    assert r.returncode == 0, r.stderr
    assert "compacted" in r.stdout
    # stores still readable afterwards
    from tendermint_tpu.libs.kvdb import SQLiteDB
    from tendermint_tpu.store.block_store import BlockStore
    bs = BlockStore(SQLiteDB(os.path.join(ran_home, "data",
                                          "blockstore.db")))
    assert bs.height() >= 4


def test_debug_dump(ran_home, tmp_path):
    out = str(tmp_path / "dump.tar.gz")
    # node is stopped: RPC fetches degrade to error stubs, config + WAL
    # still collected
    r = _cli("--home", ran_home, "debug-dump", "--output-file", out,
             "--rpc-laddr", "127.0.0.1:1")
    assert r.returncode == 0, r.stderr
    with tarfile.open(out) as tar:
        names = tar.getnames()
    assert "config.toml" in names
    assert any(n.startswith("cs.wal") for n in names)
    assert "status.json" in names
