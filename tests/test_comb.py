"""Fixed-base comb verify path (ops/ed25519 + ops/curve, ADR-013).

Two tiers, split by XLA compile cost (the tier-1 budget has no headroom
for another kernel family — the guard tests below pin exactly that):

  * tier-1: structure and routing.  Group-op inventory by TRACING the
    kernels with instrumented curve ops (jax.eval_shape runs the Python
    body once, so the comb's zero doublings and the >= 2.5x group-op
    reduction are counted, not asserted from constants); lane/validator
    bucket guards (the comb reuses the ladder's bucket_size buckets —
    no new XLA shape family); the unified DeviceLRU (bounds under
    concurrency, the old _pub_cache one-over-bound race); comb routing
    with stubbed kernels (build/hit/subset/mixed/eviction/budget); the
    chaos matrix at the comb site (corrupt-bitmap caught by degrade's
    spot check, raise degrades, bitmaps exact).
  * slow: the bitmap-identity sweep with REAL kernels (comb vs ladder
    vs the host bignum oracle over valid/invalid/torsion/non-canonical
    encodings, mixed known+unknown keys, eviction mid-stream), the
    8-device CPU mesh path, the VerifyScheduler lane, and jit-vs-eager
    equality of the comb kernel itself.
"""
from __future__ import annotations

import hashlib
import threading

import numpy as np
import pytest

from tendermint_tpu.crypto import _edref
from tendermint_tpu.crypto import batch as cb
from tendermint_tpu.crypto import degrade
from tendermint_tpu.crypto import ed25519 as edkeys
from tendermint_tpu.libs import fail
from tendermint_tpu.libs.metrics import Registry
from tendermint_tpu.ops import curve as C
from tendermint_tpu.ops import ed25519 as edops
from tendermint_tpu.ops import field as F


@pytest.fixture(autouse=True)
def _comb_state():
    """Every test starts from a clean comb world: empty table cache, no
    config overrides, no armed chaos modes, fresh degrade runtime."""
    edops.table_cache_clear()
    edops._comb_enabled_override = None
    edops._comb_min_override = None
    edops._table_budget_override = None
    fail.reset()
    yield
    edops.table_cache_clear()
    edops._comb_enabled_override = None
    edops._comb_min_override = None
    edops._table_budget_override = None
    fail.reset()
    degrade.reset()


def _batch(n, pool=6, tag=b"comb"):
    seeds = [(0x7A00 + i % pool).to_bytes(32, "little") for i in range(n)]
    msgs = [b"%s vote %d" % (tag, i) for i in range(n)]
    pubs = [_edref.pubkey_from_seed(s) for s in seeds]
    sigs = [_edref.sign(s, m) for s, m in zip(seeds, msgs)]
    return pubs, msgs, sigs


def _oracle(pubs, msgs, sigs):
    out = np.zeros(len(pubs), dtype=bool)
    for i in range(len(pubs)):
        try:
            out[i] = bool(_edref.verify(bytes(pubs[i]), bytes(msgs[i]),
                                        bytes(sigs[i])))
        except Exception:  # noqa: BLE001 - malformed = invalid
            out[i] = False
    return out


def _stub_kernels(monkeypatch, record=None, bits_for=None):
    """Replace the comb kernels with shape-checking stubs so routing
    tests never pay an XLA compile.  bits_for(nb) supplies the 'device'
    bitmap (defaults to all-true); record collects launch shapes."""
    import jax.numpy as jnp

    def build(pub):
        k = pub.shape[0]
        if record is not None:
            record.setdefault("builds", []).append(k)
        return C.Cached(None, None, None, None), jnp.ones(k, dtype=bool)

    def kernel(r, sd, kd, vidx, ty, tm, tz, td, dok, by, bm, bt):
        nb = r.shape[0]
        assert sd.shape == (nb, 64) and kd.shape == (nb, 64)
        assert vidx.shape == (nb,)
        if record is not None:
            record.setdefault("launches", []).append(nb)
        if bits_for is not None:
            return jnp.asarray(bits_for(nb))
        return jnp.ones(nb, dtype=bool)

    monkeypatch.setattr(edops, "comb_build_kernel", build)
    monkeypatch.setattr(edops, "comb_kernel", kernel)
    monkeypatch.setattr(edops, "_base_comb", lambda: (None, None, None))
    # stubbed tests are single-device: the conftest's 8-device CPU mesh
    # would route through the REAL jitted mesh comb (an XLA compile)
    from tendermint_tpu.parallel import sharding
    monkeypatch.setattr(sharding, "_PLANE", False)


# ---------------------------------------------------------------------------
# tier-1: group-op inventory by tracing (no compile)
# ---------------------------------------------------------------------------


# captured ONCE at import: repeated _count_group_ops calls re-patch the
# same attributes, and capturing at call time would nest the wrappers
_REAL_OPS = {n: getattr(C, n)
             for n in ("dbl", "dbl_no_t", "add_cached", "madd_niels")}


def _count_group_ops(monkeypatch, fn, *avals):
    """Trace fn over shape avals with instrumented curve group ops.
    Control-flow bodies are traced a small fixed number of times; the
    caller measures that multiplicity with a probe."""
    import jax

    counts = {"dbl": 0, "add": 0}

    def wrap(name, bucket):
        def inner(*a, **kw):
            counts[bucket] += 1
            return _REAL_OPS[name](*a, **kw)
        return inner

    monkeypatch.setattr(C, "dbl", wrap("dbl", "dbl"))
    monkeypatch.setattr(C, "dbl_no_t", wrap("dbl_no_t", "dbl"))
    monkeypatch.setattr(C, "add_cached", wrap("add_cached", "add"))
    monkeypatch.setattr(C, "madd_niels", wrap("madd_niels", "add"))
    jax.eval_shape(fn, *avals)
    return counts


def test_group_op_inventory_traced(monkeypatch):
    """The acceptance arithmetic, counted from the kernels themselves:
    the comb performs ZERO doublings and >= 2.5x fewer group ops per
    launch than the ladder; the published constants can't drift.

    jax may trace a loop body MORE than once (scan traces for aval
    discovery and again for the final jaxpr), so the loop-body
    multiplicity is measured with a one-op probe first."""
    import jax

    B, K = 8, 8
    i32 = np.int32
    sds = jax.ShapeDtypeStruct
    ext = C.Ext(*(sds((F.NLIMB, B), i32) for _ in range(4)))
    dig = sds((64, B), i32)

    # trace multiplicity of a fori body / a scan body (one dbl each)
    m_fori = _count_group_ops(
        monkeypatch,
        lambda p: jax.lax.fori_loop(0, 64, lambda i, q: C.dbl(q), p),
        ext)["dbl"]
    m_scan = _count_group_ops(
        monkeypatch,
        lambda p: jax.lax.scan(lambda g, _: (C.dbl(g), g.x), p, None,
                               length=64),
        ext)["dbl"]
    assert m_fori >= 1 and m_scan >= 1

    # ladder: one var-table build + 64 fori iterations
    tab = _count_group_ops(monkeypatch, edops._build_var_table, ext)
    assert (tab["dbl"], tab["add"]) == (4, 3)
    lad = _count_group_ops(monkeypatch, edops.straus_ladder,
                           ext, dig, dig)
    body_dbl, rd = divmod(lad["dbl"] - tab["dbl"], m_fori)
    body_add, ra = divmod(lad["add"] - tab["add"], m_fori)
    assert rd == 0 and ra == 0, lad
    ladder_total = {"doublings": tab["dbl"] + 64 * body_dbl,
                    "adds": tab["add"] + 64 * body_add}
    assert ladder_total == edops.LADDER_GROUP_OPS

    # comb: 64 iterations of two additions, nothing else
    comb = _count_group_ops(
        monkeypatch, edops.comb_verify_staged,
        sds((B, 32), np.uint8), sds((B, 64), np.int8),
        sds((B, 64), np.int8), sds((B,), i32),
        *(sds((64, 9, F.NLIMB, K), i32) for _ in range(4)),
        sds((K,), np.bool_),
        *(sds((64, 9, F.NLIMB), i32) for _ in range(3)))
    assert comb["dbl"] == 0
    body_add, ra = divmod(comb["add"], m_fori)
    assert ra == 0, comb
    comb_total = {"doublings": 0, "adds": 64 * body_add}
    assert comb_total == edops.COMB_GROUP_OPS

    lad_ops = ladder_total["doublings"] + ladder_total["adds"]
    comb_ops = comb_total["adds"]
    assert lad_ops / comb_ops >= 2.5, (lad_ops, comb_ops)

    # the build scan amortizes: 5 doublings + 3 additions per window,
    # paid once per validator SET, not per signature
    bld = _count_group_ops(monkeypatch, edops.comb_build_kernel_impl,
                           sds((K, 32), np.uint8))
    assert (bld["dbl"], bld["add"]) == (5 * m_scan, 3 * m_scan)


def test_comb_reuses_ladder_lane_buckets(monkeypatch):
    """Tier-1 shape guard: the comb kernel pads its batch axis with the
    SAME bucket_size buckets as every other kernel (floor nb=64) and
    pads the validator axis to powers of two (floor 8) — no new XLA
    shape family for the compile budget to absorb."""
    rec = {}
    _stub_kernels(monkeypatch, record=rec)
    monkeypatch.setattr(edops, "_comb_min_override", 1)
    for n in (5, 24, 64, 90):
        pubs, msgs, sigs = _batch(n, pool=min(n, 6), tag=b"bkt%d" % n)
        out = edops.verify_batch(pubs, msgs, sigs, cache_pubs=True)
        assert out.shape == (n,)
        assert edops.last_launch()["nb"] == edops.bucket_size(n)
    assert rec["launches"] == [edops.bucket_size(n)
                               for n in (5, 24, 64, 90)]
    for k in rec["builds"]:
        assert k >= 8 and (k & (k - 1)) == 0, rec["builds"]


# ---------------------------------------------------------------------------
# tier-1: the unified DeviceLRU
# ---------------------------------------------------------------------------


def test_device_lru_bounds_and_recency():
    evicted = []
    lru = edops.DeviceLRU(max_entries=3,
                          on_evict=lambda k, v: evicted.append(k))
    for i in range(5):
        lru.put(i, f"v{i}")
    assert len(lru) == 3 and evicted == [0, 1]
    assert lru.get(2) == "v2"   # refresh recency
    lru.put(9, "v9")
    assert 2 in lru and 3 not in lru  # 3 was oldest after the refresh
    assert lru.hits == 1 and lru.evictions == 3


def test_device_lru_byte_bound_and_first_wins():
    lru = edops.DeviceLRU(max_bytes=100)
    lru.put("a", 1, nbytes=60)
    lru.put("b", 2, nbytes=60)       # over budget: evicts a
    assert "a" not in lru and lru.total_bytes == 60
    assert lru.put("b", 3, nbytes=60) == 2  # racing upload: first wins
    assert lru.total_bytes == 60
    # a single entry larger than the budget is kept, not thrashed
    lru2 = edops.DeviceLRU(max_bytes=10)
    lru2.put("big", 1, nbytes=50)
    assert "big" in lru2


def test_device_lru_never_over_bound_under_concurrency():
    """The regression the old _pub_cache had: a hit's pop/re-insert
    racing a filler left the dict one over _PUB_CACHE_MAX.  Hammer
    get/put from many threads and assert the bound holds at every
    observation point."""
    lru = edops.DeviceLRU(max_entries=4)
    stop = threading.Event()
    violations = []

    def hammer(tid):
        rng = np.random.default_rng(tid)
        while not stop.is_set():
            k = int(rng.integers(0, 12))
            if lru.get(k) is None:
                lru.put(k, k)

    def watch():
        while not stop.is_set():
            n = len(lru)
            if n > 4:
                violations.append(n)

    threads = [threading.Thread(target=hammer, args=(t,), daemon=True)
               for t in range(6)] + \
        [threading.Thread(target=watch, daemon=True)]
    for t in threads:
        t.start()
    import time
    time.sleep(0.5)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    assert not violations, violations
    assert len(lru) <= 4


# ---------------------------------------------------------------------------
# tier-1: routing (stubbed kernels — no compile)
# ---------------------------------------------------------------------------


def test_comb_routing_build_hit_subset_mixed(monkeypatch):
    rt = degrade.configure(registry=Registry("comb_route"))
    rec = {}
    _stub_kernels(monkeypatch, record=rec)
    monkeypatch.setattr(edops, "_comb_min_override", 8)

    pubs, msgs, sigs = _batch(24)
    # below the build threshold without tables: ladder, no build
    assert edops._comb_try(pubs[:4], msgs[:4], sigs[:4], True,
                           None) is None
    assert "builds" not in rec

    # build + engage
    out = edops.verify_batch(pubs, msgs, sigs, cache_pubs=True)
    assert out.all() and rec["builds"] == [8]
    ll = edops.last_launch()
    assert ll["path"] == "comb" and ll["table_build"] and ll["set_k"] == 6
    assert ll["group_ops"]["doublings"] == 0

    # hit: same set, no cache_pubs (the scheduler-lane shape)
    assert edops.verify_batch(pubs, msgs, sigs).all()
    assert rec["builds"] == [8] and not edops.last_launch()["table_build"]
    assert rt.metrics.table_hits.value() == 1
    assert rt.metrics.table_cache_bytes.value() == \
        edops._table_cache.total_bytes > 0

    # subset of the set resolves through the key-level index
    assert edops.verify_batch(pubs[:5], msgs[:5], sigs[:5]).all()
    assert edops.last_launch()["path"] == "comb"
    assert rt.metrics.table_hits.value() == 2

    # mixed known+unknown keys: the whole batch ladders (stub would
    # have recorded a launch)
    s2 = (0x9911).to_bytes(32, "little")
    launches = len(rec["launches"])
    out = edops.verify_batch(
        pubs[:3] + [_edref.pubkey_from_seed(s2)],
        msgs[:3] + [b"m"], sigs[:3] + [_edref.sign(s2, b"m")])
    assert out.all() and len(rec["launches"]) == launches
    assert edops.last_launch()["path"] == "xla"


def test_comb_disabled_and_budget_declined(monkeypatch):
    rt = degrade.configure(registry=Registry("comb_cfg"))
    rec = {}
    _stub_kernels(monkeypatch, record=rec)
    monkeypatch.setattr(edops, "_comb_min_override", 1)
    pubs, msgs, sigs = _batch(12)

    edops.set_comb_config(enabled=False)
    assert edops.verify_batch(pubs, msgs, sigs, cache_pubs=True).all()
    assert "launches" not in rec and edops.last_launch()["path"] == "xla"

    # budget 0: build declined, routed as comb/declined, ladder verifies
    edops.set_comb_config(enabled=True, table_cache_mb=0)
    assert edops.verify_batch(pubs, msgs, sigs, cache_pubs=True).all()
    assert "launches" not in rec
    assert rt.metrics.msm_route.value(path="comb", outcome="declined") == 1


def test_comb_eviction_midstream_falls_back(monkeypatch):
    """Evicting a set mid-stream degrades that set's batches to the
    ladder — same bitmap, eviction counted, key index cleaned up."""
    rt = degrade.configure(registry=Registry("comb_evict"))
    rec = {}
    _stub_kernels(monkeypatch, record=rec)
    monkeypatch.setattr(edops, "_comb_min_override", 1)
    # budget fits exactly one k_pad=8 set (~1.55 MB): 2 MB
    edops.set_comb_config(table_cache_mb=2)

    pubs_a, msgs_a, sigs_a = _batch(12, tag=b"setA")
    pubs_b, msgs_b, sigs_b = _batch(12, tag=b"setB")
    pubs_b = [_edref.pubkey_from_seed((0x7F00 + i % 6).to_bytes(
        32, "little")) for i in range(12)]
    sigs_b = [_edref.sign((0x7F00 + i % 6).to_bytes(32, "little"), m)
              for i, m in enumerate(msgs_b)]

    assert edops.verify_batch(pubs_a, msgs_a, sigs_a,
                              cache_pubs=True).all()
    assert edops.last_launch()["path"] == "comb"
    assert edops.verify_batch(pubs_b, msgs_b, sigs_b,
                              cache_pubs=True).all()  # evicts set A
    assert rt.metrics.table_evictions.value() == 1
    assert len(edops._table_cache) == 1

    # set A now unknown: ladder fallback, bitmap identical to the oracle
    out = edops.verify_batch(pubs_a, msgs_a, sigs_a)
    assert edops.last_launch()["path"] == "xla"
    assert (out == _oracle(pubs_a, msgs_a, sigs_a)).all() and out.all()
    # key index holds only set B's keys
    with edops._table_key_lock:
        assert len(edops._table_key_index) == 6


def test_eviction_of_overlapping_set_repoints_surviving_keys(monkeypatch):
    """Validator-set changes overlap: when set B (sharing keys with a
    still-resident set A) stole those keys' index entries and is then
    evicted, the index must repoint them to A — not drop them, which
    silently disabled A's subset/no-build comb lookups until rebuild."""
    degrade.configure(registry=Registry("comb_repoint"))
    rec = {}
    _stub_kernels(monkeypatch, record=rec)
    monkeypatch.setattr(edops, "_comb_min_override", 1)
    edops.set_comb_config(table_cache_mb=4)  # fits two k_pad=8 sets

    seeds_a = [(0x7A00 + i).to_bytes(32, "little") for i in range(6)]
    seeds_b = seeds_a[:4] + [(0x9A00 + i).to_bytes(32, "little")
                             for i in range(2)]
    seeds_c = [(0xBB00 + i).to_bytes(32, "little") for i in range(6)]

    def sigset(seeds, tag):
        msgs = [b"%s vote %d" % (tag, i) for i in range(len(seeds))]
        return ([_edref.pubkey_from_seed(s) for s in seeds], msgs,
                [_edref.sign(s, m) for s, m in zip(seeds, msgs)])

    for seeds, tag in ((seeds_a, b"A"), (seeds_b, b"B")):
        p, m, s = sigset(seeds, tag)
        assert edops.verify_batch(p, m, s, cache_pubs=True).all()
    assert len(edops._table_cache) == 2
    # touch A so B is the LRU victim, then build C to evict B
    p, m, s = sigset(seeds_a, b"A2")
    assert edops.verify_batch(p, m, s).all()
    p, m, s = sigset(seeds_c, b"C")
    assert edops.verify_batch(p, m, s, cache_pubs=True).all()
    assert len(edops._table_cache) == 2

    # the keys B shared with A survive B's eviction: a subset batch
    # over them (no cache_pubs — the scheduler-lane shape) still combs
    p, m, s = sigset(seeds_a[:4], b"A3")
    assert edops.verify_batch(p, m, s).all()
    assert edops.last_launch()["path"] == "comb"
    # B's unique keys are gone; A's 6 + C's 6 remain
    with edops._table_key_lock:
        assert len(edops._table_key_index) == 12


def test_comb_batch_over_max_chunk_is_chunked(monkeypatch):
    """A batch above MAX_CHUNK must sub-launch in MAX_CHUNK chunks like
    every other device path (split_chunked_launch), not mint a fresh
    power-of-two bucket shape per giant size class.  MAX_CHUNK shrunk to
    the MIN_BUCKET floor so the stub sees the chunking without a 65k
    staging bill."""
    degrade.configure(registry=Registry("comb_chunk"))
    rec = {}
    state = {"arm": False, "i": 0}

    def bits(nb):
        # armed: the 3rd launch (tail chunk) rejects its local lane 21
        state["i"] += 1
        v = np.ones(nb, dtype=bool)
        if state["arm"] and state["i"] % 3 == 0:
            v[21] = False
        return v

    _stub_kernels(monkeypatch, record=rec, bits_for=bits)
    monkeypatch.setattr(edops, "_comb_min_override", 1)
    monkeypatch.setattr(edops, "MAX_CHUNK", 64)

    pubs, msgs, sigs = _batch(150)
    out = edops.verify_batch(pubs, msgs, sigs, cache_pubs=True)
    assert out.all() and out.shape == (150,)
    # 64 + 64 + 22->64 lanes: every launch inside the existing bucket
    assert rec["launches"] == [64, 64, 64]
    ll = edops.last_launch()
    assert ll["path"] == "comb" and ll["n"] == 150 and ll["nb"] == 192
    # a device verdict in the LAST chunk lands on the right global lane
    # through the concatenation (tail lane 21 -> 2*64 + 21 = 149)
    state["arm"] = True
    out = edops.verify_batch(pubs, msgs, sigs)
    assert not out[149] and out[:149].all()


# ---------------------------------------------------------------------------
# tier-1: chaos at the comb site (stubbed kernels; the degrade plumbing
# above the kernel is exactly what runs against real hardware)
# ---------------------------------------------------------------------------


def _prebuild(monkeypatch, pubs, msgs, sigs, truth):
    _stub_kernels(monkeypatch,
                  bits_for=lambda nb: np.pad(truth, (0, nb - len(truth))))
    monkeypatch.setattr(edops, "_comb_min_override", 1)
    # build through the production seam (stubbed build kernel)
    assert edops.verify_batch(pubs, msgs, sigs, cache_pubs=True) is not None
    assert edops.last_launch()["path"] == "comb"


def _chaos_runtime():
    cfg = degrade.DegradeConfig(
        failure_threshold=3, launch_timeout_s=120.0,
        backoff_base_s=10.0, backoff_max_s=100.0, backoff_jitter=0.0)
    return degrade.configure(cfg, clock=lambda: 0.0,
                             registry=Registry("comb_chaos"))


@pytest.mark.parametrize("mode,reason", [
    ("corrupt-bitmap", "integrity"), ("raise", "raise")])
def test_chaos_at_comb_site_bitmap_exact(monkeypatch, mode, reason):
    """corrupt-bitmap at the comb site is caught by the degradation
    runtime's host spot check (a comb kernel replying garbage is
    degraded, not trusted); an injected raise degrades the lane.  In
    both classes the caller's bitmap is byte-identical to the host
    path."""
    monkeypatch.setenv("TM_TPU_FORCE_BATCH", "1")
    rt = _chaos_runtime()
    privs = [edkeys.PrivKey(bytes([i + 1]) * 32) for i in range(16)]
    msgs = [b"comb chaos %d" % i for i in range(16)]
    sigs = [p.sign(m) for p, m in zip(privs, msgs)]
    sigs[5] = bytes([sigs[5][0] ^ 1]) + sigs[5][1:]
    pubs = [p.pub_key().bytes() for p in privs]
    truth = _oracle(pubs, msgs, sigs)
    assert not truth[5] and truth.sum() == 15
    _prebuild(monkeypatch, pubs, msgs, sigs, truth)

    fail.set_mode("ops.ed25519.comb", mode)
    bv = cb.BatchVerifier(tpu_threshold=4)
    for p, m, s in zip(privs, msgs, sigs):
        bv.add(p.pub_key(), m, s)
    ok, bits = bv.verify()
    assert not ok and (bits == truth).all(), bits
    assert fail.fired("ops.ed25519.comb", mode) >= 1
    assert rt.metrics.device_failures.value(
        site="batch.ed25519", reason=reason) == 1
    assert rt.metrics.host_fallbacks.value(
        site="batch.ed25519", reason=reason) == 1


def test_real_device_fault_propagates_like_chaos(monkeypatch):
    """A RuntimeError out of the comb kernel (the class real device
    faults raise — jaxlib's XlaRuntimeError subclasses RuntimeError)
    must propagate to the degradation runtime exactly like an injected
    raise — NOT be swallowed as a comb bug and re-dispatched through
    the ladder on the same possibly-dead device."""
    rt = degrade.configure(registry=Registry("comb_fault"))
    rec = {}
    _stub_kernels(monkeypatch, record=rec)
    monkeypatch.setattr(edops, "_comb_min_override", 8)
    pubs, msgs, sigs = _batch(16)
    assert edops.verify_batch(pubs, msgs, sigs, cache_pubs=True).all()

    def dying(*a, **kw):
        raise RuntimeError("simulated XlaRuntimeError: device halted")

    monkeypatch.setattr(edops, "comb_kernel", dying)
    with pytest.raises(RuntimeError):
        edops.verify_batch(pubs, msgs, sigs, cache_pubs=True)
    # not routed as a swallowed comb bug
    assert rt.metrics.msm_route.value(path="comb", outcome="error") == 0


def test_ladder_bound_batch_skips_distinct_key_sort(monkeypatch):
    """Once some unrelated set is cached, a large batch of UNKNOWN keys
    (blocksync, cache_pubs=False) must bail on an O(1) key-index probe
    — never pay the O(n log n) distinct-key sort only to ladder
    anyway."""
    rec = {}
    _stub_kernels(monkeypatch, record=rec)
    monkeypatch.setattr(edops, "_comb_min_override", 8)
    pubs, msgs, sigs = _batch(16)
    assert edops.verify_batch(pubs, msgs, sigs, cache_pubs=True).all()
    assert rec["builds"] == [8]

    def boom(*a, **kw):
        raise AssertionError("np.unique on a ladder-bound batch")

    oseeds = [(0x8B00 + i).to_bytes(32, "little") for i in range(12)]
    omsgs = [b"unknown %d" % i for i in range(12)]
    other = [_edref.pubkey_from_seed(s) for s in oseeds]
    osigs = [_edref.sign(s, m) for s, m in zip(oseeds, omsgs)]
    real_unique = np.unique
    np.unique = boom
    try:
        assert edops._comb_try(other, omsgs, osigs, False, None) is None
    finally:
        np.unique = real_unique
    # a known-set batch still resolves (the probe passes, unique runs)
    assert edops.verify_batch(pubs[:6], msgs[:6], sigs[:6]).all()
    assert edops.last_launch()["path"] == "comb"


# ---------------------------------------------------------------------------
# tier-1: config plumbing
# ---------------------------------------------------------------------------


def test_config_comb_roundtrip(tmp_path):
    from tendermint_tpu.config.config import Config

    cfg = Config(home=str(tmp_path))
    assert cfg.batch_verifier.comb is True
    assert cfg.batch_verifier.table_cache_mb == 256
    cfg.batch_verifier.comb = False
    cfg.batch_verifier.table_cache_mb = 64
    cfg.save()
    cfg2 = Config.load(str(tmp_path))
    assert cfg2.batch_verifier.comb is False
    assert cfg2.batch_verifier.table_cache_mb == 64
    cfg2.validate_basic()
    cfg2.batch_verifier.table_cache_mb = -1
    with pytest.raises(ValueError, match="table_cache_mb"):
        cfg2.validate_basic()


def test_set_comb_config_wins_over_env(monkeypatch):
    monkeypatch.setenv("TM_TPU_COMB", "0")
    monkeypatch.setenv("TM_TPU_TABLE_CACHE_MB", "1")
    assert not edops.comb_enabled()
    edops.set_comb_config(enabled=True, table_cache_mb=512)
    assert edops.comb_enabled()
    assert edops.table_cache_budget_bytes() == 512 << 20


# ---------------------------------------------------------------------------
# slow: real kernels — the bitmap-identity sweep and the mesh/scheduler
# paths.  Kernels run UNJITTED (eager) so the only compiles are the
# loop bodies; int32 limb arithmetic is exact, so eager and jit produce
# bit-identical results (pinned by test_comb_jit_matches_eager).
# ---------------------------------------------------------------------------


def _eager_kernels(monkeypatch):
    monkeypatch.setattr(edops, "comb_kernel", edops.comb_verify_staged)
    monkeypatch.setattr(edops, "comb_build_kernel",
                        edops.comb_build_kernel_impl)
    monkeypatch.setattr(edops, "verify_kernel", edops.verify_staged)


def _order8_point():
    from test_msm import _order8_point as f
    return f()


def _torsion_residual_sig(seed, msg):
    """The ADR-009 divergence vector: R' = [r]B + T8 — cofactorless
    reject (comb AND ladder must agree on it)."""
    pub = _edref.pubkey_from_seed(seed)
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    T8 = _order8_point()
    r_nonce = int.from_bytes(
        hashlib.sha512(b"comb torsion nonce").digest(), "little") % _edref.L
    r_enc = _edref._encode(_edref._add(_edref._mul(r_nonce, _edref.BASE),
                                       T8))
    k = int.from_bytes(
        hashlib.sha512(r_enc + pub + msg).digest(), "little") % _edref.L
    s = (r_nonce + k * a) % _edref.L
    return pub, r_enc + s.to_bytes(32, "little")


@pytest.mark.slow
def test_comb_bitmap_identity_sweep(monkeypatch):
    """Comb vs ladder vs host bignum oracle over every encoding class:
    valid, tampered, s >= L, non-canonical R, non-canonical pubkey y,
    negative zero, non-square y, identity key, torsion pubkey, and the
    ADR-009 torsion-residual signature.  One batch, nb=64 bucket."""
    monkeypatch.setenv("TM_TPU_NO_MESH", "1")
    from tendermint_tpu.parallel import sharding
    monkeypatch.setattr(sharding, "_PLANE", None)
    _eager_kernels(monkeypatch)
    monkeypatch.setattr(edops, "_comb_min_override", 1)

    n = 24
    pubs, msgs, sigs = _batch(n, pool=8, tag=b"sweep")
    pubs, sigs, msgs = list(pubs), list(sigs), list(msgs)
    # 1: tampered sig; 2: wrong message binding
    sigs[1] = bytes([sigs[1][0] ^ 1]) + sigs[1][1:]
    msgs[2] = msgs[2] + b"!"
    # 3: non-canonical s (>= L)
    s_big = int.from_bytes(sigs[3][32:], "little") + _edref.L
    sigs[3] = sigs[3][:32] + s_big.to_bytes(32, "little")
    # 4: non-canonical R encoding — y_enc = p + 1 decodes (to y = 1
    # after reduction) but the byte compare must reject it
    sigs[4] = (2 ** 255 - 18).to_bytes(32, "little") + sigs[4][32:]
    # 5: identity pubkey; 6: negative zero (x=0, sign=1); 7: non-square y
    pubs[5] = (1).to_bytes(32, "little")
    pubs[6] = ((1 << 255) | 1).to_bytes(32, "little")
    y = 2
    while _edref._recover_x(y, 0) is not None:
        y += 1
    pubs[7] = y.to_bytes(32, "little")
    # 8: torsion (order-8) pubkey with an honest-format signature
    T8 = _order8_point()
    pubs[8] = _edref._encode(T8)
    # 9: torsion-residual signature (ADR-009 divergence vector)
    tseed = (0x7E01).to_bytes(32, "little")
    pubs[9], sigs[9] = _torsion_residual_sig(tseed, msgs[9])
    # 10: non-canonical pubkey y_enc = p (accepted-and-reduced to the
    # y = 0 order-4 point, matching Go's fe.SetBytes — the comb TABLES
    # are built from the same decompress, so the verdict must agree)
    pubs[10] = (2 ** 255 - 19).to_bytes(32, "little")

    truth = _oracle(pubs, msgs, sigs)
    assert not truth[1:4].any() and not truth[4]

    # ladder first (comb off), then comb (build + engage): bit-identical
    edops.set_comb_config(enabled=False)
    lad = edops.verify_batch(pubs, msgs, sigs, cache_pubs=True)
    assert edops.last_launch()["path"] == "xla"
    edops.set_comb_config(enabled=True)
    comb = edops.verify_batch(pubs, msgs, sigs, cache_pubs=True)
    ll = edops.last_launch()
    assert ll["path"] == "comb" and ll["table_build"]
    assert (comb == lad).all(), (comb, lad)
    assert (comb == truth).all(), (comb, truth)

    # and again as a pure cache hit (the steady-state block shape)
    comb2 = edops.verify_batch(pubs, msgs, sigs)
    assert edops.last_launch()["path"] == "comb"
    assert (comb2 == truth).all()


@pytest.mark.slow
def test_comb_mesh_identity_8dev():
    """The 8-device CPU mesh path: tables replicated per shard, batch
    rows split, bitmap bitwise-identical to single-device comb AND to
    the ladder, unaligned batch size included."""
    import os
    from tendermint_tpu.parallel import sharding

    os.environ.pop("TM_TPU_NO_MESH", None)
    sharding._PLANE = None
    try:
        plane = sharding.data_plane()
        assert plane is not None and plane.nshard >= 8
        edops._comb_min_override = 1

        n = 19  # deliberately not a multiple of the mesh
        pubs, msgs, sigs = _batch(n, pool=5, tag=b"mesh")
        sigs = list(sigs)
        sigs[4] = bytes([sigs[4][0] ^ 1]) + sigs[4][1:]
        truth = _oracle(pubs, msgs, sigs)

        comb = edops.verify_batch(pubs, msgs, sigs, cache_pubs=True)
        ll = edops.last_launch()
        assert ll["path"] == "mesh-comb" and ll["shards"] == plane.nshard
        assert (comb == truth).all(), (comb, truth)

        edops._comb_enabled_override = False
        lad = edops.verify_batch(pubs, msgs, sigs, cache_pubs=True)
        assert (comb == lad).all()
    finally:
        sharding._PLANE = None


@pytest.mark.slow
def test_comb_through_scheduler(monkeypatch):
    """A VerifyScheduler window whose keys resolve to a cached set runs
    the comb on the sched.ed25519 lane — same bitmap, path=comb in the
    launch record."""
    from tendermint_tpu.crypto import scheduler as vsched

    monkeypatch.setenv("TM_TPU_FORCE_BATCH", "1")
    monkeypatch.setenv("TM_TPU_NO_MESH", "1")
    from tendermint_tpu.parallel import sharding
    monkeypatch.setattr(sharding, "_PLANE", None)
    _eager_kernels(monkeypatch)
    monkeypatch.setattr(edops, "_comb_min_override", 1)

    privs = [edkeys.PrivKey(bytes([0x41 + i]) * 32) for i in range(8)]
    pubs = [p.pub_key().bytes() for p in privs]
    msgs = [b"sched comb %d" % i for i in range(32)]
    sigs = [privs[i % 8].sign(m) for i, m in enumerate(msgs)]
    sigs[7] = bytes([sigs[7][0] ^ 1]) + sigs[7][1:]
    truth = _oracle([pubs[i % 8] for i in range(32)], msgs, sigs)

    # build the set once through the bulk path
    assert edops.verify_batch(
        [pubs[i % 8] for i in range(32)], msgs, sigs,
        cache_pubs=True) is not None
    assert edops.last_launch()["path"] == "comb"

    cb.verified_sigs = cb.SigCache()  # no free hits for the window
    sched = vsched.install(vsched.VerifyScheduler(window_s=0.001,
                                                  tpu_threshold=4))
    sched.start()
    try:
        items = [(privs[i % 8].pub_key(), msgs[i], sigs[i])
                 for i in range(32)]
        bits = sched.submit(items, vsched.Priority.CONSENSUS).result(
            timeout=120)
        assert (bits == truth).all(), bits
        assert edops.last_launch()["path"] == "comb"
    finally:
        sched.stop()
        vsched.uninstall(sched)


@pytest.mark.slow
def test_comb_jit_matches_eager(monkeypatch):
    """Pins jit-vs-eager bit identity of the comb kernel itself (the
    sweep runs eager for compile budget; production runs jitted)."""
    import jax.numpy as jnp

    n = 12
    pubs, msgs, sigs = _batch(n, pool=4, tag=b"jit")
    sigs = list(sigs)
    sigs[2] = bytes([sigs[2][0] ^ 1]) + sigs[2][1:]
    pub_m = edops._to_u8_matrix(pubs, 32)
    uniq, inverse = np.unique(pub_m, axis=0, return_inverse=True)
    inverse = np.asarray(inverse).reshape(-1)
    k_pad = edops._comb_k_pad(uniq.shape[0])
    pub_pad = np.zeros((k_pad, 32), np.uint8)
    pub_pad[:uniq.shape[0]] = uniq
    tab, dec_ok = edops.comb_build_kernel_impl(pub_pad)
    _, r_b, s_b, kk, host_ok = edops._stage_rows(
        pub_m, edops._to_u8_matrix(sigs, 64), msgs)
    sd = edops.scalars_to_digits(s_b)
    kd = edops.scalars_to_digits(kk)
    vidx = inverse.astype(np.int32)
    args = (jnp.asarray(r_b), jnp.asarray(sd), jnp.asarray(kd),
            jnp.asarray(vidx), tab.ypx, tab.ymx, tab.z, tab.t2d,
            dec_ok, *edops._base_comb())
    eager = np.asarray(edops.comb_verify_staged(*args))
    jitted = np.asarray(edops.comb_kernel(*args))
    assert (eager == jitted).all()
    truth = _oracle(pubs, msgs, sigs)
    assert ((eager & host_ok) == truth).all()
