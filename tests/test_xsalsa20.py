"""crypto/xsalsa20: NaCl secretbox (the reference's legacy symmetric
cipher, crypto/xsalsa20symmetric/symmetric.go)."""
import pytest

from tendermint_tpu.crypto.xsalsa20 import (SymmetricError, _salsa20_core,
                                            decrypt_symmetric,
                                            encrypt_symmetric, hsalsa20,
                                            poly1305, secretbox_open,
                                            secretbox_seal)


def test_salsa20_core_zero_fixed_point():
    """Core(x) = x + doubleround^10(x); x = 0 is a fixed point at 0 —
    but the real state always carries the sigma constants, so also pin
    a nonzero structural property: the core is 64 bytes."""
    assert _salsa20_core([0] * 16) == b"\x00" * 64


def test_poly1305_rfc8439_vector():
    key = bytes.fromhex("85d6be7857556d337f4452fe42d506a8"
                        "0103808afb0db2fd4abff6af4149f51b")
    tag = poly1305(b"Cryptographic Forum Research Group", key)
    assert tag.hex() == "a8061dc1305136c6c22b8baf0c0127a9"


def test_secretbox_nacl_paper_vector_prefix():
    """The crypto_secretbox vector from the NaCl paper (also the
    golang.org/x/crypto/nacl/secretbox test): a stream cipher's
    ciphertext prefix depends only on the plaintext prefix, so the
    48-byte prefix pins key schedule, HSalsa20, counter layout, and the
    keystream offset-32 construction."""
    key = bytes.fromhex("1b27556473e985d462cd51197a9a46c7"
                        "6009549eac6474f206c4ee0844f68389")
    nonce = bytes.fromhex("69696ee955b62b73cd62bda875fc73d6"
                          "8219e0036b7a0b37")
    m48 = bytes.fromhex(
        "be075fc53c81f2d5cf141316ebeb0c7b5228c52a4c62cbd44b66849b64244ffc"
        "e5ecbaaf33bd751a1ac728d45e6c6129")
    ct = secretbox_seal(m48, nonce, key)[16:]  # strip the tag
    assert ct.hex() == (
        "8e993b9f48681273c29650ba32fc76ce48332ea7164d96a4476fb8c531a1186a"
        "c0dfc17c98dce87b4da7f011ec48c972")


def test_hsalsa20_subkey_shape():
    out = hsalsa20(b"\x01" * 32, b"\x02" * 16)
    assert len(out) == 32 and out != b"\x00" * 32


def test_encrypt_decrypt_roundtrip():
    secret = b"somesecretoflengththirtytwo===32"
    for pt in (b"a", b"sometext", b"x" * 1000):
        ct = encrypt_symmetric(pt, secret)
        assert len(ct) == 24 + 16 + len(pt)
        assert decrypt_symmetric(ct, secret) == pt
        # distinct nonces per call
        assert encrypt_symmetric(pt, secret) != ct
    # empty plaintext: same refusal as the reference's length check
    # (symmetric.go:40 `len(ciphertext) <= secretbox.Overhead+nonceLen`)
    with pytest.raises(SymmetricError):
        decrypt_symmetric(encrypt_symmetric(b"", secret), secret)


def test_tamper_and_wrong_key_rejected():
    secret = b"somesecretoflengththirtytwo===32"
    ct = bytearray(encrypt_symmetric(b"armored private key", secret))
    for pos in (0, 24, 40, len(ct) - 1):  # nonce, tag, ciphertext
        bad = bytearray(ct)
        bad[pos] ^= 1
        with pytest.raises(SymmetricError):
            decrypt_symmetric(bytes(bad), secret)
    with pytest.raises(SymmetricError):
        decrypt_symmetric(bytes(ct), b"B" * 32)
    with pytest.raises(SymmetricError):
        decrypt_symmetric(b"short", secret)
    with pytest.raises(SymmetricError):
        encrypt_symmetric(b"x", b"shortkey")


def test_secretbox_open_matches_seal():
    key = b"\x07" * 32
    nonce = b"\x09" * 24
    boxed = secretbox_seal(b"hello world", nonce, key)
    assert secretbox_open(boxed, nonce, key) == b"hello world"
