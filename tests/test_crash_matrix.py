"""Crash-recovery matrix over the planted fail points.

The reference exercises its commit-path crash windows by killing the
process at indexed `fail.Fail()` sites and asserting WAL + handshake
replay recovers (reference consensus/replay_test.go crash matrix,
libs/fail/fail.go:28-39).  Here: a single-validator node in a subprocess
dies at each FAIL_TEST_INDEX juncture of the first commit — between
block-save, WAL EndHeight fsync, ABCI-response save, app commit and
state save (consensus/state.py fail points 10-12,
state/execution.py 1-4) — then restarts from the same home dir and must
make progress past the crashed height.
"""
from __future__ import annotations

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the child runs a real node (file WAL, SQLite stores, FilePV) until the
# block store reaches the target height, then exits 0
CHILD = r"""
import os, sys, time
sys.path.insert(0, {repo!r})
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")
import tendermint_tpu
import jax
jax.config.update("jax_platforms", "cpu")

from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.config.config import Config
from tendermint_tpu.node import Node
from tendermint_tpu.p2p.key import NodeKey
from tendermint_tpu.privval.file_pv import FilePV
from tendermint_tpu.types.basic import Timestamp
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

home, target = sys.argv[1], int(sys.argv[2])
cfg = Config(home=home)
cfg.p2p.laddr = "127.0.0.1:0"
cfg.p2p.pex = False
cfg.rpc.enabled = False
c = cfg.consensus
c.timeout_propose = c.timeout_prevote = c.timeout_precommit = 0.2
c.timeout_propose_delta = c.timeout_prevote_delta = \
    c.timeout_precommit_delta = 0.1
c.timeout_commit = 0.05
cfg.ensure_dirs()
pv = FilePV.load_or_generate(cfg.priv_validator_key_file(),
                             cfg.priv_validator_state_file())
NodeKey.load_or_generate(cfg.node_key_file())
if not os.path.exists(cfg.genesis_file()):
    pub = pv.get_pub_key()
    gdoc = GenesisDoc(chain_id="crash-matrix-chain",
                      genesis_time=Timestamp(1700000000, 0),
                      validators=[GenesisValidator(
                          address=pub.address(), pub_key_type=pub.type_name,
                          pub_key_bytes=pub.bytes(), power=10)])
    with open(cfg.genesis_file(), "w") as f:
        f.write(gdoc.to_json())

node = Node(cfg, KVStoreApplication())
node.start()
deadline = time.time() + 60
while time.time() < deadline:
    if node.block_store.height() >= target:
        node.stop()
        sys.exit(0)
    time.sleep(0.05)
sys.exit(3)  # no progress
"""


def _run(home: str, target: int, fail_index: int | None):
    env = dict(os.environ)
    env.pop("FAIL_TEST_INDEX", None)
    if fail_index is not None:
        env["FAIL_TEST_INDEX"] = str(fail_index)
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, "-c", CHILD.format(repo=REPO), home, str(target)],
        env=env, capture_output=True, timeout=120)


# 7 fail points per commit: consensus 10,11,12 then execution 1,2,3,4
@pytest.mark.slow
@pytest.mark.parametrize("fail_index", range(7))
def test_crash_at_fail_point_then_recover(tmp_path, fail_index):
    home = str(tmp_path / "node")

    r = _run(home, target=3, fail_index=fail_index)
    assert r.returncode == 77, (
        f"expected death at fail point {fail_index}, rc={r.returncode}\n"
        f"stderr: {r.stderr[-2000:].decode(errors='replace')}")

    # restart without injection: WAL catchup + handshake replay must
    # recover whatever the crash window left and keep committing
    r = _run(home, target=3, fail_index=None)
    assert r.returncode == 0, (
        f"recovery after fail point {fail_index} failed rc={r.returncode}\n"
        f"stderr: {r.stderr[-2000:].decode(errors='replace')}")


@pytest.mark.slow
def test_crash_matrix_double_restart(tmp_path):
    """Crash at the first juncture, recover, then crash again at a later
    juncture of a subsequent commit, and recover again."""
    home = str(tmp_path / "node")
    r = _run(home, target=3, fail_index=0)
    assert r.returncode == 77
    r = _run(home, target=3, fail_index=10)  # a later hit, height >= 2
    assert r.returncode == 77
    r = _run(home, target=4, fail_index=None)
    assert r.returncode == 0, r.stderr[-2000:].decode(errors="replace")
