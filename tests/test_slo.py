"""libs/slo: the sliding-window SLO estimator (ADR-016) — disabled
no-op discipline (trace.py's contract), exact-over-the-window quantiles
vs a sorted-array oracle (wraparound included), burn rates against
targets, and the config/env wiring."""
from __future__ import annotations

import math
import random
import threading
import timeit

import pytest

from tendermint_tpu.libs import slo
from tendermint_tpu.libs.slo import SloEstimator


@pytest.fixture(autouse=True)
def _clean():
    yield
    slo.disable()
    slo.reset()


# ---------------------------------------------------------------------------
# disabled path
# ---------------------------------------------------------------------------

def test_disabled_records_nothing():
    est = SloEstimator(window=16, enabled=False)
    for i in range(100):
        est.observe("consensus", i / 1000.0)
    assert est.window_values("consensus") == []
    assert est.quantile("consensus", 0.99) is None
    assert est.stream_report("consensus") is None
    est.enable()
    est.observe("consensus", 0.001)
    assert est.window_values("consensus") == [0.001]
    est.disable()
    est.observe("consensus", 0.002)
    assert est.window_values("consensus") == [0.001]


def test_disabled_call_site_overhead_sub_microsecond():
    """The scheduler and the direct verify path call slo.observe()
    unconditionally per settled request, so the disabled path must
    stay sub-microsecond (one enabled check, one return) — same gate
    trace.py carries.  min-of-repeats dodges CI load spikes."""
    slo.disable()
    n = 20000

    def site():
        slo.observe("consensus", 0.0042)

    per_call = min(timeit.repeat(site, number=n, repeat=5)) / n
    assert per_call < 1e-6, f"disabled observe cost {per_call * 1e9:.0f} ns"


# ---------------------------------------------------------------------------
# quantiles vs the sorted-array oracle
# ---------------------------------------------------------------------------

def _oracle_quantile(window_vals, q):
    """Nearest-rank over the sorted window: the smallest value with at
    least ceil(q*n) of the window at or below it."""
    vals = sorted(window_vals)
    k = max(1, math.ceil(q * len(vals)))
    return vals[k - 1]


@pytest.mark.parametrize("window,total", [
    (64, 40),     # partially filled ring
    (64, 64),     # exactly full
    (64, 1000),   # wrapped many times
    (1, 17),      # degenerate one-slot ring
])
def test_quantiles_match_sorted_oracle(window, total):
    """Property: for ANY observation stream, the estimator's quantile
    equals the nearest-rank quantile of the LAST `window` observations
    — the ring is an exact sliding window, wraparound included."""
    rng = random.Random(window * 100003 + total)
    est = SloEstimator(window=window, enabled=True)
    seen = []
    for _ in range(total):
        v = rng.expovariate(100.0)  # latency-shaped heavy tail
        est.observe("s", v)
        seen.append(v)
    tail = seen[-window:]
    assert sorted(est.window_values("s")) == sorted(tail)
    for q in (0.01, 0.5, 0.9, 0.99, 1.0):
        assert est.quantile("s", q) == _oracle_quantile(tail, q), (
            window, total, q)


def test_streams_are_independent():
    est = SloEstimator(window=8, enabled=True)
    for i in range(8):
        est.observe("a", 0.001)
        est.observe("b", 0.100)
    assert est.quantile("a", 0.99) == 0.001
    assert est.quantile("b", 0.99) == 0.100


# ---------------------------------------------------------------------------
# burn rate
# ---------------------------------------------------------------------------

def test_burn_rate_against_target():
    """10% of the window over a p99 target burns the 1% budget at 10x."""
    est = SloEstimator(window=100, enabled=True,
                       targets={"mempool": 0.05})
    for i in range(100):
        est.observe("mempool", 0.2 if i % 10 == 0 else 0.01)
    rep = est.stream_report("mempool")
    assert rep["n"] == 100
    assert rep["target_p99_s"] == 0.05
    assert rep["over_target_frac"] == pytest.approx(0.10)
    assert rep["burn_rate"] == pytest.approx(10.0)
    # a stream with no target reports quantiles but no burn rate
    est.observe("commit", 0.01)
    rep2 = est.stream_report("commit")
    assert "burn_rate" not in rep2 and "target_p99_s" not in rep2


def test_report_shape_and_reset():
    est = SloEstimator(window=4, enabled=True, targets={"commit": 1.0})
    est.observe("commit", 0.5)
    rep = est.report()
    assert rep["enabled"] is True and rep["window"] == 4
    assert rep["targets_s"] == {"commit": 1.0}
    assert rep["streams"]["commit"]["p50_s"] == 0.5
    est.reset()
    assert est.report()["streams"] == {}


# ---------------------------------------------------------------------------
# process-global wiring
# ---------------------------------------------------------------------------

def test_set_config_wins_over_env_both_ways(monkeypatch):
    """Node wiring: [slo] enable=true arms the estimator even without
    TM_TPU_SLO; enable=false disarms it even WITH TM_TPU_SLO=1 (the
    same both-ways contract as secp.set_lane_enabled)."""
    monkeypatch.delenv("TM_TPU_SLO", raising=False)
    slo.set_config(enabled=True, window=32,
                   targets={"consensus": 0.005})
    assert slo.is_enabled()
    assert slo.EST.window == 32
    assert slo.EST.targets == {"consensus": 0.005}
    slo.observe("consensus", 0.001)
    assert slo.quantile("consensus", 0.5) == 0.001

    monkeypatch.setenv("TM_TPU_SLO", "1")
    slo.set_config(enabled=False)
    assert not slo.is_enabled()


def test_enable_resizes_window_and_drops_stale_rings():
    est = SloEstimator(window=4, enabled=True)
    for i in range(4):
        est.observe("s", float(i))
    est.enable(window=8)
    assert est.window == 8
    assert est.window_values("s") == []  # rings are sized at creation
    for i in range(3):
        est.observe("s", float(i))
    assert len(est.window_values("s")) == 3


def test_concurrent_observes_keep_ring_bounded():
    est = SloEstimator(window=64, enabled=True)

    def worker(k):
        for i in range(500):
            est.observe("hot", k + i / 1000.0)

    ts = [threading.Thread(target=worker, args=(k,)) for k in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    vals = est.window_values("hot")
    assert len(vals) == 64
    assert est.stream_report("hot")["n"] == 64
