"""IngressGate (mempool/ingress.py, ADR-018): overload-safe mempool
admission — staged CheckTx parity, cache-poison + blocking-under-lock
regressions, the ingress.* chaos matrix, per-source rate-limit
fairness, and the flood-isolation acceptance scenario (a sustained
over-capacity MEMPOOL-class flood must not starve CONSENSUS-class
verifies or the commit path).

No XLA kernels compile here: every scheduler is built with
tpu_threshold high enough that all verification stays on host lanes,
and batches stay far below the device cutover anyway."""
from __future__ import annotations

import threading
import time

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.crypto import ed25519 as edkeys
from tendermint_tpu.crypto import scheduler as vsched
from tendermint_tpu.libs import fail, slo
from tendermint_tpu.libs.metrics import Registry
from tendermint_tpu.mempool import ingress as ing
from tendermint_tpu.mempool.ingress import (IngressGate, make_signed_tx,
                                            parse_signed_tx)
from tendermint_tpu.mempool.mempool import CODE_APP_EXCEPTION, Mempool
from tendermint_tpu.mempool.priority_mempool import PriorityMempool


class EchoApp(abci.Application):
    """CheckTx accepts everything except txs starting with b'bad';
    counts calls; optional per-call delay / raise."""

    def __init__(self, delay_s: float = 0.0):
        self.delay_s = delay_s
        self.calls = 0
        self.raise_on = None  # tx prefix that makes check_tx RAISE
        self._lock = threading.Lock()

    def check_tx(self, req):
        with self._lock:
            self.calls += 1
        if self.raise_on is not None and req.tx.startswith(self.raise_on):
            raise RuntimeError("app exploded")
        if self.delay_s:
            time.sleep(self.delay_s)
        if req.tx.startswith(b"bad") or b"\x00bad" in req.tx[:110]:
            return abci.ResponseCheckTx(code=10, log="app says no")
        return abci.ResponseCheckTx(code=abci.CODE_TYPE_OK, gas_wanted=1)


@pytest.fixture(autouse=True)
def _clean():
    fail.reset()
    ing.set_enabled(None)
    yield
    fail.reset()
    ing.set_enabled(None)
    vsched.uninstall()


@pytest.fixture
def gate_factory():
    """Build + start gates on private mempools; stopped at teardown
    (the conftest thread-leak guard watches the workers)."""
    created = []

    def make(app=None, mempool=None, start=True, **kw):
        mp = mempool if mempool is not None else \
            Mempool(app or EchoApp(), registry=Registry())
        g = IngressGate(mp, **kw).attach()
        created.append(g)
        if start:
            g.start()
        return g, mp

    yield make
    for g in created:
        g.stop()


@pytest.fixture
def sched_factory():
    created = []

    def make(**kw):
        kw.setdefault("tpu_threshold", 10 ** 9)  # host lanes only
        s = vsched.VerifyScheduler(**kw)
        created.append(s)
        vsched.install(s)
        s.start()
        return s

    yield make
    for s in created:
        s.stop()
    vsched.uninstall()


_PRIVS = [edkeys.PrivKey(bytes([(i * 11 + 5) % 255 + 1]) * 32)
          for i in range(8)]


def _sigtx(i: int, tag: bytes = b"flood") -> bytes:
    return make_signed_tx(_PRIVS[i % len(_PRIVS)],
                          tag + b" payload %06d" % i)


def _consensus_triples(n: int, tag: bytes = b"vote"):
    msgs = [tag + b" sign bytes %06d" % i for i in range(n)]
    return [( _PRIVS[i % len(_PRIVS)].pub_key(), msgs[i],
              _PRIVS[i % len(_PRIVS)].sign(msgs[i])) for i in range(n)]


# ---------------------------------------------------------------------------
# staged-admission parity + the two bugfix regressions
# ---------------------------------------------------------------------------

def test_gate_results_identical_to_synchronous_path(gate_factory):
    """The same tx sequence through the gate and through a synchronous
    twin mempool yields bitwise-identical ResponseCheckTx objects for
    every rejection class the sync path can produce."""
    mp = Mempool(EchoApp(), registry=Registry(), size_limit=3)
    g, _mp = gate_factory(mempool=mp)
    twin = Mempool(EchoApp(), registry=Registry(), size_limit=3)
    txs = ([b"ok-0", b"ok-0"]                     # admit + cache dup
           + [b"bad-app"]                         # app rejection
           + [b"x" * (g.mempool.max_tx_bytes + 1)]  # too large
           + [b"ok-1", b"ok-2"]                   # fill to the limit
           + [b"ok-late"])                        # mempool full
    got = [g.check_tx(t, timeout=10.0) for t in txs]
    want = [twin.check_tx(t) for t in txs]
    assert got == want
    assert [r.log for r in want] == ["", "tx already in cache",
                                     "app says no", "tx too large",
                                     "", "", "mempool is full"]


def test_checktx_cache_poisoning_regression():
    """An app exception used to propagate out of check_tx and leave
    the tx hash in TxCache — every retry bounced as "already in cache"
    forever.  Now: coded error, cache clean, the retry reaches the app
    again."""
    app = EchoApp()
    app.raise_on = b"boom"
    mp = Mempool(app, registry=Registry())
    res = mp.check_tx(b"boom-tx")
    assert res.code == CODE_APP_EXCEPTION and "check_tx failed" in res.log
    assert app.calls == 1
    app.raise_on = None  # the app recovers
    res2 = mp.check_tx(b"boom-tx")
    assert res2.is_ok() and app.calls == 2  # retry reached the app
    assert mp.size() == 1


def test_priority_mempool_cache_poisoning_regression():
    class PrioBoom(abci.Application):
        def __init__(self):
            self.calls = 0
            self.armed = True

        def check_tx(self, req):
            self.calls += 1
            if self.armed:
                raise RuntimeError("v1 app exploded")
            return abci.ResponseCheckTx(code=abci.CODE_TYPE_OK,
                                        priority=1)
    app = PrioBoom()
    mp = PriorityMempool(app, registry=Registry())
    res = mp.check_tx(b"\x05\x00v1-boom")
    assert res.code == CODE_APP_EXCEPTION
    app.armed = False
    assert mp.check_tx(b"\x05\x00v1-boom").is_ok() and app.calls == 2


def test_app_code_2_rejection_is_not_poisoned():
    """An app that legitimately RETURNS code 2 (the same value as
    CODE_APP_EXCEPTION) is a normal rejection: the cache claim must be
    released so a retry reaches the app again — on BOTH mempools."""
    class Code2App(abci.Application):
        def __init__(self):
            self.calls = 0
            self.accept = False

        def check_tx(self, req):
            self.calls += 1
            if self.accept:
                return abci.ResponseCheckTx(code=0, priority=1)
            return abci.ResponseCheckTx(code=2, log="app code 2")

    for mk in (lambda a: Mempool(a, registry=Registry()),
               lambda a: PriorityMempool(a, registry=Registry())):
        app = Code2App()
        mp = mk(app)
        res = mp.check_tx(b"code2-tx")
        assert res.code == 2 and res.log == "app code 2"
        app.accept = True
        assert mp.check_tx(b"code2-tx").is_ok()  # not "already in cache"
        assert app.calls == 2


def test_app_call_runs_outside_the_mempool_lock():
    """A slow app must not hold the mempool hostage: while check_tx is
    blocked inside the app, lock-taking reads return immediately (the
    lock now brackets only map mutation)."""
    app = EchoApp(delay_s=0.4)
    mp = Mempool(app, registry=Registry())
    t = threading.Thread(target=mp.check_tx, args=(b"slow-tx",),
                         daemon=True)
    t.start()
    # wait until the app call is in flight
    deadline = time.monotonic() + 2.0
    while app.calls == 0 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert app.calls == 1
    t0 = time.monotonic()
    mp.size(), mp.reap_max_txs(-1), mp.txs_after(0)
    assert time.monotonic() - t0 < 0.2  # not serialized behind the app
    t.join(timeout=2.0)
    assert not t.is_alive() and mp.size() == 1


# ---------------------------------------------------------------------------
# overload policy: queue-full busy, rate-limit fairness
# ---------------------------------------------------------------------------

def test_queue_full_rejects_busy_with_retry_hint(gate_factory):
    fail.set_mode("ingress.checktx", "latency:150")  # stall the worker
    g, mp = gate_factory(queue_size=4, batch=2)
    futs = [g.submit(b"q-%d" % i) for i in range(12)]
    busy = [f for f in futs if f.done() and f.retry_after_s is not None]
    assert busy, "over-capacity submissions must bounce immediately"
    for f in busy:
        r = f.result(timeout=0)
        assert r.code == 1 and r.codespace == "ingress"
        assert r.log == "mempool is busy"
        assert f.retry_after_s > 0
    deadline = time.monotonic() + 5.0
    while not fail.fired("ingress.checktx", "latency:150") and \
            time.monotonic() < deadline:
        time.sleep(0.01)
    assert fail.fired("ingress.checktx", "latency:150") >= 1
    assert mp.metrics.rejected_txs.value(reason="busy") >= len(busy)
    fail.clear()
    # the queued ones settle once the worker catches up
    for f in futs:
        assert f.result(timeout=10.0) is not None


def test_per_source_rate_limit_fairness(gate_factory):
    """8-thread hammer: one flooding source must not push a modest
    source into rejection — buckets are per source."""
    g, mp = gate_factory(queue_size=4096, rate_per_s=25.0, burst=5)
    flood_rejected = []
    nice_results = []

    def flood(k):
        for i in range(60):
            f = g.submit(b"fl-%d-%d" % (k, i), source="p2p:flooder")
            if f.done() and f.retry_after_s is not None:
                flood_rejected.append(f)

    def nice(k):
        f = g.submit(b"ni-%d" % k, source=f"p2p:nice{k}")
        nice_results.append(f.result(timeout=10.0))

    threads = [threading.Thread(target=flood, args=(k,)) for k in range(6)]
    threads += [threading.Thread(target=nice, args=(k,)) for k in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(flood_rejected) >= 300  # 360 attempts vs burst 5 + trickle
    for r in nice_results:  # the modest sources were never rate-limited
        assert r.is_ok(), r
    assert mp.metrics.rejected_txs.value(reason="ratelimit") \
        >= len(flood_rejected)
    assert g.stats()["ratelimited"] == len(flood_rejected)


# ---------------------------------------------------------------------------
# chaos matrix: every ingress.* site, raise + latency, exact parity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", ["raise", "latency:60"])
def test_chaos_ingress_admit(gate_factory, mode):
    fail.set_mode("ingress.admit", mode)
    g, _ = gate_factory(app=EchoApp())
    twin = Mempool(EchoApp(), registry=Registry())
    txs = [b"adm-%d" % i for i in range(3)] + [b"adm-0", b"bad-adm"]
    got = [g.check_tx(t, timeout=10.0) for t in txs]
    want = [twin.check_tx(t) for t in txs]
    assert got == want
    assert fail.fired("ingress.admit", mode) >= len(txs)
    if mode == "raise":  # fell back to the synchronous in-caller path
        assert g.stats()["submitted"] == len(txs)
        assert g.depth() == 0


@pytest.mark.parametrize("mode", ["raise", "latency:60"])
def test_chaos_ingress_checktx(gate_factory, mode):
    fail.set_mode("ingress.checktx", mode)
    g, mp = gate_factory(app=EchoApp())
    twin = Mempool(EchoApp(), registry=Registry())
    txs = [b"ctx-%d" % i for i in range(3)] + [b"ctx-0", b"bad-ctx"]
    got = [g.check_tx(t, timeout=10.0) for t in txs]
    want = [twin.check_tx(t) for t in txs]
    assert got == want
    assert fail.fired("ingress.checktx", mode) >= 1
    if mode == "raise":
        assert g.stats()["fallback_batches"] >= 1
    assert mp.size() == 3


@pytest.mark.parametrize("mode", ["raise", "latency:60"])
def test_chaos_ingress_recheck(gate_factory, mode):
    """raise at the scheduling seam ⇒ update() degrades to the
    synchronous in-caller recheck (the pre-gate behavior): stale txs
    are gone the moment update() returns."""
    class StaleApp(EchoApp):
        def __init__(self):
            super().__init__()
            self.stale = False

        def check_tx(self, req):
            if self.stale and req.type == abci.CheckTxType.RECHECK:
                return abci.ResponseCheckTx(code=1, log="stale")
            return super().check_tx(req)

    app = StaleApp()
    g, mp = gate_factory(app=app, recheck_slice=4)
    for i in range(5):
        assert g.check_tx(b"rc-%d" % i, timeout=10.0).is_ok()
    assert mp.size() == 5
    app.stale = True
    fail.set_mode("ingress.recheck", mode)
    mp.lock()
    try:
        mp.update(2, [])
    finally:
        mp.unlock()
    assert fail.fired("ingress.recheck", mode) == 1
    if mode == "raise":
        assert mp.size() == 0  # synchronous recheck already ran
    else:
        deadline = time.monotonic() + 10.0
        while mp.size() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert mp.size() == 0  # offloaded recheck drained the pool


def test_update_returns_in_o_committed_with_gate_attached(gate_factory):
    """Post-block recheck rides the ingress worker: update() must not
    pay a per-resident-tx app round trip on the commit path."""
    app = EchoApp(delay_s=0.02)  # 20 ms per app call
    g, mp = gate_factory(app=app, recheck_slice=8)
    app.delay_s = 0.0
    for i in range(30):
        assert g.check_tx(b"res-%d" % i, timeout=10.0).is_ok()
    app.delay_s = 0.02
    mp.lock()
    try:
        t0 = time.monotonic()
        mp.update(3, [])
        dt = time.monotonic() - t0
    finally:
        mp.unlock()
    # synchronous recheck would cost 30 * 20 ms = 600 ms
    assert dt < 0.2, f"update() held the commit path {dt:.3f}s"
    deadline = time.monotonic() + 20.0
    while g.stats()["rechecked"] < 30 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert g.stats()["rechecked"] >= 30  # and the recheck DID happen


# ---------------------------------------------------------------------------
# batched signature pre-verification through the scheduler
# ---------------------------------------------------------------------------

def test_preverify_rejects_refuted_signature_before_the_app(
        gate_factory, sched_factory):
    sched_factory()
    app = EchoApp()
    g, mp = gate_factory(app=app)
    good = _sigtx(1, tag=b"pv-good")
    bad = bytearray(_sigtx(2, tag=b"pv-bad"))
    bad[len(ing.SIGTX_MAGIC) + 32] ^= 0x01  # corrupt the signature
    bad = bytes(bad)
    r_good = g.check_tx(good, timeout=30.0)
    r_bad = g.check_tx(bad, timeout=30.0)
    assert r_good.is_ok()
    assert r_bad.code == 1 and r_bad.log == "invalid signature"
    assert mp.metrics.rejected_txs.value(reason="sig") == 1
    # the refuted tx never burned an app call; the good one did
    assert app.calls == 1
    # the cache claim was released: a corrected retry is not "already
    # in cache"
    assert g.check_tx(good, timeout=30.0).log == "tx already in cache"


def test_preverify_skipped_when_scheduler_absent(gate_factory):
    """No scheduler ⇒ the app still sees every tx (the synchronous
    path's behavior); SIGTX parsing alone must not reject."""
    app = EchoApp()
    g, _ = gate_factory(app=app)
    assert g.check_tx(_sigtx(3, tag=b"nosched"), timeout=10.0).is_ok()
    assert app.calls == 1


# ---------------------------------------------------------------------------
# flood isolation: the acceptance scenario
# ---------------------------------------------------------------------------

def test_flood_cannot_starve_consensus_verifies(gate_factory,
                                                sched_factory):
    """Sustained over-capacity MEMPOOL-class flood concurrent with
    CONSENSUS-class preverify traffic: CONSENSUS verifies are never
    shed and keep completing correctly, the commit path's update()
    stays O(committed), queue depth stays bounded, and the overload
    surfaces as busy rejections + MEMPOOL sheds — with the SLO stream
    and admission metrics moving."""
    from tendermint_tpu.crypto import degrade

    # max_pending below one gate preverify batch: every MEMPOOL-class
    # submission sheds (the overload regime); CONSENSUS is admitted by
    # class policy no matter what
    s = sched_factory(window_s=0.001, max_pending=4)
    app = EchoApp()
    g, mp = gate_factory(app=app, queue_size=48, batch=8, workers=2)
    metrics = degrade.runtime().metrics
    shed_before = metrics.sched_shed_total.value(priority="mempool")
    cons_shed_before = metrics.sched_shed_total.value(priority="consensus")
    slo.set_config(enabled=True, window=256,
                   targets={"mempool": 0.25})
    stop = threading.Event()
    depth_samples = []
    cons_rounds = 0
    cons_err = []

    # pre-sign the flood outside the timed region (host signing is
    # slow; the flood itself must be submission-bound)
    flood_txs = [[_sigtx(k * 1000 + i, tag=b"fl%d" % k)
                  for i in range(60)] for k in range(4)]
    raw_txs = [b"raw-flood-%04d" % i for i in range(120)]
    triples = _consensus_triples(12)

    def flooder(k):
        while not stop.is_set():
            for tx in flood_txs[k]:
                g.submit(tx, source=f"p2p:peer{k}")
            depth_samples.append(g.depth())
            for tx in raw_txs[k * 30:(k + 1) * 30]:
                g.submit(tx, source="rpc")
            if stop.is_set():
                return

    def consensus_loop():
        nonlocal cons_rounds
        while cons_rounds < 6:
            ok, bits = vsched.verify_items(
                triples, vsched.Priority.CONSENSUS,
                deadline=time.monotonic() + 0.005)
            if not (ok and bits.all()):
                cons_err.append(bits)
                return
            cons_rounds += 1
            # the commit path: lock -> update -> unlock must stay
            # O(committed txs) while the flood rages
            mp.lock()
            try:
                t0 = time.monotonic()
                mp.update(cons_rounds, [])
                commit_dt = time.monotonic() - t0
            finally:
                mp.unlock()
            assert commit_dt < 0.5, commit_dt

    threads = [threading.Thread(target=flooder, args=(k,), daemon=True)
               for k in range(4)]
    cons = threading.Thread(target=consensus_loop)
    try:
        for t in threads:
            t.start()
        cons.start()
        cons.join(timeout=60.0)
        assert not cons.is_alive(), \
            "consensus preverify starved by the mempool flood"
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5.0)
        slo.set_config(enabled=False)
    assert not cons_err, "consensus bitmaps corrupted under flood"
    assert cons_rounds == 6  # consensus made progress, every round
    # zero CONSENSUS sheds; MEMPOOL sheds moved
    assert metrics.sched_shed_total.value(priority="consensus") \
        == cons_shed_before
    assert metrics.sched_shed_total.value(priority="mempool") > shed_before
    assert g.stats()["preverify_shed"] > 0
    # overload surfaced as retryable busy rejections, and the queue
    # never exceeded its bound
    assert mp.metrics.rejected_txs.value(reason="busy") > 0
    assert depth_samples and max(depth_samples) <= g.queue_size
    # observability moved: admission latency histogram + SLO stream
    assert mp.metrics.admission_latency.count() > 0
    rep = slo.stream_report("mempool")
    assert rep is not None and rep["n"] > 0


# ---------------------------------------------------------------------------
# reactor + RPC backpressure seams
# ---------------------------------------------------------------------------

def test_reactor_routes_through_gate_and_throttles(gate_factory):
    from tendermint_tpu.mempool import reactor as reactor_mod

    class FakePeer:
        id = "peer-a"

    fail.set_mode("ingress.checktx", "latency:100")  # keep the queue full
    g, mp = gate_factory(queue_size=2, batch=1)
    reactor = reactor_mod.MempoolReactor(mp, gate=g)
    reactor.THROTTLE_S = 0.05
    msg = reactor_mod.encode_msg(
        reactor_mod.TxsMessage([b"gs-%d" % i for i in range(6)]))
    t0 = time.monotonic()
    reactor.receive(0x30, FakePeer(), msg)
    dt = time.monotonic() - t0
    assert dt >= reactor.THROTTLE_S  # saturated queue parked the reader
    fail.clear()
    deadline = time.monotonic() + 10.0
    while mp.size() < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert mp.size() >= 2  # the queued ones landed


def test_rpc_surfaces_429_style_busy(gate_factory):
    """broadcast_tx_{sync,async,commit} map a gate overload rejection
    to the RPC_BUSY_CODE error with a Retry-After hint."""
    import base64

    from tendermint_tpu.rpc.server import RPC_BUSY_CODE, RPCServer

    fail.set_mode("ingress.checktx", "latency:150")
    g, mp = gate_factory(queue_size=1, batch=1)

    class FakeNode:
        pass

    node = FakeNode()
    node.mempool = mp
    node.ingress_gate = g
    node.event_bus = None
    srv = RPCServer.__new__(RPCServer)  # no HTTP listener needed
    srv.node = node
    # fill the queue, then overflow
    g.submit(b"rpc-fill-0")
    g.submit(b"rpc-fill-1")
    arg = base64.b64encode(b"rpc-overflow").decode()
    from tendermint_tpu.rpc.server import RPCError
    for call in (srv.broadcast_tx_sync, srv.broadcast_tx_async,
                 srv.broadcast_tx_commit):
        with pytest.raises(RPCError) as ei:
            call(tx=arg)
        assert ei.value.code == RPC_BUSY_CODE
        assert "retry after" in str(ei.value)
    fail.clear()


def test_node_wires_gate_and_config_disable(tmp_path):
    """Default config ⇒ the node constructs + wires the gate; [mempool]
    ingress_enable=false ⇒ no gate and the reactor keeps the direct
    path (config wins over a stale env in both directions)."""
    import argparse
    import os

    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.cmd.__main__ import cmd_init
    from tendermint_tpu.config.config import Config
    from tendermint_tpu.node.node import Node

    home = str(tmp_path / "n0")
    cmd_init(argparse.Namespace(home=home, chain_id="ingress-chain"))
    cfg = Config.load(home)
    node = Node(cfg, KVStoreApplication(), in_memory=True)
    assert node.ingress_gate is not None
    assert node.mempool_reactor.gate is node.ingress_gate
    # config OFF wins over a stale env ON
    os.environ["TM_TPU_INGRESS"] = "1"
    try:
        cfg2 = Config.load(home)
        cfg2.mempool.ingress_enable = False
        node2 = Node(cfg2, KVStoreApplication(), in_memory=True)
        assert node2.ingress_gate is None
        assert node2.mempool_reactor.gate is None
    finally:
        del os.environ["TM_TPU_INGRESS"]
    # env OFF wins when config defers (module-level switch)
    ing.set_enabled(None)
    os.environ["TM_TPU_INGRESS"] = "0"
    try:
        assert not ing.enabled()
    finally:
        del os.environ["TM_TPU_INGRESS"]
    assert ing.enabled()  # default: on


def test_sigtx_envelope_roundtrip_and_hostile_bytes():
    priv = _PRIVS[0]
    tx = make_signed_tx(priv, b"payload")
    pub, msg, sig = parse_signed_tx(tx)
    assert pub == priv.pub_key().bytes()
    assert priv.pub_key().verify_signature(msg, sig)
    assert parse_signed_tx(b"not an envelope") is None
    assert parse_signed_tx(ing.SIGTX_MAGIC) is None  # truncated
    assert parse_signed_tx(b"") is None


def test_gate_stop_settles_pending_as_busy(gate_factory):
    fail.set_mode("ingress.checktx", "latency:300")
    g, _ = gate_factory(queue_size=16, batch=1)
    futs = [g.submit(b"st-%d" % i) for i in range(8)]
    g.stop()
    fail.clear()
    for f in futs:
        r = f.result(timeout=5.0)
        assert r.code == 0 or r.codespace == "ingress"
    # at least the never-drained tail was settled busy, not stranded
    assert any(f.result(timeout=0).codespace == "ingress" for f in futs)
