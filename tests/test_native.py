"""Native C staging library (native/staging.c via libs/native.py).

Oracle: hashlib (OpenSSL) for SHA-512, Python bignum for mod L — the same
semantics as Go crypto/ed25519's challenge computation (reference
crypto/ed25519/ed25519.go:148, SHA-512(R||A||M) then ScReduce).
"""
import hashlib

import numpy as np
import pytest

from tendermint_tpu.libs import native

L = (1 << 252) + 27742317777372353535851937790883648493

pytestmark = pytest.mark.skipif(
    native.get_lib() is None, reason="no C toolchain for native staging")


def test_sha512_prefixed_matches_hashlib_across_block_boundaries():
    rng = np.random.default_rng(0)
    # lengths straddling every SHA-512 padding/block edge for 64B prefix
    for mlen in (0, 1, 47, 48, 63, 64, 111, 112, 127, 128, 200, 300):
        B = 9
        prefix = np.ascontiguousarray(
            rng.integers(0, 256, (B, 64), dtype=np.uint8))
        msgs = [rng.integers(0, 256, mlen, dtype=np.uint8).tobytes()
                for _ in range(B)]
        got = native.sha512_prefixed(prefix, msgs)
        exp = np.stack([np.frombuffer(
            hashlib.sha512(prefix[i].tobytes() + msgs[i]).digest(),
            dtype=np.uint8) for i in range(B)])
        assert (got == exp).all(), mlen


def test_sha512_prefixed_fixed_width_array_path():
    rng = np.random.default_rng(1)
    B, mlen = 33, 118
    prefix = np.ascontiguousarray(
        rng.integers(0, 256, (B, 64), dtype=np.uint8))
    msgs = rng.integers(0, 256, (B, mlen), dtype=np.uint8)
    got = native.sha512_prefixed(prefix, msgs)
    exp = np.stack([np.frombuffer(
        hashlib.sha512(prefix[i].tobytes() + msgs[i].tobytes()).digest(),
        dtype=np.uint8) for i in range(B)])
    assert (got == exp).all()


def test_sha512_plain_and_variable_lengths():
    rng = np.random.default_rng(2)
    msgs = [rng.integers(0, 256, int(l), dtype=np.uint8).tobytes()
            for l in rng.integers(0, 400, 40)]
    got = native.sha512_plain(msgs)
    exp = np.stack([np.frombuffer(hashlib.sha512(m).digest(), dtype=np.uint8)
                    for m in msgs])
    assert (got == exp).all()


def test_mod_l_edge_cases_and_random():
    rng = np.random.default_rng(3)
    vals = [0, 1, L - 1, L, L + 1, 2 * L, 4 * L + 7, (1 << 512) - 1,
            1 << 252, L << 259, (L - 1) << 259, (1 << 512) - 12345]
    d = np.zeros((len(vals) + 64, 64), dtype=np.uint8)
    for i, v in enumerate(vals):
        d[i] = np.frombuffer(v.to_bytes(64, "little"), dtype=np.uint8)
    d[len(vals):] = rng.integers(0, 256, (64, 64), dtype=np.uint8)
    got = native.mod_l(d)
    for i in range(d.shape[0]):
        exp = int.from_bytes(d[i].tobytes(), "little") % L
        assert int.from_bytes(got[i].tobytes(), "little") == exp, i


def test_challenge_scalars_fused():
    rng = np.random.default_rng(4)
    B = 17
    prefix = np.ascontiguousarray(
        rng.integers(0, 256, (B, 64), dtype=np.uint8))
    msgs = rng.integers(0, 256, (B, 30), dtype=np.uint8)
    got = native.challenge_scalars(prefix, msgs)
    for i in range(B):
        dig = hashlib.sha512(prefix[i].tobytes() + msgs[i].tobytes()).digest()
        assert int.from_bytes(got[i].tobytes(), "little") == \
            int.from_bytes(dig, "little") % L


def test_scalar_canonical():
    vals = [0, 1, L - 1, L, L + 1, 2**256 - 1, 1 << 252, 12345]
    s = np.stack([np.frombuffer(v.to_bytes(32, "little"), dtype=np.uint8)
                  for v in vals])
    got = native.scalar_canonical(s)
    assert list(got) == [v < L for v in vals]


def test_prepare_batch_packed_roundtrip():
    """Packed staging agrees with the reference staging layout."""
    from tendermint_tpu.crypto import _edref
    from tendermint_tpu.ops import ed25519 as edops

    seeds = [i.to_bytes(32, "little") for i in range(1, 9)]
    msgs = [b"packed staging %d" % i for i in range(8)]
    pubs = [_edref.pubkey_from_seed(s) for s in seeds]
    sigs = [_edref.sign(s, m) for s, m in zip(seeds, msgs)]
    packed, ok = edops.prepare_batch_packed(pubs, sigs, msgs)
    assert ok.all() and packed.shape == (128, 8)
    pu = packed.view(np.uint8)
    for i in range(8):
        assert pu[0:32, i].tobytes() == pubs[i]
        assert pu[32:64, i].tobytes() == sigs[i][:32]
        assert pu[64:96, i].tobytes() == sigs[i][32:]
        dig = hashlib.sha512(sigs[i][:32] + pubs[i] + msgs[i]).digest()
        assert int.from_bytes(pu[96:128, i].tobytes(), "little") == \
            int.from_bytes(dig, "little") % L
