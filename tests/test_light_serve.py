"""LightServe serving plane (light/service.py, ADR-026).

Tier-1 covers the plane's mechanics with countable stub certificates
(no XLA compile): cross-client coalescing runs ONE shared verification
per certificate identity (within a batch and across workers), refusal
paths settle immediately with Retry-After (queue overflow, per-client
rate limit, verify timeout, stopping service), chaos at light.serve /
light.coalesce degrades to direct per-request verification with
verdicts identical to the solo path, follow cursors advance over a
real committed chain and evict least-recently-polled under pressure,
and the comb prewarm pins path=comb / first_launch=False for the
first post-change request.  The slow tier runs the acceptance wave
with REAL kernels: N concurrent clients over one large validator set
cost exactly one coalesced comb device launch and zero new XLA
shapes, per-client verdicts identical to solo verification.
"""
from __future__ import annotations

import threading
import time

import pytest

from helpers import build_chain, make_genesis
from tendermint_tpu.libs import fail, trace
from tendermint_tpu.libs.kvdb import MemDB
from tendermint_tpu.light import service as lightsvc
from tendermint_tpu.light import verifier
from tendermint_tpu.light.service import (LightRequest, LightServe,
                                          LightVerdict)
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.store.block_store import BlockStore
from tendermint_tpu.types.basic import Timestamp
from tendermint_tpu.types.light_block import SignedHeader

PERIOD = 3600.0 * 24 * 14
NOW = Timestamp(1700005000, 0)
CHAIN = "light-serve-chain"


@pytest.fixture(autouse=True)
def _clean_world():
    fail.reset()
    yield
    fail.reset()


# ---------------------------------------------------------------------------
# countable stub certificates: the "trusting" kind only needs
# trusted_vals + untrusted.commit, so the coalescing identity and the
# shared-execution mechanics are testable without signatures
# ---------------------------------------------------------------------------


class _StubBlockID:
    def __init__(self, h):
        self.hash = b"blk-%027d" % h


class _StubCommit:
    def __init__(self, h):
        self.height = h
        self.round = 0
        self.block_id = _StubBlockID(h)


class _StubHeader:
    def __init__(self, h):
        self.commit = _StubCommit(h)


class _StubVals:
    """A countable certificate verifier.  `calls` records every actual
    verification execution — the coalescing assertions count THESE, not
    settle events."""

    def __init__(self, tag: bytes, fail_with=None, block_on=None,
                 started=None):
        self.tag = tag
        self.calls = []
        self.fail_with = fail_with
        self.block_on = block_on
        self.started = started

    def hash(self):
        return b"vals-" + self.tag

    def verify_commit_light_trusting(self, chain_id, commit, trust_level):
        if self.started is not None:
            self.started.set()
        if self.block_on is not None:
            assert self.block_on.wait(30.0), "plug never released"
        self.calls.append((chain_id, commit.height))
        if self.fail_with is not None:
            raise self.fail_with


def _req(vals, h=5):
    return LightRequest("trusting", CHAIN, trusted_vals=vals,
                        untrusted=_StubHeader(h))


def _svc(**kw):
    kw.setdefault("prewarm", False)
    return LightServe(BlockStore(MemDB()), StateStore(MemDB()), CHAIN,
                      **kw)


def _plug(svc):
    """Occupy the (single) worker with a blocking certificate so later
    submissions accumulate in the queue deterministically.  Returns
    (release_event, plug_future)."""
    release, started = threading.Event(), threading.Event()
    vals = _StubVals(b"plug", block_on=release, started=started)
    fut = svc.submit(_req(vals, h=999), client="plug")
    assert started.wait(10.0), "worker never picked up the plug"
    return release, fut


# ---------------------------------------------------------------------------
# coalescing: one shared execution per certificate identity
# ---------------------------------------------------------------------------


def test_same_certificate_coalesces_to_one_execution():
    svc = _svc(workers=1, batch=256)
    svc.start()
    try:
        release, plug_fut = _plug(svc)
        vals = _StubVals(b"shared")
        futs = [svc.submit(_req(vals), client=f"client-{i}")
                for i in range(24)]
        assert svc.depth() == 24
        release.set()
        assert plug_fut.result(timeout=30.0).ok
        verdicts = [f.result(timeout=30.0) for f in futs]
        assert all(v.ok for v in verdicts)
        # 24 requests over the same (chain, valset, height) certificate
        # ran ONE verification
        assert len(vals.calls) == 1
        st = svc.stats()
        # plug leads its own group; the wave is one lead + 23 hits
        assert st["coalesce_lead"] == 2
        assert st["coalesce_hit"] == 23
        assert st["verified"] == 25
        # latency samples per client for the debug surface
        assert len(svc._per_client_p99_ms()) == 25
    finally:
        svc.stop()


def test_distinct_certificates_and_shared_failure_verdicts():
    """Distinct identities each run once; a failing certificate refutes
    EVERY coalesced waiter with the verifier's message — identical to
    what the solo direct path answers."""
    svc = _svc(workers=1, batch=256)
    svc.start()
    try:
        release, plug_fut = _plug(svc)
        good = _StubVals(b"good")
        bad = _StubVals(
            b"bad", fail_with=verifier.LightError("insufficient power"))
        good_futs = [svc.submit(_req(good), client=f"g{i}")
                     for i in range(4)]
        bad_futs = [svc.submit(_req(bad, h=7), client=f"b{i}")
                    for i in range(4)]
        release.set()
        assert plug_fut.result(timeout=30.0).ok
        for f in good_futs:
            assert f.result(timeout=30.0).ok
        for f in bad_futs:
            v = f.result(timeout=30.0)
            assert not v.ok and v.error == "insufficient power"
            assert v.retry_after_s is None  # refuted, not retryable
        assert len(good.calls) == 1 and len(bad.calls) == 1
        # the solo path answers the same verdicts
        solo_ok = svc._verify_direct(_req(_StubVals(b"good2")))
        solo_bad = svc._verify_direct(_req(_StubVals(
            b"bad2", fail_with=verifier.LightError("insufficient power"))))
        assert solo_ok.ok
        assert not solo_bad.ok and solo_bad.error == "insufficient power"
        assert svc.stats()["refuted"] == 4
    finally:
        svc.stop()


def test_invalid_request_refused_at_header_stage():
    svc = _svc()
    svc.start()
    try:
        v = svc.verify(LightRequest("trusting", CHAIN,
                                    untrusted=_StubHeader(5)),
                       client="broken", timeout=10.0)
        assert not v.ok and "trusting request needs" in v.error
        st = svc.stats()
        assert st["invalid"] == 1 and st["coalesce_lead"] == 0
        # adjacent/non-adjacent height discipline is checked host-side
        sh3, sh4, sh9 = _StubHeader(3), _StubHeader(4), _StubHeader(9)
        for t, u, kind, msg in (
                (sh3, sh9, "adjacent", "must be adjacent"),
                (sh3, sh4, "non_adjacent", "must be non adjacent")):
            v = svc.verify(
                LightRequest(kind, CHAIN, trusted=_Hdr(t), untrusted=_Hdr(u),
                             untrusted_vals=_StubVals(b"x"), now=NOW),
                timeout=10.0)
            assert not v.ok and msg in v.error
    finally:
        svc.stop()


class _Hdr:
    """Adds the .height the adjacent checks read to a stub header."""

    def __init__(self, sh):
        self.commit = sh.commit
        self.height = sh.commit.height


def test_unknown_kind_rejected():
    with pytest.raises(ValueError, match="unknown light request kind"):
        LightRequest("sideways", CHAIN)


def test_cross_worker_inflight_dedupe():
    """The cross-batch seam directly: a second worker hitting an
    in-flight key becomes a follower — no second execution, shared
    verdict (including the error case)."""
    svc = _svc()
    release, started = threading.Event(), threading.Event()
    vals = _StubVals(b"inflight", block_on=release, started=started)
    key, run = svc._cert_tasks(_req(vals))[0]
    out = {}

    def lead():
        out["lead"] = svc._cert_verify(key, run, 1)

    def follow():
        assert started.wait(10.0)
        out["follow"] = svc._cert_verify(key, run, 1)

    t1 = threading.Thread(target=lead)
    t2 = threading.Thread(target=follow)
    t1.start()
    t2.start()
    time.sleep(0.05)
    release.set()
    t1.join(10.0)
    t2.join(10.0)
    assert out["lead"] is None and out["follow"] is None
    assert len(vals.calls) == 1
    st = svc.stats()
    assert st["coalesce_lead"] == 1 and st["coalesce_hit"] == 1
    # the in-flight map is drained — nothing leaks across requests
    assert not svc._inflight


# ---------------------------------------------------------------------------
# chaos: light.serve / light.coalesce degrade to direct verification
# ---------------------------------------------------------------------------


def test_chaos_light_serve_degrades_to_in_caller_direct():
    svc = _svc()
    svc.start()
    try:
        fail.set_mode("light.serve", "raise")
        good, bad = _StubVals(b"cg"), _StubVals(
            b"cb", fail_with=verifier.LightError("no quorum"))
        f1 = svc.submit(_req(good), client="c")
        f2 = svc.submit(_req(bad), client="c")
        # settled synchronously in the caller — no queue, no worker
        assert f1.done() and f2.done()
        assert f1.result(0).ok
        v2 = f2.result(0)
        assert not v2.ok and v2.error == "no quorum"
        assert fail.fired("light.serve", "raise") >= 2
        st = svc.stats()
        assert st["direct_path"] == 2 and st["coalesce_lead"] == 0
        assert svc.depth() == 0
    finally:
        svc.stop()


def test_chaos_light_coalesce_degrades_to_per_request_direct():
    svc = _svc(workers=1, batch=256)
    svc.start()
    try:
        fail.set_mode("light.coalesce", "raise")
        release, started = threading.Event(), threading.Event()
        plug_vals = _StubVals(b"plug2", block_on=release, started=started)
        plug_fut = svc.submit(_req(plug_vals, h=999), client="plug")
        assert started.wait(10.0)
        vals = _StubVals(b"chaos")
        futs = [svc.submit(_req(vals), client=f"c{i}") for i in range(6)]
        release.set()
        assert plug_fut.result(timeout=30.0).ok
        assert all(f.result(timeout=30.0).ok for f in futs)
        # degraded: per-request certificate runs, no dedupe — but the
        # verdicts are identical to the coalesced plane's
        assert len(vals.calls) == 6
        assert fail.fired("light.coalesce", "raise") >= 2
        st = svc.stats()
        assert st["coalesce_direct"] == 7  # plug + the 6-wave
        assert st["coalesce_lead"] == 0 and st["coalesce_hit"] == 0
        assert st["verified"] == 7
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# the front door: immediate refusals with Retry-After
# ---------------------------------------------------------------------------


def test_ratelimit_refusal_immediate_with_retry_after():
    svc = _svc(rate_per_s=2.0, burst=1)
    svc.start()
    try:
        vals = _StubVals(b"rl")
        assert svc.verify(_req(vals), client="flooder", timeout=10.0).ok
        f = svc.submit(_req(vals), client="flooder")
        assert f.done()  # settled at submit, nothing queued
        v = f.result(0)
        assert not v.ok and "rate limited" in v.error
        assert v.retry_after_s == pytest.approx(0.5)  # 1/rate
        # another client has its own bucket
        assert svc.verify(_req(vals), client="other", timeout=10.0).ok
        st = svc.stats()
        assert st["ratelimited"] == 1 and st["verified"] == 2
    finally:
        svc.stop()


def test_set_rate_reclamps_live_buckets():
    svc = _svc(rate_per_s=100.0, burst=50)
    svc.start()
    try:
        vals = _StubVals(b"clamp")
        assert svc.verify(_req(vals), client="c", timeout=10.0).ok
        svc.set_rate(rate_per_s=0.001, burst=1)
        # the clamp-down never grants saved-up tokens: the bucket was
        # re-clamped to burst=1 and the refill rate is ~zero
        assert svc.verify(_req(vals), client="c", timeout=10.0).ok
        v = svc.submit(_req(vals), client="c").result(0)
        assert not v.ok and v.retry_after_s is not None
    finally:
        svc.stop()


def test_queue_overflow_busy_verdict():
    svc = _svc(queue_size=4, batch=1, workers=1)
    svc.start()
    try:
        release, plug_fut = _plug(svc)
        vals = _StubVals(b"flood")
        queued = [svc.submit(_req(vals), client=f"q{i}") for i in range(4)]
        assert svc.depth() == 4
        spill = svc.submit(_req(vals), client="spill")
        assert spill.done()
        v = spill.result(0)
        assert not v.ok and v.error == "light serve is busy"
        assert 0.1 <= v.retry_after_s <= 5.0
        release.set()
        assert plug_fut.result(timeout=30.0).ok
        assert all(f.result(timeout=30.0).ok for f in queued)
        st = svc.stats()
        assert st["busy"] == 1 and st["verified"] == 5
    finally:
        svc.stop()


def test_verify_timeout_maps_to_busy():
    svc = _svc(workers=1)
    svc.start()
    try:
        release, plug_fut = _plug(svc)
        v = svc.verify(_req(_StubVals(b"slowpoke")), client="w",
                       timeout=0.05)
        assert not v.ok and v.retry_after_s is not None
        assert "timed out" in v.error
        release.set()
        assert plug_fut.result(timeout=30.0).ok
    finally:
        svc.stop()


def test_stop_settles_stranded_and_post_stop_goes_direct():
    svc = _svc(queue_size=16, batch=1, workers=1)
    svc.start()
    release, plug_fut = _plug(svc)
    vals = _StubVals(b"stranded")
    stranded = [svc.submit(_req(vals), client=f"s{i}") for i in range(3)]
    threading.Timer(0.2, release.set).start()
    svc.stop()
    for f in stranded:
        v = f.result(timeout=10.0)
        assert not v.ok and v.error == "light serve stopping"
        assert v.retry_after_s is not None
    assert plug_fut.result(timeout=10.0).ok
    # a stopped service serves in-caller — correct answers, no queue
    post = _StubVals(b"post")
    f = svc.submit(_req(post), client="late")
    assert f.done() and f.result(0).ok and len(post.calls) == 1


# ---------------------------------------------------------------------------
# follow cursors over a real committed chain
# ---------------------------------------------------------------------------


def _chain_stores(n_heights=6, n_vals=4):
    from tendermint_tpu.blocksync.replay import block_id_of
    from tendermint_tpu.state.state import state_from_genesis

    gdoc, privs = make_genesis(n_vals)
    blocks, commits, states = build_chain(gdoc, privs, n_heights)
    block_store, state_store = BlockStore(MemDB()), StateStore(MemDB())
    for b, c in zip(blocks, commits):
        _bid, parts = block_id_of(b)
        block_store.save_block(b, parts, c)
    state_store.save(state_from_genesis(gdoc))  # height-1 validators
    for st in states:
        state_store.save(st)
    return gdoc, blocks, block_store, state_store


def test_follow_cursor_subscribe_poll_advance():
    gdoc, blocks, block_store, state_store = _chain_stores(6)
    svc = LightServe(block_store, state_store, gdoc.chain_id,
                     cursor_batch=4, prewarm=False)
    cid = svc.subscribe("alice")
    out = svc.poll(cid)
    assert [lb.height for lb in out] == [1, 2, 3, 4]
    # each served light block carries the committed header + the
    # certifying commit + that height's validator set
    for lb in out:
        assert lb.signed_header.header.hash() == \
            blocks[lb.height - 1].header.hash()
        assert lb.signed_header.commit.height == lb.height
        assert not lb.validators.is_nil_or_empty()
    out = svc.poll(cid)
    assert [lb.height for lb in out] == [5, 6]  # top uses seen commit
    assert svc.poll(cid) == []  # caught up
    # explicit from_height and a bounded max_items
    cid2 = svc.subscribe("bob", from_height=4)
    assert [lb.height for lb in svc.poll(cid2, max_items=2)] == [4, 5]
    svc.unsubscribe(cid2)
    assert svc.poll(cid2) is None
    assert svc.stats()["polled"] == 8


def test_follow_cursor_eviction_per_client_and_global():
    gdoc, _blocks, block_store, state_store = _chain_stores(3)
    svc = LightServe(block_store, state_store, gdoc.chain_id,
                     max_cursors_per_client=2, max_cursors=3,
                     prewarm=False)
    a1 = svc.subscribe("alice")
    a2 = svc.subscribe("alice")
    svc.poll(a1)  # a1 freshly polled: a2 is now alice's stalest
    a3 = svc.subscribe("alice")  # per-client bound: evicts a2
    assert svc.poll(a2) is None and svc.poll(a1) is not None
    b1 = svc.subscribe("bob")
    c1 = svc.subscribe("carol")  # global bound (3): evicts stalest
    assert svc.poll(c1) is not None and svc.poll(b1) is not None
    rep = svc.report()
    assert rep["cursors"]["total"] <= 3
    assert svc.stats()["cursors_evicted"] >= 2
    assert a3 is not None


def test_report_shape_and_coalesce_ratio():
    svc = _svc()
    svc.start()
    try:
        vals = _StubVals(b"rep")
        assert svc.verify(_req(vals), client="r", timeout=10.0).ok
        rep = svc.report()
        assert rep["running"] and rep["chain_id"] == CHAIN
        assert rep["stats"]["verified"] == 1
        assert 0.0 <= rep["coalesce_ratio"] <= 1.0
        assert rep["config"]["queue"] == svc.queue_size
        assert "per_client_p99_ms" in rep and "r" in rep["per_client_p99_ms"]
        # module surface (GET /debug/light reads this)
        lightsvc.install(svc)
        try:
            assert lightsvc.report()["running"]
        finally:
            lightsvc.install(None)
        assert lightsvc.report() == {"enabled": lightsvc.enabled(),
                                     "running": False}
    finally:
        svc.stop()


def test_enable_config_wins_over_env(monkeypatch):
    monkeypatch.setenv("TM_TPU_LIGHT_SERVE", "0")
    lightsvc.set_enabled(None)
    try:
        assert not lightsvc.enabled()
        lightsvc.set_enabled(True)   # config wins over the stale env
        assert lightsvc.enabled()
        monkeypatch.setenv("TM_TPU_LIGHT_SERVE", "1")
        lightsvc.set_enabled(False)  # ...in both directions
        assert not lightsvc.enabled()
    finally:
        lightsvc.set_enabled(None)


def test_config_light_serve_roundtrip(tmp_path):
    from tendermint_tpu.config.config import Config

    cfg = Config(home=str(tmp_path))
    assert cfg.light_serve.enable is True
    cfg.light_serve.enable = False
    cfg.light_serve.queue = 128
    cfg.light_serve.rate_per_s = 40.0
    cfg.light_serve.burst = 8
    cfg.save()
    back = Config.load(str(tmp_path))
    assert back.light_serve.enable is False
    assert back.light_serve.queue == 128
    assert back.light_serve.rate_per_s == pytest.approx(40.0)
    assert back.light_serve.burst == 8
    back.validate_basic()
    back.light_serve.queue = 0
    with pytest.raises(ValueError, match="light_serve.queue"):
        back.validate_basic()


# ---------------------------------------------------------------------------
# prewarm (satellite: ops/ed25519.prewarm/prewarm_async)
# ---------------------------------------------------------------------------


def test_prewarm_pins_comb_path_first_launch_false(monkeypatch):
    """After a valset-change prewarm, the FIRST real request finds the
    tables resident and the kernel shape seen: path=comb,
    first_launch=False, no table build on the request path."""
    from test_comb import _batch, _stub_kernels
    from tendermint_tpu.crypto import degrade
    from tendermint_tpu.libs.metrics import Registry
    from tendermint_tpu.ops import ed25519 as edops

    degrade.configure(registry=Registry("light_prewarm"))
    edops.table_cache_clear()
    rec = {}
    _stub_kernels(monkeypatch, record=rec)
    monkeypatch.setattr(edops, "_comb_min_override", 1)
    try:
        pubs, msgs, sigs = _batch(12, pool=6, tag=b"warmset")
        assert edops.prewarm(pubs)
        assert rec["builds"] == [8]  # tables built off the request path
        # the first "request": same set, scheduler-lane shape (no
        # cache_pubs) — comb hit, bucket already seen, zero builds
        assert edops.verify_batch(pubs, msgs, sigs).all()
        ll = edops.last_launch()
        assert ll["path"] == "comb"
        assert ll["first_launch"] is False
        assert not ll["table_build"] and rec["builds"] == [8]
        # prewarm is idempotent — resident tables short-circuit
        assert edops.prewarm(pubs, warm_kernel=False)
        assert rec["builds"] == [8]
    finally:
        edops.table_cache_clear()
        degrade.reset()


def test_prewarm_async_lands_off_thread(monkeypatch):
    from test_comb import _batch, _stub_kernels
    from tendermint_tpu.crypto import degrade
    from tendermint_tpu.libs.metrics import Registry
    from tendermint_tpu.ops import ed25519 as edops

    degrade.configure(registry=Registry("light_prewarm_async"))
    edops.table_cache_clear()
    rec = {}
    _stub_kernels(monkeypatch, record=rec)
    monkeypatch.setattr(edops, "_comb_min_override", 1)
    try:
        pubs, _msgs, _sigs = _batch(12, pool=6, tag=b"asyncset")
        done = threading.Event()
        orig = edops.prewarm

        def _tracked(keys, warm_kernel=True):
            try:
                return orig(keys, warm_kernel=warm_kernel)
            finally:
                done.set()

        monkeypatch.setattr(edops, "prewarm", _tracked)
        edops.prewarm_async(pubs)
        # wait for the WHOLE prewarm (tables + kernel warm) so the
        # worker never outlives the stubbed kernels
        assert done.wait(10.0)
        assert rec.get("builds") == [8]
    finally:
        edops.table_cache_clear()
        degrade.reset()


def test_service_prewarms_current_set_on_start():
    """on_start warms the CURRENT set (nobody waits for a valset change)
    and the valset watcher prewarms again on the update event."""
    from tendermint_tpu.types.event_bus import EventBus

    gdoc, _blocks, block_store, state_store = _chain_stores(3)
    bus = EventBus()
    calls = []
    svc = LightServe(block_store, state_store, gdoc.chain_id,
                     prewarm=True, event_bus=bus)

    import tendermint_tpu.ops.ed25519 as edops
    orig = edops.prewarm_async
    edops.prewarm_async = lambda keys: calls.append(len(list(keys)))
    try:
        svc.start()
        assert calls and calls[0] == 4  # the current 4-validator set
        bus.publish_validator_set_updates([])
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and len(calls) < 2:
            time.sleep(0.01)
        assert len(calls) >= 2
        assert svc.stats()["prewarms"] >= 2
    finally:
        edops.prewarm_async = orig
        svc.stop()


# ---------------------------------------------------------------------------
# locksan: the serving plane's four locks under concurrent clients
# ---------------------------------------------------------------------------


@pytest.mark.locksan
def test_locksan_concurrent_serve_hammer():
    """A fresh LightServe built UNDER the lockset monitor (its _cond /
    _rl_lock / _cur_lock / _stats_lock are wrapped and ranked), hammered
    by concurrent submitters, followers and report readers — the
    declared ordering holds (the conftest fixture fails the test on any
    inversion) and every settled verdict is correct."""
    gdoc, _blocks, block_store, state_store = _chain_stores(3)
    svc = LightServe(block_store, state_store, gdoc.chain_id,
                     workers=2, rate_per_s=10_000.0, burst=10_000,
                     prewarm=False)
    svc.start()
    stop = threading.Event()
    bad = []

    def submitter(k):
        vals = _StubVals(b"hammer-%d" % (k % 2))
        for _ in range(200):
            v = svc.verify(_req(vals), client=f"h{k}", timeout=30.0)
            if not (v.ok or v.retry_after_s is not None):
                bad.append(v.error)

    def follower(k):
        while not stop.is_set():
            cid = svc.subscribe(f"f{k}")
            svc.poll(cid)
            svc.unsubscribe(cid)

    threads = [threading.Thread(target=submitter, args=(k,))
               for k in range(4)] + \
        [threading.Thread(target=follower, args=(k,)) for k in range(2)]
    for t in threads:
        t.start()
    try:
        for _ in range(30):
            svc.report()
            time.sleep(0.01)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=15.0)
        svc.stop()
    assert not bad, bad
    st = svc.stats()
    assert st["verified"] == 800 and st["refuted"] == 0
    # every execution was a lead or a coalesced hit — none lost
    assert st["coalesce_lead"] + st["coalesce_hit"] == 800


# ---------------------------------------------------------------------------
# slow: the acceptance wave with REAL kernels — one coalesced comb
# launch for N clients, zero new XLA shapes, solo-identical verdicts
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_acceptance_wave_one_coalesced_comb_launch(monkeypatch):
    monkeypatch.setenv("TM_TPU_FORCE_BATCH", "1")
    monkeypatch.setenv("TM_TPU_NO_MESH", "1")
    from tendermint_tpu.parallel import sharding
    monkeypatch.setattr(sharding, "_PLANE", None)
    from tendermint_tpu.crypto import degrade
    from tendermint_tpu.devtools.tmlint.runtime import CompileSentinel
    from tendermint_tpu.libs.metrics import Registry
    from tendermint_tpu.ops import ed25519 as edops

    degrade.configure(registry=Registry("light_accept"))
    edops.table_cache_clear()
    monkeypatch.setattr(edops, "_comb_min_override", 1)

    # 48 validators: the minimal >2/3 commit prefix is 33 signatures,
    # over the device-lane threshold (32) — the certificate is a real
    # comb launch, not a host-lane verify
    gdoc, privs = make_genesis(48)
    blocks, commits, states = build_chain(gdoc, privs, 2)
    trusted = SignedHeader(blocks[0].header, commits[0])
    untrusted = SignedHeader(blocks[1].header, commits[1])
    vals = states[1].validators

    def req():
        return LightRequest("adjacent", gdoc.chain_id, trusted=trusted,
                            untrusted=untrusted, untrusted_vals=vals,
                            now=NOW, trusting_period_s=PERIOD)

    svc = LightServe(BlockStore(MemDB()), StateStore(MemDB()),
                     gdoc.chain_id, workers=1, batch=256, prewarm=False)
    svc.start()
    try:
        # solo baseline + warm: tables and the nb=64 comb shape land
        # BEFORE the measured wave
        assert edops.prewarm([v.pub_key.bytes() for v in vals.validators])
        solo = svc._verify_direct(req())
        assert solo.ok, solo.error

        trace.enable(capacity=1 << 14)
        since = trace.last_seq()
        sentinel = CompileSentinel(max_new_compiles=0).start()

        release, plug_fut = _plug(svc)
        futs = [svc.submit(req(), client=f"client-{i}") for i in range(12)]
        release.set()
        assert plug_fut.result(timeout=60.0).ok
        verdicts = [f.result(timeout=120.0) for f in futs]
        # per-client verdicts identical to the solo baseline
        assert all(v.ok == solo.ok and v.error == solo.error
                   for v in verdicts)

        sentinel.check()  # zero new kernel compiles, no new bucket
        spans = trace.snapshot(since)
        coal = [r for r in spans if r["name"] == "light.coalesce"
                and r["attrs"].get("cls") == "light"]
        assert len(coal) == 1, coal  # ONE shared certificate execution
        assert coal[0]["attrs"]["waiters"] == 12
        launches = [r for r in spans if r["name"] == "device.launch"]
        assert len(launches) == 1, launches  # ONE comb launch, period
        ll = edops.last_launch()
        assert ll["path"] == "comb" and ll["first_launch"] is False
        st = svc.stats()
        assert st["coalesce_hit"] >= 11
    finally:
        svc.stop()
        edops.table_cache_clear()
        degrade.reset()
