"""4-validator localnet over real TCP sockets with perturbations
(reference consensus/reactor_test.go + test/e2e/runner/perturb.go:28
intent): the full Switch/SecretConnection/MConnection stack plus all four
reactors must commit blocks, survive a peer disconnect, and survive a
node kill/restart (WAL + store recovery, then catch-up)."""
from __future__ import annotations

import os
import tempfile
import time

import pytest

from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.cmd.__main__ import main as cli_main
from tendermint_tpu.config.config import Config
from tendermint_tpu.consensus.config import test_config as fast_config
from tendermint_tpu.node import Node

N = 4
BASE_P2P = 39356


def _load_node(home: str) -> Node:
    cfg = Config.load(home)
    cfg.home = home
    cfg.consensus = fast_config()
    cfg.rpc.enabled = False  # RPC surface is covered by test_node_e2e
    return Node(cfg, KVStoreApplication())


def _wait_height(nodes, h, timeout=90.0, who=None):
    deadline = time.time() + timeout
    watch = nodes if who is None else [nodes[i] for i in who]
    while time.time() < deadline:
        heights = [n.block_store.height() for n in watch]
        if min(heights) >= h:
            return heights
        time.sleep(0.25)
    raise AssertionError(
        f"localnet stalled below {h}: "
        f"{[n.block_store.height() for n in watch]}")


@pytest.mark.slow
def test_four_validator_socket_localnet_with_perturbations():
    tmp = tempfile.mkdtemp(prefix="tm_localnet_")
    cli_main(["testnet", "--v", str(N), "--o", tmp,
              "--chain-id", "localnet-chain",
              "--starting-p2p-port", str(BASE_P2P),
              "--starting-rpc-port", str(BASE_P2P + 100)])
    homes = [os.path.join(tmp, f"node{i}") for i in range(N)]

    nodes = [_load_node(h) for h in homes]
    try:
        for n in nodes:
            n.start()

        # ---- phase 1: all four commit over real sockets ----------------
        _wait_height(nodes, 5)
        for n in nodes:
            assert n.switch.num_peers() >= 2, "mesh did not form"

        # ---- phase 2: disconnect perturbation ---------------------------
        # (perturb.go "disconnect"): drop one peer link; persistent-peer
        # reconnect must restore it and the chain must keep advancing.
        victim = nodes[1]
        peer = next(iter(victim.switch.peers.values()))
        victim.switch.stop_peer_for_error(peer, "test disconnect")
        h = max(n.block_store.height() for n in nodes)
        _wait_height(nodes, h + 3)
        deadline = time.time() + 30
        while time.time() < deadline and victim.switch.num_peers() < N - 1:
            time.sleep(0.25)
        assert victim.switch.num_peers() == N - 1, "peer did not reconnect"

        # ---- phase 3: kill/restart perturbation --------------------------
        # (perturb.go "kill"/"restart"): stop node3; the remaining 3/4
        # (75% > 2/3) keep committing; a fresh Node over the same home dir
        # recovers stores + WAL + privval state and catches back up.
        nodes[3].stop()
        h = max(n.block_store.height() for n in nodes)
        _wait_height(nodes, h + 3, who=[0, 1, 2])

        time.sleep(0.5)  # let the old listener fully close
        nodes[3] = _load_node(homes[3])
        nodes[3].start()
        target = max(n.block_store.height() for n in nodes[:3]) + 3
        _wait_height(nodes, target, timeout=120.0)

        # the restarted node is a validator again: its signature must show
        # up in a fresh commit (catch-up worked end to end, not just sync)
        addr3 = nodes[3].priv_validator.get_pub_key().address()
        deadline = time.time() + 60
        signed = False
        while time.time() < deadline and not signed:
            hh = nodes[0].block_store.height()
            commit = nodes[0].block_store.load_seen_commit(hh)
            if commit is None and hh > 1:
                commit = nodes[0].block_store.load_block_commit(hh - 1)
            if commit is not None:
                vals = nodes[0].state.validators
                for sig in commit.signatures:
                    if sig.validator_address == addr3 and sig.signature:
                        signed = True
                        break
            time.sleep(0.25)
        assert signed, "restarted validator never re-signed a commit"
    finally:
        for n in nodes:
            try:
                n.stop()
            except Exception:  # noqa: BLE001
                pass
