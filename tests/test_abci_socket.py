"""ABCI socket server/client + proxy: an external kvstore process serves a
node over unix sockets through the 4-connection multiplexer
(reference abci/client/socket_client.go, proxy/app_conn.go)."""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import time

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.client import SocketClient
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.abci.server import ABCIServer
from tendermint_tpu.proxy import AppConns, ClientCreator

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_socket_roundtrip_in_thread():
    """Server in-thread: every method crosses the wire and comes back
    typed."""
    sock_path = os.path.join(tempfile.mkdtemp(), "abci.sock")
    srv = ABCIServer(KVStoreApplication(), f"unix://{sock_path}")
    srv.start()
    try:
        cli = SocketClient(f"unix://{sock_path}")
        assert cli.echo("hello") == "hello"
        cli.flush()
        info = cli.info(abci.RequestInfo())
        assert info.last_block_height == 0
        r = cli.check_tx(abci.RequestCheckTx(tx=b"a=1"))
        assert r.is_ok()
        cli.begin_block(abci.RequestBeginBlock(hash=b"\x01" * 32))
        dr = cli.deliver_tx(b"a=1")
        assert dr.code == abci.CODE_TYPE_OK
        cli.end_block(1)
        c = cli.commit()
        assert c.data  # app hash
        q = cli.query(abci.RequestQuery(data=b"a"))
        assert q.value == b"1"
        cli.close()
    finally:
        srv.stop()


def test_proxy_four_connections():
    sock_path = os.path.join(tempfile.mkdtemp(), "abci.sock")
    srv = ABCIServer(KVStoreApplication(), f"unix://{sock_path}")
    srv.start()
    try:
        conns = AppConns(ClientCreator.remote(f"unix://{sock_path}"))
        assert conns.consensus is not conns.mempool
        assert conns.query.info(abci.RequestInfo()).last_block_height == 0
        r = conns.mempool.check_tx(abci.RequestCheckTx(tx=b"x=y"))
        assert r.is_ok()
        conns.stop()
    finally:
        srv.stop()


# demoted from @pytest.mark.slow: 4.98 s on CPU (< 5 s bar, pytest.ini)
def test_external_kvstore_process_backs_a_chain():
    """The VERDICT done-criterion: kvstore as a separate OS process passes
    the consensus e2e (single-validator node commits blocks through the
    socket)."""
    tmp = tempfile.mkdtemp(prefix="tm_abci_")
    sock = f"unix://{os.path.join(tmp, 'app.sock')}"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    app_proc = subprocess.Popen(
        [sys.executable, "-m", "tendermint_tpu.cmd", "abci-kvstore",
         "--address", sock],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    try:
        time.sleep(1.0)
        assert app_proc.poll() is None, app_proc.stderr.read().decode()

        # single-validator node with the remote app
        from tendermint_tpu.config.config import Config
        from tendermint_tpu.crypto import ed25519 as edkeys
        from tendermint_tpu.node import Node
        from tendermint_tpu.privval.file_pv import FilePV
        from tendermint_tpu.types.basic import Timestamp
        from tendermint_tpu.types.genesis import (GenesisDoc,
                                                  GenesisValidator)

        cfg = Config(home=os.path.join(tmp, "node"))
        cfg.ensure_dirs()
        cfg.p2p.laddr = "127.0.0.1:0"
        cfg.rpc.enabled = False
        pv = FilePV.load_or_generate(cfg.priv_validator_key_file(),
                                     cfg.priv_validator_state_file())
        pub = pv.get_pub_key()
        gdoc = GenesisDoc(
            chain_id="abci-socket-chain",
            genesis_time=Timestamp(1700000000, 0),
            validators=[GenesisValidator(
                address=pub.address(), pub_key_type=pub.type_name,
                pub_key_bytes=pub.bytes(), power=10)])
        with open(cfg.genesis_file(), "w") as f:
            f.write(gdoc.to_json())
        node = Node(cfg, AppConns(ClientCreator.remote(sock)),
                    in_memory=True)
        node.start()
        try:
            deadline = time.time() + 60
            while time.time() < deadline and \
                    node.block_store.height() < 3:
                time.sleep(0.2)
            assert node.block_store.height() >= 3
            # the app state lives in the EXTERNAL process
            q = node.app.query(abci.RequestQuery(data=b"nope"))
            assert q.code == abci.CODE_TYPE_OK
        finally:
            node.stop()
    finally:
        app_proc.send_signal(signal.SIGTERM)
        try:
            app_proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            app_proc.kill()
