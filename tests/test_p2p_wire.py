"""Canonical proto wire codecs for the p2p reactor channels (reference
proto/tendermint/{consensus,blocksync,mempool,statesync,p2p}/types.proto).

Three layers of checks:
  * golden byte layouts — hand-assembled reference encodings (field
    numbers / wire types straight from the .proto schemas) must decode,
    and our encodings must reproduce them byte for byte;
  * roundtrips over every message type;
  * decoder fuzz — arbitrary garbage must raise ProtoError, never
    unpickle or crash.
"""
from __future__ import annotations

import pickle
import random

import pytest

from tendermint_tpu.blocksync import reactor as bsr
from tendermint_tpu.consensus import messages as cm
from tendermint_tpu.evidence import reactor as evr
from tendermint_tpu.libs import protodec as pd
from tendermint_tpu.libs import protoenc as pe
from tendermint_tpu.libs.bits import BitArray
from tendermint_tpu.mempool import reactor as mpr
from tendermint_tpu.p2p import pex, wire
from tendermint_tpu.statesync import reactor as ssr
from tendermint_tpu.types.basic import (BlockID, PartSetHeader,
                                        SignedMsgType, Timestamp)
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.vote import Vote

BID = BlockID(b"\x11" * 32, PartSetHeader(3, b"\x22" * 32))


# -- golden layouts ---------------------------------------------------------

def test_blocksync_golden_bytes():
    # Message{block_request=1{height=1:varint}}: tag(1,BYTES)=0x0a,
    # body = tag(1,VARINT)=0x08 + 7
    assert bsr.encode_msg(bsr.BlockRequest(7)) == b"\x0a\x02\x08\x07"
    assert bsr.decode_msg(b"\x0a\x02\x08\x07") == bsr.BlockRequest(7)
    # Message{status_response=5{height=1, base=2}}: tag(5,BYTES)=0x2a
    want = b"\x2a\x04\x08\x64\x10\x05"
    assert bsr.encode_msg(bsr.StatusResponse(base=5, height=100)) == want
    got = bsr.decode_msg(want)
    assert (got.base, got.height) == (5, 100)
    # empty StatusRequest: tag(4,BYTES)=0x22 + len 0
    assert bsr.encode_msg(bsr.StatusRequest()) == b"\x22\x00"


def test_mempool_golden_bytes():
    # Message{txs=1{txs=[b"ab", b""]}}: inner repeated bytes field 1
    inner = b"\x0a\x02ab\x0a\x00"
    want = b"\x0a" + bytes([len(inner)]) + inner
    assert mpr.encode_msg(mpr.TxsMessage([b"ab", b""])) == want
    assert mpr.decode_msg(want).txs == [b"ab", b""]


def test_statesync_golden_bytes():
    # ChunkRequest{height=9, format=1, index=2} in oneof field 3
    inner = b"\x08\x09\x10\x01\x18\x02"
    want = b"\x1a" + bytes([len(inner)]) + inner
    assert ssr.encode_msg(ssr.ChunkRequest(9, 1, 2)) == want
    m = ssr.decode_msg(want)
    assert (m.height, m.format, m.index) == (9, 1, 2)


def test_pex_golden_bytes():
    # PexAddrs with one NetAddress{id="ab", ip="1.2.3.4", port=26656}
    na = (b"\x0a\x02ab" + b"\x12\x071.2.3.4"
          + b"\x18" + pe.uvarint(26656))
    inner = b"\x0a" + bytes([len(na)]) + na
    want = b"\x12" + bytes([len(inner)]) + inner
    assert pex.encode_msg(pex.PexAddrs([("ab", "1.2.3.4:26656")])) == want
    assert pex.decode_msg(want).addrs == [("ab", "1.2.3.4:26656")]


def test_consensus_has_vote_golden_bytes():
    # Message{has_vote=7{height=3, round=1, type=1(prevote), index=2}}
    inner = b"\x08\x03\x10\x01\x18\x01\x20\x02"
    want = b"\x3a" + bytes([len(inner)]) + inner
    m = cm.HasVoteMessage(3, 1, int(SignedMsgType.PREVOTE), 2)
    assert cm.encode_msg(m) == want
    got = cm.decode_msg(want)
    assert (got.height, got.round, got.type, got.index) == (3, 1, 1, 2)


def test_bitarray_proto_matches_reference_layout():
    # BitArray{bits=10, elems=[0b1000000101]}: packed repeated uint64
    ba = BitArray.from_indices(10, [0, 8, 9])
    body = ba.proto()
    f = pd.parse(body)
    assert pd.get_int(f, 1) == 10
    assert pd.get_packed_uvarints(f, 2) == [0b1100000001]
    rt = BitArray.from_proto(body)
    assert rt == ba
    # unpacked form (older encoders) also accepted
    unpacked = pe.varint_field(1, 10) + pe.tag(2, pe.WT_VARINT) \
        + pe.uvarint(0b1100000001)
    assert BitArray.from_proto(unpacked) == ba


# -- roundtrips -------------------------------------------------------------

def _vote():
    return Vote(type=SignedMsgType.PRECOMMIT, height=5, round=1,
                block_id=BID, timestamp=Timestamp(1700000123, 456),
                validator_address=b"\x33" * 20, validator_index=2,
                signature=b"\x44" * 64)


def test_consensus_roundtrips():
    from tendermint_tpu.types.part_set import PartSet

    ps = PartSet.from_data(b"x" * 300, part_size=128)
    msgs = [
        cm.NewRoundStepMessage(9, 2, 3, -1),
        cm.ProposalGossip(Proposal(height=9, round=2, pol_round=-1,
                                   block_id=BID,
                                   timestamp=Timestamp(1700000000, 1),
                                   signature=b"\x55" * 64)),
        cm.BlockPartGossip(9, 2, ps.get_part(0)),
        cm.VoteGossip(_vote()),
        cm.HasVoteMessage(9, 2, int(SignedMsgType.PRECOMMIT), 7),
        cm.VoteSetMaj23Message(9, 2, int(SignedMsgType.PREVOTE), BID),
        cm.VoteSetBitsMessage(9, 2, int(SignedMsgType.PREVOTE), BID,
                              10, BitArray.from_indices(10, [1, 9])
                              .to_bytes()),
    ]
    for m in msgs:
        data = cm.encode_msg(m)
        out = cm.decode_msg(data)
        assert type(out) is type(m)
        assert cm.encode_msg(out) == data  # stable re-encode
    # nil-BlockID maj23 (a nil-prevote majority) survives
    m = cm.VoteSetMaj23Message(9, 2, int(SignedMsgType.PREVOTE), BlockID())
    out = cm.decode_msg(cm.encode_msg(m))
    assert out.block_id == BlockID()


def test_blocksync_statesync_evidence_roundtrips():
    for m in (bsr.BlockRequest(4), bsr.NoBlockResponse(5),
              bsr.BlockResponse(b"\x0a\x00"), bsr.StatusRequest(),
              bsr.StatusResponse(2, 9)):
        assert bsr.decode_msg(bsr.encode_msg(m)) == m
    for m in (ssr.SnapshotsRequest(),
              ssr.SnapshotsResponse(7, 1, 4, b"h" * 32, b"meta"),
              ssr.ChunkRequest(7, 1, 2),
              ssr.ChunkResponse(7, 1, 2, b"chunk", False),
              ssr.ChunkResponse(7, 1, 3, b"", True)):
        assert ssr.decode_msg(ssr.encode_msg(m)) == m
    ev = evr.EvidenceGossip([b"\x0a\x00", b"\x12\x00"])
    assert evr.decode_msg(evr.encode_msg(ev)) == ev


def test_channel_registry_covers_all_node_channels():
    for ch in (0x00, 0x20, 0x21, 0x22, 0x30, 0x38, 0x40, 0x60, 0x61):
        assert ch in wire._CODECS, f"channel {ch:#x} has no codec"
    # unregistered channel cannot send (no pickle fallback)
    with pytest.raises(KeyError):
        wire.encode(0x7F, object())


# -- decoder fuzz -----------------------------------------------------------

def test_wire_decoders_reject_garbage_and_pickle():
    class Evil:
        def __reduce__(self):
            return (print, ("pwned",))

    decoders = [cm.decode_msg, bsr.decode_msg, mpr.decode_msg,
                ssr.decode_msg, evr.decode_msg, pex.decode_msg]
    rng = random.Random(1234)
    payloads = [pickle.dumps(Evil()), b"\x80\x04."]
    payloads += [bytes(rng.randrange(256) for _ in range(n))
                 for n in (1, 3, 17, 64, 300) for _ in range(40)]
    for dec in decoders:
        for p in payloads:
            try:
                dec(p)
            except ValueError:
                pass  # ProtoError subclasses ValueError
            # anything else (arbitrary exception, code execution) fails


def test_truncated_valid_messages_raise():
    data = cm.encode_msg(cm.VoteGossip(_vote()))
    for cut in range(1, len(data)):
        try:
            cm.decode_msg(data[:cut])
        except ValueError:
            pass


def test_node_info_proto_and_compat():
    """DefaultNodeInfo proto roundtrip + CompatibleWith gating (reference
    p2p/types.proto, p2p/node_info.go:179)."""
    from tendermint_tpu.p2p.switch import NodeInfo

    a = NodeInfo(node_id="aa" * 20, listen_addr="1.2.3.4:26656",
                 network="chain-x", version="0.34.20",
                 channels=bytes([0x20, 0x21, 0x22, 0x40]), moniker="a",
                 rpc_address="tcp://0.0.0.0:26657")
    rt = NodeInfo.from_bytes(a.to_bytes())
    assert rt == a

    b = NodeInfo.from_bytes(a.to_bytes())
    assert a.compatible_with(b) is None
    b.protocol_block += 1
    assert "Block version" in a.compatible_with(b)
    b = NodeInfo.from_bytes(a.to_bytes())
    b.network = "other-net"
    assert "different network" in a.compatible_with(b)
    b = NodeInfo.from_bytes(a.to_bytes())
    b.channels = bytes([0x77])
    assert "no common channels" in a.compatible_with(b)
    # proto layout spot check: field 2 is the node id string
    from tendermint_tpu.libs import protodec as pd
    f = pd.parse(a.to_bytes())
    assert pd.get_string(f, 2) == "aa" * 20
    pv = pd.parse(pd.get_message(f, 1))
    assert pd.get_uint(pv, 2) == 11  # BlockProtocol


def test_consensus_new_valid_block_and_pol_roundtrip():
    """Reference Message members new_valid_block(2) / proposal_pol(4)
    must decode (a reference peer broadcasts NewValidBlock routinely —
    rejecting it would disconnect every Go peer)."""
    m = cm.NewValidBlockMessage(
        height=9, round=1, block_part_set_header=PartSetHeader(4, b"\x0b" * 32),
        block_parts=BitArray.from_indices(4, [0, 2]), is_commit=True)
    out = cm.decode_msg(cm.encode_msg(m))
    assert (out.height, out.round, out.is_commit) == (9, 1, True)
    assert out.block_part_set_header == m.block_part_set_header
    assert out.block_parts == m.block_parts

    p = cm.ProposalPOLMessage(height=9, proposal_pol_round=0,
                              proposal_pol=BitArray.from_indices(6, [5]))
    out = cm.decode_msg(cm.encode_msg(p))
    assert out.proposal_pol == p.proposal_pol


def test_vote_set_bits_channel_codec_registered():
    """The dedicated catchup channel 0x23 must have a wire codec: a
    missing registration makes every VoteSetMaj23 answer raise KeyError
    inside receive(), which the switch treats as a peer error."""
    from tendermint_tpu.consensus import messages as cm
    from tendermint_tpu.p2p import wire as p2p_wire
    from tendermint_tpu.libs.bits import BitArray

    msg = cm.VoteSetBitsMessage(9, 2, int(SignedMsgType.PREVOTE), BID,
                                10, BitArray.from_indices(10, [1, 9])
                                .to_bytes())
    data = p2p_wire.encode(cm.VOTE_SET_BITS_CHANNEL, msg)
    out = p2p_wire.decode(cm.VOTE_SET_BITS_CHANNEL, data)
    assert type(out) is type(msg)
