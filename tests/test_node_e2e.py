"""Node assembly + CLI end-to-end: a 2-validator chain formed by two OS
processes from genesis files on disk, talked to over RPC — the
done-criterion for node/CLI/RPC (reference node/node_test.go +
test/e2e intent), exercising the full socket p2p stack
(Switch/SecretConnection/MConnection + all four reactors)."""
from __future__ import annotations

import base64
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rpc(port, method, **params):
    body = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                       "params": params}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=5) as r:
        payload = json.loads(r.read())
    if "error" in payload:
        raise RuntimeError(payload["error"])
    return payload["result"]


@pytest.mark.slow
def test_two_process_localnet():
    tmp = tempfile.mkdtemp(prefix="tm_e2e_")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # keep node procs off the TPU tunnel
    env.pop("TMHOME", None)
    # free-ish ports in a less common range
    p2p0, p2p1, rpc0, rpc1 = 28656, 28657, 28658, 28659
    r = subprocess.run(
        [sys.executable, "-m", "tendermint_tpu.cmd", "testnet",
         "--v", "2", "--o", tmp, "--chain-id", "e2e-chain",
         "--starting-p2p-port", str(p2p0),
         "--starting-rpc-port", str(rpc0)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    # testnet wrote two homes with shared genesis + crossed peers
    g0 = json.load(open(os.path.join(tmp, "node0/config/genesis.json")))
    g1 = json.load(open(os.path.join(tmp, "node1/config/genesis.json")))
    assert g0 == g1 and len(g0["validators"]) == 2

    procs = []
    try:
        for i in (0, 1):
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "tendermint_tpu.cmd",
                 "--home", os.path.join(tmp, f"node{i}"), "start"],
                cwd=REPO, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE))
        # wait for the chain to advance on both nodes
        deadline = time.time() + 120
        heights = [0, 0]
        while time.time() < deadline and min(heights) < 3:
            time.sleep(1.0)
            for i, port in enumerate((rpc0 + 0, rpc0 + 1)):
                try:
                    st = _rpc(port, "status")
                    heights[i] = int(
                        st["sync_info"]["latest_block_height"])
                except Exception:
                    pass
            for p in procs:
                assert p.poll() is None, (
                    f"node died: {p.stderr.read().decode()[-2000:]}")
        assert min(heights) >= 3, f"chain stalled at {heights}"

        # RPC surface sanity on a live chain
        st = _rpc(rpc0, "status")
        assert st["node_info"]["network"] == "e2e-chain"
        b = _rpc(rpc0, "block", height=2)
        assert b["block"]["header"]["height"] == "2"
        c = _rpc(rpc0, "commit", height=2)
        assert c["signed_header"]["commit"]["height"] == "2"
        v = _rpc(rpc0, "validators")
        assert v["total"] == "2"
        ni = _rpc(rpc0, "net_info")
        assert ni["n_peers"] >= 1

        # a tx flows through the mempool reactor and commits on both
        tx = base64.b64encode(b"e2ekey=e2eval").decode()
        res = _rpc(rpc1, "broadcast_tx_sync", tx=tx)
        assert res["code"] == 0, res
        deadline = time.time() + 60
        found = False
        while time.time() < deadline and not found:
            time.sleep(1.0)
            q = _rpc(rpc0, "abci_query", path="/store", data=b"e2ekey".hex())
            if base64.b64decode(q["response"]["value"] or "") == b"e2eval":
                found = True
        assert found, "tx did not commit/propagate"
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
