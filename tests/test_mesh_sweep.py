"""Overlapped mesh data plane (ADR-027): chunk-knob arithmetic, the
budget ladder for comb table placement, topology-keyed plane
invalidation, global-plane gating/latching, lockstep propagation across
the degrade lane-worker boundary, and chaos at all three mesh seams —
plus the slow-tier bitmap-identity sweeps with REAL kernels across
shard counts, ragged remainders, chunked double-buffered staging, and
the comb repl/shard/eviction matrix.

Tier-1 keeps to host-side structure and the pre-compile chaos seams
(the injects fire before any XLA work); every real-kernel sweep is
slow-tier, same budget discipline as tests/test_comb.py.
"""
from __future__ import annotations

import os

import numpy as np
import pytest

from tendermint_tpu.crypto import _edref
from tendermint_tpu.crypto import degrade
from tendermint_tpu.crypto import devobs
from tendermint_tpu.libs import fail
from tendermint_tpu.ops import ed25519 as edops
from tendermint_tpu.parallel import sharding


@pytest.fixture(autouse=True)
def _mesh_state():
    """Each test starts from a clean mesh world: default chunk knob, no
    armed chaos, no comb overrides, and the plane latches restored.

    The process-wide plane OBJECT is saved and put back, never dropped:
    its _fns dict holds every mesh bucket the suite has compiled so
    far, and replacing it with None would force each later test file
    to recompile those buckets (tens of seconds per file)."""
    with sharding._PLANE_LOCK:
        saved = (sharding._PLANE, sharding._PLANE_KEY,
                 sharding._GLOBAL_PLANE)
    sharding.set_mesh_chunk(None)
    sharding._poison_seen = False
    sharding._poison_next_check = 0.0
    fail.reset()
    edops._comb_enabled_override = None
    edops._comb_min_override = None
    edops._table_budget_override = None
    yield
    sharding.set_mesh_chunk(None)
    sharding._poison_seen = False
    sharding._poison_next_check = 0.0
    fail.reset()
    edops._comb_enabled_override = None
    edops._comb_min_override = None
    edops._table_budget_override = None
    with sharding._PLANE_LOCK:
        (sharding._PLANE, sharding._PLANE_KEY,
         sharding._GLOBAL_PLANE) = saved
    degrade.reset()


def _batch(n, pool=None, tag=b"sweep"):
    seeds = [(0x6B00 + (i % pool if pool else i)).to_bytes(32, "little")
             for i in range(n)]
    msgs = [b"%s %d" % (tag, i) for i in range(n)]
    pubs = [_edref.pubkey_from_seed(s) for s in seeds]
    sigs = [_edref.sign(s, m) for s, m in zip(seeds, msgs)]
    return pubs, msgs, sigs


def _oracle(pubs, msgs, sigs):
    out = np.zeros(len(pubs), dtype=bool)
    for i in range(len(pubs)):
        try:
            out[i] = bool(_edref.verify(bytes(pubs[i]), bytes(msgs[i]),
                                        bytes(sigs[i])))
        except Exception:  # noqa: BLE001 - malformed = invalid
            out[i] = False
    return out


def _corrupt(sigs, *lanes):
    sigs = list(sigs)
    for i in lanes:
        sigs[i] = sigs[i][:32] + bytes(32)
    return sigs


class _FakeEntry:
    """comb_mesh_mode consults only k_pad; the chaos seam fires before
    any table attribute is touched."""

    def __init__(self, k_pad=8):
        self.k_pad = k_pad
        self.mesh_repl = None
        self.mesh_shard = None


# ---------------------------------------------------------------------------
# tier-1: the chunk knob (raw coordinate vs pow2-floored effective)
# ---------------------------------------------------------------------------


def test_chunk_knob_pow2_floor_clamp_and_revert(monkeypatch):
    """The control plane steers the RAW value; the EFFECTIVE chunk is
    its power-of-two floor inside [_MESH_CHUNK_MIN, MAX_CHUNK] — so
    additive knob steps always move the raw coordinate (recovery can
    climb back to static) while launches stay in known compile
    buckets."""
    monkeypatch.delenv("TM_TPU_MESH_CHUNK", raising=False)
    assert sharding.mesh_chunk_raw() == sharding.MESH_CHUNK_DEFAULT
    assert sharding.mesh_chunk_lanes() == sharding.MESH_CHUNK_DEFAULT

    sharding.set_mesh_chunk(3000)          # raw moves exactly
    assert sharding.mesh_chunk_raw() == 3000
    assert sharding.mesh_chunk_lanes() == 2048   # pow2 floor
    sharding.set_mesh_chunk(4096 + 1024)   # a knob step past a pow2
    assert sharding.mesh_chunk_lanes() == 4096
    sharding.set_mesh_chunk(7)             # clamped at the floor
    assert sharding.mesh_chunk_lanes() == sharding._MESH_CHUNK_MIN
    sharding.set_mesh_chunk(10 ** 9)       # clamped at MAX_CHUNK
    assert sharding.mesh_chunk_lanes() == \
        1 << (edops.MAX_CHUNK.bit_length() - 1)

    sharding.set_mesh_chunk(None)          # revert to env/default
    monkeypatch.setenv("TM_TPU_MESH_CHUNK", "600")
    assert sharding.mesh_chunk_raw() == 600
    assert sharding.mesh_chunk_lanes() == 512
    monkeypatch.setenv("TM_TPU_MESH_CHUNK", "junk")
    assert sharding.mesh_chunk_raw() == sharding.MESH_CHUNK_DEFAULT


# ---------------------------------------------------------------------------
# tier-1: the comb table-placement budget ladder
# ---------------------------------------------------------------------------


def test_comb_mesh_mode_budget_ladder():
    """repl while TWO table copies fit (the build copy + one replica
    per device), shard while table + 1/nshard slice fits AND the
    validator bucket divides the mesh, None below that — never the
    ladder."""
    plane = sharding.data_plane()
    assert plane is not None and plane.nshard >= 2
    tb = edops._TABLE_BYTES_PER_KEY
    entry = _FakeEntry(k_pad=8)

    edops._table_budget_override = 2 * 8 * tb
    assert plane.comb_mesh_mode(entry) == "repl"
    edops._table_budget_override = 8 * tb + (8 * tb) // plane.nshard
    assert plane.comb_mesh_mode(entry) == "shard"
    edops._table_budget_override = 8 * tb + (8 * tb) // plane.nshard - 1
    assert plane.comb_mesh_mode(entry) is None
    # a validator bucket the mesh doesn't divide can't shard its table
    odd = _FakeEntry(k_pad=plane.nshard * 8 + 1)
    edops._table_budget_override = odd.k_pad * tb * 2 - 1
    if odd.k_pad % plane.nshard:
        assert plane.comb_mesh_mode(odd) is None


# ---------------------------------------------------------------------------
# tier-1: topology-keyed plane invalidation (the degrade re-probe seam)
# ---------------------------------------------------------------------------


def test_topology_invalidation_drops_stale_plane(monkeypatch):
    plane = sharding.data_plane()
    assert plane is not None
    # same topology: the latch holds, nothing dropped
    assert sharding.invalidate_on_topology_change() is False
    assert sharding.data_plane() is plane
    # the device list the plane latched on is gone (backend flap):
    # the next probe drops all three latches for lazy rebuild
    with sharding._PLANE_LOCK:
        sharding._PLANE_KEY = ("stale", -1)
    assert sharding.invalidate_on_topology_change() is True
    assert sharding._PLANE is None and sharding._GLOBAL_PLANE is None
    fresh = sharding.data_plane()
    assert fresh is not None and fresh is not plane

    # the NO_MESH latch records its topology too: a re-probe on the
    # same device list must NOT thrash the forced-off plane
    monkeypatch.setenv("TM_TPU_NO_MESH", "1")
    with sharding._PLANE_LOCK:
        sharding._PLANE = None
        sharding._PLANE_KEY = None
    assert sharding.data_plane() is None
    assert sharding._PLANE is False
    assert sharding._PLANE_KEY is not None
    assert sharding.invalidate_on_topology_change() is False
    assert sharding._PLANE is False


# ---------------------------------------------------------------------------
# tier-1: global-plane gating, the lockstep window, the failure latch
# ---------------------------------------------------------------------------


def test_global_plane_gating_and_failure_latch(monkeypatch):
    """global_plane() answers ONLY inside a lockstep() window on a
    multi-process runtime; a real collective fault latches it off
    until a topology re-probe clears the latch."""
    monkeypatch.delenv("TM_TPU_NO_MESH", raising=False)
    # single-process runtime: never ready, lockstep or not
    assert sharding.global_mesh_ready() is False
    with sharding.lockstep():
        assert sharding.global_plane() is None

    # pretend a multi-process runtime: still gated on lockstep
    monkeypatch.setattr(sharding.jax, "process_count", lambda: 2)
    assert sharding.global_mesh_ready() is True
    assert sharding.global_plane() is None          # not in lockstep
    with sharding.lockstep():
        assert sharding.in_lockstep()
        with sharding.lockstep():                   # re-entrant
            assert sharding.in_lockstep()
        gp = sharding.global_plane()
        assert gp is not None and gp.MESH_PATH == "global-mesh"
        # a real (non-chaos) collective fault latches the plane off
        sharding.disable_global_plane()
        assert sharding.global_plane() is None
    assert not sharding.in_lockstep()
    # the kill switches win over everything
    with sharding._PLANE_LOCK:
        sharding._GLOBAL_PLANE = None
    monkeypatch.setenv("TM_TPU_NO_GLOBAL_MESH", "1")
    with sharding.lockstep():
        assert sharding.global_plane() is None


def test_lockstep_propagates_across_lane_worker():
    """degrade.submit captures the caller's lockstep depth and re-arms
    it inside the lane worker (same discipline as the trace parent
    span): without it, every production dispatch would observe
    in_lockstep() == False on the worker thread and the global plane
    would be unreachable from the one call site built for it."""
    from tendermint_tpu.libs.metrics import Registry

    rt = degrade.configure(registry=Registry("mesh_lockstep"))
    try:
        seen = {}

        def probe():
            seen["locked"] = sharding.in_lockstep()
            return np.ones(4, dtype=bool)

        with sharding.lockstep():
            out = rt.run("batch.ed25519", probe,
                         lambda: np.zeros(4, dtype=bool))
        assert np.asarray(out).all()
        assert seen["locked"] is True

        out = rt.run("batch.ed25519", probe,
                     lambda: np.zeros(4, dtype=bool))
        assert np.asarray(out).all()
        assert seen["locked"] is False
    finally:
        degrade.reset()


# ---------------------------------------------------------------------------
# tier-1: chaos at the three mesh seams (pre-compile, so cheap)
# ---------------------------------------------------------------------------


def test_chaos_mesh_stage_degrades_to_single_device(monkeypatch):
    """A raise at sharding.mesh_stage falls THIS batch back to the
    single-device ladder — the mesh fault is caught inside
    ops/ed25519.verify_batch, never escaping to the degrade runtime.
    The ladder itself is stubbed to the host oracle (keeping the seam
    pre-compile: the slow sweeps below pin the real-kernel bitmap);
    what this test owns is the route — chaos fires, the fallback takes
    the single-device path, and the host_ok mask/slice plumbing holds."""
    assert sharding.data_plane() is not None
    fail.set_mode("sharding.mesh_stage", "raise")
    pubs, msgs, sigs = _batch(13, tag=b"stage-chaos")
    sigs = _corrupt(sigs, 5)
    truth = _oracle(pubs, msgs, sigs)
    hit = {}

    def _ladder_stub(**arrs):
        hit["nb"] = int(next(iter(arrs.values())).shape[0])
        return edops.jnp.asarray(
            np.pad(truth, (0, hit["nb"] - len(truth))))

    monkeypatch.setattr(edops, "verify_kernel", _ladder_stub)
    bm = edops.verify_batch(pubs, msgs, sigs)
    assert fail.fired("sharding.mesh_stage", "raise") >= 1
    assert hit["nb"] == edops.bucket_size(13)
    ll = edops.last_launch()
    assert ll["shards"] == 1 and ll["path"] != "mesh-xla"
    assert (bm == truth).all()


def test_chaos_mesh_comb_seam_fires_before_any_launch():
    """The sharding.mesh_comb inject sits after the budget decision and
    before any staging/dispatch: arming it raises out of verify_comb
    (ops/ed25519._comb_try catches and runs the single-device comb)."""
    plane = sharding.data_plane()
    assert plane is not None
    edops._table_budget_override = 10 ** 12     # mode 'repl' for sure
    fail.set_mode("sharding.mesh_comb", "raise")
    with pytest.raises(fail.InjectedFault):
        plane.verify_comb(np.zeros((8, 32), np.uint8),
                          np.zeros((8, 64), np.int8),
                          np.zeros((8, 64), np.int8),
                          np.zeros(8, np.int32), _FakeEntry(), None)
    assert fail.fired("sharding.mesh_comb", "raise") >= 1
    # a declined budget never reaches the seam: the caller falls to the
    # single-device comb without a chaos hit
    fired0 = fail.fired("sharding.mesh_comb", "raise")
    edops._table_budget_override = 1
    assert plane.verify_comb(np.zeros((8, 32), np.uint8),
                             np.zeros((8, 64), np.int8),
                             np.zeros((8, 64), np.int8),
                             np.zeros(8, np.int32),
                             _FakeEntry(), None) is None
    assert fail.fired("sharding.mesh_comb", "raise") == fired0


class _FakeCoord:
    """A stand-in jax.distributed coordination client: a dict-backed
    KV store plus a barrier log (wait_at_barrier raising is the real
    client's timeout shape)."""

    def __init__(self, barrier_error=None):
        self.kv = {}
        self.barriers = []
        self.barrier_error = barrier_error

    def key_value_set(self, key, val):
        self.kv[key] = val

    def key_value_dir_get(self, d):
        return [(k, v) for k, v in sorted(self.kv.items())
                if k.startswith(d)]

    def key_value_delete(self, key):
        pref = key.rstrip("/")
        for k in [k for k in self.kv if k.startswith(pref)]:
            del self.kv[k]

    def wait_at_barrier(self, name, timeout_ms):
        if self.barrier_error is not None:
            raise self.barrier_error
        self.barriers.append(name)


def test_global_plane_pins_static_chunk_lanes(monkeypatch):
    """The chunk count is part of the cross-process collective's
    shape, and the knob/env are steered PER-PROCESS: the global plane
    must pin the code-constant default while the local plane keeps
    following the governed knob — otherwise two peers steered across a
    power-of-two boundary launch mismatched chunk sequences into the
    same collective and deadlock."""
    monkeypatch.delenv("TM_TPU_MESH_CHUNK", raising=False)
    gp = sharding._GlobalDataPlane(
        sharding.make_mesh(sharding.jax.local_devices()))
    local = sharding.data_plane()
    assert local is not None
    static = sharding._static_chunk_lanes()
    assert static == sharding.mesh_chunk_lanes()  # untouched knob

    sharding.set_mesh_chunk(static // 2)           # steer the knob
    assert local._chunk_lanes() == static // 2
    assert gp._chunk_lanes() == static             # pinned
    monkeypatch.setenv("TM_TPU_MESH_CHUNK", str(static // 4))
    sharding.set_mesh_chunk(None)                  # env now governs
    assert local._chunk_lanes() == static // 4
    assert gp._chunk_lanes() == static             # still pinned


def test_barrier_propagates_real_rendezvous_failure(monkeypatch):
    """_barrier exists so no process dispatches into a collective a
    peer is still compiling: a REAL rendezvous failure (timeout,
    missing peer) must propagate so verify_batch's handler latches the
    plane off — only the no-service cases are silent no-ops."""
    boom = _FakeCoord(barrier_error=RuntimeError("barrier deadline"))
    monkeypatch.setattr(sharding, "_coord_client", lambda: boom)
    with pytest.raises(RuntimeError, match="barrier deadline"):
        sharding._barrier("tm_tpu_gmesh_step_64")
    # single-process / uninitialized runtime: no peers, no-op
    monkeypatch.setattr(sharding, "_coord_client", lambda: None)
    sharding._barrier("tm_tpu_gmesh_step_64")


def test_latch_poison_propagates_cross_process(monkeypatch):
    """disable_global_plane publishes a per-process poison key;
    global_plane() on a HEALTHY peer sees it and latches too — one
    faulted participant costs the job at most the in-flight batch, not
    one degrade timeout per peer per batch — and the topology re-probe
    that clears the local latch clears the poison directory with it."""
    coord = _FakeCoord()
    monkeypatch.setattr(sharding, "_coord_client", lambda: coord)
    monkeypatch.setattr(sharding.jax, "process_count", lambda: 2)
    monkeypatch.delenv("TM_TPU_NO_MESH", raising=False)
    monkeypatch.delenv("TM_TPU_NO_GLOBAL_MESH", raising=False)

    # the faulting process publishes its latch
    sharding.disable_global_plane()
    assert any(k.startswith(sharding._GMESH_POISON_DIR)
               for k in coord.kv)

    # a healthy peer with a LIVE plane latches on sight of the poison
    gp = sharding._GlobalDataPlane(
        sharding.make_mesh(sharding.jax.local_devices()))
    with sharding._PLANE_LOCK:
        sharding._GLOBAL_PLANE = gp
    sharding._poison_seen = False
    sharding._poison_next_check = 0.0
    with sharding.lockstep():
        assert sharding.global_plane() is None
    assert sharding._GLOBAL_PLANE is False

    # topology re-probe clears the local latch AND the poison keys
    assert sharding.data_plane() is not None   # populate _PLANE
    with sharding._PLANE_LOCK:
        sharding._PLANE_KEY = ("stale", -1)
    assert sharding.invalidate_on_topology_change() is True
    assert not coord.kv
    assert sharding._poison_seen is False


def test_mesh_tables_ledger_charges_once_under_race():
    """Two threads racing the first comb-table replication both
    device_put (benign — one copy wins the slot) but the mesh_tables
    ledger must be charged exactly once: _table_evicted frees the
    winning tuple's bytes once, so a double charge would drift the
    gauge upward forever."""
    import threading as th

    plane = sharding.data_plane()
    assert plane is not None
    k_pad = 4
    tables = type("T", (), {})()
    for name in ("ypx", "ymx", "z", "t2d"):
        setattr(tables, name, np.zeros((1, 1, 1, k_pad), np.uint32))
    entry = _FakeEntry(k_pad=k_pad)
    entry.tables = tables
    entry.dec_ok = np.ones(k_pad, dtype=bool)
    entry.index = ()                   # _table_evicted walks the keys
    base = (np.zeros(1, np.uint32),) * 3
    tbytes = (plane.nshard - 1) * k_pad * edops._TABLE_BYTES_PER_KEY

    devobs.reset()
    devobs.enable()
    try:
        start = th.Barrier(4)
        outs = []

        def racer():
            start.wait()
            outs.append(plane._comb_repl_operands(entry, base))

        threads = [th.Thread(target=racer) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # every racer got the SAME committed tuple, charged once
        assert all(o is outs[0] for o in outs)
        rep = devobs.OBS.ledger_report()
        assert rep["mesh_tables"]["bytes"] == tbytes
        # eviction frees exactly what was charged: the gauge returns
        # to zero instead of drifting
        edops._table_evicted("race-set", entry)
        rep = devobs.OBS.ledger_report()
        assert rep["mesh_tables"]["bytes"] == 0
    finally:
        devobs.reset()
        devobs.enable()


def test_lockstep_wedge_latches_global_plane_on_first_timeout():
    """A coordinated (lockstep) launch that wedges past the launch
    deadline on a multi-process runtime is the global collective's
    signature hang — a peer never entered, and the worker thread never
    returns, so verify_batch's exception handler can't latch.  The
    degrade settle latches on the FIRST such timeout, bounding the
    job-wide convergence to one hung batch per process instead of one
    launch deadline per subsequent batch."""
    import threading as th
    import unittest.mock as mock

    from tendermint_tpu.libs.metrics import Registry

    cfg = degrade.DegradeConfig()
    cfg.launch_timeout_s = 0.05
    rt = degrade.configure(cfg, registry=Registry("mesh_wedge"))
    release = th.Event()

    def wedged():
        release.wait(5.0)
        return np.ones(4, dtype=bool)

    try:
        with mock.patch.object(sharding.jax, "process_count",
                               lambda: 2):
            with sharding._PLANE_LOCK:
                sharding._GLOBAL_PLANE = None
            with sharding.lockstep():
                out = rt.run("batch.ed25519", wedged,
                             lambda: np.zeros(4, dtype=bool))
            assert not np.asarray(out).any()       # host fallback
            assert sharding._GLOBAL_PLANE is False  # first wedge latched

            # a NON-lockstep wedge never touches the global latch
            with sharding._PLANE_LOCK:
                sharding._GLOBAL_PLANE = None
            out = rt.run("batch.ed25519", wedged,
                         lambda: np.zeros(4, dtype=bool))
            assert not np.asarray(out).any()
            assert sharding._GLOBAL_PLANE is None
    finally:
        release.set()
        degrade.reset()


def test_chaos_global_plane_seam_fires_before_any_collective():
    """sharding.global_plane injects at the top of the global compact
    launch — BEFORE the AOT compile/barrier — so a chaos raise degrades
    the batch without ever entering a collective a peer would wait
    on."""
    gp = sharding._GlobalDataPlane(
        sharding.make_mesh(sharding.jax.local_devices()))
    fail.set_mode("sharding.global_plane", "raise")
    pubs, msgs, sigs = _batch(9, tag=b"gchaos")
    with pytest.raises(fail.InjectedFault):
        gp.verify_batch(pubs, msgs, sigs)
    assert fail.fired("sharding.global_plane", "raise") >= 1


# ---------------------------------------------------------------------------
# slow: bitmap-identity sweeps with REAL kernels
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_ladder_bitmap_identity_across_shard_counts():
    """The overlapped compact ladder at 2/4/8 shards, ragged remainders
    included: bitmap identical to the host oracle and the single-device
    ladder, pad lanes never valid, the psum'd all_valid bit recorded,
    every bucket a CompileSentinel-known shape."""
    from tendermint_tpu.devtools.tmlint.runtime import CompileSentinel

    devs = sharding.jax.local_devices()
    assert len(devs) >= 8
    pubs, msgs, sigs = _batch(13, tag=b"ladder-sweep")
    sigs = _corrupt(sigs, 3, 11)
    truth = _oracle(pubs, msgs, sigs)

    edops._comb_enabled_override = False        # pin the ladder
    single = None
    for k in (2, 4, 8):
        plane = sharding._DataPlane(sharding.make_mesh(devs[:k]))
        bm = plane.verify_batch(pubs, msgs, sigs)
        ll = edops.last_launch()
        assert ll["path"] == "mesh-xla" and ll["shards"] == k
        assert ll["nb"] % k == 0
        assert CompileSentinel.bucket_allowed(ll["nb"], k), ll
        assert ll["all_valid"] is False
        assert (bm == truth).all(), (k, bm, truth)
        single = bm if single is None else single
        assert (bm == single).all()


@pytest.mark.slow
def test_chunked_staging_overlap_and_identity():
    """Forcing the chunk knob to the floor on a 2-shard plane makes the
    nb=1024 bucket a 2-chunk double-buffered launch: chunk_overlap
    lands in the record (> 0: the second chunk's puts are issued while
    chunk one computes), per-shard put walls cover both chunks, and the
    bitmap stays identical to the host oracle."""
    devs = sharding.jax.local_devices()
    devobs.enable()
    try:
        plane = sharding._DataPlane(sharding.make_mesh(devs[:2]))
        sharding.set_mesh_chunk(256)            # chunk = 2 * 256 = 512
        pubs, msgs, sigs = _batch(700, tag=b"chunk-sweep")
        sigs = _corrupt(sigs, 650)
        bm = plane.verify_batch(pubs, msgs, sigs)
        ll = edops.last_launch()
        assert ll["path"] == "mesh-xla" and ll["nb"] == 1024
        assert ll["chunks"] == 2
        assert ll["chunk_overlap"] > 0.0
        assert len(ll["shard_h2d_s"]) == 2
        assert not bm[650] and bm[:650].all() and bm[651:].all()
    finally:
        devobs.disable()


@pytest.mark.slow
def test_comb_placement_matrix_subset_and_eviction():
    """The budget matrix with real kernels: replicated mesh comb,
    sharded-table gather layout (tight budget), single-device comb
    (budget below a slice), each bitwise-identical to the host oracle;
    the mesh_tables ledger charges replicas and frees them on
    eviction; a SUBSET batch after eviction still verifies exactly."""
    plane = sharding.data_plane()
    assert plane is not None and plane.nshard >= 8
    devobs.enable()
    edops._comb_min_override = 1
    tb = edops._TABLE_BYTES_PER_KEY
    try:
        pubs, msgs, sigs = _batch(23, pool=8, tag=b"comb-sweep")
        sigs = _corrupt(sigs, 7)
        truth = _oracle(pubs, msgs, sigs)

        # replicated: nshard-1 extra copies on the mesh_tables books
        bm = edops.verify_batch(pubs, msgs, sigs, cache_pubs=True)
        ll = edops.last_launch()
        assert ll["path"] == "mesh-comb" and ll["shards"] == plane.nshard
        assert (bm == truth).all()
        ledger = devobs.ledger_report()["mesh_tables"]["bytes"]
        assert ledger >= (plane.nshard - 1) * 8 * tb

        # subset of the cached set rides the same tables (no rebuild);
        # wide enough for worth_sharding on the 8-way mesh
        sub = [0, 2, 5, 7, 11, 13, 16, 19, 21]
        bs = edops.verify_batch([pubs[i] for i in sub],
                                [msgs[i] for i in sub],
                                [sigs[i] for i in sub])
        assert edops.last_launch()["path"] == "mesh-comb"
        assert not edops.last_launch()["table_build"]
        assert (bs == truth[sub]).all()

        # mid-run eviction frees the replicas; the next subset call
        # re-resolves (rebuild on this cache_pubs batch) — exact bitmap
        edops.table_cache_clear()
        assert devobs.ledger_report()["mesh_tables"]["bytes"] == 0
        bs2 = edops.verify_batch([pubs[i] for i in sub],
                                 [msgs[i] for i in sub],
                                 [sigs[i] for i in sub],
                                 cache_pubs=True)
        assert (bs2 == truth[sub]).all()

        # tight budget: the sharded-table gather layout, same bitmap
        edops.table_cache_clear()
        edops._table_budget_override = 8 * tb + (8 * tb) // plane.nshard
        bm2 = edops.verify_batch(pubs, msgs, sigs, cache_pubs=True)
        assert edops.last_launch()["path"] == "mesh-comb-sharded"
        assert (bm2 == truth).all()

        # below a slice: single-device comb, NOT the ladder
        edops.table_cache_clear()
        edops._table_budget_override = 8 * tb + tb // 4
        bm3 = edops.verify_batch(pubs, msgs, sigs, cache_pubs=True)
        ll3 = edops.last_launch()
        assert ll3["path"] == "comb" and ll3["shards"] == 1
        assert (bm3 == truth).all()
    finally:
        devobs.disable()
        edops.table_cache_clear()   # this test's tables, not the suite's
