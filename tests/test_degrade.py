"""crypto/degrade.py unit tests: circuit-breaker lifecycle, backend
probing with backoff, launch timeout/quarantine, and host-fallback
plumbing — all with a deterministic injected clock and a private metrics
registry (the runtime under test never touches the process-global one).
"""
from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from tendermint_tpu.crypto import degrade
from tendermint_tpu.libs import fail
from tendermint_tpu.libs.metrics import Registry


@pytest.fixture(autouse=True)
def _clean():
    fail.reset()
    yield
    fail.reset()
    degrade.reset()


def _cfg(**kw):
    base = dict(failure_threshold=3, launch_timeout_s=5.0,
                backoff_base_s=10.0, backoff_max_s=100.0,
                backoff_jitter=0.0)
    base.update(kw)
    return degrade.DegradeConfig(**base)


class Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_breaker_opens_after_consecutive_failures_only():
    clk = Clock()
    br = degrade.CircuitBreaker(_cfg(), clock=clk)
    for _ in range(2):
        assert br.try_acquire()
        br.record_failure("x")
    assert br.state == degrade.CLOSED
    # a success resets the consecutive count
    assert br.try_acquire()
    br.record_success()
    for _ in range(2):
        assert br.try_acquire()
        br.record_failure("x")
    assert br.state == degrade.CLOSED
    assert br.try_acquire()
    br.record_failure("x")
    assert br.state == degrade.OPEN
    assert not br.try_acquire()


def test_breaker_probe_backoff_and_reclose():
    clk = Clock()
    trans = []
    br = degrade.CircuitBreaker(_cfg(failure_threshold=1), clock=clk)
    br.add_listener(lambda o, n, r: trans.append((o, n)))
    assert br.try_acquire()
    br.record_failure("boom")
    assert br.state == degrade.OPEN
    # before the deadline: denied; no half-open transition
    clk.t = 9.9
    assert not br.try_acquire()
    # deadline passed: exactly ONE probe is granted
    clk.t = 10.1
    assert br.try_acquire()
    assert br.state == degrade.HALF_OPEN
    assert not br.try_acquire()  # concurrent callers stay host-side
    # failed probe -> re-open with the delay doubled
    br.record_failure("still down")
    assert br.state == degrade.OPEN
    clk.t = 10.1 + 19.9
    assert not br.try_acquire()
    clk.t = 10.1 + 20.1
    assert br.try_acquire()
    br.record_success()
    assert br.state == degrade.CLOSED
    assert trans == [(degrade.CLOSED, degrade.OPEN),
                     (degrade.OPEN, degrade.HALF_OPEN),
                     (degrade.HALF_OPEN, degrade.OPEN),
                     (degrade.OPEN, degrade.HALF_OPEN),
                     (degrade.HALF_OPEN, degrade.CLOSED)]
    # backoff resets after the re-close: next open waits base_s again
    assert br.try_acquire()
    br.record_failure("y")
    assert br.state == degrade.OPEN
    t_open = clk.t
    clk.t = t_open + 10.1
    assert br.try_acquire()


def test_breaker_backoff_caps():
    clk = Clock()
    br = degrade.CircuitBreaker(_cfg(failure_threshold=1,
                                     backoff_base_s=40.0,
                                     backoff_max_s=60.0), clock=clk)
    assert br.try_acquire()
    br.record_failure("a")
    clk.t += 40.1
    assert br.try_acquire()  # probe
    br.record_failure("b")   # doubles to min(80, 60) = 60
    t0 = clk.t
    clk.t = t0 + 59.9
    assert not br.try_acquire()
    clk.t = t0 + 60.1
    assert br.try_acquire()


def test_listener_unsubscribe():
    br = degrade.CircuitBreaker(_cfg(failure_threshold=1), clock=Clock())
    got = []
    unsub = br.add_listener(lambda o, n, r: got.append(n))
    br.try_acquire()
    br.record_failure("x")
    assert got == [degrade.OPEN]
    unsub()
    br.record_success()
    assert got == [degrade.OPEN]


def test_runtime_run_success_failure_and_breaker_open():
    clk = Clock()
    rt = degrade.DeviceLaneRuntime(_cfg(failure_threshold=2), clock=clk,
                                   registry=Registry("t"))
    ok = rt.run("site", lambda: np.array([True, True]),
                host_fn=lambda: np.array([False, False]))
    assert ok.all()
    assert rt.metrics.device_launches.value(site="site") == 1

    host = np.array([True, False])
    for i in range(2):
        out = rt.run("site", lambda: 1 / 0, host_fn=lambda: host)
        assert (out == host).all()
    assert rt.breaker.state == degrade.OPEN
    assert rt.metrics.device_failures.value(site="site",
                                            reason="raise") == 2
    # breaker open: host_fn without a device attempt
    out = rt.run("site", lambda: np.array([True, True]),
                 host_fn=lambda: host)
    assert (out == host).all()
    assert rt.metrics.host_fallbacks.value(site="site",
                                           reason="breaker_open") == 1
    assert rt.metrics.device_launches.value(site="site") == 3


def test_runtime_timeout_quarantines_and_recovers():
    clk = Clock()
    rt = degrade.DeviceLaneRuntime(
        _cfg(failure_threshold=10, launch_timeout_s=0.05), clock=clk,
        registry=Registry("t"))
    release = threading.Event()

    def wedged():
        release.wait(5.0)
        return np.array([True])

    host = np.array([True])
    out = rt.run("site", wedged, host_fn=lambda: host)
    assert (out == host).all()
    assert rt.metrics.device_failures.value(site="site",
                                            reason="timeout") == 1
    release.set()
    # the wedged worker was quarantined: a fresh launch must NOT queue
    # behind it and must succeed promptly
    rt.cfg.launch_timeout_s = 5.0
    out = rt.run("site", lambda: np.array([False]),
                 host_fn=lambda: np.array([True]))
    assert not out[0]
    assert rt.breaker.state == degrade.CLOSED


def test_task_raised_timeouterror_is_raise_not_wait_timeout():
    """A TimeoutError raised BY the device fn (e.g. a socket timeout on
    the tunnel) is a device raise; only an expired result-wait counts as
    the timeout class and quarantines the worker."""
    rt = degrade.DeviceLaneRuntime(
        _cfg(failure_threshold=10, launch_timeout_s=5.0), clock=Clock(),
        registry=Registry("t"))

    def sock_timeout():
        raise TimeoutError("tunnel read timed out")

    host = np.array([True])
    out = rt.run("site", sock_timeout, host_fn=lambda: host)
    assert (out == host).all()
    assert rt.metrics.device_failures.value(site="site",
                                            reason="raise") == 1
    assert rt.metrics.device_failures.value(site="site",
                                            reason="timeout") == 0


def test_runtime_spot_check_rejects_corrupt_device_result():
    rt = degrade.DeviceLaneRuntime(_cfg(failure_threshold=10),
                                   clock=Clock(), registry=Registry("t"))
    host = np.array([True, True])
    out = rt.run("site", lambda: np.array([False, False]),
                 host_fn=lambda: host,
                 spot_check=lambda bits: bool(bits[0]))
    assert (out == host).all()
    assert rt.metrics.device_failures.value(site="site",
                                            reason="integrity") == 1


def test_runtime_injection_sites():
    """fail.py modes reach the device fn through submit()'s wrapper.
    Ad-hoc sites must be registered before arming (a typo'd site in a
    chaos test would otherwise never fire, tmlint TM305)."""
    rt = degrade.DeviceLaneRuntime(_cfg(failure_threshold=10),
                                   clock=Clock(), registry=Registry("t"))
    host = np.array([True])
    with pytest.raises(ValueError, match="not registered"):
        fail.set_mode("site", "raise")
    fail.register("site")
    fail.set_mode("site", "raise")
    out = rt.run("site", lambda: np.array([False]), host_fn=lambda: host)
    assert (out == host).all()
    assert fail.fired("site", "raise") == 1
    assert rt.metrics.device_failures.value(site="site", reason="raise") \
        == 1
    fail.set_mode("site", "corrupt-bitmap")
    out = rt.run("site", lambda: np.array([False]), host_fn=lambda: host,
                 spot_check=lambda bits: not bits[0])
    # device said False, corruption flipped to True, spot check expected
    # False -> integrity failure -> host result
    assert (out == host).all()
    assert fail.fired("site", "corrupt-bitmap") == 1


def test_backend_probe_backoff(monkeypatch):
    """An init failure is retried after backoff instead of being cached
    forever (the _backend_ok regression this runtime replaces)."""
    clk = Clock()
    rt = degrade.DeviceLaneRuntime(_cfg(backoff_base_s=10.0), clock=clk,
                                   registry=Registry("t"))
    calls = []

    class FakeJax:
        @staticmethod
        def default_backend():
            calls.append(clk.t)
            if len(calls) < 3:
                raise RuntimeError("Unable to initialize backend")
            return "tpu"

    import sys
    monkeypatch.setitem(sys.modules, "jax", FakeJax())
    assert not rt.backend_available()
    # cached-negative until the probe deadline — no probe storm
    assert not rt.backend_available()
    assert len(calls) == 1
    clk.t = 10.1
    assert not rt.backend_available()
    assert len(calls) == 2
    # second retry backs off 20s from the failed probe
    clk.t = 10.1 + 20.1
    assert rt.backend_available()
    assert len(calls) == 3
    # a live backend is stable: no further probes
    clk.t += 1000
    assert rt.backend_available()
    assert len(calls) == 3


def test_env_failpoints_parsing(monkeypatch):
    # a typo'd env key must fail loudly at the first inject, not
    # silently never fire (same contract as the set_mode guard)
    monkeypatch.setenv("TM_TPU_FAILPOINTS", "a.typo=raise")
    with pytest.raises(ValueError, match="not registered"):
        fail.inject("anything.at.all")
    fail.register("a.site")
    fail.register("b.site")
    monkeypatch.setenv("TM_TPU_FAILPOINTS",
                       "a.site=raise; b.site=latency:1")
    with pytest.raises(fail.InjectedFault):
        fail.inject("a.site")
    t0 = time.monotonic()
    fail.inject("b.site")
    assert time.monotonic() - t0 < 1.0
    fail.inject("c.site")  # unarmed: no-op
    # programmatic arming wins and wildcard matches
    fail.set_mode("*", "raise")
    with pytest.raises(fail.InjectedFault):
        fail.inject("c.site")
