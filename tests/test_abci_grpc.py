"""ABCI gRPC transport (reference abci/client/grpc_client.go,
abci/server/grpc_server.go): the reference e2e matrix's third transport.
Payloads are the bare Request*/Response* messages — the same bytes as
the socket oneof envelope's embedded body, so the golden-fixture suite
(tests/test_abci_golden.py) covers this codec too; here the transport
itself is driven end to end against a kvstore."""
from __future__ import annotations

import pytest

pytest.importorskip("grpc")  # grpcio is optional everywhere in-tree

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.grpc import (GRPCClient, GRPCServer,
                                      decode_request_bare,
                                      decode_response_bare,
                                      encode_request_bare,
                                      encode_response_bare)
from tendermint_tpu.abci.kvstore import KVStoreApplication


@pytest.fixture
def grpc_pair():
    srv = GRPCServer(KVStoreApplication(), "127.0.0.1:0")
    srv.start()
    cli = GRPCClient(srv.addr)
    yield srv, cli
    cli.close()
    srv.stop()


def test_grpc_roundtrip(grpc_pair):
    """Every method crosses the wire and comes back typed."""
    _, cli = grpc_pair
    assert cli.echo("hello") == "hello"
    cli.flush()
    info = cli.info(abci.RequestInfo())
    assert info.last_block_height == 0
    r = cli.check_tx(abci.RequestCheckTx(tx=b"a=1"))
    assert r.is_ok()
    cli.begin_block(abci.RequestBeginBlock(hash=b"\x01" * 32))
    dr = cli.deliver_tx(b"a=1")
    assert dr.code == abci.CODE_TYPE_OK
    cli.end_block(1)
    c = cli.commit()
    assert c.data  # app hash
    q = cli.query(abci.RequestQuery(data=b"a"))
    assert q.value == b"1"


def test_grpc_snapshot_methods(grpc_pair):
    _, cli = grpc_pair
    snaps = cli.list_snapshots()
    assert snaps == []
    resp = cli.offer_snapshot(
        abci.Snapshot(height=1, format=1, chunks=1, hash=b"h"), b"apph")
    assert resp is not None


def test_grpc_app_exception_maps_to_client_error(grpc_pair):
    from tendermint_tpu.abci.client import ABCIClientError

    srv, cli = grpc_pair

    def boom(_req):
        raise RuntimeError("kvstore exploded")

    srv.app.query = boom
    with pytest.raises(ABCIClientError, match="kvstore exploded"):
        cli.query(abci.RequestQuery(data=b"a"))


def test_bare_codec_roundtrip_all_methods():
    """encode/decode_request_bare and _response_bare round-trip for the
    whole method matrix (same internal objects the golden suite uses)."""
    from tendermint_tpu.abci import wire

    cases = [
        ("echo", "hi"),
        ("flush", None),
        ("info", abci.RequestInfo(version="v1")),
        ("deliver_tx", b"k=v"),
        ("end_block", 7),
        ("commit", None),
        ("list_snapshots", None),
    ]
    for method, req in cases:
        bare = encode_request_bare(method, req)
        # must equal the socket envelope's embedded body byte-for-byte
        env = wire.encode_request(method, req)
        assert bare in env and len(bare) <= len(env)
        got = decode_request_bare(method, bare)
        assert wire.encode_request(method, got) == env

    resp_cases = [
        ("echo", "hi"),
        ("info", abci.ResponseInfo(last_block_height=3)),
        ("deliver_tx", abci.ResponseDeliverTx(code=0, data=b"x")),
        ("commit", abci.ResponseCommit(data=b"h")),
    ]
    for method, resp in resp_cases:
        bare = encode_response_bare(method, resp)
        env = wire.encode_response(method, resp)
        assert bare in env
        got = decode_response_bare(method, bare)
        assert wire.encode_response(method, got) == env


@pytest.mark.slow
def test_external_grpc_kvstore_backs_a_chain(tmp_path):
    """Transport-matrix parity (reference e2e --abci grpc): a kvstore in
    a separate OS process serves gRPC and a single-validator node
    commits blocks through it."""
    import os
    import signal
    import socket
    import subprocess
    import sys
    import time

    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    addr = f"grpc://127.0.0.1:{port}"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    app_proc = subprocess.Popen(
        [sys.executable, "-m", "tendermint_tpu.cmd", "abci-kvstore",
         "--address", addr],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    try:
        time.sleep(1.5)
        assert app_proc.poll() is None, app_proc.stderr.read().decode()

        from tendermint_tpu.config.config import Config
        from tendermint_tpu.node import Node
        from tendermint_tpu.privval.file_pv import FilePV
        from tendermint_tpu.proxy import AppConns, ClientCreator
        from tendermint_tpu.types.basic import Timestamp
        from tendermint_tpu.types.genesis import (GenesisDoc,
                                                  GenesisValidator)

        cfg = Config(home=str(tmp_path / "node"))
        cfg.ensure_dirs()
        cfg.p2p.laddr = "127.0.0.1:0"
        cfg.rpc.enabled = False
        pv = FilePV.load_or_generate(cfg.priv_validator_key_file(),
                                     cfg.priv_validator_state_file())
        pub = pv.get_pub_key()
        gdoc = GenesisDoc(
            chain_id="abci-grpc-chain",
            genesis_time=Timestamp(1700000000, 0),
            validators=[GenesisValidator(
                address=pub.address(), pub_key_type=pub.type_name,
                pub_key_bytes=pub.bytes(), power=10)])
        with open(cfg.genesis_file(), "w") as f:
            f.write(gdoc.to_json())
        node = Node(cfg, AppConns(ClientCreator.remote(addr)),
                    in_memory=True)
        node.start()
        try:
            deadline = time.time() + 60
            while time.time() < deadline and \
                    node.block_store.height() < 3:
                time.sleep(0.2)
            assert node.block_store.height() >= 3
            # the app state lives in the EXTERNAL process, over gRPC
            q = node.app.query(abci.RequestQuery(data=b"nope"))
            assert q.code == abci.CODE_TYPE_OK
        finally:
            node.stop()
    finally:
        app_proc.send_signal(signal.SIGTERM)
        try:
            app_proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            app_proc.kill()
