"""State sync: snapshot discovery/offer/apply, light-verified app hash,
state bootstrap, and resuming via blocksync from the snapshot height
(reference statesync/syncer_test.go intent)."""
from __future__ import annotations

import pytest

from helpers import build_chain, make_genesis
from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.blocksync.replay import block_id_of, replay_window
from tendermint_tpu.libs.kvdb import MemDB
from tendermint_tpu.light import (Client, DictProvider, LightStore,
                                  TrustOptions)
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.state import state_from_genesis
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.statesync import StateProvider, Syncer
from tendermint_tpu.statesync.syncer import StateSyncError
from tendermint_tpu.store.block_store import BlockStore
from tendermint_tpu.types.basic import Timestamp
from tendermint_tpu.types.light_block import LightBlock, SignedHeader

NOW = Timestamp(1700005000, 0)


def _served_chain(n_heights=20, n_vals=4, snapshot_interval=5):
    """A 'serving node': chain built with a snapshotting kvstore."""
    gdoc, privs = make_genesis(n_vals)

    def mk_app():
        app = KVStoreApplication()
        app.snapshot_interval = snapshot_interval
        app.snapshot_chunk_size = 128  # force multi-chunk snapshots
        return app

    # build_chain uses its own executor/app; rebuild here with snapshots on
    app = mk_app()
    ex = BlockExecutor(StateStore(MemDB()), app)
    blocks, commits, states = build_chain(
        gdoc, privs, n_heights, txs_fn=lambda h: [b"k%d=v%d" % (h, h)])
    # replay into the snapshotting app
    store = BlockStore(MemDB())
    state = state_from_genesis(gdoc)
    applied = 0
    while applied < n_heights:
        state, n = replay_window(ex, store, state, blocks[applied:],
                                 commits[applied:], max_window=8)
        applied += n
    lbs = {}
    for i, b in enumerate(blocks):
        lbs[b.header.height] = LightBlock(
            SignedHeader(b.header, commits[i]), states[i].validators)
    return gdoc, privs, app, blocks, commits, states, lbs


def test_statesync_bootstrap_and_resume():
    gdoc, privs, serving_app, blocks, commits, states, lbs = _served_chain()
    snaps = serving_app.list_snapshots()
    assert snaps, "serving app must have taken snapshots"
    best = max(s.height for s in snaps)
    assert best == 20 or best % 5 == 0

    # fresh node: empty app, light client anchored at height 1
    fresh_app = KVStoreApplication()
    lc = Client(gdoc.chain_id, TrustOptions(1, lbs[1].hash(), 3600.0 * 24),
                DictProvider(gdoc.chain_id, lbs), [], LightStore(MemDB()))
    sp = StateProvider(lc, NOW)

    def fetch(snapshot, index, peer):
        return (serving_app.load_snapshot_chunk(
            snapshot.height, snapshot.format, index), peer)

    syncer = Syncer(fresh_app, sp, fetch)
    for s in snaps:
        syncer.add_snapshot(s, "peer1")
    state, commit = syncer.sync_any()

    # the head snapshot (h=20) cannot be verified until headers H+1/H+2
    # exist, so the syncer falls back to the best verifiable one
    h = state.last_block_height
    assert h == 15
    assert fresh_app.height == h
    # restored state is the serving app's state AS OF the snapshot height
    assert fresh_app.data == {k: v for k, v in serving_app.data.items()
                              if int(k[1:]) <= h}
    assert state.app_hash == states[h - 1].app_hash
    assert commit.height == h

    # resume: blocksync the remaining blocks on top of the restored state
    store = BlockStore(MemDB())
    store.save_seen_commit(h, commit)
    ex = BlockExecutor(StateStore(MemDB()), fresh_app)
    remaining = blocks[h:]
    rem_commits = commits[h:]
    applied = 0
    while applied < len(remaining):
        state, n = replay_window(ex, store, state, remaining[applied:],
                                 rem_commits[applied:], max_window=8)
        applied += n
    assert state.last_block_height == len(blocks)
    assert state.app_hash == states[-1].app_hash


def test_statesync_rejects_corrupt_snapshot():
    gdoc, privs, serving_app, blocks, commits, states, lbs = _served_chain()
    fresh_app = KVStoreApplication()
    lc = Client(gdoc.chain_id, TrustOptions(1, lbs[1].hash(), 3600.0 * 24),
                DictProvider(gdoc.chain_id, lbs), [], LightStore(MemDB()))
    sp = StateProvider(lc, NOW)

    def bad_fetch(snapshot, index, peer):
        body = serving_app.load_snapshot_chunk(
            snapshot.height, snapshot.format, index)
        return b"\x00" + body[1:], peer

    syncer = Syncer(fresh_app, sp, bad_fetch)
    for s in serving_app.list_snapshots():
        syncer.add_snapshot(s, "peer1")
    from tendermint_tpu.statesync import StateSyncError
    with pytest.raises(StateSyncError):
        syncer.sync_any()


def test_statestore_bootstrap_persists_validator_sets():
    """Reference state/store.go Bootstrap: a snapshot-restored state must
    make load_validators(H), H+1 and H+2 answer — a plain save() only
    writes H+2, starving evidence verification and light providers."""
    from tendermint_tpu.libs.kvdb import MemDB
    from tendermint_tpu.state.store import StateStore

    gdoc, privs, serving_app, blocks, commits, states, lbs = _served_chain()
    st = states[10]
    h = st.last_block_height
    ss = StateStore(MemDB())
    ss.bootstrap(st)
    assert ss.load().last_block_height == h
    for hh in (h, h + 1, h + 2):
        assert ss.load_validators(hh) is not None, hh
    assert ss.load_consensus_params(h + 1) is not None


def test_statesync_concurrent_fetchers_with_flaky_transport():
    """The fetcher pool (reference syncer.go:411) must restore correctly
    when fetches are slow, arrive out of order, and fail transiently —
    and ban peers the app rejects."""
    import threading
    import time as _t

    gdoc, privs, serving_app, blocks, commits, states, lbs = _served_chain()
    snaps = serving_app.list_snapshots()
    fresh_app = KVStoreApplication()
    lc = Client(gdoc.chain_id, TrustOptions(1, lbs[1].hash(), 3600.0 * 24),
                DictProvider(gdoc.chain_id, lbs), [], LightStore(MemDB()))
    sp = StateProvider(lc, NOW)

    seen_threads = set()
    fail_once = set()
    lock = threading.Lock()

    def flaky_fetch(snapshot, index, peer):
        with lock:
            seen_threads.add(threading.current_thread().name)
            if index not in fail_once:
                fail_once.add(index)
                raise StateSyncError(f"transient fail {index}")
        _t.sleep(0.05 * ((index * 7) % 3))  # out-of-order arrivals
        return (serving_app.load_snapshot_chunk(
            snapshot.height, snapshot.format, index), peer)

    syncer = Syncer(fresh_app, sp, flaky_fetch, fetchers=4)
    for s in snaps:
        syncer.add_snapshot(s, "peer1")
    state, commit = syncer.sync_any()
    # best VERIFIABLE snapshot: heights within two of the chain head
    # cannot be light-verified yet (needs headers to H+2)
    head = max(b.header.height for b in blocks)
    best_ok = max(s.height for s in snaps if s.height <= head - 2)
    assert state.last_block_height == best_ok
    info = fresh_app.info(abci.RequestInfo())
    assert info.last_block_height == state.last_block_height
    # at least two distinct fetcher threads participated
    assert len(seen_threads) >= 2, seen_threads


def test_statesync_gives_up_after_chunk_retry_limit():
    gdoc, privs, serving_app, blocks, commits, states, lbs = _served_chain()
    snaps = serving_app.list_snapshots()
    fresh_app = KVStoreApplication()
    lc = Client(gdoc.chain_id, TrustOptions(1, lbs[1].hash(), 3600.0 * 24),
                DictProvider(gdoc.chain_id, lbs), [], LightStore(MemDB()))
    sp = StateProvider(lc, NOW)

    def dead_fetch(snapshot, index, peer):
        raise StateSyncError("peer gone")

    syncer = Syncer(fresh_app, sp, dead_fetch, fetchers=3)
    for s in snaps:
        syncer.add_snapshot(s, "peer1")
    with pytest.raises(StateSyncError):
        syncer.sync_any()
