"""State sync: snapshot discovery/offer/apply, light-verified app hash,
state bootstrap, and resuming via blocksync from the snapshot height
(reference statesync/syncer_test.go intent)."""
from __future__ import annotations

import pytest

from helpers import build_chain, make_genesis
from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.blocksync.replay import block_id_of, replay_window
from tendermint_tpu.libs.kvdb import MemDB
from tendermint_tpu.light import (Client, DictProvider, LightStore,
                                  TrustOptions)
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.state import state_from_genesis
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.statesync import StateProvider, Syncer
from tendermint_tpu.statesync.syncer import StateSyncError
from tendermint_tpu.store.block_store import BlockStore
from tendermint_tpu.types.basic import Timestamp
from tendermint_tpu.types.light_block import LightBlock, SignedHeader

NOW = Timestamp(1700005000, 0)


def _served_chain(n_heights=20, n_vals=4, snapshot_interval=5,
                  chunk_size=128):
    """A 'serving node': chain built with a snapshotting kvstore."""
    gdoc, privs = make_genesis(n_vals)

    def mk_app():
        app = KVStoreApplication()
        app.snapshot_interval = snapshot_interval
        app.snapshot_chunk_size = chunk_size  # force multi-chunk snapshots
        return app

    # build_chain uses its own executor/app; rebuild here with snapshots on
    app = mk_app()
    ex = BlockExecutor(StateStore(MemDB()), app)
    blocks, commits, states = build_chain(
        gdoc, privs, n_heights, txs_fn=lambda h: [b"k%d=v%d" % (h, h)])
    # replay into the snapshotting app
    store = BlockStore(MemDB())
    state = state_from_genesis(gdoc)
    applied = 0
    while applied < n_heights:
        state, n = replay_window(ex, store, state, blocks[applied:],
                                 commits[applied:], max_window=8)
        applied += n
    lbs = {}
    for i, b in enumerate(blocks):
        lbs[b.header.height] = LightBlock(
            SignedHeader(b.header, commits[i]), states[i].validators)
    return gdoc, privs, app, blocks, commits, states, lbs


def test_statesync_bootstrap_and_resume():
    gdoc, privs, serving_app, blocks, commits, states, lbs = _served_chain()
    snaps = serving_app.list_snapshots()
    assert snaps, "serving app must have taken snapshots"
    best = max(s.height for s in snaps)
    assert best == 20 or best % 5 == 0

    # fresh node: empty app, light client anchored at height 1
    fresh_app = KVStoreApplication()
    lc = Client(gdoc.chain_id, TrustOptions(1, lbs[1].hash(), 3600.0 * 24),
                DictProvider(gdoc.chain_id, lbs), [], LightStore(MemDB()))
    sp = StateProvider(lc, NOW)

    def fetch(snapshot, index, peer):
        return (serving_app.load_snapshot_chunk(
            snapshot.height, snapshot.format, index), peer)

    syncer = Syncer(fresh_app, sp, fetch)
    for s in snaps:
        syncer.add_snapshot(s, "peer1")
    state, commit = syncer.sync_any()

    # the head snapshot (h=20) cannot be verified until headers H+1/H+2
    # exist, so the syncer falls back to the best verifiable one
    h = state.last_block_height
    assert h == 15
    assert fresh_app.height == h
    # restored state is the serving app's state AS OF the snapshot height
    assert fresh_app.data == {k: v for k, v in serving_app.data.items()
                              if int(k[1:]) <= h}
    assert state.app_hash == states[h - 1].app_hash
    assert commit.height == h

    # resume: blocksync the remaining blocks on top of the restored state
    store = BlockStore(MemDB())
    store.save_seen_commit(h, commit)
    ex = BlockExecutor(StateStore(MemDB()), fresh_app)
    remaining = blocks[h:]
    rem_commits = commits[h:]
    applied = 0
    while applied < len(remaining):
        state, n = replay_window(ex, store, state, remaining[applied:],
                                 rem_commits[applied:], max_window=8)
        applied += n
    assert state.last_block_height == len(blocks)
    assert state.app_hash == states[-1].app_hash


def test_statesync_rejects_corrupt_snapshot():
    gdoc, privs, serving_app, blocks, commits, states, lbs = _served_chain()
    fresh_app = KVStoreApplication()
    lc = Client(gdoc.chain_id, TrustOptions(1, lbs[1].hash(), 3600.0 * 24),
                DictProvider(gdoc.chain_id, lbs), [], LightStore(MemDB()))
    sp = StateProvider(lc, NOW)

    def bad_fetch(snapshot, index, peer):
        body = serving_app.load_snapshot_chunk(
            snapshot.height, snapshot.format, index)
        return b"\x00" + body[1:], peer

    syncer = Syncer(fresh_app, sp, bad_fetch)
    for s in serving_app.list_snapshots():
        syncer.add_snapshot(s, "peer1")
    from tendermint_tpu.statesync import StateSyncError
    with pytest.raises(StateSyncError):
        syncer.sync_any()


def test_statestore_bootstrap_persists_validator_sets():
    """Reference state/store.go Bootstrap: a snapshot-restored state must
    make load_validators(H), H+1 and H+2 answer — a plain save() only
    writes H+2, starving evidence verification and light providers."""
    from tendermint_tpu.libs.kvdb import MemDB
    from tendermint_tpu.state.store import StateStore

    gdoc, privs, serving_app, blocks, commits, states, lbs = _served_chain()
    st = states[10]
    h = st.last_block_height
    ss = StateStore(MemDB())
    ss.bootstrap(st)
    assert ss.load().last_block_height == h
    for hh in (h, h + 1, h + 2):
        assert ss.load_validators(hh) is not None, hh
    assert ss.load_consensus_params(h + 1) is not None


def test_statesync_concurrent_fetchers_with_flaky_transport():
    """The fetcher pool (reference syncer.go:411) must restore correctly
    when fetches are slow, arrive out of order, and fail transiently —
    and ban peers the app rejects."""
    import threading
    import time as _t

    gdoc, privs, serving_app, blocks, commits, states, lbs = _served_chain()
    snaps = serving_app.list_snapshots()
    fresh_app = KVStoreApplication()
    lc = Client(gdoc.chain_id, TrustOptions(1, lbs[1].hash(), 3600.0 * 24),
                DictProvider(gdoc.chain_id, lbs), [], LightStore(MemDB()))
    sp = StateProvider(lc, NOW)

    seen_threads = set()
    fail_once = set()
    lock = threading.Lock()

    def flaky_fetch(snapshot, index, peer):
        with lock:
            seen_threads.add(threading.current_thread().name)
            if index not in fail_once:
                fail_once.add(index)
                raise StateSyncError(f"transient fail {index}")
        _t.sleep(0.05 * ((index * 7) % 3))  # out-of-order arrivals
        return (serving_app.load_snapshot_chunk(
            snapshot.height, snapshot.format, index), peer)

    syncer = Syncer(fresh_app, sp, flaky_fetch, fetchers=4)
    for s in snaps:
        syncer.add_snapshot(s, "peer1")
    state, commit = syncer.sync_any()
    # best VERIFIABLE snapshot: heights within two of the chain head
    # cannot be light-verified yet (needs headers to H+2)
    head = max(b.header.height for b in blocks)
    best_ok = max(s.height for s in snaps if s.height <= head - 2)
    assert state.last_block_height == best_ok
    info = fresh_app.info(abci.RequestInfo())
    assert info.last_block_height == state.last_block_height
    # at least two distinct fetcher threads participated
    assert len(seen_threads) >= 2, seen_threads


def test_statesync_gives_up_after_chunk_retry_limit():
    gdoc, privs, serving_app, blocks, commits, states, lbs = _served_chain()
    snaps = serving_app.list_snapshots()
    fresh_app = KVStoreApplication()
    lc = Client(gdoc.chain_id, TrustOptions(1, lbs[1].hash(), 3600.0 * 24),
                DictProvider(gdoc.chain_id, lbs), [], LightStore(MemDB()))
    sp = StateProvider(lc, NOW)

    def dead_fetch(snapshot, index, peer):
        raise StateSyncError("peer gone")

    syncer = Syncer(fresh_app, sp, dead_fetch, fetchers=3)
    for s in snaps:
        syncer.add_snapshot(s, "peer1")
    with pytest.raises(StateSyncError):
        syncer.sync_any()


# ---------------------------------------------------------------------------
# ADR-022 fast-join: per-chunk integrity, per-peer accounting, resume,
# bounded serving, chaos matrix
# ---------------------------------------------------------------------------

import hashlib
import os
import subprocess
import sys
import threading
import time

from tendermint_tpu.libs import fail, slo, trace
from tendermint_tpu.libs.kvdb import SQLiteDB
from tendermint_tpu.statesync import integrity
from tendermint_tpu.statesync.ledger import RestoreLedger
from tendermint_tpu.statesync.syncer import (ChunkBusy, SnapshotRejected,
                                             metrics as ss_metrics)
from tendermint_tpu.statesync import syncer as ssync

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _light_sp(gdoc, lbs):
    lc = Client(gdoc.chain_id, TrustOptions(1, lbs[1].hash(), 3600.0 * 24),
                DictProvider(gdoc.chain_id, lbs), [], LightStore(MemDB()))
    return StateProvider(lc, NOW)


def _chunk_of(app, snapshot, index):
    return app.load_snapshot_chunk(snapshot.height, snapshot.format, index)


class _RecordingApp(KVStoreApplication):
    """Records every chunk the syncer hands to apply_snapshot_chunk —
    the pre-app integrity assertion reads this."""

    def __init__(self):
        super().__init__()
        self.applied = []

    def apply_snapshot_chunk(self, index, chunk, sender):
        self.applied.append((index, bytes(chunk), sender))
        return super().apply_snapshot_chunk(index, chunk, sender)


def test_chunk_metadata_roundtrip_and_tamper():
    chunks = [b"a" * 10, b"b" * 10, b"tail"]
    meta = integrity.make_chunk_metadata(chunks)
    digests = integrity.parse_chunk_metadata(meta, 3)
    assert digests is not None and len(digests) == 3
    for i, c in enumerate(chunks):
        assert integrity.verify_chunk(digests, i, c)
        assert not integrity.verify_chunk(digests, i, c + b"x")
    # malformed headers refuse instead of half-trusting
    assert integrity.parse_chunk_metadata(b"", 3) is None
    assert integrity.parse_chunk_metadata(b"junkmeta", 3) is None
    assert integrity.parse_chunk_metadata(meta, 2) is None  # count lies
    bad = bytearray(meta)
    bad[10] ^= 0xFF  # break the embedded root
    assert integrity.parse_chunk_metadata(bytes(bad), 3) is None
    # stored-prefix re-verification keeps only intact chunks
    stored = {0: chunks[0], 1: b"rotten", 2: chunks[2]}
    assert integrity.verify_chunks(digests, stored) == [0, 2]
    # legacy snapshots (no digests): everything is returned, the app's
    # end-to-end check stays the only guard
    assert integrity.verify_chunks(None, stored) == [0, 1, 2]


def test_kvstore_snapshots_carry_chunk_digests():
    _, _, serving_app, _, _, _, _ = _served_chain()
    for s in serving_app.list_snapshots():
        digests = integrity.parse_chunk_metadata(s.metadata, s.chunks)
        assert digests is not None, "kvstore snapshot lacks digest meta"
        for i in range(s.chunks):
            assert integrity.verify_chunk(digests, i,
                                          _chunk_of(serving_app, s, i))


def test_corrupt_chunk_detected_pre_app_and_banned():
    """THE tentpole invariant: a Byzantine provider's corrupt chunk is
    detected on the fetch thread BEFORE the app call, the offending
    peer is banned, the chunk refetches from an honest peer, and the
    restore completes with the exact app state."""
    gdoc, privs, serving_app, blocks, commits, states, lbs = _served_chain()
    fresh_app = _RecordingApp()
    banned = []
    m = ss_metrics()
    base_corrupt = m.chunks_verified.value(outcome="corrupt")
    base_banned = m.peers_banned.value()

    def fetch(snapshot, index, peer):
        body = _chunk_of(serving_app, snapshot, index)
        if peer == "evil":
            return b"\x00" * len(body), peer
        return body, peer

    syncer = Syncer(fresh_app, _light_sp(gdoc, lbs), fetch,
                    ban_peer=lambda p, r: banned.append((p, r)),
                    fetchers=3)
    for s in serving_app.list_snapshots():
        # the Byzantine peer advertises FIRST so rotation starts there
        syncer.add_snapshot(s, "evil")
        syncer.add_snapshot(s, "good1")
        syncer.add_snapshot(s, "good2")
    state, commit = syncer.sync_any()

    h = state.last_block_height
    assert fresh_app.height == h
    assert fresh_app.data == {k: v for k, v in serving_app.data.items()
                              if int(k[1:]) <= h}
    # every chunk the app saw was intact (pre-app detection)
    snaps = {(s.height, s.format): s for s in serving_app.list_snapshots()}
    for idx, chunk, sender in fresh_app.applied:
        assert sender != "evil" or chunk == _chunk_of(
            serving_app, snaps[(h, 1)], idx)
        assert hashlib.sha256(chunk).digest() == hashlib.sha256(
            _chunk_of(serving_app, snaps[(h, 1)], idx)).digest()
    assert any(p == "evil" for p, _ in banned), banned
    assert not any(p.startswith("good") for p, _ in banned), banned
    assert m.chunks_verified.value(outcome="corrupt") > base_corrupt
    assert m.peers_banned.value() > base_banned
    assert m.time_to_synced.value() > 0


def test_one_dead_peer_of_three_completes():
    """Regression for the per-chunk accounting bug: a single dead peer
    used to burn the whole snapshot's retry budget.  With per-peer
    counters + rotation, 1 dead of 3 providers must complete."""
    gdoc, privs, serving_app, blocks, commits, states, lbs = _served_chain()
    fresh_app = KVStoreApplication()
    banned = []
    asked = []
    lock = threading.Lock()

    def fetch(snapshot, index, peer):
        with lock:
            asked.append(peer)
        if peer == "dead":
            raise StateSyncError("connection refused")
        return _chunk_of(serving_app, snapshot, index), peer

    syncer = Syncer(fresh_app, _light_sp(gdoc, lbs), fetch,
                    ban_peer=lambda p, r: banned.append(p),
                    fetchers=3, retries=2)
    for s in serving_app.list_snapshots():
        syncer.add_snapshot(s, "dead")       # dead hint advertised first
        syncer.add_snapshot(s, "alive1")
        syncer.add_snapshot(s, "alive2")
    state, commit = syncer.sync_any()
    assert state.last_block_height == 15
    assert fresh_app.height == 15
    # rotation really spread across the live providers
    assert {"alive1", "alive2"} <= set(asked)
    # the dead peer's failures never spilled onto the live ones
    assert "alive1" not in banned and "alive2" not in banned
    stats = syncer.last_restore
    assert stats is not None and stats["chunks"] >= 1


def test_peer_book_budget_epochs_and_ban():
    """Per-peer accounting semantics: one strike per backoff EPOCH
    (concurrent same-burst failures don't double-strike), budget
    exhaustion bans via the callback, corrupt chunks ban instantly,
    success resets the counter."""
    from tendermint_tpu.statesync.syncer import _PeerBook

    banned = []
    book = _PeerBook(["a", "b"], retries=2,
                     ban_cb=lambda p, r: banned.append((p, r)))
    t0 = time.monotonic()
    # burst: 4 concurrent fetches that all STARTED before the first
    # strike landed -> one strike total
    assert book.failure("a", t0, "x") is False
    for _ in range(3):
        assert book.failure("a", t0, "x") is False
    assert book.dead_peers() == []
    # distinct epochs: strikes 2 then 3 (> retries=2) -> dead + banned
    assert book.failure("a", time.monotonic(), "x") is False
    assert book.failure("a", time.monotonic(), "x") is True
    assert book.dead_peers() == ["a"]
    assert banned and banned[0][0] == "a"
    # rotation never hands out a dead peer; b still serves
    for _ in range(4):
        peer, wait_s = book.pick()
        if peer is not None:
            assert peer == "b"
    # success resets b's counter
    book.failure("b", time.monotonic(), "x")
    book.success("b")
    peer, _ = book.pick()
    assert peer == "b"
    # corrupt chunk: instant ban, then all_dead aborts the plane
    book.ban("b", "digest mismatch")
    assert book.all_dead()
    assert ("b", "digest mismatch") in banned
    peer, wait_s = book.pick()
    assert peer is None and wait_s < 0


def test_busy_peer_backs_off_without_strike():
    """ChunkBusy (the bounded server's refusal) rotates + backs off
    but never bans: a loaded server is not a dead one."""
    gdoc, privs, serving_app, blocks, commits, states, lbs = _served_chain()
    fresh_app = KVStoreApplication()
    banned = []
    busy_hits = [0]

    def fetch(snapshot, index, peer):
        if peer == "loaded":
            busy_hits[0] += 1
            raise ChunkBusy("busy", retry_after_s=0.05)
        return _chunk_of(serving_app, snapshot, index), peer

    syncer = Syncer(fresh_app, _light_sp(gdoc, lbs), fetch,
                    ban_peer=lambda p, r: banned.append(p), fetchers=2)
    for s in serving_app.list_snapshots():
        syncer.add_snapshot(s, "loaded")
        syncer.add_snapshot(s, "calm")
    state, _ = syncer.sync_any()
    assert state.last_block_height == 15
    assert busy_hits[0] >= 1
    assert banned == []


def test_ledger_resume_skips_stored_chunks():
    """In-process resume: a transport abort mid-restore keeps the
    verified prefix in the ledger; the next attempt refetches ONLY the
    missing chunks and completes."""
    gdoc, privs, serving_app, blocks, commits, states, lbs = \
        _served_chain(chunk_size=32)   # many chunks: die mid-restore
    ledger = RestoreLedger(MemDB(), group_every=2)
    # die after 3 successful fetches on attempt 1
    fetches = []
    lock = threading.Lock()

    def flaky(snapshot, index, peer):
        with lock:
            fetches.append(index)
            if len(fetches) > 3 and flaky.armed:
                raise StateSyncError("transport died")
        return _chunk_of(serving_app, snapshot, index), peer

    flaky.armed = True
    fresh_app = KVStoreApplication()
    syncer = Syncer(fresh_app, _light_sp(gdoc, lbs), flaky,
                    fetchers=1, retries=1, ledger=ledger)
    best = max(s.height for s in serving_app.list_snapshots()
               if s.height <= 18)
    target = [s for s in serving_app.list_snapshots()
              if s.height == best][0]
    syncer.add_snapshot(target, "peer1")
    with pytest.raises(StateSyncError):
        syncer.sync_any()
    stored_before = len(ledger.begin(target))
    assert 1 <= stored_before <= 3
    man = ledger.manifest()
    assert man is not None and man["height"] == target.height

    # attempt 2: healthy transport — only the gap is fetched
    flaky.armed = False
    first_attempt = len(fetches)
    fresh_app2 = KVStoreApplication()
    syncer2 = Syncer(fresh_app2, _light_sp(gdoc, lbs), flaky,
                     fetchers=1, ledger=ledger)
    syncer2.add_snapshot(target, "peer1")
    state, commit = syncer2.sync_any()
    assert state.last_block_height == target.height
    assert fresh_app2.height == target.height
    refetched = len(fetches) - first_attempt
    assert refetched == target.chunks - stored_before, \
        (refetched, target.chunks, stored_before)
    assert syncer2.last_restore["resumed"] == stored_before
    # completion clears the ledger
    assert ledger.manifest() is None


def test_ledger_clears_on_snapshot_rejection():
    """Chunks of a REJECTED snapshot must not linger: the next begin()
    starts clean."""
    gdoc, privs, serving_app, blocks, commits, states, lbs = _served_chain()
    ledger = RestoreLedger(MemDB(), group_every=2)

    class RejectingApp(KVStoreApplication):
        def apply_snapshot_chunk(self, index, chunk, sender):
            r = super().apply_snapshot_chunk(index, chunk, sender)
            if self._restoring is None and r.result == \
                    abci.ResponseApplySnapshotChunk.ACCEPT:
                raise RuntimeError("app exploded after restore")
            return r

    def fetch(snapshot, index, peer):
        return _chunk_of(serving_app, snapshot, index), peer

    syncer = Syncer(RejectingApp(), _light_sp(gdoc, lbs), fetch,
                    fetchers=2, ledger=ledger)
    for s in serving_app.list_snapshots():
        syncer.add_snapshot(s, "peer1")
    with pytest.raises(StateSyncError):
        syncer.sync_any()
    assert ledger.manifest() is None
    assert list(ledger.db.iterate_prefix(b"ss:")) == []


def test_statesync_chaos_matrix():
    """raise/latency/corrupt at the statesync seams, with the degrade
    contract pinned per site (the exercised-chaos-site gate in
    test_lint.py keys on these literals: statesync.fetch,
    statesync.verify, statesync.apply)."""
    gdoc, privs, serving_app, blocks, commits, states, lbs = _served_chain()

    def fetch(snapshot, index, peer):
        return _chunk_of(serving_app, snapshot, index), peer

    def run_sync(app=None, **kw):
        syncer = Syncer(app or KVStoreApplication(),
                        _light_sp(gdoc, lbs), fetch, **kw)
        for s in serving_app.list_snapshots():
            syncer.add_snapshot(s, "p1")
            syncer.add_snapshot(s, "p2")
        return syncer.sync_any()

    # fetch raise: every provider eventually exhausts -> StateSyncError,
    # the app never sees a chunk
    app = _RecordingApp()
    fail.set_mode("statesync.fetch", "raise")
    try:
        with pytest.raises(StateSyncError):
            run_sync(app=app, retries=1)
        assert fail.fired("statesync.fetch", "raise") >= 1
        assert app.applied == []
    finally:
        fail.clear()

    # fetch latency: absorbed, restore completes
    fail.set_mode("statesync.fetch", "latency:30")
    try:
        state, _ = run_sync()
        assert state.last_block_height == 15
        assert fail.fired("statesync.fetch", "latency:30") >= 1
    finally:
        fail.clear()

    # corrupt-chunk: flipped bytes are detected pre-app on EVERY
    # provider -> all banned -> StateSyncError, app untouched
    app = _RecordingApp()
    m = ss_metrics()
    base_corrupt = m.chunks_verified.value(outcome="corrupt")
    fail.set_mode("statesync.fetch", "corrupt-chunk")
    try:
        with pytest.raises(StateSyncError):
            run_sync(app=app, retries=1)
        assert fail.fired("statesync.fetch", "corrupt-chunk") >= 1
        assert app.applied == []
        assert m.chunks_verified.value(outcome="corrupt") > base_corrupt
    finally:
        fail.clear()

    # verify raise: machinery fault -> retried as transport error, app
    # untouched, eventually StateSyncError (no ban storm: the fault is
    # ours, not proven peer misbehavior -> peers die of exhausted
    # budgets, not digest bans)
    app = _RecordingApp()
    fail.set_mode("statesync.verify", "raise")
    try:
        with pytest.raises(StateSyncError):
            run_sync(app=app, retries=1)
        assert fail.fired("statesync.verify", "raise") >= 1
        assert app.applied == []
    finally:
        fail.clear()

    # apply raise: app-layer restore failure -> snapshot REJECTED (and
    # blacklisted), surfaced as no-viable-snapshots
    fail.set_mode("statesync.apply", "raise")
    try:
        with pytest.raises(StateSyncError, match="REJECTED"):
            run_sync()
        assert fail.fired("statesync.apply", "raise") >= 1
    finally:
        fail.clear()

    # apply latency: absorbed
    fail.set_mode("statesync.apply", "latency:20")
    try:
        state, _ = run_sync()
        assert state.last_block_height == 15
        assert fail.fired("statesync.apply", "latency:20") >= 1
    finally:
        fail.clear()


def test_statesync_spans_and_slo_stream():
    gdoc, privs, serving_app, blocks, commits, states, lbs = _served_chain()
    fresh_app = KVStoreApplication()

    def fetch(snapshot, index, peer):
        return _chunk_of(serving_app, snapshot, index), peer

    syncer = Syncer(fresh_app, _light_sp(gdoc, lbs), fetch, fetchers=2)
    for s in serving_app.list_snapshots():
        syncer.add_snapshot(s, "peer1")
    since = trace.last_seq()
    trace.enable(capacity=4096)
    slo.set_config(enabled=True, window=256)
    try:
        state, _ = syncer.sync_any()
    finally:
        spans = trace.snapshot(since=since)
        trace.disable()
        rep = slo.stream_report("statesync")
        slo.set_config(enabled=False)
    assert state.last_block_height == 15
    got = {s["name"] for s in spans}
    assert "statesync.fetch" in got and "statesync.apply" in got, \
        sorted(got)[:20]
    # pipelining: some fetch span for a later chunk starts before the
    # apply span of an earlier chunk ends (fetch of k+1 overlaps apply)
    applies = [s for s in spans if s["name"] == "statesync.apply"]
    fetches = [s for s in spans if s["name"] == "statesync.fetch"]
    assert rep is not None and rep["n"] >= 1
    assert applies and fetches


def test_serve_bounded_queue_ratelimit_and_chaos():
    """The serving side (reactor): per-peer token buckets refuse with
    busy + Retry-After, the queue stays bounded, chaos raise at
    statesync.serve answers busy instead of killing the server."""
    from tendermint_tpu.statesync.reactor import (ChunkRequest,
                                                  ChunkResponse,
                                                  StateSyncReactor)

    _, _, serving_app, _, _, _, _ = _served_chain()
    snap = serving_app.list_snapshots()[0]

    class FakePeer:
        def __init__(self, pid):
            self.id = pid
            self.sent = []
            self._lock = threading.Lock()

        def try_send(self, ch, msg):
            with self._lock:
                self.sent.append(msg)
            return True

        def responses(self):
            with self._lock:
                return list(self.sent)

    m = ss_metrics()
    base_refused = sum(m.serve_refused.value(reason=r)
                      for r in ("busy", "ratelimit"))
    base_served = m.chunks_served.value()
    reactor = StateSyncReactor(serving_app, serve_rate_per_s=50.0,
                               serve_burst=4, serve_queue=8)
    reactor.start()
    try:
        flooder = FakePeer("flooder")
        req = ChunkRequest(snap.height, snap.format, 0)
        from tendermint_tpu.statesync.reactor import (CHUNK_CHANNEL,
                                                      encode_msg)
        raw = encode_msg(req)
        for _ in range(64):
            reactor.receive(CHUNK_CHANNEL, flooder, raw)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            rs = flooder.responses()
            if len(rs) >= 64:
                break
            time.sleep(0.02)
        rs = flooder.responses()
        refused = [r for r in rs if r.busy]
        served = [r for r in rs if not r.busy and not r.missing]
        assert refused, "flood was never refused"
        assert all(r.retry_after_ms > 0 for r in refused)
        assert served, "polite share was never served"
        assert sum(m.serve_refused.value(reason=r)
                   for r in ("busy", "ratelimit")) > base_refused
        assert m.chunks_served.value() > base_served

        # a SECOND peer is not starved by the flooder's bucket
        polite = FakePeer("polite")
        reactor.receive(CHUNK_CHANNEL, polite, raw)
        deadline = time.monotonic() + 5.0
        while not polite.responses() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert polite.responses() and not polite.responses()[0].busy

        # chaos: serve raise answers busy (reason=error), server lives
        base_err = m.serve_refused.value(reason="error")
        fail.set_mode("statesync.serve", "raise")
        try:
            chaotic = FakePeer("chaotic")
            reactor.receive(CHUNK_CHANNEL, chaotic, raw)
            deadline = time.monotonic() + 5.0
            while not chaotic.responses() and \
                    time.monotonic() < deadline:
                time.sleep(0.02)
            assert fail.fired("statesync.serve", "raise") >= 1
            assert chaotic.responses() and chaotic.responses()[0].busy
            assert m.serve_refused.value(reason="error") > base_err
        finally:
            fail.clear()
        # latency at the serve seam: absorbed, still served
        fail.set_mode("statesync.serve", "latency:30")
        try:
            lagged = FakePeer("lagged")
            reactor.receive(CHUNK_CHANNEL, lagged, raw)
            deadline = time.monotonic() + 5.0
            while not lagged.responses() and \
                    time.monotonic() < deadline:
                time.sleep(0.02)
            assert fail.fired("statesync.serve", "latency:30") >= 1
            assert lagged.responses() and not lagged.responses()[0].busy
        finally:
            fail.clear()
    finally:
        reactor.stop()


def test_statesync_config_roundtrip_env_and_wiring(tmp_path):
    """[statesync] knobs: TOML round-trip, validate_basic, and
    config-wins-over-env in BOTH directions (module resolution)."""
    from tendermint_tpu.config.config import Config
    from tendermint_tpu.statesync import reactor as ssreactor

    cfg = Config(home=str(tmp_path))
    cfg.state_sync.fetchers = 7
    cfg.state_sync.chunk_timeout_ms = 2500.0
    cfg.state_sync.retries = 5
    cfg.state_sync.serve_rate_per_s = 42.5
    cfg.state_sync.serve_burst = 9
    cfg.slo.statesync_p99_ms = 123.0
    cfg.save()
    back = Config.load(str(tmp_path))
    assert back.state_sync.fetchers == 7
    assert back.state_sync.chunk_timeout_ms == 2500.0
    assert back.state_sync.retries == 5
    assert back.state_sync.serve_rate_per_s == 42.5
    assert back.state_sync.serve_burst == 9
    assert back.slo.statesync_p99_ms == 123.0
    back.validate_basic()
    for mutate in (lambda c: setattr(c.state_sync, "fetchers", 0),
                   lambda c: setattr(c.state_sync, "chunk_timeout_ms", 0),
                   lambda c: setattr(c.state_sync, "retries", 0),
                   lambda c: setattr(c.state_sync, "serve_rate_per_s", -1),
                   lambda c: setattr(c.state_sync, "serve_burst", 0)):
        bad = Config.load(str(tmp_path))
        mutate(bad)
        with pytest.raises(ValueError, match="state_sync"):
            bad.validate_basic()

    # env is the node-less default; set_config (and explicit Syncer
    # args, which is how the node wires [statesync]) wins BOTH ways
    os.environ["TM_TPU_SS_FETCHERS"] = "11"
    os.environ["TM_TPU_SS_SERVE_RATE"] = "9.5"
    try:
        assert ssync.default_fetchers() == 11
        assert ssreactor.default_serve_rate_per_s() == 9.5
        ssync.set_config(fetchers=2)
        assert ssync.default_fetchers() == 2      # config beats env
        ssync.set_config(fetchers=None)
        assert ssync.default_fetchers() == 11     # back to env
        s = Syncer(object(), object(), lambda *a: None, fetchers=3)
        assert s._fetchers() == 3                 # explicit arg beats all
        s2 = Syncer(object(), object(), lambda *a: None)
        assert s2._fetchers() == 11
    finally:
        del os.environ["TM_TPU_SS_FETCHERS"]
        del os.environ["TM_TPU_SS_SERVE_RATE"]
        ssync.set_config(fetchers=None)
    assert ssync.default_fetchers() == ssync.DEFAULT_FETCHERS


_RESUME_CHILD = r"""
REPO_DIR = @@REPO@@
import os, sys
sys.path.insert(0, REPO_DIR)
sys.path.insert(0, os.path.join(REPO_DIR, "tests"))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["TM_TPU_DISABLE_BATCH"] = "1"

from test_statesync import _served_chain, _light_sp
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.libs.kvdb import SQLiteDB
from tendermint_tpu.statesync.ledger import RestoreLedger
from tendermint_tpu.statesync.syncer import Syncer

home, kill_after = sys.argv[1], int(sys.argv[2])
gdoc, privs, serving_app, blocks, commits, states, lbs = \
    _served_chain(chunk_size=32)

# die IMMEDIATELY after the kill_after-th chunk lands in the ledger:
# the process vanishes mid-restore, no flush, no close
puts = {"n": 0}
orig = RestoreLedger.put_chunk
def dying(self, index, data):
    orig(self, index, data)
    puts["n"] += 1
    if puts["n"] == kill_after:
        os._exit(77)
RestoreLedger.put_chunk = dying

ledger = RestoreLedger(SQLiteDB(os.path.join(home, "statesync.db")),
                       group_every=2)
def fetch(snapshot, index, peer):
    return (serving_app.load_snapshot_chunk(
        snapshot.height, snapshot.format, index), peer)
syncer = Syncer(KVStoreApplication(), _light_sp(gdoc, lbs), fetch,
                fetchers=1, ledger=ledger)
best = [s for s in serving_app.list_snapshots() if s.height == 15][0]
syncer.add_snapshot(best, "peer1")
syncer.sync_any()
sys.exit(3)  # the kill should have fired mid-restore
"""


def test_crash_resume_os_exit_mid_restore(tmp_path):
    """Child process really dies (os._exit) mid-restore; the parent
    reopens the SQLite-backed ledger, finds the durable verified
    prefix (manifest + chunks), and a fresh sync resumes from the
    frontier — refetching ONLY the gap — to the exact app state.
    Host-only by construction: the restore path launches no device
    kernels, so no new XLA shapes compile (the nb=64 discipline)."""
    home = str(tmp_path)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    kill_after = 4
    r = subprocess.run(
        [sys.executable, "-c",
         _RESUME_CHILD.replace("@@REPO@@", repr(REPO)), home,
         str(kill_after)],
        env=env, capture_output=True, timeout=180)
    assert r.returncode == 77, (
        f"child rc={r.returncode}\n"
        f"stderr: {r.stderr[-2000:].decode(errors='replace')}")

    # parent: rebuild the identical chain (helpers are deterministic)
    gdoc, privs, serving_app, blocks, commits, states, lbs = \
        _served_chain(chunk_size=32)
    target = [s for s in serving_app.list_snapshots()
              if s.height == 15][0]
    ledger = RestoreLedger(SQLiteDB(os.path.join(home, "statesync.db")),
                           group_every=2)
    man = ledger.manifest()
    assert man is not None and man["height"] == 15, man
    stored = ledger.begin(target)
    # group_every=2, killed after put #4: exactly the committed groups
    # are durable (the open group may be lost, never half-landed)
    assert 2 <= len(stored) <= 4, sorted(stored)
    digests = integrity.parse_chunk_metadata(target.metadata,
                                             target.chunks)
    assert sorted(stored) == integrity.verify_chunks(digests, stored)

    fetched = []

    def fetch(snapshot, index, peer):
        fetched.append(index)
        return _chunk_of(serving_app, snapshot, index), peer

    fresh_app = KVStoreApplication()
    syncer = Syncer(fresh_app, _light_sp(gdoc, lbs), fetch,
                    fetchers=2, ledger=ledger)
    syncer.add_snapshot(target, "peer1")
    state, commit = syncer.sync_any()
    assert state.last_block_height == 15
    assert fresh_app.height == 15
    assert fresh_app.data == {k: v for k, v in serving_app.data.items()
                              if int(k[1:]) <= 15}
    assert state.app_hash == states[14].app_hash
    # the frontier resumed: only the gap was refetched
    assert len(set(fetched)) == target.chunks - len(stored), \
        (sorted(set(fetched)), target.chunks, sorted(stored))
    assert syncer.last_restore["resumed"] == len(stored)
    ledger.close()


def test_statesync_fresh_join_scenario():
    """ADR-022 NetHarness acceptance: a fresh node statesyncs from a
    LIVE committing net under a corrupt provider, a serving-validator
    kill mid-stream, and a chunk-request flood — zero invariant
    violations, the joiner restores from a snapshot (no block 1) and
    follows, the flood is refused.  Host-only verify (4-lane batches
    under the tpu threshold): no XLA shapes."""
    from tendermint_tpu.networks import scenarios
    from tendermint_tpu.networks.harness import NetHarness

    res = NetHarness.run(scenarios.by_name("statesync_fresh_join"),
                         seed=7)
    assert res["ctx"]["serve_refusals"] >= 1
    assert not res["violations"], res["violations"]
    joiner = f"node{res['ctx']['joiner']}"
    assert res["heights"][joiner] >= 2


# ---------------------------------------------------------------------------
# review hardening regressions (ADR-022): metadata-keyed snapshot
# identity, sender-matched response routing, slow-burst epochs,
# busy-forever bound, stop interruption
# ---------------------------------------------------------------------------

def test_poisoned_metadata_cannot_frame_honest_providers():
    """A Byzantine FIRST advertiser attaching a self-consistent but
    wrong digest list to the real (height, format, hash) must not
    poison the snapshot entry honest providers advertise: metadata is
    part of the snapshot identity, so the poisoned advertisement is a
    DIFFERENT snapshot that fails alone while the honest one
    restores — and no honest peer is banned for 'corrupt' chunks."""
    gdoc, privs, serving_app, blocks, commits, states, lbs = _served_chain()
    target = [s for s in serving_app.list_snapshots()
              if s.height == 15][0]
    # crafted metadata: digests of garbage, CORRECTLY rooted — it
    # parses, it is self-consistent, it is simply a lie
    fake_meta = integrity.make_chunk_metadata(
        [b"garbage-%d" % i for i in range(target.chunks)])
    poisoned = abci.Snapshot(target.height, target.format, target.chunks,
                             target.hash, fake_meta)
    banned = []

    def fetch(snapshot, index, peer):
        return _chunk_of(serving_app, snapshot, index), peer

    fresh_app = KVStoreApplication()
    syncer = Syncer(fresh_app, _light_sp(gdoc, lbs), fetch,
                    ban_peer=lambda p, r: banned.append(p),
                    fetchers=2, retries=1)
    syncer.add_snapshot(poisoned, "evil")      # evil advertises FIRST
    syncer.add_snapshot(target, "honest1")
    syncer.add_snapshot(target, "honest2")
    state, _ = syncer.sync_any()
    assert state.last_block_height == 15
    assert fresh_app.height == 15
    # the honest providers were never framed by the poisoned digests
    assert "honest1" not in banned and "honest2" not in banned


def test_spoofed_chunk_response_cannot_satisfy_honest_request():
    """Response routing is keyed by SENDER: a Byzantine peer spamming
    missing=True responses must not settle (or fail) a fetch addressed
    to a different peer."""
    from tendermint_tpu.statesync.reactor import (ChunkResponse,
                                                  StateSyncReactor)

    _, _, serving_app, _, _, _, _ = _served_chain()
    snap = serving_app.list_snapshots()[0]
    body = _chunk_of(serving_app, snap, 0)

    class FakePeer:
        def __init__(self, pid):
            self.id = pid

        def try_send(self, ch, msg):
            return True

    class FakeSwitch:
        def __init__(self, peers):
            self.peers = {p.id: p for p in peers}

    honest, spoofer = FakePeer("honest"), FakePeer("spoofer")
    reactor = StateSyncReactor(serving_app, chunk_timeout_s=1.5)
    reactor.switch = FakeSwitch([honest, spoofer])

    result = {}

    def fetchit():
        try:
            result["r"] = reactor._fetch_chunk(snap, 0, "honest")
        except Exception as e:  # noqa: BLE001 - asserted below
            result["err"] = e

    t = threading.Thread(target=fetchit, daemon=True)
    t.start()
    time.sleep(0.1)
    # the spoofer races in a missing=True for the same chunk ...
    reactor.receive.__func__  # (direct internal delivery below)
    with reactor._chunks_cv:
        reactor._chunks[(snap.height, snap.format, 0, "spoofer")] = \
            ChunkResponse(snap.height, snap.format, 0, b"", missing=True)
        reactor._chunks_cv.notify_all()
    time.sleep(0.2)
    assert "err" not in result and "r" not in result, result
    # ... and only the HONEST peer's real response satisfies the fetch
    with reactor._chunks_cv:
        reactor._chunks[(snap.height, snap.format, 0, "honest")] = \
            ChunkResponse(snap.height, snap.format, 0, body)
        reactor._chunks_cv.notify_all()
    t.join(timeout=3.0)
    assert result.get("r") == (body, "honest"), result


def test_peer_book_slow_burst_is_one_epoch():
    """N concurrent fetches stalling together earn ONE slow strike,
    not N (the same epoch guard as transport failures)."""
    from tendermint_tpu.statesync.syncer import _PeerBook

    book = _PeerBook(["a"], retries=2)
    t0 = time.monotonic()
    for _ in range(5):
        book.slow("a", t0)   # one burst: all started before the strike
    assert book.dead_peers() == []
    book.slow("a", time.monotonic())   # a NEW epoch strikes again
    book.slow("a", time.monotonic())   # third epoch: budget exhausted
    assert book.dead_peers() == ["a"]


def test_always_busy_provider_aborts_instead_of_hanging():
    """A provider that answers busy FOREVER must not hang sync_any:
    every BUSY_STRIKES_AFTER consecutive busies convert into a strike
    until the budget exhausts and the restore aborts."""
    from tendermint_tpu.statesync.syncer import _PeerBook

    gdoc, privs, serving_app, blocks, commits, states, lbs = _served_chain()

    def busy_fetch(snapshot, index, peer):
        raise ChunkBusy("permanently saturated", retry_after_s=0.005)

    syncer = Syncer(KVStoreApplication(), _light_sp(gdoc, lbs),
                    busy_fetch, fetchers=2, retries=1)
    target = [s for s in serving_app.list_snapshots()
              if s.height == 15][0]
    syncer.add_snapshot(target, "loaded")
    t0 = time.monotonic()
    with pytest.raises(StateSyncError):
        syncer.sync_any()
    # bounded: (retries+1) * BUSY_STRIKES_AFTER busies at tiny
    # retry-after + backoffs — well under a minute, not forever
    assert time.monotonic() - t0 < 60.0
    assert _PeerBook.BUSY_STRIKES_AFTER >= 2  # contract the bound rests on


def test_stop_event_interrupts_inflight_restore():
    """Node.stop must not wait behind a wedged fetch plane: setting
    the syncer's stop_event aborts the in-flight restore promptly
    (ledger kept — the next process resumes)."""
    gdoc, privs, serving_app, blocks, commits, states, lbs = _served_chain()
    stop = threading.Event()

    def stalling_fetch(snapshot, index, peer):
        if index > 0:
            time.sleep(0.15)   # a slow transport, not a dead one
        return _chunk_of(serving_app, snapshot, index), peer

    syncer = Syncer(KVStoreApplication(), _light_sp(gdoc, lbs),
                    stalling_fetch, fetchers=1, stop_event=stop)
    for s in serving_app.list_snapshots():
        syncer.add_snapshot(s, "peer1")
    threading.Timer(0.1, stop.set).start()
    t0 = time.monotonic()
    with pytest.raises(StateSyncError):
        syncer.sync_any()
    assert time.monotonic() - t0 < 5.0


def test_unawaited_chunk_responses_are_dropped():
    """receive() stores ONLY responses some fetcher is awaiting: an
    unawaited response is stale or spam either way, so the response
    map is bounded by the fetcher count, not by remote input — and a
    response flood cannot evict an honest in-flight response."""
    from tendermint_tpu.statesync.reactor import (CHUNK_CHANNEL,
                                                  ChunkResponse,
                                                  StateSyncReactor,
                                                  encode_msg)

    _, _, serving_app, _, _, _, _ = _served_chain()
    snap = serving_app.list_snapshots()[0]

    class FakePeer:
        def __init__(self, pid):
            self.id = pid

        def try_send(self, ch, msg):
            return True

    reactor = StateSyncReactor(serving_app)
    spammer = FakePeer("spammer")
    for i in range(200):
        reactor.receive(CHUNK_CHANNEL, spammer, encode_msg(
            ChunkResponse(snap.height, snap.format, i % 8, b"junk")))
    assert reactor._chunks == {}
    # an awaited key IS stored
    key = (snap.height, snap.format, 0, "spammer")
    with reactor._chunks_cv:
        reactor._awaited.add(key)
    reactor.receive(CHUNK_CHANNEL, spammer, encode_msg(
        ChunkResponse(snap.height, snap.format, 0, b"real")))
    assert key in reactor._chunks
