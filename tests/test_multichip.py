"""Multi-device sharding regression tests.

The driver validates multi-chip correctness by calling
__graft_entry__.dryrun_multichip(N) with N virtual CPU devices; these tests
pin that path so it can never silently regress (VERDICT r1 item 1 — the r1
dryrun died on the environment's accelerator plugin before building a mesh).
"""
import os
import subprocess
import sys

import numpy as np
import jax
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_sharded_verifier_8dev_mesh():
    """In-proc: the sharded verifier runs over the 8-device CPU mesh the
    conftest forces, with a corrupted lane localized correctly."""
    sys.path.insert(0, REPO)
    import __graft_entry__ as g
    from tendermint_tpu.parallel import sharding

    devices = jax.devices("cpu")
    assert len(devices) >= 8, devices
    mesh = sharding.make_mesh(devices[:8])
    dev = g._example_batch(32)
    _, run = sharding.make_sharded_verifier(mesh)
    bitmap = run(dev)
    assert bitmap.shape == (32,) and bitmap.all()

    bad = dict(dev)
    r = np.array(bad["r"], copy=True)
    r[3, 0] ^= 1
    bad["r"] = r
    bitmap = run(bad)
    assert not bitmap[3]
    assert bitmap[:3].all() and bitmap[4:].all()


def test_sharded_verifier_unaligned_batch():
    """Batch size not divisible by the mesh: padding must not corrupt the
    returned bitmap slice."""
    sys.path.insert(0, REPO)
    import __graft_entry__ as g
    from tendermint_tpu.parallel import sharding

    mesh = sharding.make_mesh(jax.devices("cpu")[:8])
    dev = g._example_batch(13)
    _, run = sharding.make_sharded_verifier(mesh)
    bitmap = run(dev)
    assert bitmap.shape == (13,) and bitmap.all()


@pytest.mark.slow
def test_dryrun_multichip_subprocess_hermetic():
    """The driver-facing entry must succeed from a hostile parent env
    (simulate the tunneled-TPU env by setting JAX_PLATFORMS to a bogus
    platform: the subprocess re-exec must override it)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "nonexistent_backend"
    env.pop("_TM_TPU_DRYRUN_INPROC", None)
    code = (f"import sys; sys.path.insert(0, {REPO!r}); "
            "import __graft_entry__ as g; g.dryrun_multichip(4)")
    r = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "sharded verify OK" in r.stdout


def test_batch_verifier_uses_mesh_data_plane(monkeypatch):
    """The PRODUCTION BatchVerifier must produce the identical bitmap
    through the mesh data plane on a multi-device host (VERDICT r2 weak
    #3): same verify_batch seam the node's reactors call."""
    sys.path.insert(0, REPO)
    from tendermint_tpu.crypto import ed25519 as edkeys
    from tendermint_tpu.crypto.batch import BatchVerifier
    from tendermint_tpu.parallel import sharding

    monkeypatch.setenv("TM_TPU_FORCE_BATCH", "1")
    plane = sharding.data_plane()
    assert plane is not None and plane.nshard >= 8

    items = []
    for i in range(19):  # deliberately not a multiple of the mesh
        k = edkeys.PrivKey((0x5100 + i).to_bytes(32, "big"))
        m = b"mesh bv %d" % i
        sig = k.sign(m)
        if i in (4, 11):
            sig = bytes([sig[0] ^ 1]) + sig[1:]
        items.append((k.pub_key(), m, sig))

    bv = BatchVerifier(tpu_threshold=1)
    for pub, m, sig in items:
        bv.add(pub, m, sig)
    all_ok, bits = bv.verify()
    assert not all_ok
    want = np.ones(19, dtype=bool)
    want[[4, 11]] = False
    assert (bits == want).all(), bits

    # oracle: identical bitmap from the forced single-device path
    monkeypatch.setenv("TM_TPU_NO_MESH", "1")
    sharding._PLANE = None
    try:
        assert sharding.data_plane() is None
        bv2 = BatchVerifier(tpu_threshold=1)
        for pub, m, sig in items:
            bv2.add(pub, m, sig)
        _, bits2 = bv2.verify()
        assert (bits2 == want).all(), bits2
    finally:
        sharding._PLANE = None
