"""Multi-device sharding regression tests.

The driver validates multi-chip correctness by calling
__graft_entry__.dryrun_multichip(N) with N virtual CPU devices; these tests
pin that path so it can never silently regress (VERDICT r1 item 1 — the r1
dryrun died on the environment's accelerator plugin before building a mesh).
"""
import os
import subprocess
import sys

import numpy as np
import jax
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_sharded_verifier_8dev_mesh():
    """In-proc: the sharded verifier runs over the 8-device CPU mesh the
    conftest forces, with a corrupted lane localized correctly."""
    sys.path.insert(0, REPO)
    import __graft_entry__ as g
    from tendermint_tpu.parallel import sharding

    devices = jax.devices("cpu")
    assert len(devices) >= 8, devices
    mesh = sharding.make_mesh(devices[:8])
    dev = g._example_batch(32)
    _, run = sharding.make_sharded_verifier(mesh)
    bitmap = run(dev)
    assert bitmap.shape == (32,) and bitmap.all()

    bad = dict(dev)
    r = np.array(bad["r"], copy=True)
    r[3, 0] ^= 1
    bad["r"] = r
    bitmap = run(bad)
    assert not bitmap[3]
    assert bitmap[:3].all() and bitmap[4:].all()


def test_sharded_verifier_unaligned_batch():
    """Batch size not divisible by the mesh: padding must not corrupt the
    returned bitmap slice."""
    sys.path.insert(0, REPO)
    import __graft_entry__ as g
    from tendermint_tpu.parallel import sharding

    mesh = sharding.make_mesh(jax.devices("cpu")[:8])
    dev = g._example_batch(13)
    _, run = sharding.make_sharded_verifier(mesh)
    bitmap = run(dev)
    assert bitmap.shape == (13,) and bitmap.all()


def _rlc_batch(n, tag=b""):
    """Deterministic valid batch via the pure-Python signer (no RNG)."""
    from tendermint_tpu.crypto import _edref

    seeds = [(0x7100 + i).to_bytes(32, "little") for i in range(n)]
    msgs = [b"rlc mesh %d " % i + tag for i in range(n)]
    pubs = [_edref.pubkey_from_seed(s) for s in seeds]
    sigs = [_edref.sign(s, m) for s, m in zip(seeds, msgs)]
    return pubs, msgs, sigs


def _fixed_z(n):
    import numpy as np
    rng = np.random.default_rng(20260803)
    return rng.integers(0, 256, size=(n, 16), dtype=np.uint8)


def test_msm_sharding_policy_and_bucket():
    """worth_sharding_msm is a bucket-memory/scan-depth policy, not a
    lane count: tiny per-shard rows are declined (the Poisson tail
    dominates T and every shard would scan nearly as many layers as one
    device), larger ones accepted; msm_bucket always divides evenly."""
    sys.path.insert(0, REPO)
    from tendermint_tpu.parallel import sharding

    plane = sharding.data_plane()
    assert plane is not None and plane.nshard >= 2
    assert not plane.worth_sharding_msm(8)
    # below one MSM_MIN_PER_SHARD row block per shard: always declined
    assert not plane.worth_sharding_msm(
        plane.MSM_MIN_PER_SHARD * plane.nshard - plane.nshard)
    assert plane.worth_sharding_msm(1024)
    assert plane.worth_sharding_msm(100_000)
    for n in (50, 256, 1000, 4096):
        nb = plane.msm_bucket(n)
        assert nb >= n and nb % plane.nshard == 0, (n, nb)


def test_rlc_sharded_verdict_matches_single_and_host_oracle(monkeypatch):
    """The mesh-sharded RLC/MSM (per-shard partial Pippenger sums,
    on-mesh reduction, psum'd verdict flags) must agree bitwise with the
    single-device RLC path — same injected z, same coefficient order —
    and with the per-sig host oracle, on valid AND adversarial batches.
    Runs at the nb=64 compile bucket (the policy itself is unit-tested
    above; forcing the shard route here keeps the XLA compile budget to
    one extra sharded program)."""
    sys.path.insert(0, REPO)
    import numpy as np

    from tendermint_tpu.crypto import _edref
    from tendermint_tpu.ops import msm
    from tendermint_tpu.parallel import sharding

    plane = sharding.data_plane()
    assert plane is not None and plane.nshard >= 2
    monkeypatch.setattr(plane, "worth_sharding_msm", lambda n: True)

    n = 50
    pubs, msgs, sigs = _rlc_batch(n)
    z = _fixed_z(n)
    assert msm.verify_batch_rlc(pubs, msgs, sigs, plane=plane, z=z) is True
    route = msm.last_route()
    assert route["path"] == "rlc-sharded" and \
        route["shards"] == plane.nshard, route
    assert msm.verify_batch_rlc(pubs, msgs, sigs, z=z) is True
    assert msm.last_route()["path"] == "rlc-single"
    assert all(_edref.verify(bytes(pubs[i]), msgs[i], sigs[i])
               for i in range(n))

    # adversarial classes: each must fail BOTH paths (and the host
    # oracle rejects the touched lane)
    tampered = [bytearray(s) for s in sigs]
    tampered[7][3] ^= 1
    swapped = list(sigs)
    swapped[1], swapped[2] = swapped[2], swapped[1]
    variants = [
        (pubs, msgs, [bytes(b) for b in tampered]),
        (pubs, [b"evil" if i == 0 else m for i, m in enumerate(msgs)],
         sigs),
        ([pubs[1] if i == 3 else p for i, p in enumerate(pubs)], msgs,
         sigs),
        (pubs, msgs, swapped),  # valid sigs, wrong lanes
    ]
    for vp, vm, vs in variants:
        assert msm.verify_batch_rlc(vp, vm, vs, plane=plane, z=z) is False
        assert msm.verify_batch_rlc(vp, vm, vs, z=z) is False

    # window sums: identical GROUP elements (affine compare — the
    # projective representatives legitimately differ with the addition
    # order) between one-device and mesh at the same staged scalars
    from tendermint_tpu.ops import curve as C
    from tendermint_tpu.ops import ed25519 as edops
    from tendermint_tpu.ops import field as F
    import jax.numpy as jnp

    pub_m = edops._to_u8_matrix(pubs, 32)
    r_bytes, zk, z2, zs = msm._stage_rlc(pub_m, msgs, sigs, z=z)
    nb = plane.msm_bucket(n)
    r_p, pub_p, zk_p, z_p = msm._pad_rows(r_bytes, pub_m, zk, z2, nb)
    c = msm._pick_c(nb)
    ws1, ok1, ov1 = msm._msm_core(
        jnp.asarray(r_p), jnp.asarray(pub_p), jnp.asarray(zk_p),
        jnp.asarray(z_p), jnp.asarray(zs), c)
    ws8, ok8, ov8 = plane.msm_window_sums(r_p, pub_p, zk_p, z_p, zs, c)
    assert bool(ok1) and bool(ok8) and not bool(ov1) and not bool(ov8)
    w1, w8 = np.asarray(ws1), np.asarray(ws8)

    def aff(ws, w):
        X = F.limbs_to_int(ws[0, :, w]) % C.P
        Y = F.limbs_to_int(ws[1, :, w]) % C.P
        Z = F.limbs_to_int(ws[2, :, w]) % C.P
        zi = pow(Z, C.P - 2, C.P)
        return (X * zi % C.P, Y * zi % C.P)

    for w in range(w1.shape[2]):
        assert aff(w1, w) == aff(w8, w), w


def test_verify_batch_seam_routes_rlc_through_mesh(monkeypatch):
    """The production ops/ed25519.verify_batch seam: the data plane is
    consulted FIRST and an opted-in RLC batch dispatches through it
    (sharded MSM); an invalid batch falls back through the plane's
    per-sig ladder with an EXACT bitmap."""
    sys.path.insert(0, REPO)
    import numpy as np

    from tendermint_tpu.ops import ed25519 as edops
    from tendermint_tpu.ops import msm
    from tendermint_tpu.parallel import sharding

    plane = sharding.data_plane()
    assert plane is not None and plane.nshard >= 2
    monkeypatch.setattr(plane, "worth_sharding_msm", lambda n: True)
    monkeypatch.setattr(msm, "_enabled_override", None)
    monkeypatch.setenv("TM_TPU_RLC", "1")
    monkeypatch.setenv("TM_TPU_RLC_MIN", "16")

    n = 50
    pubs, msgs, sigs = _rlc_batch(n, tag=b"seam")
    out = edops.verify_batch(pubs, msgs, sigs)
    assert out.shape == (n,) and out.all()
    route = msm.last_route()
    assert route["path"] == "rlc-sharded" and \
        route["shards"] == plane.nshard, route

    bad = [bytearray(s) for s in sigs]
    bad[11][5] ^= 0x40
    out = edops.verify_batch(pubs, msgs, [bytes(b) for b in bad])
    want = np.ones(n, dtype=bool)
    want[11] = False
    assert (out == want).all(), out


@pytest.mark.slow
def test_dryrun_multichip_subprocess_hermetic():
    """The driver-facing entry must succeed from a hostile parent env
    (simulate the tunneled-TPU env by setting JAX_PLATFORMS to a bogus
    platform: the subprocess re-exec must override it)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "nonexistent_backend"
    env.pop("_TM_TPU_DRYRUN_INPROC", None)
    code = (f"import sys; sys.path.insert(0, {REPO!r}); "
            "import __graft_entry__ as g; g.dryrun_multichip(4)")
    r = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "sharded verify OK" in r.stdout
    # the capture must say which verify path ran (per-sig vs RLC) and
    # that the RLC batch actually took the mesh-sharded MSM
    assert "path=rlc-sharded" in r.stdout, r.stdout


def test_batch_verifier_uses_mesh_data_plane(monkeypatch):
    """The PRODUCTION BatchVerifier must produce the identical bitmap
    through the mesh data plane on a multi-device host (VERDICT r2 weak
    #3): same verify_batch seam the node's reactors call."""
    sys.path.insert(0, REPO)
    from tendermint_tpu.crypto import ed25519 as edkeys
    from tendermint_tpu.crypto.batch import BatchVerifier
    from tendermint_tpu.parallel import sharding

    monkeypatch.setenv("TM_TPU_FORCE_BATCH", "1")
    plane = sharding.data_plane()
    assert plane is not None and plane.nshard >= 8

    items = []
    for i in range(19):  # deliberately not a multiple of the mesh
        k = edkeys.PrivKey((0x5100 + i).to_bytes(32, "big"))
        m = b"mesh bv %d" % i
        sig = k.sign(m)
        if i in (4, 11):
            sig = bytes([sig[0] ^ 1]) + sig[1:]
        items.append((k.pub_key(), m, sig))

    bv = BatchVerifier(tpu_threshold=1)
    for pub, m, sig in items:
        bv.add(pub, m, sig)
    all_ok, bits = bv.verify()
    assert not all_ok
    want = np.ones(19, dtype=bool)
    want[[4, 11]] = False
    assert (bits == want).all(), bits

    # oracle: identical bitmap from the forced single-device path
    monkeypatch.setenv("TM_TPU_NO_MESH", "1")
    sharding._PLANE = None
    try:
        assert sharding.data_plane() is None
        bv2 = BatchVerifier(tpu_threshold=1)
        for pub, m, sig in items:
            bv2.add(pub, m, sig)
        _, bits2 = bv2.verify()
        assert (bits2 == want).all(), bits2
    finally:
        sharding._PLANE = None
