"""tmlint: the tier-1 gate plus rule/sanitizer self-tests (ISSUE 6,
docs/adr/adr-014-tmlint.md).

Three layers:

  1. the gate — the static suite over the real tree must be clean
     against devtools/lint_baseline.json (which is empty: violations
     get fixed, not baselined), and docs/lint.md must be current;
  2. rule self-tests — every rule is exercised on small positive AND
     negative fixture snippets, so a rule regression (a pass that
     silently stops matching) fails loudly here, not months later;
  3. sanitizer proofs — the compile sentinel fails a deliberately
     bucket-violating launch record and passes the real nb=64 suite
     (tests/test_batch_verifier.py carries the fixture), and the
     lockset monitor detects a seeded inversion and runs green over a
     real scheduler round trip.
"""
from __future__ import annotations

import ast
import json
import os
import threading

import pytest

from tendermint_tpu.devtools import lockorder
from tendermint_tpu.devtools.tmlint import core
from tendermint_tpu.devtools.tmlint import passes_hygiene
from tendermint_tpu.devtools.tmlint import passes_locks
from tendermint_tpu.devtools.tmlint import passes_shape
from tendermint_tpu.devtools.tmlint.core import Corpus, SourceFile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def corpus_of(**files) -> Corpus:
    """Corpus from inline snippets; keys use __ for / (keyword-arg
    friendly) or pass a dict via files_."""
    c = Corpus(root="/nonexistent")
    for path, src in files.items():
        path = path.replace("__", "/")
        try:
            tree, err = ast.parse(src), None
        except SyntaxError as e:
            tree, err = None, str(e)
        c.files[path] = SourceFile(path, src, tree, err)
    return c


def hits(findings, rule, path=None):
    return [f for f in findings
            if f.rule == rule and (path is None or f.path == path)]


# ---------------------------------------------------------------------------
# 1. the gate
# ---------------------------------------------------------------------------

def test_tree_is_clean_against_baseline():
    """The tier-1 tmlint gate: zero unbaselined findings on the tree.
    THE static invariants — bucket discipline, lock order, daemon
    threads, optional deps, chaos/trace/metric registries — hold."""
    findings = core.run_lint(root=ROOT)
    baseline = core.load_baseline(
        os.path.join(ROOT, "devtools", "lint_baseline.json"))
    new = [f for f in findings if f.key() not in baseline]
    assert not new, "tmlint found unbaselined violations:\n" + \
        "\n".join(f.render() for f in new)
    stale = set(baseline) - {f.key() for f in findings}
    assert not stale, f"stale baseline entries: {sorted(stale)}"


def test_docs_lint_md_current():
    """scripts/metricsgen.py-style staleness gate for docs/lint.md."""
    with open(os.path.join(ROOT, "docs", "lint.md"),
              encoding="utf-8") as f:
        assert f.read() == core.generate_docs(), (
            "docs/lint.md is stale; run "
            "python -m tendermint_tpu.devtools.tmlint --docs")


def test_cli_json_and_report(tmp_path, capsys):
    """--json output is consumable by scripts/lint_report.py."""
    rc = core.main(["--json", "--baseline",
                    "devtools/lint_baseline.json"])
    out = capsys.readouterr().out
    data = json.loads(out)
    assert rc == 0 and data["new"] == []
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "lint_report", os.path.join(ROOT, "scripts", "lint_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    p = tmp_path / "lint.json"
    p.write_text(out)
    rc = mod.main([str(p)])
    rep = capsys.readouterr().out
    assert rc == 0 and "tmlint report" in rep


# ---------------------------------------------------------------------------
# 2. rule self-tests (positive fixture = detected, negative = clean)
# ---------------------------------------------------------------------------

def test_rule_tm101_raw_shape():
    bad = corpus_of(**{"tendermint_tpu/ops/fx.py": """
import jax, jax.numpy as jnp
verify_kernel = jax.jit(lambda x: x)
def route(xs):
    n = len(xs)
    buf = jnp.zeros(n)
    return verify_kernel(buf)
"""})
    f = hits(passes_shape.check(bad), "TM101")
    # two findings: the raw-sized constructor AND the tainted buffer
    # reaching the jit entry
    assert any("jnp.zeros" in x.msg for x in f)
    assert any("verify_kernel" in x.msg for x in f)

    good = corpus_of(**{"tendermint_tpu/ops/fx.py": """
import jax, jax.numpy as jnp
verify_kernel = jax.jit(lambda x: x)
def bucket_size(n):
    return max(64, 1 << (n - 1).bit_length())
def route(xs):
    n = len(xs)
    nb = bucket_size(n)
    buf = jnp.zeros(nb)
    return verify_kernel(buf)
"""})
    assert not hits(passes_shape.check(good), "TM101")


def test_rule_tm101_jit_entry_argument():
    bad = corpus_of(**{"tendermint_tpu/ops/fx.py": """
import jax
verify_kernel = jax.jit(lambda x: x)
def route(xs, arr):
    return verify_kernel(arr[:len(xs)])
"""})
    f = hits(passes_shape.check(bad), "TM101")
    assert len(f) == 1 and "verify_kernel" in f[0].msg
    # padding with a blessed width is the sanctioned idiom
    good = corpus_of(**{"tendermint_tpu/ops/fx.py": """
import jax
import numpy as np
verify_kernel = jax.jit(lambda x: x)
def route(xs, arr):
    n = len(xs)
    nb = bucket_size(n)
    arr = np.pad(arr, (0, nb - n))
    return verify_kernel(arr)
"""})
    assert not hits(passes_shape.check(good), "TM101")


def test_rule_tm102_uncached_jit():
    bad = corpus_of(**{"tendermint_tpu/ops/fx.py": """
import jax
def route(g, x):
    return jax.jit(g)(x)
"""})
    assert len(hits(passes_shape.check(bad), "TM102")) == 1
    good = corpus_of(**{"tendermint_tpu/ops/fx.py": """
import jax
class P:
    def fn(self, g, key):
        f = jax.jit(g)
        self._fns.setdefault(key, f)
        return self._fns[key]
"""})
    assert not hits(passes_shape.check(good), "TM102")


LOCK_FIXTURE = """
import threading
import time
_global_lock = threading.Lock()
class VerifyScheduler:
    def __init__(self):
        self._cond = threading.Condition()
    def bad_order(self):
        with self._cond:
            with _global_lock:
                pass
    def bad_block(self):
        with self._cond:
            time.sleep(0.1)
    def ok_wait(self):
        with self._cond:
            self._cond.wait(0.1)
"""


def test_rule_tm201_lock_order_inversion():
    """Seeded inversion: the fixture reuses the DECLARED ids
    (crypto/scheduler.py _cond rank 20, _global_lock rank 10), nested
    the wrong way round."""
    c = corpus_of(**{"tendermint_tpu__crypto__scheduler.py": LOCK_FIXTURE})
    f = hits(passes_locks.check(c), "TM201")
    assert len(f) == 1 and "_global_lock" in f[0].msg \
        and f[0].qual == "VerifyScheduler.bad_order"
    # error-recovery paths are NOT blind spots: the same inversion
    # nested only inside an except handler is still found
    only_except = corpus_of(**{"tendermint_tpu/crypto/scheduler.py": """
import threading
_global_lock = threading.Lock()
class VerifyScheduler:
    def __init__(self):
        self._cond = threading.Condition()
    def recover(self):
        with self._cond:
            try:
                pass
            except Exception:
                with _global_lock:
                    pass
"""})
    f2 = hits(passes_locks.check(only_except), "TM201")
    assert len(f2) == 1 and f2[0].qual == "VerifyScheduler.recover"


def test_rule_tm202_blocking_and_condition_wait():
    c = corpus_of(**{"tendermint_tpu__crypto__scheduler.py": LOCK_FIXTURE})
    f = hits(passes_locks.check(c), "TM202")
    # time.sleep under _cond flagged; _cond.wait under _cond is NOT
    assert len(f) == 1 and f[0].qual == "VerifyScheduler.bad_block"
    assert ".sleep()" in f[0].msg


def test_rule_tm203_tm204_table_parity():
    c = corpus_of(**{"tendermint_tpu/crypto/fx.py": """
import threading
_mystery_lock = threading.Lock()
"""})
    findings = passes_locks.check(c)
    f = hits(findings, "TM203")
    assert len(f) == 1 and "_mystery_lock" in f[0].msg
    # every declared id is absent from this tiny corpus -> TM204 keeps
    # the table honest in the other direction
    assert len(hits(findings, "TM204")) == len(lockorder.LOCK_ORDER)


def test_rule_tm301_thread_daemon():
    bad = corpus_of(**{"tendermint_tpu/libs/fx.py": """
import threading
def spawn():
    threading.Thread(target=print).start()
"""})
    assert len(hits(passes_hygiene.check(bad), "TM301")) == 1
    good = corpus_of(**{"tendermint_tpu/libs/fx.py": """
import threading
def spawn():
    threading.Thread(target=print, daemon=True).start()
def spawn_joined():
    ts = [threading.Thread(target=print)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
"""})
    assert not hits(passes_hygiene.check(good), "TM301")
    # a STRING join in the same function must not satisfy the
    # joined-by-creator exemption
    strjoin = corpus_of(**{"tendermint_tpu/libs/fx.py": """
import threading
def spawn(parts):
    label = ", ".join(parts)
    threading.Thread(target=print, name=label).start()
"""})
    assert len(hits(passes_hygiene.check(strjoin), "TM301")) == 1


def test_rule_tm302_optional_import():
    bad = corpus_of(**{"tendermint_tpu/libs/fx.py": "import grpc\n"})
    assert len(hits(passes_hygiene.check(bad), "TM302")) == 1
    good = corpus_of(**{"tendermint_tpu/libs/fx.py": """
try:
    import grpc
except ImportError:
    grpc = None
"""})
    assert not hits(passes_hygiene.check(good), "TM302")


def test_rule_tm303_backslash_fstring():
    """The py3.10 breakage class: backslash inside a replacement field.
    Detected from TOKENS — on 3.10 ast.parse refuses the file outright
    (which is also asserted: the snippet must stay a SyntaxError here,
    or this rule's motivation changed under our feet)."""
    src = 'x = 1\ny = f"{x\\t}"\n'
    found = passes_hygiene.find_fstring_backslashes(src)
    assert len(found) == 1 and found[0][0] == 2
    c = corpus_of(**{"tendermint_tpu/libs/fx.py": src})
    findings = passes_hygiene.check(c)
    assert len(hits(findings, "TM303")) == 1
    # literal-part escapes are FINE on 3.10 and must not be flagged
    ok = 'y = f"a\\n{x}b\\t"\nz = f"{{literal}}\\n"\n'
    assert not passes_hygiene.find_fstring_backslashes(ok)
    # and the rule reports where the interpreter would reject the file
    import sys
    if sys.version_info < (3, 12):
        with pytest.raises(SyntaxError):
            ast.parse(src)


def test_rule_tm304_except_pass():
    bad = corpus_of(**{"tendermint_tpu/ops/fx.py": """
def f():
    try:
        g()
    except Exception:
        pass
"""})
    assert len(hits(passes_hygiene.check(bad), "TM304")) == 1
    good = corpus_of(**{"tendermint_tpu/ops/fx.py": """
def f():
    try:
        g()
    except Exception:  # noqa: BLE001 - probe failure is not fatal
        pass
"""})
    assert not hits(passes_hygiene.check(good), "TM304")
    # outside the hot-path scope the rule does not apply
    elsewhere = corpus_of(**{"tendermint_tpu/rpc/fx.py": """
def f():
    try:
        g()
    except Exception:
        pass
"""})
    assert not hits(passes_hygiene.check(elsewhere), "TM304")


FAIL_REGISTRY = """
REGISTERED_SITES = frozenset({"good.site"})
DYNAMIC_SITE_PREFIXES = frozenset({"lane."})
"""


def test_rule_tm305_fail_sites():
    c = corpus_of(**{
        "tendermint_tpu__libs__fail.py": FAIL_REGISTRY,
        "tendermint_tpu__ops__fx.py": """
from tendermint_tpu.libs import fail
def f():
    fail.inject("bad.site")
    fail.inject("good.site")
    fail.inject("lane.anything")
    fail.inject(dynamic_name)
""",
    })
    f = hits(passes_hygiene.check(c), "TM305")
    assert len(f) == 1 and "bad.site" in f[0].msg


def test_rule_tm306_trace_spans():
    c = corpus_of(**{
        "tendermint_tpu__libs__trace.py":
            'KNOWN_SPANS = frozenset({"known.span"})\n',
        "tendermint_tpu__ops__fx.py": """
from tendermint_tpu.libs import trace
def f():
    with trace.span("known.span"):
        trace.instant("rogue.span")
""",
    })
    f = hits(passes_hygiene.check(c), "TM306")
    assert len(f) == 1 and "rogue.span" in f[0].msg


def test_rule_tm307_metric_attrs():
    c = corpus_of(**{
        "tendermint_tpu__libs__metrics.py": """
class CryptoMetrics:
    def __init__(self, reg):
        self.known_total = reg.counter("c", "known_total", "")
""",
        "tendermint_tpu__crypto__fx.py": """
def f(rt):
    rt.metrics.known_total.inc()
    rt.metrics.tyop_total.inc()
""",
    })
    f = hits(passes_hygiene.check(c), "TM307")
    assert len(f) == 1 and "tyop_total" in f[0].msg


# ---------------------------------------------------------------------------
# registries stay honest in BOTH directions
# ---------------------------------------------------------------------------

CHAOS_TEST_FILES = ("test_chaos_matrix.py", "test_comb.py",
                    "test_control.py", "test_degrade.py",
                    "test_devobs.py", "test_ingress.py",
                    "test_latency_observatory.py",
                    "test_light_serve.py", "test_mesh_sweep.py",
                    "test_netharness.py", "test_netobs.py",
                    "test_observatory.py",
                    "test_pipeline.py", "test_propose_fastpath.py",
                    "test_scheduler.py", "test_statesync.py")


def _armed_sites() -> set:
    """Every registered-site literal appearing in the chaos suites.
    Sites are armed either directly (fail.set_mode("ops...", mode)) or
    through parametrized case tables (the CASES tuples in
    test_chaos_matrix.py feed set_mode via a variable), so the honest
    static signal is: the literal site name occurs in the file at all —
    combined with the suites' own `fail.fired(site, mode) >= 1`
    assertions, which prove the injection actually triggered."""
    from tendermint_tpu.libs import fail

    armed = set()
    for name in CHAOS_TEST_FILES:
        with open(os.path.join(ROOT, "tests", name),
                  encoding="utf-8") as fh:
            tree = ast.parse(fh.read())
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    fail.is_registered(node.value) and \
                    node.value != "*":
                armed.add(node.value)
    return armed


def test_every_registered_chaos_site_is_exercised():
    """The coverage gate the registry exists for: each static inject
    site (ops.*) must be armed by a chaos test, and each dynamic lane
    family (batch./sched./bulk. — one shared degrade.submit seam per
    family) must have at least one armed member.  Chaos coverage can't
    silently rot when a new site is registered."""
    from tendermint_tpu.libs import fail

    armed = _armed_sites()
    static = {s for s in fail.REGISTERED_SITES
              if s.startswith(("ops.", "sharding."))}
    missing = static - armed
    assert not missing, (
        f"registered chaos sites never armed by {CHAOS_TEST_FILES}: "
        f"{sorted(missing)}")
    for prefix in fail.DYNAMIC_SITE_PREFIXES:
        assert any(s.startswith(prefix) for s in armed), (
            f"no chaos test arms any '{prefix}*' lane site")
    # registered non-ops sites either belong to a dynamic family or are
    # standalone literals (lanepool.verify) that must each be armed —
    # a literal site is its own family of one
    for s in fail.REGISTERED_SITES - static:
        if not any(s.startswith(p) for p in fail.DYNAMIC_SITE_PREFIXES):
            assert s in armed, (
                f"literal chaos site {s!r} never armed by "
                f"{CHAOS_TEST_FILES}")


def test_set_mode_refuses_unregistered_site():
    from tendermint_tpu.libs import fail

    with pytest.raises(ValueError, match="not registered"):
        fail.set_mode("definitely.not.registered", "raise")
    site = fail.register("tmlint.selftest.site")
    try:
        fail.set_mode(site, "raise")
        with pytest.raises(fail.InjectedFault):
            fail.inject(site)
    finally:
        fail.clear(site)


def test_known_spans_all_appear_in_tree():
    """Reverse direction of TM306: a KNOWN_SPANS name nothing emits is
    registry rot."""
    from tendermint_tpu.libs import trace

    corpus = core.load_corpus(ROOT)
    blob = "\n".join(f.src for f in corpus.files.values())
    dead = [s for s in trace.KNOWN_SPANS if f'"{s}"' not in blob]
    assert not dead, f"KNOWN_SPANS entries no call site emits: {dead}"


# ---------------------------------------------------------------------------
# 3. sanitizer proofs
# ---------------------------------------------------------------------------

def test_compile_sentinel_flags_foreign_bucket():
    """A launch bucket outside the known shape set must fail check().
    Seeded via the same _seen_buckets seam _record_launch feeds, so no
    XLA compile is spent proving it."""
    from tendermint_tpu.devtools.tmlint.runtime import CompileSentinel
    from tendermint_tpu.ops import ed25519 as edops

    s = CompileSentinel().start()
    key = ("tmlint-selftest", 100, 1)  # nb=100: not a bucket shape
    with edops._launch_lock:
        edops._seen_buckets.add(key)
    try:
        with pytest.raises(AssertionError, match="outside the known"):
            s.check()
    finally:
        with edops._launch_lock:
            edops._seen_buckets.discard(key)
    # nb=64 (the shared lane bucket) and chunk multiples pass
    assert CompileSentinel.bucket_allowed(64)
    assert CompileSentinel.bucket_allowed(edops.SPLIT_CHUNK * 7)
    assert CompileSentinel.bucket_allowed(edops.MAX_CHUNK * 2)
    assert not CompileSentinel.bucket_allowed(100)
    assert not CompileSentinel.bucket_allowed(0)


def test_compile_sentinel_counts_watched_entry_compiles():
    """Cache growth on a watched jit entry is counted, and
    max_new_compiles=0 turns it into a failure (the 'no new compile
    budget' contract tests opt into)."""
    import jax
    import jax.numpy as jnp

    from tendermint_tpu.devtools.tmlint.runtime import CompileSentinel

    probe = jax.jit(lambda x: x + 1)
    s = CompileSentinel(extra_entries=[("probe", probe)],
                        max_new_compiles=0).start()
    probe(jnp.ones(3))  # trivial host-CPU compile, milliseconds
    with pytest.raises(AssertionError, match="new kernel compile"):
        s.check()
    s2 = CompileSentinel(extra_entries=[("probe", probe)],
                         max_new_compiles=0).start()
    probe(jnp.ones(3))  # cache hit: same shape
    assert s2.check()["compiles"] == {}


def test_locksan_detects_seeded_inversion():
    from tendermint_tpu.devtools.tmlint.runtime import LockSanitizer

    san = LockSanitizer(include_paths=("tests/",),
                        rank_overrides={"tests/test_lint.py:lo": 10,
                                        "tests/test_lint.py:hi": 20})
    with san:
        lo = threading.Lock()
        hi = threading.Lock()
        with hi:
            with lo:  # rank 10 under rank 20: inversion
                pass
        with lo:
            with hi:  # declared order: clean
                pass
    assert len(san.violations) == 1
    assert "tests/test_lint.py:lo" in san.violations[0]
    assert ("tests/test_lint.py:hi", "tests/test_lint.py:lo") in san.edges


def test_locksan_condition_protocol():
    """A sanitized Condition (wrapped RLock underneath) must keep the
    full wait/notify protocol working, and wait() must not corrupt the
    held-set tracking."""
    from tendermint_tpu.devtools.tmlint.runtime import LockSanitizer

    san = LockSanitizer(include_paths=("tests/",))
    with san:
        cond = threading.Condition()
        fired = []

        def waiter():
            with cond:
                fired.append(cond.wait(timeout=5.0))

        t = threading.Thread(target=waiter, daemon=True)
        with cond:
            t.start()
        # let the waiter take the condition and park
        import time
        time.sleep(0.05)
        with cond:
            cond.notify_all()
        t.join(timeout=5.0)
    assert fired == [True]
    assert not san.violations


@pytest.mark.locksan
def test_locksan_green_on_real_scheduler_roundtrip():
    """The acceptance run, in-process: a fresh degradation runtime and
    VerifyScheduler built UNDER the monitor (so every lock they create
    is wrapped), driven through a real submit -> coalesce -> host-lane
    -> resolve round trip.  The declared order holds — this is the same
    check TM_TPU_LOCKSAN=1 applies to the whole suite (the locksan
    marker arms the conftest fixture, which fails the test on any
    recorded inversion)."""
    from tendermint_tpu.crypto import batch as cb
    from tendermint_tpu.crypto import degrade
    from tendermint_tpu.crypto import ed25519 as edkeys
    from tendermint_tpu.crypto import scheduler as vsched
    from tendermint_tpu.libs.metrics import Registry

    degrade.configure(registry=Registry("locksan"))
    try:
        s = vsched.VerifyScheduler(window_s=0.001, max_batch=64,
                                   tpu_threshold=1 << 30)
        s.start()
        try:
            privs = [edkeys.PrivKey(bytes([i + 1]) * 32)
                     for i in range(8)]
            items = [(p.pub_key(), b"locksan %d" % i, p.sign(
                b"locksan %d" % i)) for i, p in enumerate(privs)]
            fut = s.submit(items, vsched.Priority.CONSENSUS)
            bits = fut.result(timeout=30.0)
            assert bits.all()
            # shed path: metrics/trace settle OUTSIDE _cond now
            tiny = vsched.VerifyScheduler(window_s=5.0, max_batch=4,
                                          max_pending=4,
                                          tpu_threshold=1 << 30)
            tiny.start()
            try:
                f1 = tiny.submit(items[:4], vsched.Priority.MEMPOOL)
                f2 = tiny.submit(items[:4], vsched.Priority.MEMPOOL)
                with pytest.raises(vsched.SchedulerShedError):
                    f2.result(timeout=5.0)
                tiny.flush()
                assert f1.result(timeout=30.0).all()
            finally:
                tiny.stop()
        finally:
            s.stop()
    finally:
        degrade.reset()
        cb.verified_sigs = cb.SigCache()
