"""Structured leveled logging (reference libs/log/tm_logger.go): line
shape, levels, module overrides, lazy values, bound context."""
from __future__ import annotations

import io

from tendermint_tpu.libs import log as tmlog


def _fresh(level="info", modules=""):
    buf = io.StringIO()
    tmlog.setup(level=level, stream=buf, module_levels=modules)
    return buf


def test_line_shape_and_levels():
    buf = _fresh("info")
    log = tmlog.logger("consensus")
    log.info("entering new round", height=5, round=0)
    log.debug("invisible", x=1)
    log.error("boom", err="nope")
    lines = buf.getvalue().splitlines()
    assert len(lines) == 2
    assert lines[0].startswith("I[") and " consensus: " in lines[0]
    assert lines[0].endswith("entering new round height=5 round=0")
    assert lines[1].startswith("E[") and lines[1].endswith("boom err=nope")


def test_lazy_values_not_computed_below_level():
    buf = _fresh("info")
    calls = []

    def expensive():
        calls.append(1)
        return "h" * 8

    log = tmlog.logger("consensus")
    log.debug("block", hash=tmlog.Lazy(expensive))
    assert calls == []          # debug disabled: never computed
    log.info("block", hash=tmlog.Lazy(expensive))
    assert calls == [1]
    assert "hash=hhhhhhhh" in buf.getvalue()


def test_module_level_overrides():
    buf = _fresh("error", modules="p2p:debug")
    tmlog.logger("consensus").info("hidden")
    tmlog.logger("p2p").debug("visible", peer="ab")
    out = buf.getvalue()
    assert "hidden" not in out
    assert "visible peer=ab" in out


def test_bound_context_and_bytes_render():
    buf = _fresh("info")
    log = tmlog.logger("node").with_(moniker="n0")
    log.info("saved block", hash=b"\xab\xcd")
    assert "moniker=n0" in buf.getvalue()
    assert "hash=abcd" in buf.getvalue()


def test_logging_never_raises():
    buf = _fresh("info")

    def broken():
        raise RuntimeError("nope")

    tmlog.logger("x").info("ok", v=tmlog.Lazy(broken))
    assert "lazy error" in buf.getvalue()
