"""Native C verification lanes for secp256k1 (BIP-340) and sr25519
(schnorrkel) — differential-tested against the pure-Python
implementations (which are themselves vector-validated), including
corrupted signatures, wrong keys, and malformed inputs."""
from __future__ import annotations

import pytest

from tendermint_tpu.crypto import secp256k1 as secp
from tendermint_tpu.crypto import sr25519 as sr
from tendermint_tpu.libs import native

pytestmark = pytest.mark.skipif(native.get_lib() is None,
                                reason="no C toolchain")


def _cases(scheme, n=60):
    pubs, msgs, sigs = [], [], []
    for i in range(n):
        seed = (0xAB00 + i).to_bytes(32, "big")
        k = (secp.PrivKey.gen_from_secret(seed) if scheme == "secp"
             else sr.PrivKey(seed))
        m = b"%s diff %d" % (scheme.encode(), i * 7)
        s = bytearray(k.sign(m))
        if i % 3 == 1:
            s[(i * 5) % 64] ^= 1 << (i % 8)   # corrupt a random bit
        if i % 7 == 3:
            m = m + b"!"                       # verify different message
        pubs.append(k.pub_key())
        msgs.append(m)
        sigs.append(bytes(s))
    return pubs, msgs, sigs


@pytest.mark.parametrize("scheme,fn", [
    ("secp", native.secp_verify), ("sr", native.sr25519_verify)])
def test_differential_vs_python(scheme, fn):
    pubs, msgs, sigs = _cases(scheme)
    want = [p.verify_signature(m, s) for p, m, s in zip(pubs, msgs, sigs)]
    assert any(want) and not all(want)  # mix of valid and invalid
    got = fn([p.bytes() for p in pubs], msgs, sigs)
    assert got is not None
    assert list(got) == want


def test_batch_verifier_routes_host_schemes_through_native():
    from tendermint_tpu.crypto.batch import BatchVerifier, verified_sigs

    bv = BatchVerifier()
    want = []
    for i in range(12):
        seed = (0xCD00 + i).to_bytes(32, "big")
        k = secp.PrivKey.gen_from_secret(seed) if i % 2 \
            else sr.PrivKey(seed)
        m = b"route %d" % i
        s = bytearray(k.sign(m))
        ok = True
        if i in (3, 8):
            s[0] ^= 1
            ok = k.pub_key().verify_signature(m, bytes(s))
        # distinct messages: no SigCache interference
        assert not verified_sigs.hit(k.pub_key().bytes(), m, bytes(s)) \
            or ok
        bv.add(k.pub_key(), m, bytes(s))
        want.append(ok)
    all_ok, bits = bv.verify()
    assert list(bits) == want
    assert all_ok == all(want)


def test_malformed_lengths_fall_back_without_crash():
    # a 32-byte "secp pub" makes the packed array irregular: the native
    # path declines and the per-item Python path scores it False
    k = secp.PrivKey.gen_from_secret(b"\x55" * 32)
    m = b"malformed"
    sig = k.sign(m)
    from tendermint_tpu.crypto.batch import BatchVerifier

    class FakePub:
        type_name = "secp256k1"

        def bytes(self):
            return b"\x02" * 32  # wrong length

        def verify_signature(self, msg, s):
            return False

    bv = BatchVerifier()
    bv.add(k.pub_key(), m, sig)
    bv.add(FakePub(), m, sig)
    all_ok, bits = bv.verify()
    assert not all_ok and bool(bits[0]) and not bits[1]
