"""Native C verification lanes for secp256k1 (BIP-340) and sr25519
(schnorrkel) — differential-tested against the pure-Python
implementations (which are themselves vector-validated), including
corrupted signatures, wrong keys, and malformed inputs."""
from __future__ import annotations

import pytest

from tendermint_tpu.crypto import secp256k1 as secp
from tendermint_tpu.crypto import sr25519 as sr
from tendermint_tpu.libs import native

pytestmark = pytest.mark.skipif(native.get_lib() is None,
                                reason="no C toolchain")


def _cases(scheme, n=60):
    pubs, msgs, sigs = [], [], []
    for i in range(n):
        seed = (0xAB00 + i).to_bytes(32, "big")
        k = (secp.PrivKey.gen_from_secret(seed) if scheme == "secp"
             else sr.PrivKey(seed))
        m = b"%s diff %d" % (scheme.encode(), i * 7)
        s = bytearray(k.sign(m))
        if i % 3 == 1:
            s[(i * 5) % 64] ^= 1 << (i % 8)   # corrupt a random bit
        if i % 7 == 3:
            m = m + b"!"                       # verify different message
        pubs.append(k.pub_key())
        msgs.append(m)
        sigs.append(bytes(s))
    return pubs, msgs, sigs


@pytest.mark.parametrize("scheme,fn", [
    ("secp", native.secp_verify), ("sr", native.sr25519_verify)])
def test_differential_vs_python(scheme, fn):
    pubs, msgs, sigs = _cases(scheme)
    want = [p.verify_signature(m, s) for p, m, s in zip(pubs, msgs, sigs)]
    assert any(want) and not all(want)  # mix of valid and invalid
    got = fn([p.bytes() for p in pubs], msgs, sigs)
    assert got is not None
    assert list(got) == want


def _secp_adversarial_cases():
    """Structured invalid encodings: any C-vs-Python divergence here is
    consensus-relevant (ADVICE r3 #4).  BIP-340: sig = R_x(32,BE)||s(32,BE),
    pubkey = 33-byte compressed."""
    k = secp.PrivKey.gen_from_secret(b"\x77" * 32)
    pub = k.pub_key().bytes()
    m = b"structured secp"
    good = k.sign(m)
    r_good, s_good = good[:32], good[32:]

    def be(x):
        return x.to_bytes(32, "big")

    # an x-coordinate with no curve point: x^3+7 a quadratic non-residue
    x = 5
    while pow((pow(x, 3, secp.P) + 7) % secp.P,
              (secp.P - 1) // 2, secp.P) == 1:
        x += 1
    off_curve_x = be(x)

    cases = [
        (pub, m, r_good + be(secp.N)),           # s == group order
        (pub, m, r_good + be(secp.N + 1)),       # s > group order
        (pub, m, be(secp.P) + s_good),           # r == field prime
        (pub, m, be(secp.P + 1) + s_good),       # r > field prime
        (pub, m, off_curve_x + s_good),          # R_x off curve
        (pub, m, b"\x00" * 64),                  # all-zero signature
        (pub, m, r_good + be(0)),                # s == 0
        (b"\x02" + be(secp.P), m, good),         # pubkey x >= p
        (b"\x02" + off_curve_x, m, good),        # pubkey off curve
        (b"\x04" + pub[1:], m, good),            # bad parity byte
        (pub, m, good),                          # control: valid
    ]
    return cases


def _sr_adversarial_cases():
    """sr25519/schnorrkel: sig = R(32 ristretto)||s(32,LE, bit255 set as
    the schnorrkel marker), pubkey = 32-byte ristretto point."""
    k = sr.PrivKey(b"\x66" * 32)
    pub = k.pub_key().bytes()
    m = b"structured sr"
    good = k.sign(m)
    r_good, s_good = good[:32], good[32:]
    L = 2**252 + 27742317777372353535851937790883648493

    def le_marked(x, marker=True):
        b = bytearray(x.to_bytes(32, "little"))
        if marker:
            b[31] |= 0x80
        return bytes(b)

    cases = [
        (pub, m, r_good + bytes(s_good[:31]) + bytes([s_good[31] & 0x7F])),
        # ^ marker bit cleared (schnorrkel rejects pre-marker encodings)
        (pub, m, r_good + le_marked(L)),         # s == group order
        (pub, m, r_good + le_marked(L + 5)),     # s > group order
        (pub, m, b"\x00" * 32 + s_good),         # R = identity (low order)
        (pub, m, b"\xFF" * 32 + s_good),         # R non-canonical encoding
        (pub, m, b"\x00" * 64),                  # all-zero signature
        (pub, m, r_good + le_marked(0)),         # s == 0 (with marker)
        (b"\x00" * 32, m, good),                 # identity pubkey
        (b"\xFF" * 32, m, good),                 # non-canonical pubkey
        (pub, m, good),                          # control: valid
    ]
    return cases


@pytest.mark.parametrize("cases_fn,fn", [
    (_secp_adversarial_cases, native.secp_verify),
    (_sr_adversarial_cases, native.sr25519_verify)])
def test_differential_structured_adversarial(cases_fn, fn):
    cases = cases_fn()
    pubs = [c[0] for c in cases]
    msgs = [c[1] for c in cases]
    sigs = [c[2] for c in cases]
    # Python lane verdicts (via the PubKey wrapper when the blob parses,
    # else the raw verify function must reject)
    mod = secp if fn is native.secp_verify else sr
    want = [mod.PubKey(p).verify_signature(m, s)
            for p, m, s in zip(pubs, msgs, sigs)]
    assert want[-1] is True          # the control case
    assert not any(want[:-1])        # every structured case invalid
    got = fn(pubs, msgs, sigs)
    assert got is not None
    assert [bool(b) for b in got] == want


def test_batch_verifier_routes_host_schemes_through_native():
    from tendermint_tpu.crypto.batch import BatchVerifier, verified_sigs

    bv = BatchVerifier()
    want = []
    for i in range(12):
        seed = (0xCD00 + i).to_bytes(32, "big")
        k = secp.PrivKey.gen_from_secret(seed) if i % 2 \
            else sr.PrivKey(seed)
        m = b"route %d" % i
        s = bytearray(k.sign(m))
        ok = True
        if i in (3, 8):
            s[0] ^= 1
            ok = k.pub_key().verify_signature(m, bytes(s))
        # distinct messages: no SigCache interference
        assert not verified_sigs.hit(k.pub_key().bytes(), m, bytes(s)) \
            or ok
        bv.add(k.pub_key(), m, bytes(s))
        want.append(ok)
    all_ok, bits = bv.verify()
    assert list(bits) == want
    assert all_ok == all(want)


def test_malformed_lengths_fall_back_without_crash():
    # a 32-byte "secp pub" makes the packed array irregular: the native
    # path declines and the per-item Python path scores it False
    k = secp.PrivKey.gen_from_secret(b"\x55" * 32)
    m = b"malformed"
    sig = k.sign(m)
    from tendermint_tpu.crypto.batch import BatchVerifier

    class FakePub:
        type_name = "secp256k1"

        def bytes(self):
            return b"\x02" * 32  # wrong length

        def verify_signature(self, msg, s):
            return False

    bv = BatchVerifier()
    bv.add(k.pub_key(), m, sig)
    bv.add(FakePub(), m, sig)
    all_ok, bits = bv.verify()
    assert not all_ok and bool(bits[0]) and not bits[1]
