"""Adaptive control plane (libs/control.py, ADR-023): policy-mode
decision table, declared-envelope enforcement, the kill switch's
exact-revert contract, chaos at the decision seam, the [control] and
[slo] budget config surface, the ingress live-rate seam, and a
locksan-proven concurrent hammer across the real setter seams."""
from __future__ import annotations

import threading
import time

import pytest

from tendermint_tpu.libs import control
from tendermint_tpu.libs.control import (KNOB_SPECS, SPEC_BY_NAME,
                                         Controller, Knob, KnobSpec)


class Holder:
    """A minimal knob seam: a float cell with getter/setter, counting
    sets so tests can assert a revert did (or did not) touch it."""

    def __init__(self, v):
        self.v = float(v)
        self.sets = 0

    def get(self):
        return self.v

    def set(self, v):
        self.v = float(v)
        self.sets += 1


@pytest.fixture(autouse=True)
def _clean_control_state():
    """Every test leaves the process-global control surface as it
    found it: no installed controller, no config override, no armed
    chaos mode at the decide seam."""
    from tendermint_tpu.libs import fail
    yield
    control.uninstall()
    control.set_config(enable=None)
    fail.clear("control.decide")


def _spec(mode="admission", name="t_knob", rng=(10.0, 100.0), step=8.0,
          direction=-1, signal="ingress_queue_depth", labels=None):
    return KnobSpec(name, safe_range=rng, step=step, direction=direction,
                    signal=signal, mode=mode, labels=labels)


# ---------------------------------------------------------------------------
# the declared envelope: KnobSpec / Knob validation
# ---------------------------------------------------------------------------

def test_knobspec_rejects_bad_declarations():
    with pytest.raises(ValueError, match="safe_range"):
        _spec(rng=(100.0, 10.0))
    with pytest.raises(ValueError, match="safe_range"):
        _spec(rng=(float("-inf"), 10.0))
    with pytest.raises(ValueError, match="step"):
        _spec(step=0.0)
    with pytest.raises(ValueError, match="step"):
        _spec(step=float("nan"))
    with pytest.raises(ValueError, match="mode"):
        _spec(mode="vibes")


def test_every_declared_spec_row_is_well_formed():
    """The literal table itself (tmlint TM308 checks it statically;
    this is the runtime twin): finite ranges, positive steps, a known
    mode, and unique names."""
    assert len({s.name for s in KNOB_SPECS}) == len(KNOB_SPECS)
    for s in KNOB_SPECS:
        lo, hi = s.safe_range
        assert lo <= hi and s.step > 0
        assert s.mode in ("throughput", "admission", "backlog",
                          "pressure", "overlap")
        assert SPEC_BY_NAME[s.name] is s


def test_knob_config_range_and_clamp_and_coerce():
    h = Holder(40.0)
    # config tightens the declared range; a nonsense range is refused
    k = Knob(_spec(), h.get, h.set, safe_range=(20.0, 80.0), step=4.0)
    assert k.clamp(200.0) == (80.0, True)
    assert k.clamp(1.0) == (20.0, True)
    assert k.clamp(33.0) == (33.0, False)
    assert k.coerce(33.4) == 33.0  # integral by default
    kf = Knob(_spec(name="t_frac"), h.get, h.set, integral=False)
    assert kf.coerce(2.5) == 2.5
    with pytest.raises(ValueError, match="finite"):
        Knob(_spec(), h.get, h.set, safe_range=(9.0, 1.0))
    with pytest.raises(ValueError, match="step"):
        Knob(_spec(), h.get, h.set, step=-1.0)


# ---------------------------------------------------------------------------
# policy modes, driven synthetically through _decide (published-signal
# dicts in, one bounded Decision out)
# ---------------------------------------------------------------------------

COLD = {"consensus": 0.0, "commit": 0.0, "block_interval": 0.0}
HOT = {"consensus": 2.0, "commit": 0.0, "block_interval": 0.0}


def _decide(ctl, k, burns, sources=None):
    return ctl._decide(k, sources or {}, burns, time.time())


def test_admission_md_clamp_and_ai_recovery():
    ctl = Controller(period_ms=10, recover_after=2)
    h = Holder(96.0)
    k = ctl.register(_spec(), h.get, h.set)
    # hot: multiplicative halve toward lo, never past it
    d = _decide(ctl, k, HOT)
    assert (d.direction, d.value, d.reason) == ("shrink", 48.0,
                                                "overload-md")
    d = _decide(ctl, k, HOT)
    assert d.value == 24.0 and h.v == 24.0
    d = _decide(ctl, k, HOT)
    assert d.value == 12.0
    d = _decide(ctl, k, HOT)
    assert d.value == 10.0  # the lo floor, never past it
    assert _decide(ctl, k, HOT) is None  # pinned at the floor
    # recovery: additive, only after recover_after clean periods
    assert _decide(ctl, k, COLD) is None
    d = _decide(ctl, k, COLD)
    assert (d.direction, d.value, d.reason) == ("grow", 18.0,
                                                "recover-ai")
    for _ in range(40):
        if _decide(ctl, k, COLD) is None:
            break
    assert h.v == 96.0  # recovery stops AT static, never past it


def test_admission_unlimited_static_engages_and_restores_zero():
    """static == 0 means "unlimited": the clamp engages from the
    range's hi, and full recovery restores the literal 0."""
    ctl = Controller(period_ms=10, recover_after=1)
    h = Holder(0.0)
    k = ctl.register(_spec(), h.get, h.set)
    d = _decide(ctl, k, HOT)
    assert (d.value, d.reason) == (100.0, "overload-engage")
    d = _decide(ctl, k, HOT)
    assert d.value == 50.0
    d = _decide(ctl, k, COLD)
    assert (d.value, d.reason) == (58.0, "recover-ai")
    while h.v != 0.0:
        d = _decide(ctl, k, COLD)
        assert d is not None and d.value <= 100.0
    assert d.reason == "recovered-static" and not k.engaged
    assert _decide(ctl, k, COLD) is None  # unlimited again: nothing to do


def test_throughput_grow_backoff_idle_recover():
    ctl = Controller(period_ms=10, recover_after=2)
    h = Holder(4.0)
    spec = _spec(mode="throughput", rng=(1.0, 16.0), step=1.0,
                 direction=1)
    k = ctl.register(spec, h.get, h.set)

    def src(depth):
        class G:
            def value(self, **kw):
                return depth
        return {spec.signal: G()}

    d = _decide(ctl, k, COLD, src(5.0))  # busy, no history yet: grow
    assert (d.direction, d.value, d.reason) == ("grow", 5.0,
                                                "backlog-cold")
    d = _decide(ctl, k, COLD, src(9.0))  # rising: grow again
    assert d.value == 6.0
    h.v = 15.5                           # a grow past hi clamps @bound
    d = _decide(ctl, k, COLD, src(20.0))
    assert (d.value, d.reason) == (16.0, "backlog-cold@bound")
    h.v = 6.0
    d = _decide(ctl, k, HOT, src(9.0))   # burn hot: step back to static
    assert (d.value, d.reason) == (5.0, "burn-hot")
    assert _decide(ctl, k, COLD, src(0.0)) is None  # idle 1
    d = _decide(ctl, k, COLD, src(0.0))             # idle 2: recover
    assert (d.value, d.reason) == (4.0, "idle-recover")
    assert h.v == k.static


def test_backlog_pinned_grow_calm_recover():
    ctl = Controller(period_ms=10, recover_after=1)
    h = Holder(4.0)
    spec = _spec(mode="backlog", rng=(2.0, 8.0), step=1.0, direction=1,
                 signal="pipeline_depth")
    k = ctl.register(spec, h.get, h.set)

    def src(depth):
        class G:
            def value(self, **kw):
                return depth
        return {spec.signal: G()}

    d = _decide(ctl, k, COLD, src(4.0))   # pinned at the current depth
    assert (d.value, d.reason) == (5.0, "queue-pinned")
    d = _decide(ctl, k, COLD, src(1.0))   # calm: back toward static
    assert (d.value, d.reason) == (4.0, "calm-recover")


def test_overlap_shrink_on_fresh_low_recover_on_healthy_or_idle():
    """The mesh staging-chunk policy (ADR-027): only a CHANGED
    chunk_overlap gauge value counts as a fresh launch (the gauge holds
    its last value between launches — steering on a stale reading would
    walk the knob to the bound on an idle mesh); fresh-and-low shrinks
    the raw chunk, healthy or idle periods recover toward static."""
    ctl = Controller(period_ms=10, recover_after=2)
    h = Holder(4096.0)
    spec = _spec(mode="overlap", name="t_chunk", rng=(1024.0, 65536.0),
                 step=1024.0, direction=-1, signal="chunk_overlap")
    k = ctl.register(spec, h.get, h.set)

    def src(ratio):
        class G:
            def value(self, **kw):
                return ratio
        return {spec.signal: G()}

    # the first reading has no history: never a step (idle, not fresh)
    assert _decide(ctl, k, COLD, src(0.10)) is None
    # unchanged gauge = no launch since: still no step
    assert _decide(ctl, k, COLD, src(0.10)) is None
    # a CHANGED low ratio is a fresh overlapped launch: shrink
    d = _decide(ctl, k, COLD, src(0.05))
    assert (d.direction, d.value, d.reason) == ("shrink", 3072.0,
                                                "overlap-low")
    d = _decide(ctl, k, COLD, src(0.03))
    assert d.value == 2048.0 and h.v == 2048.0
    # fresh healthy readings: recover toward static after recover_after
    assert _decide(ctl, k, COLD, src(0.55)) is None
    d = _decide(ctl, k, COLD, src(0.60))
    assert (d.value, d.reason) == (3072.0, "overlap-recover")
    # the path going idle (gauge frozen) also recovers toward static
    assert _decide(ctl, k, COLD, src(0.60)) is None
    d = _decide(ctl, k, COLD, src(0.60))
    assert (d.value, d.reason) == (4096.0, "overlap-recover")
    assert h.v == k.static
    # pinned at the declared floor: a shrink below lo clamps to prev
    h.v = 1024.0
    assert _decide(ctl, k, COLD, src(0.01)) is None


def test_overlap_freshness_tracks_launch_seq():
    """With the companion <signal>_seq gauge published, freshness is
    the LAUNCH SEQUENCE, not the ratio value: a busy mesh path that
    repeatedly publishes the same stable low ratio keeps stepping the
    knob (the value-change test would misread it as idle and walk the
    knob back toward static with the overlap target unmet), while a
    frozen seq — no launches — still reads as idle."""
    ctl = Controller(period_ms=10, recover_after=2)
    h = Holder(4096.0)
    spec = _spec(mode="overlap", name="t_chunk_seq",
                 rng=(1024.0, 65536.0), step=1024.0, direction=-1,
                 signal="chunk_overlap")
    k = ctl.register(spec, h.get, h.set)

    def src(ratio, seq):
        class G:
            def __init__(self, v):
                self.v = v

            def value(self, **kw):
                return self.v
        return {spec.signal: G(ratio),
                spec.signal + "_seq": G(float(seq))}

    # first reading seeds the seq history: never a step
    assert _decide(ctl, k, COLD, src(0.10, 1)) is None
    # SAME ratio, advancing seq = fresh launches below target: shrink
    d = _decide(ctl, k, COLD, src(0.10, 2))
    assert (d.direction, d.value, d.reason) == ("shrink", 3072.0,
                                                "overlap-low")
    d = _decide(ctl, k, COLD, src(0.10, 3))
    assert d.value == 2048.0 and h.v == 2048.0
    # frozen seq = no launches: idle periods recover toward static
    assert _decide(ctl, k, COLD, src(0.10, 3)) is None
    d = _decide(ctl, k, COLD, src(0.10, 3))
    assert (d.value, d.reason) == (3072.0, "overlap-recover")
    # healthy fresh launches also recover, exactly like the value test
    assert _decide(ctl, k, COLD, src(0.80, 4)) is None
    d = _decide(ctl, k, COLD, src(0.80, 5))
    assert (d.value, d.reason) == (4096.0, "overlap-recover")
    assert h.v == k.static


def test_decision_seam_refusal_and_error_containment():
    ctl = Controller(period_ms=10)
    h = Holder(64.0)
    k = ctl.register(_spec(), h.get,
                     lambda v: False)  # the seam refuses (busy)
    d = _decide(ctl, k, HOT)
    assert d.direction == "held" and "seam-busy" in d.reason
    assert h.v == 64.0

    def boom():
        raise RuntimeError("subsystem stopped")

    k2 = ctl.register(_spec(name="t_other"), h.get, h.set)
    k2.getter = boom  # the subsystem stopped AFTER registration
    d = _decide(ctl, k2, HOT)
    assert d.direction == "error" and "subsystem stopped" in d.reason


# ---------------------------------------------------------------------------
# the kill switch: exact revert, ring evidence, gauge truth
# ---------------------------------------------------------------------------

def test_kill_switch_reverts_every_knob_exactly():
    from tendermint_tpu.libs.metrics import ControlMetrics
    ctl = Controller(period_ms=10)
    cells = {}
    for i, name in enumerate(("t_a", "t_b", "t_c")):
        h = cells[name] = Holder(10.0 + i)
        ctl.register(_spec(name=name, rng=(1.0, 1000.0)), h.get, h.set)
    # steer every knob away from static, then flip the switch
    for h in cells.values():
        h.set(h.v + 500.0)
    ctl.kill("operator")
    m = ControlMetrics()
    for name, h in cells.items():
        k = ctl._knobs[name]
        assert h.v == k.static  # the exact registration-time value
        assert m.knob_value.value(knob=name) == k.static
    assert m.killed.value() == 1.0
    rep = ctl.report()
    assert rep["killed"] == "operator"
    ringed = [d for d in rep["decisions"] if d["direction"] == "revert"]
    # EVERY knob rings on a revert event, steered or not
    assert {d["knob"] for d in ringed} == set(cells)
    assert all(d["reason"] == "kill:operator" for d in ringed)
    # a knob already at static reverts without touching its seam
    sets_before = cells["t_a"].sets
    ctl.revert_all("again")
    assert cells["t_a"].sets == sets_before
    assert len([d for d in ctl.report()["decisions"]
                if d["reason"] == "again"]) == len(cells)


def test_running_controller_kill_and_disable_within_one_period():
    """The integration contract the diurnal_weather scenario gates on:
    with the loop RUNNING, both control.kill() and a config disable
    hand every knob back to static within one period."""
    ctl = control.install(Controller(period_ms=20))
    h = Holder(50.0)
    ctl.register(_spec(rng=(1.0, 1000.0)), h.get, h.set)
    control.set_config(enable=True)
    ctl.start()
    try:
        h.set(700.0)
        control.kill("test")
        assert h.v == 50.0  # kill() reverts synchronously
        assert ctl.killed() == "test"
        # config disable (the other half of the switch): the LOOP must
        # notice within one period, no operator call involved
        ctl2 = Controller(period_ms=20)
        h2 = Holder(5.0)
        ctl2.register(_spec(name="t_d", rng=(1.0, 1000.0)), h2.get,
                      h2.set)
        ctl.stop()
        control.uninstall()
        control.install(ctl2)
        ctl2.start()
        h2.set(900.0)
        control.set_config(enable=False)
        deadline = time.monotonic() + 5.0
        while h2.v != 5.0 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert h2.v == 5.0
        assert any(d["reason"] == "disabled"
                   for d in ctl2.report()["decisions"])
    finally:
        control.uninstall()


def test_install_surface_refuses_second_running_controller():
    ctl = control.install(Controller(period_ms=50))
    ctl.start()
    try:
        with pytest.raises(RuntimeError, match="already installed"):
            control.install(Controller())
        assert control.running() is ctl
    finally:
        control.uninstall()
    assert control.installed() is None and not ctl.is_running()
    # no controller: report() still serves the debug payload shape
    rep = control.report()
    assert rep["running"] is False and rep["knobs"] == {}


def test_config_enable_wins_over_env_both_ways(monkeypatch):
    monkeypatch.setenv("TM_TPU_CONTROL", "1")
    assert control.enabled()
    control.set_config(enable=False)
    assert not control.enabled()  # config beats the armed env var
    monkeypatch.setenv("TM_TPU_CONTROL", "0")
    control.set_config(enable=True)
    assert control.enabled()      # ...in BOTH directions
    control.set_config(enable=None)
    assert not control.enabled()  # cleared: env rules again


# ---------------------------------------------------------------------------
# chaos at the decision seam: a fault is a controller malfunction, and
# a malfunctioning controller fails STATIC
# ---------------------------------------------------------------------------

def test_chaos_raise_at_decide_skips_period_and_fails_static():
    from tendermint_tpu.libs import fail
    from tendermint_tpu.libs.metrics import ControlMetrics
    ctl = control.install(Controller(period_ms=20))
    h = Holder(30.0)
    ctl.register(_spec(rng=(1.0, 1000.0)), h.get, h.set)
    control.set_config(enable=True)
    skipped0 = ControlMetrics().decisions.value(knob="period",
                                                direction="skipped")
    ctl.start()
    try:
        h.set(600.0)
        fail.set_mode("control.decide", "raise")
        deadline = time.monotonic() + 5.0
        while (fail.fired("control.decide", "raise") < 2
               and time.monotonic() < deadline):
            time.sleep(0.005)
        assert fail.fired("control.decide", "raise") >= 2
        assert h.v == 30.0  # fail-static: the chaos fault reverted it
        assert any(d["reason"] == "chaos"
                   for d in ctl.report()["decisions"])
        assert ctl.report()["skipped_periods"] >= 2
        assert ControlMetrics().decisions.value(
            knob="period", direction="skipped") >= skipped0 + 2
        # the loop SURVIVES: disarm and it decides again
        fail.clear("control.decide")
        p0 = ctl.report()["periods"]
        deadline = time.monotonic() + 5.0
        while (ctl.report()["periods"] <= p0
               and time.monotonic() < deadline):
            time.sleep(0.005)
        assert ctl.is_running() and ctl.report()["periods"] > p0
    finally:
        fail.clear("control.decide")
        control.uninstall()


def test_chaos_latency_at_decide_stalls_but_never_wedges():
    from tendermint_tpu.libs import fail
    ctl = control.install(Controller(period_ms=10))
    control.set_config(enable=True)
    fail.set_mode("control.decide", "latency:30")
    ctl.start()
    try:
        deadline = time.monotonic() + 5.0
        while (fail.fired("control.decide", "latency:30") < 2
               and time.monotonic() < deadline):
            time.sleep(0.005)
        assert fail.fired("control.decide", "latency:30") >= 2
        assert ctl.is_running()  # slow periods, live loop
    finally:
        fail.clear("control.decide")
        control.uninstall()


# ---------------------------------------------------------------------------
# the [control] + [slo] budget config surface
# ---------------------------------------------------------------------------

def test_control_and_budget_config_toml_roundtrip(tmp_path):
    from tendermint_tpu.config.config import Config
    cfg = Config(home=str(tmp_path), moniker="ctl")
    cfg.control.enable = True            # non-default (ADR-023)
    cfg.control.period_ms = 250.0
    cfg.control.recover_after = 5
    cfg.control.ingress_rate_per_s_min = 64.0
    cfg.control.ingress_rate_per_s_max = 5000.0
    cfg.control.sched_window_ms_step = 0.25
    cfg.slo.consensus_budget_pct = 10.0  # non-default (satellite 1)
    cfg.slo.block_interval_budget_pct = 2.5
    cfg.save()
    back = Config.load(str(tmp_path))
    assert back.control.enable is True
    assert back.control.period_ms == 250.0
    assert back.control.recover_after == 5
    assert back.control.range_of("ingress_rate_per_s") == (64.0, 5000.0)
    assert back.control.step_of("sched_window_ms") == 0.25
    assert back.slo.consensus_budget_pct == 10.0
    assert back.slo.budgets()["consensus"] == 0.10
    assert back.slo.budgets()["block_interval"] == 0.025
    back.control.validate_basic()
    back.slo.validate_basic()


def test_control_config_validate_rejects_nonsense():
    from tendermint_tpu.config.config import ControlConfig, SLOConfig
    cc = ControlConfig()
    cc.period_ms = 0
    with pytest.raises(ValueError, match="period_ms"):
        cc.validate_basic()
    cc = ControlConfig()
    cc.pipeline_depth_min = 40.0  # min > max
    with pytest.raises(ValueError, match="pipeline_depth_min"):
        cc.validate_basic()
    cc = ControlConfig()
    cc.comb_min_batch_step = 0.0
    with pytest.raises(ValueError, match="comb_min_batch_step"):
        cc.validate_basic()
    sc = SLOConfig()
    sc.consensus_budget_pct = 0.0
    with pytest.raises(ValueError, match="consensus_budget_pct"):
        sc.validate_basic()
    sc.consensus_budget_pct = 150.0
    with pytest.raises(ValueError, match="consensus_budget_pct"):
        sc.validate_basic()


def test_every_declared_knob_has_a_config_row():
    """[control] carries one min/max/step triple per KNOB_SPECS row —
    a new spec row without its config envelope is a drift bug."""
    from tendermint_tpu.config.config import ControlConfig
    cc = ControlConfig()
    assert set(cc.KNOBS) == set(SPEC_BY_NAME)
    for s in KNOB_SPECS:
        lo, hi = cc.range_of(s.name)
        # the config DEFAULT matches the declared literal envelope
        assert (lo, hi) == s.safe_range
        assert cc.step_of(s.name) == s.step


# ---------------------------------------------------------------------------
# [slo] per-stream budgets + the published target gauge (satellite 1)
# ---------------------------------------------------------------------------

def test_slo_budget_scales_burn_rate():
    from tendermint_tpu.libs.slo import SloEstimator
    est = SloEstimator(window=10, enabled=True,
                       targets={"consensus": 0.1},
                       budgets={"consensus": 0.10})
    for v in [0.05] * 8 + [0.2] * 2:  # 20% of the window over target
        est.observe("consensus", v)
    rep = est.stream_report("consensus")
    assert rep["over_target_frac"] == pytest.approx(0.2)
    assert rep["budget"] == 0.10
    assert rep["burn_rate"] == pytest.approx(2.0)
    # same observations, p99-convention budget: 20x the burn
    est.budgets = {"consensus": 0.01}
    assert est.stream_report("consensus")["burn_rate"] == \
        pytest.approx(20.0)
    # a nonsense budget falls back to the p99 convention, never a /0
    est.budgets = {"consensus": 0.0}
    assert est.stream_report("consensus")["burn_rate"] == \
        pytest.approx(20.0)


def test_slo_set_config_publishes_target_gauge():
    from tendermint_tpu.libs import slo
    from tendermint_tpu.libs.metrics import CryptoMetrics
    try:
        slo.set_config(targets={"consensus": 0.25, "mempool": 1.5},
                       budgets={"consensus": 0.05})
        m = CryptoMetrics()
        assert m.slo_target.value(stream="consensus") == 0.25
        assert m.slo_target.value(stream="mempool") == 1.5
        assert slo.report()["budgets"]["consensus"] == 0.05
        # config-wins, both ways: enabled untouched unless asked
        assert not slo.is_enabled()
    finally:
        slo.set_config(enabled=False, targets={}, budgets={})
        slo.reset()


# ---------------------------------------------------------------------------
# the ingress live-rate seam (satellite 2): set_rate re-clamps LIVE
# per-source buckets, not only future ones
# ---------------------------------------------------------------------------

def test_ingress_set_rate_reclamps_live_buckets():
    from tendermint_tpu.libs.metrics import Registry
    from tendermint_tpu.mempool.ingress import IngressGate
    from tendermint_tpu.mempool.mempool import Mempool

    class Accept:
        def check_tx(self, req):
            from tendermint_tpu.abci import types as abci
            return abci.ResponseCheckTx(code=0)

    mp = Mempool(Accept(), registry=Registry())
    g = IngressGate(mp, rate_per_s=1000.0, burst=500, workers=1).attach()
    g.start()
    try:
        # create a LIVE bucket for this source with saved-up allowance
        assert g.submit(b"tx-0", source="peer-a") is not None
        b = g._buckets["peer-a"]
        assert b.rate == 1000.0 and b.burst == 500.0
        b.tokens = 499.0  # a flood's saved-up allowance
        g.set_rate(rate_per_s=50.0, burst=10)
        assert g.rate_per_s == 50.0 and g.burst == 10.0
        # the live bucket is re-clamped: rate, burst AND tokens — the
        # saved-up allowance must shrink with the burst, not outlive it
        assert b.rate == 50.0 and b.burst == 10.0
        assert b.tokens <= 10.0
        # None leaves a dimension untouched; rate 0 disables limiting
        g.set_rate(burst=25)
        assert g.rate_per_s == 50.0 and b.burst == 25.0
        g.set_rate(rate_per_s=0.0)
        assert g.rate_per_s == 0.0
        for i in range(64):  # unlimited again: no ratelimit rejections
            r = g.submit(b"tx-%d" % i, source="peer-a")
            assert not (r.done() and "rate limited"
                        in r.result(0.1).log)
    finally:
        g.stop()


# ---------------------------------------------------------------------------
# the locksan hammer: real seams, concurrent steering, exact results
# ---------------------------------------------------------------------------

@pytest.mark.locksan
def test_locksan_hammer_concurrent_steering_and_verifies():
    """The TM201 proof for the control plane: a RUNNING controller's
    decide loop, concurrent scheduler submits, an ingress flood, a
    pipelined block replay and a thread spinning every registered
    knob's setter across its safe range — all under the lockset
    monitor, with exact verify results and the pipelined replay's
    final state byte-identical to a static (serial, untouched) twin.
    Any Controller._lock edge that violates its declared LEAF rank
    fails the test with the offending acquisition."""
    from helpers import build_chain, make_genesis
    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.blocksync.replay import replay_window
    from tendermint_tpu.crypto import batch as cbatch
    from tendermint_tpu.crypto import ed25519 as edkeys
    from tendermint_tpu.crypto import scheduler as vsched
    from tendermint_tpu.libs.kvdb import GroupCommitDB, MemDB
    from tendermint_tpu.libs.metrics import Registry
    from tendermint_tpu.mempool.ingress import IngressGate
    from tendermint_tpu.mempool.mempool import Mempool
    from tendermint_tpu.state import pipeline
    from tendermint_tpu.state.execution import BlockExecutor
    from tendermint_tpu.state.state import state_from_genesis
    from tendermint_tpu.state.store import StateStore
    from tendermint_tpu.store.block_store import BlockStore

    class Accept:
        def check_tx(self, req):
            from tendermint_tpu.abci import types as abci
            return abci.ResponseCheckTx(code=0)

    cbatch.verified_sigs = cbatch.SigCache()
    privs = [edkeys.PrivKey(bytes([i + 1]) * 32) for i in range(8)]
    items = [(p.pub_key(), b"ctl hammer %d" % i,
              p.sign(b"ctl hammer %d" % i))
             for i, p in enumerate(privs)]
    gdoc, gprivs = make_genesis(4)
    blocks, commits, _states = build_chain(gdoc, gprivs, 8)

    def _replay(ex, store, st):
        applied = 0
        while applied < len(blocks):
            for i, c in enumerate(commits):
                ex.mark_commit_verified(i + 1, c)
            st, n = replay_window(ex, store, st, blocks[applied:],
                                  commits[applied:], max_window=4)
            assert n > 0
            applied += n
        return st

    # the static twin: serial replay, no pipeline, no steering
    ex1 = BlockExecutor(StateStore(MemDB()), KVStoreApplication())
    st_static = _replay(ex1, BlockStore(MemDB()), state_from_genesis(gdoc))

    sched = vsched.VerifyScheduler(window_s=0.001, max_batch=64,
                                   tpu_threshold=1 << 30)
    sched.start()
    mp = Mempool(Accept(), registry=Registry())
    gate = IngressGate(mp, queue_size=256, batch=32, workers=1,
                       rate_per_s=200.0, burst=64).attach()
    gate.start()
    pipe = pipeline.set_config(enable=True, depth=4,
                               group_commit_heights=4)
    ctl = control.install(Controller(period_ms=5))
    ctl.register(SPEC_BY_NAME["sched_window_ms"],
                 lambda: sched.window_s * 1000.0,
                 lambda ms: sched.set_window(ms / 1000.0),
                 integral=False)
    ctl.register(SPEC_BY_NAME["ingress_rate_per_s"],
                 lambda: gate.rate_per_s,
                 lambda r: gate.set_rate(rate_per_s=r))
    ctl.register(SPEC_BY_NAME["pipeline_depth"],
                 lambda: float(pipe.depth),
                 lambda d: pipe.set_depth(int(d)))
    control.set_config(enable=True)
    ctl.start()
    stop = threading.Event()
    errors = []

    def spin_knobs():
        lo_w, hi_w = SPEC_BY_NAME["sched_window_ms"].safe_range
        lo_d, hi_d = SPEC_BY_NAME["pipeline_depth"].safe_range
        vals = [lo_w, hi_w, 2.0, 5.0]
        i = 0
        while not stop.is_set():
            sched.set_window(vals[i % len(vals)] / 1000.0)
            gate.set_rate(rate_per_s=float(32 + (i % 8) * 64),
                          burst=float(16 + (i % 4) * 16))
            # set_depth may refuse mid-window (False) — that IS the
            # seam contract the controller's "held" decision rides on
            pipe.set_depth(int(lo_d + (i % 4) * 2) if i % 2
                           else int(hi_d // 2))
            if i % 7 == 0:
                ctl.revert_all("hammer")
            i += 1
            time.sleep(0.001)

    def submit_verifies(k):
        try:
            for _ in range(6):
                fut = sched.submit(items, vsched.Priority.CONSENSUS)
                assert fut.result(timeout=30.0).all()
        except Exception as e:  # noqa: BLE001 - collected for the main
            errors.append(e)    # thread's assertion

    def flood():
        i = 0
        while not stop.is_set():
            gate.submit(b"flood %d" % i, source="hammer")
            i += 1
            time.sleep(0.0005)

    def pipelined_replays():
        try:
            for _ in range(3):
                ex = BlockExecutor(StateStore(GroupCommitDB(MemDB())),
                                   KVStoreApplication())
                st = _replay(ex, BlockStore(GroupCommitDB(MemDB())),
                             state_from_genesis(gdoc))
                # exact vs the static twin, every round, mid-steering
                assert st.app_hash == st_static.app_hash
                assert st.last_block_id == st_static.last_block_id
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=spin_knobs, name="knob-spin"),
               threading.Thread(target=flood, name="flood"),
               threading.Thread(target=pipelined_replays,
                                name="replay")] + \
        [threading.Thread(target=submit_verifies, args=(k,),
                          name=f"verify-{k}") for k in range(3)]
    try:
        for t in threads:
            t.start()
        for t in threads[3:]:
            t.join(timeout=60.0)
        threads[2].join(timeout=60.0)
        stop.set()
        for t in threads[:2]:
            t.join(timeout=10.0)
        assert not errors, errors
        assert all(not t.is_alive() for t in threads)
        # the kill switch still lands exactly after all that churn
        control.kill("hammer-done")
        assert sched.window_s * 1000.0 == pytest.approx(
            ctl._knobs["sched_window_ms"].static)
        assert gate.rate_per_s == pytest.approx(
            ctl._knobs["ingress_rate_per_s"].static)
        assert float(pipe.depth) == pytest.approx(
            ctl._knobs["pipeline_depth"].static)
    finally:
        stop.set()
        control.uninstall()
        pipeline.set_config(enable=False)
        gate.stop()
        sched.stop()
