"""Concurrent multi-scheme lane executor (ADR-015) acceptance tests.

The tentpole claim, proven via flight-recorder span timestamps: a mixed
ed25519+secp256k1+sr25519 batch runs its host lanes on >= 2 host-pool
workers CONCURRENTLY with the in-flight device lane — the old serial
host-lane walk's `sum` wall-clock is replaced by `max` — while every
bitmap stays byte-identical to the per-item host oracle and to the
serial (pool-disabled) path.
"""
from __future__ import annotations

import numpy as np
import pytest

from tendermint_tpu.crypto import batch as cb
from tendermint_tpu.crypto import degrade
from tendermint_tpu.crypto import ed25519 as ed
from tendermint_tpu.crypto import lanepool
from tendermint_tpu.crypto import secp256k1 as secp
from tendermint_tpu.crypto import sr25519 as sr
from tendermint_tpu.libs import fail
from tendermint_tpu.libs import trace
from tendermint_tpu.libs.metrics import Registry


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    # fresh SigCache so host lanes really verify (a warm cache would
    # short-circuit the C lanes this file is about)
    monkeypatch.setattr(cb, "verified_sigs", cb.SigCache())
    fail.reset()
    # pin the pool size: the span assertions need >= 2 pool workers and
    # must not depend on the runner's core count (auto-sizing on a
    # 1-core CI box would disable the pool entirely)
    lanepool.set_workers(4)
    yield
    fail.reset()
    lanepool.set_workers(None)
    degrade.reset()


def _mixed_items(n_ed=16, n_secp=6, n_sr=6, tag=b"mx", bad=()):
    items = []
    for i in range(n_ed):
        k = ed.PrivKey((0x5100 + i).to_bytes(32, "big"))
        m = b"%s ed %d" % (tag, i)
        items.append((k.pub_key(), m, k.sign(m)))
    for i in range(n_secp):
        k = secp.PrivKey.gen_from_secret(b"%s-secp-%d" % (tag, i))
        m = b"%s secp %d" % (tag, i)
        items.append((k.pub_key(), m, k.sign(m)))
    for i in range(n_sr):
        k = sr.PrivKey((0x5200 + i).to_bytes(32, "little"))
        m = b"%s sr %d" % (tag, i)
        items.append((k.pub_key(), m, k.sign(m)))
    out = []
    for i, (p, m, s) in enumerate(items):
        if i in bad:
            s = bytes([s[0] ^ 1]) + s[1:]
        out.append((p, m, s))
    return out


def _verify(items, threshold):
    bv = cb.BatchVerifier(tpu_threshold=threshold)
    for p, m, s in items:
        bv.add(p, m, s)
    return bv.verify()


def _oracle(items):
    out = np.zeros(len(items), dtype=bool)
    for i, (p, m, s) in enumerate(items):
        try:
            out[i] = p.verify_signature(m, s)
        except Exception:  # noqa: BLE001 - malformed = invalid
            out[i] = False
    return out


def _spans(records, name):
    return [r for r in records if r["name"] == name and r["ph"] == "X"]


def _overlaps(a, b):
    a0, a1 = a["ts_ns"], a["ts_ns"] + a["dur_ns"]
    b0, b1 = b["ts_ns"], b["ts_ns"] + b["dur_ns"]
    return a0 < b1 and b0 < a1


# ---------------------------------------------------------------------------
# the tier-1 acceptance test (ISSUE 7)
# ---------------------------------------------------------------------------

def test_mixed_batch_host_lanes_overlap_device_lane(monkeypatch):
    """Flight-recorder proof that serial-loop `sum` became `max`: the
    secp256k1 and sr25519 host lanes run on two DISTINCT host-pool
    worker threads, their spans overlap each other in time, and both
    overlap the ed25519 device launch — with the bitmap byte-identical
    to the per-item host oracle.  Injected latency (120 ms at the host
    C seam, 120 ms at the device kernel seam) makes every lane's span
    long enough that real concurrency is the only way the overlap
    assertions can hold; the generous margins keep slow-CI noise out."""
    monkeypatch.setenv("TM_TPU_FORCE_BATCH", "1")
    monkeypatch.delenv("TM_TPU_DISABLE_BATCH", raising=False)
    # the ed device lane here is the XLA kernel forced onto CPU in the
    # SHARED nb=64 bucket (no new compile shapes); first use in the
    # process may still pay the one-off bucket compile, so the launch
    # budget stays generous
    degrade.configure(degrade.DegradeConfig(launch_timeout_s=600.0),
                      registry=Registry("mixedlanes"))
    items = _mixed_items(bad=(3, 17, 24))  # one offender per scheme
    # host oracle FIRST (cache untouched: oracle bypasses BatchVerifier)
    base = _oracle(items)
    assert base.sum() == len(items) - 3

    # warm the shared nb=64 ed bucket BEFORE tracing: a cold first
    # compile would stretch the device span to tens of seconds and the
    # wall-vs-sum assertion would compare lanes against compile time
    warm = _mixed_items(n_ed=16, n_secp=0, n_sr=0, tag=b"warm")
    ok, _ = _verify(warm, threshold=8)
    assert ok

    # stretch every lane so overlap is unambiguous in the trace
    fail.set_mode("lanepool.verify", "latency:120")
    fail.set_mode("ops.ed25519.verify_batch", "latency:120")
    was_enabled = trace.is_enabled()
    trace.enable()
    seq0 = trace.last_seq()
    try:
        ok, bits = _verify(items, threshold=8)  # ed(16) device;
        #                                         secp(6)/sr(6) host
    finally:
        if not was_enabled:
            trace.disable()
        fail.clear()
    assert (bits == base).all()
    assert not ok

    records = trace.snapshot(since=seq0)
    host = _spans(records, "batch.host_lane")
    assert len(host) == 2, host
    # >= 2 distinct pool workers — not the caller thread
    assert all(str(r["tname"]).startswith("host-lane-pool") for r in host)
    assert len({r["tid"] for r in host}) == 2
    # ... running concurrently with each other
    assert _overlaps(host[0], host[1])
    # ... and with the device lane's launch span
    launches = _spans(records, "device.launch")
    assert launches, records
    launch = launches[-1]
    assert all(_overlaps(launch, h) for h in host)
    # wall-clock: max over lanes, not their sum.  Each host lane slept
    # >= 50 ms and the device kernel seam another 50 ms, so the serial
    # walk would cost >= 150 ms; concurrent lanes stay well under.
    walls = [h["dur_ns"] for h in host] + [launch["dur_ns"]]
    wall_union = max(r["ts_ns"] + r["dur_ns"] for r in host + [launch]) \
        - min(r["ts_ns"] for r in host + [launch])
    assert wall_union < 0.75 * sum(walls), (wall_union, walls)
    # the lane report agrees (this is what BENCH_MIXED=1 publishes)
    rep = cb.last_lane_report()
    assert len(rep["lanes"]) == 3
    assert {(ln["scheme"], ln["kind"]) for ln in rep["lanes"]} == {
        ("ed25519", "device"), ("secp256k1", "host"), ("sr25519", "host")}
    assert rep["overlap_ratio"] > 0.25, rep


def test_mixed_sweep_concurrent_vs_serial_vs_oracle(monkeypatch):
    """Bitmap-identity sweep: pooled concurrent lanes vs the serial
    (pool-disabled) path vs the per-item host oracle, with a tampered
    signature in each scheme and a malformed-length signature thrown
    in.  Pure host path — no device routing at all."""
    monkeypatch.delenv("TM_TPU_FORCE_BATCH", raising=False)
    items = _mixed_items(n_ed=10, n_secp=18, n_sr=18, tag=b"sweep",
                         bad=(2, 12, 30))
    # malformed length in the secp lane: must be invalid, not fatal
    p, m, s = items[15]
    items[15] = (p, m, s[:40])
    base = _oracle(items)
    assert base.sum() == len(items) - 4

    ok, bits = _verify(items, threshold=1 << 30)
    assert (bits == base).all() and not ok

    monkeypatch.setattr(cb, "verified_sigs", cb.SigCache())
    lanepool.set_workers(1)  # serial in-caller fallback
    ok2, bits2 = _verify(items, threshold=1 << 30)
    assert (bits2 == base).all() and not ok2


def test_single_cache_miss_takes_native_c_lane(monkeypatch):
    """Regression for the `len(miss) >= 2` gate (ISSUE 7 satellite): a
    SINGLE cache miss must route through the native C verifier instead
    of the ~5 ms/sig pure-Python path."""
    from tendermint_tpu.libs import native

    if native.get_lib() is None:
        pytest.skip("no C toolchain: native lane unavailable")
    k = secp.PrivKey.gen_from_secret(b"single-miss")
    m = b"single miss msg"
    s = k.sign(m)
    calls = []
    real = lanepool.verify_sharded

    def spy(tname, pubs, msgs, sigs, **kw):
        calls.append((tname, len(pubs)))
        return real(tname, pubs, msgs, sigs, **kw)

    monkeypatch.setattr(lanepool, "verify_sharded", spy)

    def no_python(self, *a, **kw):
        raise AssertionError("pure-Python per-item path used for a "
                             "single miss")

    monkeypatch.setattr(secp.PubKey, "verify_signature", no_python)
    bv = cb.BatchVerifier()
    bv.add(k.pub_key(), m, s)
    ok, bits = bv.verify()
    assert ok and bits.tolist() == [True]
    assert calls == [("secp256k1", 1)]


def test_scheduler_window_host_lanes_run_on_pool(monkeypatch):
    """The same restructure inside VerifyScheduler._execute: a mixed
    window's host lanes land on >= 2 distinct pool workers with
    overlapping spans, and the coalesced bitmap matches the oracle."""
    from tendermint_tpu.crypto import scheduler as vsched

    monkeypatch.delenv("TM_TPU_FORCE_BATCH", raising=False)
    items = _mixed_items(n_ed=6, n_secp=8, n_sr=8, tag=b"sched",
                         bad=(1, 9, 18))
    base = _oracle(items)
    fail.set_mode("lanepool.verify", "latency:40")
    was_enabled = trace.is_enabled()
    trace.enable()
    seq0 = trace.last_seq()
    s = vsched.VerifyScheduler(window_s=0.001)
    s.start()
    try:
        bits = s.submit(items, vsched.Priority.COMMIT).result(timeout=120)
    finally:
        s.stop()
        if not was_enabled:
            trace.disable()
        fail.clear()
    assert (bits == base).all()

    records = trace.snapshot(since=seq0)
    host = _spans(records, "sched.host_lane")
    pooled = [r for r in host
              if str(r["tname"]).startswith("host-lane-pool")]
    assert len({r["tid"] for r in pooled}) >= 2, host
    slow = [r for r in host if r["name"] == "sched.host_lane"
            and r["dur_ns"] >= 30_000_000]
    assert len(slow) >= 2 and _overlaps(slow[0], slow[1]), host
