"""BatchVerifier routing/bitmap semantics + mesh-sharded verification."""
import random

import numpy as np

from tendermint_tpu.crypto import ed25519 as edkeys
from tendermint_tpu.crypto.batch import BatchVerifier

rng = random.Random(1234)


def _signed(n, msg_len=40):
    privs = [edkeys.PrivKey(bytes(rng.randrange(256) for _ in range(32)))
             for _ in range(n)]
    msgs = [bytes(rng.randrange(256) for _ in range(msg_len)) for _ in range(n)]
    sigs = [p.sign(m) for p, m in zip(privs, msgs)]
    return privs, msgs, sigs


def test_empty():
    ok, bits = BatchVerifier().verify()
    assert ok and bits.shape == (0,)


def test_small_batch_routes_to_cpu_and_passes():
    privs, msgs, sigs = _signed(3)
    bv = BatchVerifier(tpu_threshold=32)
    for p, m, s in zip(privs, msgs, sigs):
        bv.add(p.pub_key(), m, s)
    ok, bits = bv.verify()
    assert ok and bits.all() and len(bits) == 3


def test_tiny_batch_never_touches_degrade_runtime(monkeypatch):
    """Batches below tpu_threshold go straight to the host lanes: the
    degradation runtime's breaker lock is shared across reactor threads
    and pure contention for a batch that could never dispatch to the
    device (VERDICT r5 / ISSUE 2 satellite)."""
    from tendermint_tpu.crypto import batch as cb

    def _boom():
        raise AssertionError("degrade.runtime() touched on tiny batch")

    monkeypatch.setattr(cb.degrade, "runtime", _boom)
    privs, msgs, sigs = _signed(5)
    bv = BatchVerifier(tpu_threshold=32)
    for i, (p, m, s) in enumerate(zip(privs, msgs, sigs)):
        if i == 2:
            s = bytes([s[0] ^ 1]) + s[1:]
        bv.add(p.pub_key(), m, s)
    ok, bits = bv.verify()
    assert not ok
    assert bits.tolist() == [True, True, False, True, True]
    # valid triples still reach the SigCache on the fast path
    assert cb.verified_sigs.hit(privs[0].pub_key().bytes(), msgs[0],
                                sigs[0])


def test_large_batch_device_bitmap_order():
    n = 60  # stays within the shared MIN_BUCKET=64 kernel shape
    privs, msgs, sigs = _signed(n)
    bad = {7, 33, 59}
    bv = BatchVerifier(tpu_threshold=8)
    for i, (p, m, s) in enumerate(zip(privs, msgs, sigs)):
        if i in bad:
            s = bytes([s[0] ^ 1]) + s[1:]
        bv.add(p.pub_key(), m, s)
    ok, bits = bv.verify()
    assert not ok
    for i in range(n):
        assert bits[i] == (i not in bad), i


def test_malformed_lengths_dont_poison_batch():
    n = 40
    privs, msgs, sigs = _signed(n)
    bv = BatchVerifier(tpu_threshold=8)
    for i, (p, m, s) in enumerate(zip(privs, msgs, sigs)):
        if i == 5:
            s = s[:50]  # truncated signature
        bv.add(p.pub_key(), m, s)
    ok, bits = bv.verify()
    assert not ok and not bits[5]
    assert bits[np.arange(n) != 5].all()


def test_sigcache_true_lru_eviction_order():
    """Eviction is LRU, not FIFO: a hit (or re-add) refreshes recency,
    so the oldest-INSERTED entry survives if it is actively used — the
    live-vote window must not be evicted by a background bulk insert
    (ISSUE 4 satellite)."""
    from tendermint_tpu.crypto.batch import SigCache

    c = SigCache(capacity=3)
    t = [(b"p%d" % i, b"m%d" % i, b"s%d" % i) for i in range(5)]
    c.add(*t[0])
    c.add(*t[1])
    c.add(*t[2])
    assert c.hit(*t[0])        # refresh 0 -> LRU order is now 1, 2, 0
    c.add(*t[3])               # evicts 1 (LRU), NOT 0 (oldest inserted)
    assert not c.hit(*t[1])
    assert c.hit(*t[0]) and c.hit(*t[2]) and c.hit(*t[3])
    c.add(*t[2])               # re-add refreshes too -> order 0, 3, 2
    c.add(*t[4])               # evicts 0
    assert not c.hit(*t[0])
    assert c.hit(*t[2]) and c.hit(*t[3]) and c.hit(*t[4])
    assert len(c) == 3


def test_sigcache_concurrent_add_hit():
    """The cache is shared across the scheduler's stage/execute workers
    and every reactor thread: hammer add/hit from 8 threads and require
    no lost updates on the hot keys, no exceptions, and the capacity
    bound to hold throughout."""
    import threading

    from tendermint_tpu.crypto.batch import SigCache

    c = SigCache(capacity=64)
    hot = [(b"hot%d" % i, b"hm%d" % i, b"hs%d" % i) for i in range(8)]
    for t in hot:
        c.add(*t)
    errors = []
    stop = threading.Event()

    def churn(k):
        try:
            for j in range(400):
                c.add(b"p%d-%d" % (k, j), b"m", b"s")
                c.hit(*hot[j % len(hot)])   # keep the hot set recent
                c.add(*hot[(j + k) % len(hot)])
                assert len(c) <= 64
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(e)
        finally:
            stop.set()

    threads = [threading.Thread(target=churn, args=(k,)) for k in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors
    # every hot key was re-added/hit continuously by all threads; true
    # LRU keeps the whole hot set resident through ~3200 cold inserts
    for t in hot:
        assert c.hit(*t)


def test_graft_entry_single_chip():
    import __graft_entry__ as ge
    import jax

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert np.asarray(out).all()


def test_device_launch_stays_in_shared_bucket(monkeypatch,
                                              compile_sentinel):
    """tmlint compile sentinel (ADR-014) proven on the real verify
    path: a forced device batch of 60 sigs pads into the SHARED nb=64
    lane bucket, so the sentinel's bucket-set check passes — it would
    fail the test on any foreign padded shape (the seeded negative
    lives in tests/test_lint.py).  Launch timeout is raised so a cold
    first compile of the shared bucket (paid HERE instead of a later
    chaos test, same per-process total) can't divert the lane to host
    fallback mid-proof."""
    from tendermint_tpu.crypto import degrade
    from tendermint_tpu.libs.metrics import Registry
    from tendermint_tpu.ops import ed25519 as edops

    monkeypatch.setenv("TM_TPU_FORCE_BATCH", "1")
    degrade.configure(degrade.DegradeConfig(launch_timeout_s=600.0),
                      registry=Registry("sentinel"))
    try:
        privs, msgs, sigs = _signed(60)
        bv = BatchVerifier(tpu_threshold=8)
        for p, m, s in zip(privs, msgs, sigs):
            bv.add(p.pub_key(), m, s)
        ok, bits = bv.verify()
        assert ok and bits.all()
        rec = edops.last_launch()
        assert rec["n"] == 60 and rec["nb"] == 64, rec
        report = compile_sentinel.check()
        assert all(b[1] == 64 for b in report["new_buckets"]), report
    finally:
        degrade.reset()


# dryrun_multichip coverage lives in tests/test_multichip.py (in-proc mesh
# tests + a slow-marked hermetic subprocess test of the driver entry).
