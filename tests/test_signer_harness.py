"""Remote-signer conformance harness (reference tools/tm-signer-harness):
run the checks against our own SignerServer/FilePV as the implementation
under test, and against a deliberately broken signer."""
from __future__ import annotations

import pytest

from tendermint_tpu.crypto import ed25519 as edkeys
from tendermint_tpu.privval.file_pv import FilePV
from tendermint_tpu.privval.harness import run_harness
from tendermint_tpu.privval.signer import SignerClient, SignerServer


def _pv():
    return FilePV(edkeys.PrivKey((0xFACE).to_bytes(32, "big")))


def test_harness_passes_for_conforming_signer():
    client = SignerClient("tcp://127.0.0.1:0", accept_timeout_s=20.0)
    port = client._listener.getsockname()[1]
    srv = SignerServer(_pv(), f"tcp://127.0.0.1:{port}")
    srv.start()
    try:
        res = run_harness(client)
    finally:
        client.close()
        srv.stop()
    assert res.ok, res.failed
    assert set(res.passed) == {"pubkey", "sign_proposal", "sign_prevote",
                               "sign_precommit", "double_sign_refusal",
                               "same_block_resign"}


def test_harness_catches_double_signer(monkeypatch):
    """A signer whose sign-state tracking is broken must fail the
    double-sign check."""
    from tendermint_tpu.privval import file_pv as fpv
    # forget all history: every sign looks like a fresh HRS
    monkeypatch.setattr(fpv._LastSignState, "check_hrs",
                        lambda self, h, r, s: False)
    pv = _pv()

    client = SignerClient("tcp://127.0.0.1:0", accept_timeout_s=20.0)
    port = client._listener.getsockname()[1]
    srv = SignerServer(pv, f"tcp://127.0.0.1:{port}")
    srv.start()
    try:
        res = run_harness(client)
    finally:
        client.close()
        srv.stop()
    assert not res.ok
    assert any("double_sign_refusal" in f for f in res.failed), res.failed
