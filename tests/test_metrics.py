"""Metrics registry + node integration (reference consensus/metrics.go,
libs go-kit/prometheus, node/node.go:959-962 prometheus listener)."""
import urllib.request

from tendermint_tpu.libs.metrics import (Counter, Gauge, Histogram,
                                         Registry, exp_buckets)


def test_counter_gauge_histogram_render():
    reg = Registry(namespace="tm_test")
    c = reg.counter("cons", "total_txs", "Total txs.")
    g = reg.gauge("cons", "height", "Height.")
    h = reg.histogram("cons", "dur", "Duration.", buckets=[0.1, 1, 10])
    c.inc()
    c.inc(4)
    g.set(42)
    h.observe(0.05)
    h.observe(5)
    h.observe(50)
    text = reg.render_text()
    assert "tm_test_cons_total_txs 5" in text
    assert "tm_test_cons_height 42" in text
    assert 'tm_test_cons_dur_bucket{le="0.1"} 1' in text
    assert 'tm_test_cons_dur_bucket{le="10"} 2' in text
    assert 'tm_test_cons_dur_bucket{le="+Inf"} 3' in text
    assert "tm_test_cons_dur_count 3" in text
    assert "# TYPE tm_test_cons_dur histogram" in text


def test_labels():
    reg = Registry("tm_test2")
    c = reg.counter("p2p", "bytes", labels=("ch_id",))
    c.inc(10, ch_id="0x20")
    c.inc(7, ch_id="0x21")
    text = reg.render_text()
    assert 'tm_test2_p2p_bytes{ch_id="0x20"} 10' in text
    assert 'tm_test2_p2p_bytes{ch_id="0x21"} 7' in text
    assert c.value(ch_id="0x20") == 10


def test_registry_reuse_is_idempotent():
    reg = Registry("tm_test3")
    a = reg.gauge("x", "g")
    b = reg.gauge("x", "g")
    assert a is b


def test_exp_buckets():
    b = exp_buckets(0.1, 10, 4)
    assert b == [0.1, 1.0, 10.0, 100.0]


def test_node_records_and_serves_metrics():
    """A committing node must expose nonzero consensus metrics over the
    RPC /metrics endpoint in Prometheus text format."""
    from tests.helpers import Node, make_genesis, wait_for_height
    from tendermint_tpu.libs.metrics import DEFAULT
    from tendermint_tpu.rpc.server import RPCServer

    gdoc, privs = make_genesis(1)
    node = Node(gdoc, privs[0], name="metrics")
    node.start()
    try:
        wait_for_height([node], 3, timeout=30)
        text = DEFAULT.render_text()
        assert "tendermint_consensus_height" in text
        hline = [ln for ln in text.splitlines()
                 if ln.startswith("tendermint_consensus_height ")][0]
        assert float(hline.split()[-1]) >= 2
        assert "tendermint_state_block_processing_time_count" in text

        srv = RPCServer(node, "127.0.0.1:0")
        srv.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/metrics", timeout=5) as r:
                body = r.read().decode()
            assert r.headers["Content-Type"].startswith("text/plain")
            assert "tendermint_consensus_height" in body
        finally:
            srv.stop()
    finally:
        node.stop()
