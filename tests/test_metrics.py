"""Metrics registry + node integration (reference consensus/metrics.go,
libs go-kit/prometheus, node/node.go:959-962 prometheus listener), the
Prometheus text-format conformance of the real GET /metrics output, and
the metricsgen docs/lint gates (reference scripts/metricsgen)."""
import importlib.util
import os
import re
import urllib.request

from tendermint_tpu.libs.metrics import (Counter, Gauge, Histogram,
                                         Registry, exp_buckets)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_counter_gauge_histogram_render():
    reg = Registry(namespace="tm_test")
    c = reg.counter("cons", "total_txs", "Total txs.")
    g = reg.gauge("cons", "height", "Height.")
    h = reg.histogram("cons", "dur", "Duration.", buckets=[0.1, 1, 10])
    c.inc()
    c.inc(4)
    g.set(42)
    h.observe(0.05)
    h.observe(5)
    h.observe(50)
    text = reg.render_text()
    assert "tm_test_cons_total_txs 5" in text
    assert "tm_test_cons_height 42" in text
    assert 'tm_test_cons_dur_bucket{le="0.1"} 1' in text
    assert 'tm_test_cons_dur_bucket{le="10"} 2' in text
    assert 'tm_test_cons_dur_bucket{le="+Inf"} 3' in text
    assert "tm_test_cons_dur_count 3" in text
    assert "# TYPE tm_test_cons_dur histogram" in text


def test_labels():
    reg = Registry("tm_test2")
    c = reg.counter("p2p", "bytes", labels=("ch_id",))
    c.inc(10, ch_id="0x20")
    c.inc(7, ch_id="0x21")
    text = reg.render_text()
    assert 'tm_test2_p2p_bytes{ch_id="0x20"} 10' in text
    assert 'tm_test2_p2p_bytes{ch_id="0x21"} 7' in text
    assert c.value(ch_id="0x20") == 10


def test_registry_reuse_is_idempotent():
    reg = Registry("tm_test3")
    a = reg.gauge("x", "g")
    b = reg.gauge("x", "g")
    assert a is b


def test_exp_buckets():
    b = exp_buckets(0.1, 10, 4)
    assert b == [0.1, 1.0, 10.0, 100.0]


def test_histogram_time_context_manager_and_manual():
    """Histogram.time() (ISSUE 8 satellite): the context-manager form
    observes the bracket's wall clock on clean exit only; the manual
    form observes exactly where the caller declares success (the
    degrade device_launch_seconds discipline); `clock` is injectable."""
    reg = Registry("tm_timer")
    h = reg.histogram("x", "dur_seconds", labels=("site",))
    clk = [100.0]

    def clock():
        return clk[0]

    with h.time(clock=clock, site="a"):
        clk[0] += 2.5
    assert h.count(site="a") == 1
    assert h.total(site="a") == 2.5

    # an exception inside the bracket skips the observation — the
    # failure path's wall belongs to failure counters, not latency
    try:
        with h.time(clock=clock, site="a"):
            clk[0] += 9.0
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    assert h.count(site="a") == 1

    # manual form: start at construction, observe() on demand
    t = h.time(clock=clock, site="b")
    clk[0] += 0.75
    t.observe()
    assert h.total(site="b") == 0.75


# ---------------------------------------------------------------------------
# text-format escaping + scrape-and-parse conformance (ISSUE 3 satellite:
# a label value carrying ", \ or a newline used to corrupt the whole
# exposition — e.g. a degrade fallback reason built from an exception)
# ---------------------------------------------------------------------------

NASTY = 'quote " backslash \\ newline \n tab\tend'

# one full sample line: name, optional {labels}, value
_SAMPLE = re.compile(
    r'^([a-z_:][a-z0-9_:]*)(?:\{(.*)\})? (-?(?:[0-9.]+(?:[eE][+-]?[0-9]+)?'
    r'|Inf)|NaN|[+-]Inf)$')
# one label pair inside the braces; values may contain escaped chars
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\\n]|\\.)*)"')


def _unescape(v: str) -> str:
    # single-pass: sequential str.replace would mis-decode a literal
    # backslash followed by 'n' ("dir\\name" -> "dir\name" is correct;
    # replace("\\n", "\n") first would yield "dir<newline>ame")
    return re.sub(r'\\(\\|n|")',
                  lambda m: {"\\": "\\", "n": "\n", '"': '"'}[m.group(1)],
                  v)


def _parse_exposition(text: str):
    """Strict line-by-line parse of the Prometheus text format; raises
    AssertionError on any malformed line.  Returns
    {(name, (label pairs...)): value}."""
    out = {}
    for ln in text.splitlines():
        if not ln:
            continue
        if ln.startswith("#"):
            assert re.match(r"^# (HELP|TYPE) [a-z_:][a-z0-9_:]*( .*)?$",
                            ln), f"malformed comment line: {ln!r}"
            continue
        m = _SAMPLE.match(ln)
        assert m, f"malformed sample line: {ln!r}"
        name, blob, value = m.groups()
        pairs = []
        if blob is not None:
            # the label blob must be exactly comma-joined label pairs —
            # any unescaped quote/newline breaks this reconstruction
            matches = list(_LABEL.finditer(blob))
            rebuilt = ",".join(mm.group(0) for mm in matches)
            assert rebuilt == blob, f"malformed label blob: {blob!r}"
            pairs = [(mm.group(1), _unescape(mm.group(2)))
                     for mm in matches]
        out[(name, tuple(pairs))] = float(value)
    return out


def test_label_value_escaping_unit():
    reg = Registry("tm_esc")
    c = reg.counter("x", "weird_total", "Help with \\ and\nnewline.",
                    labels=("v",))
    c.inc(3, v=NASTY)
    text = reg.render_text()
    # no raw newline may survive inside any sample line
    for ln in text.splitlines():
        assert "\n" not in ln
    parsed = _parse_exposition(text)
    key = ("tm_esc_x_weird_total", (("v", NASTY),))
    assert parsed[key] == 3.0
    # HELP line escapes backslash + newline per the spec
    assert "# HELP tm_esc_x_weird_total Help with \\\\ and\\nnewline." \
        in text.splitlines()


def test_metrics_endpoint_scrape_and_parse_conformance():
    """Register nasty label values into the DEFAULT registry, scrape the
    REAL GET /metrics route (rpc/server.py renders DEFAULT), and strict-
    parse the whole exposition — the corruption the seed had would fail
    the blob reconstruction."""
    from tendermint_tpu.libs.metrics import DEFAULT
    from tendermint_tpu.rpc.server import RPCServer

    c = DEFAULT.counter("conformance", "nasty_total",
                        "Scrape conformance probe.", labels=("v",))
    c.inc(7, v=NASTY)
    h = DEFAULT.histogram("conformance", "nasty_seconds",
                          "Histogram with labeled series.",
                          labels=("site",), buckets=[0.1, 1])
    h.observe(0.5, site='weird "site"\n')

    class _StubNode:  # /metrics never touches the node
        config = None

    srv = RPCServer(_StubNode(), "127.0.0.1:0")
    srv.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=5) as r:
            body = r.read().decode()
    finally:
        srv.stop()
        # drop the probes: DEFAULT is process-global and later tests
        # must not see conformance leftovers on /metrics
        with DEFAULT._lock:
            DEFAULT._metrics.pop(c.name, None)
            DEFAULT._metrics.pop(h.name, None)
    parsed = _parse_exposition(body)
    assert parsed[("tendermint_conformance_nasty_total",
                   (("v", NASTY),))] == 7.0
    assert parsed[("tendermint_conformance_nasty_seconds_bucket",
                   (("site", 'weird "site"\n'), ("le", "1")))] == 1.0


# ---------------------------------------------------------------------------
# metricsgen parity: docs/metrics.md regenerates cleanly + metrics lint
# (the Go reference catches these classes at compile time)
# ---------------------------------------------------------------------------

def _metricsgen():
    spec = importlib.util.spec_from_file_location(
        "metricsgen", os.path.join(_ROOT, "scripts", "metricsgen.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_metricsgen_docs_not_stale():
    """docs/metrics.md must match what scripts/metricsgen.py generates
    from the registered bundles — regenerate and commit when this
    fails."""
    mg = _metricsgen()
    with open(os.path.join(_ROOT, "docs", "metrics.md")) as f:
        current = f.read()
    assert current == mg.generate(), (
        "docs/metrics.md is stale; run: python scripts/metricsgen.py")


def test_metrics_lint():
    """Every registered metric name is legal, every histogram declares
    sorted buckets, and no two bundles register colliding names."""
    mg = _metricsgen()
    name_re = re.compile(r"[a-z_:][a-z0-9_:]*$")
    owner = {}
    for title, cls in mg.BUNDLES:
        for name, m in mg.bundle_metrics(cls):
            assert name_re.fullmatch(name), (title, name)
            for ln in m.label_names:
                assert re.fullmatch(r"[a-zA-Z_][a-zA-Z0-9_]*", ln), (
                    name, ln)
            assert m.help, f"{name}: missing help text"
            if isinstance(m, Histogram):
                assert m.buckets, f"{name}: histogram without buckets"
                assert m.buckets == sorted(m.buckets), name
                assert len(set(m.buckets)) == len(m.buckets), name
            prev = owner.setdefault(name, cls.__name__)
            assert prev == cls.__name__, (
                f"{name} registered by both {prev} and {cls.__name__}")


def test_node_records_and_serves_metrics():
    """A committing node must expose nonzero consensus metrics over the
    RPC /metrics endpoint in Prometheus text format."""
    from tests.helpers import Node, make_genesis, wait_for_height
    from tendermint_tpu.libs.metrics import DEFAULT
    from tendermint_tpu.rpc.server import RPCServer

    gdoc, privs = make_genesis(1)
    node = Node(gdoc, privs[0], name="metrics")
    node.start()
    try:
        wait_for_height([node], 3, timeout=30)
        text = DEFAULT.render_text()
        assert "tendermint_consensus_height" in text
        hline = [ln for ln in text.splitlines()
                 if ln.startswith("tendermint_consensus_height ")][0]
        assert float(hline.split()[-1]) >= 2
        assert "tendermint_state_block_processing_time_count" in text

        srv = RPCServer(node, "127.0.0.1:0")
        srv.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/metrics", timeout=5) as r:
                body = r.read().decode()
            assert r.headers["Content-Type"].startswith("text/plain")
            assert "tendermint_consensus_height" in body
        finally:
            srv.stop()
    finally:
        node.stop()
